"""Benchmark entry: TPC-H throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

Headline: TPC-H Q1 lineitem rows/sec at SF10 through the full SQL path
(scan->filter->project->group-aggregate->sort), steady-state (arrays
pinned on device, program cached) — BASELINE.md ladder config 3's scale
on one chip; the analog of the reference's in-process benchmark harness
(testing/trino-benchmark/.../HandTpchQuery1.java, BenchmarkSuite).

Every query measures in its OWN SUBPROCESS: the tunneled TPU backend
can wedge into a persistent INVALID_ARGUMENT state under the
accumulated HBM footprint of several SF10 queries in one process
(observed q01 -> q06 sequences failing where either alone passes), and
a process is the only reliable reset. The persistent XLA compile cache
(presto_tpu/__init__.py) keeps the per-process compile cost to cache
loads; the table datagen cache keeps data loads to seconds.

``vs_baseline`` compares against a single-threaded vectorized NumPy
implementation of the same query at the same SF measured on this host —
the stand-in for BASELINE.json config 1 ("CPU Java-equivalent
operators"), since the reference repo publishes no absolute numbers
(BASELINE.md). Join queries (q03 3-way, q05 six-way) get their own
NumPy baselines (sort + searchsorted merge joins — the vectorized best
case for a CPU) so the driver's "Q1/Q3/Q5 vs baseline" metric has a
ratio per query, not just Q1.

Measurement order puts the JOIN queries first among details — rounds 3
and 4 exhausted the budget before ever measuring a join at SF10
(VERDICT r04 item 1). Q9 — the 6-relation join the cost-based
reorderer (presto_tpu/cost/) exists for — gets a RESERVED budget slice
ahead of lower-priority q06: five consecutive rounds reported it
"skipped: bench time budget exhausted" because everything before it
consumed the budget; now q01's child timeout AND q03/q05 may not eat
into its reserve, q06 runs last on whatever remains, and if the
reserve is starved anyway (datagen overrun, timeout floors) the run
reports ``q09_reserve_starved`` (seconds missing) instead of hiding
the gap behind the generic skip message.

Each query reports cold AND warm: after the cold compile+run, the
query reruns in a fresh process against the persistent AOT program
cache (exec/progcache.py, PRESTO_TPU_PROGRAM_CACHE_DIR — bench
defaults it to /tmp/presto_tpu_progcache), emitting
``qNN_warm_rows_per_sec`` with ``qNN_warm_compiles`` (0 when the
cache held) plus the real ``compile_s``/``execute_s`` split from the
obs compile histogram. The store persists across bench invocations,
so repeat runs' "cold" measurements are warm too — which is what
finally fits Q9 inside the budget.

Q3/Q5 additionally measure a LITERAL VARIANT in the same (cold) child
process — the same query with a shifted date / different region —
reporting ``qNN_variant_warm_rows_per_sec`` and
``qNN_variant_compiles``: with plan templates (presto_tpu/templates/)
the variant hits the executable compiled for the original literals,
so variant_compiles must be 0 and the variant wall is pure execute.

``bench.py --serve`` (also folded into the default run as serve_*
detail keys, in its own subprocess) drives N concurrent HTTP clients
through the real protocol against an in-process coordinator and
reports sustained queries/sec, p50/p99 latency, and error counts —
the concurrent-serving scale metric. One client drives in ARROW
result mode (X-Presto-TPU-Result: arrow, binary result pages), and
the serve report ends with a STREAMED full-table SELECT
(``qstream_rows_per_sec`` + ``qstream_peak_queue_pages``: the page
queue must peak at its bound regardless of result size — the O(page)
coordinator-memory claim of the streaming data plane). The default
run also reports ``wire_{arrow,npz}_mb_per_sec`` — exchange page
round-trip MB/s per codec (parallel/wire.py). Knobs:
PRESTO_TPU_BENCH_SERVE_CLIENTS (4), PRESTO_TPU_BENCH_SERVE_S (20),
PRESTO_TPU_BENCH_SERVE_SF (0.01).

Each measured query also reports its compile-time device-cost totals
(``qNN_flops``/``qNN_hbm_bytes``/``qNN_roofline`` — obs/devprof
harvest of XLA cost_analysis, attributed over the plan and summed), so
a wall regression is attributable: costs moved = the plan changed,
costs flat = runtime/scheduling. ``bench.py --compare OLD.json
NEW.json [threshold]`` diffs two BENCH files and prints per-key
regressions beyond the threshold (default 10%), exiting nonzero for
CI gating.

``PRESTO_TPU_BENCH_SKEW=zipf:<s>`` additionally measures q05/q09
against a Zipf(s)-skewed datagen variant (lineitem part/supplier FKs
and orders custkeys follow bounded Zipf over the key space),
reporting ``qNN_skew_rows_per_sec`` and ``qNN_skew_vs_uniform`` — the
skew-aware join work (cost/skew.py hybrid distribution + salting,
MultiJoin) is graded on that ratio staying near 1.

Env knobs: PRESTO_TPU_BENCH_SF (default 10), PRESTO_TPU_BENCH_REPS (2),
PRESTO_TPU_BENCH_BUDGET_S (default 600), PRESTO_TPU_BENCH_Q9_RESERVE_S
(default 150 — Q9's guaranteed slice), PRESTO_TPU_TPCH_CACHE (default
/tmp/presto_tpu_tpch_cache — table datagen cache; generated on first
run, ~4 min at SF10, fast raw-npy load afterwards),
PRESTO_TPU_PROGRAM_CACHE_DIR (persistent AOT program store).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

os.environ.setdefault("PRESTO_TPU_TPCH_CACHE",
                      "/tmp/presto_tpu_tpch_cache")

CUTOFF_Q1 = int((np.datetime64("1998-09-02")
                 - np.datetime64("1970-01-01")).astype(int))
DATE_Q3 = int((np.datetime64("1995-03-15")
               - np.datetime64("1970-01-01")).astype(int))
D5_LO = int((np.datetime64("1994-01-01")
             - np.datetime64("1970-01-01")).astype(int))
D5_HI = int((np.datetime64("1995-01-01")
             - np.datetime64("1970-01-01")).astype(int))

_CHILD = r"""
import json, os, sys, time
import numpy as np
from presto_tpu import Engine
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.executor import run_plan_live
from presto_tpu.obs.metrics import REGISTRY
from tests.tpch_queries import QUERIES

name = sys.argv[1]
sf = float(sys.argv[2])
reps = int(sys.argv[3])
engine = Engine()
# skew mode (PRESTO_TPU_BENCH_SKEW): the parent arms this for the
# dedicated q05/q09 skew measurements only
engine.register_catalog("tpch", TpchConnector(
    scale=sf, skew=os.environ.get("PRESTO_TPU_BENCH_SKEW_ACTIVE") or None))
# kernel backend override (PRESTO_TPU_BENCH_KERNEL_BACKEND): the
# parent forces pallas/xla for the per-backend q05/q09 comparison
from presto_tpu import kernels as _K
_kb = os.environ.get("PRESTO_TPU_BENCH_KERNEL_BACKEND")
if _kb:
    engine.session.set("kernel_backend", _kb)
plan, _ = engine.plan_sql(QUERIES[name])
compiles = REGISTRY.counter("presto_tpu_programs_compiled_total")
compile_hist = REGISTRY.histogram("presto_tpu_compile_seconds")
hits = REGISTRY.counter("presto_tpu_program_cache_hits_total")
t0 = time.perf_counter()
# host materialization = real device sync (block_until_ready does not
# reliably block on tunneled accelerator platforms)
np.asarray(run_plan_live(engine, plan))
first = time.perf_counter() - t0
times = []
for _ in range(reps):
    t0 = time.perf_counter()
    np.asarray(run_plan_live(engine, plan))
    times.append(time.perf_counter() - t0)
top_ops = None
device_syncs = None
cost_totals = None
if reps:
    # ONE extra steady run under a qstats scope, OUTSIDE the timed
    # samples, so the child can report the top operators by
    # attributed wall (which operator dominates —
    # system.operator_stats' per-kernel split) without the stats
    # recording ever inflating steady_s
    from presto_tpu.obs import qstats as QS
    syncs = REGISTRY.counter("presto_tpu_device_syncs_total")
    s0 = int(syncs.total())
    with QS.query("bench-" + name, QUERIES[name], "bench") as qr:
        np.asarray(run_plan_live(engine, plan))
    # host round-trips per steady execute, through the counted
    # exec/hostsync boundary (lint/devicesync.py proves there are no
    # uncounted ones): each is ~a full device round-trip of latency
    device_syncs = int(syncs.total()) - s0
    snap = qr.snapshot()
    ops = [o for st in snap["stages"] for t in st["tasks"]
           for o in t["operators"]]
    ops.sort(key=lambda o: -(o.get("wallMillis") or 0))
    top_ops = [{"node": o["nodeType"], "label": o["label"],
                "wall_ms": o.get("wallMillis"),
                "kernel": o.get("kernel") or ""}
               for o in ops[:3]]
    # device-cost totals from the new operator attribution
    # (obs/devprof.py): query flops, bytes moved, and the roofline
    # ratio of the whole query's arithmetic intensity against the
    # configured device peaks
    qflops = sum(int(o.get("flops") or 0) for o in ops)
    qbytes = sum(int(o.get("hbmBytes") or 0) for o in ops)
    if qflops:
        from presto_tpu.obs import devprof
        pf, pb = devprof.device_peaks()
        cost_totals = {
            "flops": qflops, "hbm_bytes": qbytes,
            "roofline": round((qflops / max(1, qbytes)) / (pf / pb),
                              4)}
    else:
        cost_totals = None
_cap_total = int(REGISTRY.counter(
    "presto_tpu_capacity_overflow_retries_total").total())
out = {
    "name": name, "first_s": round(first, 3),
    "kernel_backend": _K.resolve(engine.session),
    # real compile/execute attribution: XLA compile wall from the obs
    # histogram (exec/executor + parallel/executor both feed it), not
    # the first-minus-steady approximation
    "compile_s": round(compile_hist.sum(), 1),
    "programs_compiled": int(compiles.value()),
    # capacity-overflow retry rungs (each one is a recompile on the
    # hot path): the adaptive-execution tier's "overflow retries go
    # to ~zero" claim is graded on this staying 0 across the suite
    "capacity_overflow_retries": _cap_total,
    "cache_hits_disk": int(hits.value(tier="disk")),
    "cache_hits_memory": int(hits.value(tier="memory"))}
if times:  # reps=0 = warm-start probe: first_s is the measurement
    out["steady_s"] = min(times)
if top_ops is not None:
    out["top_operators"] = top_ops
if device_syncs is not None:
    out["device_syncs"] = device_syncs
if cost_totals is not None:
    out.update(cost_totals)
variant = sys.argv[4] if len(sys.argv) > 4 else ""
if variant:
    # literal-variant warm measurement (plan templates): the same
    # query shape with a different date/region, run in THIS process —
    # variant_compiles must be 0 on a template hit (templates/)
    old, new = variant.split("=>")
    vplan, _ = engine.plan_sql(QUERIES[name].replace(old, new))
    c0 = int(compiles.value())
    t0 = time.perf_counter()
    np.asarray(run_plan_live(engine, vplan))
    out["variant_s"] = round(time.perf_counter() - t0, 3)
    out["variant_compiles"] = int(compiles.value()) - c0
    out["template_hits"] = int(REGISTRY.counter(
        "presto_tpu_template_cache_hits_total").value())
    out["template_misses"] = int(REGISTRY.counter(
        "presto_tpu_template_cache_misses_total").value())
print(json.dumps(out))
"""

# literal-variant specs per query ("old=>new" textual swap): the
# serving scenario the plan-template subsystem exists for — same query
# shape, different constants
VARIANTS = {
    "q03": "date '1995-03-15'=>date '1995-03-22'",
    "q05": "'ASIA'=>'EUROPE'",
}


def measure_query(name: str, sf: float, reps: int,
                  timeout_s: float, skew: str | None = None,
                  kernel_backend: str | None = None) -> dict:
    """One query's (first, steady) walls + compile attribution and
    program-cache counters, isolated in a subprocess. With
    PRESTO_TPU_PROGRAM_CACHE_DIR set (bench default) a SECOND call for
    the same query measures the warm start: the fresh process loads
    the AOT executables from the persistent store instead of
    compiling. ``skew`` ("zipf:<s>") points the child at the
    Zipf-skewed datagen variant (PRESTO_TPU_BENCH_SKEW mode);
    ``kernel_backend`` forces the child's kernel dispatch (the
    pallas-vs-xla per-backend comparison)."""
    t0 = time.perf_counter()
    argv = [sys.executable, "-c", _CHILD, name, str(sf), str(reps)]
    if name in VARIANTS and reps > 0 and not skew and not kernel_backend:
        # variant rides the COLD child only: the warm-start probe
        # (reps=0) measures the persistent cache, not templates, and
        # the per-backend comparison reruns read only steady_s
        argv.append(VARIANTS[name])
    env = dict(os.environ)
    env.pop("PRESTO_TPU_BENCH_SKEW_ACTIVE", None)
    env.pop("PRESTO_TPU_BENCH_KERNEL_BACKEND", None)
    if skew:
        env["PRESTO_TPU_BENCH_SKEW_ACTIVE"] = skew
    if kernel_backend:
        env["PRESTO_TPU_BENCH_KERNEL_BACKEND"] = kernel_backend
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return {"error": "timed out"}
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-1:]
        return {"error": (tail[0] if tail else "subprocess failed")[:200]}
    line = (proc.stdout or "").strip().splitlines()[-1]
    out = json.loads(line)
    out["wall_s"] = round(time.perf_counter() - t0, 1)
    return out


def warm_metrics(detail: dict, name: str, nrows: int, sf: float,
                 budget_left: float) -> None:
    """Warm-start rerun of ``name`` in a FRESH process: the persistent
    program cache should make it execute-dominated (zero compiles).
    Fills qNN_warm_rows_per_sec / qNN_warm_* detail keys."""
    if budget_left <= 45:
        detail[f"{name}_warm_skipped"] = "bench time budget exhausted"
        return
    # reps=0: the warm-start wall IS first_s, a steady rep would just
    # double the budget cost of every warm measurement
    r = measure_query(name, sf, 0, min(budget_left - 10, 240))
    if "error" in r:
        detail[f"{name}_warm_error"] = r["error"]
        return
    # first_s of a warm process = upload + execute (compile skipped);
    # floor it so a sub-millisecond tiny-SF warm run cannot divide by
    # the child's rounded-to-zero wall
    detail[f"{name}_warm_rows_per_sec"] = round(
        nrows / max(r["first_s"], 1e-3))
    detail[f"{name}_warm_compiles"] = r.get("programs_compiled")
    detail[f"{name}_warm_cache_hits_disk"] = r.get("cache_hits_disk")
    detail[f"{name}_warm_compile_s"] = r.get("compile_s")


# -- exchange wire throughput per codec (parallel/wire.py) -------------------
# Host-side only (pure numpy/pyarrow, no device): encode+decode a
# representative exchange page — ints, short decimals, dictionary
# varchar, a nullable double — per codec, reporting round-trip MB/s.
# The Arrow data plane is graded on this ratio: columnar IPC removes
# the serde term that left the link idle (PAPERS.md 2204.03032).


def wire_metrics(detail: dict) -> None:
    from presto_tpu import types as T
    from presto_tpu.block import Column
    from presto_tpu.parallel import wire

    n = 1 << 18  # ~5 MB of raw column bytes, one exchange-page scale
    rng = np.random.default_rng(0)
    cols = {
        "k": Column(T.BIGINT, rng.integers(0, 1 << 40, n)),
        "p": Column(T.DecimalType(12, 2), rng.integers(0, 10**7, n)),
        "s": Column(T.VARCHAR, rng.integers(0, 64, n, dtype=np.int32),
                    None,
                    np.asarray([f"val{i:03d}" for i in range(64)],
                               object)),
        "v": Column(T.DOUBLE, rng.random(n), rng.random(n) > 0.1),
    }
    raw = sum(np.asarray(c.data).nbytes for c in cols.values())
    for codec in (wire.WIRE_ARROW, wire.WIRE_NPZ):
        if codec == wire.WIRE_ARROW and not wire.have_arrow():
            detail["wire_arrow_skipped"] = "pyarrow unavailable"
            continue
        blob = wire.columns_to_bytes(cols, codec=codec)  # warm
        t0 = time.perf_counter()
        reps = 0
        while time.perf_counter() - t0 < 0.5:
            blob = wire.columns_to_bytes(cols, codec=codec)
            wire.bytes_to_columns(blob)
            reps += 1
        wall = time.perf_counter() - t0
        detail[f"wire_{codec}_mb_per_sec"] = round(
            raw * reps / wall / 1e6, 1)
        detail[f"wire_{codec}_page_bytes"] = len(blob)
    a = detail.get("wire_arrow_mb_per_sec")
    z = detail.get("wire_npz_mb_per_sec")
    if a and z:
        detail["wire_arrow_vs_npz"] = round(a / z, 2)


# -- per-kernel microbench + interpret-mode parity (bench.py --kernels) ------
# Pallas-vs-XLA rows/s for each kernel in the dispatch table
# (presto_tpu/kernels/), plus Q5/Q9 result parity between the two
# backends at tiny SF. On TPU the microbench grades the real Mosaic
# lowering; on CPU-only containers the Pallas numbers are interpret
# mode — correctness evidence, not speed (which is exactly what the
# acceptance asks for there).


def run_kernel_bench() -> dict:
    import jax
    import jax.numpy as jnp

    from presto_tpu import Engine
    from presto_tpu import kernels as K
    from presto_tpu.connectors.tpch import TpchConnector
    from tests.tpch_queries import QUERIES

    detail: dict = {"kernel_default_backend": K.default_backend()}
    rng = np.random.default_rng(7)
    n = int(os.environ.get("PRESTO_TPU_BENCH_KERNEL_ROWS",
                           str(1 << 15)))
    bh = jnp.asarray(rng.integers(0, n, n).astype(np.uint64))
    ph = jnp.asarray(rng.integers(0, 2 * n, n).astype(np.uint64))
    ones = jnp.ones((n,), bool)
    vals = jnp.asarray(rng.integers(-(1 << 40), 1 << 40, n))
    sids = jnp.asarray(rng.integers(0, 64, n).astype(np.int32))
    keep = jnp.asarray(rng.random(n) > 0.5)
    cols = {"a": vals, "b": keep}

    def timed_rows_per_sec(fn) -> float:
        fn()  # warm: compile outside the timed window
        t0 = time.perf_counter()
        reps = 0
        while time.perf_counter() - t0 < 0.4:
            fn()
            reps += 1
        return round(n * reps / (time.perf_counter() - t0))

    for be in ("pallas", "xla"):
        with K.use_backend(be):
            join_fn = jax.jit(lambda: K.dispatch("join_lookup")(
                bh, ones, ph, ones, 2 * n)[0])
            agg_fn = jax.jit(lambda: K.dispatch("agg_sum")(
                vals, sids, 64))
            cmp_fn = jax.jit(lambda: K.dispatch("compact")(
                keep, cols, n)["a"])
            for kname, fn in (("join", join_fn), ("agg", agg_fn),
                              ("compact", cmp_fn)):
                try:
                    detail[f"kernel_{kname}_{be}_rows_per_sec"] = \
                        timed_rows_per_sec(lambda f=fn: np.asarray(f()))
                except Exception as exc:  # noqa: BLE001 - additive
                    detail[f"kernel_{kname}_{be}_error"] = \
                        repr(exc)[:200]

    # Q5/Q9 parity: byte-identical results pallas (interpret on CPU)
    # vs xla through the full SQL path at tiny SF
    conn = TpchConnector(scale=0.01)
    for qname in ("q05", "q09"):
        try:
            results = {}
            for be in ("xla", "pallas"):
                e = Engine()
                e.register_catalog("tpch", conn)
                e.session.set("kernel_backend", be)
                results[be] = e.execute(QUERIES[qname])
            detail[f"{qname}_pallas_parity"] = (
                results["xla"] == results["pallas"])
        except Exception as exc:  # noqa: BLE001 - additive metric
            detail[f"{qname}_parity_error"] = repr(exc)[:200]
    return detail


def kernel_metrics(detail: dict, budget_left: float) -> None:
    """Run the per-kernel microbench + parity check in its OWN
    subprocess (same device-isolation rationale as measure_query)."""
    if budget_left <= 90:
        detail["kernel_bench_skipped"] = "bench time budget exhausted"
        return
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--kernels"],
            capture_output=True, text=True,
            timeout=min(budget_left - 10, 300),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = (proc.stdout or "").strip().splitlines()[-1]
        detail.update(json.loads(line).get("detail") or {})
    except Exception as exc:  # noqa: BLE001 - additive metrics
        detail["kernel_bench_error"] = repr(exc)[:200]


# -- concurrent-serving QPS bench (bench.py --serve) -------------------------
# Drives N concurrent HTTP clients through the REAL protocol (POST
# /v1/statement + nextUri polling) against an in-process coordinator,
# reporting sustained queries/sec and p50/p99 latency — the scale
# metric ROADMAP item 1 asks for alongside rows/s. The query mix is
# deliberately small-shape (compiled once in a warmup pass) so the
# numbers measure the SERVING path — dispatch, admission, session
# overrides, result paging — not XLA compile.

SERVE_QUERIES = (
    "select count(*) from nation",
    "select r_name, count(*) as c from region group by r_name "
    "order by r_name",
    "select n_regionkey, count(*) as c from nation "
    "group by n_regionkey order by n_regionkey",
    "select count(*) from supplier where s_acctbal > 0",
)


def _quantile_ms(sorted_s: list, q: float) -> float:
    if not sorted_s:
        return 0.0
    idx = min(len(sorted_s) - 1, int(q * len(sorted_s)))
    return round(sorted_s[idx] * 1e3, 2)


def _serve_repeat_phase(base: str, repeat: float, nclients: int,
                        duration: float) -> dict:
    """Tenant-scale repeated-query mix (server/serving.py): a
    ``repeat`` fraction of each client's issues re-run an IDENTICAL
    SELECT — protocol-layer result-cache hits after the first pass —
    and the rest are template VARIANTS of one parameterized shape,
    issued under a small ``batch_window_ms`` so concurrent arrivals
    stack into vmapped cross-query batches (exec/batch.py). Reports
    the hit/variant split, batch mean size, and cache hit ratios."""
    import threading

    from presto_tpu.client import Client
    from presto_tpu.obs.metrics import REGISTRY

    hits0 = REGISTRY.counter(
        "presto_tpu_result_cache_hits_total").value()
    miss0 = REGISTRY.counter(
        "presto_tpu_result_cache_misses_total").value()
    hit_lat: list[list] = [[] for _ in range(nclients)]
    var_lat: list[list] = [[] for _ in range(nclients)]
    errors = [0] * nclients
    deadline = time.perf_counter() + duration

    def drive(i: int) -> None:
        c = Client(base, user=f"repeat{i}")
        # variants ride the cross-query batch window; identical
        # re-issues fast-path out of the cache before ever seeing it
        c.session_properties = {"batch_window_ms": 4.0}
        n = 0
        while time.perf_counter() < deadline:
            identical = (n % 100) < int(repeat * 100)
            if identical:
                sql = SERVE_QUERIES[(i + n) % len(SERVE_QUERIES)]
            else:
                # per-client, per-issue literal: same template
                # fingerprint, (almost) never the same cache key
                v = ((i * 9973 + n * 37) % 100000) / 10.0
                sql = ("select count(*) from supplier "
                       f"where s_acctbal > {v}")
            t0 = time.perf_counter()
            try:
                c.execute(sql, poll_interval=0.005)
                (hit_lat if identical else var_lat)[i].append(
                    time.perf_counter() - t0)
            except Exception:  # noqa: BLE001 - keep driving
                errors[i] += 1
            n += 1

    threads = [threading.Thread(target=drive, args=(i,))
               for i in range(nclients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    all_hit = sorted(x for per in hit_lat for x in per)
    all_var = sorted(x for per in var_lat for x in per)
    hits = REGISTRY.counter(
        "presto_tpu_result_cache_hits_total").value() - hits0
    misses = REGISTRY.counter(
        "presto_tpu_result_cache_misses_total").value() - miss0
    batch_hist = REGISTRY.histogram("presto_tpu_batch_size_queries")
    batch_count = batch_hist.count()
    completed = len(all_hit) + len(all_var)
    return {
        "serve_repeat_fraction": repeat,
        "serve_repeat_seconds": round(wall, 1),
        "serve_repeat_queries": completed,
        "serve_repeat_qps": round(completed / max(wall, 1e-9), 1),
        "serve_hit_qps": round(len(all_hit) / max(wall, 1e-9), 1),
        "serve_hit_p50_ms": _quantile_ms(all_hit, 0.50),
        "serve_hit_p99_ms": _quantile_ms(all_hit, 0.99),
        "serve_variant_qps": round(len(all_var) / max(wall, 1e-9), 1),
        "serve_batched_queries": int(REGISTRY.counter(
            "presto_tpu_batched_queries_total").value()),
        "serve_batch_mean_size": (
            round(batch_hist.sum() / batch_count, 2)
            if batch_count else 0.0),
        "serve_result_cache_hits": int(hits),
        "serve_result_cache_misses": int(misses),
        "serve_result_cache_hit_ratio": round(
            hits / max(1.0, hits + misses), 3),
        "serve_repeat_errors": sum(errors),
    }


def _serve_scaleout_phase(sf: float, duration: float) -> dict:
    """Elastic scale-out: drive a 2-worker cluster through the HTTP
    coordinator, then JOIN two standby workers mid-run via PUT
    /v1/node (the drain API's mirror image — exactly an autoscaler's
    move) and report first-half vs second-half QPS. The scheduler
    consults live workers per dispatch, so the joined pair picks up
    shards as soon as their first heartbeat flips them active."""
    import threading
    import urllib.request

    from presto_tpu import Engine
    from presto_tpu.client import Client
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.parallel.coordinator import ClusterCoordinator
    from presto_tpu.parallel.worker import WorkerServer
    from presto_tpu.server import CoordinatorServer

    # below SF 0.1 a shard is ~30k rows and per-task dispatch overhead
    # swamps the shard work, reading as a spurious QPS cliff at the
    # join; >= 0.1 the per-query cost is shard-count-invariant and the
    # halves compare cleanly
    sf = max(sf, 0.1)
    nclients = 4
    workers = [
        WorkerServer({"tpch": TpchConnector(scale=sf)},
                     node_id=f"bw{i}").start()
        for i in range(4)]
    local = Engine()
    local.register_catalog("tpch", TpchConnector(scale=sf))
    coord = ClusterCoordinator(local, heartbeat_interval_s=0.2).start()
    for w in workers:
        coord.add_worker(w.uri)
    srv = CoordinatorServer(local, cluster=coord).start()

    def _put(url: str, payload: dict) -> None:
        req = urllib.request.Request(
            url, method="PUT", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     "X-Trino-User": "scale"})
        urllib.request.urlopen(req, timeout=10).close()

    def _wait_live(n: int) -> None:
        deadline = time.perf_counter() + 10
        while len(coord.live_workers()) != n \
                and time.perf_counter() < deadline:
            time.sleep(0.05)

    try:
        base = f"http://127.0.0.1:{srv.port}"
        sql = ("select l_returnflag, count(*) as c from lineitem "
               "group by l_returnflag order by l_returnflag")
        warm = Client(base, user="scale")
        _wait_live(4)
        warm.execute(sql)  # 4-shard fragment programs compile here
        # drain two workers back out (graceful worker-side drain) so
        # the timed run STARTS at 2 and both shard configurations are
        # warm — the mid-run JOIN then measures rebalancing, not XLA
        for w in workers[2:]:
            _put(w.uri + "/v1/info/state", {"state": "SHUTTING_DOWN"})
        _wait_live(2)
        warm.execute(sql)  # 2-shard fragment programs compile here
        done: list[list] = [[] for _ in range(nclients)]
        t0 = time.perf_counter()
        t_mid = t0 + duration / 2
        t_end = t0 + duration

        def drive(i: int) -> None:
            c = Client(base, user=f"scale{i}")
            while time.perf_counter() < t_end:
                try:
                    c.execute(sql, poll_interval=0.005)
                    done[i].append(time.perf_counter())
                except Exception:  # noqa: BLE001 - keep driving
                    pass

        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(nclients)]
        for t in threads:
            t.start()
        time.sleep(max(0.0, t_mid - time.perf_counter()))
        # the autoscaler's move: the worker re-activates, then
        # announces itself to the running coordinator over PUT
        # /v1/node (joining -> active on its next heartbeat)
        for w in workers[2:]:
            _put(w.uri + "/v1/info/state", {"state": "ACTIVE"})
            _put(base + "/v1/node", {"uri": w.uri})
        for t in threads:
            t.join()
        stamps = [x for per in done for x in per]
        first = sum(1 for x in stamps if x <= t_mid)
        second = len(stamps) - first
        half = max(duration / 2, 1e-9)
        # structural evidence the rebalance happened: the final query
        # fanned out across the grown cluster. On a single-core
        # container the sharded work time-slices one CPU, so the
        # visible scale-out signal is membership-follow at QPS parity
        # (a real core/chip per worker is what turns it into speedup);
        # serve_scaleout_cpus makes that context part of the record.
        return {
            "serve_scaleout_sf": sf,
            "serve_scaleout_qps_2w": round(first / half, 1),
            "serve_scaleout_qps_4w": round(second / half, 1),
            "serve_scaleout_live_workers": len(coord.live_workers()),
            "serve_scaleout_final_nshards":
                (coord.last_distribution or {}).get("nshards"),
            "serve_scaleout_cpus": len(os.sched_getaffinity(0)),
        }
    finally:
        srv.stop()
        coord.stop()
        for w in workers:
            try:
                w.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass


def run_serve_bench() -> dict:
    """The --serve mode body: returns (and prints) the serve detail."""
    import threading

    from presto_tpu import Engine
    from presto_tpu.client import Client, QueryFailed
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.server import CoordinatorServer

    nclients = int(os.environ.get("PRESTO_TPU_BENCH_SERVE_CLIENTS",
                                  "4"))
    duration = float(os.environ.get("PRESTO_TPU_BENCH_SERVE_S", "20"))
    sf = float(os.environ.get("PRESTO_TPU_BENCH_SERVE_SF", "0.01"))
    engine = Engine()
    engine.register_catalog("tpch", TpchConnector(scale=sf))
    srv = CoordinatorServer(engine).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        warm = Client(base, user="bench")
        for q in SERVE_QUERIES:
            warm.execute(q)  # compile outside the timed window

        latencies: list[list] = [[] for _ in range(nclients)]
        errors = [0] * nclients
        deadline = time.perf_counter() + duration

        def drive(i: int) -> None:
            # client 0 drives in ARROW result mode: the serving path's
            # binary page delivery gets exercised (and measured) right
            # alongside the JSON one
            c = Client(base, user=f"bench{i}",
                       result_format="arrow" if i == 0 else "json")
            n = 0
            while time.perf_counter() < deadline:
                sql = SERVE_QUERIES[(i + n) % len(SERVE_QUERIES)]
                t0 = time.perf_counter()
                try:
                    c.execute(sql, poll_interval=0.005)
                    latencies[i].append(time.perf_counter() - t0)
                except QueryFailed:
                    errors[i] += 1
                except Exception:  # noqa: BLE001 - transport hiccups
                    # a dead driver thread would silently skew
                    # serve_qps; count the failure and keep driving
                    errors[i] += 1
                n += 1

        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(nclients)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start
        all_lat = sorted(x for per in latencies for x in per)
        completed = len(all_lat)
        # template hit/miss counters (templates/): the coordinator
        # runs in-process, so the registry's totals cover exactly the
        # queries this bench drove
        from presto_tpu.obs.metrics import REGISTRY
        out = {
            "serve_clients": nclients,
            "serve_arrow_clients": 1 if nclients else 0,
            "serve_seconds": round(wall, 1),
            "serve_sf": sf,
            "serve_queries_completed": completed,
            "serve_qps": round(completed / max(wall, 1e-9), 1),
            "serve_p50_ms": _quantile_ms(all_lat, 0.50),
            "serve_p99_ms": _quantile_ms(all_lat, 0.99),
            "serve_errors": sum(errors),
            "serve_template_hits": int(REGISTRY.counter(
                "presto_tpu_template_cache_hits_total").value()),
            "serve_template_misses": int(REGISTRY.counter(
                "presto_tpu_template_cache_misses_total").value()),
        }
        # adaptive-execution counters (parallel/adaptive.py +
        # ft/speculate.py + the capacity retry ladder): the overflow
        # total must stay 0 across the serve mix, and the replan/
        # speculation totals make mid-query adaptivity visible in the
        # same BENCH json as everything else (they only move when the
        # serve mix runs TASK-mode cluster queries)
        out["serve_capacity_overflow_retries"] = int(REGISTRY.counter(
            "presto_tpu_capacity_overflow_retries_total").total())
        out["serve_adaptive_replans"] = int(REGISTRY.counter(
            "presto_tpu_adaptive_replans_total").total())
        out["serve_speculative_attempts"] = int(REGISTRY.counter(
            "presto_tpu_speculative_attempts_total").value())

        # streamed full-table SELECT (ROADMAP item 1's acceptance):
        # every lineitem row through the bounded-page-queue protocol
        # in arrow result mode. qstream_peak_queue_pages is the
        # O(page) coordinator-memory proof — it must sit at the
        # RESULT_QUEUE_PAGES cap regardless of result size — and the
        # query-pool peak shows admission charges not scaling with
        # the result either.
        try:
            qc = Client(base, user="qstream", result_format="arrow")
            sql = "select l_orderkey, l_extendedprice from lineitem"
            t0 = time.perf_counter()
            _, qrows = qc.execute(sql, poll_interval=0.005)
            qwall = time.perf_counter() - t0
            peak_pages = 0
            for q in srv.manager.snapshot():
                if q.sql == sql and q.result is not None:
                    peak_pages = max(peak_pages, q.result.peak_depth)
            out.update({
                "qstream_rows": len(qrows),
                "qstream_rows_per_sec": round(
                    len(qrows) / max(qwall, 1e-9)),
                "qstream_peak_queue_pages": peak_pages,
                "qstream_peak_query_pool_bytes":
                    srv.manager.query_pool.peak,
            })
        except Exception as exc:  # noqa: BLE001 - additive metric
            out["qstream_error"] = repr(exc)[:200]

        # tenant-scale serving phases (server/serving.py): the
        # repeated-query mix re-uses the warm in-process server; the
        # scale-out phase boots its own 4-worker cluster
        repeat = float(os.environ.get("PRESTO_TPU_BENCH_SERVE_REPEAT",
                                      "0.8"))
        if repeat > 0:
            try:
                out.update(_serve_repeat_phase(
                    base, repeat, nclients, min(duration, 10.0)))
            except Exception as exc:  # noqa: BLE001 - additive
                out["serve_repeat_error"] = repr(exc)[:200]
        if os.environ.get("PRESTO_TPU_BENCH_SERVE_SCALEOUT",
                          "1") != "0":
            try:
                out.update(_serve_scaleout_phase(sf, min(duration,
                                                         12.0)))
            except Exception as exc:  # noqa: BLE001 - additive
                out["serve_scaleout_error"] = repr(exc)[:200]
        return out
    finally:
        srv.stop()


def serve_metrics(detail: dict, budget_left: float) -> None:
    """Run the QPS bench in its OWN subprocess (the parent stays off
    the device, same isolation rationale as measure_query) and fold
    the serve_* keys into the bench detail."""
    need = float(os.environ.get("PRESTO_TPU_BENCH_SERVE_S", "20")) + 60
    if budget_left <= need:
        detail["serve_skipped"] = "bench time budget exhausted"
        return
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--serve"],
            capture_output=True, text=True,
            timeout=min(budget_left - 10, need + 120),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        line = (proc.stdout or "").strip().splitlines()[-1]
        out = json.loads(line)
        detail.update(out.get("detail") or {})
    except Exception as exc:  # noqa: BLE001 - serve is additive
        detail["serve_error"] = repr(exc)[:200]


def _cols(table, names):
    return {c: np.asarray(table.columns[c].data) for c in names}


def _strs(table, name):
    col = table.columns[name]
    return np.asarray(col.dictionary)[np.asarray(col.data)]


def numpy_q1(li) -> float:
    """Single-pass vectorized NumPy Q1; returns wall seconds."""
    t0 = time.perf_counter()
    mask = li["l_shipdate"] <= CUTOFF_Q1
    rf = li["l_returnflag"][mask]
    ls = li["l_linestatus"][mask]
    qty = li["l_quantity"][mask]
    price = li["l_extendedprice"][mask]
    disc = li["l_discount"][mask]
    tax = li["l_tax"][mask]
    disc_price = price * (100 - disc)
    charge = disc_price * (100 + tax)
    gid = rf.astype(np.int64) * 64 + ls.astype(np.int64)
    uniq, inv = np.unique(gid, return_inverse=True)
    k = len(uniq)
    for col in (qty, price, disc, disc_price, charge):
        np.bincount(inv, weights=col.astype(np.float64), minlength=k)
    np.bincount(inv, minlength=k)
    return time.perf_counter() - t0


def numpy_q3(li, orders, cust_building) -> float:
    """Vectorized NumPy Q3: searchsorted merge joins + bincount
    group-by + top-10 — the single-threaded CPU best case."""
    t0 = time.perf_counter()
    ck = np.sort(cust_building)
    om = orders["o_orderdate"] < DATE_Q3
    oc = orders["o_custkey"][om]
    pos = np.clip(np.searchsorted(ck, oc), 0, len(ck) - 1)
    om2 = ck[pos] == oc
    okey = orders["o_orderkey"][om][om2]
    odate = orders["o_orderdate"][om][om2]
    oprio = orders["o_shippriority"][om][om2]
    order_sorted = np.argsort(okey)
    oks = okey[order_sorted]
    lm = li["l_shipdate"] > DATE_Q3
    lkey = li["l_orderkey"][lm]
    lpos = np.clip(np.searchsorted(oks, lkey), 0, len(oks) - 1)
    hit = oks[lpos] == lkey
    lkey = lkey[hit]
    rev = (li["l_extendedprice"][lm][hit].astype(np.float64)
           * (100 - li["l_discount"][lm][hit]))
    uniq, inv = np.unique(lkey, return_inverse=True)
    revenue = np.bincount(inv, weights=rev, minlength=len(uniq))
    top = np.argsort(-revenue)[:10]
    _ = (uniq[top], revenue[top],
         odate[order_sorted][np.searchsorted(oks, uniq[top])],
         oprio[order_sorted][np.searchsorted(oks, uniq[top])])
    return time.perf_counter() - t0


def numpy_q9(li, ps, orders, supp, green_part) -> float:
    """Vectorized NumPy Q9: the 6-relation profit join (part,
    supplier, lineitem, partsupp, orders, nation) via dense key
    lookups + a sorted composite-key merge into partsupp — the
    single-threaded CPU best case the reorderer's Q9 number is graded
    against."""
    t0 = time.perf_counter()
    lm = green_part[li["l_partkey"]]
    lpart = li["l_partkey"][lm]
    lsupp = li["l_suppkey"][lm]
    lord = li["l_orderkey"][lm]
    # partsupp lookup by composite (partkey, suppkey)
    smax = int(ps["ps_suppkey"].max()) + 1
    pskey = ps["ps_partkey"].astype(np.int64) * smax + ps["ps_suppkey"]
    order = np.argsort(pskey)
    pskey_sorted = pskey[order]
    cost_sorted = ps["ps_supplycost"][order]
    probe = lpart.astype(np.int64) * smax + lsupp
    pos = np.clip(np.searchsorted(pskey_sorted, probe), 0,
                  len(pskey_sorted) - 1)
    supplycost = cost_sorted[pos]
    # orders lookup: order year by o_orderkey (sorted merge)
    osort = np.argsort(orders["o_orderkey"])
    oks = orders["o_orderkey"][osort]
    years = (orders["o_orderdate"][osort]
             .astype("datetime64[D]").astype("datetime64[Y]")
             .astype(np.int64) + 1970)
    year = years[np.clip(np.searchsorted(oks, lord), 0, len(oks) - 1)]
    # supplier -> nation, dense by suppkey
    snat = np.zeros(int(supp["s_suppkey"].max()) + 1, dtype=np.int64)
    snat[supp["s_suppkey"]] = supp["s_nationkey"]
    nat = snat[lsupp]
    amount = (li["l_extendedprice"][lm].astype(np.float64)
              * (100 - li["l_discount"][lm])
              - supplycost.astype(np.float64) * li["l_quantity"][lm])
    gid = nat * 4096 + (year - 1970)
    uniq, inv = np.unique(gid, return_inverse=True)
    np.bincount(inv, weights=amount, minlength=len(uniq))
    return time.perf_counter() - t0


def numpy_q5(li, orders, cust, supp, asia_nations) -> float:
    """Vectorized NumPy Q5: six-way star join via searchsorted."""
    t0 = time.perf_counter()
    nset = np.sort(asia_nations)

    def in_nations(nk):
        p = np.clip(np.searchsorted(nset, nk), 0, len(nset) - 1)
        return nset[p] == nk

    cm = in_nations(cust["c_nationkey"])
    ckey = np.sort(cust["c_custkey"][cm])
    cnat = cust["c_nationkey"][np.argsort(cust["c_custkey"])][
        np.searchsorted(np.sort(cust["c_custkey"]), ckey)]
    om = ((orders["o_orderdate"] >= D5_LO)
          & (orders["o_orderdate"] < D5_HI))
    oc = orders["o_custkey"][om]
    p = np.clip(np.searchsorted(ckey, oc), 0, len(ckey) - 1)
    hit = ckey[p] == oc
    okey = orders["o_orderkey"][om][hit]
    onat = cnat[p[hit]]
    osort = np.argsort(okey)
    oks, onats = okey[osort], onat[osort]
    lkey = li["l_orderkey"]
    lp = np.clip(np.searchsorted(oks, lkey), 0, len(oks) - 1)
    lhit = oks[lp] == lkey
    snat_by_key = np.zeros(int(supp["s_suppkey"].max()) + 1,
                           dtype=np.int64)
    snat_by_key[supp["s_suppkey"]] = supp["s_nationkey"]
    snat = snat_by_key[li["l_suppkey"][lhit]]
    same = snat == onats[lp[lhit]]
    rev = (li["l_extendedprice"][lhit][same].astype(np.float64)
           * (100 - li["l_discount"][lhit][same]))
    nat = snat[same]
    uniq, inv = np.unique(nat, return_inverse=True)
    np.bincount(inv, weights=rev, minlength=len(uniq))
    return time.perf_counter() - t0


# -- BENCH-file regression compare (bench.py --compare) ----------------------

# direction by key suffix/substring: throughput-like keys regress when
# they FALL, cost/latency-like keys regress when they RISE. Keys that
# match neither pattern (backends, paths, ratios like vs_baseline) are
# informational and never gate.
_HIGHER_BETTER = ("rows_per_sec", "mb_per_sec", "_qps", "qps",
                  "template_hits")
_LOWER_BETTER = ("_s", "_flops", "_hbm_bytes", "_compiles",
                 "_programs_compiled", "_device_syncs", "_page_bytes",
                 "_retries", "_errors", "_misses")
# deliberately ungated: the result cache answers the serve mix at the
# protocol layer, so serve-mode template hits collapsing is the cache
# WORKING, not template sharing regressing (the q*_template_hits keys
# still gate — those phases run with the cache cold)
_UNGATED = ("serve_template_hits", "serve_template_misses",
            "serve_result_cache_misses")


def _compare_direction(key: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 ungated."""
    if key in _UNGATED:
        return 0
    for pat in _HIGHER_BETTER:
        if key.endswith(pat) or pat in key:
            return 1
    for pat in _LOWER_BETTER:
        if key.endswith(pat):
            return -1
    return 0


def _bench_detail(path: str) -> dict:
    """Load a BENCH_rXX.json file: either the bare final JSON object
    or JSON-lines output (last object with a detail wins)."""
    detail: dict = {}
    with open(path) as f:
        text = f.read()
    try:
        obj = json.loads(text)
        objs = obj if isinstance(obj, list) else [obj]
    except ValueError:
        objs = []
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    objs.append(json.loads(line))
                except ValueError:
                    continue
    # the hand-recorded BENCH_rXX.json wrappers carry the run's final
    # JSON line as a STRING under "tail" — unwrap it, else the compare
    # sees zero keys and the gate is vacuous
    for obj in list(objs):
        if isinstance(obj, dict) and isinstance(obj.get("tail"), str):
            for line in obj["tail"].splitlines():
                line = line.strip()
                if line.startswith("{"):
                    try:
                        objs.append(json.loads(line))
                    except ValueError:
                        continue
    for obj in objs:
        if isinstance(obj, dict) and isinstance(obj.get("detail"), dict):
            detail = obj["detail"]
            if "metric" in obj and isinstance(
                    obj.get("value"), (int, float)):
                detail = {**detail, obj["metric"]: obj["value"]}
    return detail


def run_compare(baseline_path: str, current_path: str,
                threshold: float) -> int:
    """Print per-key regressions beyond ``threshold`` (fractional
    change in the bad direction); return the regression count so the
    CI caller can gate on a nonzero exit."""
    base = _bench_detail(baseline_path)
    cur = _bench_detail(current_path)
    regressions = 0
    for key in sorted(set(base) & set(cur)):
        b, c = base[key], cur[key]
        if not isinstance(b, (int, float)) \
                or not isinstance(c, (int, float)) \
                or isinstance(b, bool) or isinstance(c, bool):
            continue
        direction = _compare_direction(key)
        if direction == 0 or b == 0:
            continue
        change = (c - b) / abs(b)
        bad = -change if direction > 0 else change
        if bad > threshold:
            regressions += 1
            print(f"REGRESSION {key}: {b:g} -> {c:g} "
                  f"({change * 100:+.1f}%, "
                  f"{'higher' if direction > 0 else 'lower'}-is-better,"
                  f" threshold {threshold * 100:.0f}%)")
    missing = sorted(k for k in base if k not in cur
                     and _compare_direction(k) != 0
                     and isinstance(base[k], (int, float)))
    for key in missing:
        print(f"MISSING {key}: present in baseline, absent in current")
    print(f"compared {baseline_path} -> {current_path}: "
          f"{regressions} regression(s), {len(missing)} missing key(s)")
    return regressions


def main() -> None:
    if "--compare" in sys.argv[1:]:
        # bench.py --compare BASELINE.json CURRENT.json [threshold]
        # CI gate: nonzero exit when any gated key moved in the bad
        # direction beyond the threshold (default 10%)
        i = sys.argv.index("--compare")
        rest = sys.argv[i + 1:]
        if len(rest) < 2:
            print("usage: bench.py --compare BASELINE.json "
                  "CURRENT.json [threshold]", file=sys.stderr)
            sys.exit(2)
        thr = float(rest[2]) if len(rest) > 2 else 0.10
        sys.exit(1 if run_compare(rest[0], rest[1], thr) else 0)
    if "--serve" in sys.argv[1:]:
        out = run_serve_bench()
        print(json.dumps({
            "metric": "serve_qps", "value": out["serve_qps"],
            "unit": "queries/s", "detail": out}))
        return
    if "--kernels" in sys.argv[1:]:
        out = run_kernel_bench()
        print(json.dumps({
            "metric": "kernel_bench", "value": 1, "unit": "report",
            "detail": out}))
        return

    sf = float(os.environ.get("PRESTO_TPU_BENCH_SF", "10"))
    reps = int(os.environ.get("PRESTO_TPU_BENCH_REPS", "2"))
    budget = float(os.environ.get("PRESTO_TPU_BENCH_BUDGET_S", "600"))
    t_start = time.perf_counter()

    # persistent AOT program cache (exec/progcache.py), inherited by
    # every child process: warm reruns — and repeat bench invocations,
    # which is what finally fits Q9 in the budget — skip lower+compile
    # entirely instead of re-paying 80-150 s per join query
    os.environ.setdefault("PRESTO_TPU_PROGRAM_CACHE_DIR",
                          "/tmp/presto_tpu_progcache")

    from presto_tpu.connectors.tpch import TpchConnector

    detail: dict = {"sf": sf, "program_cache_dir":
                    os.environ["PRESTO_TPU_PROGRAM_CACHE_DIR"]}

    # materialize the datagen cache BEFORE any timed subprocess (cold
    # cache costs ~4 min at SF10; children then load raw npy in
    # seconds). The connector is host-side only here — no device use,
    # so the children's TPU processes stay pristine.
    t0 = time.perf_counter()
    tpch = TpchConnector(scale=sf)
    lineitem = tpch.table("lineitem")
    nrows = lineitem.nrows
    detail["datagen_s"] = round(time.perf_counter() - t0, 1)

    # exchange wire MB/s per codec (host-side, ~1 s): the data-plane
    # serde term, independent of any query
    try:
        wire_metrics(detail)
    except Exception as exc:  # noqa: BLE001 - additive metric
        detail["wire_bench_error"] = repr(exc)[:200]

    # Q9's reserved slice (PRESTO_TPU_BENCH_Q9_RESERVE_S): read BEFORE
    # anything timed so every earlier measurement's timeout can be
    # shaped around it — five rounds in a row q09 was "skipped: bench
    # time budget exhausted" because q01 (whose child timeout ignored
    # the reserve) and the join queries ate the whole budget first
    q9_reserve = float(os.environ.get("PRESTO_TPU_BENCH_Q9_RESERVE_S",
                                      "150"))

    # headline: Q1 through the full SQL frontend. Its child timeout
    # excludes Q9's reserve too — BENCH_r05's q01 alone burned ~200 s
    # of compile+measure, and the old `left - 120` cap let it spend
    # straight into the slice the joins loop was supposed to protect
    left = budget - (time.perf_counter() - t_start)
    r = measure_query("q01", sf, reps,
                      max(left - q9_reserve - 120, 120))
    if "error" in r:
        # a broken headline is still a bench result; report zero rather
        # than crash the driver
        headline = {"metric": f"tpch_q1_sf{sf:g}_rows_per_sec",
                    "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
                    "error": r["error"]}
        print(json.dumps(headline), flush=True)
        print(json.dumps({**headline, "detail": detail}))
        return
    q1_steady = r["steady_s"]
    detail["q01_compile_s"] = r.get("compile_s",
                                    round(r["first_s"] - q1_steady, 1))
    detail["q01_execute_s"] = round(q1_steady, 2)
    detail["q01_programs_compiled"] = r.get("programs_compiled")
    detail["q01_device_syncs"] = r.get("device_syncs")
    if r.get("flops"):
        detail["q01_flops"] = r["flops"]
        detail["q01_hbm_bytes"] = r.get("hbm_bytes")
        detail["q01_roofline"] = r.get("roofline")
    rows_per_sec = nrows / q1_steady

    # single-thread NumPy Q1 baseline (config-1 stand-in)
    li = _cols(lineitem, ("l_shipdate", "l_returnflag", "l_linestatus",
                          "l_quantity", "l_extendedprice", "l_discount",
                          "l_tax"))
    base_best = min(numpy_q1(li) for _ in range(2))
    del li
    headline = {
        "metric": f"tpch_q1_sf{sf:g}_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(base_best / q1_steady, 3),
    }
    # emit the headline NOW: whatever happens later, the last stdout
    # line is a valid result; on success the final line below (with
    # details) replaces it
    print(json.dumps(headline), flush=True)

    # NumPy join baselines (host-side, cheap)
    try:
        li = _cols(lineitem, ("l_orderkey", "l_suppkey", "l_shipdate",
                              "l_extendedprice", "l_discount"))
        orders = _cols(tpch.table("orders"),
                       ("o_orderkey", "o_custkey", "o_orderdate",
                        "o_shippriority"))
        cust = _cols(tpch.table("customer"),
                     ("c_custkey", "c_nationkey"))
        seg = _strs(tpch.table("customer"), "c_mktsegment")
        cust_building = cust["c_custkey"][seg == "BUILDING"]
        supp = _cols(tpch.table("supplier"),
                     ("s_suppkey", "s_nationkey"))
        nat = _cols(tpch.table("nation"), ("n_nationkey", "n_regionkey"))
        reg_names = _strs(tpch.table("region"), "r_name")
        asia = np.asarray(tpch.table("region").columns["r_regionkey"]
                          .data)[reg_names == "ASIA"]
        asia_nations = nat["n_nationkey"][np.isin(nat["n_regionkey"],
                                                  asia)]
        detail["q03_numpy_s"] = round(numpy_q3(li, orders,
                                               cust_building), 2)
        detail["q05_numpy_s"] = round(numpy_q5(li, orders, cust, supp,
                                               asia_nations), 2)
        # Q9 baseline: 6-relation profit join over the green parts
        li9 = _cols(lineitem, ("l_orderkey", "l_partkey", "l_suppkey",
                               "l_quantity", "l_extendedprice",
                               "l_discount"))
        ps = _cols(tpch.table("partsupp"),
                   ("ps_partkey", "ps_suppkey", "ps_supplycost"))
        pnames = _strs(tpch.table("part"), "p_name")
        pkeys = np.asarray(tpch.table("part").columns["p_partkey"].data)
        green_part = np.zeros(int(pkeys.max()) + 1, dtype=bool)
        green_part[pkeys[np.char.find(pnames.astype("U"),
                                      "green") >= 0]] = True
        detail["q09_numpy_s"] = round(numpy_q9(li9, ps, orders, supp,
                                               green_part), 2)
        del li, li9, ps, orders, cust, supp
    except Exception as exc:  # baseline failure must not kill bench
        detail["numpy_join_baseline_error"] = repr(exc)[:200]

    # detail queries, JOINS FIRST (q03/q05 are the driver's metric).
    # q09 runs BEFORE q06 and holds a reserved slice the earlier
    # queries may not consume — five rounds in a row it was skipped as
    # "bench time budget exhausted" without ever being measured.
    for name in ("q03", "q05", "q09", "q06"):
        left = budget - (time.perf_counter() - t_start)
        if name in ("q03", "q05"):
            left -= q9_reserve  # keep q09's slice untouchable
        if name == "q09" and left < q9_reserve:
            # the reserve was eaten anyway (datagen overrun, a slow
            # q01 floor, numpy baselines): FAIL THE RESERVE LOUDLY —
            # a silent generic skip is how five rounds went by with
            # q09 never measured; the starved marker names the gap so
            # the budget regression is attributable, and q09 still
            # runs on whatever remains if it plausibly can
            detail["q09_reserve_starved"] = round(q9_reserve - left, 1)
        if left <= 60:
            detail[f"{name}_skipped"] = "bench time budget exhausted"
            continue
        r = measure_query(name, sf, reps, left - 15)
        if "error" in r:
            detail[f"{name}_error"] = r["error"]
            continue
        detail[f"{name}_rows_per_sec"] = round(nrows / r["steady_s"])
        detail[f"{name}_compile_s"] = r.get(
            "compile_s", round(r["first_s"] - r["steady_s"], 1))
        detail[f"{name}_execute_s"] = round(r["steady_s"], 2)
        detail[f"{name}_programs_compiled"] = r.get("programs_compiled")
        detail[f"{name}_device_syncs"] = r.get("device_syncs")
        detail[f"{name}_capacity_overflow_retries"] = r.get(
            "capacity_overflow_retries")
        # which kernel backend the child resolved (auto = pallas on
        # TPU, xla on CPU) + its top-3 operators by attributed wall
        detail[f"{name}_kernel_backend"] = r.get("kernel_backend")
        if r.get("top_operators"):
            detail[f"{name}_top_operators"] = r["top_operators"]
        # compile-time XLA cost totals (obs/devprof harvest summed over
        # the query's operator attribution) + query-level roofline
        # ratio: a perf regression that does not move these is a
        # runtime/scheduling regression, one that does is a plan change
        if r.get("flops"):
            detail[f"{name}_flops"] = r["flops"]
            detail[f"{name}_hbm_bytes"] = r.get("hbm_bytes")
            detail[f"{name}_roofline"] = r.get("roofline")
        if "variant_s" in r:
            # literal-variant warm rerun inside the cold child: with
            # plan templates on, variant_compiles MUST be 0 — the
            # variant hit the executable compiled for the original
            # literals (the ROADMAP item 2 serving scenario)
            detail[f"{name}_variant_warm_rows_per_sec"] = round(
                nrows / max(r["variant_s"], 1e-3))
            detail[f"{name}_variant_compiles"] = r["variant_compiles"]
            detail[f"{name}_template_hits"] = r.get("template_hits")
            detail[f"{name}_template_misses"] = r.get(
                "template_misses")
        base = detail.get(f"{name}_numpy_s")
        if base:
            detail[f"{name}_vs_baseline"] = round(
                base / r["steady_s"], 2)

    # per-backend q05/q09 (the kernel-backend comparison): when the
    # default run resolved to pallas (a TPU container), measure the
    # XLA fallback too, so the execute-phase kernel speedup is
    # checkable per backend from one BENCH file. On CPU containers
    # the default IS xla and the pallas side is interpret mode —
    # kernel_metrics() below reports interpret-mode PARITY instead
    # (correctness, not speed).
    for name in ("q05", "q09"):
        if detail.get(f"{name}_kernel_backend") != "pallas":
            continue
        left = budget - (time.perf_counter() - t_start)
        if left <= 60:
            detail[f"{name}_xla_skipped"] = "bench time budget " \
                                            "exhausted"
            continue
        r = measure_query(name, sf, reps, left - 15,
                          kernel_backend="xla")
        if "error" in r:
            detail[f"{name}_xla_error"] = r["error"]
            continue
        detail[f"{name}_xla_rows_per_sec"] = round(
            nrows / r["steady_s"])
        pallas_rps = detail.get(f"{name}_rows_per_sec")
        if pallas_rps:
            detail[f"{name}_pallas_vs_xla"] = round(
                pallas_rps / detail[f"{name}_xla_rows_per_sec"], 3)

    # Zipf-skew measurements (PRESTO_TPU_BENCH_SKEW=zipf:<s>): q05/q09
    # rerun against the Zipf-skewed datagen variant, so skew
    # regressions — one hot key collapsing the all_to_all onto a
    # single shard, capacity-overflow retry ladders — become visible
    # the way cold-compile ones did. The skew-aware join paths
    # (cost/skew.py hybrid distribution + salting, MultiJoin) are what
    # keeps these within range of the uniform numbers.
    skew = os.environ.get("PRESTO_TPU_BENCH_SKEW")
    if skew:
        detail["skew"] = skew
        t0 = time.perf_counter()
        try:
            TpchConnector(scale=sf, skew=skew).table("lineitem")
            detail["skew_datagen_s"] = round(time.perf_counter() - t0,
                                             1)
        except Exception as exc:  # bad spec must not kill the bench
            detail["skew_error"] = repr(exc)[:200]
            skew = None
    for name in ("q05", "q09") if skew else ():
        left = budget - (time.perf_counter() - t_start)
        if left <= 60:
            detail[f"{name}_skew_skipped"] = "bench time budget " \
                                             "exhausted"
            continue
        r = measure_query(name, sf, reps, left - 15, skew=skew)
        if "error" in r:
            detail[f"{name}_skew_error"] = r["error"]
            continue
        detail[f"{name}_skew_rows_per_sec"] = round(
            nrows / r["steady_s"])
        detail[f"{name}_skew_programs_compiled"] = r.get(
            "programs_compiled")
        uni = detail.get(f"{name}_rows_per_sec")
        if uni:
            detail[f"{name}_skew_vs_uniform"] = round(
                detail[f"{name}_skew_rows_per_sec"] / uni, 3)

    # warm starts LAST, so they can only spend what the cold
    # measurements (the driver's metrics, budget-shaped exactly as
    # before) left over: each query reruns in a FRESH process against
    # the persistent program cache — the compile-latency subsystem's
    # proof that a warm process is execute-dominated
    for name in ("q01", "q03", "q05", "q09", "q06"):
        if f"{name}_rows_per_sec" in detail or name == "q01":
            warm_metrics(detail, name, nrows, sf,
                         budget - (time.perf_counter() - t_start))

    # per-kernel pallas-vs-xla microbench + Q5/Q9 backend parity
    # (own subprocess, tiny SF)
    kernel_metrics(detail, budget - (time.perf_counter() - t_start))

    # concurrent-serving QPS + latency (own subprocess, tiny SF): the
    # scale numbers ride the same BENCH json as the throughput ones
    serve_metrics(detail, budget - (time.perf_counter() - t_start))

    # suite-wide capacity-overflow retry total (each rung is a
    # recompile): the adaptive-execution acceptance claim is that this
    # stays ZERO across the bench suite — measured, not inferred
    detail["capacity_overflow_retries_total"] = sum(
        v for k, v in detail.items()
        if k.endswith("_capacity_overflow_retries")
        and isinstance(v, int))

    print(json.dumps({**headline, "detail": detail}))


if __name__ == "__main__":
    sys.exit(main())
