"""Benchmark entry: TPC-H Q1 throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is lineitem rows/sec through the full Q1 kernel
(scan→filter→project→group-aggregate→sort), steady-state (arrays resident
on device, compiled once) — the analog of the reference's
HandTpchQuery1 in-process benchmark
(testing/trino-benchmark/.../HandTpchQuery1.java, BenchmarkSuite).

``vs_baseline`` compares against a single-threaded vectorized NumPy
implementation of the same query measured on this host — the stand-in for
BASELINE.json config 1 ("CPU Java-equivalent operators"), since the
reference repo publishes no absolute numbers (BASELINE.md).

Env knobs: PRESTO_TPU_BENCH_SF (default 1.0), PRESTO_TPU_BENCH_REPS (5).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def numpy_q1_baseline(arrays: dict[str, np.ndarray], cutoff: int) -> float:
    """Single-pass vectorized NumPy Q1; returns wall seconds."""
    t0 = time.perf_counter()
    mask = arrays["l_shipdate"] <= cutoff
    rf = arrays["l_returnflag"][mask]
    ls = arrays["l_linestatus"][mask]
    qty = arrays["l_quantity"][mask]
    price = arrays["l_extendedprice"][mask]
    disc = arrays["l_discount"][mask]
    tax = arrays["l_tax"][mask]
    disc_price = price * (100 - disc)
    charge = disc_price * (100 + tax)
    gid = rf.astype(np.int64) * 64 + ls.astype(np.int64)
    uniq, inv = np.unique(gid, return_inverse=True)
    k = len(uniq)
    for col in (qty, price, disc, disc_price, charge):
        np.bincount(inv, weights=col.astype(np.float64), minlength=k)
    np.bincount(inv, minlength=k)
    return time.perf_counter() - t0


def steady_state_sql(engine, sql: str, reps: int) -> float:
    """Compile a SQL query once (via the engine's program cache, with
    capacity retries) and return the best steady-state wall seconds over
    ``reps`` device-resident runs."""
    from presto_tpu.exec.executor import run_plan_live

    plan, _ = engine.plan_sql(sql)
    np.asarray(run_plan_live(engine, plan))  # compile + warm all segs
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        # host materialization = real device sync (block_until_ready
        # does not reliably block on tunneled accelerator platforms)
        np.asarray(run_plan_live(engine, plan))
        times.append(time.perf_counter() - t0)
    return min(times)


def detail_main(name: str) -> None:
    """Subprocess entry: measure one TPC-H query, print rows/sec."""
    from presto_tpu import Engine
    from presto_tpu.connectors.tpch import TpchConnector
    from tests.tpch_queries import QUERIES

    sf = float(os.environ.get("PRESTO_TPU_BENCH_SF", "1.0"))
    engine = Engine()
    engine.register_catalog("tpch", TpchConnector(scale=sf))
    nrows = engine.catalogs["tpch"].table("lineitem").nrows
    best = steady_state_sql(engine, QUERIES[name], 3)
    print(nrows / best)


def main() -> None:
    one = os.environ.get("PRESTO_TPU_BENCH_ONE")
    if one:
        return detail_main(one)
    sf = float(os.environ.get("PRESTO_TPU_BENCH_SF", "1.0"))
    reps = int(os.environ.get("PRESTO_TPU_BENCH_REPS", "5"))

    import jax

    from presto_tpu import Engine
    from presto_tpu.benchmarks import q1_plan
    from presto_tpu.benchmarks.handq import _days
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.exec.executor import collect_scans, make_traced

    engine = Engine()
    engine.register_catalog("tpch", TpchConnector(scale=sf))
    plan = q1_plan()
    scan_inputs = collect_scans(plan, engine)
    nrows = scan_inputs[0].nrows

    traced_fn, flat_arrays, _meta = make_traced(scan_inputs, plan, {})
    device_args = [jax.device_put(a) for a in flat_arrays]
    compiled = jax.jit(traced_fn)
    # sync by materializing the live mask on host: block_until_ready
    # does not reliably block on tunneled accelerator platforms
    np.asarray(compiled(*device_args)[1])  # compile + warmup

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(compiled(*device_args)[1])
        times.append(time.perf_counter() - t0)
    best = min(times)
    rows_per_sec = nrows / best

    # single-thread NumPy baseline (config-1 stand-in)
    li = {sym: np.asarray(a) for sym, a in
          zip(scan_inputs[0].arrays, flat_arrays)}
    base_times = [numpy_q1_baseline(li, _days("1998-09-02"))
                  for _ in range(3)]
    base_rows_per_sec = nrows / min(base_times)

    # join/secondary queries through the full SQL frontend (analog of the
    # reference's BenchmarkSuite covering HandTpchQuery1/6 plus SQL-driven
    # TPC-H runs) — reported as detail so join-path regressions are
    # visible. Each runs in a SUBPROCESS: a device OOM / TPU worker crash
    # in a detail query must not take down the headline measurement.
    detail = {}
    budget = float(os.environ.get("PRESTO_TPU_BENCH_BUDGET_S", "330"))
    t_detail = time.perf_counter()
    if os.environ.get("PRESTO_TPU_BENCH_Q1_ONLY") != "1":
        import subprocess
        # q05's six-table join exceeds single-chip HBM at SF1 (its
        # multi-chip home is the v5e-8 config, BASELINE.md ladder 4);
        # bench it at a bounded SF and record the SF used
        sf_cap = {"q05": 0.25}
        for name in ("q06", "q03", "q05"):
            left = budget - (time.perf_counter() - t_detail)
            if left <= 0:
                detail[f"{name}_skipped"] = "bench time budget exhausted"
                continue
            q_sf = min(sf, sf_cap.get(name, sf))
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    env={**os.environ, "PRESTO_TPU_BENCH_ONE": name,
                         "PRESTO_TPU_BENCH_SF": str(q_sf)},
                    capture_output=True, text=True, timeout=left,
                    cwd=os.path.dirname(os.path.abspath(__file__)))
                out = proc.stdout.strip().splitlines()
                detail[f"{name}_rows_per_sec"] = round(float(out[-1]))
                if q_sf != sf:
                    detail[f"{name}_sf"] = q_sf
            except Exception as exc:  # never let detail kill the headline
                detail[f"{name}_error"] = f"{type(exc).__name__}: {exc}"[:200]

    print(json.dumps({
        "metric": f"tpch_q1_sf{sf:g}_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / base_rows_per_sec, 3),
        "detail": detail,
    }))


if __name__ == "__main__":
    sys.exit(main())
