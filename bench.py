"""Benchmark entry: TPC-H throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

Headline: TPC-H Q1 lineitem rows/sec at SF10 through the full SQL path
(scan->filter->project->group-aggregate->sort), steady-state (arrays
pinned on device, program cached) — BASELINE.md ladder config 3's scale
on one chip; the analog of the reference's in-process benchmark harness
(testing/trino-benchmark/.../HandTpchQuery1.java, BenchmarkSuite).

``vs_baseline`` compares against a single-threaded vectorized NumPy
implementation of Q1 at the same SF measured on this host — the stand-in
for BASELINE.json config 1 ("CPU Java-equivalent operators"), since the
reference repo publishes no absolute numbers (BASELINE.md).

Detail queries (q06 scan/agg, q03 3-way join, q05 six-way join) run in
the SAME process so lineitem device pins are shared; each reports
rows/sec at the SF it ran. A time budget guards the driver's wall clock:
whatever measured before exhaustion is reported, the rest is marked
skipped.

Env knobs: PRESTO_TPU_BENCH_SF (default 10), PRESTO_TPU_BENCH_REPS (3),
PRESTO_TPU_BENCH_BUDGET_S (default 600), PRESTO_TPU_TPCH_CACHE (default
/tmp/presto_tpu_tpch_cache — table datagen cache; generated on first
run, ~4 min at SF10, fast raw-npy load afterwards).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

os.environ.setdefault("PRESTO_TPU_TPCH_CACHE",
                      "/tmp/presto_tpu_tpch_cache")


def numpy_q1_baseline(arrays: dict[str, np.ndarray], cutoff: int) -> float:
    """Single-pass vectorized NumPy Q1; returns wall seconds."""
    t0 = time.perf_counter()
    mask = arrays["l_shipdate"] <= cutoff
    rf = arrays["l_returnflag"][mask]
    ls = arrays["l_linestatus"][mask]
    qty = arrays["l_quantity"][mask]
    price = arrays["l_extendedprice"][mask]
    disc = arrays["l_discount"][mask]
    tax = arrays["l_tax"][mask]
    disc_price = price * (100 - disc)
    charge = disc_price * (100 + tax)
    gid = rf.astype(np.int64) * 64 + ls.astype(np.int64)
    uniq, inv = np.unique(gid, return_inverse=True)
    k = len(uniq)
    for col in (qty, price, disc, disc_price, charge):
        np.bincount(inv, weights=col.astype(np.float64), minlength=k)
    np.bincount(inv, minlength=k)
    return time.perf_counter() - t0


def steady_state_sql(engine, sql: str, reps: int) -> float:
    """Compile a SQL query once (via the engine's program cache, with
    capacity retries) and return the best steady-state wall seconds over
    ``reps`` device-resident runs."""
    from presto_tpu.exec.executor import run_plan_live

    plan, _ = engine.plan_sql(sql)
    np.asarray(run_plan_live(engine, plan))  # compile + warm all segs
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        # host materialization = real device sync (block_until_ready
        # does not reliably block on tunneled accelerator platforms)
        np.asarray(run_plan_live(engine, plan))
        times.append(time.perf_counter() - t0)
    return min(times)


def main() -> None:
    sf = float(os.environ.get("PRESTO_TPU_BENCH_SF", "10"))
    reps = int(os.environ.get("PRESTO_TPU_BENCH_REPS", "3"))
    budget = float(os.environ.get("PRESTO_TPU_BENCH_BUDGET_S", "600"))
    t_start = time.perf_counter()

    from presto_tpu import Engine
    from presto_tpu.connectors.tpch import TpchConnector
    from tests.tpch_queries import QUERIES

    engine = Engine()
    engine.register_catalog("tpch", TpchConnector(scale=sf))
    lineitem = engine.catalogs["tpch"].table("lineitem")
    nrows = lineitem.nrows

    # headline: Q1 through the full SQL frontend
    best = steady_state_sql(engine, QUERIES["q01"], reps)
    rows_per_sec = nrows / best

    # single-thread NumPy baseline (config-1 stand-in)
    li = {c: np.asarray(lineitem.columns[c].data)
          for c in ("l_shipdate", "l_returnflag", "l_linestatus",
                    "l_quantity", "l_extendedprice", "l_discount",
                    "l_tax")}
    cutoff = int((np.datetime64("1998-09-02")
                  - np.datetime64("1970-01-01")).astype(int))
    base_best = min(numpy_q1_baseline(li, cutoff) for _ in range(3))
    base_rows_per_sec = nrows / base_best
    del li

    headline = {
        "metric": f"tpch_q1_sf{sf:g}_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / base_rows_per_sec, 3),
    }
    # emit the headline NOW: if a detail query dies inside the device
    # runtime (uncatchable), the last stdout line is still a valid
    # result; on success the final line below (with details) replaces
    # it as the last line
    print(json.dumps(headline), flush=True)

    # detail queries share this process's device pins (q06's columns
    # are a subset of q01's; q03/q05/q09 add the join columns). Each is
    # alarm-guarded so one hung query cannot eat the whole budget; a
    # Python-level failure never kills the headline.
    import signal

    class _DetailTimeout(Exception):
        pass

    def _on_alarm(_sig, _frm):
        raise _DetailTimeout()

    signal.signal(signal.SIGALRM, _on_alarm)
    detail = {"sf": sf}
    for name in ("q06", "q03", "q05", "q09"):
        left = budget - (time.perf_counter() - t_start)
        if left <= 60:
            detail[f"{name}_skipped"] = "bench time budget exhausted"
            continue
        signal.alarm(int(left))
        try:
            q_best = steady_state_sql(engine, QUERIES[name], reps)
            detail[f"{name}_rows_per_sec"] = round(nrows / q_best)
        except _DetailTimeout:
            detail[f"{name}_error"] = "timed out"
        except Exception as exc:  # never let detail kill the headline
            detail[f"{name}_error"] = f"{type(exc).__name__}: {exc}"[:200]
        finally:
            signal.alarm(0)

    print(json.dumps({**headline, "detail": detail}))


if __name__ == "__main__":
    sys.exit(main())
