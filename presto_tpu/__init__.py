"""presto_tpu — a TPU-native distributed SQL query engine.

A from-scratch rebuild of the capabilities of the reference MPP SQL engine
(Trino ~v360, see /root/reference) designed TPU-first:

- Columnar data lives in HBM as struct-of-arrays JAX arrays with validity
  masks (the analog of trino-spi's Page/Block, reference
  core/trino-spi/src/main/java/io/trino/spi/Page.java:33).
- Row expressions compile to jitted XLA kernels instead of JVM bytecode
  (reference core/trino-main/.../sql/gen/ExpressionCompiler.java).
- Group-by / join hash tables are static-shape scatter/gather kernels on
  device (reference operator/MultiChannelGroupByHash.java:55,
  operator/join/PagesHash.java:35).
- Distribution is a jax.sharding.Mesh + shard_map: hash repartition is an
  all_to_all over ICI, broadcast join build sides are all_gathers, and
  partial->final aggregation is the psum-tree analog of Trino's
  partial aggregation (reference sql/planner/optimizations/AddExchanges.java).

Static shapes everywhere: filters carry selection masks instead of
compacting, hash tables have planner-chosen capacities with host-side
retry on overflow, and exchanges pad to fixed per-partition capacities.
"""

import os

import jax

# SQL semantics need 64-bit integers (BIGINT, scaled DECIMAL) and float64.
# This must run before any array is materialised.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: SQL plans compile to large monolithic
# programs (tens of seconds for multi-join queries); caching the compiled
# executables on disk makes repeat processes (test suite, bench driver)
# pay the compile once per program. Opt out with PRESTO_TPU_XLA_CACHE="".
_cache_dir = os.environ.get(
    "PRESTO_TPU_XLA_CACHE",
    os.path.join(os.path.dirname(__file__), os.pardir, ".xla_cache"))
if _cache_dir:
    jax.config.update("jax_compilation_cache_dir",
                      os.path.abspath(_cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # pin the entry codec to zlib: the zstandard one-shot C compressor
    # segfaults on the multi-hundred-MB serialized executables long
    # pytest sessions produce (observed deterministically ~60 compiled
    # programs in); zlib is slower but never crashes the process
    from jax._src import compilation_cache as _jcc
    _jcc.zstd = None
    _jcc.zstandard = None

from presto_tpu.types import (  # noqa: E402
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    VARCHAR,
    DecimalType,
    DataType,
)
from presto_tpu.block import Column, Table  # noqa: E402
from presto_tpu.session import Session  # noqa: E402
from presto_tpu.engine import Engine  # noqa: E402

__version__ = "0.1.0"

__all__ = [
    "BIGINT",
    "BOOLEAN",
    "DATE",
    "DOUBLE",
    "INTEGER",
    "VARCHAR",
    "DecimalType",
    "DataType",
    "Column",
    "Table",
    "Session",
    "Engine",
]
