from presto_tpu.benchmarks.handq import q1_plan, q6_plan  # noqa: F401
