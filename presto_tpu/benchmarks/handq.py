"""Hand-built TPC-H physical plans.

Analog of the reference's hand-constructed operator-tree benchmarks
(testing/trino-benchmark/src/main/java/io/trino/benchmark/HandTpchQuery1.java,
HandTpchQuery6.java:50): the flagship kernels expressed directly as plan
nodes, used by bench.py and __graft_entry__.py without going through the
SQL frontend.
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.expr import ir
from presto_tpu.expr.aggregates import AggCall
from presto_tpu.plan import nodes as N

DEC2 = T.DecimalType(12, 2)
DEC4 = T.DecimalType(18, 4)
DEC6 = T.DecimalType(18, 6)
SUM2 = T.DecimalType(18, 2)


def _days(s: str) -> int:
    return int((np.datetime64(s) - np.datetime64("1970-01-01")).astype(int))


def _scan(table, cols, types, catalog="tpch"):
    return N.TableScan(catalog, table, {c: c for c in cols},
                       dict(zip(cols, types)))


def _ref(name, t):
    return ir.ColumnRef(t, name)


def q1_plan(catalog: str = "tpch") -> N.PlanNode:
    """TPC-H Q1: pricing summary report (scan+filter+project+group-agg+sort)."""
    scan = _scan(
        "lineitem",
        ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
         "l_discount", "l_tax", "l_shipdate"],
        [T.VARCHAR, T.VARCHAR, DEC2, DEC2, DEC2, DEC2, T.DATE], catalog)
    pred = ir.Call(T.BOOLEAN, "lte", (
        _ref("l_shipdate", T.DATE), ir.Literal(T.DATE, _days("1998-09-02"))))
    filt = N.Filter(scan, pred)

    one_minus_disc = ir.Call(DEC2, "subtract", (
        ir.Literal(DEC2, 100), _ref("l_discount", DEC2)))
    disc_price = ir.Call(DEC4, "multiply", (
        _ref("l_extendedprice", DEC2), one_minus_disc))
    one_plus_tax = ir.Call(DEC2, "add", (
        ir.Literal(DEC2, 100), _ref("l_tax", DEC2)))
    charge = ir.Call(DEC6, "multiply", (disc_price, one_plus_tax))
    proj = N.Project(filt, {
        "l_returnflag": _ref("l_returnflag", T.VARCHAR),
        "l_linestatus": _ref("l_linestatus", T.VARCHAR),
        "l_quantity": _ref("l_quantity", DEC2),
        "l_extendedprice": _ref("l_extendedprice", DEC2),
        "l_discount": _ref("l_discount", DEC2),
        "disc_price": disc_price,
        "charge": charge,
    })
    agg = N.Aggregate(proj, ["l_returnflag", "l_linestatus"], {
        "sum_qty": AggCall("sum", _ref("l_quantity", DEC2), SUM2),
        "sum_base_price": AggCall("sum", _ref("l_extendedprice", DEC2), SUM2),
        "sum_disc_price": AggCall("sum", _ref("disc_price", DEC4), DEC4),
        "sum_charge": AggCall("sum", _ref("charge", DEC6), DEC6),
        "avg_qty": AggCall("avg", _ref("l_quantity", DEC2), T.DOUBLE),
        "avg_price": AggCall("avg", _ref("l_extendedprice", DEC2), T.DOUBLE),
        "avg_disc": AggCall("avg", _ref("l_discount", DEC2), T.DOUBLE),
        "count_order": AggCall("count_star", None, T.BIGINT),
    })
    sort = N.Sort(agg, [N.Ordering("l_returnflag"),
                        N.Ordering("l_linestatus")])
    names = ["l_returnflag", "l_linestatus", "sum_qty", "sum_base_price",
             "sum_disc_price", "sum_charge", "avg_qty", "avg_price",
             "avg_disc", "count_order"]
    return N.Output(sort, names, names)


Q1_SQL_SQLITE = (
    "SELECT l_returnflag, l_linestatus, sum(l_quantity), "
    "sum(l_extendedprice), sum(l_extendedprice * (1 - l_discount)), "
    "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)), "
    "avg(l_quantity), avg(l_extendedprice), avg(l_discount), count(*) "
    "FROM lineitem WHERE l_shipdate <= '1998-09-02' "
    "GROUP BY l_returnflag, l_linestatus "
    "ORDER BY l_returnflag, l_linestatus")


def q6_plan(catalog: str = "tpch") -> N.PlanNode:
    """TPC-H Q6: forecasting revenue change (scan+filter+global agg)."""
    scan = _scan("lineitem",
                 ["l_extendedprice", "l_discount", "l_quantity", "l_shipdate"],
                 [DEC2, DEC2, DEC2, T.DATE], catalog)
    pred = ir.Call(T.BOOLEAN, "and", (
        ir.Call(T.BOOLEAN, "gte", (_ref("l_shipdate", T.DATE),
                                   ir.Literal(T.DATE, _days("1994-01-01")))),
        ir.Call(T.BOOLEAN, "lt", (_ref("l_shipdate", T.DATE),
                                  ir.Literal(T.DATE, _days("1995-01-01")))),
        ir.Call(T.BOOLEAN, "gte", (_ref("l_discount", DEC2),
                                   ir.Literal(DEC2, 5))),
        ir.Call(T.BOOLEAN, "lte", (_ref("l_discount", DEC2),
                                   ir.Literal(DEC2, 7))),
        ir.Call(T.BOOLEAN, "lt", (_ref("l_quantity", DEC2),
                                  ir.Literal(DEC2, 2400))),
    ))
    filt = N.Filter(scan, pred)
    proj = N.Project(filt, {"revenue_in": ir.Call(
        DEC4, "multiply", (_ref("l_extendedprice", DEC2),
                           _ref("l_discount", DEC2)))})
    agg = N.Aggregate(proj, [], {
        "revenue": AggCall("sum", _ref("revenue_in", DEC4), DEC4)})
    return N.Output(agg, ["revenue"], ["revenue"])
