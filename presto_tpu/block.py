"""Columnar data model: Column and Table.

The analog of the reference's Page/Block
(core/trino-spi/src/main/java/io/trino/spi/Page.java:33,
spi/block/Block.java:25). Differences, chosen for TPU execution:

- Struct-of-arrays: a Table is an ordered map of name -> Column where each
  column's values are one flat device array in HBM.
- Static shapes: instead of compacting after a filter (dynamic output
  cardinality breaks XLA), a Table carries a boolean selection ``mask``.
  Downstream kernels treat masked-off rows as absent. This replaces the
  reference's positions list in PageProcessor
  (operator/project/PageProcessor.java:54).
- Null handling: each Column may carry a ``valid`` bitmap (True = non-null),
  the analog of Block.isNull.
- Strings are dictionary codes (spi/block/DictionaryBlock.java:35 precedent)
  with the **sorted** host-side dictionary, so code order == collation order
  and device-side <, min, max, sort on codes are correct for any single
  dictionary.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Mapping

import numpy as np

from presto_tpu import types as T


@dataclasses.dataclass
class Column:
    dtype: T.DataType
    data: object  # jnp.ndarray | np.ndarray, shape [N] physical values
    valid: object | None = None  # bool[N]; None means all valid
    dictionary: np.ndarray | None = None  # host-side str array for VARCHAR

    def __len__(self) -> int:
        return int(self.data.shape[0])

    def with_data(self, data, valid=...) -> "Column":
        return Column(
            self.dtype,
            data,
            self.valid if valid is ... else valid,
            self.dictionary,
        )


@dataclasses.dataclass
class EncodedStrings:
    """A string column already in (codes, sorted dictionary) form.

    Generators that pick from bounded vocabularies emit this directly so
    large tables skip the O(n log n) object-array re-encode in
    dictionary_encode — the analog of the reference producing
    DictionaryBlocks at the source (spi/block/DictionaryBlock.java:35).
    ``dictionary`` must be lexicographically sorted (code order ==
    collation order, the engine-wide invariant)."""

    codes: np.ndarray  # int32 [n]
    dictionary: np.ndarray  # object [k], sorted

    def __len__(self) -> int:
        return int(self.codes.shape[0])

    def __getitem__(self, idx) -> "EncodedStrings":
        return EncodedStrings(self.codes[idx], self.dictionary)

    def decode(self) -> np.ndarray:
        return self.dictionary[self.codes]


def dictionary_encode(values: Iterable[str]) -> tuple[np.ndarray, np.ndarray]:
    """Encode strings to (codes int32, sorted dictionary).

    The dictionary is sorted so that code comparisons implement string
    collation on device.
    """
    arr = np.asarray(values, dtype=object)
    # np.unique on object arrays sorts lexicographically.
    dictionary, codes = np.unique(arr.astype("U"), return_inverse=True)
    return codes.astype(np.int32), dictionary.astype(object)


def column_from_numpy(
    dtype: T.DataType, values: np.ndarray, valid: np.ndarray | None = None
) -> Column:
    """Build a Column from host values. Strings are dictionary-encoded;
    decimals must already be scaled integers."""
    if isinstance(dtype, T.VarcharType):
        if isinstance(values, EncodedStrings):
            return Column(dtype, values.codes, valid, values.dictionary)
        codes, dictionary = dictionary_encode(values)
        return Column(dtype, codes, valid, dictionary)
    return Column(dtype, np.asarray(values, dtype=dtype.physical_dtype), valid)


@dataclasses.dataclass
class Table:
    """An ordered collection of equal-length Columns plus a selection mask.

    ``nrows`` is the physical array length; ``mask`` (bool[nrows] or None)
    selects the live rows. ``None`` means all rows live.
    """

    columns: dict[str, Column]
    nrows: int
    mask: object | None = None

    def column(self, name: str) -> Column:
        return self.columns[name]

    @property
    def names(self) -> list[str]:
        return list(self.columns.keys())

    def with_mask(self, mask) -> "Table":
        return Table(dict(self.columns), self.nrows, mask)

    def select(self, names: list[str]) -> "Table":
        return Table({n: self.columns[n] for n in names}, self.nrows, self.mask)

    @staticmethod
    def from_numpy(
        schema: Mapping[str, T.DataType], data: Mapping[str, np.ndarray]
    ) -> "Table":
        cols = {}
        n = None
        for name, dtype in schema.items():
            col = column_from_numpy(dtype, data[name])
            if n is None:
                n = len(col)
            elif len(col) != n:
                raise ValueError(f"column {name} length mismatch")
            cols[name] = col
        return Table(cols, n or 0)

    # ---- host-side result extraction -------------------------------------

    def to_pylist(self) -> list[tuple]:
        """Decode live rows to Python tuples (host side, for results/tests)."""
        mask = None if self.mask is None else np.asarray(self.mask)
        decoded = []
        valids = []
        for col in self.columns.values():
            data = np.asarray(col.data)
            valid = None if col.valid is None else np.asarray(col.valid)
            decoded.append(_decode_column(col.dtype, data, col.dictionary))
            valids.append(valid)
        rows = []
        for i in range(self.nrows):
            if mask is not None and not mask[i]:
                continue
            rows.append(
                tuple(
                    None
                    if valids[j] is not None and not valids[j][i]
                    else decoded[j][i]
                    for j in range(len(decoded))
                )
            )
        return rows


def _decode_column(dtype: T.DataType, data: np.ndarray, dictionary):
    if isinstance(dtype, T.VarcharType):
        if dictionary is None:
            # host-materialized strings (varlen aggregates): already
            # decoded Python objects, no code indirection
            return data
        if not len(dictionary):
            return np.full(len(data), "", object)
        safe = np.clip(data, 0, len(dictionary) - 1)
        out = dictionary[safe]
        # Out-of-range codes (e.g. -1 padding from outer-join fill) -> "".
        out = np.where((data < 0) | (data >= len(dictionary)), "", out)
        return out
    if isinstance(dtype, T.DecimalType):
        if getattr(data, "ndim", 1) == 2:
            # LONG decimal limbs [n, 2] -> exact Python Decimals
            import decimal
            lo = data[:, 0].astype(np.uint64)
            hi = data[:, 1].astype(np.int64)
            out = np.empty(len(data), object)
            with decimal.localcontext() as ctx:
                ctx.prec = 50  # 38 digits + headroom for quantize
                q = decimal.Decimal(10) ** -dtype.scale
                for i in range(len(data)):
                    raw = int(hi[i]) * (1 << 64) + int(lo[i])
                    out[i] = (decimal.Decimal(raw) * q).quantize(q)
            return out
        return data.astype(np.float64) / dtype.unscale_factor
    if isinstance(dtype, T.DateType):
        epoch = np.datetime64("1970-01-01")
        return (epoch + data.astype("timedelta64[D]")).astype("datetime64[D]")
    if isinstance(dtype, T.TimestampType):
        epoch = np.datetime64("1970-01-01", "us")
        return epoch + data.astype("timedelta64[us]")
    if isinstance(dtype, T.TimeType):
        return data.astype("timedelta64[us]")
    if isinstance(dtype, T.BooleanType):
        return data.astype(bool)
    if isinstance(dtype, T.DoubleType):
        return data.astype(np.float64)
    return data


# --- ARRAY column bridging (host object lists <-> padded 2D device) --------


def pad_object_lists(element: T.DataType, data: np.ndarray):
    """Host object array of Python lists -> (data2d, lengths, emask,
    dictionary) in the fixed-capacity device layout (expr/compile.Val).
    NULL rows / NULL elements become dead padding."""
    n = len(data)
    lens = np.array([0 if row is None else len(row) for row in data],
                    np.int32)
    cap = max(int(lens.max()) if n else 1, 1)
    emask = np.zeros((n, cap), bool)
    if isinstance(element, T.VarcharType):
        vocab = sorted({str(x) for row in data if row is not None
                        for x in row if x is not None})
        code_of = {x: i for i, x in enumerate(vocab)}
        d2 = np.zeros((n, cap), np.int32)
        for i, row in enumerate(data):
            for j, x in enumerate(row or ()):
                if x is not None:
                    d2[i, j] = code_of[str(x)]
                    emask[i, j] = True
        return d2, lens, emask, np.array(vocab, dtype=object)
    d2 = np.zeros((n, cap), element.physical_dtype)
    for i, row in enumerate(data):
        for j, x in enumerate(row or ()):
            if x is not None:
                d2[i, j] = x
                emask[i, j] = True
    return d2, lens, emask, None


def lists_from_padded(element: T.DataType, data2d: np.ndarray,
                      lengths: np.ndarray, emask: np.ndarray | None,
                      dictionary) -> np.ndarray:
    """Inverse of pad_object_lists: decode to an object array of
    Python lists (NULL elements as None)."""
    n, cap = data2d.shape[:2]
    flat = _decode_column(element, data2d.reshape(-1),
                          dictionary).reshape(n, cap)
    out = np.empty(n, object)
    for i in range(n):
        ln = int(lengths[i])
        row = []
        for j in range(ln):
            if emask is not None and not emask[i, j]:
                row.append(None)
            else:
                row.append(flat[i, j])
        out[i] = row
    return out
