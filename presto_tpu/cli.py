"""Interactive SQL shell.

Analog of the reference's trino-cli (client/trino-cli/.../Trino.java:40,
Console.java:82): a readline REPL that talks either to a coordinator over
the REST protocol (--server) or to an in-process engine (default, with
the tpch tiny catalog loaded), rendering aligned result tables.

Usage:
  python -m presto_tpu.cli                 # in-process, tpch tiny
  python -m presto_tpu.cli --scale 1.0
  python -m presto_tpu.cli --server http://localhost:8080
  python -m presto_tpu.cli -e "select 1"   # one-shot
"""

from __future__ import annotations

import argparse
import sys
import time


def _render(columns: list[str], rows: list) -> str:
    cells = [[("NULL" if v is None else str(v)) for v in row]
             for row in rows]
    widths = [len(c) for c in columns]
    for row in cells:
        for i, v in enumerate(row):
            widths[i] = max(widths[i], len(v))
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(c.ljust(w) for c, w in zip(columns, widths)), sep]
    for row in cells:
        out.append(" | ".join(v.ljust(w) for v, w in zip(row, widths)))
    return "\n".join(out)


class _InProcessBackend:
    def __init__(self, scale: float):
        from presto_tpu import Engine
        from presto_tpu.connectors.memory import MemoryConnector
        from presto_tpu.connectors.tpch import TpchConnector
        self.engine = Engine()
        self.engine.register_catalog("tpch", TpchConnector(scale=scale))
        self.engine.register_catalog("memory", MemoryConnector())

    def execute(self, sql: str):
        from presto_tpu.sql import ast as A
        from presto_tpu.sql.parser import parse_statement
        stmt = parse_statement(sql)
        if isinstance(stmt, A.QueryStatement):
            plan, _ = self.engine.plan_sql(sql)
            names = plan.names
            return names, self.engine.execute(sql)
        rows = self.engine.execute(sql)
        width = len(rows[0]) if rows else 1
        return [f"_col{i}" for i in range(width)], rows


class _RemoteBackend:
    def __init__(self, url: str, user: str):
        from presto_tpu.client import Client
        self.client = Client(url, user)

    def execute(self, sql: str):
        # live progress on the poll loop (the coordinator's monotonic
        # qstats stage-walk estimate), drawn on stderr and erased when
        # the result lands so piped stdout stays clean
        shown = [False]

        def on_progress(p: float) -> None:
            if not sys.stderr.isatty():
                return
            filled = int(round(20 * p))
            sys.stderr.write(
                f"\r[{'#' * filled}{'.' * (20 - filled)}] "
                f"{p * 100:3.0f}%")
            sys.stderr.flush()
            shown[0] = True

        try:
            columns, rows = self.client.execute(
                sql, on_progress=on_progress)
        finally:
            if shown[0]:
                sys.stderr.write("\r" + " " * 28 + "\r")
                sys.stderr.flush()
        return [c["name"] for c in columns], rows


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="presto-tpu")
    p.add_argument("--server", help="coordinator URL (default in-process)")
    p.add_argument("--user", default="presto")
    p.add_argument("--scale", type=float, default=0.01,
                   help="tpch scale for in-process mode")
    p.add_argument("-e", "--execute", help="run one statement and exit")
    args = p.parse_args(argv)

    backend = (_RemoteBackend(args.server, args.user) if args.server
               else _InProcessBackend(args.scale))

    def run_one(sql: str) -> None:
        t0 = time.perf_counter()
        try:
            columns, rows = backend.execute(sql)
        except Exception as e:  # noqa: BLE001
            print(f"Query failed: {e}", file=sys.stderr)
            return
        wall = time.perf_counter() - t0
        print(_render(columns, rows))
        print(f"({len(rows)} rows, {wall:.2f}s)")

    if args.execute:
        run_one(args.execute)
        return 0

    try:
        import readline  # noqa: F401 - line editing side effect
    except ImportError:
        pass
    print("presto-tpu CLI — \\q to quit")
    buf: list[str] = []
    while True:
        try:
            prompt = "presto> " if not buf else "     -> "
            line = input(prompt)
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if line.strip() in ("\\q", "quit", "exit"):
            return 0
        if not line.strip():
            continue
        buf.append(line)
        if line.rstrip().endswith(";"):
            sql = "\n".join(buf).rstrip().rstrip(";")
            buf = []
            run_one(sql)


if __name__ == "__main__":
    sys.exit(main())
