"""Client library: submit SQL over the REST protocol, follow nextUri.

Analog of the reference's trino-client StatementClientV1
(client/trino-client/.../StatementClientV1.java:61,323-335): POST the
statement, then advance() along nextUri until the server stops returning
one, accumulating data pages. Results STREAM: the server delivers pages
while the query is still RUNNING (bounded producer queue, see
server/results.py), so the loop drains data as it appears and only
sleeps on genuinely empty polls.

``result_format="arrow"`` asks the server for binary result pages
(``X-Presto-TPU-Result: arrow``): each nextUri fetch returns the wire
codec's Arrow bytes untouched, decoded client-side into the SAME row
values the JSON path yields.
"""

from __future__ import annotations

import http.client
import io
import json
import re
import threading
import time
import urllib.error
from urllib.parse import urlsplit


class QueryFailed(Exception):
    """Carries the protocol error code (reference errorName —
    QUERY_QUEUE_FULL, CLUSTER_OUT_OF_MEMORY, EXCEEDED_TIME_LIMIT, ...)
    so callers triage overload shedding vs real failures."""

    def __init__(self, message: str, error_name: str | None = None):
        super().__init__(message)
        self.error_name = error_name


class Client:
    def __init__(self, base_url: str, user: str = "presto",
                 password: str | None = None,
                 result_format: str = "json"):
        self.base_url = base_url.rstrip("/")
        self.user = user
        self.password = password
        self.result_format = result_format
        self.warnings: list = []
        # monotonic 0..1 progress of the last execute() (the protocol
        # stats blob's qstats stage-walk estimate)
        self.last_progress: float = 0.0
        # session properties accumulated from SET SESSION statements,
        # replayed on every request via X-Trino-Session (the reference
        # client's session accumulation, StatementClientV1)
        self.session_properties: dict[str, object] = {}
        # prepared statements accumulated from PREPARE/DEALLOCATE,
        # replayed via X-Trino-Prepared-Statement (the reference's
        # addedPreparedStatements round-trip)
        self.prepared_statements: dict[str, str] = {}
        # per-thread persistent HTTP/1.1 connections: one TCP connect
        # (and one server handler thread) per client thread instead of
        # per request — the serving fast path answers a repeated
        # SELECT in a single round trip on an already-open socket
        self._conns: dict[int, http.client.HTTPConnection] = {}
        self._conns_lock = threading.Lock()

    def _new_conn(self) -> http.client.HTTPConnection:
        from presto_tpu.server.httpbase import client_ssl_context
        sp = urlsplit(self.base_url)
        if sp.scheme == "https":
            import ssl
            ctx = client_ssl_context()
            if ctx is None:
                ctx = ssl.create_default_context()
            conn: http.client.HTTPConnection = \
                http.client.HTTPSConnection(
                    sp.hostname, sp.port, timeout=300, context=ctx)
        else:
            conn = http.client.HTTPConnection(sp.hostname, sp.port,
                                              timeout=300)
        conn.connect()
        # request/response pairs ping-pong on this socket: Nagle +
        # delayed ACK would add ~40ms to every exchange
        import socket
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    def _drop_conn(self, tid: int) -> None:
        with self._conns_lock:
            conn = self._conns.pop(tid, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _headers(self) -> dict:
        headers = {"X-Trino-User": self.user}
        if self.result_format != "json":
            headers["X-Presto-TPU-Result"] = self.result_format
        if self.session_properties:
            from urllib.parse import quote
            # values are URL-encoded so a comma/equals inside a value
            # cannot corrupt the comma-separated header (the reference
            # protocol encodes the same way)
            headers["X-Trino-Session"] = ",".join(
                f"{k}={quote(str(v))}"
                for k, v in self.session_properties.items())
        if self.prepared_statements:
            from urllib.parse import quote
            headers["X-Trino-Prepared-Statement"] = ",".join(
                f"{quote(k)}={quote(v)}"
                for k, v in self.prepared_statements.items())
        if self.password is not None:
            import base64
            cred = base64.b64encode(
                f"{self.user}:{self.password}".encode()).decode()
            headers["Authorization"] = f"Basic {cred}"
        return headers

    def _request(self, method: str, url: str, body: bytes | None = None):
        sp = urlsplit(url)
        path = sp.path + (f"?{sp.query}" if sp.query else "")
        headers = self._headers()
        tid = threading.get_ident()
        resp = None
        for attempt in (0, 1):
            with self._conns_lock:
                conn = self._conns.get(tid)
            if conn is None:
                conn = self._new_conn()
                with self._conns_lock:
                    self._conns[tid] = conn
            try:
                conn.request(method, path, body=body, headers=headers)
            except (http.client.HTTPException, OSError):
                # send-phase failure: the server processed nothing, so
                # a fresh-connection retry is safe for ANY method (the
                # usual cause is the far end closing an idle socket)
                self._drop_conn(tid)
                if attempt:
                    raise
                continue
            try:
                resp = conn.getresponse()
            except (http.client.HTTPException, OSError):
                self._drop_conn(tid)
                # the request reached the wire: only retry methods the
                # server may safely see twice (a POSTed statement could
                # otherwise double-submit)
                if attempt or method not in ("GET", "DELETE"):
                    raise
                continue
            break
        status = resp.status
        data = resp.read()  # always drain: keep-alive needs EOF
        if status >= 400:
            # the connection may hold an unread request body (e.g. a
            # 401 sent before the server read our POST data): never
            # reuse it after an error response
            self._drop_conn(tid)
        if status == 429:
            # overload shedding answers 429 with the QueryResults JSON
            # (QUERY_QUEUE_FULL + Retry-After); surface it as a result
            # so execute() raises the classified QueryFailed. Other
            # statuses (401 auth, 404 ownership) raise like urllib did.
            try:
                return json.loads(data)
            except (ValueError, TypeError):
                pass
        if status >= 400:
            raise urllib.error.HTTPError(url, status, resp.reason,
                                         resp.headers, io.BytesIO(data))
        ctype = resp.headers.get("Content-Type", "")
        if ctype.startswith("application/vnd.presto-tpu"):
            return self._binary_result(data, resp.headers, url)
        return json.loads(data or b"{}")

    def close(self) -> None:
        """Close this client's persistent connections (optional; idle
        server threads also time out on their own)."""
        with self._conns_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    def _binary_result(self, body: bytes, headers, url: str) -> dict:
        """An arrow result page -> the SAME QueryResults shape the
        JSON envelope carries: the body's wire bytes decode to rows
        byte-identical to the buffered/JSON path, state/token/columns
        come off the response headers."""
        from presto_tpu.server.results import rows_from_wire_page

        out: dict = {"stats": {
            "state": headers.get("X-PrestoTpu-State", "RUNNING")}}
        cols = headers.get("X-PrestoTpu-Columns")
        if cols:
            out["columns"] = json.loads(cols)
        if body:
            out["data"] = rows_from_wire_page(body)
        if headers.get("X-PrestoTpu-Complete") != "1":
            nxt = headers.get("X-PrestoTpu-Next-Token", "0")
            out["nextUri"] = re.sub(r"/\d+$", f"/{nxt}", url)
        return out

    def execute(self, sql: str, poll_interval: float = 0.02,
                on_progress=None):
        """Run SQL; returns (columns, rows). Blocks until the result
        stream drains. Server-side diagnostics accumulate in
        ``self.warnings`` (reference StatementClientV1
        currentStatusInfo().getWarnings). ``on_progress`` (when given)
        is called with the protocol stats blob's monotonic 0..1
        ``progress`` estimate whenever it advances; the latest value
        is also kept on ``self.last_progress``."""
        out = self._request("POST", f"{self.base_url}/v1/statement",
                            sql.encode())
        columns = None
        rows: list[list] = []
        self.warnings = []
        self.last_progress = 0.0
        while True:
            progress = out.get("stats", {}).get("progress")
            if progress is not None \
                    and progress > self.last_progress:
                self.last_progress = float(progress)
                if on_progress is not None:
                    on_progress(self.last_progress)
            if "error" in out and out["error"]:
                raise QueryFailed(out["error"].get("message", "failed"),
                                  out["error"].get("errorName"))
            if out.get("columns"):
                columns = out["columns"]
            if out.get("setSession"):
                self.session_properties.update(out["setSession"])
            if out.get("addedPreparedStatements"):
                self.prepared_statements.update(
                    out["addedPreparedStatements"])
            for name in out.get("deallocatedPreparedStatements") or ():
                self.prepared_statements.pop(name, None)
            if out.get("warnings"):
                self.warnings = out["warnings"]
            rows.extend(out.get("data", []))
            next_uri = out.get("nextUri")
            if next_uri is None:
                return columns or [], rows
            state = out.get("stats", {}).get("state")
            if state in ("QUEUED", "RUNNING") and not out.get("data"):
                # only an EMPTY poll sleeps: streamed pages arriving
                # while RUNNING drain back-to-back at wire speed
                time.sleep(poll_interval)
            out = self._request("GET", next_uri)

    def submit(self, sql: str) -> tuple[str, dict]:
        """Fire-and-poll entry: POST the statement, return
        (query_id, first response) without waiting for completion."""
        out = self._request("POST", f"{self.base_url}/v1/statement",
                            sql.encode())
        return out["id"], out

    def query_state(self, query_id: str) -> str:
        info = self._request("GET",
                             f"{self.base_url}/v1/query/{query_id}")
        return info.get("state", "UNKNOWN")

    def cancel(self, query_id: str) -> None:
        self._request(
            "DELETE",
            f"{self.base_url}/v1/statement/executing/{query_id}/0")

    def server_info(self) -> dict:
        return self._request("GET", f"{self.base_url}/v1/info")

    def queries(self) -> list[dict]:
        return self._request("GET", f"{self.base_url}/v1/query")
