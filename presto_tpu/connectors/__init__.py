"""Connectors: data sources pluggable under the engine.

The analog of the reference's SPI + plugin/ tree
(core/trino-spi/src/main/java/io/trino/spi/connector/Connector.java:45).
A Connector exposes schemas, row counts/stats, and materialises tables as
columnar ``Table`` objects ready for device upload.
"""

from presto_tpu.connectors.base import Connector, TableStats
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.connectors.memory import MemoryConnector

__all__ = ["Connector", "TableStats", "TpchConnector", "MemoryConnector"]
