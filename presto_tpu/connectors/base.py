"""Connector SPI.

Minimal analog of the reference's connector contract
(spi/connector/ConnectorMetadata.java, ConnectorSplitManager,
ConnectorPageSourceProvider). v1 exposes whole tables as columnar batches;
split-granular streaming arrives with the block-streaming executor.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

from presto_tpu import types as T
from presto_tpu.block import Table


@dataclasses.dataclass
class TableStats:
    """Planner statistics, analog of spi/statistics/TableStatistics."""

    row_count: int
    # per-column distinct-value estimates (used to size hash tables)
    ndv: dict[str, int] = dataclasses.field(default_factory=dict)


class Connector:
    name: str = "connector"

    def table_names(self) -> list[str]:
        raise NotImplementedError

    def table_schema(self, name: str) -> Mapping[str, T.DataType]:
        raise NotImplementedError

    def table(self, name: str) -> Table:
        """Materialise the full table (host-side arrays)."""
        raise NotImplementedError

    def stats(self, name: str) -> TableStats:
        raise NotImplementedError

    def row_count_estimate(self, name: str) -> int:
        """Cheap row-count estimate for join ordering (must not force data
        generation; analog of spi ConnectorMetadata.getTableStatistics)."""
        return self.stats(name).row_count

    def ndv_estimates(self, name: str) -> dict[str, int]:
        """Cheap per-column distinct-value estimates used to size hash
        tables at plan time (must not force data generation; analog of
        the reference tpch connector's shipped column statistics,
        plugin/trino-tpch src/main/resources column stats JSON). Missing
        columns mean unknown."""
        return {}

    def column_range_estimates(
            self, name: str) -> dict[str, tuple[float, float]]:
        """Cheap per-column (min, max) physical-value estimates for
        range-predicate selectivity (must not force data generation;
        analog of spi/statistics ColumnStatistics range). Missing
        columns mean unknown."""
        return {}

    def unique_keys(self, name: str) -> list[tuple[str, ...]]:
        """Column sets known unique (primary keys). Lets the planner pick
        the single-match hash-join fast path (reference JoinNode's
        maySkipOutputDuplicates analog)."""
        return []

    def partitioning(self, name: str) -> tuple[str, ...] | None:
        """Connector-defined partitioning: the column set this table can
        be hash-bucketed on at the source (reference
        spi/connector/ConnectorNodePartitioningProvider +
        TpchBucketFunction). The distributed executor shards such scans
        by key hash instead of by row blocks, so joins/aggregations on
        those keys skip the FIXED_HASH exchange entirely."""
        return None

    def table_version(self, name: str) -> int | None:
        """Monotonic per-table data version for result caching. A
        connector whose tables can change under it must bump the
        version on every write; ``None`` (the default) declares the
        table's contents unversioned, which makes any query touching
        it ineligible for the result cache — stale hits are
        structurally impossible, not merely unlikely (analog of the
        reference's ConnectorMetadata.getTableHandle freshness
        contract used by materialized-view staleness checks)."""
        return None

    def apply_filter(self, name: str, conjuncts) -> str | None:
        """Offer pushable filter conjuncts
        (connectors/expression.ComparisonExpr). A connector that can
        skip provably-irrelevant data returns a DECORATED table name
        resolving to the constrained scan through table()/table_schema;
        None means no pushdown. The engine keeps the full filter above
        the scan, so acceptance is a superset guarantee, never exact
        evaluation (reference ConnectorMetadata.applyFilter +
        spi/expression/ConnectorExpression.java)."""
        return None

    def begin_write(self, name: str,
                    schema: "Mapping[str, T.DataType] | None" = None):
        """Streaming write: returns a PageSink accepting pages and
        committing on finish (reference
        spi/connector/ConnectorPageSink.java:22). ``schema`` set =
        CREATE TABLE AS (table materializes at finish); None = INSERT
        into an existing table. Default adapter buffers pages and
        commits through create_table/insert for connectors without a
        native sink."""
        return _BufferingPageSink(self, name, schema)

    def delete_rows(self, name: str, mask) -> int:
        """Delete rows where mask is true (None = all); returns the
        deleted count. Analog of spi row-level delete
        (ConnectorMetadata beginDelete + DeleteOperator)."""
        raise NotImplementedError(
            f"connector {self.name} does not support DELETE")

    def update_rows(self, name: str, values, valids, mask) -> int:
        """Assign values[col] on rows where mask is true (None = all);
        returns the updated count. Analog of spi UpdateOperator."""
        raise NotImplementedError(
            f"connector {self.name} does not support UPDATE")


class PageSink:
    """Streaming write target (spi/connector/ConnectorPageSink.java:22):
    append pages, then finish() commits atomically and returns the row
    count; abort() discards."""

    def append_page(self, data: "Mapping[str, object]",
                    valid: "Mapping[str, object | None]") -> None:
        raise NotImplementedError

    def finish(self) -> int:
        raise NotImplementedError

    def abort(self) -> None:
        pass


class _BufferingPageSink(PageSink):
    """Default adapter: accumulates pages host-side, commits whole via
    the connector's create_table/insert."""

    def __init__(self, connector: Connector, name: str, schema):
        import numpy as np
        self._np = np
        self.connector = connector
        self.name = name
        self.schema = dict(schema) if schema is not None else None
        self._pages: list = []
        self._rows = 0

    def append_page(self, data, valid) -> None:
        self._pages.append((dict(data), dict(valid)))
        self._rows += len(next(iter(data.values()), []))

    def finish(self) -> int:
        np = self._np
        if not self._pages:
            if self.schema is not None:
                self.connector.create_table(self.name, self.schema,
                                            {}, {})
            return 0
        cols = list(self._pages[0][0])
        if len(self._pages) == 1:
            data = {c: np.asarray(self._pages[0][0][c]) for c in cols}
        else:
            data = {c: np.concatenate(
                [np.asarray(p[0][c]) for p in self._pages])
                for c in cols}
        valid = {}
        for c in cols:
            vs = [p[1].get(c) for p in self._pages]
            if any(v is not None for v in vs):
                valid[c] = np.concatenate([
                    np.asarray(v) if v is not None
                    else np.ones(len(p[0][c]), bool)
                    for v, p in zip(vs, self._pages)])
            else:
                valid[c] = None
        if self.schema is not None:
            self.connector.create_table(self.name, self.schema, data,
                                        valid)
        else:
            self.connector.insert(self.name, data, valid)
        return self._rows
