"""Connector SPI.

Minimal analog of the reference's connector contract
(spi/connector/ConnectorMetadata.java, ConnectorSplitManager,
ConnectorPageSourceProvider). v1 exposes whole tables as columnar batches;
split-granular streaming arrives with the block-streaming executor.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

from presto_tpu import types as T
from presto_tpu.block import Table


@dataclasses.dataclass
class TableStats:
    """Planner statistics, analog of spi/statistics/TableStatistics."""

    row_count: int
    # per-column distinct-value estimates (used to size hash tables)
    ndv: dict[str, int] = dataclasses.field(default_factory=dict)


class Connector:
    name: str = "connector"

    def table_names(self) -> list[str]:
        raise NotImplementedError

    def table_schema(self, name: str) -> Mapping[str, T.DataType]:
        raise NotImplementedError

    def table(self, name: str) -> Table:
        """Materialise the full table (host-side arrays)."""
        raise NotImplementedError

    def stats(self, name: str) -> TableStats:
        raise NotImplementedError

    def row_count_estimate(self, name: str) -> int:
        """Cheap row-count estimate for join ordering (must not force data
        generation; analog of spi ConnectorMetadata.getTableStatistics)."""
        return self.stats(name).row_count

    def ndv_estimates(self, name: str) -> dict[str, int]:
        """Cheap per-column distinct-value estimates used to size hash
        tables at plan time (must not force data generation; analog of
        the reference tpch connector's shipped column statistics,
        plugin/trino-tpch src/main/resources column stats JSON). Missing
        columns mean unknown."""
        return {}

    def column_range_estimates(
            self, name: str) -> dict[str, tuple[float, float]]:
        """Cheap per-column (min, max) physical-value estimates for
        range-predicate selectivity (must not force data generation;
        analog of spi/statistics ColumnStatistics range). Missing
        columns mean unknown."""
        return {}

    def unique_keys(self, name: str) -> list[tuple[str, ...]]:
        """Column sets known unique (primary keys). Lets the planner pick
        the single-match hash-join fast path (reference JoinNode's
        maySkipOutputDuplicates analog)."""
        return []

    def partitioning(self, name: str) -> tuple[str, ...] | None:
        """Connector-defined partitioning: the column set this table can
        be hash-bucketed on at the source (reference
        spi/connector/ConnectorNodePartitioningProvider +
        TpchBucketFunction). The distributed executor shards such scans
        by key hash instead of by row blocks, so joins/aggregations on
        those keys skip the FIXED_HASH exchange entirely."""
        return None

    def delete_rows(self, name: str, mask) -> int:
        """Delete rows where mask is true (None = all); returns the
        deleted count. Analog of spi row-level delete
        (ConnectorMetadata beginDelete + DeleteOperator)."""
        raise NotImplementedError(
            f"connector {self.name} does not support DELETE")

    def update_rows(self, name: str, values, valids, mask) -> int:
        """Assign values[col] on rows where mask is true (None = all);
        returns the updated count. Analog of spi UpdateOperator."""
        raise NotImplementedError(
            f"connector {self.name} does not support UPDATE")
