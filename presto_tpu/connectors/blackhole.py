"""Blackhole connector: /dev/null tables with synthetic scans.

Analog of the reference's plugin/trino-blackhole (BlackHoleMetadata /
BlackHolePageSourceProvider): writes are accepted and discarded; scans
produce a configurable number of synthetic constant rows — used to
exercise writer paths and scan scheduling without storing data.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Table
from presto_tpu.connectors.base import Connector, TableStats


class BlackholeConnector(Connector):
    name = "blackhole"

    def __init__(self, rows_per_table: int = 0,
                 page_processing_delay_s: float = 0.0):
        self.rows_per_table = rows_per_table
        # synthetic scan latency (reference pageProcessingDelay) —
        # makes deterministic slow queries for scheduler/admission tests
        self.page_processing_delay_s = page_processing_delay_s
        self._schemas: dict[str, dict[str, T.DataType]] = {}
        self._rows: dict[str, int] = {}
        self.rows_written: dict[str, int] = {}

    def create_table(self, name: str, schema: Mapping[str, T.DataType],
                     data=None, valid=None) -> None:
        self._schemas[name] = dict(schema)
        self.rows_written[name] = 0
        if data is not None:  # CTAS: row count recorded, data dropped
            n = len(next(iter(data.values()), []))
            self.rows_written[name] = n

    def set_split_count(self, name: str, rows: int) -> None:
        """Configure the synthetic row count a scan of ``name`` yields
        (the reference configures rows_per_page x pages_per_split)."""
        self._rows[name] = rows

    def insert(self, name: str, data, valid=None) -> None:
        self.rows_written[name] += len(next(iter(data.values()), []))

    def drop_table(self, name: str) -> None:
        self._schemas.pop(name, None)
        self._rows.pop(name, None)
        self.rows_written.pop(name, None)

    def delete_rows(self, name: str, mask) -> int:
        return 0  # nothing stored, nothing deleted

    def table_names(self) -> list[str]:
        return list(self._schemas)

    def table_schema(self, name: str):
        return self._schemas[name]

    def table(self, name: str) -> Table:
        if self.page_processing_delay_s:
            import time
            from presto_tpu.exec.cancel import checkpoint
            # sleep in slices so a cancel lands mid-delay (the scan is
            # the cancellation seam, like Driver yield quanta)
            deadline = time.monotonic() + self.page_processing_delay_s
            while time.monotonic() < deadline:
                checkpoint()
                time.sleep(min(0.05, max(deadline - time.monotonic(), 0)))
            checkpoint()
        schema = self._schemas[name]
        n = self._rows.get(name, self.rows_per_table)
        cols = {}
        for c, dtype in schema.items():
            if isinstance(dtype, T.VarcharType):
                cols[c] = np.full(n, "", dtype=object)
            else:
                cols[c] = np.zeros(n, dtype=dtype.physical_dtype)
        return Table.from_numpy(schema, cols)

    def row_count_estimate(self, name: str) -> int:
        return max(self._rows.get(name, self.rows_per_table), 1)

    def stats(self, name: str) -> TableStats:
        return TableStats(row_count=self.row_count_estimate(name))
