"""Connector expression pushdown IR (reference
spi/expression/ConnectorExpression.java + ConnectorMetadata.applyFilter).

A deliberately small, connector-facing predicate language: per-column
comparisons against constants, conjunctions of them. The optimizer
offers a scan's filter conjuncts in this form; a connector may use them
to SKIP DATA IT CAN PROVE IRRELEVANT (parquet row-group min/max
pruning, partition elimination). Skipping is a superset guarantee — the
engine keeps the full filter above the scan, so connectors never need
to evaluate predicates exactly, only conservatively.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ColumnExpr:
    """A reference to the connector's column (source column NAME, not
    the plan symbol)."""

    column: str


@dataclasses.dataclass(frozen=True)
class ConstantExpr:
    """A literal in the column's PHYSICAL domain (dates as epoch days,
    decimals as scaled ints)."""

    value: object


@dataclasses.dataclass(frozen=True)
class ComparisonExpr:
    """column <op> constant, op in =, <>, <, <=, >, >=."""

    op: str
    column: ColumnExpr
    constant: ConstantExpr


def scan_conjuncts(predicate, assignments: dict[str, str]):
    """Extract pushable ComparisonExprs from a Filter predicate over a
    scan. ``assignments`` maps plan symbols -> connector column names.
    Unrecognized conjuncts are simply not offered (the full filter
    still runs above the scan)."""
    from presto_tpu.expr import ir

    out: list[ComparisonExpr] = []

    def walk(e):
        if isinstance(e, ir.Call) and e.fn == "and":
            for a in e.args:
                walk(a)
            return
        if isinstance(e, ir.Call) and e.fn in (
                "eq", "neq", "lt", "lte", "gt", "gte"):
            a, b = e.args
            if isinstance(b, ir.ColumnRef) and isinstance(a, ir.Literal):
                a, b = b, a
                flip = {"lt": "gt", "lte": "gte",
                        "gt": "lt", "gte": "lte"}
                fn = flip.get(e.fn, e.fn)
            elif isinstance(a, ir.ColumnRef) and isinstance(
                    b, ir.Literal):
                fn = e.fn
            else:
                return
            col = assignments.get(a.name)
            if col is None or b.value is None:
                return
            if not isinstance(b.value, (int, float)):
                return
            op = {"eq": "=", "neq": "<>", "lt": "<", "lte": "<=",
                  "gt": ">", "gte": ">="}[fn]
            out.append(ComparisonExpr(op, ColumnExpr(col),
                                      ConstantExpr(b.value)))

    walk(predicate)
    return out
