"""information_schema + system catalogs: engine metadata as tables.

Analog of the reference's engine-side virtual catalogs
(connector/informationschema/InformationSchemaMetadata.java +
connector/system/* — NodeSystemTable, QuerySystemTable, and the
information_schema page sources). Both connectors reflect the LIVE
engine state on every scan: registering a catalog or running a query is
immediately visible in the next SELECT.
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Table
from presto_tpu.connectors.base import Connector, TableStats


def _make_table(schema: dict, rows: list[tuple]) -> Table:
    cols = {}
    for i, (name, dtype) in enumerate(schema.items()):
        vals = [r[i] for r in rows]
        if isinstance(dtype, T.VarcharType):
            cols[name] = np.array(vals, dtype=object)
        else:
            cols[name] = np.asarray(vals, dtype=dtype.physical_dtype)
    return Table.from_numpy(schema, cols)


class _ReflectiveConnector(Connector):
    """Shared plumbing: schemas are static, rows are produced fresh per
    scan from the engine."""

    SCHEMAS: dict[str, dict[str, T.DataType]] = {}

    def __init__(self, engine):
        self.engine = engine

    def table_names(self) -> list[str]:
        return list(self.SCHEMAS)

    def table_schema(self, name: str):
        return self.SCHEMAS[name]

    def table(self, name: str) -> Table:
        return _make_table(self.SCHEMAS[name], self._rows(name))

    def row_count_estimate(self, name: str) -> int:
        return max(len(self._rows(name)), 1)

    def stats(self, name: str) -> TableStats:
        return TableStats(row_count=self.row_count_estimate(name))

    def _rows(self, name: str) -> list[tuple]:
        raise NotImplementedError


class InformationSchemaConnector(_ReflectiveConnector):
    """Catalog `information_schema` (reference
    connector/informationschema; the 2-part name model plays the role
    of the per-catalog schema)."""

    name = "information_schema"

    SCHEMAS = {
        "schemata": {
            "catalog_name": T.VARCHAR, "schema_name": T.VARCHAR,
        },
        "tables": {
            "table_catalog": T.VARCHAR, "table_schema": T.VARCHAR,
            "table_name": T.VARCHAR, "table_type": T.VARCHAR,
        },
        "columns": {
            "table_catalog": T.VARCHAR, "table_schema": T.VARCHAR,
            "table_name": T.VARCHAR, "column_name": T.VARCHAR,
            "ordinal_position": T.BIGINT, "data_type": T.VARCHAR,
            "is_nullable": T.VARCHAR,
        },
    }

    def _user_catalogs(self):
        return {name: c for name, c in self.engine.catalogs.items()
                if not isinstance(c, _ReflectiveConnector)}

    def _rows(self, name: str) -> list[tuple]:
        if name == "schemata":
            return [(cat, "default")
                    for cat in sorted(self._user_catalogs())]
        if name == "tables":
            return [(cat, "default", t, "BASE TABLE")
                    for cat, conn in sorted(self._user_catalogs().items())
                    for t in sorted(conn.table_names())]
        if name == "columns":
            rows = []
            for cat, conn in sorted(self._user_catalogs().items()):
                for t in sorted(conn.table_names()):
                    for i, (col, dtype) in enumerate(
                            conn.table_schema(t).items()):
                        rows.append((cat, "default", t, col, i + 1,
                                     str(dtype), "YES"))
            return rows
        raise KeyError(name)


class SystemConnector(_ReflectiveConnector):
    """Catalog `system`: runtime tables (reference connector/system
    NodeSystemTable, QuerySystemTable, and a session-properties table
    mirroring the jdbc/metadata ones)."""

    name = "system"

    SCHEMAS = {
        "nodes": {
            "node_id": T.VARCHAR, "http_uri": T.VARCHAR,
            "node_version": T.VARCHAR, "coordinator": T.VARCHAR,
            "state": T.VARCHAR,
        },
        "queries": {
            "query_id": T.VARCHAR, "state": T.VARCHAR,
            "user": T.VARCHAR, "query": T.VARCHAR,
            "output_rows": T.BIGINT, "wall_ms": T.BIGINT,
            "error": T.VARCHAR,
        },
        "session_properties": {
            "name": T.VARCHAR, "value": T.VARCHAR,
            "default": T.VARCHAR, "type": T.VARCHAR,
            "description": T.VARCHAR,
        },
    }

    def _rows(self, name: str) -> list[tuple]:
        if name == "nodes":
            return [("local", "local://0", "presto-tpu", "true",
                     "active")]
        if name == "queries":
            return [(e.query_id, e.state, e.user, e.sql,
                     e.output_rows, int(e.elapsed_ms), e.error or "")
                    for e in self.engine.events.history]
        if name == "session_properties":
            from presto_tpu.session import SYSTEM_SESSION_PROPERTIES
            return [(n, str(self.engine.session.get(n)), str(d),
                     t.__name__, desc)
                    for n, (d, t, desc) in sorted(
                        SYSTEM_SESSION_PROPERTIES.items())]
        raise KeyError(name)
