"""information_schema + system catalogs: engine metadata as tables.

Analog of the reference's engine-side virtual catalogs
(connector/informationschema/InformationSchemaMetadata.java +
connector/system/* — NodeSystemTable, QuerySystemTable, and the
information_schema page sources). Both connectors reflect the LIVE
engine state on every scan: registering a catalog or running a query is
immediately visible in the next SELECT.
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Table
from presto_tpu.connectors.base import Connector, TableStats


def _make_table(schema: dict, rows: list[tuple]) -> Table:
    cols = {}
    for i, (name, dtype) in enumerate(schema.items()):
        vals = [r[i] for r in rows]
        if isinstance(dtype, T.VarcharType):
            cols[name] = np.array(vals, dtype=object)
        else:
            cols[name] = np.asarray(vals, dtype=dtype.physical_dtype)
    return Table.from_numpy(schema, cols)


class _ReflectiveConnector(Connector):
    """Shared plumbing: schemas are static, rows are produced fresh per
    scan from the engine."""

    SCHEMAS: dict[str, dict[str, T.DataType]] = {}

    def __init__(self, engine):
        self.engine = engine

    def table_names(self) -> list[str]:
        return list(self.SCHEMAS)

    def table_schema(self, name: str):
        return self.SCHEMAS[name]

    def table(self, name: str) -> Table:
        return _make_table(self.SCHEMAS[name], self._rows(name))

    def row_count_estimate(self, name: str) -> int:
        return max(len(self._rows(name)), 1)

    def stats(self, name: str) -> TableStats:
        return TableStats(row_count=self.row_count_estimate(name))

    def _rows(self, name: str) -> list[tuple]:
        raise NotImplementedError


class InformationSchemaConnector(_ReflectiveConnector):
    """Catalog `information_schema` (reference
    connector/informationschema; the 2-part name model plays the role
    of the per-catalog schema)."""

    name = "information_schema"

    SCHEMAS = {
        "schemata": {
            "catalog_name": T.VARCHAR, "schema_name": T.VARCHAR,
        },
        "tables": {
            "table_catalog": T.VARCHAR, "table_schema": T.VARCHAR,
            "table_name": T.VARCHAR, "table_type": T.VARCHAR,
        },
        "columns": {
            "table_catalog": T.VARCHAR, "table_schema": T.VARCHAR,
            "table_name": T.VARCHAR, "column_name": T.VARCHAR,
            "ordinal_position": T.BIGINT, "data_type": T.VARCHAR,
            "is_nullable": T.VARCHAR,
        },
    }

    def _user_catalogs(self):
        return {name: c for name, c in self.engine.catalogs.items()
                if not isinstance(c, _ReflectiveConnector)}

    def _rows(self, name: str) -> list[tuple]:
        if name == "schemata":
            return [(cat, "default")
                    for cat in sorted(self._user_catalogs())]
        if name == "tables":
            return [(cat, "default", t, "BASE TABLE")
                    for cat, conn in sorted(self._user_catalogs().items())
                    for t in sorted(conn.table_names())]
        if name == "columns":
            rows = []
            for cat, conn in sorted(self._user_catalogs().items()):
                for t in sorted(conn.table_names()):
                    for i, (col, dtype) in enumerate(
                            conn.table_schema(t).items()):
                        rows.append((cat, "default", t, col, i + 1,
                                     str(dtype), "YES"))
            return rows
        raise KeyError(name)


class SystemConnector(_ReflectiveConnector):
    """Catalog `system`: runtime tables (reference connector/system —
    NodeSystemTable, QuerySystemTable, the task/optimizer-runtime
    tables of the ``system.runtime`` schema, and a session-properties
    table mirroring the jdbc/metadata ones). The stats-backed tables
    (`tasks`, `operator_stats`, `plan_divergence`) read the live
    obs/qstats recorders, so the engine can be debugged with itself
    MID-FLIGHT: a running query's tasks are visible to a concurrent
    ``SELECT * FROM system.tasks``."""

    name = "system"

    SCHEMAS = {
        "nodes": {
            "node_id": T.VARCHAR, "http_uri": T.VARCHAR,
            "node_version": T.VARCHAR, "coordinator": T.VARCHAR,
            "state": T.VARCHAR, "active_tasks": T.BIGINT,
        },
        "queries": {
            "query_id": T.VARCHAR, "state": T.VARCHAR,
            "user": T.VARCHAR, "query": T.VARCHAR,
            "output_rows": T.BIGINT, "wall_ms": T.BIGINT,
            "error": T.VARCHAR,
        },
        "tasks": {
            "query_id": T.VARCHAR, "stage": T.VARCHAR,
            "task_id": T.VARCHAR, "node": T.VARCHAR,
            "state": T.VARCHAR, "shard": T.BIGINT,
            "input_rows": T.BIGINT, "output_rows": T.BIGINT,
            "exchange_pages": T.BIGINT, "exchange_bytes": T.BIGINT,
            "exchange_bytes_arrow": T.BIGINT,
            "exchange_bytes_npz": T.BIGINT,
            "spooled_pages": T.BIGINT, "programs": T.BIGINT,
            "compiles": T.BIGINT, "cache_hits": T.BIGINT,
            "template_hits": T.BIGINT, "retries": T.BIGINT,
            "compile_ms": T.BIGINT, "execute_ms": T.BIGINT,
            "wall_ms": T.BIGINT, "peak_memory_bytes": T.BIGINT,
        },
        "operator_stats": {
            "query_id": T.VARCHAR, "stage": T.VARCHAR,
            "task_id": T.VARCHAR, "plan_node_id": T.VARCHAR,
            "node_type": T.VARCHAR, "label": T.VARCHAR,
            "input_rows": T.BIGINT, "output_rows": T.BIGINT,
            "output_bytes": T.BIGINT, "est_rows": T.BIGINT,
            # per-operator kernel attribution (presto_tpu/kernels/):
            # which backend:kernel pairs the operator dispatched, and
            # its cost-weighted share of the program's execute wall —
            # "which operator dominates" is answerable from SQL
            "kernel": T.VARCHAR, "wall_ms": T.BIGINT,
            # device-cost attribution (obs/devprof.py): the program's
            # XLA cost_analysis/memory_analysis split across its plan
            # nodes, plus arithmetic intensity (flops/byte) and the
            # roofline ratio against PRESTO_TPU_DEVICE_PEAK_FLOPS/_BW
            "flops": T.BIGINT, "hbm_bytes": T.BIGINT,
            "intensity": T.DOUBLE, "roofline": T.DOUBLE,
        },
        "plan_divergence": {
            "query_id": T.VARCHAR, "stage": T.VARCHAR,
            "plan_node_id": T.VARCHAR, "node_type": T.VARCHAR,
            "table_name": T.VARCHAR, "est_rows": T.BIGINT,
            "actual_rows": T.BIGINT, "ratio": T.DOUBLE,
        },
        # mid-query adaptive-execution audit (parallel/adaptive.py):
        # every remainder re-plan, per-node strategy flip, capacity
        # re-bucket and speculative re-dispatch, with the est-vs-
        # actual rows that triggered it and the old -> new strategy
        "adaptive_decisions": {
            "query_id": T.VARCHAR, "stage": T.VARCHAR,
            "kind": T.VARCHAR, "node_type": T.VARCHAR,
            "detail": T.VARCHAR, "est_rows": T.BIGINT,
            "actual_rows": T.BIGINT, "old_strategy": T.VARCHAR,
            "new_strategy": T.VARCHAR,
        },
        "query_history": {
            "query_id": T.VARCHAR, "state": T.VARCHAR,
            "user": T.VARCHAR, "query": T.VARCHAR,
            "output_rows": T.BIGINT, "wall_ms": T.BIGINT,
            "create_time": T.DOUBLE, "error": T.VARCHAR,
        },
        "session_properties": {
            "name": T.VARCHAR, "value": T.VARCHAR,
            "default": T.VARCHAR, "type": T.VARCHAR,
            "description": T.VARCHAR,
        },
        # the serving result cache (server/serving.py), entry by
        # entry: which plan fingerprints are cached against which
        # table versions, and how hard each entry is working
        "result_cache": {
            "fingerprint": T.VARCHAR, "tables": T.VARCHAR,
            "rows": T.BIGINT, "bytes": T.BIGINT,
            "hits": T.BIGINT, "age_ms": T.BIGINT,
        },
    }

    def _rows(self, name: str) -> list[tuple]:
        if name == "nodes":
            return self._node_rows()
        if name == "queries":
            return [(e.query_id, e.state, e.user, e.sql,
                     e.output_rows, int(e.elapsed_ms), e.error or "")
                    for e in self.engine.events.history]
        if name == "tasks":
            return self._task_rows()
        if name == "operator_stats":
            return self._operator_rows()
        if name == "plan_divergence":
            from presto_tpu.obs.qstats import DIVERGENCE
            return [(r["query_id"], r["stage"], r["plan_node_id"],
                     r["node_type"], r["table"], r["est_rows"],
                     r["actual_rows"], float(r["ratio"]))
                    for r in DIVERGENCE.records()]
        if name == "adaptive_decisions":
            from presto_tpu.obs.qstats import ADAPTIVE
            return [(r["query_id"], r["stage"], r["kind"],
                     r["node_type"], r["detail"], r["est_rows"],
                     r["actual_rows"], r["old_strategy"],
                     r["new_strategy"])
                    for r in ADAPTIVE.records()]
        if name == "query_history":
            history = getattr(self.engine, "history", None)
            if history is None:
                return []
            return [(str(r.get("query_id") or ""),
                     str(r.get("state") or ""),
                     str(r.get("user") or ""),
                     str(r.get("query") or ""),
                     int(r.get("output_rows") or 0),
                     int(float(r.get("elapsed_ms") or 0)),
                     float(r.get("create_time") or 0.0),
                     str(r.get("error") or ""))
                    for r in history.records()]
        if name == "session_properties":
            from presto_tpu.session import SYSTEM_SESSION_PROPERTIES
            return [(n, str(self.engine.session.get(n)), str(d),
                     t.__name__, desc)
                    for n, (d, t, desc) in sorted(
                        SYSTEM_SESSION_PROPERTIES.items())]
        if name == "result_cache":
            serving = getattr(self.engine, "_serving_view", None)
            if serving is None:
                return []
            return serving.cache.snapshot()
        raise KeyError(name)

    def _node_rows(self) -> list[tuple]:
        """Live cluster view: the coordinator plus every registered
        worker's heartbeat-observed state (alive / draining / dead)
        and active task count — wired to the same RemoteWorker state
        `/v1/cluster` serves, instead of the old hardcoded single
        local row (reference NodeSystemTable over the
        InternalNodeManager)."""
        rows = [("coordinator", "local://0", "presto-tpu", "true",
                 "active", 0)]
        cluster = getattr(self.engine, "_cluster_view", None)
        if cluster is None:
            return rows
        for w in list(cluster.workers):
            if w.state == "joining":
                # a joining node has no heartbeat history yet; its
                # decayed failure ratio must not label it dead
                state = "joining"
            elif not w.alive:
                state = "dead"
            elif w.state == "shutting_down":
                state = "draining"
            else:
                state = "active"
            rows.append((w.node_id or w.uri, w.uri, "presto-tpu",
                         "false", state, int(w.active_tasks)))
        return rows

    def _stage_tasks(self):
        """(query_id, stage, task dict) across every tracked query —
        remote stages first, then the coordinator-local stage, exactly
        the GET /v1/query/{id} tree flattened."""
        from presto_tpu.obs.qstats import STORE
        out = []
        for rec in STORE.recorders():
            snap = rec.snapshot()
            for stage in snap["stages"]:
                for t in stage["tasks"]:
                    out.append((snap["queryId"], stage["stage"], t))
        return out

    def _task_rows(self) -> list[tuple]:
        return [
            (qid, stage, t["taskId"], t["node"], t["state"],
             int(t["shard"]), int(t["inputRows"]),
             int(t["outputRows"]), int(t["exchangePages"]),
             int(t["exchangeBytes"]),
             int((t.get("exchangeBytesByCodec") or {})
                 .get("arrow", 0)),
             int((t.get("exchangeBytesByCodec") or {}).get("npz", 0)),
             int(t["spooledPages"]),
             int(t["programs"]), int(t["compiles"]),
             int(t["cacheHits"]), int(t["templateHits"]),
             int(t["retries"]), int(t["compileMillis"]),
             int(t["executeMillis"]), int(t["wallMillis"]),
             int(t["peakMemoryBytes"]))
            for qid, stage, t in self._stage_tasks()]

    def _operator_rows(self) -> list[tuple]:
        return [
            (qid, stage, t["taskId"], str(op["planNodeId"]),
             op["nodeType"], op["label"], int(op["inputRows"]),
             int(op["outputRows"]), int(op["outputBytes"]),
             int(op["estRows"]), str(op.get("kernel") or ""),
             int(op.get("wallMillis") or 0),
             int(op.get("flops") or 0), int(op.get("hbmBytes") or 0),
             float(op.get("intensity") or 0.0),
             float(op.get("roofline") or 0.0))
            for qid, stage, t in self._stage_tasks()
            for op in t["operators"]]
