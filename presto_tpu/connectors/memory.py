"""In-memory table connector.

Analog of the reference's plugin/trino-memory (MemoryPagesStore): tables
created/inserted at runtime, stored as host numpy columns plus optional
validity masks (NULL support matches spi Block.isNull).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Column, Table, column_from_numpy
from presto_tpu.connectors.base import Connector, TableStats


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self) -> None:
        self._schemas: dict[str, dict[str, T.DataType]] = {}
        self._data: dict[str, dict[str, np.ndarray]] = {}
        self._valid: dict[str, dict[str, np.ndarray | None]] = {}
        # monotonic per-table write counters backing table_version();
        # bumped by every mutation INCLUDING drop (a re-created table
        # must not resurrect cached results for its predecessor)
        self._versions: dict[str, int] = {}

    def _bump(self, name: str) -> None:
        self._versions[name] = self._versions.get(name, 0) + 1

    def table_version(self, name: str) -> int | None:
        return self._versions.get(name, 0)

    def create_table(
        self, name: str, schema: Mapping[str, T.DataType],
        data: Mapping[str, np.ndarray] | None = None,
        valid: Mapping[str, np.ndarray | None] | None = None,
    ) -> None:
        self._schemas[name] = dict(schema)
        if data is None:
            data = {c: np.empty(0, dtype=object if isinstance(t, T.VarcharType)
                                else t.physical_dtype)
                    for c, t in schema.items()}
        self._data[name] = {c: np.asarray(v, dtype=object if isinstance(
            self._schemas[name][c], T.VarcharType) else None)
            for c, v in data.items()}
        self._valid[name] = {c: (None if valid is None else valid.get(c))
                             for c in schema}
        self._bump(name)

    def insert(self, name: str, data: Mapping[str, np.ndarray],
               valid: Mapping[str, np.ndarray | None] | None = None) -> None:
        for i, c in enumerate(self._schemas[name]):
            new = np.asarray(data[c])
            old_n = len(self._data[name][c])
            self._data[name][c] = np.concatenate(
                [self._data[name][c], new])
            new_valid = None if valid is None else valid.get(c)
            old_valid = self._valid[name].get(c)
            if new_valid is not None or old_valid is not None:
                if old_valid is None:
                    old_valid = np.ones(old_n, dtype=bool)
                if new_valid is None:
                    new_valid = np.ones(len(new), dtype=bool)
                self._valid[name][c] = np.concatenate(
                    [old_valid, new_valid])
        self._bump(name)

    def delete_rows(self, name: str, mask) -> int:
        n = len(next(iter(self._data[name].values()), []))
        if mask is None:
            mask = np.ones(n, dtype=bool)
        keep = ~np.asarray(mask)
        for c in self._schemas[name]:
            self._data[name][c] = self._data[name][c][keep]
            v = self._valid[name].get(c)
            if v is not None:
                self._valid[name][c] = v[keep]
        self._bump(name)
        return int(mask.sum())

    def update_rows(self, name: str, values, valids, mask) -> int:
        n = len(next(iter(self._data[name].values()), []))
        if mask is None:
            mask = np.ones(n, dtype=bool)
        m = np.asarray(mask)
        for c, new in values.items():
            is_str = isinstance(self._schemas[name][c], T.VarcharType)
            arr = self._data[name][c]
            arr[m] = np.asarray(new, dtype=object if is_str else None)[m]
            nv = None if valids is None else valids.get(c)
            old_v = self._valid[name].get(c)
            if nv is not None or old_v is not None:
                if old_v is None:
                    old_v = np.ones(n, dtype=bool)
                new_v = nv if nv is not None else np.ones(n, dtype=bool)
                old_v[m] = np.asarray(new_v)[m]
                self._valid[name][c] = old_v
        self._bump(name)
        return int(m.sum())

    def snapshot(self):
        """Deep copy of the store for transaction rollback
        (transaction.py copy-on-first-write)."""
        return (
            {t: dict(cols) for t, cols in self._schemas.items()},
            {t: {c: np.copy(a) for c, a in cols.items()}
             for t, cols in self._data.items()},
            {t: {c: None if v is None else np.copy(v)
                 for c, v in cols.items()}
             for t, cols in self._valid.items()},
        )

    def restore(self, snap) -> None:
        schemas, data, valid = snap
        touched = set(self._schemas) | set(schemas)
        self._schemas = {t: dict(cols) for t, cols in schemas.items()}
        self._data = {t: dict(cols) for t, cols in data.items()}
        self._valid = {t: dict(cols) for t, cols in valid.items()}
        # counters stay monotonic across rollback: restored contents
        # differ from the post-write state, so the version must move
        for t in touched:
            self._bump(t)

    def drop_table(self, name: str) -> None:
        self._schemas.pop(name, None)
        self._data.pop(name, None)
        self._valid.pop(name, None)
        self._bump(name)

    def table_names(self) -> list[str]:
        return list(self._schemas)

    def table_schema(self, name: str):
        return self._schemas[name]

    def table(self, name: str) -> Table:
        schema = self._schemas[name]
        cols: dict[str, Column] = {}
        n = 0
        for c, dtype in schema.items():
            col = column_from_numpy(dtype, self._data[name][c],
                                    self._valid[name].get(c))
            cols[c] = col
            n = len(col)
        return Table(cols, n)

    def stats(self, name: str) -> TableStats:
        n = len(next(iter(self._data[name].values()))) if self._data[name] else 0
        return TableStats(row_count=n)

