"""In-memory table connector.

Analog of the reference's plugin/trino-memory (MemoryPagesStore): tables
created/inserted at runtime, stored as host numpy columns.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Table
from presto_tpu.connectors.base import Connector, TableStats


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self) -> None:
        self._schemas: dict[str, dict[str, T.DataType]] = {}
        self._data: dict[str, dict[str, np.ndarray]] = {}

    def create_table(
        self, name: str, schema: Mapping[str, T.DataType],
        data: Mapping[str, np.ndarray] | None = None,
    ) -> None:
        self._schemas[name] = dict(schema)
        if data is None:
            data = {c: np.empty(0, dtype=object if isinstance(t, T.VarcharType)
                                else t.physical_dtype)
                    for c, t in schema.items()}
        self._data[name] = {c: np.asarray(v, dtype=object if isinstance(
            self._schemas[name][c], T.VarcharType) else None)
            for c, v in data.items()}

    def insert(self, name: str, data: Mapping[str, np.ndarray]) -> None:
        for c in self._schemas[name]:
            self._data[name][c] = np.concatenate(
                [self._data[name][c], np.asarray(data[c])])

    def drop_table(self, name: str) -> None:
        self._schemas.pop(name, None)
        self._data.pop(name, None)

    def table_names(self) -> list[str]:
        return list(self._schemas)

    def table_schema(self, name: str):
        return self._schemas[name]

    def table(self, name: str) -> Table:
        return Table.from_numpy(self._schemas[name], self._data[name])

    def stats(self, name: str) -> TableStats:
        n = len(next(iter(self._data[name].values()))) if self._data[name] else 0
        return TableStats(row_count=n)
