"""Parquet catalog: tables backed by .parquet files on disk — the
engine's first non-synthetic data source (reference lib/trino-parquet
feeding the hive connector's page source; here the from-scratch reader
in formats/parquet.py feeds device columns through the standard
connector SPI).

Layout: a directory where each table is either ``<name>.parquet`` or a
subdirectory ``<name>/`` of part files (concatenated in sorted order —
the multi-file table layout hive-style writers produce).
"""

from __future__ import annotations

import os
from typing import Mapping

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Column, Table, column_from_numpy
from presto_tpu.connectors.base import Connector, TableStats
from presto_tpu.formats.parquet import ParquetFile


class ParquetConnector(Connector):
    name = "parquet"

    def __init__(self, directory: str):
        self.directory = directory
        self._tables: dict[str, Table] = {}  # base-name tables
        # constrained ('#rg:' decorated) materializations, bounded:
        # every new filter constant mints a new token
        self._constrained: dict[str, Table] = {}
        self._pf_cache: dict[str, ParquetFile] = {}
        self._files: dict[str, list[str]] = {}
        for entry in sorted(os.listdir(directory)):
            full = os.path.join(directory, entry)
            if entry.endswith(".parquet") and os.path.isfile(full):
                self._files[entry[:-len(".parquet")]] = [full]
            elif os.path.isdir(full):
                parts = sorted(
                    os.path.join(full, f) for f in os.listdir(full)
                    if f.endswith(".parquet"))
                if parts:
                    self._files[entry] = parts

    def table_names(self) -> list[str]:
        return sorted(self._files)

    # decorated names: "<table>#rg:<file>=<g0>,<g1>;..." select a
    # row-group subset chosen by apply_filter (reference applyFilter
    # returning a constrained ConnectorTableHandle)
    @staticmethod
    def _parse_name(name: str):
        if "#rg:" not in name:
            return name, None
        base, spec = name.split("#rg:", 1)
        keep: dict[int, list[int]] = {}
        for part in spec.split(";"):
            if not part:
                continue
            fi, gs = part.split("=")
            keep[int(fi)] = ([int(g) for g in gs.split(",")]
                             if gs else [])
        return base, keep

    def _meta(self, name: str) -> list[ParquetFile]:
        base, _keep = self._parse_name(name)
        if base not in self._files:
            raise KeyError(f"no parquet table {base}")
        out = []
        for path in self._files[base]:
            pf = self._pf_cache.get(path)
            if pf is None:
                pf = self._pf_cache[path] = ParquetFile(path)
            out.append(pf)
        return out

    def apply_filter(self, name: str, conjuncts) -> str | None:
        """Row-group pruning from footer min/max statistics: keep only
        groups whose [min, max] can intersect every conjunct
        (reference parquet TupleDomainParquetPredicate +
        ConnectorMetadata.applyFilter). Returns a decorated table name,
        or None when nothing prunes."""
        from presto_tpu.connectors.expression import ComparisonExpr

        base, _ = self._parse_name(name)
        files = self._meta(base)
        spec_parts = []
        pruned_any = False
        for fi, f in enumerate(files):
            ngroups = len(f.row_groups)
            keep = list(range(ngroups))
            stats_cache: dict[str, list] = {}
            for c in conjuncts:
                if not isinstance(c, ComparisonExpr):
                    continue
                v = c.constant.value
                if not isinstance(v, (int, float)):
                    continue
                col = c.column.column
                if col not in stats_cache:
                    try:
                        stats_cache[col] = f.column_stats(col)
                    except Exception:
                        stats_cache[col] = [None] * ngroups
                stats = stats_cache[col]
                kept = []
                for g in keep:
                    st = stats[g]
                    if st is None:
                        kept.append(g)
                        continue
                    mn, mx = st
                    ok = {"=": mn <= v <= mx, "<>": True,
                          "<": mn < v, "<=": mn <= v,
                          ">": mx > v, ">=": mx >= v}[c.op]
                    if ok:
                        kept.append(g)
                keep = kept
            if not keep and ngroups:
                # keep one group so the scan keeps a static shape; the
                # engine's filter above the scan drops its rows
                keep = [0]
            if len(keep) < ngroups:
                pruned_any = True
            spec_parts.append(
                f"{fi}=" + ",".join(str(g) for g in keep))
        if not pruned_any:
            return None
        return f"{base}#rg:" + ";".join(spec_parts)

    def table_schema(self, name: str) -> Mapping[str, T.DataType]:
        return self._meta(name)[0].schema()

    def row_count_estimate(self, name: str) -> int:
        # footers only — no data pages decode
        base, keep = self._parse_name(name)
        files = self._meta(base)
        if keep is None:
            return max(1, sum(f.num_rows for f in files))
        total = 0
        for fi, f in enumerate(files):
            for g in keep.get(fi, range(len(f.row_groups))):
                total += int(f.row_groups[g][3])
        return max(1, total)

    def stats(self, name: str) -> TableStats:
        return TableStats(row_count=self.row_count_estimate(name))

    def table(self, name: str) -> Table:
        cached = (self._tables.get(name)
                  or self._constrained.get(name))
        if cached is not None:
            return cached
        base, keep = self._parse_name(name)
        files = self._meta(base)
        schema = files[0].schema()
        cols: dict[str, Column] = {}
        for cname, dtype in schema.items():
            vals_parts = []
            valid_parts = []
            any_null = False
            for fi, f in enumerate(files):
                v, ok = f.read_column(
                    cname, None if keep is None else keep.get(fi))
                vals_parts.append(v)
                valid_parts.append(
                    ok if ok is not None else np.ones(len(v), bool))
                any_null = any_null or ok is not None
            if len(vals_parts) == 1:
                vals = vals_parts[0]
            elif vals_parts and vals_parts[0].ndim == 2:
                vals = np.vstack(vals_parts)
            else:
                vals = np.concatenate(vals_parts)
            valid = (np.concatenate(valid_parts) if any_null else None)
            if isinstance(dtype, T.DecimalType) and dtype.is_long:
                cols[cname] = Column(dtype, vals, valid)
            else:
                cols[cname] = column_from_numpy(dtype, vals, valid)
        nrows = len(next(iter(cols.values())).data) if cols else 0
        tbl = Table(cols, nrows)
        if keep is None:
            self._tables[name] = tbl
        else:
            if len(self._constrained) >= 4:
                self._constrained.pop(next(iter(self._constrained)))
            self._constrained[name] = tbl
        return tbl
