"""Parquet catalog: tables backed by .parquet files on disk — the
engine's first non-synthetic data source (reference lib/trino-parquet
feeding the hive connector's page source; here the from-scratch reader
in formats/parquet.py feeds device columns through the standard
connector SPI).

Layout: a directory where each table is either ``<name>.parquet`` or a
subdirectory ``<name>/`` of part files (concatenated in sorted order —
the multi-file table layout hive-style writers produce).
"""

from __future__ import annotations

import os
from typing import Mapping

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Column, Table, column_from_numpy
from presto_tpu.connectors.base import Connector, TableStats
from presto_tpu.formats.parquet import ParquetFile


class ParquetConnector(Connector):
    name = "parquet"

    def __init__(self, directory: str):
        self.directory = directory
        self._tables: dict[str, Table] = {}
        self._files: dict[str, list[str]] = {}
        for entry in sorted(os.listdir(directory)):
            full = os.path.join(directory, entry)
            if entry.endswith(".parquet") and os.path.isfile(full):
                self._files[entry[:-len(".parquet")]] = [full]
            elif os.path.isdir(full):
                parts = sorted(
                    os.path.join(full, f) for f in os.listdir(full)
                    if f.endswith(".parquet"))
                if parts:
                    self._files[entry] = parts

    def table_names(self) -> list[str]:
        return sorted(self._files)

    def _meta(self, name: str) -> list[ParquetFile]:
        if name not in self._files:
            raise KeyError(f"no parquet table {name}")
        return [ParquetFile(p) for p in self._files[name]]

    def table_schema(self, name: str) -> Mapping[str, T.DataType]:
        return self._meta(name)[0].schema()

    def row_count_estimate(self, name: str) -> int:
        # footers only — no data pages decode
        return max(1, sum(f.num_rows for f in self._meta(name)))

    def stats(self, name: str) -> TableStats:
        return TableStats(row_count=self.row_count_estimate(name))

    def table(self, name: str) -> Table:
        cached = self._tables.get(name)
        if cached is not None:
            return cached
        files = self._meta(name)
        schema = files[0].schema()
        cols: dict[str, Column] = {}
        for cname, dtype in schema.items():
            vals_parts = []
            valid_parts = []
            any_null = False
            for f in files:
                v, ok = f.read_column(cname)
                vals_parts.append(v)
                valid_parts.append(
                    ok if ok is not None else np.ones(len(v), bool))
                any_null = any_null or ok is not None
            if len(vals_parts) == 1:
                vals = vals_parts[0]
            elif vals_parts and vals_parts[0].ndim == 2:
                vals = np.vstack(vals_parts)
            else:
                vals = np.concatenate(vals_parts)
            valid = (np.concatenate(valid_parts) if any_null else None)
            if isinstance(dtype, T.DecimalType) and dtype.is_long:
                cols[cname] = Column(dtype, vals, valid)
            else:
                cols[cname] = column_from_numpy(dtype, vals, valid)
        nrows = len(next(iter(cols.values())).data) if cols else 0
        tbl = Table(cols, nrows)
        self._tables[name] = tbl
        return tbl
