"""Row-range split view over another connector.

The multi-host analog of connector splits (split/SplitManager.java,
plugin/trino-tpch/.../TpchSplitManager.java:55 — dsdgen generates each
split's row range independently): a worker assigned split (shard,
nshards) sees every table of the base catalog restricted to its
contiguous row range, so workers scan disjoint row ranges of the same
deterministic tables without any coordinator data movement.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.block import Column, Table
from presto_tpu.connectors.base import Connector, TableStats


class SplitConnector(Connector):
    name = "split"

    def __init__(self, base: Connector, shard: int, nshards: int):
        assert 0 <= shard < nshards
        self.base = base
        self.shard = shard
        self.nshards = nshards

    def _range(self, name: str, n: int) -> tuple[int, int]:
        per = -(-n // self.nshards)
        return min(self.shard * per, n), min((self.shard + 1) * per, n)

    def table_names(self) -> list[str]:
        return self.base.table_names()

    def table_schema(self, name: str):
        return self.base.table_schema(name)

    def table(self, name: str) -> Table:
        t = self.base.table(name)
        lo, hi = self._range(name, t.nrows)
        cols = {}
        for c, col in t.columns.items():
            cols[c] = Column(
                col.dtype, np.asarray(col.data)[lo:hi],
                None if col.valid is None
                else np.asarray(col.valid)[lo:hi],
                col.dictionary)
        # base tables carry no selection mask (connector contract)
        return Table(cols, hi - lo, None)

    def row_count_estimate(self, name: str) -> int:
        return max(1, self.base.row_count_estimate(name) // self.nshards)

    def ndv_estimates(self, name: str) -> dict[str, int]:
        return self.base.ndv_estimates(name)

    def unique_keys(self, name: str):
        return self.base.unique_keys(name)

    def column_range_estimates(self, name: str):
        # value ranges survive row splitting; without this forwarding
        # the dense-key join annotation (plan/dense.py) silently
        # disappears on workers
        return self.base.column_range_estimates(name)

    def stats(self, name: str) -> TableStats:
        return TableStats(row_count=self.row_count_estimate(name))
