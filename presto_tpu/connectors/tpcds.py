"""TPC-DS catalog: schemas + deterministic synthetic data generation.

Analog of the reference's plugin/trino-tpcds (TpcdsConnectorFactory over
io.trino.tpcds dsdgen). Schemas follow the TPC-DS specification for the
core star-schema tables; generation is a simplified deterministic model
(uniform/zipf-ish draws seeded per table) — enough for planner/executor
parity work and oracle-checked query correctness at small scales. The
reference's dsdgen fidelity (exact row contents) is NOT reproduced; the
oracle cross-check keeps correctness honest because both sides read the
same generated data.
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Table
from presto_tpu.connectors.base import Connector, TableStats

DEC2 = T.DecimalType(7, 2)

_D = lambda s: int((np.datetime64(s) - np.datetime64("1970-01-01"))
                   .astype(int))

SCHEMAS: dict[str, dict[str, T.DataType]] = {
    "date_dim": {
        "d_date_sk": T.BIGINT, "d_date_id": T.VARCHAR, "d_date": T.DATE,
        "d_year": T.BIGINT, "d_moy": T.BIGINT, "d_dom": T.BIGINT,
        "d_qoy": T.BIGINT, "d_dow": T.BIGINT,
        "d_day_name": T.VARCHAR,
        "d_month_seq": T.BIGINT, "d_week_seq": T.BIGINT,
        "d_quarter_name": T.VARCHAR,
    },
    "item": {
        "i_item_sk": T.BIGINT, "i_item_id": T.VARCHAR,
        "i_item_desc": T.VARCHAR, "i_current_price": DEC2,
        "i_wholesale_cost": DEC2, "i_brand_id": T.BIGINT,
        "i_brand": T.VARCHAR, "i_class_id": T.BIGINT,
        "i_class": T.VARCHAR, "i_category_id": T.BIGINT,
        "i_category": T.VARCHAR, "i_manufact_id": T.BIGINT,
        "i_manufact": T.VARCHAR, "i_manager_id": T.BIGINT,
        "i_color": T.VARCHAR, "i_product_name": T.VARCHAR,
        "i_size": T.VARCHAR, "i_units": T.VARCHAR,
    },
    "customer": {
        "c_customer_sk": T.BIGINT, "c_customer_id": T.VARCHAR,
        "c_current_cdemo_sk": T.BIGINT, "c_current_hdemo_sk": T.BIGINT,
        "c_current_addr_sk": T.BIGINT, "c_first_name": T.VARCHAR,
        "c_last_name": T.VARCHAR, "c_birth_year": T.BIGINT,
        "c_birth_country": T.VARCHAR, "c_email_address": T.VARCHAR,
        "c_salutation": T.VARCHAR, "c_preferred_cust_flag": T.VARCHAR,
        "c_birth_month": T.BIGINT, "c_birth_day": T.BIGINT,
        "c_login": T.VARCHAR, "c_first_sales_date_sk": T.BIGINT,
        "c_first_shipto_date_sk": T.BIGINT,
        "c_last_review_date_sk": T.BIGINT,
    },
    "customer_address": {
        "ca_address_sk": T.BIGINT, "ca_address_id": T.VARCHAR,
        "ca_city": T.VARCHAR, "ca_county": T.VARCHAR,
        "ca_state": T.VARCHAR, "ca_zip": T.VARCHAR,
        "ca_country": T.VARCHAR, "ca_gmt_offset": T.DecimalType(5, 2),
        "ca_street_number": T.VARCHAR, "ca_street_name": T.VARCHAR,
        "ca_street_type": T.VARCHAR, "ca_suite_number": T.VARCHAR,
        "ca_location_type": T.VARCHAR,
    },
    "customer_demographics": {
        "cd_demo_sk": T.BIGINT, "cd_gender": T.VARCHAR,
        "cd_marital_status": T.VARCHAR,
        "cd_education_status": T.VARCHAR,
        "cd_purchase_estimate": T.BIGINT,
        "cd_credit_rating": T.VARCHAR, "cd_dep_count": T.BIGINT,
        "cd_dep_employed_count": T.BIGINT,
        "cd_dep_college_count": T.BIGINT,
    },
    "household_demographics": {
        "hd_demo_sk": T.BIGINT, "hd_income_band_sk": T.BIGINT,
        "hd_buy_potential": T.VARCHAR, "hd_dep_count": T.BIGINT,
        "hd_vehicle_count": T.BIGINT,
    },
    "store": {
        "s_store_sk": T.BIGINT, "s_store_id": T.VARCHAR,
        "s_store_name": T.VARCHAR, "s_number_employees": T.BIGINT,
        "s_city": T.VARCHAR, "s_county": T.VARCHAR,
        "s_state": T.VARCHAR, "s_gmt_offset": T.DecimalType(5, 2),
        "s_company_id": T.BIGINT, "s_company_name": T.VARCHAR,
        "s_zip": T.VARCHAR, "s_market_id": T.BIGINT,
        "s_street_number": T.VARCHAR, "s_street_name": T.VARCHAR,
        "s_street_type": T.VARCHAR, "s_suite_number": T.VARCHAR,
    },
    "warehouse": {
        "w_warehouse_sk": T.BIGINT, "w_warehouse_id": T.VARCHAR,
        "w_warehouse_name": T.VARCHAR, "w_warehouse_sq_ft": T.BIGINT,
        "w_city": T.VARCHAR, "w_state": T.VARCHAR,
        "w_county": T.VARCHAR, "w_country": T.VARCHAR,
    },
    "promotion": {
        "p_promo_sk": T.BIGINT, "p_promo_id": T.VARCHAR,
        "p_channel_dmail": T.VARCHAR, "p_channel_email": T.VARCHAR,
        "p_channel_tv": T.VARCHAR, "p_promo_name": T.VARCHAR,
        "p_channel_event": T.VARCHAR,
    },
    "store_sales": {
        "ss_sold_date_sk": T.BIGINT, "ss_sold_time_sk": T.BIGINT,
        "ss_item_sk": T.BIGINT,
        "ss_customer_sk": T.BIGINT, "ss_cdemo_sk": T.BIGINT,
        "ss_hdemo_sk": T.BIGINT, "ss_addr_sk": T.BIGINT,
        "ss_store_sk": T.BIGINT, "ss_promo_sk": T.BIGINT,
        "ss_ticket_number": T.BIGINT, "ss_quantity": T.BIGINT,
        "ss_wholesale_cost": DEC2, "ss_list_price": DEC2,
        "ss_sales_price": DEC2, "ss_ext_discount_amt": DEC2,
        "ss_ext_sales_price": DEC2, "ss_ext_wholesale_cost": DEC2,
        "ss_ext_list_price": DEC2, "ss_coupon_amt": DEC2,
        "ss_net_paid": DEC2, "ss_net_profit": DEC2,
        "ss_ext_tax": DEC2,
    },
    "catalog_sales": {
        "cs_sold_date_sk": T.BIGINT, "cs_item_sk": T.BIGINT,
        "cs_bill_customer_sk": T.BIGINT, "cs_ship_customer_sk": T.BIGINT,
        "cs_bill_cdemo_sk": T.BIGINT, "cs_bill_hdemo_sk": T.BIGINT,
        "cs_ship_date_sk": T.BIGINT, "cs_warehouse_sk": T.BIGINT,
        "cs_ship_mode_sk": T.BIGINT, "cs_call_center_sk": T.BIGINT,
        "cs_promo_sk": T.BIGINT, "cs_order_number": T.BIGINT,
        "cs_quantity": T.BIGINT, "cs_wholesale_cost": DEC2,
        "cs_list_price": DEC2, "cs_sales_price": DEC2,
        "cs_ext_discount_amt": DEC2, "cs_ext_sales_price": DEC2,
        "cs_ext_wholesale_cost": DEC2, "cs_ext_list_price": DEC2,
        "cs_ext_ship_cost": DEC2, "cs_coupon_amt": DEC2,
        "cs_net_paid": DEC2, "cs_net_profit": DEC2,
        "cs_bill_addr_sk": T.BIGINT, "cs_ship_addr_sk": T.BIGINT,
        "cs_sold_time_sk": T.BIGINT, "cs_catalog_page_sk": T.BIGINT,
        "cs_net_paid_inc_tax": DEC2,
    },
    "web_sales": {
        "ws_sold_date_sk": T.BIGINT, "ws_sold_time_sk": T.BIGINT,
        "ws_item_sk": T.BIGINT,
        "ws_bill_customer_sk": T.BIGINT, "ws_ship_customer_sk": T.BIGINT,
        "ws_ship_hdemo_sk": T.BIGINT, "ws_ship_addr_sk": T.BIGINT,
        "ws_ship_date_sk": T.BIGINT, "ws_warehouse_sk": T.BIGINT,
        "ws_web_site_sk": T.BIGINT, "ws_web_page_sk": T.BIGINT,
        "ws_ship_mode_sk": T.BIGINT,
        "ws_promo_sk": T.BIGINT, "ws_order_number": T.BIGINT,
        "ws_quantity": T.BIGINT, "ws_list_price": DEC2,
        "ws_sales_price": DEC2,
        "ws_ext_discount_amt": DEC2, "ws_ext_sales_price": DEC2,
        "ws_ext_wholesale_cost": DEC2, "ws_ext_ship_cost": DEC2,
        "ws_net_paid": DEC2, "ws_net_profit": DEC2,
        "ws_bill_addr_sk": T.BIGINT, "ws_wholesale_cost": DEC2,
        "ws_ext_list_price": DEC2,
    },
    "catalog_returns": {
        "cr_returned_date_sk": T.BIGINT, "cr_item_sk": T.BIGINT,
        "cr_order_number": T.BIGINT,
        "cr_returning_customer_sk": T.BIGINT,
        "cr_call_center_sk": T.BIGINT,
        "cr_return_quantity": T.BIGINT, "cr_return_amount": DEC2,
        "cr_refunded_cash": DEC2, "cr_net_loss": DEC2,
        "cr_returning_addr_sk": T.BIGINT, "cr_reversed_charge": DEC2,
        "cr_catalog_page_sk": T.BIGINT, "cr_return_amt_inc_tax": DEC2,
        "cr_store_credit": DEC2,
    },
    "web_returns": {
        "wr_returned_date_sk": T.BIGINT, "wr_item_sk": T.BIGINT,
        "wr_order_number": T.BIGINT,
        "wr_returning_customer_sk": T.BIGINT,
        "wr_return_quantity": T.BIGINT, "wr_return_amt": DEC2,
        "wr_refunded_cash": DEC2, "wr_net_loss": DEC2,
        "wr_refunded_cdemo_sk": T.BIGINT,
        "wr_returning_addr_sk": T.BIGINT,
        "wr_returning_cdemo_sk": T.BIGINT,
        "wr_refunded_addr_sk": T.BIGINT,
        "wr_reason_sk": T.BIGINT, "wr_web_page_sk": T.BIGINT,
        "wr_fee": DEC2,
    },
    "web_site": {
        "web_site_sk": T.BIGINT, "web_site_id": T.VARCHAR,
        "web_name": T.VARCHAR, "web_company_name": T.VARCHAR,
    },
    "web_page": {
        "wp_web_page_sk": T.BIGINT, "wp_web_page_id": T.VARCHAR,
        "wp_char_count": T.BIGINT,
    },
    "time_dim": {
        "t_time_sk": T.BIGINT, "t_time_id": T.VARCHAR,
        "t_time": T.BIGINT, "t_hour": T.BIGINT,
        "t_minute": T.BIGINT, "t_second": T.BIGINT,
        "t_meal_time": T.VARCHAR,
    },
    "ship_mode": {
        "sm_ship_mode_sk": T.BIGINT, "sm_ship_mode_id": T.VARCHAR,
        "sm_type": T.VARCHAR, "sm_carrier": T.VARCHAR,
        "sm_code": T.VARCHAR,
    },
    "store_returns": {
        "sr_returned_date_sk": T.BIGINT, "sr_item_sk": T.BIGINT,
        "sr_customer_sk": T.BIGINT, "sr_ticket_number": T.BIGINT,
        "sr_reason_sk": T.BIGINT,
        "sr_return_quantity": T.BIGINT, "sr_return_amt": DEC2,
        "sr_net_loss": DEC2, "sr_store_sk": T.BIGINT,
        "sr_cdemo_sk": T.BIGINT,
    },
    "inventory": {
        "inv_date_sk": T.BIGINT, "inv_item_sk": T.BIGINT,
        "inv_warehouse_sk": T.BIGINT,
        "inv_quantity_on_hand": T.BIGINT,
    },
    "reason": {
        "r_reason_sk": T.BIGINT, "r_reason_id": T.VARCHAR,
        "r_reason_desc": T.VARCHAR,
    },
    "income_band": {
        "ib_income_band_sk": T.BIGINT, "ib_lower_bound": T.BIGINT,
        "ib_upper_bound": T.BIGINT,
    },
    "call_center": {
        "cc_call_center_sk": T.BIGINT, "cc_call_center_id": T.VARCHAR,
        "cc_name": T.VARCHAR, "cc_class": T.VARCHAR,
        "cc_employees": T.BIGINT, "cc_manager": T.VARCHAR,
        "cc_county": T.VARCHAR,
    },
    "catalog_page": {
        "cp_catalog_page_sk": T.BIGINT, "cp_catalog_page_id": T.VARCHAR,
        "cp_department": T.VARCHAR, "cp_catalog_number": T.BIGINT,
        "cp_catalog_page_number": T.BIGINT, "cp_type": T.VARCHAR,
    },
}

_BASE_ROWS = {
    "date_dim": 2556,  # 7 years of days
    "item": 18_000, "customer": 100_000, "customer_address": 50_000,
    "customer_demographics": 19_208, "household_demographics": 7_200,
    "store": 12, "warehouse": 5, "promotion": 300,
    "store_sales": 2_880_000, "catalog_sales": 1_440_000,
    "web_sales": 720_000, "store_returns": 288_000,
    "catalog_returns": 144_000, "web_returns": 72_000,
    "inventory": 783_000,
    "web_site": 30, "web_page": 60, "time_dim": 86_400,
    "ship_mode": 20,
    "reason": 35, "income_band": 20, "call_center": 6,
    "catalog_page": 11_718,
}

_UNIQUE = {
    # business identifiers (c_customer_id, i_item_id are spec-unique)
    # matter for FD-based group-key reduction: q4/q11/q74 group the
    # year_total CTE by customer_id plus its dependent attributes
    "date_dim": [("d_date_sk",), ("d_date",)],
    "item": [("i_item_sk",)],
    "customer": [("c_customer_sk",), ("c_customer_id",)],
    "customer_address": [("ca_address_sk",)],
    "customer_demographics": [("cd_demo_sk",)],
    "household_demographics": [("hd_demo_sk",)],
    "store": [("s_store_sk",)], "warehouse": [("w_warehouse_sk",)],
    "promotion": [("p_promo_sk",)],
    "web_site": [("web_site_sk",)], "web_page": [("wp_web_page_sk",)],
    "time_dim": [("t_time_sk",)], "ship_mode": [("sm_ship_mode_sk",)],
    "reason": [("r_reason_sk",)],
    "income_band": [("ib_income_band_sk",)],
    "call_center": [("cc_call_center_sk",)],
    "catalog_page": [("cp_catalog_page_sk",)],
}

_CATEGORIES = ["Home", "Books", "Electronics", "Shoes", "Women", "Men",
               "Jewelry", "Sports", "Music", "Children"]
_CLASSES = ["accent", "classical", "fiction", "fitness", "athletic",
            "portable", "dresses", "pants", "birdal", "estate",
            "maternity", "infants", "swimwear", "country", "rock"]
_STATES = ["TN", "GA", "OH", "TX", "CA", "NY", "WA", "IL", "MI", "NC"]
# dsdgen-style syllable brands referenced verbatim by official query
# filters (q53/q63/q89 and kin)
_BRANDS = ["amalgimporto #1", "importoamalg #1", "scholaramalgamalg #7",
           "scholaramalgamalg #9", "scholaramalgamalg #14",
           "exportiunivamalg #9", "edu packscholar #1", "exportischolar #1",
           "exportiexporti #1", "amalgamalg #1", "univamalgamalg #10",
           "maxinameless #4"]
_COLORS = ["red", "blue", "green", "yellow", "black", "white", "purple",
           "orange", "pink", "brown", "chartreuse", "ivory", "slate",
           "khaki", "salmon", "plum"]
_CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville",
           "Liberty", "Pleasant Hill", "Riverside", "Salem", "Union"]
_DAYNAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
_FIRST = ["James", "Mary", "John", "Linda", "Robert", "Susan", "David",
          "Karen", "Paul", "Nancy"]
_LAST = ["Smith", "Johnson", "Brown", "Jones", "Miller", "Davis",
         "Wilson", "Moore", "Taylor", "White"]


class TpcdsGenerator:
    START = _D("1998-01-01")

    def __init__(self, scale: float, seed: int = 20030527,
                 sales_provider=None):
        self.scale = scale
        self.seed = seed
        # Returns tables sample real (order_number, item_sk, ...) rows
        # from their sales table so the join keys hit. The connector
        # wires this to its Table cache so the numeric sales arrays are
        # held once; standalone generators fall back to regeneration.
        self._sales = sales_provider or self.generate

    def rows(self, name: str) -> int:
        base = _BASE_ROWS[name]
        if name in ("date_dim", "store", "warehouse", "promotion",
                    "customer_demographics", "household_demographics",
                    "web_site", "web_page", "time_dim", "ship_mode",
                    "reason", "income_band", "call_center",
                    "catalog_page"):
            return base
        return max(10, int(base * self.scale))

    def _rng(self, salt: int) -> np.random.Generator:
        return np.random.default_rng(self.seed + salt * 7919)

    def generate(self, name: str) -> dict[str, np.ndarray]:
        return getattr(self, "_g_" + name)()

    def _g_date_dim(self):
        n = self.rows("date_dim")
        sk = np.arange(1, n + 1)
        dates = self.START + np.arange(n)
        civil = (np.datetime64("1970-01-01")
                 + dates.astype("timedelta64[D]"))
        years = civil.astype("datetime64[Y]").astype(int) + 1970
        months = civil.astype("datetime64[M]").astype(int) % 12 + 1
        dom = (civil - civil.astype("datetime64[M]")).astype(int) + 1
        dow = (dates + 4) % 7
        return {
            "d_date_sk": sk,
            "d_date_id": np.array([f"AAAAAAAA{sk_:010d}" for sk_ in sk],
                                  object),
            "d_date": dates.astype(np.int32),
            "d_year": years, "d_moy": months, "d_dom": dom,
            "d_qoy": (months - 1) // 3 + 1,
            "d_dow": dow,
            "d_day_name": np.array(_DAYNAMES, object)[dow],
            "d_month_seq": (years - 1900) * 12 + months - 1,
            "d_week_seq": (dates - self.START) // 7,
            "d_quarter_name": np.array(
                [f"{y}Q{q}" for y, q in
                 zip(years, (months - 1) // 3 + 1)], object),
        }

    def _g_item(self):
        n = self.rows("item")
        rng = self._rng(1)
        sk = np.arange(1, n + 1)
        brand_id = rng.integers(1, 1000, n) * 10 + rng.integers(1, 10, n)
        cat = rng.integers(0, len(_CATEGORIES), n)
        cls = rng.integers(0, len(_CLASSES), n)
        manu = rng.integers(1, 1000, n)
        return {
            "i_item_sk": sk,
            "i_item_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "i_item_desc": np.array(
                [f"item description {sk_ % 997}" for sk_ in sk], object),
            "i_current_price": rng.integers(100, 10000, n),
            "i_wholesale_cost": rng.integers(50, 7000, n),
            "i_brand_id": brand_id,
            "i_brand": np.where(
                brand_id % 7 < 3,
                np.array(_BRANDS, object)[brand_id % len(_BRANDS)],
                np.array([f"brand#{b}" for b in brand_id % 500],
                         object)),
            "i_class_id": cls + 1,
            "i_class": np.array(_CLASSES, object)[cls],
            "i_category_id": cat + 1,
            "i_category": np.array(_CATEGORIES, object)[cat],
            "i_manufact_id": manu,
            "i_manufact": np.array(
                [f"manufact#{m}" for m in manu % 200], object),
            "i_manager_id": rng.integers(1, 100, n),
            "i_color": np.array(_COLORS, object)[
                rng.integers(0, len(_COLORS), n)],
            "i_product_name": np.array(
                [f"product{sk_ % 4999}n" for sk_ in sk], object),
            "i_size": np.array(
                ["small", "medium", "large", "extra large", "economy",
                 "N/A", "petite"], object)[rng.integers(0, 7, n)],
            "i_units": np.array(
                ["Each", "Dozen", "Case", "Pallet", "Box"], object)[
                rng.integers(0, 5, n)],
        }

    def _g_customer(self):
        n = self.rows("customer")
        rng = self._rng(2)
        sk = np.arange(1, n + 1)
        return {
            "c_customer_sk": sk,
            "c_customer_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "c_current_cdemo_sk": rng.integers(
                1, self.rows("customer_demographics") + 1, n),
            "c_current_hdemo_sk": rng.integers(
                1, self.rows("household_demographics") + 1, n),
            "c_current_addr_sk": rng.integers(
                1, self.rows("customer_address") + 1, n),
            "c_first_name": np.array(_FIRST, object)[
                rng.integers(0, len(_FIRST), n)],
            "c_last_name": np.array(_LAST, object)[
                rng.integers(0, len(_LAST), n)],
            "c_birth_year": rng.integers(1930, 1995, n),
            "c_birth_country": np.array(
                ["UNITED STATES", "CANADA", "MEXICO", "FRANCE",
                 "GERMANY"], object)[rng.integers(0, 5, n)],
            "c_email_address": np.array(
                [f"c{sk_}@example.com" for sk_ in sk], object),
            "c_salutation": np.array(
                ["Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir"], object)[
                rng.integers(0, 6, n)],
            "c_preferred_cust_flag": np.array(["N", "Y"], object)[
                rng.integers(0, 2, n)],
            "c_birth_month": rng.integers(1, 13, n),
            "c_birth_day": rng.integers(1, 29, n),
            "c_login": np.array([f"login{sk_}" for sk_ in sk], object),
            "c_first_sales_date_sk": rng.integers(
                1, self.rows("date_dim") + 1, n),
            "c_first_shipto_date_sk": rng.integers(
                1, self.rows("date_dim") + 1, n),
            "c_last_review_date_sk": rng.integers(
                1, self.rows("date_dim") + 1, n),
        }

    def _g_customer_address(self):
        n = self.rows("customer_address")
        rng = self._rng(3)
        sk = np.arange(1, n + 1)
        return {
            "ca_address_sk": sk,
            "ca_address_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "ca_city": np.array(_CITIES, object)[
                rng.integers(0, len(_CITIES), n)],
            "ca_county": np.array(
                [f"{c} County" for c in _CITIES], object)[
                rng.integers(0, len(_CITIES), n)],
            "ca_state": np.array(_STATES, object)[
                rng.integers(0, len(_STATES), n)],
            "ca_zip": np.array(
                [f"{z:05d}" for z in rng.integers(10000, 99999, n)],
                object),
            "ca_country": np.full(n, "United States", object),
            "ca_gmt_offset": rng.choice(
                np.array([-800, -700, -600, -500]), n),
            "ca_street_number": np.array(
                [str(x) for x in rng.integers(1, 1000, n)], object),
            "ca_street_name": np.array(
                [f"{c} Street" for c in
                 np.array(_CITIES)[rng.integers(0, len(_CITIES), n)]],
                object),
            "ca_street_type": np.array(
                ["Street", "Ave", "Blvd", "Way", "Ct", "Dr", "Ln"],
                object)[rng.integers(0, 7, n)],
            "ca_suite_number": np.array(
                [f"Suite {x}" for x in rng.integers(0, 100, n)], object),
            "ca_location_type": np.array(
                ["apartment", "condo", "single family"], object)[
                rng.integers(0, 3, n)],
        }

    def _g_customer_demographics(self):
        n = self.rows("customer_demographics")
        i = np.arange(n)
        return {
            "cd_demo_sk": i + 1,
            "cd_gender": np.array(["M", "F"], object)[i % 2],
            "cd_marital_status": np.array(
                ["M", "S", "D", "W", "U"], object)[(i // 2) % 5],
            "cd_education_status": np.array(
                ["Primary", "Secondary", "College", "2 yr Degree",
                 "4 yr Degree", "Advanced Degree", "Unknown"],
                object)[(i // 10) % 7],
            "cd_purchase_estimate": (i % 20) * 500 + 500,
            "cd_credit_rating": np.array(
                ["Low Risk", "Good", "High Risk", "Unknown"],
                object)[(i // 70) % 4],
            "cd_dep_count": i % 7,
            "cd_dep_employed_count": (i // 7) % 7,
            "cd_dep_college_count": (i // 49) % 7,
        }

    def _g_household_demographics(self):
        n = self.rows("household_demographics")
        i = np.arange(n)
        return {
            "hd_demo_sk": i + 1,
            "hd_income_band_sk": i % 20 + 1,
            "hd_buy_potential": np.array(
                [">10000", "5001-10000", "1001-5000", "501-1000",
                 "0-500", "Unknown"], object)[i % 6],
            "hd_dep_count": i % 10,
            "hd_vehicle_count": i % 5,
        }

    def _g_store(self):
        n = self.rows("store")
        rng = self._rng(4)
        sk = np.arange(1, n + 1)
        return {
            "s_store_sk": sk,
            "s_store_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "s_store_name": np.array(
                ["ought", "able", "pri", "ese", "anti", "cally", "ation",
                 "eing", "n st", "bar", "ought2", "able2"],
                object)[:n],
            "s_number_employees": rng.integers(200, 300, n),
            "s_city": np.array(_CITIES, object)[
                rng.integers(0, len(_CITIES), n)],
            "s_county": np.array(
                [f"{c} County" for c in _CITIES], object)[
                rng.integers(0, len(_CITIES), n)],
            "s_state": np.array(_STATES, object)[
                rng.integers(0, len(_STATES), n)],
            "s_gmt_offset": rng.choice(np.array([-600, -500]), n),
            "s_company_id": np.ones(n, dtype=np.int64),
            "s_company_name": np.array(["Unknown"] * n, object),
            "s_zip": np.array(
                [f"{z:05d}" for z in rng.integers(10000, 99999, n)],
                object),
            "s_market_id": rng.integers(1, 11, n),
            "s_street_number": np.array(
                [str(x) for x in rng.integers(1, 1000, n)], object),
            "s_street_name": np.array(
                [f"{c} Street" for c in
                 np.array(_CITIES)[rng.integers(0, len(_CITIES), n)]],
                object),
            "s_street_type": np.array(
                ["Street", "Ave", "Blvd", "Way"], object)[
                rng.integers(0, 4, n)],
            "s_suite_number": np.array(
                [f"Suite {x}" for x in rng.integers(0, 100, n)], object),
        }

    def _g_warehouse(self):
        n = self.rows("warehouse")
        rng = self._rng(5)
        sk = np.arange(1, n + 1)
        return {
            "w_warehouse_sk": sk,
            "w_warehouse_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "w_warehouse_name": np.array(
                [f"Warehouse {sk_}" for sk_ in sk], object),
            "w_warehouse_sq_ft": rng.integers(50_000, 1_000_000, n),
            "w_city": np.array(_CITIES, object)[
                rng.integers(0, len(_CITIES), n)],
            "w_state": np.array(_STATES, object)[
                rng.integers(0, len(_STATES), n)],
            "w_county": np.array(
                [f"{c} County" for c in _CITIES], object)[
                rng.integers(0, len(_CITIES), n)],
            "w_country": np.full(n, "United States", object),
        }

    def _g_promotion(self):
        n = self.rows("promotion")
        rng = self._rng(6)
        sk = np.arange(1, n + 1)
        yn = np.array(["Y", "N"], object)
        return {
            "p_promo_sk": sk,
            "p_promo_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "p_channel_dmail": yn[rng.integers(0, 2, n)],
            "p_channel_email": yn[rng.integers(0, 2, n)],
            "p_channel_tv": yn[rng.integers(0, 2, n)],
            "p_promo_name": np.array(
                [f"promo {sk_ % 50}" for sk_ in sk], object),
            "p_channel_event": yn[rng.integers(0, 2, n)],
        }

    def _sales_common(self, n, rng, n_dates):
        date_sk = rng.integers(1, n_dates + 1, n)
        item_sk = rng.integers(1, self.rows("item") + 1, n)
        qty = rng.integers(1, 100, n)
        wholesale = rng.integers(100, 10000, n)
        list_price = (wholesale * rng.integers(110, 200, n)) // 100
        sales_price = (list_price * rng.integers(30, 100, n)) // 100
        return date_sk, item_sk, qty, wholesale, list_price, sales_price

    def _g_store_sales(self):
        n = self.rows("store_sales")
        rng = self._rng(7)
        n_dates = self.rows("date_dim")
        date_sk, item_sk, qty, wholesale, lp, sp = self._sales_common(
            n, rng, n_dates)
        ext_sales = sp * qty
        ext_wholesale = wholesale * qty
        ext_list = lp * qty
        coupon = np.where(rng.integers(0, 10, n) == 0,
                          ext_sales // 10, 0)
        net_paid = ext_sales - coupon
        return {
            "ss_sold_date_sk": date_sk,
            "ss_sold_time_sk": rng.integers(
                1, self.rows("time_dim") + 1, n),
            "ss_item_sk": item_sk,
            "ss_customer_sk": rng.integers(
                1, self.rows("customer") + 1, n),
            "ss_cdemo_sk": rng.integers(
                1, self.rows("customer_demographics") + 1, n),
            "ss_hdemo_sk": rng.integers(
                1, self.rows("household_demographics") + 1, n),
            "ss_addr_sk": rng.integers(
                1, self.rows("customer_address") + 1, n),
            "ss_store_sk": rng.integers(1, self.rows("store") + 1, n),
            "ss_promo_sk": rng.integers(1, self.rows("promotion") + 1, n),
            "ss_ticket_number": np.arange(1, n + 1) // 4 + 1,
            "ss_quantity": qty,
            "ss_wholesale_cost": wholesale,
            "ss_list_price": lp,
            "ss_sales_price": sp,
            "ss_ext_discount_amt": ext_list - ext_sales,
            "ss_ext_sales_price": ext_sales,
            "ss_ext_wholesale_cost": ext_wholesale,
            "ss_ext_list_price": ext_list,
            "ss_ext_tax": (ext_sales * rng.integers(0, 9, n)) // 100,
            "ss_coupon_amt": coupon,
            "ss_net_paid": net_paid,
            "ss_net_profit": net_paid - ext_wholesale,
        }

    def _g_catalog_sales(self):
        n = self.rows("catalog_sales")
        rng = self._rng(8)
        n_dates = self.rows("date_dim")
        date_sk, item_sk, qty, wholesale, lp, sp = self._sales_common(
            n, rng, n_dates)
        ext_sales = sp * qty
        ext_list = lp * qty
        coupon = np.where(rng.integers(0, 10, n) == 0,
                          ext_sales // 10, 0)
        net_paid = ext_sales - coupon
        return {
            "cs_sold_date_sk": date_sk,
            "cs_item_sk": item_sk,
            "cs_bill_customer_sk": rng.integers(
                1, self.rows("customer") + 1, n),
            "cs_ship_customer_sk": rng.integers(
                1, self.rows("customer") + 1, n),
            "cs_bill_cdemo_sk": rng.integers(
                1, self.rows("customer_demographics") + 1, n),
            "cs_bill_hdemo_sk": rng.integers(
                1, self.rows("household_demographics") + 1, n),
            "cs_ship_date_sk": np.minimum(
                date_sk + rng.integers(1, 30, n), n_dates),
            "cs_warehouse_sk": rng.integers(
                1, self.rows("warehouse") + 1, n),
            "cs_ship_mode_sk": rng.integers(
                1, self.rows("ship_mode") + 1, n),
            "cs_call_center_sk": rng.integers(1, 7, n),
            # ~half the promo keys miss the promotion table so LEFT
            # JOIN promotion (Q72) produces real NULL p_promo_sk rows
            "cs_promo_sk": rng.integers(
                1, 2 * self.rows("promotion") + 1, n),
            "cs_order_number": np.arange(1, n + 1) // 3 + 1,
            "cs_quantity": qty,
            "cs_wholesale_cost": wholesale,
            "cs_list_price": lp,
            "cs_sales_price": sp,
            "cs_ext_discount_amt": ext_list - ext_sales,
            "cs_ext_sales_price": ext_sales,
            "cs_ext_wholesale_cost": wholesale * qty,
            "cs_ext_list_price": ext_list,
            "cs_ext_ship_cost": (ext_sales * rng.integers(2, 10, n)) // 100,
            "cs_coupon_amt": coupon,
            "cs_net_paid": net_paid,
            "cs_net_profit": net_paid - wholesale * qty,
            "cs_bill_addr_sk": rng.integers(
                1, self.rows("customer_address") + 1, n),
            "cs_ship_addr_sk": rng.integers(
                1, self.rows("customer_address") + 1, n),
            "cs_sold_time_sk": rng.integers(
                1, self.rows("time_dim") + 1, n),
            "cs_catalog_page_sk": rng.integers(
                1, self.rows("catalog_page") + 1, n),
            "cs_net_paid_inc_tax": net_paid + (ext_sales
                                               * rng.integers(0, 9, n)
                                               ) // 100,
        }

    def _g_web_sales(self):
        n = self.rows("web_sales")
        rng = self._rng(9)
        n_dates = self.rows("date_dim")
        date_sk, item_sk, qty, wholesale, lp, sp = self._sales_common(
            n, rng, n_dates)
        ext_sales = sp * qty
        ext_list = lp * qty
        return {
            "ws_sold_date_sk": date_sk,
            "ws_sold_time_sk": rng.integers(
                1, self.rows("time_dim") + 1, n),
            "ws_item_sk": item_sk,
            "ws_bill_customer_sk": rng.integers(
                1, self.rows("customer") + 1, n),
            "ws_ship_customer_sk": rng.integers(
                1, self.rows("customer") + 1, n),
            "ws_ship_hdemo_sk": rng.integers(
                1, self.rows("household_demographics") + 1, n),
            "ws_ship_addr_sk": rng.integers(
                1, self.rows("customer_address") + 1, n),
            "ws_ship_date_sk": np.minimum(
                date_sk + rng.integers(1, 30, n), n_dates),
            "ws_warehouse_sk": rng.integers(
                1, self.rows("warehouse") + 1, n),
            "ws_web_site_sk": rng.integers(
                1, self.rows("web_site") + 1, n),
            "ws_web_page_sk": rng.integers(
                1, self.rows("web_page") + 1, n),
            "ws_ship_mode_sk": rng.integers(
                1, self.rows("ship_mode") + 1, n),
            "ws_promo_sk": rng.integers(1, self.rows("promotion") + 1, n),
            "ws_order_number": np.arange(1, n + 1) // 3 + 1,
            "ws_quantity": qty,
            "ws_list_price": lp,
            "ws_sales_price": sp,
            "ws_ext_discount_amt": ext_list - ext_sales,
            "ws_ext_sales_price": ext_sales,
            "ws_ext_wholesale_cost": wholesale * qty,
            "ws_ext_ship_cost": (ext_sales * rng.integers(2, 10, n)) // 100,
            "ws_net_paid": ext_sales,
            "ws_net_profit": ext_sales - wholesale * qty,
            "ws_bill_addr_sk": rng.integers(
                1, self.rows("customer_address") + 1, n),
            "ws_wholesale_cost": wholesale,
            "ws_ext_list_price": ext_list,
        }

    def _g_store_returns(self):
        """Samples real store_sales rows so the
        (sr_customer_sk, sr_item_sk, sr_ticket_number) triple joins back
        to its sale (Q25/Q29 shapes need matching return lines)."""
        n = self.rows("store_returns")
        rng = self._rng(10)
        ss = self._sales("store_sales")
        idx = rng.integers(0, len(ss["ss_ticket_number"]), n)
        return {
            "sr_returned_date_sk": np.minimum(
                ss["ss_sold_date_sk"][idx] + rng.integers(1, 60, n),
                self.rows("date_dim")),
            "sr_item_sk": ss["ss_item_sk"][idx],
            "sr_customer_sk": ss["ss_customer_sk"][idx],
            "sr_ticket_number": ss["ss_ticket_number"][idx],
            "sr_store_sk": rng.integers(1, self.rows("store") + 1, n),
            "sr_cdemo_sk": rng.integers(
                1, self.rows("customer_demographics") + 1, n),
            "sr_reason_sk": rng.integers(
                1, self.rows("reason") + 1, n),
            "sr_return_quantity": np.minimum(
                rng.integers(1, 20, n), ss["ss_quantity"][idx]),
            "sr_return_amt": rng.integers(100, 50000, n),
            "sr_net_loss": rng.integers(50, 20000, n),
        }

    def _g_catalog_returns(self):
        """Returns sample real catalog_sales rows so the
        (cr_order_number, cr_item_sk) pairs join back (reference dsdgen
        emits returns for a fraction of sales lines)."""
        n = self.rows("catalog_returns")
        rng = self._rng(12)
        cs = self._sales("catalog_sales")
        idx = rng.integers(0, len(cs["cs_order_number"]), n)
        qty = np.minimum(rng.integers(1, 20, n), cs["cs_quantity"][idx])
        amt = cs["cs_sales_price"][idx] * qty
        return {
            "cr_returned_date_sk": np.minimum(
                cs["cs_ship_date_sk"][idx] + rng.integers(1, 60, n),
                self.rows("date_dim")),
            "cr_item_sk": cs["cs_item_sk"][idx],
            "cr_order_number": cs["cs_order_number"][idx],
            "cr_returning_customer_sk": cs["cs_bill_customer_sk"][idx],
            "cr_call_center_sk": rng.integers(
                1, self.rows("call_center") + 1, n),
            "cr_return_quantity": qty,
            "cr_return_amount": amt,
            "cr_refunded_cash": (amt * rng.integers(50, 100, n)) // 100,
            "cr_net_loss": rng.integers(50, 20000, n),
            "cr_returning_addr_sk": rng.integers(
                1, self.rows("customer_address") + 1, n),
            "cr_reversed_charge": (amt * rng.integers(0, 40, n)) // 100,
            "cr_catalog_page_sk": rng.integers(
                1, self.rows("catalog_page") + 1, n),
            "cr_return_amt_inc_tax": amt + (amt * rng.integers(0, 9, n)
                                            ) // 100,
            "cr_store_credit": (amt * rng.integers(0, 30, n)) // 100,
        }

    def _g_web_returns(self):
        n = self.rows("web_returns")
        rng = self._rng(13)
        ws = self._sales("web_sales")
        idx = rng.integers(0, len(ws["ws_order_number"]), n)
        qty = np.minimum(rng.integers(1, 20, n), ws["ws_quantity"][idx])
        amt = ws["ws_sales_price"][idx] * qty
        return {
            "wr_returned_date_sk": np.minimum(
                ws["ws_ship_date_sk"][idx] + rng.integers(1, 60, n),
                self.rows("date_dim")),
            "wr_item_sk": ws["ws_item_sk"][idx],
            "wr_order_number": ws["ws_order_number"][idx],
            "wr_returning_customer_sk": ws["ws_bill_customer_sk"][idx],
            "wr_return_quantity": qty,
            "wr_return_amt": amt,
            "wr_refunded_cash": (amt * rng.integers(50, 100, n)) // 100,
            "wr_net_loss": rng.integers(50, 20000, n),
            "wr_refunded_cdemo_sk": rng.integers(
                1, self.rows("customer_demographics") + 1, n),
            "wr_returning_addr_sk": rng.integers(
                1, self.rows("customer_address") + 1, n),
            "wr_returning_cdemo_sk": rng.integers(
                1, self.rows("customer_demographics") + 1, n),
            "wr_refunded_addr_sk": rng.integers(
                1, self.rows("customer_address") + 1, n),
            "wr_reason_sk": rng.integers(1, self.rows("reason") + 1, n),
            "wr_web_page_sk": rng.integers(
                1, self.rows("web_page") + 1, n),
            "wr_fee": rng.integers(50, 10000, n),
        }

    def _g_web_site(self):
        n = self.rows("web_site")
        sk = np.arange(1, n + 1)
        names = ["pri", "able", "ought", "ese", "anti", "cally"]
        return {
            "web_site_sk": sk,
            "web_site_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "web_name": np.array(
                [f"site_{sk_ % 8}" for sk_ in sk], object),
            "web_company_name": np.array(names, object)[sk % len(names)],
        }

    def _g_web_page(self):
        n = self.rows("web_page")
        rng = self._rng(14)
        sk = np.arange(1, n + 1)
        return {
            "wp_web_page_sk": sk,
            "wp_web_page_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "wp_char_count": rng.integers(100, 8000, n),
        }

    def _g_time_dim(self):
        n = self.rows("time_dim")
        sk = np.arange(1, n + 1)
        sec = np.arange(n)
        hour = sec // 3600
        meal = np.full(n, "", object)
        meal[(hour >= 6) & (hour < 9)] = "breakfast"
        meal[(hour >= 11) & (hour < 14)] = "lunch"
        meal[(hour >= 17) & (hour < 20)] = "dinner"
        return {
            "t_time_sk": sk,
            "t_time_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "t_time": sec, "t_hour": hour,
            "t_minute": (sec // 60) % 60, "t_second": sec % 60,
            "t_meal_time": meal,
        }

    def _g_reason(self):
        n = self.rows("reason")
        sk = np.arange(1, n + 1)
        descs = ["Package was damaged", "Stopped working",
                 "Did not get it on time", "Not the product that "
                 "was ordred", "Parts missing", "Does not work with "
                 "a product that I have", "Gift exchange",
                 "Did not like the color", "Did not like the model",
                 "Did not fit", "Wrong size", "Lost my job",
                 "Found a better price in a store",
                 "Found a better extension in a store",
                 "No service location in my area",
                 "Duplicate purchase", "Its is a boring color",
                 "Reason 18", "Reason 19", "unknown"]
        return {
            "r_reason_sk": sk,
            "r_reason_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "r_reason_desc": np.array(descs, object)[
                (sk - 1) % len(descs)],
        }

    def _g_income_band(self):
        n = self.rows("income_band")
        sk = np.arange(1, n + 1)
        return {
            "ib_income_band_sk": sk,
            "ib_lower_bound": (sk - 1) * 10_000,
            "ib_upper_bound": sk * 10_000,
        }

    def _g_call_center(self):
        n = self.rows("call_center")
        sk = np.arange(1, n + 1)
        names = ["NY Metro", "Mid Atlantic", "Hawaii/Alaska",
                 "North Midwest", "California", "Pacific Northwest"]
        classes = ["large", "medium", "small"]
        return {
            "cc_call_center_sk": sk,
            "cc_call_center_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "cc_name": np.array(names, object)[(sk - 1) % len(names)],
            "cc_class": np.array(classes, object)[
                (sk - 1) % len(classes)],
            "cc_employees": sk * 1000 % 7 * 100 + 100,
            "cc_manager": np.array(_FIRST, object)[(sk - 1) % len(_FIRST)],
            "cc_county": np.array(_CITIES, object)[(sk - 1) % len(_CITIES)],
        }

    def _g_catalog_page(self):
        n = self.rows("catalog_page")
        sk = np.arange(1, n + 1)
        depts = ["DEPARTMENT"]
        types = ["bi-annual", "quarterly", "monthly"]
        return {
            "cp_catalog_page_sk": sk,
            "cp_catalog_page_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "cp_department": np.array(depts, object)[np.zeros(n, int)],
            "cp_catalog_number": (sk - 1) // 108 + 1,
            "cp_catalog_page_number": (sk - 1) % 108 + 1,
            "cp_type": np.array(types, object)[(sk - 1) % len(types)],
        }

    def _g_ship_mode(self):
        n = self.rows("ship_mode")
        sk = np.arange(1, n + 1)
        types = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"]
        carriers = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL",
                    "TBS", "ZHOU", "LATVIAN", "DIAMOND", "ORIENTAL",
                    "BARIAN", "BOXBUNDLES", "ALLIANCE", "GREAT EASTERN",
                    "HARMSTORF", "PRIVATECARRIER", "GERMA", "MSC",
                    "RUPEKSA", "GUARANTEED"]
        return {
            "sm_ship_mode_sk": sk,
            "sm_ship_mode_id": np.array(
                [f"AAAAAAAA{sk_:08d}" for sk_ in sk], object),
            "sm_type": np.array(types, object)[(sk - 1) % len(types)],
            "sm_carrier": np.array(carriers, object)[
                (sk - 1) % len(carriers)],
            "sm_code": np.array(["AIR", "SURFACE", "SEA"], object)[
                (sk - 1) % 3],
        }

    def _g_inventory(self):
        n = self.rows("inventory")
        rng = self._rng(11)
        return {
            "inv_date_sk": rng.integers(1, self.rows("date_dim") + 1, n),
            "inv_item_sk": rng.integers(1, self.rows("item") + 1, n),
            "inv_warehouse_sk": rng.integers(
                1, self.rows("warehouse") + 1, n),
            "inv_quantity_on_hand": rng.integers(0, 1000, n),
        }


class TpcdsConnector(Connector):
    """Catalog `tpcds`; tiny scale = 0.001 (~3k store_sales rows)."""

    name = "tpcds"

    def __init__(self, scale: float = 0.001, seed: int = 20030527):
        self.scale = scale
        self.gen = TpcdsGenerator(scale, seed,
                                  sales_provider=self._sales_arrays)
        self._tables: dict[str, Table] = {}

    def _sales_arrays(self, name: str) -> dict[str, np.ndarray]:
        """Numeric sales arrays for the returns generators, served from
        the Table cache so the big sales tables are resident once (the
        returns samplers only touch numeric columns, which Tables store
        unchanged)."""
        t = self.table(name)
        return {c: np.asarray(col.data) for c, col in t.columns.items()}

    def table_names(self) -> list[str]:
        return list(SCHEMAS)

    def table_schema(self, name: str):
        return SCHEMAS[name]

    def table(self, name: str) -> Table:
        if name not in self._tables:
            self._tables[name] = Table.from_numpy(
                SCHEMAS[name], self.gen.generate(name))
        return self._tables[name]

    def row_count_estimate(self, name: str) -> int:
        return self.gen.rows(name)

    def unique_keys(self, name: str) -> list[tuple[str, ...]]:
        return list(_UNIQUE.get(name, []))

    # TPC-DS surrogate keys are named for the dimension they reference;
    # the ndv of an FK column is (at most) that dimension's row count —
    # the analog of the reference tpcds connector's shipped column
    # statistics (plugin/trino-tpcds TpcdsMetadata statistics). Longest
    # suffix wins (cs_bill_cdemo_sk -> customer_demographics before
    # _demo_sk could mis-route).
    _SK_SUFFIX = (
        ("_call_center_sk", "call_center"),
        ("_catalog_page_sk", "catalog_page"),
        ("_web_page_sk", "web_page"),
        ("_web_site_sk", "web_site"),
        ("_ship_mode_sk", "ship_mode"),
        ("_income_band_sk", "income_band"),
        ("_warehouse_sk", "warehouse"),
        ("_customer_sk", "customer"),
        ("_cdemo_sk", "customer_demographics"),
        ("_hdemo_sk", "household_demographics"),
        ("_demo_sk", "customer_demographics"),
        ("_addr_sk", "customer_address"),
        ("_date_sk", "date_dim"),
        ("_time_sk", "time_dim"),
        ("_item_sk", "item"),
        ("_store_sk", "store"),
        ("_promo_sk", "promotion"),
        ("_reason_sk", "reason"),
    )

    def ndv_estimates(self, name: str) -> dict[str, int]:
        rows = self.gen.rows(name)
        out: dict[str, int] = {}
        for col in self.table_schema(name):
            for suffix, ref in self._SK_SUFFIX:
                if col.endswith(suffix):
                    out[col] = min(self.gen.rows(ref), rows)
                    break
        return out

    def stats(self, name: str) -> TableStats:
        return TableStats(row_count=self.gen.rows(name))
