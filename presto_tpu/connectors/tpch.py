"""TPC-H synthetic data connector.

Analog of the reference's plugin/trino-tpch (TpchConnectorFactory,
TpchMetadata, TpchSplitManager.java:32). Vectorised NumPy generation with
spec-shaped distributions (dates, discounts, priorities, FK structure,
the partsupp supplier formula) so query selectivities are realistic. The
generator is deterministic per (scale, seed), and the same arrays feed both
the device tables and the sqlite oracle used in tests — so correctness
checks do not depend on matching official dbgen byte-for-byte.

Decimal columns are generated as scaled int64 (cents etc.) per
presto_tpu.types.DecimalType.
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import EncodedStrings, Table
from presto_tpu.connectors.base import Connector, TableStats

# --- spec constants ---------------------------------------------------------

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]

TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]

COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush "
    "brown burlywood burnished chartreuse chiffon chocolate coral cornflower "
    "cornsilk cream cyan dark deep dim dodger drab firebrick floral forest "
    "frosted gainsboro ghost goldenrod green grey honeydew hot indian ivory "
    "khaki lace lavender lawn lemon light lime linen magenta maroon medium "
    "metallic midnight mint misty moccasin navajo navy olive orange orchid "
    "pale papaya peach peru pink plum powder puff purple red rose rosy royal "
    "saddle salmon sandy seashell sienna sky slate smoke snow spring steel "
    "tan thistle tomato turquoise violet wheat white yellow"
).split()

COMMENT_WORDS = (
    "carefully quickly furiously slyly blithely final pending express bold "
    "regular ironic even special unusual silent deposits requests accounts "
    "packages instructions theodolites foxes pinto beans dependencies ideas "
    "platelets realms sleep haggle nag wake cajole boost detect integrate "
    "Customer Complaints above according across against along"
).split()

# date epochs (days since 1970-01-01)
_D = lambda s: (np.datetime64(s) - np.datetime64("1970-01-01")).astype(int)
STARTDATE = int(_D("1992-01-01"))
ENDDATE = int(_D("1998-08-02"))
CURRENTDATE = int(_D("1995-06-17"))

DEC2 = T.DecimalType(12, 2)

SCHEMAS: dict[str, dict[str, T.DataType]] = {
    "region": {
        "r_regionkey": T.BIGINT, "r_name": T.VARCHAR, "r_comment": T.VARCHAR,
    },
    "nation": {
        "n_nationkey": T.BIGINT, "n_name": T.VARCHAR,
        "n_regionkey": T.BIGINT, "n_comment": T.VARCHAR,
    },
    "supplier": {
        "s_suppkey": T.BIGINT, "s_name": T.VARCHAR, "s_address": T.VARCHAR,
        "s_nationkey": T.BIGINT, "s_phone": T.VARCHAR,
        "s_acctbal": DEC2, "s_comment": T.VARCHAR,
    },
    "part": {
        "p_partkey": T.BIGINT, "p_name": T.VARCHAR, "p_mfgr": T.VARCHAR,
        "p_brand": T.VARCHAR, "p_type": T.VARCHAR, "p_size": T.BIGINT,
        "p_container": T.VARCHAR, "p_retailprice": DEC2,
        "p_comment": T.VARCHAR,
    },
    "partsupp": {
        "ps_partkey": T.BIGINT, "ps_suppkey": T.BIGINT,
        "ps_availqty": T.BIGINT, "ps_supplycost": DEC2,
        "ps_comment": T.VARCHAR,
    },
    "customer": {
        "c_custkey": T.BIGINT, "c_name": T.VARCHAR, "c_address": T.VARCHAR,
        "c_nationkey": T.BIGINT, "c_phone": T.VARCHAR, "c_acctbal": DEC2,
        "c_mktsegment": T.VARCHAR, "c_comment": T.VARCHAR,
    },
    "orders": {
        "o_orderkey": T.BIGINT, "o_custkey": T.BIGINT,
        "o_orderstatus": T.VARCHAR, "o_totalprice": DEC2,
        "o_orderdate": T.DATE, "o_orderpriority": T.VARCHAR,
        "o_clerk": T.VARCHAR, "o_shippriority": T.BIGINT,
        "o_comment": T.VARCHAR,
    },
    "lineitem": {
        "l_orderkey": T.BIGINT, "l_partkey": T.BIGINT, "l_suppkey": T.BIGINT,
        "l_linenumber": T.BIGINT, "l_quantity": DEC2,
        "l_extendedprice": DEC2, "l_discount": DEC2, "l_tax": DEC2,
        "l_returnflag": T.VARCHAR, "l_linestatus": T.VARCHAR,
        "l_shipdate": T.DATE, "l_commitdate": T.DATE,
        "l_receiptdate": T.DATE, "l_shipinstruct": T.VARCHAR,
        "l_shipmode": T.VARCHAR, "l_comment": T.VARCHAR,
    },
}


def _pick(vocab, idx: np.ndarray) -> EncodedStrings:
    """Select from a small vocabulary, emitting codes into the sorted
    vocabulary directly (no per-row object strings)."""
    sorted_dict, inv = np.unique(
        np.array(vocab, dtype="U64"), return_inverse=True)
    return EncodedStrings(inv.astype(np.int32)[idx],
                          sorted_dict.astype(object))


_COMMENT_COMBOS: tuple | None = None


def _comments(rng: np.random.Generator, n: int) -> EncodedStrings:
    """Short pseudo-comments from a bounded vocabulary (so the string
    dictionary stays small at scale). Patterns like '%special%requests%'
    (Q13) and '%Customer%Complaints%' (Q16) occur with realistic rarity.
    All |words|^3 combos form one shared sorted dictionary; rows carry
    codes only, so generation is O(n) integer work."""
    global _COMMENT_COMBOS
    w = np.array(COMMENT_WORDS, dtype=object)
    k = len(w)
    if _COMMENT_COMBOS is None:
        c0 = np.repeat(w, k * k)
        c1 = np.tile(np.repeat(w, k), k)
        c2 = np.tile(w, k * k)
        combos = c0 + " " + c1 + " " + c2
        sorted_dict, inv = np.unique(combos.astype("U"),
                                     return_inverse=True)
        _COMMENT_COMBOS = (sorted_dict.astype(object),
                           inv.astype(np.int32))
    sorted_dict, inv = _COMMENT_COMBOS
    i = rng.integers(0, k, size=(n, 3))
    flat = (i[:, 0] * k + i[:, 1]) * k + i[:, 2]
    codes = inv[flat]
    if n < (1 << 17):
        # small tables: compact to the realized values so host-side
        # dictionary scans (LIKE, unions) don't pay for the full vocab
        used, remap = np.unique(codes, return_inverse=True)
        return EncodedStrings(remap.astype(np.int32), sorted_dict[used])
    return EncodedStrings(codes, sorted_dict)


def _phone(nationkey: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    cc = (nationkey + 10).astype(np.int64)
    a = rng.integers(100, 1000, len(nationkey))
    b = rng.integers(100, 1000, len(nationkey))
    c = rng.integers(1000, 10000, len(nationkey))
    dash = np.full(len(cc), "-", dtype="U1")
    out = np.char.add(np.char.zfill(cc.astype("U2"), 2), dash)
    out = np.char.add(np.char.add(out, a.astype("U3")), dash)
    out = np.char.add(np.char.add(out, b.astype("U3")), dash)
    out = np.char.add(out, c.astype("U4"))
    return out.astype(object)


def _keyed_names(prefix: str, keys: np.ndarray) -> "EncodedStrings":
    """Vectorized '<prefix>#000000001'-style names. Zero-padded per-key
    names ascend with the key, so the identity mapping over the
    already-sorted dictionary avoids a unique/argsort pass."""
    names = np.char.add(f"{prefix}#",
                        np.char.zfill(keys.astype("U9"), 9))
    return EncodedStrings(np.arange(len(keys), dtype=np.int32),
                          names.astype(object))


def _retailprice(partkey: np.ndarray) -> np.ndarray:
    """Scaled-by-100 retail price, spec 4.2.3 formula (exact, in cents)."""
    pk = partkey.astype(np.int64)
    return 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)


def _ps_suppkey(partkey: np.ndarray, i: np.ndarray, s: int) -> np.ndarray:
    """The spec's partsupp supplier formula; also used for l_suppkey so the
    lineitem -> partsupp join (Q9) has matches."""
    pk = partkey.astype(np.int64)
    return (pk + i * (s // 4 + (pk - 1) // s)) % s + 1


class TpchGenerator:
    """``zipf`` (exponent s, None = spec-uniform) skews the FK draws
    that drive join distribution — lineitem's part keys (and through
    the spec's supplier formula, its supplier keys) and orders'
    customer keys follow a bounded Zipf(s) over the key space — so
    skew-aware join benchmarks (bench.py PRESTO_TPU_BENCH_SKEW) and
    the hybrid-distribution oracle tests exercise heavy hitters on
    real TPC-H shapes. Primary keys, payload columns and row counts
    stay exactly the uniform generator's."""

    def __init__(self, scale: float, seed: int = 19920101,
                 zipf: float | None = None):
        self.scale = scale
        self.seed = seed
        self.zipf = zipf
        self.n_supplier = max(int(10_000 * scale), 40)
        self.n_part = max(int(200_000 * scale), 200)
        self.n_customer = max(int(150_000 * scale), 150)
        self.n_orders = self.n_customer * 10

    def _rng(self, salt: int) -> np.random.Generator:
        return np.random.default_rng([self.seed, salt])

    def _fk(self, rng: np.random.Generator, n_keys: int,
            size: int) -> np.ndarray:
        """FK column over 1..n_keys: uniform, or bounded Zipf(s) via
        inverse-CDF when skewed. Ranks scatter over the key space with
        a fixed odd multiplier so heavy hitters are not the
        consecutive low ids (which dense-key direct tables would
        otherwise make artificially cheap)."""
        if not self.zipf:
            return rng.integers(1, n_keys + 1, size).astype(np.int64)
        w = 1.0 / np.power(
            np.arange(1, n_keys + 1, dtype=np.float64), self.zipf)
        cdf = np.cumsum(w)
        cdf /= cdf[-1]
        ranks = np.searchsorted(cdf, rng.random(size), side="left")
        return (ranks.astype(np.int64) * 2654435761 % n_keys) + 1

    def region(self):
        return {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": np.array(REGIONS, dtype=object),
            "r_comment": _comments(self._rng(1), 5),
        }

    def nation(self):
        return {
            "n_nationkey": np.arange(25, dtype=np.int64),
            "n_name": np.array([n for n, _ in NATIONS], dtype=object),
            "n_regionkey": np.array([r for _, r in NATIONS], dtype=np.int64),
            "n_comment": _comments(self._rng(2), 25),
        }

    def supplier(self):
        rng = self._rng(3)
        n = self.n_supplier
        keys = np.arange(1, n + 1, dtype=np.int64)
        nationkey = rng.integers(0, 25, n).astype(np.int64)
        return {
            "s_suppkey": keys,
            "s_name": _keyed_names("Supplier", keys),
            "s_address": _comments(rng, n),
            "s_nationkey": nationkey,
            "s_phone": _phone(nationkey, rng),
            "s_acctbal": rng.integers(-99999, 1_000_000, n).astype(np.int64),
            "s_comment": _comments(rng, n),
        }

    def part(self):
        rng = self._rng(4)
        n = self.n_part
        keys = np.arange(1, n + 1, dtype=np.int64)
        colors = np.array(COLORS, dtype=object)
        name_idx = rng.integers(0, len(colors), size=(n, 5))
        names = colors[name_idx[:, 0]]
        for j in range(1, 5):
            names = names + " " + colors[name_idx[:, j]]
        mfgr = rng.integers(1, 6, n)
        brand = mfgr * 10 + rng.integers(1, 6, n)
        type_vocab = [f"{a} {b} {c}" for a in TYPE_S1 for b in TYPE_S2
                      for c in TYPE_S3]
        t1 = rng.integers(0, len(TYPE_S1), n)
        t2 = rng.integers(0, len(TYPE_S2), n)
        t3 = rng.integers(0, len(TYPE_S3), n)
        types_arr = _pick(
            type_vocab,
            (t1 * len(TYPE_S2) + t2) * len(TYPE_S3) + t3)
        cont_vocab = [f"{a} {b}" for a in CONTAINER_S1
                      for b in CONTAINER_S2]
        c1 = rng.integers(0, len(CONTAINER_S1), n)
        c2 = rng.integers(0, len(CONTAINER_S2), n)
        containers = _pick(cont_vocab, c1 * len(CONTAINER_S2) + c2)
        return {
            "p_partkey": keys,
            "p_name": names,
            "p_mfgr": _pick([f"Manufacturer#{m}" for m in range(1, 6)],
                            mfgr - 1),
            "p_brand": _pick(
                [f"Brand#{m}{s}" for m in range(1, 6)
                 for s in range(1, 6)],
                (mfgr - 1) * 5 + (brand - mfgr * 10 - 1)),
            "p_type": types_arr,
            "p_size": rng.integers(1, 51, n).astype(np.int64),
            "p_container": containers,
            "p_retailprice": _retailprice(keys),
            "p_comment": _comments(rng, n),
        }

    def partsupp(self):
        rng = self._rng(5)
        pk = np.repeat(np.arange(1, self.n_part + 1, dtype=np.int64), 4)
        i = np.tile(np.arange(4, dtype=np.int64), self.n_part)
        return {
            "ps_partkey": pk,
            "ps_suppkey": _ps_suppkey(pk, i, self.n_supplier),
            "ps_availqty": rng.integers(1, 10000, len(pk)).astype(np.int64),
            "ps_supplycost": rng.integers(100, 100001, len(pk)).astype(np.int64),
            "ps_comment": _comments(rng, len(pk)),
        }

    def customer(self):
        rng = self._rng(6)
        n = self.n_customer
        keys = np.arange(1, n + 1, dtype=np.int64)
        nationkey = rng.integers(0, 25, n).astype(np.int64)
        seg = rng.integers(0, len(SEGMENTS), n)
        return {
            "c_custkey": keys,
            "c_name": _keyed_names("Customer", keys),
            "c_address": _comments(rng, n),
            "c_nationkey": nationkey,
            "c_phone": _phone(nationkey, rng),
            "c_acctbal": rng.integers(-99999, 1_000_000, n).astype(np.int64),
            "c_mktsegment": _pick(SEGMENTS, seg),
            "c_comment": _comments(rng, n),
        }

    def _order_line_counts(self):
        rng = self._rng(7)
        return rng.integers(1, 8, self.n_orders)

    def orders_and_lineitem(self):
        rng = self._rng(8)
        n = self.n_orders
        okeys = np.arange(1, n + 1, dtype=np.int64)
        # custkey: uniform (or Zipf-skewed) over customers, excluding
        # multiples of 3 (spec 4.2.3)
        ck = self._fk(rng, self.n_customer, n)
        bump = ck % 3 == 0
        ck = np.where(bump, np.maximum((ck + 1) % (self.n_customer + 1), 1), ck)
        ck = np.where(ck % 3 == 0, np.maximum(ck - 2, 1), ck)
        odate = rng.integers(STARTDATE, ENDDATE - 151 + 1, n).astype(np.int32)

        counts = self._order_line_counts()
        total_lines = int(counts.sum())
        l_orderkey = np.repeat(okeys, counts)
        l_odate = np.repeat(odate, counts)
        # line number within its order, vectorized: global position minus
        # the order's start offset
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        ln = (np.arange(total_lines, dtype=np.int64)
              - np.repeat(starts, counts) + 1)

        lrng = self._rng(9)
        lpk = self._fk(lrng, self.n_part, total_lines)
        lsk = _ps_suppkey(
            lpk, lrng.integers(0, 4, total_lines), self.n_supplier)
        qty = lrng.integers(1, 51, total_lines).astype(np.int64)
        eprice = qty * _retailprice(lpk)  # qty * price(cents) -> cents
        disc = lrng.integers(0, 11, total_lines).astype(np.int64)  # 0.00-0.10
        tax = lrng.integers(0, 9, total_lines).astype(np.int64)  # 0.00-0.08
        sdate = (l_odate + lrng.integers(1, 122, total_lines)).astype(np.int32)
        cdate = (l_odate + lrng.integers(30, 91, total_lines)).astype(np.int32)
        rdate = (sdate + lrng.integers(1, 31, total_lines)).astype(np.int32)
        returned = rdate <= CURRENTDATE
        # dictionaries sorted: ["A","N","R"], ["F","O"]
        rflag = EncodedStrings(
            np.where(returned,
                     np.where(lrng.random(total_lines) < 0.5, 2, 0),
                     1).astype(np.int32),
            np.array(["A", "N", "R"], object))
        open_line = sdate > CURRENTDATE
        lstatus = EncodedStrings(open_line.astype(np.int32),
                                 np.array(["F", "O"], object))

        lineitem = {
            "l_orderkey": l_orderkey,
            "l_partkey": lpk,
            "l_suppkey": lsk,
            "l_linenumber": ln,
            "l_quantity": qty * 100,  # decimal(12,2) scaled
            "l_extendedprice": eprice,
            "l_discount": disc,
            "l_tax": tax,
            "l_returnflag": rflag,
            "l_linestatus": lstatus,
            "l_shipdate": sdate,
            "l_commitdate": cdate,
            "l_receiptdate": rdate,
            "l_shipinstruct": _pick(
                INSTRUCTIONS,
                lrng.integers(0, len(INSTRUCTIONS), total_lines)),
            "l_shipmode": _pick(
                SHIPMODES, lrng.integers(0, len(SHIPMODES), total_lines)),
            "l_comment": _comments(lrng, total_lines),
        }

        # o_totalprice = sum(extendedprice * (1+tax) * (1-discount)), rounded
        # to cents; o_orderstatus from line statuses.
        line_total = np.round(
            eprice * (100 + tax) * (100 - disc) / 10000.0).astype(np.int64)
        totalprice = np.zeros(n, dtype=np.int64)
        np.add.at(totalprice, l_orderkey - 1, line_total)
        n_open = np.zeros(n, dtype=np.int64)
        np.add.at(n_open, l_orderkey - 1, open_line.astype(np.int64))
        # dictionary sorted: ["F","O","P"]
        status = EncodedStrings(
            np.where(n_open == counts, 1,
                     np.where(n_open == 0, 0, 2)).astype(np.int32),
            np.array(["F", "O", "P"], object))

        orders = {
            "o_orderkey": okeys,
            "o_custkey": ck,
            "o_orderstatus": status,
            "o_totalprice": totalprice,
            "o_orderdate": odate,
            "o_orderpriority": _pick(
                PRIORITIES, rng.integers(0, len(PRIORITIES), n)),
            # zero-padded clerk names sort numerically, so the distinct
            # clerk list is already the sorted dictionary
            "o_clerk": EncodedStrings(
                rng.integers(
                    0, max(int(1000 * self.scale), 10), n
                ).astype(np.int32),
                np.array([f"Clerk#{c:09d}" for c in
                          range(1, max(int(1000 * self.scale), 10) + 1)],
                         object)),
            "o_shippriority": np.zeros(n, dtype=np.int64),
            "o_comment": _comments(rng, n),
        }
        return orders, lineitem


class TpchConnector(Connector):
    """Catalog `tpch` with one schema per scale factor (tiny = 0.01).
    ``skew`` = None (spec-uniform) or "zipf:<s>" / float s — Zipf-skew
    the FK columns (see TpchGenerator)."""

    name = "tpch"

    def __init__(self, scale: float = 0.01, seed: int = 19920101,
                 skew: str | float | None = None):
        self.scale = scale
        zipf = None
        if isinstance(skew, str) and skew:
            kind, _, arg = skew.partition(":")
            if kind.strip().lower() != "zipf":
                raise ValueError(f"unknown skew mode: {skew!r}")
            zipf = float(arg or 1.0)
        elif skew:
            zipf = float(skew)
        self.gen = TpchGenerator(scale, seed, zipf=zipf)
        self._cache: dict[str, dict[str, np.ndarray]] = {}
        self._tables: dict[str, Table] = {}

    def table_names(self) -> list[str]:
        return list(SCHEMAS.keys())

    def table_schema(self, name: str):
        return SCHEMAS[name]

    def table_version(self, name: str) -> int | None:
        # generated data is immutable for the connector's lifetime:
        # one constant version makes every tpch scan result-cacheable
        return 0

    def _raw(self, name: str) -> dict[str, np.ndarray]:
        if name not in self._cache:
            loaded = self._disk_load(name)
            if loaded is not None:
                self._cache[name] = loaded
            elif name in ("orders", "lineitem"):
                orders, lineitem = self.gen.orders_and_lineitem()
                self._cache["orders"] = orders
                self._cache["lineitem"] = lineitem
                self._disk_store("orders", orders)
                self._disk_store("lineitem", lineitem)
            else:
                self._cache[name] = getattr(self.gen, name)()
                self._disk_store(name, self._cache[name])
        return self._cache[name]

    # Optional on-disk table cache (PRESTO_TPU_TPCH_CACHE=<dir>):
    # regenerating SF10+ per bench process would eat the bench budget.
    # One DIRECTORY per table with one raw .npy per column, loaded with
    # mmap so "load" is instant and pages stream from disk during the
    # device transfer (EncodedStrings split into codes + pickled dict).
    def _disk_path(self, name: str):
        import os
        d = os.environ.get("PRESTO_TPU_TPCH_CACHE")
        if not d:
            return None
        tag = (f"_zipf{self.gen.zipf:g}" if self.gen.zipf else "")
        return os.path.join(
            d, f"tpch_sf{self.scale:g}_s{self.gen.seed}{tag}_{name}")

    def _disk_load(self, name: str):
        import os
        path = self._disk_path(name)
        if path is None or not os.path.exists(
                os.path.join(path, "_complete")):
            return None
        out: dict[str, np.ndarray] = {}
        for col in SCHEMAS[name]:
            codes = os.path.join(path, f"{col}.codes.npy")
            # plain load, NOT mmap: the engine's device-pin cache keys
            # on array identity, and np.asarray over a memmap makes a
            # fresh view object per access (cache miss -> re-transfer)
            if os.path.exists(codes):
                out[col] = EncodedStrings(
                    np.load(codes),
                    np.load(os.path.join(path, f"{col}.dict.npy"),
                            allow_pickle=True))
            else:
                # allow_pickle: raw object string columns (phones,
                # part names) pickle through np.save
                out[col] = np.load(os.path.join(path, f"{col}.npy"),
                                   allow_pickle=True)
        return out

    def _disk_store(self, name: str, raw: dict) -> None:
        import os
        import tempfile
        path = self._disk_path(name)
        if path is None or os.path.exists(
                os.path.join(path, "_complete")):
            return
        parent = os.path.dirname(path) or "."
        os.makedirs(parent, exist_ok=True)
        tmp = tempfile.mkdtemp(dir=parent)
        try:
            for col, a in raw.items():
                if isinstance(a, EncodedStrings):
                    np.save(os.path.join(tmp, f"{col}.codes.npy"),
                            a.codes)
                    np.save(os.path.join(tmp, f"{col}.dict.npy"),
                            a.dictionary, allow_pickle=True)
                else:
                    np.save(os.path.join(tmp, f"{col}.npy"), a)
            open(os.path.join(tmp, "_complete"), "w").close()
            try:
                os.replace(tmp, path)  # atomic vs concurrent processes
            except OSError:
                # a partial dir from a crashed run blocks the rename
                import shutil
                if not os.path.exists(os.path.join(path, "_complete")):
                    shutil.rmtree(path, ignore_errors=True)
                    os.replace(tmp, path)
                else:
                    shutil.rmtree(tmp, ignore_errors=True)
        except BaseException:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def table(self, name: str) -> Table:
        if name not in self._tables:
            self._tables[name] = Table.from_numpy(SCHEMAS[name], self._raw(name))
        return self._tables[name]

    _BASE_ROWS = {
        "region": 5, "nation": 25, "supplier": 10_000, "part": 200_000,
        "partsupp": 800_000, "customer": 150_000, "orders": 1_500_000,
        "lineitem": 6_000_000,
    }
    _UNIQUE_KEYS = {
        "region": [("r_regionkey",)],
        "nation": [("n_nationkey",)],
        "supplier": [("s_suppkey",)],
        "part": [("p_partkey",)],
        "partsupp": [("ps_partkey", "ps_suppkey")],
        "customer": [("c_custkey",)],
        "orders": [("o_orderkey",)],
        "lineitem": [("l_orderkey", "l_linenumber")],
    }

    def row_count_estimate(self, name: str) -> int:
        base = self._BASE_ROWS[name]
        if name in ("region", "nation"):
            return base
        return max(1, int(base * self.scale))

    def unique_keys(self, name: str) -> list[tuple[str, ...]]:
        return list(self._UNIQUE_KEYS.get(name, []))

    # orders and lineitem bucket by orderkey, exactly the reference's
    # tpch partitioning (plugin/trino-tpch TpchNodePartitioningProvider
    # + TpchBucketFunction): the orderkey join/group never reshuffles
    _PARTITIONING = {"orders": ("o_orderkey",),
                     "lineitem": ("l_orderkey",)}

    def partitioning(self, name: str) -> tuple[str, ...] | None:
        return self._PARTITIONING.get(name)

    # Scale-free distinct-value counts from the TPC-H spec (the analog of
    # the reference's shipped tpch column statistics,
    # plugin/trino-tpch/src/main/resources/tpch/statistics).
    _NDV_CONST = {
        "lineitem": {"l_returnflag": 3, "l_linestatus": 2, "l_shipmode": 7,
                     "l_shipinstruct": 4, "l_linenumber": 7,
                     "l_quantity": 50, "l_discount": 11, "l_tax": 9},
        "orders": {"o_orderstatus": 3, "o_orderpriority": 5,
                   "o_orderdate": 2406},
        "part": {"p_brand": 25, "p_mfgr": 5, "p_size": 50, "p_type": 150,
                 "p_container": 40},
        "customer": {"c_mktsegment": 5, "c_nationkey": 25},
        "supplier": {"s_nationkey": 25},
        "nation": {"n_nationkey": 25, "n_name": 25, "n_regionkey": 5},
        "region": {"r_regionkey": 5, "r_name": 5},
    }
    # Key columns whose NDV scales with the referenced table's cardinality.
    _NDV_KEY = {
        "lineitem": {"l_orderkey": "orders", "l_partkey": "part",
                     "l_suppkey": "supplier"},
        "orders": {"o_orderkey": "orders", "o_custkey": "customer"},
        "partsupp": {"ps_partkey": "part", "ps_suppkey": "supplier"},
        "part": {"p_partkey": "part"},
        "supplier": {"s_suppkey": "supplier"},
        "customer": {"c_custkey": "customer"},
        "nation": {},
        "region": {},
    }

    def ndv_estimates(self, name: str) -> dict[str, int]:
        out = dict(self._NDV_CONST.get(name, {}))
        rows = self.row_count_estimate(name)
        for col, ref in self._NDV_KEY.get(name, {}).items():
            out[col] = min(self.row_count_estimate(ref), rows)
        return {c: min(n, rows) for c, n in out.items()}

    # Physical-value (min, max) per column for range-predicate
    # selectivity, from the generator's closed-form distributions above
    # (analog of the reference tpch connector's shipped column stats,
    # plugin/trino-tpch src/main/resources JSON). Dates are day numbers,
    # decimals scaled integers.
    _RANGE_CONST = {
        "orders": {"o_orderdate": (STARTDATE, ENDDATE - 151),
                   "o_totalprice": (90000, 60000000)},
        "lineitem": {"l_shipdate": (STARTDATE + 1, ENDDATE - 30),
                     "l_commitdate": (STARTDATE + 30, ENDDATE - 61),
                     "l_receiptdate": (STARTDATE + 2, ENDDATE),
                     "l_quantity": (100, 5000),
                     "l_discount": (0, 10),
                     "l_tax": (0, 8),
                     "l_extendedprice": (90000, 11000000),
                     "l_linenumber": (1, 7)},
        "part": {"p_size": (1, 50), "p_retailprice": (90000, 210000)},
        "partsupp": {"ps_supplycost": (100, 100000),
                     "ps_availqty": (1, 9999)},
        "customer": {"c_acctbal": (-99999, 999999)},
        "supplier": {"s_acctbal": (-99999, 999999)},
        "nation": {"n_nationkey": (0, 24), "n_regionkey": (0, 4)},
        "region": {"r_regionkey": (0, 4)},
    }

    def column_range_estimates(self, name: str):
        out = dict(self._RANGE_CONST.get(name, {}))
        # primary keys are dense 1..n
        key_col = {"orders": "o_orderkey", "customer": "c_custkey",
                   "part": "p_partkey", "supplier": "s_suppkey"}
        if name in key_col:
            out[key_col[name]] = (1, self.row_count_estimate(name))
        if name == "lineitem":
            out["l_orderkey"] = (1, self.row_count_estimate("orders"))
            out["l_partkey"] = (1, self.row_count_estimate("part"))
            out["l_suppkey"] = (1, self.row_count_estimate("supplier"))
        return out

    def stats(self, name: str) -> TableStats:
        raw = self._raw(name)
        nrows = len(next(iter(raw.values())))
        ndv = {}
        for col, dtype in SCHEMAS[name].items():
            if isinstance(dtype, T.VarcharType):
                # cheap estimate: sample
                sample = raw[col][: min(nrows, 10000)]
                if isinstance(sample, EncodedStrings):
                    ndv[col] = int(len(np.unique(sample.codes)))
                else:
                    ndv[col] = int(len(np.unique(sample.astype("U"))))
            else:
                lo = raw[col].min() if nrows else 0
                hi = raw[col].max() if nrows else 0
                ndv[col] = int(min(nrows, max(int(hi - lo) + 1, 1)))
        return TableStats(row_count=nrows, ndv=ndv)
