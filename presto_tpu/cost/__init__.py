"""Cost-based optimizer subsystem.

The engine's analog of the reference's ``io.trino.cost`` package:

- :mod:`presto_tpu.cost.stats` — StatsCalculator, per-PlanNode
  bottom-up propagation of PlanNodeStatsEstimate (rows, per-symbol
  NDV/range/null fraction, bytes) seeded from the connector TableStats
  SPI;
- :mod:`presto_tpu.cost.model` — CostCalculator pricing CPU, memory
  and mesh-aware ICI network per node, plus the single
  broadcast-vs-partitioned decision and dense-span gate every physical
  chooser consults;
- :mod:`presto_tpu.cost.reorder` — the ReorderJoins optimizer rule (DP
  up to 8 relations, greedy above), wired into plan/optimizer.py
  behind ``optimizer_join_reordering_strategy``;
- :mod:`presto_tpu.cost.skew` — the heavy-hitter/salting decision
  refining "partitioned" into "hybrid" joins (hot build keys
  broadcast, cold tail hash-partitioned) from ledger-seeded NDV
  statistics.
"""

from __future__ import annotations

from presto_tpu.cost.model import (CostCalculator, PlanCostEstimate,
                                   decide_join_distribution,
                                   dense_span_eligible)
from presto_tpu.cost.reorder import reorder_joins
from presto_tpu.cost.skew import SkewDecision, decide_skew
from presto_tpu.cost.stats import (PlanNodeStatsEstimate, StatsCalculator,
                                   SymbolStats)

__all__ = [
    "CostCalculator", "PlanCostEstimate", "PlanNodeStatsEstimate",
    "SkewDecision", "StatsCalculator", "SymbolStats",
    "decide_join_distribution", "decide_skew", "dense_span_eligible",
    "explain_estimates", "reorder_joins", "row_estimates",
]


def _fmt(v: float) -> str:
    """Compact magnitude for EXPLAIN (62.5k, 1.2M)."""
    for unit, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if v >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def explain_estimates(plan, engine) -> dict[int, str]:
    """id(node) -> 'Estimates: {...}' detail line for EXPLAIN output
    (reference planprinter/PlanPrinter.formatEstimates). Never raises:
    a node whose stats blow up is simply left unannotated."""
    stats = StatsCalculator(engine)
    cost = CostCalculator()
    out: dict[int, str] = {}

    def visit(node) -> None:
        try:
            est = stats.stats(node)
            c = cost.cost(node, stats)
            mark = "" if est.confident else "?"
            out[id(node)] = (
                f"Estimates: {{rows: {int(est.row_count)}{mark} "
                f"({_fmt(est.output_bytes(node.output_types()))}B), "
                f"cpu: {_fmt(c.cpu)}, memory: {_fmt(c.memory)}B, "
                f"network: {_fmt(c.network)}B}}")
        except Exception:
            pass
        for s in node.sources():
            visit(s)

    visit(plan)
    return out


def row_estimates(plan, engine) -> dict[int, int]:
    """id(node) -> estimated output rows, for EXPLAIN ANALYZE's
    estimated-vs-actual annotations."""
    stats = StatsCalculator(engine)
    out: dict[int, int] = {}

    def visit(node) -> None:
        try:
            out[id(node)] = int(stats.stats(node).row_count)
        except Exception:
            pass
        for s in node.sources():
            visit(s)

    visit(plan)
    return out
