"""Remainder re-costing from mid-query actuals.

The cost-model half of adaptive re-planning (parallel/adaptive.py):
once the TASK-mode stage walk has materialized part of a plan, the
remainder's leaves include ``__exchange__`` carrier scans standing in
for completed stages — relations whose row counts are no longer
estimates but MEASURED. :class:`OverlayStats` is a StatsCalculator
whose table-scan rule answers those carriers from the observed counts
(with the producing subtree's cumulative filter selectivity preserved,
so the unique-build containment rule keeps working through a carrier
dimension), and :func:`reannotate` re-runs the physical-choice
annotations the ReorderJoins pass originally wrote — ``build_rows``,
``capacity``/``output_capacity``, broadcast-vs-partitioned
``distribution``, skew ``hot_keys``/``salt_factor``, aggregate
capacity hints — over the remainder with actuals substituted.

Stability contract (same as the divergence-ledger feedback in
cost/stats.py): every rewritten annotation is power-of-two bucketed
and only rewritten when the correction is MATERIAL (>= the
StatsCalculator FEEDBACK_BAND, 4x), so a replan whose estimates were
roughly right leaves the plan — and therefore the template/program
cache keys — untouched, and a corrected shape costs at most one
compile before templating normally.
"""

from __future__ import annotations

import dataclasses

from presto_tpu.cost.model import (DEFAULT_MESH_SHARDS,
                                   decide_join_distribution)
from presto_tpu.cost.skew import decide_skew
from presto_tpu.cost.stats import PlanNodeStatsEstimate, StatsCalculator
from presto_tpu.ops.hash import next_pow2
from presto_tpu.plan import nodes as N


@dataclasses.dataclass(frozen=True)
class CarrierStats:
    """Observed statistics of one materialized exchange carrier: the
    stage's actual mesh-total output rows, and the cumulative filter
    selectivity of the subtree it materialized (actual rows over the
    base relation's estimated rows — the containment input unique-build
    joins against this carrier need)."""

    rows: int
    selectivity: float = 1.0


class OverlayStats(StatsCalculator):
    """StatsCalculator that answers ``__exchange__`` carrier scans
    from observed :class:`CarrierStats` instead of the unknown-catalog
    fallback; every other rule (joins, aggregates, the ledger
    feedback) is inherited unchanged."""

    def __init__(self, engine, carriers: dict[str, CarrierStats]):
        super().__init__(engine)
        self.carriers = dict(carriers)

    def _s_tablescan(self, node: N.TableScan) -> PlanNodeStatsEstimate:
        if node.catalog == "__exchange__":
            hit = self.carriers.get(node.table)
            if hit is not None:
                return PlanNodeStatsEstimate(
                    max(float(hit.rows), 1.0), {}, True,
                    min(max(hit.selectivity, 1e-9), 1.0))
        return super()._s_tablescan(node)


def _has_partitioned_carrier(node: N.PlanNode,
                             carriers: dict) -> bool:
    """True when ``node``'s subtree contains a carrier that was
    PRODUCED hash-partitioned: its consumption layout is fixed (each
    consumer owns its partition), so a join over it must stay
    partitioned — flipping to broadcast would need an 'all' read the
    producer's buffer reader accounting was never sized for."""
    if isinstance(node, N.TableScan):
        hit = carriers.get(node.table) \
            if node.catalog == "__exchange__" else None
        return hit is not None and hit.partition_keys is not None
    return any(_has_partitioned_carrier(s, carriers)
               for s in node.sources())


def reannotate(plan: N.PlanNode, engine, stats: OverlayStats,
               exchange_sources: dict | None = None,
               note=None) -> N.PlanNode:
    """Re-run the physical-choice annotations over a remainder plan
    with actuals substituted (the mid-flight twin of
    cost/reorder._Ctx._annotate_only). ``note(kind, node, est, actual,
    old, new)`` is called once per MATERIAL rewrite so the caller can
    audit decisions into ``system.adaptive_decisions``. Returns the
    (possibly identical) rewritten plan."""
    session = getattr(engine, "session", None)
    mode = "automatic"
    threshold = None
    hot_threshold = 0
    max_salt = 0
    if session is not None:
        mode = str(session.get("join_distribution_type")
                   or "automatic").lower()
        threshold = int(session.get("broadcast_join_threshold_rows"))
        hot_threshold = int(session.get("skew_hot_key_threshold") or 0)
        max_salt = int(session.get("join_salting") or 0)
    exchange_sources = exchange_sources or {}

    def tell(kind, node, est, actual, old, new):
        if note is not None:
            note(kind, node, est, actual, old, new)

    def revise_join(node: N.Join) -> N.Join:
        b_est = stats.stats(node.right)
        p_est = stats.stats(node.left)
        new_rows = next_pow2(max(int(b_est.row_count), 1))
        old_rows = node.build_rows
        out_rows = None
        if not node.build_unique:
            out_rows, _c = stats.equi_join_rows(
                p_est, b_est, node.criteria, node.build_unique)
        material = old_rows is None or StatsCalculator._material(
            float(old_rows), float(new_rows))
        if not material and out_rows is not None \
                and node.output_capacity is not None:
            # an expanding join's OUTPUT capacity also depends on the
            # probe side: a probe-only divergence must still re-bucket
            # it (each undersized rung is a recompile)
            material = StatsCalculator._material(
                float(node.output_capacity),
                float(next_pow2(max(2 * int(out_rows), 2))))
        if not material:
            return node
        old_dist = decide_join_distribution(
            node.distribution if node.distribution != "automatic"
            else None, mode, old_rows, threshold)
        if _has_partitioned_carrier(node.right, exchange_sources):
            # production layout dictates consumption: stay partitioned
            new_dist = "partitioned"
            hot_keys = salt = None
        else:
            new_dist = decide_join_distribution(None, mode, new_rows,
                                                threshold)
            hot_keys = salt = None
            if new_dist == "partitioned" and mode == "automatic" \
                    and node.join_type == N.JoinType.INNER:
                d = decide_skew(p_est, b_est, node.criteria,
                                node.build_unique,
                                join_type_inner=True,
                                nshards=DEFAULT_MESH_SHARDS,
                                hot_threshold=hot_threshold,
                                max_salt=max_salt)
                if d.active:
                    new_dist = "hybrid" if d.hybrid else new_dist
                    hot_keys = d.hot_keys
                    salt = (d.salt_factor if d.salt_factor > 1
                            else None)
        out_cap = node.output_capacity
        if out_rows is not None:
            cap = min(2 * max(int(out_rows), int(p_est.row_count)),
                      8 * max(int(p_est.row_count),
                              int(b_est.row_count)))
            out_cap = next_pow2(max(cap, 2))
        tell("join-capacity", node, old_rows or -1, new_rows,
             str(node.capacity), str(next_pow2(2 * new_rows)))
        if new_dist != old_dist:
            tell("join-distribution", node, old_rows or -1, new_rows,
                 old_dist, new_dist)
        return dataclasses.replace(
            node, build_rows=new_rows,
            capacity=next_pow2(2 * max(int(b_est.row_count), 1)),
            output_capacity=out_cap, distribution=new_dist,
            hot_keys=hot_keys, salt_factor=salt)

    def revise_multijoin(node: N.MultiJoin) -> N.MultiJoin:
        rows_list = list(node.build_rows)
        dists = list(node.distributions)
        changed = False
        for i, build in enumerate(node.builds):
            b_est = stats.stats(build)
            new_rows = next_pow2(max(int(b_est.row_count), 1))
            old_rows = rows_list[i] if i < len(rows_list) else None
            if old_rows is not None and not StatsCalculator._material(
                    float(old_rows), float(new_rows)):
                continue
            old_dist = decide_join_distribution(
                (dists[i] if i < len(dists) else None) or None,
                mode, old_rows, threshold)
            new_dist = decide_join_distribution(None, mode, new_rows,
                                                threshold)
            while len(rows_list) <= i:
                rows_list.append(None)
            while len(dists) <= i:
                dists.append("automatic")
            rows_list[i] = new_rows
            dists[i] = new_dist
            changed = True
            tell("multijoin-leg", node, old_rows or -1, new_rows,
                 old_dist, new_dist)
        if not changed:
            return node
        return dataclasses.replace(node, build_rows=rows_list,
                                   distributions=dists)

    def revise_aggregate(node: N.Aggregate) -> N.Aggregate:
        if not node.group_keys or node.capacity is None:
            # no hint: the runtime derives a safe input-sized default
            return node
        groups = max(int(stats.stats(node).row_count), 1)
        new_cap = next_pow2(2 * groups)
        if not StatsCalculator._material(float(node.capacity),
                                         float(new_cap)):
            return node
        tell("aggregate-capacity", node, node.capacity // 2, groups,
             str(node.capacity), str(new_cap))
        return dataclasses.replace(node, capacity=new_cap)

    def visit(node: N.PlanNode) -> N.PlanNode:
        if isinstance(node, N.Join) and node.criteria \
                and node.filter is None:
            return revise_join(node)
        if isinstance(node, N.MultiJoin):
            return revise_multijoin(node)
        if isinstance(node, N.Aggregate):
            return revise_aggregate(node)
        return node

    return N.rewrite_bottom_up(plan, visit)
