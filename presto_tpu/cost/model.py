"""Mesh-aware cost model.

The analog of the reference's cost/CostCalculatorUsingExchanges +
TaskCountEstimator: prices CPU, memory, and NETWORK per plan node,
where network models the TPU mesh reality of parallel/executor.py
rather than generic bytes:

- a BROADCAST join is an ``all_gather`` of the build shard — every
  device receives the full build side, so ``build_bytes * (n - 1)``
  bytes cross ICI links;
- a PARTITIONED join is an ``all_to_all`` of BOTH sides — each row
  moves to its hash-owner shard with probability ``(n - 1) / n``, so
  ``(probe_bytes + build_bytes) * (n - 1) / n`` bytes cross ICI.

This module is also the SINGLE home of the engine's physical-choice
thresholds: the broadcast-vs-partitioned decision
(:func:`decide_join_distribution`, consumed by parallel/executor.py,
parallel/fragmenter.py and cost/reorder.py — the three sites can no
longer disagree about a join's distribution) and the dense-key span
eligibility (:func:`dense_span_eligible`, consumed by plan/dense.py).
"""

from __future__ import annotations

import dataclasses
import math

from presto_tpu.plan import nodes as N

# builds at or under this estimated row count broadcast instead of
# repartitioning both sides when no session threshold is supplied
# (matches the broadcast_join_threshold_rows session default; reference
# DetermineJoinDistributionType AUTOMATIC cutoff)
DEFAULT_BROADCAST_ROWS = 1 << 20

# mesh size assumed when pricing plans before a mesh exists (EXPLAIN,
# plan-time reordering); the driver's standard test mesh
DEFAULT_MESH_SHARDS = 8

# widest direct-address table the executor will allocate (slots), and
# the widest relative to the build side — a 16M-slot table for a
# 100-row build wastes HBM for no probe savings (moved here from
# plan/dense.py so span eligibility is a cost-model decision)
MAX_SPAN = 1 << 24
MAX_SPAN_FACTOR = 16

# relative weight of one hash-table-resident byte vs one CPU row-op in
# the scalar cost used for join enumeration (reference
# CostComparator's cpu/memory/network weights)
MEMORY_WEIGHT = 1.0
NETWORK_WEIGHT = 2.0


def decide_join_distribution(node_distribution: str | None,
                             mode: str | None,
                             build_rows: int | None,
                             threshold: int | None = None) -> str:
    """THE broadcast-vs-partitioned decision (reference
    DetermineJoinDistributionType): an explicit per-node distribution
    wins, then a forced session mode, then the AUTOMATIC row-count
    threshold (unknown build size broadcasts, matching the historical
    behavior of both the fragmenter and the runtime executor).
    "hybrid" (skew-aware hot-key broadcast + cold-tail partition,
    cost/skew.py) is a per-node refinement of "partitioned": callers
    without a hybrid path treat it as partitioned."""
    if node_distribution in ("broadcast", "partitioned", "hybrid"):
        return node_distribution
    m = (mode or "automatic").lower()
    if m == "broadcast":
        return "broadcast"
    if m == "partitioned":
        return "partitioned"
    if threshold is None:
        threshold = DEFAULT_BROADCAST_ROWS
    if build_rows is not None and build_rows > threshold:
        return "partitioned"
    return "broadcast"


def dense_span_eligible(rng: tuple, build_rows: int | None) -> bool:
    """May a (lo, hi) build-key range use a direct-address table?
    Memory-cost gate shared by plan/dense.py's join and semi-join
    annotations."""
    lo, hi = rng
    span = hi - lo + 1
    if span <= 0 or span > MAX_SPAN:
        return False
    if build_rows and span > max(MAX_SPAN_FACTOR * build_rows, 4096):
        return False
    return True


def broadcast_net_bytes(build_bytes: float, nshards: int) -> float:
    """ICI bytes of replicating the build side: all_gather of each
    device's shard to every peer."""
    return build_bytes * max(nshards - 1, 0)


def partitioned_net_bytes(probe_bytes: float, build_bytes: float,
                          nshards: int) -> float:
    """ICI bytes of hash-repartitioning both sides: all_to_all moves a
    row off-shard with probability (n-1)/n."""
    if nshards <= 1:
        return 0.0
    return (probe_bytes + build_bytes) * (nshards - 1) / nshards


@dataclasses.dataclass(frozen=True)
class PlanCostEstimate:
    """Per-node cost components (reference cost/PlanCostEstimate.java):
    cpu in row-operations, memory in resident bytes, network in ICI
    bytes."""

    cpu: float = 0.0
    memory: float = 0.0
    network: float = 0.0

    def plus(self, other: "PlanCostEstimate") -> "PlanCostEstimate":
        return PlanCostEstimate(self.cpu + other.cpu,
                                self.memory + other.memory,
                                self.network + other.network)

    def scalar(self) -> float:
        """Single comparable magnitude for plan enumeration."""
        return (self.cpu + MEMORY_WEIGHT * self.memory
                + NETWORK_WEIGHT * self.network)


ZERO_COST = PlanCostEstimate()


class CostCalculator:
    """Local (non-cumulative) cost of each plan node, given a
    StatsCalculator for its inputs. ``nshards`` is the mesh size the
    network model assumes; plan-time consumers use the default."""

    def __init__(self, nshards: int = DEFAULT_MESH_SHARDS,
                 broadcast_threshold: int | None = None):
        self.nshards = max(int(nshards), 1)
        self.broadcast_threshold = broadcast_threshold

    def join_cost(self, probe, build, out_rows: float,
                  build_types, probe_types,
                  distribution: str = "automatic") -> PlanCostEstimate:
        """Price one hash join from its side estimates: probe+build+
        output row-ops, the build hash table resident in HBM, and the
        distribution's ICI traffic."""
        build_bytes = build.output_bytes(build_types)
        probe_bytes = probe.output_bytes(probe_types)
        dist = decide_join_distribution(
            distribution if distribution != "automatic" else None,
            None, int(build.row_count), self.broadcast_threshold)
        if dist == "broadcast":
            net = broadcast_net_bytes(build_bytes, self.nshards)
            mem = build_bytes  # full build table on every device
        else:
            net = partitioned_net_bytes(probe_bytes, build_bytes,
                                        self.nshards)
            mem = build_bytes / self.nshards
        cpu = probe.row_count + 2.0 * build.row_count + out_rows
        return PlanCostEstimate(cpu, mem, net)

    def cost(self, node: N.PlanNode, stats) -> PlanCostEstimate:
        """Local cost of ``node``; ``stats`` is a StatsCalculator."""
        est = stats.stats(node)
        if isinstance(node, N.TableScan):
            return PlanCostEstimate(
                est.row_count, est.output_bytes(node.output_types()), 0)
        if isinstance(node, N.Join):
            probe = stats.stats(node.left)
            build = stats.stats(node.right)
            return self.join_cost(probe, build, est.row_count,
                                  node.right.output_types(),
                                  node.left.output_types(),
                                  node.distribution)
        if isinstance(node, N.MultiJoin):
            # fused star chain: each build priced like the binary join
            # it replaced (its own distribution), the probe estimate
            # FOLDING forward through each leg's unique-build
            # containment — an early selective dimension shrinks every
            # later leg's priced probe, exactly as the cascade's
            # per-join stats would
            total = PlanCostEstimate(est.row_count, 0, 0)
            cur = stats.stats(node.spine)
            for i, build in enumerate(node.builds):
                b = stats.stats(build)
                dist = (node.distributions[i]
                        if i < len(node.distributions) else "automatic")
                out_rows = max(
                    cur.row_count * min(b.selectivity, 1.0), 1.0)
                total = total.plus(self.join_cost(
                    cur, b, out_rows, build.output_types(),
                    node.spine.output_types(), dist))
                cur = dataclasses.replace(cur, row_count=out_rows)
            return total
        if isinstance(node, N.SemiJoin):
            src = stats.stats(node.source)
            filt = stats.stats(node.filter_source)
            fbytes = filt.output_bytes(
                node.filter_source.output_types())
            # filter side replicates (parallel executor semantics)
            return PlanCostEstimate(
                src.row_count + filt.row_count, fbytes,
                broadcast_net_bytes(fbytes, self.nshards))
        if isinstance(node, N.CrossJoin):
            left = stats.stats(node.left)
            right = stats.stats(node.right)
            rbytes = right.output_bytes(node.right.output_types())
            return PlanCostEstimate(
                est.row_count, rbytes,
                broadcast_net_bytes(rbytes, self.nshards))
        if isinstance(node, (N.Aggregate, N.Distinct, N.MarkDistinct)):
            src = stats.stats(node.sources()[0])
            out_bytes = est.output_bytes(node.output_types())
            # partial states gather (or repartition) across the mesh
            return PlanCostEstimate(
                src.row_count, out_bytes,
                broadcast_net_bytes(out_bytes, self.nshards))
        if isinstance(node, N.Exchange):
            src = stats.stats(node.source)
            bytes_ = src.output_bytes(node.output_types())
            if node.kind == N.ExchangeType.REPLICATE:
                net = broadcast_net_bytes(bytes_, self.nshards)
            else:  # gather / repartition move each row once
                net = partitioned_net_bytes(bytes_, 0.0, self.nshards)
            return PlanCostEstimate(src.row_count, 0, net)
        if isinstance(node, (N.Sort, N.TopN, N.Window,
                             N.MatchRecognize)):
            src = stats.stats(node.sources()[0])
            n = max(src.row_count, 2.0)
            return PlanCostEstimate(n * math.log2(n), 0, 0)
        # row-at-a-time operators: Filter/Project/Limit/Union/Unnest/
        # Values/Output and anything future
        srcs = node.sources()
        cpu = sum(stats.stats(s).row_count for s in srcs) \
            if srcs else est.row_count
        return PlanCostEstimate(cpu, 0, 0)
