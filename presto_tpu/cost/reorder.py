"""Cost-based join reordering (reference
sql/planner/iterative/rule/ReorderJoins.java +
DetermineJoinDistributionType).

The logical planner orders join graphs greedily at plan time
(plan/planner.py _order_joins) using leg-local estimates. This pass
re-enumerates every maximal INNER equi-join region of the OPTIMIZED
plan with full plan-wide statistics (cost/stats.py):

- regions of up to :data:`MAX_DP_RELATIONS` relations run an exact
  left-deep dynamic program over the equi-join graph (the engine's
  executors and fragmenter are built around probe spines, so bushy
  shapes are deliberately out of the search space);
- larger regions fall back to a greedy walk driven by the same cost
  function.

Decisions are WRITTEN INTO the Join nodes — ``build_rows`` (power-of-
two-bucketed so the compiled-program cache keeps hitting),
``capacity``/``output_capacity`` hints, ``build_unique`` (recomputed
structurally via plan/dense.unique_key_sets), and under AUTOMATIC
session mode the explicit broadcast-vs-partitioned ``distribution``
from the cost model — so the fragmenter, the runtime distribution
choice, and power-of-two hash-table sizing all consume one set of
estimates.

Session control (``optimizer_join_reordering_strategy``):

- ``AUTOMATIC``  — full cost-based reordering (default);
- ``ELIMINATE_CROSS_JOINS`` — keep the planner's order (its join-graph
  walk already never introduces a cross join where an equi edge
  exists) but refresh estimate annotations from plan-wide stats;
- ``NONE`` — leave plans exactly as planned.
"""

from __future__ import annotations

import dataclasses

from presto_tpu.cost.model import (CostCalculator, DEFAULT_MESH_SHARDS,
                                   decide_join_distribution)
from presto_tpu.cost.skew import decide_skew
from presto_tpu.cost.stats import StatsCalculator
from presto_tpu.ops.hash import next_pow2
from presto_tpu.plan import nodes as N

# DP enumeration bound: 2^8 subset states; beyond this the greedy walk
# takes over (reference ReorderJoins JOIN_REORDERING_MAX_JOINS analog)
MAX_DP_RELATIONS = 8


def reorder_joins(plan: N.PlanNode, engine) -> N.PlanNode:
    """Entry point, wired into plan/optimizer.optimize."""
    session = getattr(engine, "session", None)
    strategy = "AUTOMATIC"
    if session is not None:
        raw = session.get("optimizer_join_reordering_strategy")
        strategy = str(raw or "AUTOMATIC").upper()
    if strategy == "NONE":
        return plan
    ctx = _Ctx(engine, strategy)
    return ctx.walk(plan)


def _is_region_join(node: N.PlanNode) -> bool:
    """Joins the flattener may absorb: INNER equi joins without residual
    filters (a residual references both sides; keeping it on its
    original join preserves placement exactly)."""
    return (isinstance(node, N.Join)
            and node.join_type == N.JoinType.INNER
            and node.criteria and node.filter is None)


class _Ctx:
    def __init__(self, engine, strategy: str):
        self.engine = engine
        self.strategy = strategy
        self.stats = StatsCalculator(engine)
        session = getattr(engine, "session", None)
        self.mode = "automatic"
        self.threshold = None
        self.hot_threshold = 0
        self.max_salt = 0
        if session is not None:
            self.mode = str(session.get(
                "join_distribution_type") or "automatic").lower()
            self.threshold = int(session.get(
                "broadcast_join_threshold_rows"))
            self.hot_threshold = int(session.get(
                "skew_hot_key_threshold") or 0)
            self.max_salt = int(session.get("join_salting") or 0)
        self.cost = CostCalculator(
            broadcast_threshold=self.threshold)

    def _skewed(self, dist: str, probe_est, build_est, criteria,
                build_unique: bool) -> tuple[str, int | None, int | None]:
        """Refine a plan-time "partitioned" choice with the skew
        decision (cost/skew.py): returns (distribution, hot_keys,
        salt_factor) to write into the Join node."""
        if dist != "partitioned":
            return dist, None, None
        d = decide_skew(probe_est, build_est, criteria, build_unique,
                        join_type_inner=True,
                        nshards=DEFAULT_MESH_SHARDS,
                        hot_threshold=self.hot_threshold,
                        max_salt=self.max_salt)
        if not d.active:
            return dist, None, None
        return (("hybrid" if d.hybrid else dist), d.hot_keys,
                (d.salt_factor if d.salt_factor > 1 else None))

    # -- tree walk ----------------------------------------------------------

    def walk(self, node: N.PlanNode) -> N.PlanNode:
        if _is_region_join(node):
            return self._reorder_region(node)
        updates = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, N.PlanNode):
                nv = self.walk(v)
                if nv is not v:
                    updates[f.name] = nv
            elif isinstance(v, list) and v \
                    and isinstance(v[0], N.PlanNode):
                nv = [self.walk(x) for x in v]
                if any(a is not b for a, b in zip(nv, v)):
                    updates[f.name] = nv
        return dataclasses.replace(node, **updates) if updates else node

    def _flatten(self, node: N.PlanNode, rels: list,
                 edges: list) -> None:
        """Collect a region's leaf relations and equi edges
        (reference MultiJoinNode.toMultiJoinNode)."""
        if _is_region_join(node):
            self._flatten(node.left, rels, edges)
            self._flatten(node.right, rels, edges)
            edges.extend(node.criteria)
        else:
            rels.append(self.walk(node))

    def _reorder_region(self, root: N.Join) -> N.PlanNode:
        if self.strategy == "ELIMINATE_CROSS_JOINS":
            # the planner's join-graph walk already avoids cross joins
            # wherever an equi edge exists; just refresh annotations
            return self._annotate_only(root)

        rels: list[N.PlanNode] = []
        raw_edges: list[tuple[str, str]] = []
        self._flatten(root, rels, raw_edges)

        # symbol -> relation index
        sym_rel: dict[str, int] = {}
        for i, r in enumerate(rels):
            for s in r.output_types():
                sym_rel[s] = i
        edges = []  # (rel_a, rel_b, sym_a, sym_b)
        for a, b in raw_edges:
            if a not in sym_rel or b not in sym_rel:
                return self._annotate_only(root)
            edges.append((sym_rel[a], sym_rel[b], a, b))

        if len(rels) <= MAX_DP_RELATIONS:
            built = self._dp(rels, edges)
        else:
            built = self._greedy(rels, edges)
        if built is None:  # disconnected graph: keep planner's shape
            return self._annotate_only(root)
        return built

    # -- candidate join construction ----------------------------------------

    def _unique_sets(self, node: N.PlanNode):
        from presto_tpu.plan.dense import unique_key_sets
        return unique_key_sets(node, self.engine)

    def _make_join(self, probe: N.PlanNode, build: N.PlanNode,
                   criteria: list[tuple[str, str]]) -> N.Join:
        """Construct one candidate join with cost-model annotations
        (capacities power-of-two, build_rows pow2-bucketed, explicit
        distribution under AUTOMATIC session mode)."""
        bsyms = frozenset(b for _, b in criteria)
        build_unique = any(k <= bsyms for k in self._unique_sets(build))
        p_est = self.stats.stats(probe)
        b_est = self.stats.stats(build)
        out_rows, _conf = self.stats.equi_join_rows(
            p_est, b_est, criteria, build_unique)
        build_rows = next_pow2(max(int(b_est.row_count), 1))
        dist = "automatic"
        hot_keys = salt = None
        if self.mode == "automatic":
            dist = decide_join_distribution(
                None, self.mode, build_rows, self.threshold)
            dist, hot_keys, salt = self._skewed(
                dist, p_est, b_est, criteria, build_unique)
        out_cap = None
        if not build_unique:
            # conservative hint, same bound as the planner: an
            # undersized guess costs one RETRY_GROWTH recompile, an
            # oversized one allocates HBM up front
            cap = min(2 * max(int(out_rows), int(p_est.row_count)),
                      8 * max(int(p_est.row_count),
                              int(b_est.row_count)))
            out_cap = next_pow2(max(cap, 2))
        return N.Join(
            probe, build, N.JoinType.INNER, list(criteria), None,
            build_unique, distribution=dist, build_rows=build_rows,
            hot_keys=hot_keys, salt_factor=salt,
            capacity=next_pow2(2 * max(int(b_est.row_count), 1)),
            output_capacity=out_cap)

    def _join_and_cost(self, probe_node, probe_cost: float,
                       build_node, build_cost: float,
                       criteria) -> tuple[N.Join, float]:
        join = self._make_join(probe_node, build_node, criteria)
        est = self.stats.stats(join)
        # price the distribution that will actually run: a forced
        # session mode overrides the node annotation (which stays
        # "automatic" so runtime forcing keeps working)
        eff_dist = decide_join_distribution(
            join.distribution if join.distribution != "automatic"
            else None, self.mode, join.build_rows, self.threshold)
        local = self.cost.join_cost(
            self.stats.stats(probe_node), self.stats.stats(build_node),
            est.row_count, build_node.output_types(),
            probe_node.output_types(), eff_dist)
        return join, probe_cost + build_cost + local.scalar()

    # -- enumeration ---------------------------------------------------------

    def _dp(self, rels: list[N.PlanNode],
            edges: list) -> N.PlanNode | None:
        """Exact left-deep DP over connected subsets: best[mask] is the
        cheapest probe spine covering ``mask``, extended one build
        relation at a time (Selinger-style, reference ReorderJoins'
        memoized createJoinAccordingToPartitioning specialized to
        left-deep shapes)."""
        n = len(rels)
        leaf_cost = [self.cost.cost(r, self.stats).scalar()
                     for r in rels]
        best: dict[int, tuple[float, N.PlanNode]] = {
            1 << i: (leaf_cost[i], rels[i]) for i in range(n)}
        for mask in range(1, 1 << n):
            if mask not in best:
                continue
            # best[mask] exists: try attaching every connected build rel
            cur_cost, cur_node = best[mask]
            for j in range(n):
                if mask & (1 << j):
                    continue
                criteria = _connecting(edges, mask, j)
                if not criteria:
                    continue
                join, total = self._join_and_cost(
                    cur_node, cur_cost, rels[j], leaf_cost[j], criteria)
                key = mask | (1 << j)
                if key not in best or total < best[key][0]:
                    best[key] = (total, join)
        full = (1 << n) - 1
        hit = best.get(full)
        return hit[1] if hit is not None else None

    def _greedy(self, rels: list[N.PlanNode],
                edges: list) -> N.PlanNode | None:
        """Greedy fallback above the DP bound: start from the largest
        relation (the fact table) and repeatedly attach the cheapest
        connected build side — the planner's walk, re-driven by
        plan-wide stats."""
        n = len(rels)
        leaf_cost = [self.cost.cost(r, self.stats).scalar()
                     for r in rels]
        start = max(range(n),
                    key=lambda i: self.stats.stats(rels[i]).row_count)
        mask = 1 << start
        node, total = rels[start], leaf_cost[start]
        while mask != (1 << n) - 1:
            cand = None
            for j in range(n):
                if mask & (1 << j):
                    continue
                criteria = _connecting(edges, mask, j)
                if not criteria:
                    continue
                join, cost = self._join_and_cost(
                    node, total, rels[j], leaf_cost[j], criteria)
                if cand is None or cost < cand[0]:
                    cand = (cost, join, j)
            if cand is None:
                return None  # disconnected
            total, node, j = cand
            mask |= 1 << j
        return node

    # -- annotation-only refresh --------------------------------------------

    def _annotate_only(self, node: N.PlanNode) -> N.PlanNode:
        """Keep the tree shape; refresh Join estimate annotations from
        plan-wide stats (ELIMINATE_CROSS_JOINS and bail-out paths)."""
        if not _is_region_join(node):
            return self.walk(node)
        left = self._annotate_only(node.left)
        right = self._annotate_only(node.right)
        out = dataclasses.replace(node, left=left, right=right)
        b_est = self.stats.stats(right)
        build_rows = next_pow2(max(int(b_est.row_count), 1))
        dist = out.distribution
        hot_keys, salt = out.hot_keys, out.salt_factor
        if dist == "automatic" and self.mode == "automatic":
            dist = decide_join_distribution(
                None, self.mode, build_rows, self.threshold)
            dist, hot_keys, salt = self._skewed(
                dist, self.stats.stats(left), b_est,
                node.criteria, node.build_unique)
        return dataclasses.replace(
            out, build_rows=build_rows,
            capacity=next_pow2(2 * max(int(b_est.row_count), 1)),
            distribution=dist, hot_keys=hot_keys, salt_factor=salt)


def _connecting(edges: list, mask: int, j: int
                ) -> list[tuple[str, str]]:
    """Criteria (probe_sym, build_sym) of edges between subset ``mask``
    and relation ``j``."""
    out = []
    for (a, b, sa, sb) in edges:
        if a == j and (mask >> b) & 1:
            out.append((sb, sa))
        elif b == j and (mask >> a) & 1:
            out.append((sa, sb))
    return out
