"""Skew-aware join-distribution decisions.

The plan-time half of the engine's heavy-hitter handling (the JSPIM
skew-aware partitioning idea, PAPERS.md 2508.08503): a partitioned
join whose probe keys concentrate on few values collapses its
``all_to_all`` onto the hot keys' owner shards — one shard receives a
heavy hitter's whole row set while its peers idle, and the
capacity-overflow retry ladder burns a recompile per rung. This module
decides, from CBO statistics (cost/stats.py — per-symbol NDVs already
seeded by the divergence ledger's ``observed_ndv`` feedback,
obs/qstats.py), whether a partitioned join should compile the HYBRID
path and/or salt its exchanges:

- **hybrid**: the traced program carries a count sketch over the probe
  keys; keys whose mesh-global row count exceeds the session
  ``skew_hot_key_threshold`` keep their probe rows LOCAL and their
  build rows replicate (``all_gather``), while the cold tail
  hash-partitions as before (parallel/executor.py ``_r_join``). The
  decision here only chooses to PAY for that machinery — the hot set
  itself is data, detected at runtime, so a hybrid program over
  uniform data degrades to a plain partitioned join (empty hot set).
- **salting**: probe rows of one key spread over ``salt_factor``
  shards (build rows tile once per salt value), bounding the cold
  tail's per-shard imbalance when even sub-threshold keys exceed a
  shard's fair share.

Everything written into plan nodes is power-of-two bucketed
(ops/hash.next_pow2), so literal variants of one query shape keep
identical fingerprints and the plan-template/program caches keep
hitting.

Heavy-hitter estimation assumes a Zipf(1) worst case when no
observation says otherwise: with N distinct keys over R probe rows,
the rank-k key holds ~ R / (k * ln N) rows, so the number of keys
exceeding a threshold T is ~ R / (T * ln N). That errs toward
compiling the hybrid path (cheap when the hot set turns out empty)
rather than missing real skew.
"""

from __future__ import annotations

import dataclasses
import math

from presto_tpu.ops.hash import next_pow2

# count-sketch width used by the runtime heavy-hitter detector
# (parallel/executor.py): collisions only over-count, so a cold key
# sharing a bucket with a hot one is merely broadcast too — correct
# either way, never a miss
SKETCH_BUCKETS = 1 << 13

# hybrid is pointless under this mesh size (one shard holds everything)
MIN_SHARDS = 2


@dataclasses.dataclass(frozen=True)
class SkewDecision:
    """Plan-time skew annotations for one partitioned join.

    ``hybrid`` selects the hot-key-broadcast path; ``hot_keys`` is the
    pow2-bucketed heavy-hitter count estimate sizing the replicated
    hot-build table; ``salt_factor`` (pow2, >= 1) fans the cold tail's
    exchange out over that many sub-buckets per key."""

    hybrid: bool = False
    hot_keys: int | None = None
    salt_factor: int = 1

    @property
    def active(self) -> bool:
        return self.hybrid or self.salt_factor > 1


NO_SKEW = SkewDecision()


def _key_ndv(probe_est, lkeys) -> tuple[float, bool]:
    """Distinct-tuple estimate of the probe join keys (product of
    per-key NDVs capped at rows), and whether every key had real
    statistics behind it."""
    ndv = 1.0
    confident = True
    for k in lkeys:
        st = probe_est.symbol(k)
        if st.ndv is None:
            confident = False
            ndv *= 32.0
        else:
            ndv *= max(st.ndv, 1.0)
    return max(min(ndv, max(probe_est.row_count, 1.0)), 1.0), confident


def estimate_hot_keys(probe_rows: float, key_ndv: float,
                      threshold: int) -> int:
    """Zipf(1) worst-case count of keys whose probe frequency exceeds
    ``threshold``: freq(rank k) ~ rows / (k * ln ndv)."""
    if threshold <= 0 or probe_rows <= 0:
        return 0
    h = max(math.log(max(key_ndv, 2.0)), 1.0)
    hot = probe_rows / (threshold * h)
    return int(min(hot, key_ndv))


def choose_salt_factor(probe_rows: float, nshards: int,
                       max_freq: float, max_salt: int) -> int:
    """Salt fan-out bounding one key's per-shard share: spread a key
    expected to hold ``max_freq`` rows over enough shards that no
    single shard receives more than the mesh's fair per-shard row
    budget. pow2-bucketed and capped at the session ``join_salting``
    limit (and at the mesh width — more salts than shards buys
    nothing)."""
    if max_salt <= 1 or nshards < MIN_SHARDS or probe_rows <= 0:
        return 1
    fair = max(probe_rows / nshards, 1.0)
    if max_freq <= fair:
        return 1
    # pow2 the demand first, then FLOOR to the caps — rounding up
    # after capping would exceed the session limit (and tiling more
    # build copies than shards buys nothing)
    cap = min(max_salt, nshards)
    f = next_pow2(int(math.ceil(max_freq / fair)))
    while f > cap:
        f //= 2
    return max(f, 1)


def decide_skew(probe_est, build_est, criteria, build_unique: bool,
                join_type_inner: bool, nshards: int,
                hot_threshold: int, max_salt: int) -> SkewDecision:
    """THE skew decision for one already-partitioned join (consulted by
    cost/reorder.py when it writes distributions into Join nodes).
    ``probe_est``/``build_est`` are PlanNodeStatsEstimates whose NDVs
    the StatsCalculator already seeded from the observed-NDV ledger, so
    history participates without a second lookup here."""
    if nshards < MIN_SHARDS:
        return NO_SKEW
    lkeys = [lk for lk, _ in criteria]
    if not lkeys:
        return NO_SKEW
    ndv, _confident = _key_ndv(probe_est, lkeys)
    rows = max(probe_est.row_count, 1.0)
    hot = estimate_hot_keys(rows, ndv, hot_threshold) \
        if hot_threshold > 0 else 0
    # a unique build holds one row per key: the replicated hot-build
    # table can never need more slots than the build side has rows
    hot = int(min(hot, max(build_est.row_count, 1.0)))
    # hybrid only when the worst-case TOP key both clears the
    # threshold and exceeds a shard's fair row share — a heavy hitter
    # smaller than rows/nshards cannot imbalance the all_to_all, and
    # compiling the hybrid path anyway would pay its second
    # full-probe-width join and wider concatenated output on every
    # execution of a perfectly uniform join. (Shapes: the runtime only
    # supports probe-preserving INNER/LEFT unique builds; FULL and
    # expanding joins keep their existing paths and rely on salting.)
    top = rows / max(math.log(max(ndv, 2.0)), 1.0)
    hybrid = bool(hot >= 1 and hot_threshold > 0
                  and top >= hot_threshold
                  and top >= rows / nshards
                  and build_unique and join_type_inner)
    salt = 1
    if max_salt > 1:
        # the hottest key the cold tail can still hold: the threshold
        # itself under hybrid (hotter keys were broadcast), else the
        # Zipf top-rank estimate
        h = max(math.log(max(ndv, 2.0)), 1.0)
        top = rows / h
        max_cold = float(min(top, hot_threshold)) if hybrid else top
        salt = choose_salt_factor(rows, nshards, max_cold, max_salt)
    if not hybrid and salt <= 1:
        return NO_SKEW
    return SkewDecision(
        hybrid=hybrid,
        hot_keys=next_pow2(max(hot, 1)) if hybrid else None,
        salt_factor=salt)
