"""Plan-wide statistics propagation.

The analog of the reference's cost/StatsCalculator.java +
ComposableStatsCalculator rule table: a per-PlanNode dispatch table
(``StatsCalculator._s_<node>``) propagates
:class:`PlanNodeStatsEstimate` — row count, per-symbol NDV / value
range / null fraction, and output bytes — bottom-up through the whole
tree, seeded from the connector ``TableStats`` SPI
(connectors/base.py row_count_estimate / ndv_estimates /
column_range_estimates).

This generalizes the leaf-only selectivity slice in ``plan/stats.py``
(which stays the shared FilterStatsCalculator) into join, aggregation,
semi-join, union and limit estimation rules, so the ReorderJoins
optimizer (cost/reorder.py) and the CostCalculator (cost/model.py)
price whole subtrees instead of single scans.

Estimates are intentionally COARSE: everything written back into plan
nodes by consumers is power-of-two-bucketed (ops/hash.next_pow2), so
similar inputs keep compiling identical programs and the
compiled-program cache (exec/executor.py) keeps hitting.

The dispatch table is registered with the plan-dispatch lint rule
(lint/dispatch.py SITES): adding a PlanNode subclass without a
``_s_`` rule here fails ``python -m presto_tpu.lint`` and tier-1
``tests/test_lint.py``.
"""

from __future__ import annotations

import dataclasses
import math
import re

from presto_tpu.plan import nodes as N
from presto_tpu.plan.stats import (UNKNOWN_FILTER_COEFFICIENT,
                                   selectivity, selectivity_informed)

_SYM_SUFFIX = re.compile(r"_\d+$")


def base_symbol(sym: str) -> str:
    """Strip the planner's per-statement ``_NN`` suffix so observation
    keys ("n_name") pool across statements that allocate different
    symbol numbers for the same base column (the divergence ledger and
    this calculator must agree on the spelling). Strips exactly ONE
    trailing suffix: every symbol the planner allocates carries one,
    so a base column itself named with a digit suffix ("address_1" ->
    symbol "address_1_17") round-trips correctly."""
    return _SYM_SUFFIX.sub("", sym)


def predicate_shape(expr) -> str:
    """Literal-normalized structural shape of a predicate expression
    ("lte(l_shipdate, ?)"): the key the divergence ledger
    (obs/qstats.py) aggregates observed selectivity under, so every
    literal variant of one predicate shape — the plan-template notion
    of sameness — pools into a single observation series. The
    ``_s_filter`` rule below consults it (ROADMAP item 4's feedback
    loop: observed selectivity outranks the static guess)."""
    from presto_tpu.expr import ir

    def walk(e) -> str:
        if isinstance(e, (ir.Literal, ir.Parameter)):
            return "?"
        if isinstance(e, ir.ColumnRef):
            # planner symbol suffixes are per-statement; the base
            # column name pools one predicate shape across statements
            return base_symbol(e.name)
        if isinstance(e, ir.Call):
            return (f"{e.fn}("
                    + ", ".join(walk(a) for a in e.args) + ")")
        if isinstance(e, ir.Cast):
            return f"cast({walk(e.arg)} as {e.dtype})"
        if isinstance(e, ir.InList):
            return f"{walk(e.arg)} in (?*{len(e.values)})"
        if isinstance(e, ir.IsNull):
            return f"{walk(e.arg)} is " \
                   f"{'not ' if e.negated else ''}null"
        return type(e).__name__.lower()

    return walk(expr)


# row count assumed for a relation with no usable connector statistics
# (exchange carrier scans, unknown catalogs); estimates derived from it
# are flagged non-confident
UNKNOWN_ROWS = 1000.0
# fallback per-symbol NDV when a join/group key has no statistics
# (the planner's _order_joins uses the same default)
DEFAULT_NDV = 32.0
# assumed elements per array for Unnest expansion
UNNEST_FACTOR = 8.0


@dataclasses.dataclass(frozen=True)
class SymbolStats:
    """Per-symbol statistics (reference cost/SymbolStatsEstimate.java):
    distinct-value estimate, physical-value range, null fraction.
    ``None`` means unknown."""

    ndv: float | None = None
    low: float | None = None
    high: float | None = None
    null_fraction: float = 0.0

    def capped(self, rows: float) -> "SymbolStats":
        if self.ndv is None or self.ndv <= rows:
            return self
        return dataclasses.replace(self, ndv=max(rows, 1.0))


@dataclasses.dataclass
class PlanNodeStatsEstimate:
    """Output estimate of one plan node (reference
    cost/PlanNodeStatsEstimate.java). ``confident`` is False once any
    contributing rule fell back to an unknown-stats default.
    ``selectivity`` is the cumulative filter fraction applied to this
    relation since its base scans — the containment input for
    unique-build joins (a filtered PK side keeps only this fraction of
    FK probe rows; the planner's RelationPlan.sel, cost/JoinStatsRule
    analog), which sidesteps the per-criterion independence error on
    composite keys."""

    row_count: float
    symbols: dict[str, SymbolStats] = dataclasses.field(
        default_factory=dict)
    confident: bool = True
    selectivity: float = 1.0

    def symbol(self, name: str) -> SymbolStats:
        return self.symbols.get(name, SymbolStats())

    def output_bytes(self, types) -> float:
        """Estimated output size: row count x sum of physical column
        widths (dictionary-encoded varchar counts its code width, the
        HBM-resident form)."""
        width = 0
        for t in types.values():
            try:
                width += t.physical_dtype().itemsize
            except Exception:
                width += 8
        return self.row_count * max(width, 1)


def _ndv_dicts(est: PlanNodeStatsEstimate):
    """(ndv, ranges) dicts in the plan/stats.selectivity format."""
    ndv = {s: int(st.ndv) for s, st in est.symbols.items()
           if st.ndv is not None and st.ndv >= 1}
    ranges = {s: (st.low, st.high) for s, st in est.symbols.items()
              if st.low is not None and st.high is not None}
    return ndv, ranges


class StatsCalculator:
    """Bottom-up stats propagation over a logical plan. One instance
    memoizes per node object, so repeated subtree queries (DP join
    enumeration) stay cheap."""

    def __init__(self, engine):
        self.engine = engine
        session = getattr(engine, "session", None)
        try:
            self.worst_case_ratio = float(
                session.get("cost_estimation_worst_case_ratio"))
        except Exception:
            self.worst_case_ratio = 8.0
        # id(node) -> (node ref pinning the id, estimate)
        self._memo: dict[int, tuple] = {}

    def stats(self, node: N.PlanNode) -> PlanNodeStatsEstimate:
        hit = self._memo.get(id(node))
        if hit is not None and hit[0] is node:
            return hit[1]
        rule = getattr(self, "_s_" + type(node).__name__.lower(),
                       self._unknown)
        est = rule(node)
        # a symbol can never have more distinct values than rows
        est.symbols = {s: st.capped(est.row_count)
                       for s, st in est.symbols.items()}
        self._memo[id(node)] = (node, est)
        return est

    def _unknown(self, node: N.PlanNode) -> PlanNodeStatsEstimate:
        srcs = node.sources()
        if srcs:
            inner = self.stats(srcs[0])
            return PlanNodeStatsEstimate(inner.row_count,
                                         dict(inner.symbols), False)
        return PlanNodeStatsEstimate(UNKNOWN_ROWS, {}, False)

    # -- observed-statistics feedback (the divergence ledger) ----------------
    #
    # Stability contract: estimates flow into pow2-bucketed plan
    # annotations (capacities, build_rows, skew decisions) that key the
    # compiled-program/template caches, so feedback must not wobble
    # them. An observation is admitted only when the static estimate is
    # MATERIALLY wrong (>= FEEDBACK_BAND off — the divergence class the
    # ledger exists to catch), and the admitted value is pow2-quantized
    # so nearby observations of one shape produce identical plans. A
    # corrected shape costs exactly one recompile, then every literal
    # variant keeps hitting.

    FEEDBACK_BAND = 4.0

    @classmethod
    def _material(cls, static: float, observed: float) -> bool:
        hi = max(static, observed)
        lo = max(min(static, observed), 1e-30)
        return hi / lo >= cls.FEEDBACK_BAND

    @staticmethod
    def _quant(value: float) -> float:
        """pow2 quantization for counts (>= 1) and fractions alike."""
        if value <= 0:
            return 1.0
        return float(2.0 ** round(math.log2(value)))

    @staticmethod
    def _ledger():
        """PR 8's divergence ledger: per-(table, predicate-shape)
        observed selectivity and per-(table, keys) observed NDV. Lazy
        import — obs/qstats imports this module for predicate_shape."""
        from presto_tpu.obs.qstats import DIVERGENCE
        return DIVERGENCE

    @staticmethod
    def _scan_table(node: N.PlanNode) -> str | None:
        """catalog.table of the single base scan under ``node``
        (through Filters/Projects), or None."""
        cur = node
        while True:
            if isinstance(cur, N.TableScan):
                return (None if str(cur.catalog).startswith("__")
                        else f"{cur.catalog}.{cur.table}")
            if not isinstance(cur, (N.Filter, N.Project)):
                return None
            cur = cur.source

    # -- leaves -------------------------------------------------------------

    def _s_tablescan(self, node: N.TableScan) -> PlanNodeStatsEstimate:
        conn = getattr(self.engine, "catalogs", {}).get(node.catalog)
        if conn is None:
            return PlanNodeStatsEstimate(UNKNOWN_ROWS, {}, False)
        try:
            rows = float(conn.row_count_estimate(node.table))
            ndv = conn.ndv_estimates(node.table)
            ranges = conn.column_range_estimates(node.table)
        except Exception:
            # decorated/pushed-down table names a connector does not
            # recognize for stats, or connectors without the SPI
            return PlanNodeStatsEstimate(UNKNOWN_ROWS, {}, False)
        symbols = {}
        ledger = self._ledger()
        tname = f"{node.catalog}.{node.table}"
        for sym, col in node.assignments.items():
            rng = ranges.get(col)
            nd = float(ndv[col]) if col in ndv else None
            # observed-NDV feedback: a real single-key distinct count
            # recorded by the divergence ledger replaces a missing or
            # materially wrong connector guess (ROADMAP item 4
            # seeding; quantized — see the stability contract above)
            seen = ledger.observed_ndv(tname, (col,))
            if seen and (nd is None or self._material(nd, seen)):
                nd = self._quant(float(seen))
            symbols[sym] = SymbolStats(
                ndv=nd,
                low=float(rng[0]) if rng else None,
                high=float(rng[1]) if rng else None)
        return PlanNodeStatsEstimate(max(rows, 1.0), symbols)

    def _s_values(self, node: N.Values) -> PlanNodeStatsEstimate:
        symbols = {}
        for i, sym in enumerate(node.symbols):
            vals = [row[i] for row in node.rows if row[i] is not None]
            nums = [v for v in vals if isinstance(v, (int, float))
                    and not isinstance(v, bool)]
            symbols[sym] = SymbolStats(
                ndv=float(len(set(map(repr, vals)))) or 1.0,
                low=float(min(nums)) if nums else None,
                high=float(max(nums)) if nums else None,
                null_fraction=(1.0 - len(vals) / len(node.rows))
                if node.rows else 0.0)
        return PlanNodeStatsEstimate(float(len(node.rows)) or 1.0,
                                     symbols)

    # -- row-preserving operators -------------------------------------------

    def _s_filter(self, node: N.Filter) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        ndv, ranges = _ndv_dicts(src)
        sel = selectivity(node.predicate, ndv, ranges)
        # observed-selectivity feedback: the ledger's average for this
        # (table, predicate shape) — literal variants pool — replaces
        # a MATERIALLY wrong static guess once a real execution has
        # been measured (quantized; see the stability contract above).
        # Only for predicates the static rule could NOT inform from
        # real statistics: the pooled mean is literal-blind, and a
        # value-aware range interpolation legitimately disagrees with
        # it on selective literals
        table = self._scan_table(node.source)
        if table is not None and not selectivity_informed(
                node.predicate, ndv, ranges):
            seen = self._ledger().observed_selectivity(
                table, predicate_shape(node.predicate))
            if seen is not None and self._material(sel, seen):
                # floor BEFORE quantizing: _quant(0) means "1" for
                # counts, but an observed empty filter must estimate
                # near-zero, not pass-everything
                sel = max(min(self._quant(max(seen, 1e-9)), 1.0),
                          1e-9)
        rows = max(src.row_count * sel, 1.0)
        return PlanNodeStatsEstimate(rows, dict(src.symbols),
                                     src.confident,
                                     src.selectivity * sel)

    def _s_project(self, node: N.Project) -> PlanNodeStatsEstimate:
        from presto_tpu.expr import ir
        src = self.stats(node.source)
        symbols = {}
        for sym, expr in node.assignments.items():
            if isinstance(expr, ir.ColumnRef):
                symbols[sym] = src.symbol(expr.name)
            else:
                symbols[sym] = SymbolStats()
        return PlanNodeStatsEstimate(src.row_count, symbols,
                                     src.confident, src.selectivity)

    def _s_sort(self, node: N.Sort) -> PlanNodeStatsEstimate:
        return self.stats(node.source)

    def _s_exchange(self, node: N.Exchange) -> PlanNodeStatsEstimate:
        return self.stats(node.source)

    def _s_output(self, node: N.Output) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        return PlanNodeStatsEstimate(
            src.row_count,
            {s: src.symbol(s) for s in node.symbols}, src.confident)

    def _s_window(self, node: N.Window) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        symbols = dict(src.symbols)
        for sym in node.functions:
            symbols[sym] = SymbolStats()
        return PlanNodeStatsEstimate(src.row_count, symbols,
                                     src.confident, src.selectivity)

    def _s_markdistinct(self, node: N.MarkDistinct
                        ) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        symbols = dict(src.symbols)
        symbols[node.mark_symbol] = SymbolStats(ndv=2.0)
        return PlanNodeStatsEstimate(src.row_count, symbols,
                                     src.confident, src.selectivity)

    # -- cardinality-changing operators -------------------------------------

    def _s_limit(self, node: N.Limit) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        rows = min(src.row_count, float(node.count))
        return PlanNodeStatsEstimate(max(rows, 1.0), dict(src.symbols),
                                     src.confident)

    def _s_topn(self, node: N.TopN) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        rows = min(src.row_count, float(node.count))
        return PlanNodeStatsEstimate(max(rows, 1.0), dict(src.symbols),
                                     src.confident)

    def _group_rows(self, src: PlanNodeStatsEstimate,
                    keys) -> tuple[float, bool]:
        """Distinct-tuple estimate over ``keys`` (product of per-key
        NDVs, capped at input rows — reference
        AggregationStatsRule.groupBy)."""
        if not keys:
            return 1.0, True
        prod = 1.0
        confident = src.confident
        for k in keys:
            nd = src.symbol(k).ndv
            if nd is None:
                nd = DEFAULT_NDV
                confident = False
            prod = min(prod * max(nd, 1.0), 1e18)
        return max(min(prod, src.row_count), 1.0), confident

    @staticmethod
    def _subtree_single_table(node: N.PlanNode) -> str | None:
        """The one base table under ``node``, or None when the subtree
        scans several — the ledger's OWN recording-side walk, so the
        record and consult keys cannot drift apart."""
        from presto_tpu.obs.qstats import _subtree_table
        return _subtree_table(node) or None

    def _s_aggregate(self, node: N.Aggregate) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        rows, confident = self._group_rows(src, node.group_keys)
        if node.group_keys:
            table = self._subtree_single_table(node)
            if table is not None:
                seen = self._ledger().observed_ndv(
                    table,
                    tuple(base_symbol(k) for k in node.group_keys))
                if seen and self._material(rows, seen):
                    # the observation covers the UNFILTERED table; a
                    # filtered source still bounds the group count
                    # (the static rule's min(prod, rows) invariant)
                    rows = min(self._quant(float(seen)),
                               max(src.row_count, 1.0))
                    confident = True
        symbols = {k: src.symbol(k) for k in node.group_keys}
        for sym in node.output_symbols:
            if sym not in symbols:
                symbols[sym] = SymbolStats()
        return PlanNodeStatsEstimate(rows, symbols, confident)

    def _s_distinct(self, node: N.Distinct) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        rows, confident = self._group_rows(
            src, list(node.source.output_types()))
        return PlanNodeStatsEstimate(rows, dict(src.symbols), confident)

    def _s_union(self, node: N.Union) -> PlanNodeStatsEstimate:
        rows = 0.0
        confident = True
        symbols = {s: SymbolStats() for s in node.symbols}
        ndv_sum: dict[str, float] = {}
        for inp, mapping in zip(node.inputs, node.mappings):
            sub = self.stats(inp)
            rows += sub.row_count
            confident = confident and sub.confident
            for out_sym, in_sym in mapping.items():
                st = sub.symbol(in_sym)
                if st.ndv is not None:
                    ndv_sum[out_sym] = ndv_sum.get(out_sym, 0.0) + st.ndv
        for sym, nd in ndv_sum.items():
            symbols[sym] = SymbolStats(ndv=nd)
        return PlanNodeStatsEstimate(max(rows, 1.0), symbols, confident)

    def _s_unnest(self, node: N.Unnest) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        symbols = dict(src.symbols)
        for sym in node.out_syms:
            symbols[sym] = SymbolStats()
        if node.ordinality_sym:
            symbols[node.ordinality_sym] = SymbolStats(low=1.0)
        return PlanNodeStatsEstimate(src.row_count * UNNEST_FACTOR,
                                     symbols, False)

    def _s_matchrecognize(self, node: N.MatchRecognize
                          ) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        rows, _ = self._group_rows(src, node.partition_by)
        symbols = {s: src.symbol(s) for s in node.partition_by}
        for sym, _k, _e, _t in node.measures:
            symbols[sym] = SymbolStats()
        return PlanNodeStatsEstimate(rows, symbols, False)

    # -- joins ---------------------------------------------------------------

    def equi_join_rows(self, probe: PlanNodeStatsEstimate,
                       build: PlanNodeStatsEstimate,
                       criteria, build_unique: bool
                       ) -> tuple[float, bool]:
        """Inner equi-join output estimate: per-criterion selectivity
        1/max(ndv_probe, ndv_build) over the row-count product
        (reference cost/JoinStatsRule.java), with the unique-build
        containment shortcut and a worst-case cap when key statistics
        are missing (session cost_estimation_worst_case_ratio)."""
        confident = probe.confident and build.confident
        if build_unique:
            # FK->PK containment: a filtered PK side keeps its
            # cumulative filter fraction of probe rows (the planner's
            # RelationPlan.sel rule, plan-wide). The per-criterion NDV
            # quotient would undercount composite keys whose columns
            # correlate (lineitem x partsupp on (partkey, suppkey)).
            return (max(probe.row_count * min(build.selectivity, 1.0),
                        1.0), confident)
        sel = 1.0
        for pk, bk in criteria:
            np_ = probe.symbol(pk).ndv
            nb = build.symbol(bk).ndv
            if np_ is None and nb is None:
                np_ = nb = DEFAULT_NDV
            if np_ is None or nb is None:
                # one-sided unknown: the quotient leans on a single
                # side's NDV — keep the estimate but let the worst-case
                # cap below bound the damage
                confident = False
            sel /= max(np_ or 1.0, nb or 1.0, 1.0)
        rows = probe.row_count * build.row_count * sel
        if not confident:
            rows = min(rows, self.worst_case_ratio
                       * max(probe.row_count, build.row_count))
        return max(rows, 1.0), confident

    def _s_join(self, node: N.Join) -> PlanNodeStatsEstimate:
        probe = self.stats(node.left)
        build = self.stats(node.right)
        rows, confident = self.equi_join_rows(
            probe, build, node.criteria, node.build_unique)
        if node.filter is not None:
            rows = max(rows * UNKNOWN_FILTER_COEFFICIENT, 1.0)
        if node.join_type == N.JoinType.LEFT:
            rows = max(rows, probe.row_count)
        elif node.join_type == N.JoinType.RIGHT:
            rows = max(rows, build.row_count)
        elif node.join_type == N.JoinType.FULL:
            rows = max(rows, probe.row_count + build.row_count)
        symbols = {**probe.symbols, **build.symbols}
        return PlanNodeStatsEstimate(
            rows, symbols, confident,
            probe.selectivity * build.selectivity)

    def _s_multijoin(self, node: N.MultiJoin) -> PlanNodeStatsEstimate:
        """Fused star chain: fold the unique-build containment rule
        over the spine, build by build — identical math to the cascade
        of binary joins it replaced, so collapsing cannot change the
        estimates the rest of the plan is costed on."""
        cur = self.stats(node.spine)
        rows, confident = cur.row_count, cur.confident
        symbols = dict(cur.symbols)
        sel = cur.selectivity
        for build, crit in zip(node.builds, node.criteria):
            b = self.stats(build)
            step = PlanNodeStatsEstimate(rows, symbols, confident, sel)
            rows, confident = self.equi_join_rows(
                step, b, crit, build_unique=True)
            symbols = {**symbols, **b.symbols}
            sel = sel * b.selectivity
        return PlanNodeStatsEstimate(max(rows, 1.0), symbols,
                                     confident, sel)

    def _s_semijoin(self, node: N.SemiJoin) -> PlanNodeStatsEstimate:
        src = self.stats(node.source)
        self.stats(node.filter_source)  # priced by the cost model
        symbols = dict(src.symbols)
        symbols[node.output] = SymbolStats(ndv=2.0)
        # the semi-join only ADDS the membership mark; the Filter above
        # consuming it is estimated by the filter rule
        return PlanNodeStatsEstimate(src.row_count, symbols,
                                     src.confident, src.selectivity)

    def _s_crossjoin(self, node: N.CrossJoin) -> PlanNodeStatsEstimate:
        left = self.stats(node.left)
        right = self.stats(node.right)
        rows = (left.row_count if node.scalar
                else left.row_count * right.row_count)
        return PlanNodeStatsEstimate(
            max(rows, 1.0), {**left.symbols, **right.symbols},
            left.confident and right.confident)
