"""PEP 249 (DB-API 2.0) driver over the coordinator HTTP protocol.

The reference ships a JDBC driver speaking the same nextUri-paged
statement protocol (client/trino-jdbc, client/trino-client); this is
the Python-ecosystem equivalent so tools written against DB-API
(SQLAlchemy dialects, pandas read_sql, plain scripts) can use the
engine without knowing its protocol.

    import presto_tpu.dbapi as dbapi
    conn = dbapi.connect(host="localhost", port=8080, user="alice")
    cur = conn.cursor()
    cur.execute("select * from tpch.nation where n_regionkey = ?", (1,))
    print(cur.description, cur.fetchall())

Parameters use qmark style with client-side literal substitution — the
same approach the reference JDBC driver takes for non-prepared
statements (PrestoPreparedStatement client-side templating).
"""

from __future__ import annotations

import datetime

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"


class Warning(Exception):  # noqa: A001 - name mandated by PEP 249
    pass


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class DataError(DatabaseError):
    pass


class IntegrityError(DatabaseError):
    pass


class InternalError(DatabaseError):
    pass


class ProgrammingError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


class NotSupportedError(DatabaseError):
    pass


def _quote(value) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, datetime.datetime):
        if value.tzinfo is not None:
            raise NotSupportedError(
                "timezone-aware datetimes are unsupported "
                "(no TIMESTAMP WITH TIME ZONE type)")
        return f"TIMESTAMP '{value:%Y-%m-%d %H:%M:%S.%f}'"
    if isinstance(value, datetime.date):
        return f"DATE '{value:%Y-%m-%d}'"
    if isinstance(value, datetime.time):
        return f"TIME '{value:%H:%M:%S.%f}'"
    if isinstance(value, str):
        return "'" + value.replace("'", "''") + "'"
    raise ProgrammingError(f"cannot bind parameter of type {type(value)}")


def _substitute(sql: str, params) -> str:
    """Replace ? placeholders, skipping string literals, double-quoted
    identifiers, and -- / block comments. Runs even with no
    parameters so a leftover ? fails client-side, not as an opaque
    server parse error."""
    out = []
    it = iter(params)
    i = 0
    n = len(sql)
    while i < n:
        ch = sql[i]
        if ch == "'" or ch == '"':
            quote = ch
            j = i + 1
            while j < n:
                if sql[j] == quote:
                    if quote == "'" and j + 1 < n and sql[j + 1] == "'":
                        j += 2  # '' escape
                        continue
                    break
                j += 1
            out.append(sql[i:j + 1])
            i = j + 1
        elif ch == "-" and i + 1 < n and sql[i + 1] == "-":
            j = sql.find("\n", i)
            j = n if j < 0 else j
            out.append(sql[i:j])
            i = j
        elif ch == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append(sql[i:j])
            i = j
        elif ch == "?":
            try:
                out.append(_quote(next(it)))
            except StopIteration:
                raise ProgrammingError("not enough parameters") from None
            i += 1
        else:
            out.append(ch)
            i += 1
    remaining = sum(1 for _ in it)
    if remaining:
        raise ProgrammingError(f"{remaining} unused parameters")
    return "".join(out)


def _parse_wire_timestamp(v: str) -> datetime.datetime:
    s = str(v).replace("T", " ")
    fmt = "%Y-%m-%d %H:%M:%S.%f" if "." in s else "%Y-%m-%d %H:%M:%S"
    return datetime.datetime.strptime(s, fmt)


def _parse_wire_time(v: str) -> datetime.time:
    fmt = "%H:%M:%S.%f" if "." in str(v) else "%H:%M:%S"
    return datetime.datetime.strptime(str(v), fmt).time()


_WIRE_CONVERTERS = {
    "date": lambda v: datetime.date.fromisoformat(str(v)),
    "timestamp": _parse_wire_timestamp,
    "time": _parse_wire_time,
}


class Cursor:
    arraysize = 1

    def __init__(self, conn: "Connection"):
        self._conn = conn
        self._rows: list[tuple] | None = None
        self._pos = 0
        self.description = None
        self.rowcount = -1

    # -- PEP 249 ------------------------------------------------------------

    def execute(self, operation: str, parameters=None) -> "Cursor":
        if self._conn._client is None:
            raise InterfaceError("cursor on a closed connection")
        from presto_tpu.client import QueryFailed

        sql = _substitute(operation, parameters or ())
        try:
            columns, rows = self._conn._client.execute(sql)
        except QueryFailed as e:
            raise DatabaseError(str(e)) from e
        except OSError as e:
            raise OperationalError(str(e)) from e
        self.description = [
            (c.get("name"), c.get("type"), None, None, None, None, None)
            for c in columns]
        convs = [_WIRE_CONVERTERS.get(str(c.get("type", "")).lower())
                 for c in columns]
        if any(convs):
            self._rows = [
                tuple(v if cv is None or v is None else cv(v)
                      for v, cv in zip(r, convs)) for r in rows]
        else:
            self._rows = [tuple(r) for r in rows]
        self._pos = 0
        self.rowcount = len(self._rows)
        return self

    def executemany(self, operation: str, seq_of_parameters) -> None:
        for p in seq_of_parameters:
            self.execute(operation, p)

    def fetchone(self):
        if self._rows is None:
            raise ProgrammingError("fetch before execute")
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: int | None = None):
        n = size if size is not None else self.arraysize
        out = []
        for _ in range(n):
            row = self.fetchone()
            if row is None:
                break
            out.append(row)
        return out

    def fetchall(self):
        if self._rows is None:
            raise ProgrammingError("fetch before execute")
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    def close(self) -> None:
        self._rows = None

    def setinputsizes(self, sizes) -> None:  # pragma: no cover - no-op
        pass

    def setoutputsize(self, size, column=None) -> None:  # pragma: no cover
        pass


class Connection:
    def __init__(self, host: str, port: int, user: str,
                 password: str | None = None, scheme: str = "http"):
        from presto_tpu.client import Client
        self._client = Client(f"{scheme}://{host}:{port}", user=user,
                              password=password)

    def cursor(self) -> Cursor:
        if self._client is None:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def close(self) -> None:
        self._client = None

    def commit(self) -> None:
        # autocommit protocol: every statement is its own transaction
        pass

    def rollback(self) -> None:
        raise NotSupportedError(
            "transactions are per-statement over the HTTP protocol; "
            "ROLLBACK is not supported here")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def connect(host: str = "localhost", port: int = 8080,
            user: str = "presto", password: str | None = None,
            scheme: str = "http") -> Connection:
    return Connection(host, port, user, password, scheme)
