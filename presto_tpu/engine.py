"""Engine: the in-process query runner.

Analog of the reference's LocalQueryRunner
(core/trino-main/src/main/java/io/trino/testing/LocalQueryRunner.java:227):
parse -> analyze -> logical plan -> optimize -> fragment -> compile jitted
kernels -> execute, all in one process. The distributed path executes
fragments under shard_map over a jax Mesh instead of HTTP remote tasks.
"""

from __future__ import annotations

from presto_tpu.block import Table
from presto_tpu.connectors.base import Connector
from presto_tpu.session import Session


class Engine:
    def __init__(self, session: Session | None = None):
        self.session = session or Session()
        self.catalogs: dict[str, Connector] = {}

    def register_catalog(self, name: str, connector: Connector) -> None:
        self.catalogs[name] = connector

    # -- SQL entry points ---------------------------------------------------

    def execute(self, sql: str) -> list[tuple]:
        """Run SQL, return result rows as Python tuples."""
        result = self.execute_table(sql)
        return result.to_pylist()

    def execute_table(self, sql: str) -> Table:
        from presto_tpu.exec.executor import execute_plan
        plan, _ = self.plan_sql(sql)
        return execute_plan(self, plan)

    def plan_sql(self, sql: str):
        from presto_tpu.sql.parser import parse_statement
        from presto_tpu.sql.analyzer import Analyzer
        from presto_tpu.plan.planner import LogicalPlanner
        from presto_tpu.plan.optimizer import optimize

        stmt = parse_statement(sql)
        analysis = Analyzer(self).analyze(stmt)
        plan = LogicalPlanner(self, analysis).plan(stmt)
        plan = optimize(plan, self)
        return plan, analysis

    def explain(self, sql: str) -> str:
        from presto_tpu.plan.printer import format_plan
        plan, _ = self.plan_sql(sql)
        return format_plan(plan)
