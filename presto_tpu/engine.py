"""Engine: the in-process query runner.

Analog of the reference's LocalQueryRunner
(core/trino-main/src/main/java/io/trino/testing/LocalQueryRunner.java:227):
parse -> analyze -> logical plan -> optimize -> compile jitted kernels ->
execute, all in one process. Statement dispatch mirrors the reference's
split between data queries (SqlQueryExecution) and DDL/session statements
(execution/*Task.java executors, sql/rewrite/ShowQueriesRewrite.java).
The distributed path executes plans under shard_map over a jax Mesh
instead of HTTP remote tasks.
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Table, _decode_column
from presto_tpu.connectors.base import Connector
from presto_tpu.obs.trace import TRACER
from presto_tpu.session import SYSTEM_SESSION_PROPERTIES, Session


class Engine:
    def __init__(self, session: Session | None = None):
        from presto_tpu.connectors.information_schema import (
            InformationSchemaConnector, SystemConnector)
        from presto_tpu.events import EventListenerManager

        self.session = session or Session()
        self.catalogs: dict[str, Connector] = {}
        # compiled-program cache: size-bounded LRU fronting an optional
        # persistent AOT disk store (exec/progcache.py; reference
        # analog: gen/PageFunctionCompiler.java:101 compiled-artifact
        # caches). Per-plan successful capacity vectors ride alongside.
        from presto_tpu.exec.progcache import ProgramCache
        self._program_cache = ProgramCache(
            max_entries=int(self.session.get("program_cache_entries")
                            or 64))
        self._caps_memory: dict = {}
        # plan templates: per-(template, segment) carrier-width memory
        # (grow-only; exec/executor._segment_carriers) so literal
        # variants keep stable downstream segment shapes
        self._carrier_caps: dict = {}
        # host->device transfer cache: id(np array) -> (host ref, dev
        # array). The strong host ref pins the id; repeat executions of
        # a query (and bench steady state) reuse HBM-resident inputs
        # instead of re-uploading every run (the reference keeps pages
        # pooled in worker memory the same way)
        self._dev_cache: dict = {}
        self._dev_cache_bytes = 0
        self.dev_cache_limit = 8 << 30  # HBM budget for pinned inputs
        # parallel segment compilation uploads scan arrays from pool
        # threads concurrently; the pin cache + byte ledger + eviction
        # loop must not interleave (two threads popping the same
        # oldest key is a KeyError)
        import threading as _t
        self._dev_cache_lock = _t.Lock()
        # runtime memory ledger: per-program tagged reservations of
        # actual input+output array bytes (memory/MemoryPool.java:44);
        # capacity 0 = unbounded (set memory_pool.capacity to enforce)
        from presto_tpu.memory import MemoryPool
        self.memory_pool = MemoryPool()
        # table-level authorization consulted by the planner at scans
        # and by DML (security/AccessControlManager.java analog)
        from presto_tpu.security import AllowAllAccessControl
        self.access_control = AllowAllAccessControl()
        # session-scoped transactions (transaction.py; reference
        # transaction/InMemoryTransactionManager)
        from presto_tpu.transaction import TransactionManager
        self.transactions = TransactionManager()
        # populated by the spill driver when a query exceeds the memory
        # budget and runs host-partitioned (exec/spill.py)
        self.last_spill: dict | None = None
        # per-THREAD warning handoff: concurrent queries on one engine
        # (the server's worker pool) must not read each other's
        # diagnostics
        import threading as _threading
        self._warn_tl = _threading.local()
        # per-THREAD one-shot plan handoff (offer_preplanned /
        # take_preplanned): the HTTP admission layer plans a query to
        # size its memory reservation; the execution path on the same
        # thread reuses that plan instead of planning twice
        self._preplanned_tl = _threading.local()
        # data-change listeners: the serving layer's result cache
        # registers here so DML actively purges entries built on the
        # pre-write table versions (connector SPI table_version keys
        # make stale hits impossible even without the purge; the
        # listener keeps the cache small and the invalidation counter
        # honest)
        self._invalidation_listeners: list = []
        # query lifecycle events + history (events.py)
        self.events = EventListenerManager()
        # persisted query history + divergence-ledger persistence
        # (obs/qstats.py): finished-query profiles append to a bounded
        # JSONL under PRESTO_TPU_HISTORY_DIR and survive restarts,
        # backing system.query_history
        import os as _os
        self.history = None
        hist_dir = _os.environ.get("PRESTO_TPU_HISTORY_DIR")
        if hist_dir:
            from presto_tpu.obs.qstats import DIVERGENCE, QueryHistory
            try:
                self.history = QueryHistory(hist_dir)
                self.events.add_listener(self.history.on_event)
                DIVERGENCE.attach_dir(hist_dir)
            except OSError:
                self.history = None  # unwritable dir: run without
        # engine-owned virtual catalogs (reference information_schema +
        # system connectors are engine-side, not plugins)
        self.catalogs["information_schema"] = \
            InformationSchemaConnector(self)
        self.catalogs["system"] = SystemConnector(self)

    def register_catalog(self, name: str, connector: Connector) -> None:
        self.catalogs[name] = connector

    def add_invalidation_listener(self, fn) -> None:
        """``fn()`` runs after every statement that may change table
        data (the same set that invalidates the device cache)."""
        self._invalidation_listeners.append(fn)

    @property
    def last_warnings(self) -> list:
        """Warnings of the CALLING THREAD's most recent query."""
        return getattr(self._warn_tl, "value", [])

    def device_array(self, a):
        """Device copy of a host scan array, cached so repeat
        executions reuse HBM-resident inputs instead of re-uploading
        (the reference keeps pages pooled in worker memory). The
        strong host ref pins the id key; FIFO eviction bounds HBM.
        Thread-safe: parallel segment compilation uploads from pool
        threads concurrently. The transfer itself runs OUTSIDE the
        lock so one wave's uploads overlap (a lost race uploads a
        duplicate once and keeps the first copy — benign)."""
        import jax
        if not isinstance(a, np.ndarray):
            return a  # already a device array (segment carriers)
        with self._dev_cache_lock:
            hit = self._dev_cache.get(id(a))
            if hit is not None and hit[0] is a:
                return hit[1]
        dev = jax.device_put(a)
        with self._dev_cache_lock:
            hit = self._dev_cache.get(id(a))
            if hit is not None and hit[0] is a:
                return hit[1]  # raced: keep the published copy
            self._dev_cache[id(a)] = (a, dev)
            self._dev_cache_bytes += a.nbytes
            while (self._dev_cache_bytes > self.dev_cache_limit
                   and len(self._dev_cache) > 1):
                k = next(iter(self._dev_cache))
                old, _old_dev = self._dev_cache.pop(k)
                self._dev_cache_bytes -= old.nbytes
            return dev

    # -- SQL entry points ---------------------------------------------------

    def execute(self, sql: str, mesh=None, cancel_token=None
                ) -> list[tuple]:
        """Run SQL, return result rows as Python tuples. With ``mesh``
        (a jax.sharding.Mesh) query plans execute data-parallel over
        every device — scans row-sharded, exchanges as ICI collectives.
        ``cancel_token`` (exec/cancel.CancelToken) interrupts execution
        at host-side checkpoints."""
        from presto_tpu.sql import ast as A
        from presto_tpu.sql.parser import parse_statement

        from presto_tpu.events import monitored

        from presto_tpu.sql.rewrite import rewrite_statement

        from presto_tpu import warnings as W

        W.push(WC := W.WarningCollector())
        try:
            stmt = rewrite_statement(parse_statement(sql), self)
            if isinstance(stmt, A.ExecutePrepared):
                # EXECUTE name USING ...: splice the literals into the
                # stored text and run the result through the normal
                # pipeline — the plan-template machinery keys every
                # variant onto one compiled program (templates/)
                sql = self._resolve_prepared(stmt)
                stmt = rewrite_statement(parse_statement(sql), self)
            with self._cancel_scope(cancel_token):
                if isinstance(stmt, A.QueryStatement):
                    return monitored(
                        self, sql,
                        lambda: self._execute_query(stmt.query,
                                                    mesh).to_pylist())
                return monitored(
                    self, sql,
                    lambda: self._execute_statement(stmt, mesh))
        finally:
            self._warn_tl.value = WC.list()
            W.pop()

    def execute_table(self, sql: str, mesh=None, cancel_token=None
                      ) -> Table:
        from presto_tpu.events import monitored
        from presto_tpu.sql import ast as A
        from presto_tpu.sql.parser import parse_statement

        from presto_tpu.sql.rewrite import rewrite_statement

        from presto_tpu import warnings as W

        W.push(WC := W.WarningCollector())
        try:
            stmt = rewrite_statement(parse_statement(sql), self)
            if isinstance(stmt, A.ExecutePrepared):
                sql = self._resolve_prepared(stmt)
                stmt = rewrite_statement(parse_statement(sql), self)
            if not isinstance(stmt, A.QueryStatement):
                raise ValueError("execute_table expects a SELECT query")
            preplanned = self.take_preplanned(sql)
            with self._cancel_scope(cancel_token):
                return monitored(
                    self, sql,
                    lambda: self._execute_query(stmt.query, mesh,
                                                preplanned=preplanned))
        finally:
            self._warn_tl.value = WC.list()
            W.pop()

    def _cancel_scope(self, token):
        """Install the cancellation token (plus the session's
        query_max_run_time deadline) for the duration of one query."""
        import contextlib
        import time as _time

        from presto_tpu.exec import cancel as C

        limit = float(self.session.get("query_max_run_time") or 0)
        if token is None and limit > 0:
            token = C.CancelToken()
        if token is not None and limit > 0 and token.deadline is None:
            token.deadline = _time.monotonic() + limit

        @contextlib.contextmanager
        def scope():
            C.install(token)
            try:
                yield
            finally:
                C.install(None)

        return scope()

    def plan_sql(self, sql: str, enable_latemat: bool | None = None):
        from presto_tpu.sql.parser import parse_statement
        from presto_tpu.sql.analyzer import Analyzer
        from presto_tpu.plan.planner import LogicalPlanner
        from presto_tpu.plan.optimizer import optimize

        import time as _time

        t0 = _time.monotonic()
        with TRACER.span("plan"):
            stmt = parse_statement(sql)
            analysis = Analyzer(self).analyze(stmt)
            self._planning_checkpoint(t0)
            plan = LogicalPlanner(self, analysis).plan(stmt)
            self._planning_checkpoint(t0)
            plan = optimize(plan, self, enable_latemat=enable_latemat)
            self._planning_checkpoint(t0)
        return plan, analysis

    def offer_preplanned(self, sql: str, plan) -> None:
        """Hand a just-built plan for ``sql`` to THIS THREAD's next
        execution of the same statement (the admission layer plans to
        size its reservation; replanning identical SQL under the same
        session on the same thread would double the planning cost).
        One-shot: consumed by the next take_preplanned, and cleared by
        clear_preplanned when the offering scope exits."""
        self._preplanned_tl.value = (sql, plan)

    def take_preplanned(self, sql: str):
        """Consume the thread's offered plan if it matches ``sql``."""
        offered = getattr(self._preplanned_tl, "value", None)
        self._preplanned_tl.value = None
        if offered is not None and offered[0] == sql:
            return offered[1]
        return None

    def clear_preplanned(self) -> None:
        self._preplanned_tl.value = None

    def _resolve_prepared(self, stmt) -> str:
        """Executable SQL of an EXECUTE against this session's
        prepared-statement registry."""
        from presto_tpu.templates.prepared import resolve_execute
        return resolve_execute(self.session.prepared_statements, stmt)

    def _planning_checkpoint(self, t0: float) -> None:
        """Planning-phase seam: observe cancellation (a reaped or
        killed query stops planning) and enforce the session's
        ``query_max_planning_time`` (reference QueryTracker
        enforceTimeLimits on queries stuck in planning)."""
        import time as _time

        from presto_tpu.exec import cancel as C

        C.checkpoint()
        limit = float(self.session.get("query_max_planning_time") or 0)
        if limit and _time.monotonic() - t0 > limit:
            raise C.TimeLimitExceeded(
                f"query exceeded query_max_planning_time "
                f"({limit:g}s)")

    def explain(self, sql: str) -> str:
        from presto_tpu.cost import explain_estimates
        from presto_tpu.plan.printer import format_plan
        plan, _ = self.plan_sql(sql)
        return format_plan(plan,
                           estimates=explain_estimates(plan, self))

    # -- internals ----------------------------------------------------------

    def _plan_query(self, query, preplanned=None):
        from presto_tpu.plan.optimizer import optimize
        from presto_tpu.plan.planner import LogicalPlanner
        from presto_tpu.sql import ast as A

        from presto_tpu.plan.sanity import validate_plan

        import time as _time

        if preplanned is not None:
            # admission already planned this exact SQL on this thread
            # (plan_sql, same session scope); only the pre-execution
            # invariant validation remains
            validate_plan(preplanned)
            return preplanned
        t0 = _time.monotonic()
        with TRACER.span("plan"):
            planner = LogicalPlanner(self, None)
            plan = planner.plan(A.QueryStatement(query))
            self._planning_checkpoint(t0)
            plan = optimize(plan, self)
            self._planning_checkpoint(t0)
            # invariant validation before execution (reference
            # PlanSanityChecker runs after every optimizer stage)
            validate_plan(plan)
        return plan

    def _execute_query(self, query, mesh=None, preplanned=None) -> Table:
        self.last_spill = None
        plan = self._plan_query(query, preplanned=preplanned)
        if mesh is not None:
            from presto_tpu.parallel.executor import (
                execute_plan_distributed)
            return execute_plan_distributed(self, plan, mesh)
        from presto_tpu.exec.executor import execute_plan
        return execute_plan(self, plan)

    def _execute_statement(self, stmt, mesh=None) -> list[tuple]:
        from presto_tpu.sql import ast as A
        try:
            return self._execute_statement_inner(stmt, mesh)
        finally:
            # DML may mutate connector arrays IN PLACE (same object
            # identity), so pinned device copies must not survive it;
            # commit/rollback restore snapshots the same way
            if isinstance(stmt, (A.CreateTableAs, A.InsertStatement,
                                 A.DeleteStatement, A.UpdateStatement,
                                 A.DropTable, A.CommitStatement,
                                 A.RollbackStatement)):
                self.invalidate_device_cache()
                for fn in list(self._invalidation_listeners):
                    fn()

    def invalidate_device_cache(self) -> None:
        with self._dev_cache_lock:
            self._dev_cache.clear()
            self._dev_cache_bytes = 0
        # the template pad cache is id-keyed the same way and must not
        # serve pre-DML padded copies of in-place-mutated arrays
        from presto_tpu.templates.shapes import invalidate_pad_cache
        invalidate_pad_cache(self)

    def _execute_statement_inner(self, stmt, mesh=None) -> list[tuple]:
        from presto_tpu.plan.printer import format_plan
        from presto_tpu.sql import ast as A

        if isinstance(stmt, A.ExplainStatement):
            if stmt.analyze:
                from presto_tpu.exec.profile import (
                    explain_analyze, explain_analyze_distributed)
                inner = stmt.statement
                if not isinstance(inner, A.QueryStatement):
                    raise ValueError("EXPLAIN ANALYZE expects a query")
                plan = self._plan_query(inner.query)
                if mesh is not None:
                    return [(explain_analyze_distributed(
                        self, plan, mesh),)]
                return [(explain_analyze(self, plan),)]
            inner = stmt.statement
            if isinstance(inner, A.QueryStatement):
                from presto_tpu.cost import explain_estimates
                plan = self._plan_query(inner.query)
                return [(format_plan(
                    plan, estimates=explain_estimates(plan, self)),)]
            raise ValueError("EXPLAIN of non-query statements unsupported")

        if isinstance(stmt, A.StartTransaction):
            self.transactions.begin()
            return []
        if isinstance(stmt, A.CommitStatement):
            self.transactions.commit()
            return []
        if isinstance(stmt, A.RollbackStatement):
            self.transactions.rollback()
            return []

        if isinstance(stmt, A.ShowCatalogs):
            return [(name,) for name in sorted(self.catalogs)]

        if isinstance(stmt, A.ShowSession):
            rows = []
            for name, (default, typ, desc) in sorted(
                    SYSTEM_SESSION_PROPERTIES.items()):
                rows.append((name, str(self.session.get(name)),
                             str(default), typ.__name__, desc))
            return rows

        if isinstance(stmt, A.SetSession):
            value = _literal_value(stmt.value)
            self.session.set(stmt.name, value)
            return []

        if isinstance(stmt, A.Prepare):
            self.session.prepared_statements[stmt.name] = stmt.sql
            return []

        if isinstance(stmt, A.Deallocate):
            if self.session.prepared_statements.pop(stmt.name,
                                                    None) is None:
                raise ValueError(
                    f"prepared statement not found: {stmt.name}")
            return []

        if isinstance(stmt, A.CreateTableAs):
            catalog, table = self._resolve_table(stmt.table)
            self.access_control.check_can_write(
                self.session.user, catalog, table)
            conn = self._connector(catalog)
            self.transactions.touch(conn)
            result = self._execute_query(stmt.query, mesh)
            schema, data, valid = _table_to_host(result, self)
            sink = conn.begin_write(table, schema)
            n = _stream_to_sink(sink, data, valid)
            return [(n,)]

        if isinstance(stmt, A.InsertStatement):
            catalog, table = self._resolve_table(stmt.table)
            self.access_control.check_can_write(
                self.session.user, catalog, table)
            conn = self._connector(catalog)
            self.transactions.touch(conn)
            result = self._execute_query(stmt.query, mesh)
            schema, data, valid = _table_to_host(result, self)
            target = conn.table_schema(table)
            names = stmt.columns or list(target)
            renamed = {t: d for t, d in zip(names, data.values())}
            revalid = {t: v for t, v in zip(names, valid.values())}
            sink = conn.begin_write(table, None)
            n = _stream_to_sink(sink, renamed, revalid)
            return [(n,)]

        if isinstance(stmt, A.DeleteStatement):
            # evaluate the predicate per row in table order and hand the
            # connector a delete mask (reference DeleteOperator +
            # ConnectorPageSink rowId delete, trimmed to the host-table
            # connectors this engine mutates in place)
            catalog, table = self._resolve_table(stmt.table)
            self.access_control.check_can_write(
                self.session.user, catalog, table)
            conn = self._connector(catalog)
            self.transactions.touch(conn)
            mask = self._row_mask(stmt.table, stmt.where, mesh)
            return [(conn.delete_rows(table, mask),)]

        if isinstance(stmt, A.UpdateStatement):
            import numpy as np

            catalog, table = self._resolve_table(stmt.table)
            self.access_control.check_can_write(
                self.session.user, catalog, table)
            conn = self._connector(catalog)
            self.transactions.touch(conn)
            target = conn.table_schema(table)
            # one scan computes the new values AND the WHERE mask, so
            # both come from the same row order
            items = []
            for col, expr in stmt.assignments:
                if col not in target:
                    raise ValueError(f"unknown column {col}")
                items.append(A.SelectItem(
                    A.CastExpression(expr, str(target[col])), col))
            pred = (A.BooleanLiteral(True) if stmt.where is None
                    else A.FunctionCall(
                        "coalesce", (stmt.where, A.BooleanLiteral(False))))
            items.append(A.SelectItem(pred, "__pred__"))
            q = A.Query(A.QuerySpec(tuple(items), False,
                                    A.TableRef(stmt.table)))
            result = self._execute_query(q, mesh)
            _, data, valid = _table_to_host(result, self)
            mask = np.asarray(data["__pred__"], dtype=bool)
            values = {col: data[col] for col, _ in stmt.assignments}
            valids = {col: valid[col] for col, _ in stmt.assignments}
            return [(conn.update_rows(table, values, valids, mask),)]

        if isinstance(stmt, A.DropTable):
            catalog, table = self._resolve_table(stmt.table)
            self.access_control.check_can_write(
                self.session.user, catalog, table)
            conn = self._connector(catalog)
            if table not in conn.table_names():
                if stmt.if_exists:
                    return []
                raise ValueError(f"table {table} does not exist")
            self.transactions.touch(conn)
            conn.drop_table(table)
            return []

        raise NotImplementedError(
            f"statement {type(stmt).__name__} not supported")

    def _row_mask(self, table_parts, where, mesh):
        """bool[n] in table row order: WHERE evaluates TRUE (NULL and
        FALSE rows are untouched, SQL DELETE/UPDATE semantics); None
        means every row."""
        import numpy as np

        from presto_tpu.sql import ast as A

        if where is None:
            return None
        pred = A.FunctionCall(
            "coalesce", (where, A.BooleanLiteral(False)))
        q = A.Query(A.QuerySpec(
            (A.SelectItem(pred, "__pred__"),), False,
            A.TableRef(table_parts)))
        result = self._execute_query(q, mesh)
        col = next(iter(result.columns.values()))
        data = np.asarray(col.data, dtype=bool)
        if result.mask is not None:
            # padded execution paths (distributed shards) interleave
            # dead slots; compact to the real table rows
            data = data[np.asarray(result.mask)]
        return data

    def _connector(self, catalog: str) -> Connector:
        conn = self.catalogs.get(catalog)
        if conn is None:
            raise ValueError(f"catalog '{catalog}' does not exist")
        return conn

    def _resolve_table(self, parts: tuple[str, ...]) -> tuple[str, str]:
        if len(parts) == 1:
            return self.session.catalog, parts[0]
        return parts[0], parts[-1]


def _literal_value(e):
    from presto_tpu.sql import ast as A

    if isinstance(e, A.StringLiteral):
        return e.value
    if isinstance(e, A.NumericLiteral):
        return float(e.text) if "." in e.text else int(e.text)
    if isinstance(e, A.BooleanLiteral):
        return e.value
    if isinstance(e, A.Identifier):
        return e.name
    raise ValueError("SET SESSION value must be a literal")


# one writer task per this many result cells (rows x columns); the task
# count grows with produced data up to the pool bound — the scaled-
# writers policy (reference ScaledWriterScheduler.java +
# SCALED_WRITER_DISTRIBUTION), applied to this engine's write-side
# bottleneck: device->host materialization and decode of result columns
WRITER_SCALING_CELLS = 1 << 20
WRITER_MAX_TASKS = 8


def _table_to_host(table: Table, engine=None):
    """Result Table -> (schema, host column arrays, validity masks) for
    connector writes. VARCHAR decodes to strings; other types keep their
    physical values (decimals stay scaled, matching column_from_numpy's
    contract). Large results convert with a scaled pool of writer
    tasks (one per column batch)."""
    schema: dict[str, T.DataType] = {}
    data: dict[str, np.ndarray] = {}
    valid: dict[str, np.ndarray | None] = {}
    mask = (np.ones(table.nrows, dtype=bool) if table.mask is None
            else np.asarray(table.mask))

    def convert(item):
        name, col = item
        raw = np.asarray(col.data)[mask]
        if isinstance(col.dtype, T.VarcharType):
            out = _decode_column(col.dtype, raw, col.dictionary)
        else:
            out = raw
        v = None if col.valid is None else np.asarray(col.valid)[mask]
        return name, col.dtype, out, v

    cells = table.nrows * max(len(table.columns), 1)
    writers = min(WRITER_MAX_TASKS,
                  max(1, cells // WRITER_SCALING_CELLS))
    items = list(table.columns.items())
    if writers > 1 and len(items) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=writers) as pool:
            # context-free by design: convert() is pure host-side
            # numpy decode — no spans, checkpoints, stats, or session
            # reads happen on the writer threads
            results = list(pool.map(convert, items))  # lint: disable=handoff
    else:
        writers = 1
        results = [convert(i) for i in items]
    if engine is not None:
        engine.last_write = {"writer_tasks": writers,
                             "rows": int(mask.sum())}
    for name, dtype, out, v in results:
        schema[name] = dtype
        data[name] = out
        valid[name] = v
    return schema, data, valid


# rows per page through a connector write sink (the scaled-writer
# analog of the reference's page-at-a-time ConnectorPageSink feed)
WRITE_PAGE_ROWS = 1 << 20


def _stream_to_sink(sink, data: dict, valid: dict) -> int:
    """Feed query output to a PageSink page-by-page, committing on
    finish (reference TableWriterOperator + ConnectorPageSink.java:22
    appendPage/finish). Aborts the sink on failure so connectors never
    see partial commits. The default buffering sink would only
    re-concatenate the pages, so it receives the whole arrays in one
    page (no redundant copy); native sinks get real pages."""
    from presto_tpu.connectors.base import _BufferingPageSink

    total = len(next(iter(data.values()), []))
    if isinstance(sink, _BufferingPageSink):
        try:
            sink.append_page(data, valid)
            return sink.finish()
        except Exception:
            sink.abort()
            raise
    try:
        start = 0
        while start < total or (start == 0 and total == 0):
            stop = min(start + WRITE_PAGE_ROWS, total)
            page = {c: a[start:stop] for c, a in data.items()}
            pvalid = {c: (None if v is None else v[start:stop])
                      for c, v in valid.items()}
            sink.append_page(page, pvalid)
            if total == 0:
                break
            start = stop
        return sink.finish()
    except Exception:
        sink.abort()
        raise
