"""Query lifecycle events + pluggable listeners.

Analog of the reference's event system (event/QueryMonitor.java:134,210
queryCreatedEvent/queryCompletedEvent -> EventListenerManager -> SPI
spi/eventlistener/EventListener.java): the engine emits a created event
when a query is admitted and a completed event with statistics when it
finishes; listeners are plain callables registered on the engine.
Recent completed events also back the system.runtime.queries table.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str
    create_time: float


@dataclasses.dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    user: str
    state: str           # FINISHED | FAILED
    create_time: float
    end_time: float
    output_rows: int
    error: str | None = None

    @property
    def elapsed_ms(self) -> float:
        return (self.end_time - self.create_time) * 1000.0


class EventListenerManager:
    """Dispatches lifecycle events to registered listeners and keeps a
    bounded history for system.runtime.queries (reference
    EventListenerManager + QuerySystemTable)."""

    def __init__(self, history: int = 1000):
        self._listeners: list[Callable] = []
        self.history: deque = deque(maxlen=history)
        self._seq = 0

    def add_listener(self, fn: Callable) -> None:
        self._listeners.append(fn)

    def next_query_id(self) -> str:
        self._seq += 1
        return f"q_{self._seq:08d}"

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._emit(event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        self.history.append(event)
        self._emit(event)

    def _emit(self, event) -> None:
        for fn in self._listeners:
            try:
                fn(event)
            except Exception:
                # a broken listener must not fail the query (reference
                # EventListenerManager swallows listener errors too)
                pass


def monitored(engine, sql: str, run: Callable):
    """Run ``run()`` under query monitoring: emits created/completed
    events and records history. Returns run()'s result."""
    mgr: EventListenerManager = engine.events
    qid = mgr.next_query_id()
    t0 = time.time()
    mgr.query_created(QueryCreatedEvent(qid, sql, engine.session.user, t0))
    try:
        result = run()
    except Exception as exc:
        mgr.query_completed(QueryCompletedEvent(
            qid, sql, engine.session.user, "FAILED", t0, time.time(),
            0, error=f"{type(exc).__name__}: {exc}"))
        raise
    if isinstance(result, list):
        rows = len(result)
    else:
        mask = getattr(result, "mask", None)
        if mask is not None:
            import numpy as np
            rows = int(np.asarray(mask).sum())
        else:
            rows = getattr(result, "nrows", 0)
    mgr.query_completed(QueryCompletedEvent(
        qid, sql, engine.session.user, "FINISHED", t0, time.time(), rows))
    return result
