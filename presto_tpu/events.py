"""Query lifecycle events + pluggable listeners.

Analog of the reference's event system (event/QueryMonitor.java:134,210
queryCreatedEvent/queryCompletedEvent -> EventListenerManager -> SPI
spi/eventlistener/EventListener.java): the engine emits a created event
when a query is admitted and a completed event with statistics when it
finishes; listeners are plain callables registered on the engine.
Recent completed events also back the system.runtime.queries table.

``monitored()`` is also the engine-level tracing entry: it opens the
query's root span (obs/trace.py) when no trace is active, so CLI /
dbapi / direct-Engine queries are traced exactly like HTTP-admitted
ones (whose root the coordinator server opens under the HTTP query id).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable

import numpy as np

from presto_tpu.obs.jsonlog import LOG
from presto_tpu.obs.trace import TRACER


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str
    create_time: float


@dataclasses.dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    user: str
    state: str           # FINISHED | FAILED
    create_time: float
    end_time: float
    output_rows: int
    error: str | None = None

    @property
    def elapsed_ms(self) -> float:
        return (self.end_time - self.create_time) * 1000.0


class EventListenerManager:
    """Dispatches lifecycle events to registered listeners and keeps a
    bounded history for system.runtime.queries (reference
    EventListenerManager + QuerySystemTable). Thread-safe: the HTTP
    server runs queries on a pool, so id allocation, listener
    registration, and the history ring are all lock-guarded."""

    def __init__(self, history: int = 1000):
        self._lock = threading.Lock()
        self._listeners: list[Callable] = []
        self._history: deque = deque(maxlen=history)
        self._seq = 0

    def add_listener(self, fn: Callable) -> None:
        with self._lock:
            self._listeners.append(fn)

    def next_query_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"q_{self._seq:08d}"

    @property
    def history(self) -> list:
        """Snapshot of recent completed events (system.runtime.queries
        reads this while pool threads append)."""
        with self._lock:
            return list(self._history)

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._emit(event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        with self._lock:
            self._history.append(event)
        LOG.log("query_completed", query_id=event.query_id,
                user=event.user, state=event.state,
                elapsed_ms=round(event.elapsed_ms, 3),
                rows=event.output_rows, error=event.error)
        self._emit(event)

    def _emit(self, event) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(event)
            except Exception:
                # a broken listener must not fail the query (reference
                # EventListenerManager swallows listener errors too)
                pass


def monitored(engine, sql: str, run: Callable):
    """Run ``run()`` under query monitoring: emits created/completed
    events, records history, opens the query's root span (child span
    when a trace — e.g. the HTTP server's — is already active), and
    opens the query's runtime-stats scope (obs/qstats.py; reused when
    the HTTP layer already opened one under the protocol query id, so
    the stats id and the trace id coincide). The completed event fires
    INSIDE the stats scope: history listeners snapshot the finished
    tree off the ambient recorder. Returns run()'s result."""
    from presto_tpu.obs import devprof
    from presto_tpu.obs import qstats as QS

    mgr: EventListenerManager = engine.events
    qid = mgr.next_query_id()
    t0 = time.time()
    want_profile = False
    try:
        want_profile = bool(engine.session.get("device_profile"))
    except Exception:  # noqa: BLE001 - sessions without the property
        pass
    mgr.query_created(QueryCreatedEvent(qid, sql, engine.session.user, t0))
    with QS.query_or_current(qid, sql, engine.session.user) as qr, \
            TRACER.root_or_span(qid, "query", query_id=qid,
                                user=engine.session.user,
                                sql=sql[:200]) as sp, \
            devprof.maybe_capture(want_profile, qid) as prof_dir:
        if prof_dir is not None:
            # known up front: history/UI snapshots taken mid-query
            # already link the artifact directory
            qr.profile_artifact = prof_dir
        try:
            result = run()
        except Exception as exc:
            if sp is not None:
                sp.attrs["error"] = f"{type(exc).__name__}: {exc}"
            qr.state = "FAILED"
            qr.error = f"{type(exc).__name__}: {exc}"[:300]
            mgr.query_completed(QueryCompletedEvent(
                qid, sql, engine.session.user, "FAILED", t0, time.time(),
                0, error=f"{type(exc).__name__}: {exc}"))
            raise
        if isinstance(result, list):
            rows = len(result)
        else:
            mask = getattr(result, "mask", None)
            if mask is not None:
                rows = int(np.asarray(mask).sum())
            else:
                rows = getattr(result, "nrows", 0)
        qr.output_rows = rows
        mgr.query_completed(QueryCompletedEvent(
            qid, sql, engine.session.user, "FINISHED", t0, time.time(),
            rows))
    return result
