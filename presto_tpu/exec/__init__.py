"""Execution: lowering logical plans to jitted XLA programs.

The analog of the reference's LocalExecutionPlanner + Driver/Operator
runtime (sql/planner/LocalExecutionPlanner.java, operator/Driver.java:63) —
but where the reference pulls Pages through a pipeline of Java operators on
worker threads, here the whole fragment traces into ONE jit so XLA fuses
scan+filter+project+aggregate into fused HBM-resident kernels.
"""
