"""Cross-query batched execution: one vmapped device dispatch for many
parameter vectors of one plan template.

Serve-mode traffic is dominated by literal variants of a few query
shapes; the template subsystem (templates/) already proves those
variants share ONE traced program whose literals are trailing device
arguments. This module converts that compile-time sharing into a
serving-throughput win: K concurrent queries on the same template
fingerprint stack their bound parameter vectors along a new leading
axis and run ``jax.vmap(traced_fn)`` over it — the scan arrays are
broadcast (in_axes=None, uploaded once), only the parameter axis maps,
and the device executes one program for all K queries (the
vmap-over-row-blocks framing from the original design notes, applied
to the parameter axis). Per-query result slices demux into ordinary
host Tables byte-identical to serial execution.

The batched executable is a DIFFERENT XLA program from the serial one,
so it gets its own program-cache lineage: the canonical base key grows
a ``("batch", K)`` component, with the same capacity-retry ladder on
top (a hash-table overflow in ANY lane grows that table for the whole
batch — the ok flags come back as one (K, k) array and reduce over the
lane axis into the shared grow_overflowed ladder).

Batch widths are BUCKETED to powers of two: a group of 3 pads its
parameter stacks to width 4 by repeating the last member's bindings,
and only the first 3 lanes demux. Without padding every distinct group
size would lower+compile its own vmapped XLA program (serve-mode group
sizes jitter with arrival timing — an open-ended compile treadmill);
with it the program count is log2-bounded per template and the steady
state is pure cache hits. The padded lanes recompute a duplicate
query's answer — wasted FLOPs bounded by <2x, never wrong results.

Eligibility (:func:`batchable`) is deliberately narrow: the plain
single-program execute path only. Plans that would stream, spill, run
grouped, segment, carry MATCH_RECOGNIZE, or aggregate varlen arrays
fall back to serial execution — correctness first, the serving layer
batches the traffic that dominates repeats anyway.
"""

from __future__ import annotations

import time

import jax
import numpy as np

from presto_tpu.block import Column, Table
from presto_tpu.exec import hostsync as HS
from presto_tpu.obs.metrics import REGISTRY
from presto_tpu.obs.trace import TRACER
from presto_tpu.plan import nodes as N
from presto_tpu import types as T

_BATCHED = REGISTRY.counter(
    "presto_tpu_batched_queries_total",
    "queries executed through a cross-query vmapped batch dispatch")
_BATCH_SIZE = REGISTRY.histogram(
    "presto_tpu_batch_size_queries",
    "queries per cross-query batched device dispatch",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0))

# the batch retry ladder mirrors prepare_plan's: 6 attempts with
# RETRY_GROWTH overshoot bounds recompiles at ~1 in practice
_MAX_ATTEMPTS = 6


def batchable(engine, plan: N.PlanNode) -> bool:
    """Can ``plan`` take the plain single-program execute path? Only
    then may the serving layer batch it (the gates mirror
    exec.executor.execute_plan's dispatch chain, checked cheaply —
    any estimate-driven doubt answers False and serial execution
    keeps its own gating)."""
    from presto_tpu.exec.executor import (_find_match_recognize,
                                          _find_split)
    from presto_tpu.exec.varlen import find_varlen_aggregate
    sess = engine.session
    if _find_match_recognize(plan) is not None:
        return False
    if find_varlen_aggregate(plan) is not None:
        return False
    if bool(sess.get("grouped_execution")):
        return False
    if int(sess.get("query_max_memory_bytes") or 0):
        return False  # could spill: the budget path owns it
    if _find_split(plan, engine) is not None:
        return False  # segmented pipeline: no single program to vmap
    block = int(sess.get("scan_block_rows") or 0)
    if block > 0 and _largest_scan_estimate(engine, plan) > block:
        return False  # could block-stream: serial path decides
    return True


def _largest_scan_estimate(engine, plan: N.PlanNode) -> int:
    if isinstance(plan, N.TableScan):
        conn = engine.catalogs.get(plan.catalog)
        if conn is None:
            return 0
        try:
            return int(conn.row_count_estimate(plan.table))
        except Exception:  # noqa: BLE001 - unknown estimate = 0
            return 0
    return max((_largest_scan_estimate(engine, s)
                for s in plan.sources()), default=0)


def run_plan_batched(engine, templates: list) -> list[Table]:
    """Execute K literal variants of one plan template as a single
    vmapped device dispatch; returns one host Table per variant, in
    input order. ``templates`` are templates/analysis.Template objects
    sharing one fingerprint (same parameterized plan, each carrying
    its own parameter values); all must hoist at least one parameter.

    Raises on any failure — the serving layer falls back to executing
    each member serially, so a batch-path defect degrades throughput,
    never correctness."""
    import uuid

    from presto_tpu import templates as TPL
    from presto_tpu.exec import progcache as PC
    from presto_tpu.exec.cancel import checkpoint
    from presto_tpu.exec.executor import (RETRY_GROWTH, _cache_key,
                                          _pool_wait, collect_scans,
                                          make_traced)

    k = len(templates)
    plan = templates[0].plan
    n_params = len(templates[0].params)
    if k < 2 or n_params == 0:
        raise ValueError("batch needs >= 2 queries and >= 1 parameter")
    pool = getattr(engine, "memory_pool", None)
    tag = "batch-" + uuid.uuid4().hex[:12]
    if pool is not None:
        from presto_tpu.exec import cancel as _cancel
        block_s, kill_s = _pool_wait(engine)
        scan_bytes = sum(
            a.nbytes
            for scan in TPL.bucket_scans(engine,
                                         collect_scans(plan, engine))
            for a in scan.arrays.values() if isinstance(a, np.ndarray))
        pool.reserve(tag, scan_bytes, block_s=block_s,
                     kill_after_s=kill_s, owner=_cancel.current())
    try:
        return _run_batched(engine, templates, k, plan, n_params)
    finally:
        if pool is not None:
            pool.free(tag)


def _run_batched(engine, templates: list, k: int, plan, n_params: int):
    from presto_tpu import templates as TPL
    from presto_tpu.exec import progcache as PC
    from presto_tpu.exec.cancel import checkpoint
    from presto_tpu.exec.executor import (RETRY_GROWTH, _cache_key,
                                          collect_scans, make_traced)

    scan_inputs = TPL.bucket_scans(engine,
                                   collect_scans(plan, engine))
    fpr = PC.platform_fingerprint()
    cache = engine._program_cache
    cache.configure(engine.session)
    serial_key, _ = _cache_key(engine, plan, scan_inputs, {})
    # bucket the batch width to the next power of two (see module
    # docstring): padding lanes repeat the last member's bindings and
    # are dropped at demux
    kp = 1 << (k - 1).bit_length()
    # the batched program's own cache lineage: same canonical plan /
    # shapes / dicts / session components, plus the batch width
    base_key = serial_key + (("batch", kp),)
    known_caps = engine._caps_memory.get(base_key)
    if known_caps is None:
        known_caps = cache.load_caps(base_key, fpr)
    capacities = dict(known_caps)

    # per-position stacks of the K queries' physical parameter values;
    # example args (placeholder string codes) carry the exact shapes
    # and dtypes the real bind will, so lowering on them is sound
    example = _stack_params(
        _pad([t.example_args() for t in templates], kp))

    for _attempt in range(_MAX_ATTEMPTS):
        checkpoint()
        caps_key = PC.bucket_capacities(capacities)
        entry = cache.lookup((base_key, caps_key), fpr)
        flat_arrays = [
            engine.device_array(scan.arrays[sym])
            if getattr(scan, "cache_device", False) else scan.arrays[sym]
            for scan in scan_inputs for sym in scan.arrays]
        if entry is None:
            traced_fn, _host_arrays, meta = make_traced(
                scan_inputs, plan, capacities, engine.session,
                params=templates[0].example_args())
            # scans broadcast (uploaded once), parameters map: the
            # whole operator chain vectorizes over the query axis
            batched_fn = jax.vmap(
                traced_fn,
                in_axes=(None,) * len(flat_arrays) + (0,) * n_params)
            from presto_tpu.exec.executor import (_COMPILES,
                                                  _COMPILE_SECONDS)
            _t0 = time.perf_counter()
            with TRACER.span("compile", attempt=_attempt,
                             root=type(plan).__name__, batch=kp):
                compiled = jax.jit(batched_fn).lower(
                    *flat_arrays, *example).compile()
            _COMPILES.inc()
            _COMPILE_SECONDS.observe(time.perf_counter() - _t0)
            cache.insert((base_key, caps_key), compiled, meta, fpr,
                         persist=False)
            cache_hit = False
        else:
            compiled, meta = entry
            cache_hit = True
        # bind THIS batch's literal values through the trace-recorded
        # string dictionaries, stacked along the query axis
        pargs = _stack_params(
            _pad([t.bind(meta.get("param_bindings"))
                  for t in templates], kp))
        with TRACER.span("execute", cache_hit=cache_hit, batch=kp):
            res, live, oks, counts = compiled(*flat_arrays, *pargs)
            # (K, k) ok flags: a table that overflowed in ANY lane
            # must grow for the whole batch
            oks_np = HS.fetch(oks, site="batch-ok-ladder")
        oks_all = np.asarray(oks_np).all(axis=0)
        if oks_all.all():
            if not cache_hit:
                cache.insert((base_key, caps_key), compiled, meta, fpr)
            if engine._caps_memory.get(base_key) != capacities:
                cache.store_caps(base_key, capacities, fpr)
            engine._caps_memory[base_key] = dict(capacities)
            _BATCHED.inc(k)
            _BATCH_SIZE.observe(float(k))
            return _demux(plan, meta, res, live, k)
        if not cache_hit:
            cache.discard((base_key, caps_key))
        from presto_tpu.ops.hash import grow_overflowed
        grow_overflowed(capacities, meta["ok_keys"], oks_all,
                        meta["used_capacity"], RETRY_GROWTH)
    from presto_tpu.ops.hash import HashChainOverflow
    raise HashChainOverflow(
        "batched hash table capacity retry limit exceeded")


def _pad(binds: list, kp: int) -> list:
    """Fill the padded batch's extra lanes with the last member's
    bindings (their results are discarded at demux)."""
    return binds + [binds[-1]] * (kp - len(binds))


def _stack_params(binds: list[list]) -> list[np.ndarray]:
    """Position-wise stack of K queries' physical parameter vectors:
    the j-th traced parameter becomes a (K, ...)-shaped device input
    mapped by vmap's leading axis."""
    n = len(binds[0])
    return [np.stack([np.asarray(b[j]) for b in binds])
            for j in range(n)]


def _demux(plan: N.PlanNode, meta: dict, res, live,
           k: int) -> list[Table]:
    """Per-lane host Tables from one batched program's outputs: lane i
    of every (K, ...) result array is exactly what the serial program
    would have produced for query i (the unpack mirrors
    exec.executor.run_plan)."""
    from presto_tpu.exec.executor import _rename_outputs

    live_np, res_np = HS.fetch((live, res), site="batch-demux")
    tables: list[Table] = []
    for lane in range(k):
        cols: dict[str, Column] = {}
        i = 0
        for sym, dtype, dictionary, has_valid in meta["out"]:
            data = res_np[i][lane]
            valid = res_np[i + 1][lane]
            i += 2
            if isinstance(dtype, T.ArrayType):
                from presto_tpu.block import lists_from_padded
                lengths, emask = res_np[i][lane], res_np[i + 1][lane]
                i += 2
                data = lists_from_padded(dtype.element, data, lengths,
                                         emask, dictionary)
                cols[sym] = Column(
                    dtype, data,
                    valid if has_valid or not valid.all() else None,
                    None)
                continue
            cols[sym] = Column(
                dtype, data,
                valid if has_valid or not valid.all() else None,
                dictionary)
        lane_live = live_np[lane]
        tables.append(Table(_rename_outputs(plan, cols),
                            len(lane_live), lane_live))
    return tables
