"""Cooperative query cancellation + run-time limits.

A monolithic XLA program cannot be interrupted mid-flight, so the
engine checks a per-thread cancellation token at every host-side
checkpoint: between capacity-retry compiles, between streamed scan
blocks, between spill partitions, and inside latency-simulating
connector scans. The reference reaches the same points through
QueryStateMachine transitions + Driver yield
(execution/QueryTracker enforced timeouts, Driver.processFor quanta);
here the quanta are the host-visible seams of device execution.

The token is thread-local because the server's dispatcher pool runs
each query wholly on one thread (server/server.py QueryManager).
"""

from __future__ import annotations

import threading
import time


class QueryCanceled(RuntimeError):
    """Raised at a checkpoint after cancel() or past the deadline."""


_state = threading.local()


class CancelToken:
    def __init__(self, deadline: float | None = None):
        self._event = threading.Event()
        self.deadline = deadline

    def cancel(self) -> None:
        self._event.set()

    def check(self) -> None:
        if self._event.is_set():
            raise QueryCanceled("query canceled")
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise QueryCanceled("query exceeded query_max_run_time")


def install(token: CancelToken | None) -> None:
    _state.token = token


def current() -> CancelToken | None:
    return getattr(_state, "token", None)


def checkpoint() -> None:
    token = getattr(_state, "token", None)
    if token is not None:
        token.check()
