"""Cooperative query cancellation + run-time limits.

A monolithic XLA program cannot be interrupted mid-flight, so the
engine checks a per-thread cancellation token at every host-side
checkpoint: between capacity-retry compiles, between streamed scan
blocks, between spill partitions, and inside latency-simulating
connector scans. The reference reaches the same points through
QueryStateMachine transitions + Driver yield
(execution/QueryTracker enforced timeouts, Driver.processFor quanta);
here the quanta are the host-visible seams of device execution.

The token is thread-local because the server's dispatcher pool runs
each query wholly on one thread (server/server.py QueryManager).
"""

from __future__ import annotations

import threading
import time


class QueryCanceled(RuntimeError):
    """Raised at a checkpoint after cancel() or past the deadline."""


class TimeLimitExceeded(QueryCanceled):
    """A query lifetime limit (query_max_run_time at a checkpoint
    deadline, query_max_planning_time at a planning seam) was
    exceeded. Distinct from a user cancellation so the protocol layer
    reports FAILED + errorName EXCEEDED_TIME_LIMIT, not CANCELED."""


_state = threading.local()


class CancelToken:
    def __init__(self, deadline: float | None = None):
        self._event = threading.Event()
        self.deadline = deadline
        # set by kill(): the exception class/message the next checkpoint
        # raises INSTEAD of the generic QueryCanceled — the low-memory
        # killer and the lifetime reaper die loudly with an
        # attributable error (MemoryKilledError, timeout), not a
        # silent cancellation. Written before the Event is set, so a
        # checkpoint that observes the flag sees the exception too.
        self.kill_exc: BaseException | None = None

    def cancel(self) -> None:
        self._event.set()

    def kill(self, exc: BaseException) -> None:
        """Cancel with a specific exception raised at checkpoints."""
        self.kill_exc = exc
        self._event.set()

    def check(self) -> None:
        if self._event.is_set():
            exc = self.kill_exc
            if exc is not None:
                # a fresh instance per raising thread: tracebacks of
                # concurrent checkpoints must not chain onto one object
                raise type(exc)(str(exc))
            raise QueryCanceled("query canceled")
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise TimeLimitExceeded("query exceeded query_max_run_time")


def install(token: CancelToken | None) -> None:
    _state.token = token


def current() -> CancelToken | None:
    return getattr(_state, "token", None)


def checkpoint() -> None:
    token = getattr(_state, "token", None)
    if token is not None:
        token.check()
