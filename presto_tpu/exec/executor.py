"""Plan executor: logical plan -> one jitted XLA program -> host Table.

Analog of LocalQueryRunner.executeInternal + createDrivers
(testing/LocalQueryRunner.java:685,745) with the crucial difference that a
fragment is ONE traced computation: XLA fuses the operator chain instead of
pulling pages operator-by-operator (reference Driver.java:354 hot loop).

Hash-table capacities: planner hints (node.capacity when set) or
2 * input-length fallback; on kernel-reported overflow the executor doubles
the capacity and recompiles — the host-side analog of the reference's
rehash (MultiChannelGroupByHash.java:140).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Column, Table
from presto_tpu.exec import hostsync as HS
from presto_tpu.exec import operators as OP
from presto_tpu.exec.operators import DTable
from presto_tpu.expr.compile import Val
from presto_tpu.obs.metrics import REGISTRY
from presto_tpu.obs.trace import TRACER
from presto_tpu.ops.hash import next_pow2
from presto_tpu.plan import nodes as N

_COMPILES = REGISTRY.counter(
    "presto_tpu_programs_compiled_total",
    "XLA programs compiled (cache misses + capacity-retry recompiles)")
_COMPILE_SECONDS = REGISTRY.histogram(
    "presto_tpu_compile_seconds", "XLA program compile wall time")


# dispatch-exhaustiveness opt-outs (lint/dispatch.py): node types the
# PlanInterpreter deliberately has no _r_ handler for
DISPATCH_EXEMPT = {
    "MatchRecognize": "execute_plan splits the plan at the "
    "MatchRecognize node before interpretation (host-side NFA, see "
    "_execute_with_match_recognize); a node reaching the interpreter "
    "fails loudly in run()",
}


@dataclasses.dataclass
class ScanInput:
    """Host-side arrays + metadata for one TableScan."""

    node: N.TableScan
    arrays: dict[str, np.ndarray]  # symbol -> physical data
    dictionaries: dict[str, np.ndarray | None]
    types: dict[str, T.DataType]
    nrows: int
    # True only for connector-owned table arrays (stable identity across
    # executions): those pin device copies via Engine.device_array.
    # Per-execution temporaries (spill partitions, match-recognize
    # carriers) would pollute the pin cache with 0%-hit entries.
    cache_device: bool = False
    # connector-defined partitioning mapped to scan SYMBOLS (set when
    # every partitioning column is scanned); the distributed executor
    # bucket-shards such scans so co-partitioned joins skip exchanges
    part_cols: tuple[str, ...] | None = None
    # set by execute_plan_distributed when this scan was actually
    # bucket-sharded (scan rows placed by key hash, not blocks)
    bucketed: bool = False


def partitioning_symbols(connector, node: "N.TableScan"
                         ) -> tuple[str, ...] | None:
    """Connector-declared partitioning mapped to this scan's symbols,
    or None when undeclared / not fully scanned. Duck-typed: worker-side
    buffer connectors don't subclass the SPI base."""
    declared = getattr(connector, "partitioning", lambda _n: None)(
        node.table)
    if not declared:
        return None
    by_col = {c: s for s, c in node.assignments.items()}
    if not all(c in by_col for c in declared):
        return None
    return tuple(by_col[c] for c in declared)


def collect_scans(plan: N.PlanNode, engine) -> list[ScanInput]:
    out = []

    def visit(node):
        if isinstance(node, N.TableScan):
            connector = engine.catalogs[node.catalog]
            tbl = connector.table(node.table)
            arrays, dicts, types = {}, {}, {}
            for sym, colname in node.assignments.items():
                col = tbl.columns[colname]
                if isinstance(col.dtype, T.ArrayType) and np.asarray(
                        col.data).dtype == object:
                    # host object lists (varlen-aggregate outputs) ->
                    # padded 2D device layout + companion arrays
                    from presto_tpu.block import pad_object_lists
                    d2, lens, emask, d = pad_object_lists(
                        col.dtype.element, np.asarray(col.data))
                    arrays[sym] = d2
                    arrays[f"{sym}$len"] = lens
                    arrays[f"{sym}$emask"] = emask
                    dicts[sym] = d
                else:
                    arrays[sym] = np.asarray(col.data)
                    dicts[sym] = col.dictionary
                if col.valid is not None:
                    # NULL masks ship as sibling arrays (spi Block.isNull)
                    arrays[f"{sym}$valid"] = np.asarray(col.valid)
                types[sym] = col.dtype
            if tbl.mask is not None:
                # table-level row mask (padded exchange buffers ship a
                # dead row so empty relations keep static shape >= 1)
                arrays["__live__"] = np.asarray(tbl.mask)
            out.append(ScanInput(
                node, arrays, dicts, types, tbl.nrows,
                cache_device=True,
                part_cols=partitioning_symbols(connector, node)))
        for s in node.sources():
            visit(s)

    visit(plan)
    return out


def _df_hash(v: Val):
    """Content hash of a key column for dynamic-filter blooms."""
    from presto_tpu.ops import hash as H
    if v.is_string:
        return H.hash_string_column(v.data, v.dictionary, v.valid)
    return H.hash_int_column(v.data, v.valid)


def preorder_index(plan: N.PlanNode) -> dict[int, int]:
    """id(node) -> stable preorder position. Capacity-override keys use
    this instead of id() so a successful capacity vector transfers to a
    structurally identical re-plan of the same query (program cache)."""
    order: dict[int, int] = {}

    def visit(node):
        order[id(node)] = len(order)
        for s in node.sources():
            visit(s)

    visit(plan)
    return order


class PlanInterpreter:
    """Walks the plan during trace, building the XLA computation."""

    def __init__(self, scans: dict[int, tuple[ScanInput, dict]],
                 capacities: dict[tuple, int], session=None,
                 node_order: dict[int, int] | None = None):
        from presto_tpu.session import Session
        self.scans = scans  # id(node) -> (ScanInput, traced arrays)
        self.capacities = capacities  # (node pos, kind) -> forced capacity
        self.node_order = node_order or {}
        self.session = session or Session()
        self.ok_flags: list = []
        self.ok_keys: list[tuple] = []
        self.used_capacity: dict[tuple, int] = {}
        # per-node kernel attribution (presto_tpu/kernels/): stable
        # preorder position -> ["pallas:join_lookup", ...] noted by
        # the dispatch table while this node's handler traced; rides
        # meta into qstats so system.operator_stats names the kernel
        # (and splits execute wall) per operator
        self.kernel_used: dict[object, list[str]] = {}
        # always-on runtime stats (obs/qstats.py): live rows out of
        # EVERY plan node, keyed by stable preorder position so the
        # counts survive replans and ride program-cache entries across
        # process restarts. Collected on the normal cached/templated
        # path — a handful of mask sums per program, no extra compiles.
        self.collect_rows = True
        self.row_counts: list[tuple[object, object]] = []
        # dynamic filtering: probe-key symbol -> (min, max) from the
        # already-traced build side; applied at the FIRST probe-subtree
        # node that outputs the symbol (i.e. the scan), the trace-time
        # analog of the reference's DynamicFilterService pushdown
        # (server/DynamicFilterService.java:102,
        # operator/DynamicFilterSourceOperator.java:55)
        self.dyn_filters: dict[str, tuple] = {}
        self._df_applied: set[str] = set()

    def run(self, node: N.PlanNode) -> DTable:
        from presto_tpu import kernels as K
        m = getattr(self, "_r_" + type(node).__name__.lower())
        with K.collect() as used:
            dt = m(node)
        if used:
            self.kernel_used[
                self.node_order.get(id(node), id(node))] = list(used)
        if self.dyn_filters:
            dt = self._apply_dyn_filters(dt)
        if self.collect_rows:
            self.row_counts.append(
                (self.node_order.get(id(node), id(node)),
                 jnp.sum(dt.live_mask().astype(jnp.int64))))
        return dt

    def _apply_dyn_filters(self, dt: DTable) -> DTable:
        keep = None
        for sym, bits in self.dyn_filters.items():
            v = dt.cols.get(sym)
            if v is None or sym in self._df_applied:
                continue
            self._df_applied.add(sym)
            m = jnp.uint64(bits.shape[0])
            h = (_df_hash(v) % m).astype(jnp.int32)
            k = bits[h]
            if v.valid is not None:
                # NULL keys never match an inner join
                k = k & v.valid
            keep = k if keep is None else (keep & k)
        if keep is None:
            return dt
        live = keep if dt.live is None else (dt.live & keep)
        return DTable(dt.cols, live, dt.n)

    def _collect_dyn_filters(self, node: N.Join, build: DTable,
                             max_bits: int = 1 << 22) -> list[str]:
        """Build a one-hash bloom mask of the build-side key set per
        equi-key before the probe subtree is traced. False positives
        only cost the pruning (the join re-verifies); false negatives
        are impossible. Returns the registered probe symbols (a symbol
        may be re-registered by a later join over the same key)."""
        live = build.live_mask()
        m = next_pow2(min(4 * max(build.n, 16), max_bits))
        registered = []
        for lk, rk in node.criteria:
            v = build.cols[rk]
            w = live if v.valid is None else (live & v.valid)
            h = (_df_hash(v) % jnp.uint64(m)).astype(jnp.int32)
            bits = jnp.zeros((m,), dtype=bool)
            bits = bits.at[jnp.where(w, h, m)].set(True, mode="drop")
            self.dyn_filters[lk] = bits
            registered.append(lk)
        return registered

    def _node_key(self, node, kind: str) -> tuple:
        return (self.node_order.get(id(node), id(node)), kind)

    def _capacity(self, node, default: int, kind: str = "table",
                  override: int | None = None) -> int:
        """Host retry override > session override > planner hint >
        default. Planner hints are normalized through next_pow2 so
        used_capacity / overflow-retry keys stay pow2-canonical even
        for hand-written non-pow2 hints (cache-entry MERGING of nearby
        hints happens upstream: cost/reorder.py writes pow2-bucketed
        hints, which is what the plan fingerprint hashes)."""
        cap = self.capacities.get(self._node_key(node, kind))
        if cap is None:
            if override:
                cap = next_pow2(override)
            elif kind == "table":
                hint = getattr(node, "capacity", None)
                cap = next_pow2(hint) if hint else default
            elif kind == "out":
                hint = getattr(node, "output_capacity", None)
                cap = next_pow2(hint) if hint else default
            else:
                cap = default
        self.used_capacity[self._node_key(node, kind)] = cap
        return cap

    def _note_ok(self, node, ok, kind: str = "table"):
        self.ok_flags.append(ok)
        self.ok_keys.append(self._node_key(node, kind))

    def _r_tablescan(self, node: N.TableScan) -> DTable:
        scan, traced = self.scans[id(node)]
        cols = {}
        for sym in node.assignments:
            cols[sym] = Val(scan.types[sym], traced[sym],
                            traced.get(f"{sym}$valid"),
                            scan.dictionaries[sym],
                            traced.get(f"{sym}$len"),
                            traced.get(f"{sym}$emask"))
        # block-streamed scans pad the last block; the pad rows are dead
        nrows = next(iter(traced.values())).shape[0] if traced else scan.nrows
        return DTable(cols, traced.get("__live__"), nrows)

    def _r_values(self, node: N.Values) -> DTable:
        cols = {}
        n = len(node.rows)
        for i, sym in enumerate(node.symbols):
            dtype = node.types[sym]
            vals = [r[i] for r in node.rows]
            if isinstance(dtype, T.VarcharType):
                from presto_tpu.block import dictionary_encode
                codes, d = dictionary_encode(np.array(vals, object))
                cols[sym] = Val(dtype, jnp.asarray(codes), None, d)
            else:
                cols[sym] = Val(dtype, jnp.asarray(
                    np.asarray(vals, dtype=dtype.physical_dtype)))
        return DTable(cols, None, n)

    def _r_filter(self, node: N.Filter) -> DTable:
        return OP.apply_filter(self.run(node.source), node.predicate)

    def _r_project(self, node: N.Project) -> DTable:
        return OP.apply_project(self.run(node.source), node.assignments)

    def _r_aggregate(self, node: N.Aggregate) -> DTable:
        src = self.run(node.source)
        if not node.group_keys:
            cap = 1
        else:
            # bounded default: overflow-retry grows it if the real group
            # count exceeds the guess (reference rehash analog)
            cap = self._capacity(
                node, next_pow2(min(2 * src.n, 1 << 22)),
                override=int(self.session.get("groupby_table_size") or 0))
        out, ok = OP.apply_aggregate(src, node, cap)
        if node.group_keys:
            self._note_ok(node, ok)
        return out

    def _r_join(self, node: N.Join) -> DTable:
        # build side first so its key range can prune the probe scan
        right = self.run(node.right)
        if (node.join_type == N.JoinType.INNER
                and self.session.get("enable_dynamic_filtering")):
            self._collect_dyn_filters(node, right)
        left = self.run(node.left)
        cap = self._capacity(node, next_pow2(2 * right.n))
        if node.build_unique and node.join_type != N.JoinType.FULL:
            # FULL always takes the expanding path: it owns the
            # unmatched-build-rows tail pass
            out, ok = OP.apply_join(left, right, node, cap)
            self._note_ok(node, ok)
            return out
        out_cap = self._capacity(node, next_pow2(2 * (left.n + right.n)),
                                 "out")
        out, t_ok, o_ok = OP.apply_expand_join(left, right, node, cap,
                                               out_cap)
        self._note_ok(node, t_ok)
        self._note_ok(node, o_ok, "out")
        return out

    def _r_multijoin(self, node: N.MultiJoin) -> DTable:
        """Fused star chain (plan/nodes.MultiJoin): trace every build
        first — registering each build's key set as a dynamic filter,
        so the spine scan prunes against ALL dimensions at once — then
        run the probe walk (one Pallas kernel under
        kernel_backend=pallas, the sequential sorted walk on XLA).
        The Pallas tables can chain-overflow; the ok flag feeds the
        capacity retry ladder like every other hash table."""
        import types as _pytypes
        builds = []
        for bnode, crit in zip(node.builds, node.criteria):
            bdt = self.run(bnode)
            builds.append(bdt)
            if self.session.get("enable_dynamic_filtering"):
                # duck-typed shim: _collect_dyn_filters only reads
                # .criteria; keys referencing earlier builds register
                # harmlessly (applied wherever the symbol first flows)
                self._collect_dyn_filters(
                    _pytypes.SimpleNamespace(criteria=crit), bdt)
        spine = self.run(node.spine)
        default = next_pow2(
            2 * max(max((b.n for b in builds), default=1), 1))
        cap = self._capacity(node, default)
        out, ok = OP.apply_multi_join(spine, builds, node,
                                      growth=max(1, cap // default))
        self._note_ok(node, ok)
        return out

    def _r_semijoin(self, node: N.SemiJoin) -> DTable:
        src = self.run(node.source)
        filt = self.run(node.filter_source)
        cap = self._capacity(node, next_pow2(2 * filt.n))
        out, ok = OP.apply_semijoin(src, filt, node, cap)
        self._note_ok(node, ok)
        return out

    def _r_crossjoin(self, node: N.CrossJoin) -> DTable:
        left = self.run(node.left)
        right = self.run(node.right)
        if node.scalar:
            return OP.apply_cross_scalar(left, right)
        return self._cross_general(node, left, right)

    def _cross_general(self, node: N.CrossJoin, left: DTable,
                       right: DTable) -> DTable:
        """Nested-loop cross join: compact both sides to their estimated
        live sizes (with overflow retry), then take the static product."""
        lcap = self._capacity(
            node, next_pow2(min(left.n, 2 * (node.left_rows or left.n))),
            "left")
        rcap = self._capacity(
            node, next_pow2(min(right.n,
                                2 * (node.right_rows or right.n))),
            "right")
        if lcap < left.n:
            left, lok = OP.compact_dtable(left, lcap)
            self._note_ok(node, lok, "left")
        if rcap < right.n:
            right, rok = OP.compact_dtable(right, rcap)
            self._note_ok(node, rok, "right")
        return OP.apply_cross_general(left, right)

    def _r_union(self, node: N.Union) -> DTable:
        parts = [self.run(s) for s in node.inputs]
        return OP.apply_union(parts, node)

    def _r_window(self, node: N.Window) -> DTable:
        return OP.apply_window(self.run(node.source), node)

    def _r_sort(self, node: N.Sort) -> DTable:
        return OP.apply_sort(self.run(node.source), node.orderings)

    def _r_topn(self, node: N.TopN) -> DTable:
        return OP.apply_topn(self.run(node.source), node.count, node.orderings)

    def _r_limit(self, node: N.Limit) -> DTable:
        return OP.apply_limit(self.run(node.source), node.count,
                              node.offset)

    def _r_distinct(self, node: N.Distinct) -> DTable:
        src = self.run(node.source)
        cap = self._capacity(node, next_pow2(min(2 * src.n, 1 << 22)))
        out, ok = OP.apply_distinct(src, cap)
        self._note_ok(node, ok)
        return out

    def _r_markdistinct(self, node: N.MarkDistinct) -> DTable:
        src = self.run(node.source)
        cap = self._capacity(node, next_pow2(min(2 * src.n, 1 << 22)))
        out, ok = OP.apply_mark_distinct(src, node, cap)
        self._note_ok(node, ok)
        return out

    def _r_unnest(self, node: N.Unnest) -> DTable:
        return OP.apply_unnest(self.run(node.source), node)

    def _r_exchange(self, node: N.Exchange) -> DTable:
        # single-device execution: exchanges are no-ops (the sharded
        # executor in parallel/ lowers them to collectives)
        return self.run(node.source)

    def _r_output(self, node: N.Output) -> DTable:
        src = self.run(node.source)
        return DTable({s: src.cols[s] for s in node.symbols}, src.live, src.n)


def make_traced(scan_inputs: list[ScanInput], plan: N.PlanNode,
                capacities: dict[int, int], session=None,
                interp_factory=None, params: list | None = None,
                collect_rows: bool = True):
    """Build (traced_fn, flat_example_args, meta). ``traced_fn`` is a pure
    jittable function from flat scan arrays to
    (result columns, live mask, ok flags, per-node row counts); ``meta``
    is populated at trace time with output schema and hash-capacity
    bookkeeping.

    ``collect_rows`` (default on — the always-on stats tree): the
    interpreter sums every node's live mask and the traced function
    returns the counts stacked as ONE extra int array (one host
    transfer for the whole plan, same trick as the ok flags), with
    ``meta["count_nodes"]`` listing the stable preorder node positions.
    ``collect_rows=False`` keeps the legacy 3-output contract for
    callers that replay one program over many partitions (spill,
    block streaming) where per-node totals would be misattributed.

    ``interp_factory`` substitutes a PlanInterpreter subclass.

    ``params`` (plan templates): example physical values of the plan's
    hoisted-literal parameter vector. The traced function then takes
    them as TRAILING arguments after the scan arrays, the interpreter
    walk runs under a TraceParams context resolving ir.Parameter
    leaves, and ``meta["param_bindings"]`` records the dictionaries
    VARCHAR parameters bound against (templates/runtime.py)."""
    flat_arrays = [
        scan.arrays[sym] for scan in scan_inputs for sym in scan.arrays]
    meta: dict[str, object] = {}
    node_order = preorder_index(plan)

    def traced_fn(*args):
        from presto_tpu import kernels as K
        it = iter(args)
        scans = {}
        for scan in scan_inputs:
            traced = {sym: next(it) for sym in scan.arrays}
            scans[id(scan.node)] = (scan, traced)
        interp = (interp_factory or PlanInterpreter)(
            scans, capacities, session, node_order)
        interp.collect_rows = collect_rows
        # resolve + install the kernel backend for this trace
        # (kernel_backend session property; ambient so operators and
        # ops/segred dispatch without threading the session through)
        backend = K.resolve(interp.session)
        if params is not None:
            from presto_tpu.templates import runtime as TR
            tp = TR.TraceParams(list(it))
            with TR.active(tp), K.use_backend(backend):
                out = interp.run(plan)
            meta["param_bindings"] = dict(tp.bindings)
        else:
            with K.use_backend(backend):
                out = interp.run(plan)
        meta["out"] = [
            (sym, v.dtype, v.dictionary, v.valid is not None)
            for sym, v in out.cols.items()]
        meta["ok_keys"] = interp.ok_keys
        meta["used_capacity"] = interp.used_capacity
        meta["kernel_backend"] = backend
        meta["kernels"] = dict(getattr(interp, "kernel_used", {}))
        res = []
        for sym, v in out.cols.items():
            res.append(v.data)
            res.append(v.valid if v.valid is not None
                       else jnp.ones((out.n,), dtype=bool))
            if v.is_array:
                # arrays ship lengths + element mask after (data, valid)
                res.append(v.lengths)
                res.append(v.elem_valid if v.elem_valid is not None
                           else jnp.ones(v.data.shape, dtype=bool))
        # ok flags ship as ONE stacked array: a tuple of device scalars
        # costs one host round-trip EACH to inspect (~90ms over a
        # tunneled device), a (k,) bool array costs one total
        oks = (jnp.stack(interp.ok_flags) if interp.ok_flags
               else jnp.zeros((0,), dtype=bool))
        if interp.row_counts:
            # stacked like the ok flags: one (k,) array costs one host
            # round-trip for the whole plan's actuals
            meta["count_nodes"] = [key for key, _ in interp.row_counts]
            return (tuple(res), out.live_mask(), oks,
                    jnp.stack([c for _, c in interp.row_counts]))
        return tuple(res), out.live_mask(), oks

    return traced_fn, flat_arrays, meta


def execute_plan(engine, plan: N.PlanNode) -> Table:
    """Compile + run a logical plan on the local device. Plans whose
    dominant scan exceeds the session block size stream block-wise (the
    split analog) when the plan shape allows it."""
    from presto_tpu.exec.spill import try_execute_spilled
    from presto_tpu.exec.streaming import try_execute_streamed
    mr = _find_match_recognize(plan)
    if mr is not None:
        return _execute_with_match_recognize(engine, plan, mr)
    from presto_tpu.exec.varlen import (
        execute_with_varlen, find_varlen_aggregate)
    vl = find_varlen_aggregate(plan)
    if vl is not None:
        return execute_with_varlen(engine, plan, vl)
    # streaming first: a block-streamed scan already bounds its working
    # set, so the memory-budget check must not veto it
    streamed = try_execute_streamed(engine, plan)
    if streamed is not None:
        return streamed
    # the memory budget (host-partitioned spill) outranks both grouped
    # execution and compile-time segmentation: an over-budget join must
    # not device-OOM mid-bucket
    spilled = try_execute_spilled(engine, plan)
    if spilled is not None:
        return spilled
    # grouped execution (lifespans): explicit opt-in, bucket-by-bucket
    # joins over co-bucketed tables
    from presto_tpu.exec.spill import try_execute_grouped
    grouped = try_execute_grouped(engine, plan)
    if grouped is not None:
        return grouped
    if _find_split(plan, engine) is not None:
        return _execute_segmented(engine, plan)
    scan_inputs = collect_scans(plan, engine)
    return run_plan(engine, plan, scan_inputs)


RETRY_GROWTH = 4  # overshoot on overflow to bound recompiles at ~1


def _cache_key(engine, plan, scan_inputs, capacities):
    """Canonical program-cache key: (plan fingerprint, input shapes,
    trace-relevant session properties) + pow2-bucketed capacity
    overrides (exec/progcache.py). The session component resolves
    through Session.get, so per-thread query overrides participate;
    properties the trace never reads (host-side limits, planner
    strategies already captured by the fingerprint) stay out so
    replans under unrelated SET SESSIONs keep hitting."""
    from presto_tpu.exec import progcache as PC
    from presto_tpu.plan.fingerprint import plan_fingerprint
    fp = plan_fingerprint(plan)
    shapes = tuple(
        (sym, a.shape, str(a.dtype))
        for scan in scan_inputs for sym, a in scan.arrays.items())
    sess = PC.trace_session_key(engine.session)
    # dictionary CONTENT digests: traced programs embed dictionary
    # codes as constants, so a data rewrite at constant shape must
    # miss (the persistent store outlives process restarts)
    dicts = PC.scan_dictionary_key(scan_inputs)
    return (fp, shapes, dicts, sess), PC.bucket_capacities(capacities)


def prepare_plan(engine, plan: N.PlanNode, scan_inputs: list[ScanInput]):
    """Resolve hash-table capacities and return
    (compiled, flat_arrays, meta, (res, live, oks, counts)) for a
    plan, reusing the engine's compiled-program cache. ``counts`` is
    the stacked per-node live-row array every program now returns
    (``meta["count_nodes"]`` aligns it with stable preorder
    positions) — the raw material of the always-on runtime stats tree
    (obs/qstats.py), recorded here so EVERY execution path (segments,
    workers, warm cache hits, template hits) feeds the same tree.

    The cache is the analog of the reference's compiled-artifact caches
    (gen/PageFunctionCompiler.java:101): programs key on
    (plan fingerprint, input shapes, session, capacity overrides), and
    the capacity vector that succeeded is remembered per plan so a
    repeat query goes straight to the right program — zero recompiles.
    On overflow, EVERY failed capacity grows RETRY_GROWTH x at once
    (host-side analog of the reference's rehash,
    MultiChannelGroupByHash.java:140, overshooting to bound the number
    of recompiles instead of doubling per node).

    The cache is two-tier (exec/progcache.py): the in-memory LRU
    fronts a persistent AOT disk store (PRESTO_TPU_PROGRAM_CACHE_DIR),
    so a warm process — or another worker sharing the directory —
    deserializes the executable instead of paying lower+compile, and
    the persisted capacity sidecar skips the overflow-retry ladder.

    Plan templates (templates/): with session ``plan_templates`` on,
    hoistable literals leave the plan before the key is computed — the
    cache keys on the parameterized TEMPLATE (plus pow2-bucketed scan
    shapes under ``template_shape_bucketing``), and this query's
    literal values enter the compiled program as trailing device
    scalars. A literal variant of an already-compiled query shape is a
    cache hit: zero compiles."""
    from presto_tpu import templates as TPL
    from presto_tpu.exec import progcache as PC
    from presto_tpu.obs import qstats as QS
    fpr = PC.platform_fingerprint()
    cache = engine._program_cache
    cache.configure(engine.session)
    # the pre-template plan, literals intact: the stats recorder
    # estimates rows on it (the CBO cannot cost Parameter leaves); the
    # tree shape is identical so preorder positions line up
    orig_plan = plan
    tpl = None
    if TPL.enabled(engine.session):
        scan_inputs = TPL.bucket_scans(engine, scan_inputs)
        tpl = TPL.parameterize(plan)
        if tpl is not None:
            plan = tpl.plan
    base_key, _ = _cache_key(engine, plan, scan_inputs, {})
    known_caps = engine._caps_memory.get(base_key)
    if known_caps is None:  # {} is a real answer: no overrides needed
        known_caps = cache.load_caps(base_key, fpr)
    capacities = dict(known_caps)

    from presto_tpu.exec.cancel import checkpoint
    for _attempt in range(6):
        checkpoint()
        caps_key = PC.bucket_capacities(capacities)
        entry = cache.lookup((base_key, caps_key), fpr)
        if tpl is not None and _attempt == 0:
            TPL.note_lookup(hit=entry is not None,
                            params=len(tpl.params))
        flat_arrays = [
            engine.device_array(scan.arrays[sym])
            if getattr(scan, "cache_device", False) else scan.arrays[sym]
            for scan in scan_inputs for sym in scan.arrays]
        pargs = tpl.example_args() if tpl is not None else []
        if entry is None:
            traced_fn, _host_arrays, meta = make_traced(
                scan_inputs, plan, capacities, engine.session,
                params=(pargs if tpl is not None else None))
            # compile-latency chaos point (ft/faults.py): lets the
            # chaos suite provoke slow compiles deterministically
            from presto_tpu.ft.faults import FAULTS
            FAULTS.delay("compile-slow", key=type(plan).__name__)
            _t0 = time.perf_counter()
            # explicit AOT lower+compile (not a first jit-wrapper call)
            # so compile and execute attribute separately in spans;
            # meta fills during the trace lower() triggers
            with TRACER.span("compile", attempt=_attempt,
                             root=type(plan).__name__):
                compiled = jax.jit(traced_fn).lower(
                    *flat_arrays, *pargs).compile()
            compile_s = time.perf_counter() - _t0
            last_compile_s = compile_s
            _COMPILES.inc()
            _COMPILE_SECONDS.observe(compile_s)
            # device-cost summary rides the meta into the program
            # cache (and its disk tier): warm hits in a fresh process
            # still attribute flops/bytes without a live Compiled
            from presto_tpu.obs import devprof
            cost = devprof.harvest(compiled)
            if cost is not None:
                meta["cost"] = cost
            if os.environ.get("PRESTO_TPU_LOG_COMPILES"):
                print(f"[compile] {compile_s:.1f}s "
                      f"caps={dict(capacities)} "
                      f"root={type(plan).__name__}", file=sys.stderr)
            # memory tier only for now: failed capacity-retry rungs
            # must not pay serialize+IO (and would pollute the store);
            # the disk persist happens below, on the successful attempt
            cache.insert((base_key, caps_key), compiled, meta, fpr,
                         persist=False)
            cache_hit = False
        else:
            compiled, meta = entry
            cache_hit = True
            last_compile_s = 0.0
        if tpl is not None:
            # bind THIS query's literal values (string parameters
            # resolve through the dictionaries the trace recorded —
            # carried in meta, so disk-tier hits bind too)
            pargs = tpl.bind(meta.get("param_bindings"))
        _t1 = time.perf_counter()
        with TRACER.span("execute", cache_hit=cache_hit):
            outs = compiled(*flat_arrays, *pargs)
            # stale-format disk entries cannot reach here (the program
            # format version rides the platform fingerprint), but a
            # defensive unpack keeps a 3-output program non-fatal
            if len(outs) == 4:
                res, live, oks, counts = outs
            else:
                (res, live, oks), counts = outs, None
            # ONE host sync for every flag — also the point the async
            # dispatch actually finishes, so the span covers real
            # device time, not just call overhead
            oks_np = HS.fetch(oks, site="ok-ladder")
        execute_s = time.perf_counter() - _t1
        if oks_np.all():
            if not cache_hit:
                cache.insert((base_key, caps_key), compiled, meta, fpr)
            if engine._caps_memory.get(base_key) != capacities:
                cache.store_caps(base_key, capacities, fpr)
            engine._caps_memory[base_key] = dict(capacities)
            # fold this program into the ambient stats tree (no-op
            # outside a task/query recording scope)
            QS.record_program(
                engine, orig_plan, meta, counts, last_compile_s,
                execute_s, cache_hit, template=tpl is not None,
                template_hit=tpl is not None and cache_hit)
            return compiled, flat_arrays, meta, (res, live, oks,
                                                 counts)
        if not cache_hit:
            # a failed rung's program is dead weight in the bounded
            # LRU: future runs jump straight to the successful caps
            cache.discard((base_key, caps_key))
        # the LOUD path of what used to be a silent in-kernel
        # give-up: grow every failed capacity and count hash-table
        # overflows, then retry (ops/hash.grow_overflowed — shared by
        # all four retry ladders)
        from presto_tpu.ops.hash import grow_overflowed
        grow_overflowed(capacities, meta["ok_keys"], oks_np,
                        meta["used_capacity"], RETRY_GROWTH)
    from presto_tpu.ops.hash import HashChainOverflow
    raise HashChainOverflow(
        "hash table capacity retry limit exceeded")


# XLA compile time grows superlinearly with program size (a 5-join
# TPC-H Q5 program compiles >10x slower than twice a 2-join Q3); plans
# with more joins than this split into separately compiled segments
# with DEVICE-RESIDENT handoff (no host round trip).
MAX_JOINS_PER_PROGRAM = 2


def _count_joins(node: N.PlanNode) -> int:
    # a MultiJoin counts its fan-in: compile-cost-wise it carries one
    # sorted probe per build, and counting it whole keeps _find_split
    # from trying to cut inside the fused operator (its children hold
    # no joins, so the splitter materializes the MultiJoin subtree —
    # or, via _find_agg_input_split, the aggregate input above it)
    own = (len(node.builds) if isinstance(node, N.MultiJoin)
           else int(isinstance(node, (N.Join, N.SemiJoin))))
    return own + sum(_count_joins(s) for s in node.sources())


def _find_split(node: N.PlanNode, engine=None):
    """A subtree with <= MAX_JOINS_PER_PROGRAM joins (at least one) to
    materialize first, or None when the plan fits one program."""
    if _count_joins(node) <= MAX_JOINS_PER_PROGRAM:
        return _find_agg_input_split(node, engine)
    if isinstance(node, N.MultiJoin):
        # the fused operator is atomic — never cut inside it. Large
        # inputs materialize it whole (so the aggregate above runs at
        # compacted live width, the same boundary the cascade's
        # aggregate-input split provided); small plans run fused with
        # everything above in one program
        if engine is None or _subtree_scan_rows(node, engine) \
                >= AGG_SPLIT_MIN_ROWS:
            return node
        return None
    kids = node.sources()
    best = max(kids, key=_count_joins)
    c = _count_joins(best)
    if c > MAX_JOINS_PER_PROGRAM:
        return _find_split(best, engine)
    if c < 1:
        return None
    # a grouped aggregate inside the chosen subtree still wants its own
    # pre-compaction boundary (its group-by must not run at join width)
    inner = _find_agg_input_split(best, engine)
    return inner if inner is not None else best


# minimum estimated scan rows under an aggregate before its input gets
# its own compaction boundary: below this, two compiles + a host sync
# cost more than grouping a small buffer at full width
AGG_SPLIT_MIN_ROWS = 1 << 21


def _subtree_scan_rows(node: N.PlanNode, engine) -> int:
    """Largest base-scan row estimate in a subtree. Segment carrier
    scans count as LARGE: a carrier only exists because an earlier
    split materialized a big intermediate, and its static width is the
    width the aggregate would otherwise churn through."""
    if isinstance(node, N.TableScan):
        if node.catalog == "__segment__":
            return 1 << 62
        conn = engine.catalogs.get(node.catalog)
        if conn is None:
            return 0
        try:
            return int(conn.row_count_estimate(node.table))
        except Exception:
            return 0
    return max((_subtree_scan_rows(s, engine) for s in node.sources()),
               default=0)


def _find_agg_input_split(node: N.PlanNode, engine=None):
    """Pre-aggregation compaction boundary: the input subtree of the
    lowest grouped Aggregate that sits above at least one join.

    Joins + selective filters leave most of a static-shape buffer dead
    (TPC-H Q3 keeps ~3M of 60M lineitem rows), yet a monolithic program
    runs the group-by's sort and payload permutations at full width —
    random-access HBM passes at 60M rows cost ~1.5s each on v5e.
    Materializing the aggregate's input as a segment lets
    run_plan_device compact it to pow2(live) first, so grouping runs at
    live width (15-20x narrower on Q3). The reference gets the same
    effect for free from row-at-a-time paging between operators
    (operator/HashAggregationOperator.java consumes compacted Pages);
    a fixed-shape dataflow needs an explicit re-bucketing boundary."""
    for s in node.sources():
        found = _find_agg_input_split(s, engine)
        if found is not None:
            return found
    if isinstance(node, N.Aggregate) and node.group_keys \
            and not isinstance(node.source, N.TableScan) \
            and _count_joins(node.source) >= 1 \
            and (engine is None or _subtree_scan_rows(
                node.source, engine) >= AGG_SPLIT_MIN_ROWS):
        return node.source
    return None


def _collect_with_carriers(plan: N.PlanNode, engine,
                           carriers: dict[int, "ScanInput"]
                           ) -> list["ScanInput"]:
    out: list[ScanInput] = []
    # segment carriers also resolve by their unique table name: the
    # boundary-pruning pass (prune_columns in _prune_subtree) rebuilds
    # every TableScan node, so identity alone cannot find a carrier
    # inside a narrowed later segment
    by_name = {
        si.node.table: si for si in carriers.values()
        if isinstance(si.node, N.TableScan)
        and si.node.catalog == "__segment__"}

    def visit(node):
        if id(node) in carriers:
            out.append(carriers[id(node)])
            return
        if isinstance(node, N.TableScan):
            if node.catalog == "__segment__" and node.table in by_name:
                out.append(_rebind_carrier(by_name[node.table], node))
                return
            out.extend(collect_scans(node, engine))
            return
        for s in node.sources():
            visit(s)

    visit(plan)
    return out


def _compact_kernel(live, data, cap: int):
    """Gather live rows to the front of a ``cap``-row buffer (device
    gather; the page-compaction analog). Padding slots hold arbitrary
    dead rows' data and are marked dead in the returned live mask.

    Live positions extract via one (u32 key, index) sort — stable, so
    row order is preserved — then every column gathers at ``cap``
    width. (jnp.nonzero's TPU lowering was measured at 5.4s on a
    60M-row mask, ~20x the cost of the sort it replaces.)"""
    n = live.shape[0]
    key = jnp.where(live, jnp.uint32(0), jnp.uint32(1))
    _, idx = jax.lax.sort(
        (key, jnp.arange(n, dtype=jnp.int32)), num_keys=1,
        is_stable=True)
    idx = idx[:cap]
    out = {k: v[idx] for k, v in data.items()}
    newlive = jnp.arange(cap) < jnp.sum(live)
    return out, newlive


_compact_jit = jax.jit(_compact_kernel, static_argnames=("cap",))


def device_outputs(meta, res, live, cap_floor: int | None = None):
    """Unpack one program's (meta, res, live) into segment-carrier form
    (arrays incl. $valid/__live__, dicts, types, n). Outputs compact to
    pow2(live count) when that at least halves the buffer, so later
    segments never churn through dead padding.

    ``cap_floor`` (plan templates): None = legacy exact compaction;
    an int (0 when no width is remembered yet) switches to templated
    sizing. Carrier widths are DATA-dependent (pow2 of the live
    count), so a literal variant whose intermediate crosses a pow2
    boundary would shift every downstream segment's input shape and
    miss the template cache. Templated sizing therefore sticks to the
    remembered per-segment width whenever the live count FITS in it
    (reusing the width exactly is what keeps downstream shapes — and
    so the compiled programs — identical across variants), and only
    when the count overflows the memory does it grow, with a 2x
    margin (the RETRY_GROWTH idea applied to widths) so nearby
    variants land in one bucket and outliers converge after a single
    recompile."""
    arrays: dict = {}
    dicts: dict = {}
    types: dict = {}
    i = 0
    for sym, dtype, dictionary, has_valid in meta["out"]:
        arrays[sym] = res[i]
        if has_valid:
            arrays[f"{sym}$valid"] = res[i + 1]
        i += 2
        if isinstance(dtype, T.ArrayType):
            arrays[f"{sym}$len"] = res[i]
            arrays[f"{sym}$emask"] = res[i + 1]
            i += 2
        dicts[sym] = dictionary
        types[sym] = dtype
    n = int(live.shape[0])
    cnt = HS.fetch_int(jnp.sum(live), site="segment-width")
    if cap_floor is None:
        cap = max(128, next_pow2(max(cnt, 1)))
    elif cap_floor and cnt <= cap_floor:
        # a remembered width the count fits in: reuse it EXACTLY
        # (0 = nothing remembered yet — must not compact to zero)
        cap = int(cap_floor)
    else:
        cap = max(128, next_pow2(2 * max(cnt, 1)), int(cap_floor))
    if cap <= n // 2:
        arrays, live = _compact_jit(live, arrays, cap=cap)
        n = cap
    arrays["__live__"] = live
    return arrays, dicts, types, n


def run_plan_device(engine, plan: N.PlanNode,
                    scan_inputs: list["ScanInput"],
                    cap_floor: int | None = None):
    """Like run_plan but keeps results as DEVICE arrays (segment
    handoff); see device_outputs. Returns (arrays, dicts, types, n,
    per-node rows=None) — the runner contract of _segment_carriers."""
    _c, _f, meta, (res, live, _oks, _counts) = prepare_plan(
        engine, plan, scan_inputs)
    return device_outputs(meta, res, live, cap_floor) + (None,)


def _pool_wait(engine) -> tuple[float, float]:
    """(block_s, kill_after_s) for memory-pool reservations: how long
    an over-capacity reservation blocks for concurrent queries to free
    bytes, and when sustained exhaustion triggers the low-memory killer
    (memory.MemoryPool.reserve; both 0 by default — the single-query
    fail-fast behavior)."""
    try:
        sess = engine.session
        return (float(sess.get("memory_reserve_timeout_s") or 0.0),
                float(sess.get("low_memory_killer_delay_s") or 0.0))
    except Exception:  # noqa: BLE001 - engines without a session
        return (0.0, 0.0)


def _contains_carrier(node: N.PlanNode, names: set[str]) -> bool:
    """Does a subtree scan any of the named __segment__ carriers?"""
    if isinstance(node, N.TableScan):
        return node.catalog == "__segment__" and node.table in names
    return any(_contains_carrier(s, names) for s in node.sources())


def _segment_carriers(engine, plan: N.PlanNode, pool_tag: str,
                      observer=None, runner=None):
    """Materialize many-join subtrees as device-resident carrier scans
    until the remaining plan fits one program. Returns the rewritten
    plan + carrier inputs. Carrier bytes are reserved under
    ``pool_tag`` (freed by the caller when the pipeline finishes).

    Segments are discovered structurally WAVE by wave: every split the
    current plan yields that does not consume a carrier of the same
    wave is mutually independent, so the wave's segments compile and
    execute concurrently on a bounded thread pool (session
    ``parallel_compile_width``; XLA compilation releases the GIL). A
    split that scans a same-wave carrier closes the wave — dependency
    order between waves is preserved exactly as the old serial loop.

    ``runner(engine, mat, scans, cap_floor=None) -> (arrays, dicts,
    types, n, node_rows)`` substitutes the per-segment executor
    (EXPLAIN ANALYZE passes a profiling runner); ``observer(seg, mat,
    arrays, n, wall_s, node_rows)`` fires per materialized segment, in
    segment order.

    Carrier widths are remembered per (plan template, segment index)
    in ``engine._carrier_caps`` and only grow: without the floor, a
    literal variant whose intermediate crosses a pow2 compaction
    boundary would shift every downstream segment's input shape and
    recompile (see device_outputs)."""
    from presto_tpu import templates as TPL
    from presto_tpu.exec import progcache as PC
    from presto_tpu.exec.streaming import _replace_node
    from presto_tpu.plan.fingerprint import plan_fingerprint

    pool = getattr(engine, "memory_pool", None)
    run = runner or run_plan_device
    tpl_mode = TPL.enabled(engine.session)
    tpl0 = TPL.parameterize(plan) if tpl_mode else None
    tfp = (tpl0.fingerprint() if tpl0 is not None
           else plan_fingerprint(plan))
    carrier_caps = getattr(engine, "_carrier_caps", None)
    if carrier_caps is None:
        carrier_caps = engine._carrier_caps = {}
    width = max(1, int(engine.session.get("parallel_compile_width")
                       or 1))
    if pool is not None and pool.capacity:
        # an enforced memory budget needs the serial guarantee: each
        # segment's reservation must be able to fail BEFORE the next
        # segment materializes device buffers — concurrent waves could
        # overshoot the budget by (width-1) intermediates
        width = 1
    carriers: dict[int, ScanInput] = {}
    seg = 0
    while True:
        # -- discover one wave of independent segments structurally --
        wave: list[tuple] = []  # (sub, mat, cnode)
        wave_names: set[str] = set()
        probe = plan
        while True:
            sub = _find_split(probe, engine)
            if sub is None or _contains_carrier(sub, wave_names):
                break
            needed = _needed_above(probe, sub)
            mat = sub  # what actually materializes (possibly narrowed)
            if needed is not None and needed < set(sub.output_symbols):
                mat = _prune_subtree(sub, needed)
            name = f"s{seg + len(wave)}"
            cnode = N.TableScan("__segment__", name,
                                {s: s for s in mat.output_symbols},
                                dict(mat.output_types()))
            probe = _replace_node(probe, sub, cnode)
            wave.append((sub, mat, cnode))
            wave_names.add(name)
        if not wave:
            break

        # -- materialize the wave (parallel when independent > 1) ----
        # pool threads inherit neither threading.locals nor
        # contextvars: hand over the cancel token, the per-thread
        # session override (HTTP queries compile under the submitter's
        # property overrides), and the trace context (spans otherwise
        # vanish for every parallel-compiled segment)
        from presto_tpu.exec import cancel as _cancel
        from presto_tpu.obs import qstats as _qs
        from presto_tpu.obs import trace as _ot
        from presto_tpu.session import (current_override,
                                        install_override)
        _tok = _cancel.current()
        _ov = current_override()
        _ctx = _ot.current_context()
        _task_rec = _qs.current_task()

        def _materialize(item):
            idx, mat = item
            _cancel.install(_tok)
            install_override(_ov)
            # the ambient stats recorder rides along too: segment
            # programs compiled on pool threads must land in the same
            # task's operator list
            _qs.install_task(_task_rec)
            scans = _collect_with_carriers(mat, engine, carriers)
            _t0 = time.perf_counter()
            with TRACER.attach(_ctx), \
                    TRACER.span("segment", index=seg + idx,
                                wave_width=len(wave)):
                floor = (carrier_caps.get((tfp, seg + idx), 0)
                         if tpl_mode else None)
                out = run(engine, mat, scans, cap_floor=floor)
            if pool is not None:
                # reserve inside the job, as the serial loop did: an
                # over-budget pipeline must raise MemoryLimitExceeded
                # before FURTHER segments materialize (with width=1
                # this is exactly the old segment-by-segment guard).
                # Freed by the CALLER's finally (_execute_segmented /
                # run_plan_live / profile.explain_analyze own pool_tag).
                block_s, kill_s = _pool_wait(engine)
                pool.reserve(pool_tag, sum(  # lint: disable=pool-discipline
                    int(a.nbytes) for a in out[0].values()),
                    block_s=block_s, kill_after_s=kill_s, owner=_tok)
            return out + (time.perf_counter() - _t0,)

        results = PC.map_parallel(
            _materialize,
            [(i, mat) for i, (_s, mat, _c) in enumerate(wave)], width)

        for (_sub, mat, cnode), (arrays, dicts, types, n, node_rows,
                                 wall_s) in zip(wave, results):
            if observer is not None:
                observer(seg, mat, arrays, n, wall_s, node_rows)
            carriers[id(cnode)] = ScanInput(cnode, arrays, dicts,
                                            types, n)
            # grow-only width memory (benign race: a lost update just
            # costs one extra compile on some later variant)
            prev = carrier_caps.get((tfp, seg))
            if prev is None or n > prev:
                if len(carrier_caps) > 512:
                    carrier_caps.clear()
                carrier_caps[(tfp, seg)] = n
            seg += 1
        # adopt the wave's fully-spliced tree: _replace_node rebuilds
        # every interior node, so re-splicing wave items 2..n into the
        # ORIGINAL plan would miss (their identity only exists in
        # ``probe``); the carrier leaves keep identity through later
        # splices, which is what _collect_with_carriers keys on
        plan = probe
    return plan, carriers


def _rebind_carrier(si: "ScanInput", node: N.TableScan) -> "ScanInput":
    """A carrier ScanInput re-pointed at a rebuilt (possibly
    column-narrowed) copy of its scan node, arrays restricted to the
    surviving symbols (+ their $valid/$len/$emask companions and the
    table-level live mask)."""
    if node is si.node and set(node.assignments) == set(si.types):
        return si
    keep = set(node.assignments)

    def base(k: str) -> str:
        # companion arrays ($valid/$len/$emask) follow their symbol;
        # note partial-agg STATE symbols legitimately contain '$'
        # (e.g. "rev$sum"), so only the companion suffix strips
        if "$" in k:
            b, suf = k.rsplit("$", 1)
            if suf in ("valid", "len", "emask"):
                return b
        return k

    arrays = {k: v for k, v in si.arrays.items()
              if k == "__live__" or base(k) in keep}
    return dataclasses.replace(
        si, node=node, arrays=arrays,
        dictionaries={s: si.dictionaries.get(s) for s in keep},
        types={s: si.types[s] for s in keep})


def _needed_above(plan: N.PlanNode, sub: N.PlanNode):
    """Symbols of ``sub``'s output the rest of ``plan`` actually
    consumes, or None when it cannot be determined.

    A monolithic program gets this for free from XLA dead-code
    elimination; a segment boundary materializes every output column,
    so an unpruned boundary pays full-width gathers for columns only
    ever used BELOW the split (join keys, filter inputs). Reuses the
    optimizer's prune_columns per-node knowledge: splice a placeholder
    scan where ``sub`` stands, prune the outer plan, and read back
    which placeholder columns survived."""
    from presto_tpu.exec.streaming import _replace_node
    from presto_tpu.plan.optimizer import prune_columns

    tag = "__needed_probe__"
    probe = N.TableScan(tag, tag, {s: s for s in sub.output_symbols},
                        dict(sub.output_types()))
    try:
        shadow = _replace_node(plan, sub, probe)
        if isinstance(shadow, N.Output):
            pruned = prune_columns(shadow)
        else:
            pruned = prune_columns(
                shadow, set(shadow.output_symbols))
    except Exception:
        return None  # unprunable shape: materialize everything

    found: list = []

    def visit(node):
        if isinstance(node, N.TableScan) and node.catalog == tag:
            found.append(node)
            return
        for s in node.sources():
            visit(s)

    visit(pruned)
    if len(found) != 1:
        return None
    return set(found[0].assignments)


def _prune_subtree(sub: N.PlanNode, needed: set):
    """Narrow a to-be-materialized subtree to ``needed`` output
    symbols (falling back to the unpruned subtree on any failure).
    An identity Project caps the subtree because relational nodes
    (joins above all) cannot drop their own pass-through columns."""
    from presto_tpu.expr import ir
    from presto_tpu.plan.optimizer import prune_columns
    types = dict(sub.output_types())
    keep = [s for s in sub.output_symbols if s in needed]
    cap = N.Project(sub, {s: ir.ColumnRef(types[s], s) for s in keep})
    try:
        pruned = prune_columns(cap, set(needed))
    except Exception:
        return sub
    if not needed <= set(pruned.output_symbols):
        return sub
    return pruned


def _execute_segmented(engine, plan: N.PlanNode) -> Table:
    """Execute a many-join plan as a pipeline of separately compiled
    segments — the engine's stage materialization (the reference
    streams between stages; here segment outputs stay in HBM and feed
    the next program as inputs)."""
    import uuid

    pool = getattr(engine, "memory_pool", None)
    tag = "seg-" + uuid.uuid4().hex[:12]
    try:
        plan, carriers = _segment_carriers(engine, plan, tag)
        return run_plan(engine, plan,
                        _collect_with_carriers(plan, engine, carriers))
    finally:
        if pool is not None:
            pool.free(tag)


def run_plan_live(engine, plan: N.PlanNode):
    """Run a plan fully on device (segmenting many-join plans) and
    return ONLY the final live mask (device array) — the steady-state
    benchmarking entry: materializing the mask is the host-side sync
    without paying result transfer."""
    import uuid

    pool = getattr(engine, "memory_pool", None)
    tag = "seg-" + uuid.uuid4().hex[:12]
    try:
        plan, carriers = _segment_carriers(engine, plan, tag)
        scans = _collect_with_carriers(plan, engine, carriers)
        _c, _f, _meta, (_res, live, _oks, _counts) = prepare_plan(
            engine, plan, scans)
        return live
    finally:
        if pool is not None:
            pool.free(tag)


def _find_match_recognize(plan: N.PlanNode):
    if isinstance(plan, N.MatchRecognize):
        return plan
    for s in plan.sources():
        found = _find_match_recognize(s)
        if found is not None:
            return found
    return None


def _execute_with_match_recognize(engine, plan: N.PlanNode,
                                  mr) -> Table:
    """Split execution around a MatchRecognize node: run its input
    subplan on device, evaluate the pattern automaton host-side
    (exec/match_recognize.py — vectorized predicates, host NFA), feed
    the matches back through a carrier scan for the rest of the plan
    (the same splice mechanism as the spill driver)."""
    from presto_tpu.exec.match_recognize import evaluate
    from presto_tpu.exec.spill import _carrier_scan
    from presto_tpu.exec.streaming import _replace_node

    input_table = execute_plan(engine, mr.source)
    matched = evaluate(input_table, mr)
    carrier_node, carrier_input = _carrier_scan("__matches__", matched)
    rest = _replace_node(plan, mr, carrier_node)
    return run_plan(engine, rest, [carrier_input])


def run_plan(engine, plan: N.PlanNode,
             scan_inputs: list[ScanInput]) -> Table:
    """Compile + run over prepared scan inputs (shared by the whole-table
    and block-streamed paths). Input and output array bytes are
    reserved in the engine's runtime memory pool for the duration
    (memory/MemoryPool.java:44 tagged-reservation analog)."""
    import uuid

    pool = getattr(engine, "memory_pool", None)
    tag = uuid.uuid4().hex[:12]
    if pool is not None:
        from presto_tpu.exec import cancel as _cancel
        block_s, kill_s = _pool_wait(engine)
        owner = _cancel.current()
        # host (numpy) inputs only: device-resident segment carriers
        # are already reserved under their pipeline's seg- tag
        pool.reserve(tag, sum(
            a.nbytes for scan in scan_inputs
            for a in scan.arrays.values()
            if isinstance(a, np.ndarray)),
            block_s=block_s, kill_after_s=kill_s, owner=owner)
    try:
        _compiled, _flat, meta, (res, live, _oks, _counts) = \
            prepare_plan(engine, plan, scan_inputs)
        if pool is not None:
            # device-side shape math only — no transfer
            pool.reserve(tag, sum(int(r.nbytes) for r in res),
                         block_s=block_s, kill_after_s=kill_s,
                         owner=owner)

        # one batched device->host transfer for every output column:
        # per-array np.asarray pays a tunnel round-trip each
        live_np, res_np = HS.fetch((live, res), site="result-demux")
        cols: dict[str, Column] = {}
        i = 0
        for sym, dtype, dictionary, has_valid in meta["out"]:
            data = res_np[i]
            valid = res_np[i + 1]
            i += 2
            if isinstance(dtype, T.ArrayType):
                from presto_tpu.block import lists_from_padded
                lengths, emask = res_np[i], res_np[i + 1]
                i += 2
                data = lists_from_padded(dtype.element, data, lengths,
                                         emask, dictionary)
                cols[sym] = Column(
                    dtype, data,
                    valid if has_valid or not valid.all() else None,
                    None)
                continue
            cols[sym] = Column(
                dtype, data,
                valid if has_valid or not valid.all() else None,
                dictionary)
        return Table(_rename_outputs(plan, cols), len(live_np), live_np)
    finally:
        if pool is not None:
            pool.free(tag)


def _rename_outputs(plan: N.PlanNode,
                    cols: dict[str, Column]) -> dict[str, Column]:
    """Key result columns by their declared output names (the symbols are
    internal; CTAS/INSERT and clients need the SQL names)."""
    if isinstance(plan, N.Output):
        return {name: cols[sym]
                for name, sym in zip(plan.names, plan.symbols)}
    return cols
