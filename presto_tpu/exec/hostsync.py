"""The designated host<->device synchronization boundary.

Every deliberate device->host read on the execute path goes through
this module: ``fetch`` (ONE batched ``jax.device_get`` over an
arbitrary pytree — a tuple of separate ``np.asarray`` calls pays a
tunnel round-trip EACH, ~90ms per array over a tunneled device),
``fetch_int`` (a scalar sizing read, e.g. a live-row count), and
``wait`` (``block_until_ready`` so an execute span covers real device
time). Each call increments ``presto_tpu_device_syncs_total`` labeled
by call site, so bench.py can report per-query sync counts
(``qNN_device_syncs``) next to wall time — the first real-TPU run
must show the hot path syncs a bounded, constant number of times per
query.

The ``device-sync`` lint (lint/devicesync.py) enforces the boundary
statically: any host-blocking sync on the execute path OUTSIDE this
module is a finding. Deliberate exceptions are declared in
``DEVICE_SYNC_EXEMPT`` below (id -> justification) and carry the same
staleness discipline as ``TRACE_KEY_EXEMPT``: an entry that matches
no finding is itself a finding.
"""

from __future__ import annotations

import jax

from presto_tpu.obs.metrics import REGISTRY

SYNCS = REGISTRY.counter(
    "presto_tpu_device_syncs_total",
    "Host-blocking device->host synchronizations through the "
    "exec.hostsync boundary, labeled by call site")


def fetch(tree, site: str):
    """One batched device->host transfer of an arbitrary pytree.
    Returns the same structure with host (numpy) leaves; host leaves
    pass through unchanged, so callers need not split mixed trees."""
    SYNCS.inc(site=site)
    return jax.device_get(tree)


def fetch_int(x, site: str) -> int:
    """Scalar sizing read (live-row count, capacity probe): one
    round-trip, one int."""
    SYNCS.inc(site=site)
    return int(jax.device_get(x))


def wait(x, site: str):
    """Block until ``x`` is computed (measurement sync): the point an
    async dispatch actually finishes, so the enclosing span/timer
    covers device time instead of call overhead. Returns ``x``."""
    SYNCS.inc(site=site)
    return jax.block_until_ready(x)


# Deliberate syncs OUTSIDE the boundary, id -> justification. Id form:
# "<relpath>:<dotted.unit.path>:<kind>" where kind names the sync
# (device_get | block_until_ready | asarray | int | float | bool |
# item | tolist). Stale entries (matching no finding) are findings.
DEVICE_SYNC_EXEMPT = {
    "presto_tpu/exec/profile.py:_profiled_compile_run:block_until_ready":
        "EXPLAIN ANALYZE execute-wall measurement: the sync IS the "
        "measurement, and it stays outside the boundary so profiling "
        "runs do not inflate the hot-path sync counter bench.py "
        "reports per query",
    "presto_tpu/exec/profile.py:_profiled_compile_run:asarray":
        "EXPLAIN ANALYZE ok-flag readback inside the measured execute "
        "window: kept raw beside the block_until_ready above so the "
        "profile's run_s includes the same readback the production "
        "ladder pays, without counting profiling syncs as hot-path "
        "syncs",
    "presto_tpu/obs/devprof.py:harvest:float":
        "compile-time cost harvest: the floats come from "
        "compiled.cost_analysis()'s host-side dict (XLA's static "
        "analysis), never from a device array — no transfer happens",
    "presto_tpu/obs/devprof.py:program_bytes:float":
        "arithmetic over the plain-dict cost summary harvest() "
        "produced (host floats persisted in progcache meta); no "
        "device value can reach here",
    "presto_tpu/obs/devprof.py:attribute:float":
        "attribution math over the harvested host-side cost summary "
        "and Python int row counts; no device value can reach here",
}
