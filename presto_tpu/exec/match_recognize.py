"""MATCH_RECOGNIZE execution: vectorized predicates + NFA matching.

TPU-native split of the reference's row-pattern machinery
(operator/window/matcher/Matcher.java NFA VM + IrRowPatternToProgram):
the per-row DEFINE predicates — the data-heavy part — evaluate
VECTORIZED over the sorted partition arrays (including the shifted
``$prev`` columns), producing one boolean array per pattern variable;
only the pattern automaton itself runs as a host loop over candidate
match positions (the reference's VM is row-at-a-time for this part
too). ONE ROW PER MATCH + AFTER MATCH SKIP PAST LAST ROW.

Thompson NFA with preference order: greedy quantifiers explore the
consume branch first; the first accepting path in preference order is
the SQL-required preferred match. A visited set per (state, position)
bounds the search linearly.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Column, Table
from presto_tpu.exec import hostsync as HS
from presto_tpu.sql import ast as A


# -- pattern -> NFA ----------------------------------------------------------


@dataclasses.dataclass
class _State:
    kind: str  # var | split | accept
    var: str | None = None
    nxt: int = -1
    alt: int = -1  # split: preferred branch is nxt, then alt


def compile_pattern(pattern) -> list[_State]:
    states: list[_State] = []

    def add(st: _State) -> int:
        states.append(st)
        return len(states) - 1

    def build(p, nxt: int) -> int:
        """Returns the entry state for pattern ``p`` continuing to
        ``nxt``."""
        if isinstance(p, A.PatVar):
            return add(_State("var", p.name.lower(), nxt))
        if isinstance(p, A.PatConcat):
            entry = nxt
            for part in reversed(p.parts):
                entry = build(part, entry)
            return entry
        if isinstance(p, A.PatAlt):
            entry = build(p.options[-1], nxt)
            for opt in reversed(p.options[:-1]):
                o = build(opt, nxt)
                entry = add(_State("split", None, o, entry))
            return entry
        if isinstance(p, A.PatQuant):
            lo, hi = p.min, p.max
            entry = nxt
            if hi is None:
                # loop: split(enter-body -> loop, exit) — greedy
                # prefers the body
                loop = add(_State("split", None, -1, nxt))
                body = build(p.term, loop)
                states[loop].nxt = body
                entry = loop
            else:
                for _ in range(hi - lo):
                    body = build(p.term, entry)
                    entry = add(_State("split", None, body, entry))
            for _ in range(lo):
                entry = build(p.term, entry)
            return entry
        raise TypeError(f"unknown pattern node {type(p).__name__}")

    accept = add(_State("accept"))
    start = build(pattern, accept)
    return states, start  # type: ignore[return-value]


def match_at(states, start: int, var_match: dict[str, np.ndarray],
             pos: int, end: int):
    """Preferred match starting at ``pos``: returns (last_pos_exclusive,
    classifier list of per-row variables) or None. Iterative DFS in
    preference order with (state, pos) dedupe."""
    stack = [(start, pos, ())]
    seen: set[tuple[int, int]] = set()
    while stack:
        st, i, path = stack.pop()
        if (st, i) in seen:
            continue
        seen.add((st, i))
        s = states[st]
        if s.kind == "accept":
            if i > pos:  # empty matches produce no row (subset)
                return i, list(path)
            continue
        if s.kind == "split":
            # LIFO stack: push the less-preferred branch first
            stack.append((s.alt, i, path))
            stack.append((s.nxt, i, path))
            continue
        # var consume
        if i < end and bool(var_match[s.var][i]):
            stack.append((s.nxt, i + 1, path + (s.var,)))
    return None


# -- operator ----------------------------------------------------------------


def evaluate(table: Table, node) -> Table:
    """Host-side MATCH_RECOGNIZE over a materialized input table.
    Returns the ONE-ROW-PER-MATCH output table."""
    import jax.numpy as jnp

    from presto_tpu.expr.compile import ExprCompiler, Val

    n = table.nrows
    live = (np.ones(n, bool) if table.mask is None
            else np.asarray(table.mask))
    idx = np.nonzero(live)[0]

    # sort by (partition, order) — numpy lexsort, least-significant last
    keys: list[np.ndarray] = []
    for o in reversed(node.orderings):
        col = table.columns[o.symbol]
        data = np.asarray(col.data)[idx]
        keys.append(-data if not o.ascending else data)
    for s in reversed(node.partition_by):
        keys.append(np.asarray(table.columns[s].data)[idx])
    order = (np.lexsort(keys) if keys
             else np.arange(len(idx)))
    ridx = idx[order]
    m = len(ridx)

    # partition boundaries in sorted order
    new_part = np.zeros(m, bool)
    if m:
        new_part[0] = True
    for s in node.partition_by:
        d = np.asarray(table.columns[s].data)[ridx]
        new_part[1:] |= d[1:] != d[:-1]
        pvalid = table.columns[s].valid
        if pvalid is not None:
            vv = np.asarray(pvalid)[ridx]
            new_part[1:] |= vv[1:] != vv[:-1]
    part_start_idx = np.nonzero(new_part)[0]

    # vectorized DEFINE predicates over sorted arrays + $prev shifts
    cols: dict[str, Val] = {}
    for sym, col in table.columns.items():
        data = np.asarray(col.data)[ridx]
        valid = (None if col.valid is None
                 else np.asarray(col.valid)[ridx])
        cols[sym] = Val(col.dtype, jnp.asarray(data),
                        None if valid is None else jnp.asarray(valid),
                        col.dictionary)
    referenced = set()
    for cond in node.defines.values():
        from presto_tpu.expr import ir as IR
        referenced |= IR.referenced_columns([cond])
    for ref in referenced:
        if "$prev" in ref:
            base, cnt = ref.rsplit("$prev", 1)
            k = int(cnt)
            src = cols[base]
            shifted = np.roll(np.asarray(src.data), k, axis=0)
            valid = (np.ones(m, bool) if src.valid is None
                     else np.asarray(src.valid))
            vshift = np.roll(valid, k)
            # rows whose PREV crosses a partition boundary are NULL
            pos_in_part = np.arange(m) - np.maximum.accumulate(
                np.where(new_part, np.arange(m), 0))
            vshift &= pos_in_part >= k
            cols[ref] = Val(src.dtype, jnp.asarray(shifted),
                            jnp.asarray(vshift), src.dictionary)

    c = ExprCompiler(cols)
    var_match: dict[str, np.ndarray] = {}
    pattern_vars = _pattern_vars(node.pattern)
    for var in pattern_vars:
        cond = node.defines.get(var)
        if cond is None:
            var_match[var] = np.ones(m, bool)  # undefined: always true
        else:
            v = c.compile(cond)
            data = np.asarray(v.data, dtype=bool)
            if v.valid is not None:
                data = data & np.asarray(v.valid)
            var_match[var] = data

    states, start_state = compile_pattern(node.pattern)

    # measure inputs evaluated once, vectorized
    measure_vals = {}
    for sym, kind, expr, _dtype in node.measures:
        if expr is not None:
            measure_vals[sym] = c.compile(expr)
    # one batched device->host fetch for ALL measures up front: reading
    # v.data / v.valid inside the per-match loop below would pay one
    # round-trip per match
    meas_host = {
        sym: HS.fetch((v.data, v.valid), site="match-measures")
        for sym, v in measure_vals.items()
    }

    out_rows: dict[str, list] = {s: [] for s in node.partition_by}
    out_meas: dict[str, list] = {sym: [] for sym, *_ in node.measures}
    out_valid: dict[str, list] = {sym: [] for sym, *_ in node.measures}
    match_no = 0
    bounds = list(part_start_idx) + [m]
    for b in range(len(bounds) - 1):
        lo, hi = bounds[b], bounds[b + 1]
        i = lo
        match_in_part = 0
        while i < hi:
            found = match_at(states, start_state, var_match, i, hi)
            if found is None:
                i += 1
                continue
            end, classifiers = found
            match_no += 1
            match_in_part += 1
            first_row, last_row = i, end - 1
            for s in node.partition_by:
                out_rows[s].append(int(ridx[first_row]))
            for sym, kind, _expr, _dtype in node.measures:
                if kind == "match_number":
                    out_meas[sym].append(match_in_part)
                    out_valid[sym].append(True)
                elif kind == "classifier":
                    out_meas[sym].append(classifiers[-1].upper())
                    out_valid[sym].append(True)
                else:
                    row = first_row if kind == "first" else last_row
                    data, vmask = meas_host[sym]
                    out_meas[sym].append(data[row])
                    ok = (True if vmask is None
                          else bool(vmask[row]))
                    out_valid[sym].append(ok)
            i = end  # AFTER MATCH SKIP PAST LAST ROW

    nout = match_no
    out_cols: dict[str, Column] = {}
    for s in node.partition_by:
        src = table.columns[s]
        rows = np.asarray(out_rows[s], dtype=np.int64)
        data = (np.asarray(src.data)[rows] if nout
                else np.empty(0, np.asarray(src.data).dtype))
        valid = None
        if src.valid is not None:
            valid = (np.asarray(src.valid)[rows] if nout
                     else np.empty(0, bool))
        out_cols[s] = Column(src.dtype, data, valid, src.dictionary)
    for sym, kind, expr, dtype in node.measures:
        valid = np.asarray(out_valid[sym], bool)
        if kind == "classifier":
            from presto_tpu.block import dictionary_encode
            codes, d = dictionary_encode(
                np.asarray(out_meas[sym], object))
            out_cols[sym] = Column(dtype, codes,
                                   None if valid.all() else valid, d)
        else:
            if expr is not None and expr.dtype and isinstance(
                    dtype, T.VarcharType):
                v = measure_vals[sym]
                out_cols[sym] = Column(
                    dtype, np.asarray(out_meas[sym]),
                    None if valid.all() else valid, v.dictionary)
            else:
                phys = dtype.physical_dtype
                out_cols[sym] = Column(
                    dtype, np.asarray(out_meas[sym], phys) if nout
                    else np.empty(0, phys),
                    None if valid.all() else valid, None)
    return Table(out_cols, nout, None)


def _pattern_vars(p) -> list[str]:
    out: list[str] = []

    def walk(q):
        if isinstance(q, A.PatVar):
            if q.name.lower() not in out:
                out.append(q.name.lower())
        elif isinstance(q, A.PatConcat):
            for x in q.parts:
                walk(x)
        elif isinstance(q, A.PatAlt):
            for x in q.options:
                walk(x)
        elif isinstance(q, A.PatQuant):
            walk(q.term)

    walk(p)
    return out
