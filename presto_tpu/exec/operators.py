"""Trace-time physical operators over masked columnar tables.

Each function takes/returns a DTable (dict of symbol -> Val plus a live
mask) during jit tracing. Static shapes: filters only update the live
mask; aggregation/join outputs have planner-chosen static capacities.

Operator parity map (reference core/trino-main/.../operator/):
- apply_filter/apply_project  <- FilterAndProjectOperator, PageProcessor
- apply_aggregate             <- HashAggregationOperator + GroupByHash
- apply_join                  <- HashBuilderOperator + LookupJoinOperator
- apply_semijoin              <- SetBuilderOperator + HashSemiJoinOperator
- apply_sort/topn/limit       <- OrderByOperator, TopNOperator, LimitOperator
- apply_distinct              <- DistinctLimitOperator/MarkDistinct family
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import kernels as K
from presto_tpu import types as T
from presto_tpu.expr import aggregates as A
from presto_tpu.expr import ir
from presto_tpu.expr.compile import ExprCompiler, Val, and_valid, cast_val
from presto_tpu.ops import hash as H
from presto_tpu.ops import segred
from presto_tpu.plan import nodes as N


@dataclasses.dataclass
class DTable:
    cols: dict[str, Val]
    live: object | None  # bool [n] or None (all live)
    n: int

    def live_mask(self):
        if self.live is None:
            return jnp.ones((self.n,), dtype=bool)
        return self.live


def _compiler(dt: DTable) -> ExprCompiler:
    return ExprCompiler(dt.cols)


def apply_filter(dt: DTable, predicate: ir.Expr) -> DTable:
    v = _compiler(dt).compile(predicate)
    keep = v.data if v.valid is None else (v.data & v.valid)  # null -> false
    live = keep if dt.live is None else (dt.live & keep)
    return DTable(dt.cols, live, dt.n)


def apply_project(dt: DTable, assignments: dict[str, ir.Expr]) -> DTable:
    c = _compiler(dt)
    out = {}
    for sym, expr in assignments.items():
        v = c.compile(expr)
        data = v.data
        if v.is_array:
            # literal arrays built from scalars have one row: broadcast
            # to the table's row count
            if data.shape[0] == 1 and dt.n != 1:
                data = jnp.broadcast_to(data, (dt.n,) + data.shape[1:])
                lengths = jnp.broadcast_to(v.lengths, (dt.n,))
                ev = (jnp.broadcast_to(
                    v.elem_valid, (dt.n,) + v.elem_valid.shape[1:])
                    if v.elem_valid is not None else None)
                valid = v.valid
                if valid is not None and valid.shape[0] == 1:
                    valid = jnp.broadcast_to(valid, (dt.n,))
                v = Val(v.dtype, data, valid, v.dictionary, lengths,
                        ev, v.map_keys)
        elif getattr(data, "ndim", 1) == 0:  # broadcast scalar literal
            data = jnp.broadcast_to(data, (dt.n,))
            valid = v.valid
            if valid is not None and getattr(valid, "ndim", 1) == 0:
                valid = jnp.broadcast_to(valid, (dt.n,))
            v = Val(v.dtype, data, valid, v.dictionary)
        elif (isinstance(v.dtype, T.DecimalType) and v.dtype.is_long
              and data.ndim == 1):  # scalar LONG decimal: [2] limbs
            data = jnp.broadcast_to(data, (dt.n, 2))
            valid = v.valid
            if valid is not None and getattr(valid, "ndim", 1) == 0:
                valid = jnp.broadcast_to(valid, (dt.n,))
            v = Val(v.dtype, data, valid, v.dictionary)
        out[sym] = v
    return DTable(out, dt.live, dt.n)


def _row_hash(dt: DTable, keys: list[str]):
    hs = []
    for k in keys:
        v = dt.cols[k]
        if v.is_string:
            hs.append(H.hash_string_column(v.data, v.dictionary, v.valid))
        elif getattr(v.data, "ndim", 1) == 2:
            # LONG decimal: both int64 limbs feed the row key (exactness
            # still comes from the limb secondary sort keys downstream)
            hs.append(H.hash_int_column(v.data[:, 0], v.valid))
            hs.append(H.hash_int_column(v.data[:, 1], v.valid))
        else:
            hs.append(H.hash_int_column(v.data, v.valid))
    return H.combine_hashes(hs)


# Max code-product capacity for the direct dictionary-code group-by path.
_DIRECT_GROUP_MAX = 1 << 16


def _long_key_operands(v: Val):
    """LONG decimal grouping identity as two u64 sort operands
    (order-preserving: sign-flipped high limb, then the low limb);
    NULL rows collapse to zeros (validity rides separately)."""
    from presto_tpu.ops import int128 as I
    khi, klo = I.sort_keys(v.data)
    if v.valid is not None:
        khi = jnp.where(v.valid, khi, jnp.uint64(0))
        klo = jnp.where(v.valid, klo, jnp.uint64(0))
    return khi, klo


def _unpack_long_key(khi, klo):
    """Inverse of _long_key_operands (modulo NULL collapsing): [n, 2]
    limbs."""
    from presto_tpu.ops import int128 as I
    return I.pack(klo, (khi ^ jnp.uint64(1 << 63)).astype(jnp.int64))


def _group_key_operand(v: Val):
    """Normalize a group-key column for exact key-identity sorting:
    NULL rows collapse to one value, NaNs to one bit pattern, and
    +-0.0 unify (SQL grouping equality), so equal keys are equal
    operands."""
    data = v.data
    if jnp.issubdtype(data.dtype, jnp.floating):
        bits = jnp.where(data == 0, jnp.zeros_like(data), data)
        bits = bits.view(jnp.int64 if data.dtype == jnp.float64
                         else jnp.int32)
        data = jnp.where(jnp.isnan(v.data),
                         jnp.full_like(bits, -1), bits)
    if v.valid is not None:
        data = jnp.where(v.valid, data, jnp.zeros_like(data))
    return data


def _direct_group_ids(dt: DTable, keys: list[str]):
    """Low-cardinality fast path: when every group key is a non-null
    dictionary-encoded column with a small code product, the group id is
    the mixed-radix code product — no hash table, no probe loop, no
    overflow retry (the analog of MultiChannelGroupByHash's dictionary /
    low-cardinality fast paths, MultiChannelGroupByHash.java:55).

    Returns (gid int32 [n], capacity, sizes) or None if inapplicable."""
    sizes = []
    for k in keys:
        v = dt.cols[k]
        if not v.is_string or v.valid is not None or v.dictionary is None:
            return None
        sizes.append(max(len(v.dictionary), 1))
    capacity = 1
    for s in sizes:
        capacity *= s
        if capacity > _DIRECT_GROUP_MAX:
            return None
    gid = jnp.zeros((dt.n,), dtype=jnp.int32)
    for k, size in zip(keys, sizes):
        code = jnp.clip(dt.cols[k].data.astype(jnp.int32), 0, size - 1)
        gid = gid * size + code
    return gid, capacity, sizes


def _agg_call_inputs(c: ExprCompiler, dt: DTable, call, live):
    """Prepared (data, weight, data2, data_valid, arg_type) for one
    aggregate call over the rows of ``dt`` (shared by the segment-op
    and sorted-scan fold paths)."""
    data2 = None
    data_valid = None
    if call.arg is not None:
        av = c.compile(call.arg)
        if call.fn == "checksum":
            # NULL rows contribute a fixed hash constant instead
            # of being excluded (checksums must see null counts)
            weight = live
        elif call.fn in A.BY_FNS:
            # min_by/max_by: a NULL x is a legal result; only
            # NULL comparison keys (arg2) exclude rows
            weight = live
            data_valid = av.valid
        else:
            weight = live if av.valid is None else (live & av.valid)
        data = A.prepare_arg(call.fn, av.data, av.dtype)
        if A.is_long_decimal(av.dtype) and getattr(
                data, "ndim", 1) == 1:
            # scalar long-decimal literal: [2] limbs -> [n, 2]
            data = jnp.broadcast_to(data, (dt.n, 2))
        if A.is_long_decimal(av.dtype) and getattr(
                data, "ndim", 1) == 2:
            if call.fn in ("sum", "avg", "min", "max",
                           "arbitrary", "count"):
                # int128 [n, 2] -> separate low/high limb columns so the
                # existing (data, data2) plumbing (sort payloads, state
                # columns) stays 1D throughout
                data, data2 = data[:, 0], data[:, 1]
            else:
                raise NotImplementedError(
                    f"{call.fn} over long decimals (precision > 18) "
                    "is not supported yet")
        if call.fn == "checksum" and av.valid is not None:
            data = jnp.where(av.valid, data,
                             jnp.uint64(0x2545F4914F6CDD1D))
        if getattr(data, "ndim", 1) == 0:
            data = jnp.broadcast_to(data, (dt.n,))
        arg_type = av.dtype
    else:
        weight = live
        data = jnp.ones((dt.n,), dtype=jnp.int64)
        arg_type = None
    if call.arg2 is not None:
        av2 = c.compile(call.arg2)
        if av2.valid is not None:
            weight = weight & av2.valid
        data2 = A.prepare_arg2(call.fn, av2.data, av2.dtype)
        if getattr(data2, "ndim", 1) == 0:
            data2 = jnp.broadcast_to(data2, (dt.n,))
    if call.mask is not None:
        mv = dt.cols[call.mask]
        weight = weight & mv.data
        if mv.valid is not None:
            weight = weight & mv.valid
    return data, weight, data2, data_valid, arg_type


def _apply_aggregate_sorted(dt: DTable, node: N.Aggregate, capacity: int,
                            c: ExprCompiler, live) -> tuple:
    """Grouped aggregation via one hash sort + segmented scans + one
    compaction sort (no group-table scatters, no random gathers: every
    per-row array rides the grouping sort as a payload, and the
    capacity-sized output is produced by a second multi-payload sort —
    see ops/segscan.py and SortedGroups.compact). Output contract
    matches the segment-op path: [capacity] rows, ok=False when the
    group count exceeds capacity."""
    # FD-reduced identity (plan/dense.py): when a subset of the group
    # keys determines the rest, only that subset hashes and sorts as
    # group identity; dependent keys (constant within each group) ride
    # as plain payloads
    id_keys = (node.fd_keys if node.fd_keys
               and set(node.fd_keys) <= set(node.group_keys)
               else node.group_keys)
    rh = _row_hash(dt, id_keys)
    is_final = node.step == N.AggStep.FINAL

    # assemble sort payloads: identity key columns first (they double
    # as SECONDARY SORT KEYS so group identity is the exact key tuple,
    # not the 64-bit hash — see SortedGroups), then per-call agg inputs
    payloads: list = []

    def _add(arr) -> int:
        payloads.append(arr)
        return len(payloads) - 1

    key_refs = []  # (sym, Val, data_idx, valid_idx)
    plain_keys = []  # float originals / FD-dependent keys ride outside
    for k in node.group_keys:
        v = dt.cols[k]
        if k not in id_keys:
            plain_keys.append((k, v, None if v.valid is None else v.valid))
            continue
        if getattr(v.data, "ndim", 1) == 2:  # LONG decimal key
            khi, klo = _long_key_operands(v)
            hi_idx, lo_idx = _add(khi), _add(klo)
            valid_idx = None if v.valid is None else _add(v.valid)
            key_refs.append((k, v, ("long", hi_idx, lo_idx), valid_idx))
            continue
        norm_idx = _add(_group_key_operand(v))
        valid_idx = None if v.valid is None else _add(v.valid)
        if jnp.issubdtype(v.data.dtype, jnp.floating):
            # the normalized operand is a bit view; keep the original
            # float data as a plain payload for output
            plain_keys.append((k, v, valid_idx))
        else:
            key_refs.append((k, v, norm_idx, valid_idx))
    num_key_payloads = len(payloads)
    for k, v, valid_ref in plain_keys:
        if isinstance(valid_ref, int) or valid_ref is None:
            valid_idx = valid_ref
        else:
            valid_idx = _add(valid_ref)
        if getattr(v.data, "ndim", 1) == 2:  # LONG decimal payload
            khi, klo = _long_key_operands(v)
            key_refs.append((k, v, ("long", _add(khi), _add(klo)),
                             valid_idx))
            continue
        key_refs.append((k, v, _add(v.data), valid_idx))

    call_refs: dict[str, tuple] = {}
    for sym, call in node.aggs.items():
        scan = call.fn in A.SCAN_FNS
        if is_final:
            sum_state = dt.cols.get(f"{sym}$sum")
            arg_type = sum_state.dtype if sum_state is not None else None
            if scan:
                idxs = {f: _add(dt.cols[f"{sym}${f}"].data)
                        for f in A.state_fields(call)}
                call_refs[sym] = ("merge", idxs, arg_type)
            else:
                call_refs[sym] = ("seg", None, arg_type)
        else:
            data, weight, data2, data_valid, arg_type = \
                _agg_call_inputs(c, dt, call, live)
            if scan:
                idxs = (_add(data), _add(weight),
                        None if data2 is None else _add(data2),
                        None if data_valid is None else _add(data_valid))
                call_refs[sym] = ("fold", idxs, arg_type)
            else:
                call_refs[sym] = ("seg", (data, weight, data2,
                                          data_valid), arg_type)

    sg = H.SortedGroups(rh, live, payloads, num_key_payloads)
    ok = sg.ngroups <= capacity
    sp = sg.payloads
    slots = None  # lazily built for segment-op fallbacks (sketches)

    # per-sorted-row arrays destined for the compaction sort
    compact_in: list = []

    def _adc(arr) -> int:
        compact_in.append(arr)
        return len(compact_in) - 1

    key_out = [(sym, v,
                ("long", _adc(sp[di[1]]), _adc(sp[di[2]]))
                if isinstance(di, tuple) else _adc(sp[di]),
                None if vi is None else _adc(sp[vi]))
               for sym, v, di, vi in key_refs]

    state_out: dict[str, dict] = {}
    seg_states: dict[str, dict] = {}
    arg_types: dict[str, object] = {}
    for sym, call in node.aggs.items():
        kind, refs, arg_type = call_refs[sym]
        arg_types[sym] = arg_type
        if kind == "fold":
            di, wi, d2i, dvi = refs
            st = A.scan_fold(
                call.fn, sp[di], sp[wi], sg,
                data2=None if d2i is None else sp[d2i],
                data_valid=None if dvi is None else sp[dvi],
                param=call.param)
            state_out[sym] = {f: _adc(arr) for f, arr in st.items()}
        elif kind == "merge":
            st = A.scan_merge(
                call.fn, {f: sp[i] for f, i in refs.items()},
                sg.live, sg)
            state_out[sym] = {f: _adc(arr) for f, arr in st.items()}
        else:  # segment-op fallback (2D sketch states can't ride sorts)
            if slots is None:
                slots = sg.slots()
            if is_final:
                fields = A.state_fields(call)
                seg_states[sym] = A.merge(
                    call.fn,
                    {f: dt.cols[f"{sym}${f}"].data for f in fields},
                    slots, capacity, live)
            else:
                data, weight, data2, data_valid = refs
                seg_states[sym] = A.fold(
                    call.fn, data, weight, slots, capacity,
                    data2=data2, data_valid=data_valid,
                    param=call.param)

    compacted, occupied = sg.compact(compact_in, capacity)

    out: dict[str, Val] = {}
    for sym, v, di, vi in key_out:
        valid = None if vi is None else compacted[vi]
        if isinstance(di, tuple):  # LONG decimal limbs
            data = _unpack_long_key(compacted[di[1]], compacted[di[2]])
            out[sym] = Val(v.dtype, data, valid, v.dictionary)
            continue
        out[sym] = Val(v.dtype, compacted[di], valid, v.dictionary)

    for sym, call in node.aggs.items():
        states = (seg_states[sym] if sym in seg_states else
                  {f: compacted[i] for f, i in state_out[sym].items()})
        out_dictionary = None
        if is_final:
            val_state = dt.cols.get(
                f"{sym}$xval" if call.fn in A.BY_FNS else f"{sym}$val")
            if val_state is not None:
                out_dictionary = val_state.dictionary
        if node.step == N.AggStep.PARTIAL:
            for f, arr in states.items():
                dictionary = None
                if f == "val" and call.arg is not None:
                    dictionary = _arg_dictionary(
                        c, call.arg2 if call.fn in A.BY_FNS
                        else call.arg)
                elif f == "xval":
                    dictionary = _arg_dictionary(c, call.arg)
                out[f"{sym}${f}"] = Val(
                    A.state_type(call, f), arr, None, dictionary)
        else:
            fdata, fvalid = A.finalize(call.fn, states, call.dtype,
                                       arg_types[sym], param=call.param)
            if out_dictionary is None and call.arg is not None:
                out_dictionary = _arg_dictionary(c, call.arg)
            out[sym] = Val(call.dtype, fdata, fvalid, out_dictionary)

    return DTable(out, occupied, capacity), ok


def apply_aggregate(dt: DTable, node: N.Aggregate, capacity: int) -> tuple:
    """Returns (DTable of [capacity] rows, ok flag)."""
    live = dt.live_mask()
    c = _compiler(dt)
    # FD-reduced keys carry dependent output columns the arithmetic
    # slot decode can't reproduce: those plans take the sorted path
    fd_reduced = (node.fd_keys
                  and set(node.fd_keys) < set(node.group_keys))
    direct = _direct_group_ids(dt, node.group_keys) \
        if node.group_keys and not fd_reduced else None

    if direct is not None:
        slots, capacity, sizes = direct
        occupancy = segred.segment_sum(
            live.astype(jnp.int32), slots, num_segments=capacity) > 0
        ok = jnp.asarray(True)
    elif node.group_keys:
        # hash-grouped path: sort-and-scan, no group-table scatters
        return _apply_aggregate_sorted(dt, node, capacity, c, live)
    else:
        # global aggregation: one group in slot 0
        slots = jnp.zeros((dt.n,), dtype=jnp.int32)
        occupancy = jnp.ones((capacity,), dtype=bool)  # capacity == 1
        ok = jnp.asarray(True)

    safe_slots = slots  # masked rows fold with weight 0, slot harmless
    out: dict[str, Val] = {}

    if direct is not None:
        out.update(_decode_direct_keys(dt, node.group_keys, sizes,
                                       capacity))

    is_final = node.step == N.AggStep.FINAL
    for sym, call in node.aggs.items():
        out_dictionary = None
        if is_final:
            states = {f: dt.cols[f"{sym}${f}"].data
                      for f in A.state_fields(call)}
            val_state = dt.cols.get(
                f"{sym}$xval" if call.fn in A.BY_FNS else f"{sym}$val")
            if val_state is not None:
                out_dictionary = val_state.dictionary
            states = A.merge(call.fn, states, safe_slots, capacity, live)
            sum_state = dt.cols.get(f"{sym}$sum")
            arg_type = sum_state.dtype if sum_state is not None else None
        else:
            data, weight, data2, data_valid, arg_type = \
                _agg_call_inputs(c, dt, call, live)
            states = A.fold(call.fn, data, weight, safe_slots, capacity,
                            data2=data2, data_valid=data_valid,
                            param=call.param)

        if node.step == N.AggStep.PARTIAL:
            for f, arr in states.items():
                dictionary = None
                if f == "val" and call.arg is not None:
                    dictionary = _arg_dictionary(
                        c, call.arg2 if call.fn in A.BY_FNS
                        else call.arg)
                elif f == "xval":
                    dictionary = _arg_dictionary(c, call.arg)
                out[f"{sym}${f}"] = Val(
                    A.state_type(call, f), arr, None, dictionary)
        else:
            fdata, fvalid = A.finalize(call.fn, states, call.dtype,
                                       arg_type, param=call.param)
            if out_dictionary is None and call.arg is not None:
                out_dictionary = _arg_dictionary(c, call.arg)
            out[sym] = Val(call.dtype, fdata, fvalid, out_dictionary)

    return DTable(out, occupancy, capacity), ok


def _decode_direct_keys(dt: DTable, keys: list[str], sizes: list[int],
                        capacity: int) -> dict[str, Val]:
    """Key columns of the direct group-by path, decoded arithmetically
    from the slot index (inverse of the mixed-radix code product)."""
    gid_range = jnp.arange(capacity, dtype=jnp.int32)
    rev: list = []
    for k, size in zip(reversed(keys), reversed(sizes)):
        rev.append((k, gid_range % size))
        gid_range = gid_range // size
    out: dict[str, Val] = {}
    for k, codes in reversed(rev):
        v = dt.cols[k]
        out[k] = Val(v.dtype, codes.astype(v.data.dtype), None,
                     v.dictionary)
    return out


def _arg_dictionary(c: ExprCompiler, arg: ir.Expr):
    """min/max over a string column keep its dictionary."""
    if isinstance(arg, ir.ColumnRef):
        v = c.columns.get(arg.name)
        if v is not None and v.is_string:
            return v.dictionary
    return None


def _verify_keys(left: DTable, right: DTable,
                 criteria: list[tuple[str, str]], probe_idx, gather):
    """Value-compare matched non-string join keys (64-bit row-hash
    collision defence — the analog of the reference's
    PagesHash.positionEqualsRow after the hash hit). String keys rely on
    content-based per-dictionary hashes (ops/hash.py blake2b), which a
    row-hash collision does not weaken."""
    eq = None
    for lk, rk in criteria:
        lv, rv = left.cols[lk], right.cols[rk]
        if lv.is_string or rv.is_string:
            continue
        ld = lv.data if probe_idx is None else lv.data[probe_idx]
        e = ld == rv.data[gather]
        eq = e if eq is None else (eq & e)
    return eq if eq is not None else True


def _and_key_valid(dt: DTable, keys: list[str], live):
    for k in keys:
        v = dt.cols[k]
        if v.valid is not None:
            live = live & v.valid
    return live


def _direct_probe(left: DTable, right: DTable, node: N.Join,
                  probe_live, build_live):
    """Direct-address probe for a dense unique build key (plan/dense.py
    hint): scatter build row indices into a span-sized table, gather at
    probe key offsets — no hashing, no sorts (one scatter + one gather
    vs sort-merge's two full-width sorts; TPU sorts cost ~6ns/row/pass).
    Returns (build_row int32 [left.n] (-1 = none), found bool)."""
    ci, lo, hi = node.dense_key
    span = hi - lo + 1
    lk, rk = node.criteria[ci]
    bkey = right.cols[rk].data.astype(jnp.int64)
    slot = (bkey - lo).astype(jnp.int32)
    table = jnp.full((span,), -1, dtype=jnp.int32)
    # last-wins on (planner-promised-impossible) duplicates, matching
    # the sort path's largest-source-index representative
    table = table.at[jnp.where(
        build_live & (bkey >= lo) & (bkey <= hi), slot, span)].max(
        jnp.arange(right.n, dtype=jnp.int32), mode="drop")
    pkey = left.cols[lk].data.astype(jnp.int64)
    in_range = (pkey >= lo) & (pkey <= hi)
    build_row = table[jnp.clip(pkey - lo, 0, span - 1).astype(jnp.int32)]
    found = probe_live & in_range & (build_row >= 0)
    return jnp.where(found, build_row, -1), found


def _verify_rest(left: DTable, right: DTable, node: N.Join,
                 probe_idx, gather):
    """Value-verify the non-dense criteria (the dense key matched by
    construction; remaining equalities are exact compares against the
    unique candidate row)."""
    ci = node.dense_key[0]
    rest = [c for i, c in enumerate(node.criteria) if i != ci]
    if not rest:
        return True
    return _verify_keys(left, right, rest, probe_idx, gather)


def apply_join(left: DTable, right: DTable, node: N.Join,
               capacity: int) -> tuple:
    """Hash join, probe side preserved (each probe row matches <= 1 build
    row — FK->PK). Returns (DTable, ok)."""
    lkeys = [lk for lk, _ in node.criteria]
    rkeys = [rk for _, rk in node.criteria]
    # SQL joins never match NULL keys: mask key-invalid rows out of both sides
    build_live = _and_key_valid(right, rkeys, right.live_mask())
    probe_live = _and_key_valid(left, lkeys, left.live_mask())

    if node.dense_key is not None:
        build_row, found = _direct_probe(left, right, node,
                                         probe_live, build_live)
        ok = jnp.asarray(True)
        gather = jnp.clip(build_row, 0, right.n - 1)
        verify = _verify_rest(left, right, node, None, gather)
        if verify is not True:
            found = found & verify
    else:
        # backend-dispatched lookup (presto_tpu/kernels/): Pallas
        # open-addressing build+probe on TPU (capacity-sized table,
        # ok=False on chain overflow -> capacity retry ladder), the
        # sorted-merge lookup as the XLA fallback (always ok)
        rh = _row_hash(right, rkeys)
        ph = _row_hash(left, lkeys)
        build_row, found, ok = K.dispatch("join_lookup")(
            rh, build_live, ph, probe_live, capacity)

        gather = jnp.clip(build_row, 0, right.n - 1)
        found = found & _verify_keys(left, right, node.criteria, None,
                                     gather)
    out = dict(left.cols)
    inner = node.join_type == N.JoinType.INNER
    for sym, v in right.cols.items():
        data = v.data[gather]
        if inner:
            # unmatched rows die via the live mask below, so the found
            # mask is redundant as per-column validity — omitting it
            # keeps build-side dictionary keys eligible for the direct
            # group-by fast path downstream
            valid = None if v.valid is None else v.valid[gather]
        else:
            valid = found if v.valid is None else (found & v.valid[gather])
        out[sym] = Val(v.dtype, data, valid, v.dictionary)

    if node.filter is not None:
        fv = ExprCompiler(out).compile(node.filter)
        match_ok = fv.data if fv.valid is None else (fv.data & fv.valid)
        found = found & match_ok

    if node.join_type == N.JoinType.INNER:
        live = probe_live & found
    elif node.join_type == N.JoinType.LEFT:
        # probe rows with NULL keys survive a LEFT join (they match
        # nothing): use the full live mask, not the key-valid one
        live = left.live_mask()
        # un-matched rows: right columns become NULL
        for sym in right.cols:
            v = out[sym]
            out[sym] = Val(v.dtype, v.data,
                           found if v.valid is None else (found & v.valid),
                           v.dictionary)
    else:
        raise NotImplementedError(f"join type {node.join_type}")
    return DTable(out, live, left.n), ok


def apply_multi_join(spine: DTable, builds: list[DTable],
                     node: "N.MultiJoin", growth: int = 1) -> tuple:
    """Fused multi-way INNER equi-join (plan/nodes.MultiJoin): one
    sequential probe walk over the spine's static width. Every build
    is unique (FK->PK) and residual-free by construction, so each step
    is one lookup whose gathered columns immediately become probe
    keys for later builds; a single live mask accumulates the
    conjunction of all matches. The cascade of binary joins this
    replaces materialized (and in segmented execution, compacted and
    re-uploaded) an intermediate DTable per join.

    Backend-dispatched (presto_tpu/kernels/): under
    ``kernel_backend=pallas`` the WHOLE chain runs as one Pallas
    probe-walk kernel over per-build open-addressing tables
    (kernels/multijoin.py — k probes while each spine tile is VMEM
    resident, no sorts); the XLA walk below is the fallback, one
    sorted lookup per step. ``growth`` scales every table capacity
    (the retry ladder's knob on chain overflow). Returns
    (DTable, ok) — ok is always True on the XLA path (sorted builds
    cannot overflow)."""
    # kernels self-note attribution: try_fused notes pallas only when
    # it actually runs; a declined chain records the XLA walk
    fused = K.dispatch("multijoin")(
        spine.cols, spine.live_mask(), spine.n,
        [(b.cols, b.live_mask(), b.n) for b in builds],
        node.criteria, growth)
    if fused is not None:
        gathers, live, ok = fused
        out = dict(spine.cols)
        for bdt, gather in zip(builds, gathers):
            for sym, v in bdt.cols.items():
                out[sym] = Val(
                    v.dtype, v.data[gather],
                    None if v.valid is None else v.valid[gather],
                    v.dictionary)
        return DTable(out, live, spine.n), ok
    K.note("xla:multijoin")
    out = dict(spine.cols)
    live = spine.live_mask()
    width = spine.n
    for bdt, crit in zip(builds, node.criteria):
        lkeys = [lk for lk, _ in crit]
        rkeys = [rk for _, rk in crit]
        acc = DTable(out, live, width)
        build_live = _and_key_valid(bdt, rkeys, bdt.live_mask())
        probe_live = _and_key_valid(acc, lkeys, live)
        rh = _row_hash(bdt, rkeys)
        _bsh, bsidx = H.sort_build_side(rh, build_live)
        ph = _row_hash(acc, lkeys)
        lo, count, found = H.probe_runs(rh, build_live, ph, probe_live)
        build_row = jnp.where(
            found, bsidx[jnp.clip(lo + count - 1, 0, bdt.n - 1)], -1)
        gather = jnp.clip(build_row, 0, bdt.n - 1)
        verify = _verify_keys(acc, bdt, crit, None, gather)
        if verify is not True:
            found = found & verify
        for sym, v in bdt.cols.items():
            # INNER: unmatched rows die via the live mask, so the found
            # mask is redundant as per-column validity (see apply_join)
            out[sym] = Val(v.dtype, v.data[gather],
                           None if v.valid is None else v.valid[gather],
                           v.dictionary)
        live = probe_live & found
    return DTable(out, live, width), jnp.asarray(True)


def concat_dtables(parts: list[DTable]) -> DTable:
    """Row-concatenate DTables with identical column sets (the hybrid
    join's hot + cold result union). Validity masks materialize where
    any part carries one; array columns keep their length/element-mask
    companions."""
    first = parts[0]
    cols: dict[str, Val] = {}
    total = sum(p.n for p in parts)
    for sym, v0 in first.cols.items():
        vs = [p.cols[sym] for p in parts]
        data = jnp.concatenate([v.data for v in vs])
        if any(v.valid is not None for v in vs):
            valid = jnp.concatenate([
                v.valid if v.valid is not None
                else jnp.ones((p.n,), dtype=bool)
                for v, p in zip(vs, parts)])
        else:
            valid = None
        lengths = ev = None
        if v0.is_array:
            lengths = jnp.concatenate([v.lengths for v in vs])
            if any(v.elem_valid is not None for v in vs):
                ev = jnp.concatenate([
                    v.elem_valid if v.elem_valid is not None
                    else jnp.ones(v.data.shape, dtype=bool)
                    for v in vs])
        cols[sym] = Val(v0.dtype, data, valid, v0.dictionary,
                        lengths, ev)
    live = jnp.concatenate([p.live_mask() for p in parts])
    return DTable(cols, live, total)


def apply_expand_join(left: DTable, right: DTable, node: N.Join,
                      capacity: int, out_capacity: int) -> tuple:
    """Expanding (many-to-many) hash join: every (probe, build) match
    becomes one output row (reference LookupJoinOperator + PositionLinks
    chains, operator/join/JoinProbe.java). Output has static capacity
    ``out_capacity``; overflow reported for host retry.

    Returns (DTable [out_capacity], table_ok, out_ok)."""
    lkeys = [lk for lk, _ in node.criteria]
    rkeys = [rk for _, rk in node.criteria]
    build_live = _and_key_valid(right, rkeys, right.live_mask())
    probe_live = _and_key_valid(left, lkeys, left.live_mask())
    full_join = node.join_type == N.JoinType.FULL
    left_join = node.join_type == N.JoinType.LEFT or full_join
    if left_join:
        # left-join preserves probe rows with NULL keys (they just match
        # nothing); only the probe lookup masks them out
        probe_rows_live = left.live_mask()
    else:
        probe_rows_live = probe_live

    rh = _row_hash(right, rkeys)
    _bsh, bsidx = H.sort_build_side(rh, build_live)
    ph = _row_hash(left, lkeys)
    lo, count, found = H.probe_runs(rh, build_live, ph, probe_live)
    t_ok = jnp.asarray(True)  # sorted build: no table, no overflow
    probe_idx, build_row, out_live, o_ok = H.expand_matches(
        lo, count, bsidx, found & probe_live,
        probe_rows_live, out_capacity, left_join)

    out: dict[str, Val] = {}
    for sym, v in left.cols.items():
        data = v.data[probe_idx]
        valid = None if v.valid is None else v.valid[probe_idx]
        out[sym] = Val(v.dtype, data, valid, v.dictionary)
    matched = build_row >= 0
    gather = jnp.clip(build_row, 0, right.n - 1)
    verify = _verify_keys(left, right, node.criteria, probe_idx, gather)
    if verify is not True and not left_join:
        out_live = out_live & (verify | ~matched)
    for sym, v in right.cols.items():
        data = v.data[gather]
        if left_join:
            valid = matched if v.valid is None \
                else (matched & v.valid[gather])
        else:
            # inner expansion emits matched rows only: matched is
            # redundant with out_live (see apply_join)
            valid = None if v.valid is None else v.valid[gather]
        out[sym] = Val(v.dtype, data, valid, v.dictionary)

    keep = matched
    f_ok = None
    if node.filter is not None:
        fv = ExprCompiler(out).compile(node.filter)
        f_ok = fv.data if fv.valid is None else (fv.data & fv.valid)
        if not left_join:
            out_live = out_live & f_ok
    if left_join and (f_ok is not None or verify is not True):
        # outer-join keep/revert pass: a match failing the residual
        # filter or the key value-verify is NOT a match (identity int
        # keys make the EMPTY-remap collision of combine_hashes
        # deterministic for INT64_MAX neighbours, so verify demotion is
        # a correctness path). A probe row whose slots ALL fail must
        # still emit exactly once, unmatched; its surviving collision
        # slots must die (reference JoinFilterFunction handling in
        # LookupJoinOperator — outer rows emit after filtering). Slots
        # of one probe row are contiguous, so "first slot" is where
        # probe_idx changes; revive it when no sibling slot survives.
        keep = matched & out_live
        if f_ok is not None:
            keep = keep & f_ok
        if verify is not True:
            keep = keep & verify
        surv = jax.ops.segment_max(
            keep.astype(jnp.int32), probe_idx,
            num_segments=left.n, indices_are_sorted=True)
        first = jnp.concatenate(
            [jnp.ones((1,), bool), probe_idx[1:] != probe_idx[:-1]])
        revert = (first & (surv[probe_idx] == 0)
                  & probe_rows_live[probe_idx] & out_live)
        out_live = keep | revert
        # right columns of reverted slots are NULL
        for sym, v in right.cols.items():
            data = out[sym].data
            valid = keep if v.valid is None \
                else (keep & v.valid[gather])
            out[sym] = Val(v.dtype, data, valid, v.dictionary)

    if full_join:
        # FULL = LEFT + the build rows no probe row matched, appended as
        # a build-sized tail region with NULL probe columns (reference
        # JoinNode.Type.FULL + LookupOuterOperator's unvisited-positions
        # pass, operator/join/LookupJoinOperator.java)
        nb = right.n
        matched_build = jnp.zeros((nb,), bool).at[jnp.where(
            keep & out_live, build_row, nb)].set(True, mode="drop")
        tail_live = right.live_mask() & ~matched_build
        zero = jnp.zeros((nb,), jnp.int32)
        out2: dict[str, Val] = {}
        for sym, v in out.items():
            if sym in left.cols:
                lv = left.cols[sym]
                tdata = lv.data[zero]  # values dead: all-NULL via valid
                tvalid = jnp.zeros((nb,), bool)
            else:
                rv = right.cols[sym]
                tdata = rv.data
                tvalid = rv.valid
            if v.valid is None and tvalid is None:
                valid = None
            else:
                va = (v.valid if v.valid is not None
                      else jnp.ones((out_capacity,), bool))
                vb = (tvalid if tvalid is not None
                      else jnp.ones((nb,), bool))
                valid = jnp.concatenate([va, vb])
            out2[sym] = Val(v.dtype, jnp.concatenate([v.data, tdata]),
                            valid, v.dictionary)
        live2 = jnp.concatenate([out_live, tail_live])
        return DTable(out2, live2, out_capacity + nb), t_ok, o_ok

    return DTable(out, out_live, out_capacity), t_ok, o_ok


def apply_semijoin(dt: DTable, filt: DTable, node: N.SemiJoin,
                   capacity: int) -> tuple:
    build_live = _and_key_valid(filt, node.filter_keys, filt.live_mask())
    probe_live = _and_key_valid(dt, node.source_keys, dt.live_mask())
    if node.dense_key is not None:
        # dense membership bitmap: one scatter + one gather, exact by
        # construction (value addressing); duplicates just re-set a bit
        lo, hi = node.dense_key
        span = hi - lo + 1
        bkey = filt.cols[node.filter_key].data.astype(jnp.int64)
        bits = jnp.zeros((span,), dtype=bool).at[jnp.where(
            build_live & (bkey >= lo) & (bkey <= hi),
            (bkey - lo).astype(jnp.int32), span)].set(True, mode="drop")
        pkey = dt.cols[node.source_key].data.astype(jnp.int64)
        in_range = (pkey >= lo) & (pkey <= hi)
        found = probe_live & in_range & bits[
            jnp.clip(pkey - lo, 0, span - 1).astype(jnp.int32)]
        ok = jnp.asarray(True)
    else:
        # backend-dispatched lookup, same dispatch as apply_join
        fh = _row_hash(filt, node.filter_keys)
        sh = _row_hash(dt, node.source_keys)
        build_row, found, ok = K.dispatch("join_lookup")(
            fh, build_live, sh, probe_live, capacity)
        found = found & _verify_keys(
            dt, filt, list(zip(node.source_keys, node.filter_keys)),
            None, jnp.clip(build_row, 0, filt.n - 1))
    out = dict(dt.cols)
    mark_valid = None
    if node.null_aware:
        # x IN (S) is NULL (not FALSE) when unmatched and either x is
        # NULL or S contains a NULL — three-valued logic that matters
        # under negation (NOT IN): such rows must NOT pass the filter
        bk = filt.cols[node.filter_keys[0]]
        build_has_null = (jnp.any(filt.live_mask() & ~bk.valid)
                          if bk.valid is not None else jnp.asarray(False))
        pk = dt.cols[node.source_keys[0]]
        probe_null = (~pk.valid if pk.valid is not None
                      else jnp.zeros((dt.n,), bool))
        # x IN (empty set) is definitively FALSE even for NULL x
        set_empty = ~jnp.any(filt.live_mask())
        mark_valid = found | set_empty | (~probe_null & ~build_has_null)
    out[node.output] = Val(T.BOOLEAN, found, mark_valid)
    return DTable(out, dt.live, dt.n), ok


def compact_dtable(dt: DTable, capacity: int) -> tuple:
    """Gather live rows to the front of a ``capacity``-row DTable (the
    page-compaction analog inside a traced program). Returns
    (DTable [capacity], ok); ok is False when live rows overflow the
    capacity (host retries with a grown capacity).

    Backend-dispatched (presto_tpu/kernels/compact.py): the Pallas
    kernel streams the mask + columns once, writing survivors densely
    from a running VMEM count; the XLA fallback is the nonzero+gather
    this always was. Stable order and the overflow flag are identical
    on both backends."""
    live = dt.live_mask()
    cnt = jnp.sum(live.astype(jnp.int32))
    ok = cnt <= capacity
    arrays: dict = {}
    for sym, v in dt.cols.items():
        arrays[f"{sym}!d"] = v.data
        if v.valid is not None:
            arrays[f"{sym}!v"] = v.valid
    out = K.dispatch("compact")(live, arrays, capacity)
    cols = {
        sym: Val(v.dtype, out[f"{sym}!d"], out.get(f"{sym}!v"),
                 v.dictionary)
        for sym, v in dt.cols.items()}
    return DTable(cols, jnp.arange(capacity) < cnt, capacity), ok


def apply_cross_general(left: DTable, right: DTable) -> DTable:
    """General nested-loop cross join: the full static product
    left.n x right.n (reference NestedLoopJoinOperator.java:46).
    Callers compact both sides first so the product is sized by live
    estimates, not input capacities."""
    nl, nr = left.n, right.n
    i = jnp.repeat(jnp.arange(nl, dtype=jnp.int32), nr)
    j = jnp.tile(jnp.arange(nr, dtype=jnp.int32), nl)
    out: dict[str, Val] = {}
    for sym, v in left.cols.items():
        out[sym] = Val(v.dtype, v.data[i],
                       None if v.valid is None else v.valid[i],
                       v.dictionary)
    for sym, v in right.cols.items():
        out[sym] = Val(v.dtype, v.data[j],
                       None if v.valid is None else v.valid[j],
                       v.dictionary)
    live = left.live_mask()[i] & right.live_mask()[j]
    return DTable(out, live, nl * nr)


def apply_cross_scalar(left: DTable, right: DTable) -> DTable:
    """Cross join against a single-row relation (uncorrelated scalar
    subquery; reference EnforceSingleRowNode + JoinNode w/o criteria):
    broadcast the scalar row's columns over the probe side."""
    rlive = right.live_mask()
    # index of the single live row (0 if none; validity handles empties)
    idx = jnp.argmax(rlive.astype(jnp.int32))
    any_live = jnp.any(rlive)
    out = dict(left.cols)
    for sym, v in right.cols.items():
        data = jnp.broadcast_to(v.data[idx],
                                (left.n,) + v.data.shape[1:])
        rv = any_live if v.valid is None else (any_live & v.valid[idx])
        valid = jnp.broadcast_to(rv, (left.n,))
        out[sym] = Val(v.dtype, data, valid, v.dictionary)
    return DTable(out, left.live, left.n)


def _unify_string_vals(vals: list[Val]) -> list[Val]:
    """Remap string Vals onto one shared sorted union dictionary."""
    dicts = [v.dictionary for v in vals]
    if all(d is dicts[0] for d in dicts):
        return vals
    union = np.unique(np.concatenate([d.astype("U") for d in dicts]))
    uobj = union.astype(object)
    out = []
    for v in vals:
        remap = jnp.asarray(
            np.searchsorted(union, v.dictionary.astype("U"))
            .astype(np.int32))
        out.append(Val(v.dtype, remap[v.data], v.valid, uobj))
    return out


def apply_union(parts: list[DTable], node: N.Union) -> DTable:
    """UNION ALL: concatenate columns (static total capacity = sum of
    input capacities), remapping each input's symbols per node.mappings
    and merging string dictionaries (reference plan/UnionNode.java)."""
    n = sum(p.n for p in parts)
    out: dict[str, Val] = {}
    for sym in node.symbols:
        dtype = node.types[sym]
        vals = []
        for p, mapping in zip(parts, node.mappings):
            v = p.cols[mapping[sym]]
            vals.append(v if v.is_string else cast_val(v, dtype))
        if isinstance(dtype, T.VarcharType):
            vals = _unify_string_vals(vals)
        long_dec = isinstance(dtype, T.DecimalType) and dtype.is_long

        def part_data(v, p):
            if long_dec:  # [n,2] / scalar [2] limbs -> [p.n, 2]
                return jnp.broadcast_to(
                    v.data if v.data.ndim == 2 else v.data[None, :],
                    (p.n, 2))
            return jnp.broadcast_to(v.data, (p.n,))

        data = jnp.concatenate([part_data(v, p)
                                for v, p in zip(vals, parts)])
        if any(v.valid is not None for v in vals):
            valid = jnp.concatenate([
                v.valid if v.valid is not None
                else jnp.ones((p.n,), dtype=bool)
                for v, p in zip(vals, parts)])
        else:
            valid = None
        out[sym] = Val(dtype, data, valid,
                       vals[0].dictionary if vals[0].is_string else None)
    live = jnp.concatenate([p.live_mask() for p in parts])
    return DTable(out, live, n)


def _sort_keys(dt: DTable, orderings: list[N.Ordering]) -> list:
    """Per-row sort key arrays: ascending lexicographic order over the
    returned list == the requested ordering (dead rows last, null
    placement per SQL semantics folded into the key values)."""
    live = dt.live_mask()
    keys = [(~live).astype(jnp.int32)]  # dead rows last
    for o in orderings:
        v = dt.cols[o.symbol]
        data = v.data
        if getattr(data, "ndim", 1) == 2:
            # LONG decimal: int128 limbs -> two u64 key levels
            # (sign-flipped high word, then the unsigned low word);
            # descending order complements both levels
            from presto_tpu.ops import int128 as I
            khi, klo = I.sort_keys(data)
            if not o.ascending:
                khi, klo = ~khi, ~klo
            if v.valid is not None:
                cls = jnp.where(v.valid, 0, 2 if _nulls_last(o) else -2
                                ).astype(jnp.int32)
                khi = jnp.where(v.valid, khi, jnp.uint64(0))
                klo = jnp.where(v.valid, klo, jnp.uint64(0))
                keys.append(cls)
            keys.append(khi)
            keys.append(klo)
            continue
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int32)
        is_float = jnp.issubdtype(data.dtype, jnp.floating)
        if not o.ascending:
            # ints reverse via bitwise NOT (~x = -x-1): monotone
            # decreasing with no INT_MIN negation wrap
            data = -data if is_float else ~data
        # Nulls and NaNs order via a separate class-key level rather
        # than folding into extreme data values: value < NaN < NULL
        # (reference NaN-is-largest + null-is-largest semantics, null
        # placement per _nulls_last). Folding would collide NULL/NaN
        # with genuine +-inf / INT_MAX data, and NaN would break
        # merge_runs_perm's rank counting (needs a total comparator) —
        # dead lanes can carry NaN from computed expressions even when
        # live rows never do.
        cls = None
        if is_float:
            nan = jnp.isnan(data)
            cls = jnp.where(nan, 1 if o.ascending else -1, 0
                            ).astype(jnp.int32)
            data = jnp.where(nan, jnp.zeros_like(data), data)
        if v.valid is not None:
            if cls is None:
                cls = jnp.zeros(data.shape, jnp.int32)
            cls = jnp.where(v.valid, cls, 2 if _nulls_last(o) else -2)
            data = jnp.where(v.valid, data, jnp.zeros_like(data))
        if cls is not None:
            keys.append(cls)
        keys.append(data)
    return keys


def _sort_perm(dt: DTable, orderings: list[N.Ordering]):
    keys = _sort_keys(dt, orderings)
    operands = tuple(keys) + (jnp.arange(dt.n, dtype=jnp.int32),)
    sorted_ops = jax.lax.sort(operands, num_keys=len(keys), is_stable=True)
    return sorted_ops[-1]


def merge_runs_perm(keys: list, k: int, m: int):
    """Permutation merging ``k`` presorted runs of ``m`` rows each
    (stored concatenated) into one sorted order — the kernel behind
    merge exchange / distributed sort (reference MergeOperator.java:44,
    docs/admin/dist-sort.rst).

    Each row's output position is its local rank plus, for every other
    run, the count of rows ordered before it — found by a vectorised
    binary search with the full lexicographic comparator, O(N·k·log m)
    elementwise work instead of re-sorting N rows (O(N·log^2 N)
    compare-exchange stages), with the expensive per-shard sorts running
    in parallel on their own devices. Ties break by (run, local rank),
    matching a stable sort of the concatenation. Key arrays must be
    NaN-free so the comparator is total — _sort_keys guarantees this by
    encoding NULL/NaN in a separate int32 class-key level and zeroing
    the data lanes underneath.
    """
    n = k * m
    run_of = jnp.arange(n, dtype=jnp.int32) // m
    local_rank = jnp.arange(n, dtype=jnp.int32) % m
    rank = local_rank
    # lower-bound binary search over [0, m] needs floor(log2 m)+1 halvings
    steps = m.bit_length()
    for j in range(k):
        run_keys = [kk[j * m:(j + 1) * m] for kk in keys]
        # ties in run j precede rows of later runs (stability)
        tie_after = run_of > j
        lo = jnp.zeros((n,), jnp.int32)
        hi = jnp.full((n,), m, jnp.int32)
        for _ in range(steps):
            mid = (lo + hi) >> 1
            lt = jnp.zeros((n,), bool)
            eq = jnp.ones((n,), bool)
            for rk, qk in zip(run_keys, keys):
                c = rk[mid]
                lt = lt | (eq & (c < qk))
                eq = eq & (c == qk)
            before = lt | (eq & tie_after)  # run[mid] orders before query
            open_ = lo < hi  # converged lanes must not move past hi
            lo = jnp.where(open_ & before, mid + 1, lo)
            hi = jnp.where(open_ & ~before, mid, hi)
        rank = rank + jnp.where(run_of == j, 0, lo)
    # rank is a permutation of 0..n-1; invert to a gather index
    return jnp.zeros((n,), jnp.int32).at[rank].set(
        jnp.arange(n, dtype=jnp.int32))


def merge_sorted_runs(dt: DTable, orderings: list[N.Ordering],
                      k: int) -> DTable:
    """Merge a table holding ``k`` concatenated presorted runs."""
    assert dt.n % k == 0
    perm = merge_runs_perm(_sort_keys(dt, orderings), k, dt.n // k)
    return _gather_table(dt, perm)


def head(dt: DTable, count: int) -> DTable:
    """Static slice of the first ``count`` rows (compaction after sort —
    the analog of a bounded PageBuilder flush before an exchange)."""
    c = min(count, dt.n)
    cols = {sym: Val(v.dtype, v.data[:c],
                     None if v.valid is None else v.valid[:c],
                     v.dictionary)
            for sym, v in dt.cols.items()}
    live = None if dt.live is None else dt.live[:c]
    return DTable(cols, live, c)


def _nulls_last(o: N.Ordering) -> bool:
    if o.nulls_first is None:
        # Trino default: nulls last in ASC, first in DESC (null = largest)
        return o.ascending
    return not o.nulls_first


def _gather_table(dt: DTable, perm) -> DTable:
    out = {}
    for sym, v in dt.cols.items():
        out[sym] = Val(v.dtype, v.data[perm],
                       None if v.valid is None else v.valid[perm],
                       v.dictionary)
    live = None if dt.live is None else dt.live[perm]
    return DTable(out, live, dt.n)


def apply_sort(dt: DTable, orderings: list[N.Ordering]) -> DTable:
    perm = _sort_perm(dt, orderings)
    return _gather_table(dt, perm)


def apply_topn(dt: DTable, count: int, orderings: list[N.Ordering]) -> DTable:
    out = apply_sort(dt, orderings)
    live = out.live_mask() & (jnp.arange(dt.n) < count)
    return DTable(out.cols, live, dt.n)


def apply_limit(dt: DTable, count: int, offset: int = 0) -> DTable:
    live = dt.live_mask()
    pos = jnp.cumsum(live.astype(jnp.int64))
    keep = (pos > offset) & (pos <= offset + count)
    return DTable(dt.cols, live & keep, dt.n)


def _keys_equal_prev(vals: list[Val], sorted_perm) -> object:
    """bool[n]: row i's key tuple equals row i-1's (in sorted order).
    Exact value comparison (not hashes). Row 0 is always False."""
    n = sorted_perm.shape[0]
    eq = jnp.ones((n,), dtype=bool)
    for v in vals:
        d = v.data[sorted_perm]
        pair_eq = d[1:] == d[:-1]
        if pair_eq.ndim == 2:  # LONG decimal limbs: equal iff both are
            pair_eq = pair_eq.all(axis=-1)
        same = jnp.concatenate(
            [jnp.zeros((1,), bool), pair_eq])
        if v.valid is not None:
            vv = v.valid[sorted_perm]
            both_null = jnp.concatenate(
                [jnp.zeros((1,), bool), ~vv[1:] & ~vv[:-1]])
            same_valid = jnp.concatenate(
                [jnp.zeros((1,), bool), vv[1:] == vv[:-1]])
            same = (same | both_null) & same_valid
        eq = eq & same
    if not vals:
        return jnp.ones((n,), dtype=bool).at[0].set(False)
    return eq.at[0].set(False)


def apply_window(dt: DTable, node: N.Window) -> DTable:
    """Window functions: sort by (partition, order) keys, compute ranks /
    running & full-partition aggregates with scans over the sorted
    layout, scatter results back to the original row order.

    TPU-native reformulation of the reference's WindowOperator +
    PagesIndex (operator/WindowOperator.java:70, PagesIndex.java:79):
    where the reference walks partitions row-by-row, every function here
    is a vectorised prefix-scan/segment reduction over the sorted array.
    """
    n = dt.n
    live = dt.live_mask()
    part_orderings = [N.Ordering(s) for s in node.partition_by]
    perm = _sort_perm(dt, part_orderings + list(node.orderings))
    inv = jnp.zeros((n,), jnp.int32).at[perm].set(
        jnp.arange(n, dtype=jnp.int32))

    pvals = [dt.cols[s] for s in node.partition_by]
    ovals = [dt.cols[o.symbol] for o in node.orderings]
    slive = live[perm]
    same_part = _keys_equal_prev(pvals, perm) & slive \
        & jnp.concatenate([jnp.zeros((1,), bool), slive[:-1]])
    same_peer = same_part & _keys_equal_prev(pvals + ovals, perm)

    idx = jnp.arange(n, dtype=jnp.int64)
    # index of this row's partition start / peer-group start: running max
    # over boundary markers
    part_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(same_part, jnp.int64(-1), idx))
    peer_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(same_peer, jnp.int64(-1), idx))

    # partition / peer-group END positions (reverse running min over
    # boundary markers) — frames and value functions need both ends
    is_last_of_part = jnp.concatenate(
        [part_start[1:] != part_start[:-1], jnp.ones((1,), bool)])
    part_end = jax.lax.associative_scan(
        jnp.minimum, jnp.where(is_last_of_part, idx, jnp.int64(n)),
        reverse=True)
    is_last_of_peer = jnp.concatenate(
        [peer_start[1:] != peer_start[:-1], jnp.ones((1,), bool)])
    peer_end = jax.lax.associative_scan(
        jnp.minimum, jnp.where(is_last_of_peer, idx, jnp.int64(n)),
        reverse=True)

    out = dict(dt.cols)
    c = ExprCompiler({s: Val(v.dtype, v.data[perm],
                             None if v.valid is None else v.valid[perm],
                             v.dictionary)
                      for s, v in dt.cols.items()})

    key_val = (c.columns.get(node.orderings[0].symbol)
               if len(node.orderings) == 1 else None)
    fctx = {"orderings": node.orderings, "same_peer": same_peer,
            "same_part": same_part, "peer_start": peer_start,
            "peer_end": peer_end, "key": key_val}
    for sym, call in node.functions.items():
        data, valid, dictionary = _window_fn(
            call, c, idx, part_start, peer_start, part_end, peer_end,
            same_part, slive, n, fctx)
        # scatter back to original order
        data = data[inv]
        valid = None if valid is None else valid[inv]
        out[sym] = Val(call.dtype, data, valid, dictionary)
    return DTable(out, dt.live, n)


def _window_fn(call: N.WindowCall, c: ExprCompiler, idx, part_start,
               peer_start, part_end, peer_end, same_part, slive, n,
               fctx=None):
    fn = call.fn
    if fn == "row_number":
        return (idx - part_start + 1), None, None
    if fn == "rank":
        return (peer_start - part_start + 1), None, None
    if fn == "dense_rank":
        new_peer = ~jnp.concatenate(
            [jnp.zeros((1,), bool), peer_start[1:] == peer_start[:-1]])
        peer_ord = jnp.cumsum(new_peer.astype(jnp.int64))
        at_start = peer_ord[jnp.clip(part_start, 0, n - 1)]
        return peer_ord - at_start + 1, None, None
    if fn == "percent_rank":
        rank = (peer_start - part_start).astype(jnp.float64)
        rows = (part_end - part_start).astype(jnp.float64)
        return jnp.where(rows > 0, rank / jnp.maximum(rows, 1), 0.0), \
            None, None
    if fn == "cume_dist":
        rows = (part_end - part_start + 1).astype(jnp.float64)
        return (peer_end - part_start + 1).astype(jnp.float64) / rows, \
            None, None
    if fn == "ntile":
        buckets = int(call.args[0].value)
        pos = idx - part_start
        rows = part_end - part_start + 1
        q, r = rows // buckets, rows % buckets
        # the first r buckets get q+1 rows (SQL ntile split)
        big_span = (q + 1) * r
        in_big = pos < big_span
        bucket = jnp.where(
            in_big, pos // jnp.maximum(q + 1, 1),
            r + (pos - big_span) // jnp.maximum(q, 1))
        return jnp.clip(bucket, 0, buckets - 1) + 1, None, None
    if fn in ("first_value", "last_value", "nth_value"):
        v = c.compile(call.args[0])
        lo, hi = _frame_bounds(call, idx, part_start, part_end,
                               peer_end, fctx)
        if fn == "first_value":
            at = lo
        elif fn == "last_value":
            at = hi
        else:
            k = int(call.args[1].value)
            at = lo + (k - 1)
        in_frame = (at >= lo) & (at <= hi) & (hi >= lo)
        src = jnp.clip(at, 0, n - 1).astype(jnp.int32)
        data = v.data[src]
        valid = in_frame if v.valid is None else (in_frame
                                                  & v.valid[src])
        return data, valid, v.dictionary
    if fn in ("lag", "lead"):
        v = c.compile(call.args[0])
        offset = 1
        if len(call.args) > 1:
            offset = int(call.args[1].value)  # planner enforces literal
        shift = -offset if fn == "lag" else offset
        src = jnp.clip(idx + shift, 0, n - 1).astype(jnp.int32)
        in_part = (part_start[src] == part_start) & \
            (src == idx + shift)
        data = v.data[src]
        valid = in_part if v.valid is None else (in_part & v.valid[src])
        return data, valid, v.dictionary
    if fn in ("sum", "count", "avg", "min", "max"):
        if call.args:
            v = c.compile(call.args[0])
            if getattr(v.data, "ndim", 1) == 2:
                raise NotImplementedError(
                    "window aggregates over long decimals "
                    "(precision > 18) are not supported yet")
            w = slive if v.valid is None else (slive & v.valid)
            vals = v.data
        else:
            v = None
            w = slive
            vals = jnp.ones((n,), jnp.int64)
        restart = ~same_part  # new partition begins (row 0 included)
        if fn == "count":
            vals = jnp.ones((n,), jnp.int64)
        if jnp.issubdtype(vals.dtype, jnp.integer):
            vals = vals.astype(jnp.int64)

        if (call.range_frame is not None
                or call.groups_frame is not None
                or (call.rows_frame is not None and (
                    call.rows_frame[0] is not None
                    or call.rows_frame[1] is not None))):
            return _frame_agg(call, fn, v, vals, w, idx, part_start,
                              part_end, restart, n, fctx)

        if call.rows_frame == (None, None) \
                or call.frame == "full_partition":
            # ROWS UNBOUNDED..UNBOUNDED == the whole partition
            frame_at = None
        elif call.frame == "rows_unbounded_current":
            # ROWS frame: ends exactly at the current row (peers excluded)
            frame_at = jnp.clip(idx, 0, n - 1)
        elif call.frame != "full_partition":
            # RANGE default includes the whole peer group — the running
            # value is the segmented scan taken at the END of this
            # row's peer group
            frame_at = jnp.clip(peer_end, 0, n - 1)
        else:
            frame_at = None

        def run_scan(masked, op):
            scanned = _segmented_scan(masked, restart, op)
            if frame_at is not None:
                return scanned[frame_at]
            # full partition: value at partition's last row
            return scanned[jnp.clip(part_end, 0, n - 1)]

        cnt = run_scan(w.astype(jnp.int64), jnp.add)
        if fn == "count":
            return cnt, None, None
        if fn in ("sum", "avg"):
            masked = jnp.where(w, vals, jnp.zeros((), vals.dtype))
            total = run_scan(masked, jnp.add)
            if fn == "avg":
                sf = total.astype(jnp.float64)
                if v is not None and isinstance(v.dtype, T.DecimalType):
                    sf = sf / v.dtype.unscale_factor
                return sf / jnp.maximum(cnt, 1), cnt > 0, None
            return total, cnt > 0, None
        if fn == "max":
            sentinel = jnp.asarray(
                jnp.iinfo(vals.dtype).min if jnp.issubdtype(
                    vals.dtype, jnp.integer) else -jnp.inf, vals.dtype)
            run = run_scan(jnp.where(w, vals, sentinel), jnp.maximum)
        else:
            sentinel = jnp.asarray(
                jnp.iinfo(vals.dtype).max if jnp.issubdtype(
                    vals.dtype, jnp.integer) else jnp.inf, vals.dtype)
            run = run_scan(jnp.where(w, vals, sentinel), jnp.minimum)
        return run, cnt > 0, (v.dictionary if v is not None else None)
    raise NotImplementedError(f"window function {fn}")


def _frame_bounds(call: N.WindowCall, idx, part_start, part_end,
                  peer_end, fctx=None):
    """Inclusive sorted-position frame [lo, hi] for value functions and
    framed aggregates. Default (no explicit frame): RANGE UNBOUNDED
    PRECEDING..CURRENT ROW = partition start .. peer-group end."""
    if call.range_frame is not None or call.groups_frame is not None:
        return _dynamic_frame_bounds(call, fctx, idx, part_start,
                                     part_end)
    rf = call.rows_frame
    if rf is not None:
        p, f = rf
        lo = part_start if p is None else jnp.maximum(idx - p,
                                                      part_start)
        hi = part_end if f is None else jnp.minimum(idx + f, part_end)
        return lo, hi
    if call.frame == "full_partition":
        return part_start, part_end
    if call.frame == "rows_unbounded_current":
        return part_start, idx
    return part_start, peer_end


def _bounded_bsearch(vals, targets, lo0, hi0, left: bool, n: int):
    """Per-row binary search: the insertion position of ``targets[i]``
    in ascending ``vals`` restricted to [lo0[i], hi0[i]) — the
    partition-respecting vectorized searchsorted behind RANGE frames
    (log2(n) gather rounds; reference window/RangeFraming.java walks
    row-at-a-time from the previous frame instead)."""

    def body(_k, lh):
        lo, hi = lh
        mid = (lo + hi) >> 1
        v = vals[jnp.clip(mid, 0, n - 1).astype(jnp.int32)]
        go = (v < targets) if left else (v <= targets)
        active = lo < hi
        return (jnp.where(active & go, mid + 1, lo),
                jnp.where(active & ~go, mid, hi))

    iters = max(int(n - 1).bit_length(), 1) + 1
    lo, hi = jax.lax.fori_loop(
        0, iters, body,
        (lo0.astype(jnp.int64), hi0.astype(jnp.int64)))
    return lo


def _dynamic_frame_bounds(call: N.WindowCall, fctx, idx, part_start,
                          part_end):
    """Inclusive [lo, hi] sorted positions of a value-based RANGE or a
    GROUPS frame (reference window/RangeFraming.java,
    GroupsFraming.java).

    GROUPS: peer groups carry a GLOBALLY ascending dense id (cumsum of
    group starts), so both bounds are one vectorized searchsorted each,
    clamped into the partition. RANGE: the sort key is ascending within
    each partition's non-null span, so bounds come from a
    partition-bounded binary search over [key - preceding,
    key + following]; null-key rows frame over their peer group (all
    nulls), and UNBOUNDED sides keep whole-partition bounds (nulls
    included), matching the reference's null handling."""
    n = idx.shape[0]
    peer_start, peer_end = fctx["peer_start"], fctx["peer_end"]
    if call.groups_frame is not None:
        p, f = call.groups_frame
        gg = jnp.cumsum((~fctx["same_peer"]).astype(jnp.int64))
        lo = part_start if p is None else jnp.maximum(
            jnp.searchsorted(gg, gg - jnp.int64(p), side="left"),
            part_start)
        hi = part_end if f is None else jnp.minimum(
            jnp.searchsorted(gg, gg + jnp.int64(f), side="right") - 1,
            part_end)
        return lo, hi

    p, f = call.range_frame
    o = fctx["orderings"][0]
    kv = fctx["key"]
    if jnp.issubdtype(kv.data.dtype, jnp.floating):
        key = kv.data.astype(jnp.float64)
        pv = jnp.float64(0 if p is None else p)
        fv = jnp.float64(0 if f is None else f)
    else:
        key = kv.data.astype(jnp.int64)
        pv = jnp.int64(0 if p is None else p)
        fv = jnp.int64(0 if f is None else f)
    if not o.ascending:
        # descending keys negate into an ascending search; PRECEDING
        # still points at the partition start side
        key = -key
    valid = kv.valid
    if valid is None:
        nn_start, nn_end = part_start, part_end
        isnull = None
    else:
        isnull = ~valid
        restart = ~fctx["same_part"]
        npref = _segmented_scan(isnull.astype(jnp.int64), restart,
                                jnp.add)
        tot = npref[jnp.clip(part_end, 0, n - 1)]
        if _nulls_last(o):
            nn_start, nn_end = part_start, part_end - tot
        else:
            nn_start, nn_end = part_start + tot, part_end
    lo = part_start if p is None else jnp.maximum(
        _bounded_bsearch(key, key - pv, nn_start, nn_end + 1, True, n),
        part_start)
    hi = part_end if f is None else jnp.minimum(
        _bounded_bsearch(key, key + fv, nn_start, nn_end + 1, False,
                         n) - 1,
        part_end)
    if isnull is not None:
        # a null-key row's offset frame is its peer group (all nulls)
        if p is not None:
            lo = jnp.where(isnull, peer_start, lo)
        if f is not None:
            hi = jnp.where(isnull, peer_end, hi)
    return lo, hi


def _sparse_minmax(masked, lo, hi, op, ident, n: int):
    """min/max over arbitrary inclusive [lo, hi] spans via a doubling
    sparse table: tables[k][i] covers [i, i + 2^k), a query is
    op(T[k][lo], T[k][hi-2^k+1]) with k = floor(log2(width)) — log2(n)
    elementwise passes to build, two 2D gathers to query (the
    RMQ-sparse-table classic; the reference's per-row accumulator loop
    has no vectorized analog)."""
    if n > (1 << 23):
        raise NotImplementedError(
            "doubly-bounded RANGE/GROUPS min/max frames over >8M "
            "sorted rows")
    levels = [masked]
    t = masked
    k = 1
    while (1 << k) <= n:
        sh = 1 << (k - 1)
        shifted = jnp.concatenate(
            [t[sh:], jnp.full((sh,), ident, t.dtype)])
        t = op(t, shifted)
        levels.append(t)
        k += 1
    table = jnp.stack(levels)  # [K, n]
    width = jnp.maximum(hi - lo + 1, 1)
    kq = jnp.floor(jnp.log2(width.astype(jnp.float64))).astype(
        jnp.int32)
    kq = jnp.clip(kq, 0, len(levels) - 1)
    span = jnp.left_shift(jnp.int64(1), kq.astype(jnp.int64))
    a = table[kq, jnp.clip(lo, 0, n - 1).astype(jnp.int32)]
    b = table[kq, jnp.clip(hi - span + 1, 0, n - 1).astype(jnp.int32)]
    return op(a, b)


def _frame_agg(call: N.WindowCall, fn: str, v, vals, w, idx,
               part_start, part_end, restart, n, fctx=None):
    """Aggregate over a general ROWS/RANGE/GROUPS frame (reference
    window/RowsFraming.java, RangeFraming.java, GroupsFraming.java).
    sum/count/avg difference two points of the segmented prefix scan;
    one-sided-unbounded min/max take a (possibly reversed) running
    scan; doubly-bounded min/max unroll one static shift+select pass
    per frame offset for ROWS (frames in practice are narrow) and use
    a doubling sparse table for value/group frames whose width is
    data-dependent."""
    if call.rows_frame is not None:
        p, f = call.rows_frame
        lo = part_start if p is None else jnp.maximum(idx - p,
                                                      part_start)
        hi = part_end if f is None else jnp.minimum(idx + f, part_end)
        rows_static = True
    else:
        p, f = (call.range_frame if call.range_frame is not None
                else call.groups_frame)
        lo, hi = _dynamic_frame_bounds(call, fctx, idx, part_start,
                                       part_end)
        rows_static = False
    empty = hi < lo
    hi_c = jnp.clip(hi, 0, n - 1).astype(jnp.int32)
    lo_c = jnp.clip(lo, 0, n - 1).astype(jnp.int32)

    def span_sum(masked):
        s = _segmented_scan(masked, restart, jnp.add)
        at_hi = s[hi_c]
        prev = s[jnp.clip(lo_c - 1, 0, n - 1)]
        has_prev = lo > part_start
        return jnp.where(empty, 0, at_hi - jnp.where(has_prev, prev, 0))

    cnt = span_sum(w.astype(jnp.int64))
    if fn == "count":
        return cnt, None, None
    if fn in ("sum", "avg"):
        total = span_sum(jnp.where(w, vals, jnp.zeros((), vals.dtype)))
        if fn == "avg":
            sf = total.astype(jnp.float64)
            if v is not None and isinstance(v.dtype, T.DecimalType):
                sf = sf / v.dtype.unscale_factor
            return sf / jnp.maximum(cnt, 1), cnt > 0, None
        return total, cnt > 0, None

    # min/max: sparse table over masked values
    is_max = fn == "max"
    if jnp.issubdtype(vals.dtype, jnp.integer):
        ident = jnp.asarray(jnp.iinfo(vals.dtype).min if is_max
                            else jnp.iinfo(vals.dtype).max, vals.dtype)
    else:
        ident = jnp.asarray(-jnp.inf if is_max else jnp.inf,
                            vals.dtype)
    op = jnp.maximum if is_max else jnp.minimum
    masked = jnp.where(w, vals, ident)
    if p is None or f is None:
        # one-sided unbounded: running scan (possibly reversed) taken
        # at the bounded end
        if p is None:
            s = _segmented_scan(masked, restart, op)
            run = s[hi_c]
        else:
            rrestart = jnp.concatenate(
                [restart[1:], jnp.ones((1,), bool)])
            s = _rsegmented_scan(masked, rrestart, op)
            run = s[lo_c]
        return jnp.where(empty, ident, run), cnt > 0, \
            (v.dictionary if v is not None else None)
    if not rows_static:
        res = _sparse_minmax(masked, lo, hi, op, ident, n)
        return jnp.where(empty, ident, res), cnt > 0, \
            (v.dictionary if v is not None else None)
    # bounded frame: one static shift + select per offset (width total
    # elementwise passes, no gathers; frames in practice are narrow —
    # moving averages of a few rows)
    width = int(p) + int(f) + 1
    if width > 1024:
        raise NotImplementedError(
            f"ROWS frame of width {width} (bounded min/max frames "
            "support width <= 1024)")
    res = jnp.full((n,), ident, masked.dtype)
    for d in range(-int(p), int(f) + 1):
        if d < 0:
            shifted = jnp.concatenate(
                [jnp.full((-d,), ident, masked.dtype), masked[:d]])
        elif d > 0:
            shifted = jnp.concatenate(
                [masked[d:], jnp.full((d,), ident, masked.dtype)])
        else:
            shifted = masked
        pos = idx + d
        inside = (pos >= lo) & (pos <= hi)
        res = op(res, jnp.where(inside, shifted, ident))
    return jnp.where(empty, ident, res), cnt > 0, \
        (v.dictionary if v is not None else None)


def _rsegmented_scan(vals, restart_rev, op):
    """Reverse segmented inclusive scan (restart flags mark segment
    ENDS)."""

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf

    out, _ = jax.lax.associative_scan(combine, (vals, restart_rev),
                                      reverse=True)
    return out


def _segmented_scan(vals, restart, op):
    """Inclusive scan that restarts wherever ``restart`` is True."""

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf

    out, _ = jax.lax.associative_scan(combine, (vals, restart))
    return out


def apply_unnest(dt: DTable, node: N.Unnest) -> DTable:
    """Expand array elements into rows (reference UnnestOperator over
    UnnestNode): output row (i, j) carries source row i's columns and
    each array's j-th element; static output size n * max_capacity.
    Multiple arrays zip to the longest length (NULL-padding shorter
    ones); NULL arrays produce no rows."""
    arrays = [dt.cols[s] for s in node.array_syms]
    cap = max(a.data.shape[1] for a in arrays)
    n = dt.n
    live = dt.live_mask()

    # per-row zip length: max of array lengths (NULL array counts 0)
    zlen = None
    for a in arrays:
        ln = a.lengths
        if a.valid is not None:
            ln = jnp.where(a.valid, ln, 0)
        zlen = ln if zlen is None else jnp.maximum(zlen, ln)

    out: dict[str, Val] = {}
    for sym, v in dt.cols.items():
        if sym in node.array_syms and sym not in node.out_syms:
            continue  # consumed arrays drop from the output
        data = jnp.repeat(v.data, cap, axis=0)
        valid = (None if v.valid is None
                 else jnp.repeat(v.valid, cap, axis=0))
        out[sym] = Val(v.dtype, data, valid, v.dictionary,
                       None if v.lengths is None
                       else jnp.repeat(v.lengths, cap, axis=0),
                       None if v.elem_valid is None
                       else jnp.repeat(v.elem_valid, cap, axis=0))
    j = jnp.tile(jnp.arange(cap, dtype=jnp.int32), n)
    for osym, asym in zip(node.out_syms, node.array_syms):
        a = dt.cols[asym]
        acap = a.data.shape[1]
        data2, em2 = a.data, a.elem_valid
        if acap != cap:  # re-pad to the common capacity
            data2 = jnp.pad(data2, [(0, 0), (0, cap - acap)])
            if em2 is not None:
                em2 = jnp.pad(em2, [(0, 0), (0, cap - acap)])
        flat = data2.reshape(n * cap)
        em = em2.reshape(n * cap) if em2 is not None else None
        within = j < jnp.repeat(a.lengths, cap)
        if a.valid is not None:
            within = within & jnp.repeat(a.valid, cap)
        valid = within if em is None else (within & em)
        out[osym] = Val(node.out_types[osym], flat, valid,
                        a.dictionary)
    if node.ordinality_sym:
        out[node.ordinality_sym] = Val(
            T.BIGINT, (j + 1).astype(jnp.int64), None)
    out_live = jnp.repeat(live, cap) & (j < jnp.repeat(zlen, cap))
    return DTable(out, out_live, n * cap)


def apply_mark_distinct(dt: DTable, node: N.MarkDistinct,
                        capacity: int) -> tuple:
    """Adds node.mark_symbol: true on the first live row of each
    distinct key tuple (reference MarkDistinctOperator.java; here one
    hash-slot assignment + a segment-min race for the first row)."""
    live = dt.live_mask()
    rh = _row_hash(dt, node.keys)
    key_ops = []
    for k in node.keys:
        v = dt.cols[k]
        if getattr(v.data, "ndim", 1) == 2:  # LONG decimal key
            khi, klo = _long_key_operands(v)
            key_ops.extend([khi, klo])
        else:
            key_ops.append(_group_key_operand(v))
        if v.valid is not None:
            key_ops.append(v.valid)
    sg = H.SortedGroups(rh, live, key_ops, len(key_ops))
    # is_new flags the first sorted row of each key run (stable sort ->
    # the smallest source index); a second sort keyed by the source row
    # index inverts the permutation without a scatter
    _, mark = jax.lax.sort((sg.sidx, sg.is_new), num_keys=1)
    cols = dict(dt.cols)
    cols[node.mark_symbol] = Val(T.BOOLEAN, mark, None, None)
    return DTable(cols, dt.live, dt.n), jnp.asarray(True)


def apply_distinct(dt: DTable, capacity: int) -> tuple:
    live = dt.live_mask()
    direct = _direct_group_ids(dt, list(dt.cols))
    if direct is not None:
        slots, capacity, sizes = direct
        occupancy = segred.segment_sum(
            live.astype(jnp.int32), slots, num_segments=capacity) > 0
        out = _decode_direct_keys(dt, list(dt.cols), sizes, capacity)
        return DTable(out, occupancy, capacity), jnp.asarray(True)
    rh = _row_hash(dt, list(dt.cols))
    payloads = []
    refs = []
    float_cols = []
    for sym, v in dt.cols.items():
        di = len(payloads)
        if getattr(v.data, "ndim", 1) == 2:  # LONG decimal key
            khi, klo = _long_key_operands(v)
            payloads.append(khi)
            payloads.append(klo)
            vi = None
            if v.valid is not None:
                vi = len(payloads)
                payloads.append(v.valid)
            refs.append((sym, v, ("long", di, di + 1), vi))
            continue
        payloads.append(_group_key_operand(v))
        vi = None
        if v.valid is not None:
            vi = len(payloads)
            payloads.append(v.valid)
        if jnp.issubdtype(v.data.dtype, jnp.floating):
            float_cols.append((sym, v, vi))
        else:
            refs.append((sym, v, di, vi))
    num_key_payloads = len(payloads)
    for sym, v, vi in float_cols:
        refs.append((sym, v, len(payloads), vi))
        payloads.append(v.data)
    sg = H.SortedGroups(rh, live, payloads, num_key_payloads)
    ok = sg.ngroups <= capacity
    compacted, occupied = sg.compact_first(sg.payloads, capacity)
    out = {}
    for sym, v, di, vi in refs:
        valid = None if vi is None else compacted[vi]
        if isinstance(di, tuple):  # LONG decimal limbs
            data = _unpack_long_key(compacted[di[1]], compacted[di[2]])
            out[sym] = Val(v.dtype, data, valid, v.dictionary)
            continue
        out[sym] = Val(v.dtype, compacted[di], valid, v.dictionary)
    return DTable(out, occupied, capacity), ok
