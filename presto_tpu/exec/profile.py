"""EXPLAIN ANALYZE: execute the plan with per-node instrumentation.

Analog of the reference's ExplainAnalyzeOperator + OperatorStats rollup
(operator/ExplainAnalyzeOperator.java:34, OperationTimer.java:30). Under
XLA the whole pipeline fuses into one computation, so per-operator wall
time is not individually observable the way the reference times each
getOutput/addInput call; instead the profile reports what the fused model
can: actual row counts flowing out of every plan node (emitted as extra
kernel outputs), plus compile and execute wall times for the whole plan.

Segmented plans (exec/executor.py _find_split) profile per SEGMENT: each
separately compiled segment re-runs under a profiling interpreter, so
per-node actual rows — including pruned probe TableScans, the numbers
the dynamic-filter effectiveness tests read — surface on every segment's
plan, not just the final program.
"""

from __future__ import annotations

import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.cost import row_estimates
from presto_tpu.exec import hostsync as HS
from presto_tpu.exec.executor import (collect_scans, device_outputs,
                                      make_traced, preorder_index)
from presto_tpu.obs.trace import TRACER
from presto_tpu.plan import nodes as N
from presto_tpu.plan.printer import format_plan


def _rows_by_node_id(plan, meta, counts) -> dict[int, int]:
    """Per-node actual rows keyed by id(node) of THIS plan's objects.
    ``meta["count_nodes"]`` keys are stable preorder positions (they
    ride program-cache entries across replans and restarts); EXPLAIN
    ANALYZE's printer annotations key by object id, so invert the
    preorder walk."""
    inv = {pos: nid for nid, pos in preorder_index(plan).items()}
    counts_np = HS.fetch(counts, site="profile-counts")
    return {inv.get(key, key): int(c)
            for key, c in zip(meta["count_nodes"], counts_np)}


def _profiled_compile_run(engine, plan, scans):
    """Shared EXPLAIN ANALYZE ladder: trace, compile OUTSIDE the
    program cache (so the profile's compile/execute walls are really
    measured, not amortized over prior queries), and retry on
    hash-table overflow. Per-node actual rows need no special
    interpreter anymore — every traced program carries them
    (PlanInterpreter.row_counts, the always-on stats contract). The
    capacity vector is SEEDED from what prepare_plan already learned
    for this plan (memory or the caps sidecar), so profiling does not
    replay the overflow ladder with an extra 80-150 s compile per
    rung. Returns (meta, res, live, counts, compile_s, run_s) of the
    successful attempt."""
    from presto_tpu import templates as TPL
    from presto_tpu.exec import executor as EX
    from presto_tpu.exec import progcache as PC

    # seed capacities under the SAME key prepare_plan stores them:
    # with templates on that is the parameterized plan over bucketed
    # scan shapes (the profiling trace itself keeps literals baked)
    kplan, kscans = plan, scans
    if TPL.enabled(engine.session):
        kscans = TPL.bucket_scans(engine, scans)
        tpl = TPL.parameterize(plan)
        if tpl is not None:
            kplan = tpl.plan
    base_key, _ = EX._cache_key(engine, kplan, kscans, {})
    known = engine._caps_memory.get(base_key)
    if known is None:
        known = engine._program_cache.load_caps(
            base_key, PC.platform_fingerprint())
    capacities: dict[tuple, int] = dict(known)
    for _attempt in range(10):
        traced_fn, flat, meta = make_traced(
            scans, plan, capacities, engine.session)
        t0 = time.perf_counter()
        with TRACER.span("compile", analyze=True):
            compiled = jax.jit(traced_fn).lower(*flat).compile()
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        with TRACER.span("execute", analyze=True):
            res, live, oks, counts = compiled(*flat)
            # raw measurement syncs (DEVICE_SYNC_EXEMPT, exec/hostsync):
            # the profile measures the readback itself, and must not
            # count into the hot-path device-sync counter
            jax.block_until_ready(live)
            oks_np = np.asarray(oks)
        run_s = time.perf_counter() - t0
        if oks_np.all():
            return meta, res, live, counts, compile_s, run_s
        from presto_tpu.ops.hash import grow_overflowed
        grow_overflowed(capacities, meta["ok_keys"], oks_np,
                        meta["used_capacity"])
    from presto_tpu.ops.hash import HashChainOverflow
    raise HashChainOverflow(
        "hash table capacity retry limit exceeded")


def _profiled_runner(engine, mat, scans, cap_floor=None):
    """run_plan_device twin for segments: returns (arrays, dicts,
    types, n, {node id: actual rows}). ``cap_floor`` keeps carrier
    widths consistent with the production (templated) pipeline."""
    meta, res, live, counts, _c, _r = _profiled_compile_run(
        engine, mat, scans)
    node_rows = _rows_by_node_id(mat, meta, counts)
    return device_outputs(meta, res, live, cap_floor) + (node_rows,)


def _annotate(mat, node_rows: dict | None, engine) -> dict[int, str]:
    """Per-node 'rows: actual (est N)' annotations for one segment."""
    if not node_rows:
        return {}
    try:
        estimated = row_estimates(mat, engine)
    except Exception:  # noqa: BLE001 - carrier scans may lack stats
        estimated = {}
    return {nid: (f"rows: {actual}" if estimated.get(nid) is None
                  else f"rows: {actual} (est {estimated[nid]})")
            for nid, actual in node_rows.items()}


def explain_analyze(engine, plan: N.PlanNode) -> str:
    """EXPLAIN ANALYZE with PER-SEGMENT wall-clock attribution: each
    separately compiled segment (many-join splits + pre-aggregation
    compaction boundaries, exec/executor.py _find_split) reports its
    own execute wall, output width, AND per-node actual row counts
    (profiling runner); the final program adds its own row counts.
    Per-operator walls inside one segment are not observable under XLA
    fusion; the segment boundary is the real unit of time on this
    engine (reference analog: operator/OperationTimer.java:30 rolled
    up per operator, ExplainAnalyzeOperator.java:34)."""
    from presto_tpu.exec import executor as EX

    seg_lines: list[str] = []
    total_t0 = time.perf_counter()

    def observe(seg, mat, arrays, n, wall_s, node_rows):
        live = HS.fetch_int(jnp.sum(arrays["__live__"]),
                            site="profile-live")
        seg_lines.append(
            f"Segment {seg} ({wall_s * 1e3:.1f} ms, "
            f"{live} live rows -> s{seg}[{n}])\n"
            + format_plan(mat,
                          annotations=_annotate(mat, node_rows,
                                                engine)))

    pool = getattr(engine, "memory_pool", None)
    tag = "explain-" + uuid.uuid4().hex[:12]
    try:
        plan, carriers = EX._segment_carriers(engine, plan, tag,
                                              observer=observe,
                                              runner=_profiled_runner)
        scan_inputs = EX._collect_with_carriers(plan, engine, carriers)
        final = _explain_one_program(engine, plan, scan_inputs)
    finally:
        if pool is not None:
            pool.free(tag)
    if not seg_lines:
        return final
    total = (time.perf_counter() - total_t0) * 1e3
    return (f"Query plan: {len(seg_lines)} materialized segment(s) + "
            f"final program, total {total:.1f} ms\n"
            + "\n".join(seg_lines)
            + "\nFinal " + final)


def _explain_one_program(engine, plan: N.PlanNode,
                         scan_inputs=None) -> str:
    if scan_inputs is None:
        scan_inputs = collect_scans(plan, engine)
    annotations: dict[int, str] = {}
    estimated = row_estimates(plan, engine)
    meta, _res, _live, counts, compile_s, run_s = \
        _profiled_compile_run(engine, plan, scan_inputs)

    # estimated-vs-actual rows per node: estimation bugs show up in
    # one place (reference PlanPrinter's EXPLAIN ANALYZE estimate
    # columns)
    for nid, actual in _rows_by_node_id(plan, meta, counts).items():
        est = estimated.get(nid)
        annotations[nid] = (f"rows: {actual}" if est is None
                            else f"rows: {actual} (est {est})")
    header = (f"Query plan (compile {compile_s * 1e3:.1f} ms, "
              f"execute {run_s * 1e3:.1f} ms)\n")
    return header + format_plan(plan, annotations=annotations)


def explain_analyze_distributed(engine, plan: N.PlanNode, mesh) -> str:
    """EXPLAIN ANALYZE for the shard_map path: per-node mesh-global row
    counts + distribution tags + compile/run wall times (VERDICT round 2
    #10 — the distributed path previously had no profile at all)."""
    from presto_tpu.parallel.executor import execute_plan_distributed

    profile: dict = {}
    execute_plan_distributed(engine, plan, mesh, profile=profile)
    estimated = row_estimates(plan, engine)
    # profile["node_rows"] keys are stable preorder positions (the
    # program-cache-stable stats keys); the printer wants object ids
    inv = {pos: nid for nid, pos in preorder_index(plan).items()}
    annotations = {}
    for pos, (rows, dist) in profile["node_rows"].items():
        nid = inv.get(pos, pos)
        est = estimated.get(nid)
        annotations[nid] = (
            f"rows: {rows} [{dist}]" if est is None
            else f"rows: {rows} (est {est}) [{dist}]")
    header = (f"Distributed plan over {mesh.devices.size} devices "
              f"(compile {profile['compile_s'] * 1e3:.1f} ms, "
              f"execute {profile['run_s'] * 1e3:.1f} ms)\n")
    return header + format_plan(plan, annotations=annotations)
