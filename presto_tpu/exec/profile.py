"""EXPLAIN ANALYZE: execute the plan with per-node instrumentation.

Analog of the reference's ExplainAnalyzeOperator + OperatorStats rollup
(operator/ExplainAnalyzeOperator.java:34, OperationTimer.java:30). Under
XLA the whole pipeline fuses into one computation, so per-operator wall
time is not individually observable the way the reference times each
getOutput/addInput call; instead the profile reports what the fused model
can: actual row counts flowing out of every plan node (emitted as extra
kernel outputs), plus compile and execute wall times for the whole plan.
"""

from __future__ import annotations

import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu.cost import row_estimates
from presto_tpu.exec.executor import PlanInterpreter, collect_scans
from presto_tpu.obs.trace import TRACER
from presto_tpu.plan import nodes as N
from presto_tpu.plan.printer import format_plan


class ProfilingInterpreter(PlanInterpreter):
    def __init__(self, scans, capacities, session=None):
        super().__init__(scans, capacities, session)
        self.row_counts: list[tuple[int, object]] = []

    def run(self, node: N.PlanNode):
        dt = super().run(node)
        self.row_counts.append(
            (id(node), jnp.sum(dt.live_mask().astype(jnp.int64))))
        return dt


def explain_analyze(engine, plan: N.PlanNode) -> str:
    """EXPLAIN ANALYZE with PER-SEGMENT wall-clock attribution: each
    separately compiled segment (many-join splits + pre-aggregation
    compaction boundaries, exec/executor.py _find_split) reports its
    own execute wall and output width, and the final program adds
    per-node row counts. Per-operator walls inside one segment are not
    observable under XLA fusion; the segment boundary is the real unit
    of time on this engine (reference analog:
    operator/OperationTimer.java:30 rolled up per operator,
    ExplainAnalyzeOperator.java:34)."""
    from presto_tpu.exec import executor as EX

    seg_lines: list[str] = []
    total_t0 = time.perf_counter()

    def observe(seg, mat, arrays, n, wall_s):
        live = int(np.asarray(jnp.sum(arrays["__live__"])))
        seg_lines.append(
            f"Segment {seg} ({wall_s * 1e3:.1f} ms, "
            f"{live} live rows -> s{seg}[{n}])\n"
            + format_plan(mat))

    pool = getattr(engine, "memory_pool", None)
    tag = "explain-" + uuid.uuid4().hex[:12]
    try:
        plan, carriers = EX._segment_carriers(engine, plan, tag,
                                              observer=observe)
        scan_inputs = EX._collect_with_carriers(plan, engine, carriers)
        final = _explain_one_program(engine, plan, scan_inputs)
    finally:
        if pool is not None:
            pool.free(tag)
    if not seg_lines:
        return final
    total = (time.perf_counter() - total_t0) * 1e3
    return (f"Query plan: {len(seg_lines)} materialized segment(s) + "
            f"final program, total {total:.1f} ms\n"
            + "\n".join(seg_lines)
            + "\nFinal " + final)


def _explain_one_program(engine, plan: N.PlanNode,
                         scan_inputs=None) -> str:
    if scan_inputs is None:
        scan_inputs = collect_scans(plan, engine)
    capacities: dict[tuple, int] = {}
    annotations: dict[int, str] = {}
    estimated = row_estimates(plan, engine)

    for _attempt in range(10):
        meta: dict[str, object] = {}

        def traced_fn(*args):
            it = iter(args)
            scans = {}
            for scan in scan_inputs:
                traced = {sym: next(it) for sym in scan.arrays}
                scans[id(scan.node)] = (scan, traced)
            interp = ProfilingInterpreter(scans, capacities,
                                          engine.session)
            out = interp.run(plan)
            meta["ok_keys"] = interp.ok_keys
            meta["used_capacity"] = interp.used_capacity
            meta["count_nodes"] = [nid for nid, _ in interp.row_counts]
            counts = tuple(c for _, c in interp.row_counts)
            return out.live_mask(), counts, tuple(interp.ok_flags)

        flat_arrays = [scan.arrays[sym] for scan in scan_inputs
                       for sym in scan.arrays]
        t0 = time.perf_counter()
        with TRACER.span("compile", analyze=True):
            compiled = jax.jit(traced_fn).lower(*flat_arrays).compile()
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        with TRACER.span("execute", analyze=True):
            live, counts, oks = compiled(*flat_arrays)
            jax.block_until_ready(live)
        run_s = time.perf_counter() - t0
        if all(bool(np.asarray(o)) for o in oks):
            break
        for key, okv in zip(meta["ok_keys"], oks):
            if not bool(np.asarray(okv)):
                capacities[key] = 4 * meta["used_capacity"][key]
    else:
        raise RuntimeError("hash table capacity retry limit exceeded")

    # estimated-vs-actual rows per node: estimation bugs show up in
    # one place (reference PlanPrinter's EXPLAIN ANALYZE estimate
    # columns)
    for nid, c in zip(meta["count_nodes"], counts):
        actual = int(np.asarray(c))
        est = estimated.get(nid)
        annotations[nid] = (f"rows: {actual}" if est is None
                            else f"rows: {actual} (est {est})")
    header = (f"Query plan (compile {compile_s * 1e3:.1f} ms, "
              f"execute {run_s * 1e3:.1f} ms)\n")
    return header + format_plan(plan, annotations=annotations)


def explain_analyze_distributed(engine, plan: N.PlanNode, mesh) -> str:
    """EXPLAIN ANALYZE for the shard_map path: per-node mesh-global row
    counts + distribution tags + compile/run wall times (VERDICT round 2
    #10 — the distributed path previously had no profile at all)."""
    from presto_tpu.parallel.executor import execute_plan_distributed

    profile: dict = {}
    execute_plan_distributed(engine, plan, mesh, profile=profile)
    estimated = row_estimates(plan, engine)
    annotations = {
        nid: (f"rows: {rows} [{dist}]" if estimated.get(nid) is None
              else f"rows: {rows} (est {estimated[nid]}) [{dist}]")
        for nid, (rows, dist) in profile["node_rows"].items()}
    header = (f"Distributed plan over {mesh.devices.size} devices "
              f"(compile {profile['compile_s'] * 1e3:.1f} ms, "
              f"execute {profile['run_s'] * 1e3:.1f} ms)\n")
    return header + format_plan(plan, annotations=annotations)
