"""Compile-latency subsystem: bounded LRU program cache + persistent
AOT disk store + parallel segment compilation.

XLA compilation dominates cold-query latency by 20-40x (BENCH r05: Q5
compiles 152 s against ~4 s of execution). The reference amortizes
codegen with compiled-artifact caches shared across queries
(gen/PageFunctionCompiler.java:101); the JAX analog treats compiled
executables as reusable artifacts keyed on canonical structure
("Fine-Tuning Data Structures for Analytical Query Processing",
PAPERS.md). Three legs:

1. **In-memory LRU** (:class:`ProgramCache`): replaces the unbounded
   ``engine._program_cache`` dict with a size-bounded (entries AND
   bytes) LRU reporting hits/misses/evictions/resident-bytes through
   the obs registry.

2. **Persistent AOT store**: entries serialize through
   ``jax.experimental.serialize_executable`` into a content-addressed
   directory (``PRESTO_TPU_PROGRAM_CACHE_DIR``), keyed by the
   canonical cache key PLUS a platform fingerprint (jax/jaxlib
   version, backend, device kind/count, mesh shape, x64 flag) so a
   warm process — or a freshly-POSTed worker task on another node
   sharing the directory — skips lower+compile entirely.  Any
   serialize/deserialize failure falls back to a live compile (miss
   counted, error counted, never a crash).  A tiny ``.caps.json``
   sidecar persists the successful hash-table capacity vector per
   plan, so a warm process goes straight to the right program instead
   of replaying the overflow-retry ladder.

3. **Parallel compilation** (:func:`map_parallel`): independent
   segments/programs compile concurrently on a bounded thread pool —
   XLA compilation releases the GIL — with the segment dependency
   order respected by the caller (exec/executor._segment_carriers
   compiles wave-by-wave).

Key canonicalization: capacities route through the same pow2
bucketing the cost-based reorderer uses (ops/hash.next_pow2), and the
session component of the key is restricted to the properties the
trace actually reads (:data:`TRACE_RELEVANT_PROPERTIES`) — resolved
through ``Session.get`` so per-thread overrides participate — so
structurally-identical replans hit the same entry.

Dictionary contents participate in the key: string-dictionary arrays
get a content digest (:func:`dictionary_token`, memoized by array
identity so the hash is paid once per process per dictionary) because
traced programs embed dictionary codes as constants and ``meta``
carries the decode dictionary — a disk entry surviving a data rewrite
at constant shape must miss, not silently decode against stale
strings.

Locking: all mutable cache state (``_entries``, ``_bytes``,
``max_entries``, ``max_bytes``) is guarded by ``self._lock``; disk IO
runs outside the lock (atomic tmp+rename writes), so a slow
serialization never blocks concurrent lookups.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import pickle
import threading
import time

from presto_tpu.obs.metrics import REGISTRY
from presto_tpu.ops.hash import next_pow2

ENV_DIR = "PRESTO_TPU_PROGRAM_CACHE_DIR"

_HITS = REGISTRY.counter(
    "presto_tpu_program_cache_hits_total",
    "compiled-program cache hits, labeled tier=memory|disk")
_MISSES = REGISTRY.counter(
    "presto_tpu_program_cache_misses_total",
    "compiled-program cache misses (a live XLA compile follows)")
_EVICTIONS = REGISTRY.counter(
    "presto_tpu_program_cache_evictions_total",
    "LRU evictions from the in-memory program cache")
_DISK_ERRORS = REGISTRY.counter(
    "presto_tpu_program_cache_disk_errors_total",
    "disk-store serialize/deserialize failures (fallback to live "
    "compile), labeled op=load|store")
_RESIDENT = REGISTRY.gauge(
    "presto_tpu_program_cache_resident_bytes",
    "estimated bytes of compiled programs resident across every "
    "live in-process LRU (delta-accounted process total)")
_ENTRIES_G = REGISTRY.gauge(
    "presto_tpu_program_cache_entries",
    "compiled programs resident across every live in-process LRU "
    "(delta-accounted process total)")
_LOAD_SECONDS = REGISTRY.histogram(
    "presto_tpu_program_cache_load_seconds",
    "wall time to deserialize one AOT program from the disk store")

# Session properties the trace-time interpreters actually read
# (PlanInterpreter / ShardedInterpreter): the canonical session
# component of a cache key. Everything else either acts at plan time
# (captured by the plan fingerprint) or host-side before/after the
# compiled program runs. The adaptive-execution properties
# (adaptive_replanning, speculative_execution, speculation_*) are
# deliberately NOT listed: they steer the coordinator's HTTP stage
# walk only, and re-keying compiled programs on them would evict warm
# entries for a knob the trace never sees. (A replan changes plan
# ANNOTATIONS — capacities, distributions — which already participate
# via the plan fingerprint and capacity buckets.)
#
# This tuple is machine-checked both ways by the ``tracekey`` lint
# rule (lint/tracekey.py): a trace-reachable session read missing
# here is an ``unsound-read`` finding (stale-executable wrong
# results), and an entry no trace-reachable code reads is a
# ``stale-key-entry`` finding (spurious recompiles). PR 15 pruned
# ``use_connector_partitioning`` on that analysis: it is read only
# host-side by execute_plan_distributed, and the bucketing decision
# it drives already rides the distributed cache key as the explicit
# per-scan ``(part_cols, bucketed)`` component.
TRACE_RELEVANT_PROPERTIES = (
    "broadcast_join_threshold_rows",
    "distributed_sort",
    "enable_dynamic_filtering",
    "groupby_table_size",
    "join_distribution_type",
    "join_salting",
    # kernel_backend selects the operator inner-loop implementation
    # (presto_tpu/kernels/ dispatch) at trace time: pallas and xla
    # traces are different programs and must not share an entry
    "kernel_backend",
    "partial_aggregation",
    "partitioned_agg_min_groups",
    "skew_hot_key_threshold",
)

# Ambient reads the tracekey provenance analysis sees inside trace
# scope that are DELIBERATELY not part of the canonical key, each with
# the soundness argument. Ids are the rule's finding ids
# (``session:<prop>``, ``env:<NAME>``, ``global:<relpath>:<NAME>``,
# ``key:<prop>``); an entry that stops matching a finding becomes a
# ``stale-exemption`` finding itself, so this registry cannot rot into
# a blanket waiver.
TRACE_KEY_EXEMPT = {
    "global:presto_tpu/ops/hash.py:_DICT_HASH_CACHE":
        "pure memoization: the cached hashes are a content-only "
        "function of the dictionary array (identity-checked strong "
        "ref), and dictionary CONTENT already rides every cache key "
        "via scan_dictionary_key — a rebuilt cache yields bit-equal "
        "values",
    "global:presto_tpu/expr/compile.py:_DATE_FORMAT_CACHE":
        "pure memoization keyed by the date_format literal: the LUT "
        "is a content-only function of the format string, which is "
        "structural (never hoisted by templates/analysis.py) and so "
        "participates in the plan fingerprint",
}

# retrace-hazard exemptions (lint/retrace.py): deliberate
# data-dependent control flow / shape construction in trace scope,
# id ("<relpath>:<dotted.unit.path>:<kind>", kind in branch | shape |
# key) -> justification. Stale entries are findings, like
# TRACE_KEY_EXEMPT above.
RETRACE_EXEMPT = {
    "presto_tpu/exec/executor.py:device_outputs:branch":
        "the branch on the live count IS the bucketing helper: both "
        "arms produce bucketed carrier widths (the remembered "
        "template width when the count fits, pow2-with-margin "
        "regrowth when it overflows), so the data dependence is "
        "confined to choosing between two cache-stable shapes",
}

DEFAULT_MAX_ENTRIES = 64
DEFAULT_MAX_BYTES = int(os.environ.get(
    "PRESTO_TPU_PROGRAM_CACHE_MEM_BYTES", 2 << 30))
# disk-store budget: oldest entries are pruned (best effort, after
# each store) once the directory exceeds this — the store accumulates
# across schema/scale/session/platform variations forever otherwise
DISK_BYTES_LIMIT = int(os.environ.get(
    "PRESTO_TPU_PROGRAM_CACHE_DISK_BYTES", 32 << 30))
# conservative stand-in when the backend cannot report code size
_DEFAULT_ENTRY_BYTES = 1 << 22


def trace_session_key(session) -> tuple:
    """Canonical session component of a cache key: only the properties
    the trace reads, resolved through Session.get so per-thread query
    overrides (server dispatch) participate."""
    return tuple((name, repr(session.get(name)))
                 for name in TRACE_RELEVANT_PROPERTIES)


def bucket_capacities(capacities: dict) -> tuple:
    """Capacity-override vector canonicalized to pow2 buckets (the
    bucketing cost/reorder.py already applies to its hints), sorted
    for key stability."""
    return tuple(sorted(
        (k, next_pow2(v)) for k, v in capacities.items()))


# dictionary content digests memoized by array identity (strong ref
# pins the id, the engine's device-pin cache uses the same pattern);
# bounded so per-execution temporary dictionaries cannot leak
_DICT_TOKENS: dict[int, tuple] = {}
_DICT_TOKENS_MAX = 256
_DICT_LOCK = threading.Lock()


def dictionary_token(arr) -> str | None:
    """Content digest of one dictionary array, or None. Traced
    programs embed dictionary codes as constants and cached meta
    carries the decode dictionary, so dictionary CONTENT — not just
    shape — must participate in cache keys."""
    import numpy as np
    if arr is None:
        return None
    key = id(arr)
    with _DICT_LOCK:
        hit = _DICT_TOKENS.get(key)
        if hit is not None and hit[0] is arr:
            return hit[1]
    data = np.asarray(arr)
    h = hashlib.blake2b(digest_size=8)
    h.update(str(len(data)).encode())
    if data.dtype == object:
        for s in data.tolist():
            h.update(str(s).encode())
            h.update(b"\0")
    else:
        h.update(np.ascontiguousarray(data).tobytes())
    digest = h.hexdigest()
    with _DICT_LOCK:
        if len(_DICT_TOKENS) >= _DICT_TOKENS_MAX:
            _DICT_TOKENS.clear()
        _DICT_TOKENS[key] = (arr, digest)
    return digest


def scan_dictionary_key(scan_inputs) -> tuple:
    """Key component covering every scanned dictionary's content."""
    return tuple(
        (i, sym, dictionary_token(d))
        for i, scan in enumerate(scan_inputs)
        for sym, d in scan.dictionaries.items() if d is not None)


# traced-program output-format version: participates in the platform
# fingerprint so persisted entries from an engine with a different
# output contract (e.g. before the always-on per-node row counts
# became a fourth program output, or before the distributed path
# stacked its ok flags into one (k,) array) miss instead of
# mis-unpacking. "cost1": meta carries the compile-time device-cost
# summary (obs/devprof.harvest) — pre-cost entries would report zero
# flops forever on warm hits, so they miss and recompile once
PROGRAM_FORMAT = "cost1"


@functools.lru_cache(maxsize=32)
def platform_fingerprint(mesh_shape: tuple | None = None) -> tuple:
    """What a serialized executable is only valid for: jax/jaxlib
    versions, backend kind, device kind and count, x64 mode, the
    engine's traced-program output format, and (for shard_map
    programs) the mesh shape."""
    import jax
    import jaxlib

    from presto_tpu import kernels as K
    devs = jax.devices()
    return (jax.__version__, jaxlib.__version__,
            jax.default_backend(), len(devs),
            getattr(devs[0], "device_kind", "?"),
            bool(jax.config.jax_enable_x64), PROGRAM_FORMAT,
            # what kernel_backend=auto resolves to here: a persisted
            # entry from a TPU process (pallas kernels inside) must
            # not be loaded by a CPU process expecting XLA bodies
            f"kernels-{K.default_backend()}",
            mesh_shape)


def entry_digest(key, fingerprint) -> str:
    """Content address of one (canonical key, platform fingerprint)
    pair in the disk store."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((key, fingerprint)).encode())
    return h.hexdigest()


def map_parallel(fn, items: list, width: int) -> list:
    """Run ``fn`` over ``items`` on a bounded thread pool, preserving
    order (XLA compilation releases the GIL, so concurrent
    lower+compile calls genuinely overlap). width<=1 or a single item
    runs inline; exceptions propagate like the serial loop."""
    if width <= 1 or len(items) <= 1:
        return [fn(it) for it in items]
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(
            max_workers=min(width, len(items))) as pool:
        return list(pool.map(fn, items))


def _estimate_nbytes(compiled, payload_len: int | None = None) -> int:
    """Resident-size estimate for LRU accounting: serialized payload
    length when known, else the backend's generated-code size, else a
    flat default."""
    if payload_len:
        return int(payload_len)
    try:
        ma = compiled.memory_analysis()
        size = int(getattr(ma, "generated_code_size_in_bytes", 0))
        if size > 0:
            return size
    except Exception:  # noqa: BLE001 - backend may not implement it
        pass
    return _DEFAULT_ENTRY_BYTES


class ProgramCache:
    """Two-tier compiled-program cache: a bounded in-memory LRU over an
    optional shared on-disk AOT store."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 disk_dir: str | None = None):
        self._lock = threading.Lock()
        # key -> (compiled, meta, nbytes); insertion order = LRU order
        self._entries: dict = {}
        self._bytes = 0
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1, int(max_bytes))
        if disk_dir is None:
            disk_dir = os.environ.get(ENV_DIR) or None
        self.disk_dir = disk_dir

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "bytes": self._bytes,
                    "max_entries": self.max_entries,
                    "max_bytes": self.max_bytes,
                    "disk_dir": self.disk_dir}

    def configure(self, session) -> None:
        """Refresh the entry bound from the session knob (SET SESSION
        program_cache_entries takes effect on the next query)."""
        try:
            limit = int(session.get("program_cache_entries") or 0)
        except KeyError:
            return
        if limit <= 0:
            return
        with self._lock:
            self.max_entries = max(1, limit)
            self._trim()

    # -- lookups ------------------------------------------------------------

    def lookup(self, key, fingerprint: tuple | None = None):
        """(compiled, meta) for ``key`` or None. Memory tier first,
        then the disk store (deserialized entries are promoted into
        memory). Counts one hit (labeled by tier) or one miss."""
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._entries[key] = ent  # re-append: most recent
        if ent is not None:
            _HITS.inc(tier="memory")
            return ent[0], ent[1]
        loaded = self._disk_load(key, fingerprint)
        if loaded is not None:
            compiled, meta, nbytes = loaded
            self._remember(key, compiled, meta, nbytes)
            _HITS.inc(tier="disk")
            return compiled, meta
        _MISSES.inc()
        return None

    def insert(self, key, compiled, meta,
               fingerprint: tuple | None = None,
               persist: bool = True) -> None:
        """Add a freshly compiled program; serialize to the disk store
        when enabled (best-effort — a backend that cannot serialize
        just keeps the memory tier)."""
        payload_len = None
        if persist and self.disk_dir:
            payload_len = self._disk_store(key, compiled, meta,
                                           fingerprint)
        self._remember(key, compiled, meta,
                       _estimate_nbytes(compiled, payload_len))

    def discard(self, key) -> None:
        """Drop one entry without counting an eviction: programs
        compiled on failed capacity-retry rungs are never looked up
        again (the capacity memory jumps straight to the successful
        vector), and keeping them would squeeze live programs out of
        the bounded LRU."""
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._bytes -= ent[2]
                _RESIDENT.dec(ent[2])
                _ENTRIES_G.dec()

    def _remember(self, key, compiled, meta, nbytes: int) -> None:
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[2]
                _RESIDENT.dec(old[2])
                _ENTRIES_G.dec()
            self._entries[key] = (compiled, meta, nbytes)
            self._bytes += nbytes
            _RESIDENT.inc(nbytes)
            _ENTRIES_G.inc()
            self._trim()

    def _trim(self) -> None:
        """Evict LRU entries beyond the entry/byte bounds (gauges track
        the process-wide total by delta, so several live caches — a
        worker holds one engine per split view — sum instead of
        clobbering each other). Caller must hold the lock."""
        while self._entries and (
                len(self._entries) > self.max_entries
                or self._bytes > self.max_bytes):
            if len(self._entries) == 1:
                # only the byte bound can be violated here
                # (max_entries >= 1): keep the single oversized entry
                break
            oldest = next(iter(self._entries))
            _, _, nb = self._entries.pop(oldest)
            self._bytes -= nb
            _RESIDENT.dec(nb)
            _ENTRIES_G.dec()
            _EVICTIONS.inc()

    # -- disk store ---------------------------------------------------------

    def _path(self, digest: str, suffix: str) -> str:
        return os.path.join(self.disk_dir, digest + suffix)

    def _disk_load(self, key, fingerprint):
        """(compiled, meta, nbytes) deserialized from the store, or
        None on any failure (missing file, corrupt pickle, backend
        refusal) — the caller falls back to a live compile. A failing
        entry is unlinked: some program classes cannot be relinked by
        the XLA CPU runtime at all ('Symbols not found'), and keeping
        the file would re-pay the failed deserialize on every warm
        start (the next process re-stores a fresh payload)."""
        if not self.disk_dir:
            return None
        path = self._path(entry_digest(key, fingerprint), ".prog")
        if not os.path.exists(path):
            return None
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as f:
                blob = pickle.load(f)
            if blob.get("key") != repr(key):
                raise ValueError("digest collision / stale entry")
            from jax.experimental import serialize_executable as _se
            compiled = _se.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"])
            _LOAD_SECONDS.observe(time.perf_counter() - t0)
            return compiled, blob["meta"], len(blob["payload"])
        except Exception:  # noqa: BLE001 - corrupt/incompatible entry
            _DISK_ERRORS.inc(op="load")
            try:
                os.unlink(path)
            except OSError:
                pass
            return None

    def _disk_store(self, key, compiled, meta,
                    fingerprint) -> int | None:
        """Serialize one executable into the store (atomic tmp+rename,
        so concurrent writers across processes can only race to the
        same content). Returns the payload length, or None when the
        backend cannot serialize."""
        digest = entry_digest(key, fingerprint)
        try:
            from jax.experimental import serialize_executable as _se
            payload, in_tree, out_tree = _se.serialize(compiled)
            blob = pickle.dumps({
                "key": repr(key), "payload": payload,
                "in_tree": in_tree, "out_tree": out_tree,
                "meta": meta})
            os.makedirs(self.disk_dir, exist_ok=True)
            tmp = self._path(digest, f".tmp.{os.getpid()}")
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._path(digest, ".prog"))
            self._prune_disk()
            return len(payload)
        except Exception:  # noqa: BLE001 - never fail the query
            _DISK_ERRORS.inc(op="store")
            return None

    def _prune_disk(self) -> None:
        """Best-effort disk budget: drop oldest-mtime entries beyond
        DISK_BYTES_LIMIT (superseded capacity rungs, dead schema/scale
        variants, stale platform fingerprints). Runs after each store
        — once per NEW program, never on the lookup path. Concurrent
        processes may race to unlink the same file; losing is fine."""
        try:
            entries = []
            total = 0
            with os.scandir(self.disk_dir) as it:
                for de in it:
                    if not de.name.endswith((".prog", ".caps.json")):
                        continue
                    st = de.stat()
                    entries.append((st.st_mtime, st.st_size, de.path))
                    total += st.st_size
            if total <= DISK_BYTES_LIMIT:
                return
            for _mtime, size, path in sorted(entries):
                try:
                    os.unlink(path)
                    total -= size
                except OSError:
                    pass
                if total <= DISK_BYTES_LIMIT:
                    break
        except Exception:  # noqa: BLE001 - pruning is best-effort
            pass

    # -- capacity sidecar ---------------------------------------------------

    def load_caps(self, base_key,
                  fingerprint: tuple | None = None) -> dict:
        """Persisted successful capacity vector for a plan, so a warm
        process skips the overflow-retry ladder. {} when absent."""
        if not self.disk_dir:
            return {}
        path = self._path(entry_digest(base_key, fingerprint),
                          ".caps.json")
        try:
            with open(path, encoding="utf-8") as f:
                rows = json.load(f)
            return {(int(pos), str(kind)): int(cap)
                    for pos, kind, cap in rows}
        except FileNotFoundError:
            return {}
        except Exception:  # noqa: BLE001 - corrupt sidecar = no caps
            _DISK_ERRORS.inc(op="load")
            return {}

    def store_caps(self, base_key, caps: dict,
                   fingerprint: tuple | None = None) -> None:
        if not self.disk_dir or not caps:
            return
        digest = entry_digest(base_key, fingerprint)
        try:
            rows = [[int(pos), str(kind), int(cap)]
                    for (pos, kind), cap in sorted(caps.items())]
            os.makedirs(self.disk_dir, exist_ok=True)
            tmp = self._path(digest, f".capstmp.{os.getpid()}")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(rows, f)
            os.replace(tmp, self._path(digest, ".caps.json"))
        except Exception:  # noqa: BLE001 - sidecar is best-effort
            _DISK_ERRORS.inc(op="store")
