"""Host-partitioned spill execution for over-budget hash joins.

Analog of the reference's spill-to-disk join
(spiller/GenericPartitioningSpiller.java:50,
operator/join/HashBuilderOperator.java:183-191 spill/unspill state
machine): when the plan-time memory estimate (presto_tpu/memory.py)
exceeds the session budget, the dominant join's build AND probe inputs
are materialized to HOST RAM (the TPU's spill medium), hash-partitioned
by the join keys on host, and the join runs partition-by-partition on
device — HBM holds one partition's tables at a time, bounded by
budget/partitions. The rest of the plan then runs over the concatenated
join output through the normal compiled path.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Column, Table
from presto_tpu.exec import hostsync as HS
from presto_tpu.memory import MemoryLimitExceeded, estimate_plan_memory
from presto_tpu.ops.hash import next_pow2
from presto_tpu.plan import nodes as N


def _compact(tbl: Table) -> Table:
    if tbl.mask is None:
        return tbl
    m = np.asarray(tbl.mask)
    cols = {}
    for s, c in tbl.columns.items():
        cols[s] = Column(c.dtype, np.asarray(c.data)[m],
                         None if c.valid is None
                         else np.asarray(c.valid)[m], c.dictionary)
    return Table(cols, int(m.sum()), None)


def _value_hash(tbl: Table, keys: list[str],
                null_canonical: bool = False) -> tuple:
    """(uint64 hash per row, all-keys-valid mask) — value-based (strings
    hash their dictionary text via the cached content hash in ops/hash)
    so probe and build partition identically even with different
    dictionaries. ``null_canonical`` replaces NULL rows' values with a
    fixed sentinel so key tuples that are group-equal (both NULL) hash
    equal — required by the aggregation spill (joins drop NULL keys
    instead)."""
    from presto_tpu.ops.hash import hash_string_dictionary

    n = tbl.nrows
    h = np.full(n, 0x243F6A8885A308D3, np.uint64)
    valid = np.ones(n, bool)
    for k in keys:
        c = tbl.columns[k]
        if c.dictionary is not None:
            lut = hash_string_dictionary(c.dictionary)
            if len(lut) == 0:
                v = np.zeros(n, np.int64)
            else:
                codes = np.clip(np.asarray(c.data).astype(np.int64),
                                0, len(lut) - 1)
                v = lut[codes].astype(np.int64)
        else:
            v = np.asarray(c.data).astype(np.int64)
        if null_canonical and c.valid is not None:
            v = np.where(np.asarray(c.valid), v,
                         np.int64(0x5BD1E995))
        x = v.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15)
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        h = (h ^ x) * np.uint64(0x100000001B3)
        if c.valid is not None:
            valid &= np.asarray(c.valid)
    return h, valid


def _slice_table(tbl: Table, idx: np.ndarray) -> Table:
    cols = {}
    for s, c in tbl.columns.items():
        cols[s] = Column(c.dtype, np.asarray(c.data)[idx],
                         None if c.valid is None
                         else np.asarray(c.valid)[idx], c.dictionary)
    return Table(cols, len(idx), None)


def _carrier_scan(name: str, tbl: Table, pad_to: int | None = None
                  ) -> tuple:
    """(TableScan node, ScanInput) serving a host Table verbatim.
    ``pad_to`` pads the arrays to a fixed row count (dead-padded via
    __live__) so differently-sized partitions share ONE compiled
    program."""
    from presto_tpu.exec.executor import ScanInput

    types = {s: c.dtype for s, c in tbl.columns.items()}
    node = N.TableScan("__spill__", name,
                       {s: s for s in types}, types)
    n = tbl.nrows
    # only padded carriers get a minimum row (their __live__ mask kills
    # the pad); an unpadded 0-row table must stay 0 rows
    total = max(pad_to, 1) if pad_to is not None else n
    arrays: dict[str, np.ndarray] = {}
    dicts: dict[str, np.ndarray | None] = {}
    for s, c in tbl.columns.items():
        a = np.asarray(c.data)
        if isinstance(c.dtype, T.ArrayType) and a.dtype == object:
            from presto_tpu.block import pad_object_lists
            d2, lens, emask, d = pad_object_lists(c.dtype.element, a)
            arrays[s] = np.pad(d2, [(0, total - n), (0, 0)])
            arrays[f"{s}$len"] = np.pad(lens, (0, total - n))
            arrays[f"{s}$emask"] = np.pad(emask,
                                          [(0, total - n), (0, 0)])
            dicts[s] = d
        else:
            arrays[s] = np.pad(a, [(0, total - n)]
                               + [(0, 0)] * (a.ndim - 1))
            dicts[s] = c.dictionary
        if c.valid is not None:
            arrays[f"{s}$valid"] = np.pad(np.asarray(c.valid),
                                          (0, total - n))
    if pad_to is not None:
        arrays["__live__"] = np.arange(total) < n
    return node, ScanInput(node, arrays, dicts, types, total)


def _concat_tables(parts: list[Table]) -> Table:
    cols: dict[str, Column] = {}
    live = np.concatenate([
        np.asarray(p.mask) if p.mask is not None
        else np.ones(p.nrows, bool) for p in parts])
    for s in parts[0].columns:
        cs = [p.columns[s] for p in parts]
        data = np.concatenate([np.asarray(c.data) for c in cs])
        if any(c.valid is not None for c in cs):
            valid = np.concatenate([
                np.asarray(c.valid) if c.valid is not None
                else np.ones(p.nrows, bool)
                for c, p in zip(cs, parts)])
        else:
            valid = None
        cols[s] = Column(cs[0].dtype, data, valid, cs[0].dictionary)
    return Table(cols, len(live), live)


def _run_partitions(engine, jp: N.Join, part_inputs: list) -> list[Table]:
    """Run the per-partition join with ONE compiled program: partitions
    are padded to identical shapes, so a single jitted trace serves all
    of them (a fresh run_plan per partition would re-trace and
    re-compile ~nparts times). Capacity overflow in any partition grows
    the table and recompiles once for all."""
    import jax

    from presto_tpu.exec.executor import make_traced

    capacities: dict[tuple, int] = {}
    for _attempt in range(10):
        pinput0, binput0 = part_inputs[0]
        # collect_rows off: one program replays over many partitions,
        # so per-node totals would misattribute to the first partition
        traced_fn, _flat, meta = make_traced(
            [pinput0, binput0], jp, capacities, engine.session,
            collect_rows=False)
        compiled = jax.jit(traced_fn)
        from presto_tpu.exec.cancel import checkpoint
        results = []
        overflow = False
        for pinput, binput in part_inputs:
            checkpoint()
            feed = [pinput.arrays[s] for s in pinput0.arrays] + \
                   [binput.arrays[s] for s in binput0.arrays]
            res, live, oks = compiled(*feed)
            oks_np = HS.fetch(oks, site="spill-ok-ladder")
            if not oks_np.all():
                for key, okv in zip(meta["ok_keys"], oks_np):
                    if not okv:
                        capacities[key] = 4 * meta["used_capacity"][key]
                overflow = True
                break
            results.append((res, live))
        if not overflow:
            break
    else:
        raise RuntimeError("spill partition capacity retry limit")

    outs = []
    for res, live in results:
        # one batched transfer per partition, not one per column
        res_np, live_np = HS.fetch((list(res), live),
                                   site="spill-demux")
        cols: dict[str, Column] = {}
        i = 0
        for sym, dtype, dictionary, has_valid in meta["out"]:
            data = res_np[i]
            valid = res_np[i + 1]
            i += 2
            cols[sym] = Column(
                dtype, data,
                valid if has_valid or not valid.all() else None,
                dictionary)
        outs.append(Table(cols, len(live_np), live_np))
    return outs


def _spill_aggregate(engine, plan: N.PlanNode, agg: N.Aggregate,
                     total: int, budget: int):
    """Aggregation spill: hash-partition the aggregate's input rows by
    GROUP KEYS on host and aggregate partition-by-partition — groups
    cannot span partitions, so per-partition SINGLE aggregation
    concatenates to the exact global result (the reference's
    SpillableHashAggregationBuilder reaches the same shape by spilling
    raw group-by input partitions and merging per partition)."""
    from presto_tpu.exec.executor import execute_plan, run_plan
    from presto_tpu.exec.streaming import _replace_node

    in_spill_before = getattr(engine, "_in_spill", False)
    engine._in_spill = True
    try:
        input_tbl = _compact(execute_plan(engine, agg.source))
    finally:
        engine._in_spill = in_spill_before

    nparts = max(2, next_pow2(-(-total // budget)))
    if nparts > 64:
        raise MemoryLimitExceeded(
            f"query estimated {total} bytes cannot be bounded by "
            f"query_max_memory_bytes={budget} within 64 spill "
            f"partitions")
    h, _valid = _value_hash(input_tbl, agg.group_keys,
                            null_canonical=True)
    part = (h % np.uint64(nparts)).astype(np.int64)
    counts = np.bincount(part, minlength=nparts)
    live_parts = [p for p in range(nparts) if counts[p] > 0]
    # pow2-bucket the partition width (lint/retrace.py): the raw
    # bincount max is a data-dependent int that would otherwise set
    # every carrier-scan shape, retracing the partition program per
    # dataset; dead padding rows are masked by the carrier's __live__
    pmax = next_pow2(max(int(counts.max()), 1))

    part_inputs = []
    ap = None
    pcap = next_pow2(max(2 * min(
        pmax, (agg.capacity or pmax)), 16))
    for p in live_parts:
        tp = _slice_table(input_tbl, np.nonzero(part == p)[0])
        cnode, cinput = _carrier_scan("agg_part", tp, pad_to=pmax)
        if ap is None:
            ap = dataclasses.replace(agg, source=cnode, capacity=pcap)
        else:
            cinput = dataclasses.replace(cinput, node=ap.source)
        part_inputs.append((cinput,))
    outs = _run_partition_plans(engine, ap, part_inputs) \
        if part_inputs else []

    if not outs:
        merged = Table(
            {s: Column(t, np.empty(0, t.physical_dtype), None,
                       np.empty(0, object)
                       if isinstance(t, T.VarcharType) else None)
             for s, t in agg.output_types().items()}, 0, None)
    else:
        merged = _concat_tables(outs)
    engine.last_spill = {"partitions": nparts, "kind": "aggregate",
                         "input_rows": input_tbl.nrows,
                         "estimated_bytes": total, "budget": budget}
    carrier_node, carrier_input = _carrier_scan("__aggregated__",
                                                _compact(merged))
    rest = _replace_node(plan, agg, carrier_node)
    return run_plan(engine, rest, [carrier_input])


def _run_partition_plans(engine, root: N.PlanNode,
                         part_inputs: list) -> list[Table]:
    """Generalized _run_partitions: one compiled program over any
    fragment with N carrier scans, replayed per partition."""
    import jax

    from presto_tpu.exec.cancel import checkpoint
    from presto_tpu.exec.executor import make_traced

    capacities: dict[tuple, int] = {}
    for _attempt in range(10):
        inputs0 = part_inputs[0]
        # collect_rows off: see _run_partitions (per-partition replay)
        traced_fn, _flat, meta = make_traced(
            list(inputs0), root, capacities, engine.session,
            collect_rows=False)
        compiled = jax.jit(traced_fn)
        results = []
        overflow = False
        for inputs in part_inputs:
            checkpoint()
            feed = []
            for inp, inp0 in zip(inputs, inputs0):
                feed.extend(inp.arrays[s] for s in inp0.arrays)
            res, live, oks = compiled(*feed)
            oks_np = HS.fetch(oks, site="spill-ok-ladder")
            if not oks_np.all():
                for key, okv in zip(meta["ok_keys"], oks_np):
                    if not okv:
                        capacities[key] = 4 * meta["used_capacity"][key]
                overflow = True
                break
            results.append((res, live))
        if not overflow:
            break
    else:
        raise RuntimeError("spill partition capacity retry limit")

    outs = []
    for res, live in results:
        # one batched transfer per partition, not one per column
        res_np, live_np = HS.fetch((list(res), live),
                                   site="spill-demux")
        cols: dict[str, Column] = {}
        i = 0
        for sym, dtype, dictionary, has_valid in meta["out"]:
            data = res_np[i]
            valid = res_np[i + 1]
            i += 2
            cols[sym] = Column(
                dtype, data,
                valid if has_valid or not valid.all() else None,
                dictionary)
        outs.append(Table(cols, len(live_np), live_np))
    return outs


def try_execute_spilled(engine, plan: N.PlanNode):
    """Execute with host-partitioned join spill, or return None when the
    budget (query_max_memory_bytes) is unset or the plan fits.

    Enforcement contract: over budget, the first join on the plan's
    root chain spills (its subplans re-enter this check recursively, so
    nested joins cascade); a plan with no spillable join fails with
    MemoryLimitExceeded — except inside a spill driver's own subplan
    executions, whose scans materialize to host (the spill medium) by
    design."""
    budget = int(engine.session.get("query_max_memory_bytes") or 0)
    if budget <= 0:
        return None
    total, per_node = estimate_plan_memory(plan, engine)
    if total <= budget:
        return None
    if not engine.session.get("spill_enabled"):
        raise MemoryLimitExceeded(
            f"query estimated {total} bytes exceeds "
            f"query_max_memory_bytes={budget} and spill is disabled")
    # the spill machinery partitions root-chain Join nodes by their
    # keys; under memory pressure that outranks multi-way fusion, so
    # fused star chains expand back into the binary cascade first
    from presto_tpu.plan.optimizer import unfuse_multijoin
    plan = unfuse_multijoin(plan)

    # first multi-source node on the root chain: a Join spills by join
    # keys; failing that, a grouped Aggregate spills by group keys
    # (SpillableHashAggregationBuilder analog); other shapes cannot be
    # partition-bounded
    node = plan
    grouped_agg = None
    while True:
        srcs = node.sources()
        if isinstance(node, N.Join) and node.criteria:
            join = node
            break
        if isinstance(node, N.Aggregate) and node.group_keys \
                and node.step == N.AggStep.SINGLE \
                and grouped_agg is None:
            grouped_agg = node
        if len(srcs) != 1:
            if grouped_agg is not None:
                return _spill_aggregate(engine, plan, grouped_agg,
                                        total, budget)
            if getattr(engine, "_in_spill", False):
                return None  # host-side subplan: already spilled medium
            raise MemoryLimitExceeded(
                f"query estimated {total} bytes exceeds "
                f"query_max_memory_bytes={budget} and this plan shape "
                f"has no spillable join on its root chain")
        node = srcs[0]

    nparts = max(2, next_pow2(-(-total // budget)))
    if nparts > 64:
        raise MemoryLimitExceeded(
            f"query estimated {total} bytes cannot be bounded by "
            f"query_max_memory_bytes={budget} within 64 spill "
            f"partitions")
    merged, build_rows = _partitioned_join_exec(engine, join, nparts)
    engine.last_spill = {"partitions": nparts,
                         "build_rows": build_rows,
                         "estimated_bytes": total, "budget": budget}
    return _resume_above_join(engine, plan, join, merged)


def _partitioned_join_exec(engine, join: N.Join, nparts: int):
    """Materialize both join sides to host, hash-partition by the join
    keys, and run the per-partition join with one compiled program.
    Shared by the memory-pressure spill path and grouped execution
    (lifespans): the only difference is what decides ``nparts``."""
    from presto_tpu.exec.executor import execute_plan

    in_spill_before = getattr(engine, "_in_spill", False)
    engine._in_spill = True
    try:
        build_tbl = _compact(execute_plan(engine, join.right))
        probe_tbl = _compact(execute_plan(engine, join.left))
    finally:
        engine._in_spill = in_spill_before

    lkeys = [lk for lk, _ in join.criteria]
    rkeys = [rk for _, rk in join.criteria]
    ph, pvalid = _value_hash(probe_tbl, lkeys)
    bh, bvalid = _value_hash(build_tbl, rkeys)
    ppart = (ph % np.uint64(nparts)).astype(np.int64)
    bpart = (bh % np.uint64(nparts)).astype(np.int64)
    outer = join.join_type == N.JoinType.LEFT
    # NULL-key rows never match: drop from build always, and from the
    # probe unless the join is outer (those rows still emit)
    if not outer:
        ppart[~pvalid] = -1
    bpart[~bvalid] = -1

    # uniform padded partition shapes -> the join compiles ONCE and the
    # same program runs for every partition (reference unspill replays
    # one operator pipeline per spilled partition too)
    pcounts = np.bincount(ppart[ppart >= 0], minlength=nparts)
    live_parts = [p for p in range(nparts) if pcounts[p] > 0]
    # pow2-bucket the carrier widths (lint/retrace.py): the raw
    # bincount maxes are data-dependent ints that would otherwise set
    # every partition carrier's shape, compiling one join program per
    # dataset; dead padding rows are masked by the carrier's __live__
    pmax = next_pow2(max(int(pcounts.max()), 1))
    bmax = next_pow2(max(int(np.bincount(bpart[bpart >= 0],
                                         minlength=nparts)
                             .max()), 1))
    part_inputs = []
    jp = None
    for p in live_parts:
        pp = _slice_table(probe_tbl, np.nonzero(ppart == p)[0])
        bp = _slice_table(build_tbl, np.nonzero(bpart == p)[0])
        pnode, pinput = _carrier_scan("probe_part", pp, pad_to=pmax)
        bnode, binput = _carrier_scan("build_part", bp, pad_to=bmax)
        if jp is None:
            jp = dataclasses.replace(
                join, left=pnode, right=bnode,
                build_rows=bmax,
                capacity=next_pow2(2 * bmax),
                output_capacity=None if join.build_unique
                else next_pow2(2 * (pmax + bmax)))
        else:
            pinput = dataclasses.replace(pinput, node=jp.left)
            binput = dataclasses.replace(binput, node=jp.right)
        part_inputs.append((pinput, binput))
    outs = _run_partitions(engine, jp, part_inputs) if part_inputs else []

    if not outs:
        merged = Table(
            {s: Column(t, np.empty(0, t.physical_dtype), None,
                       np.empty(0, object)
                       if isinstance(t, T.VarcharType) else None)
             for s, t in join.output_types().items()}, 0, None)
    else:
        merged = _concat_tables(outs)
    return merged, build_tbl.nrows


def _resume_above_join(engine, plan, join, merged: Table):
    from presto_tpu.exec.executor import run_plan
    from presto_tpu.exec.streaming import _replace_node
    carrier_node, carrier_input = _carrier_scan("__joined__",
                                                _compact(merged))
    rest = _replace_node(plan, join, carrier_node)
    return run_plan(engine, rest, [carrier_input])


# --- grouped execution (lifespans) -----------------------------------------


def _bucketed_keys(engine, node):
    """Connector-declared partitioning symbols for a Filter*/TableScan
    subtree, or None."""
    from presto_tpu.exec.executor import partitioning_symbols
    while isinstance(node, N.Filter):
        node = node.source
    if not isinstance(node, N.TableScan):
        return None
    conn = engine.catalogs.get(node.catalog)
    if conn is None:
        return None
    return partitioning_symbols(conn, node)


def try_execute_grouped(engine, plan):
    """Grouped execution over co-bucketed tables: when both sides of a
    root-chain join are scans of tables whose connector-defined
    partitioning IS the join key, execute the join bucket-by-bucket so
    peak memory is one bucket's working set — the lifespans model
    (reference execution/Lifespan.java:26 +
    scheduler/group/LifespanScheduler.java, StageExecutionDescriptor
    grouped execution), opted in via the grouped_execution session
    property."""
    if not engine.session.get("grouped_execution"):
        return None
    node = plan
    while True:
        if isinstance(node, N.Join) and node.criteria:
            lkeys = tuple(lk for lk, _ in node.criteria)
            rkeys = tuple(rk for _, rk in node.criteria)
            if (_bucketed_keys(engine, node.left) == lkeys
                    and _bucketed_keys(engine, node.right) == rkeys):
                nparts = max(1, int(
                    engine.session.get("grouped_execution_partitions")))
                merged, build_rows = _partitioned_join_exec(
                    engine, node, nparts)
                engine.last_grouped = {"partitions": nparts,
                                       "build_rows": build_rows,
                                       "keys": list(lkeys)}
                return _resume_above_join(engine, plan, node, merged)
            return None
        srcs = node.sources()
        if len(srcs) != 1:
            return None
        node = srcs[0]
