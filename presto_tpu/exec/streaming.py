"""Block-streamed scan execution: the split analog.

The reference streams tables through workers as connector splits
(split/SplitManager.java, plugin/trino-tpch/.../TpchSplitManager.java:55)
so no operator ever holds a whole table. The TPU analog: when a plan is a
single big scan feeding (through filters/projections) one aggregation,
execute the scan in fixed-size row blocks through ONE compiled
partial-aggregate kernel, accumulate the per-block partial states
(bounded by the group-count capacity, not the table size), then run the
rest of the plan over the merged partials. HBM holds one block at a
time, so tables larger than device memory stream through.

Shape requirements (else the whole-table path runs): exactly one
TableScan; only Filter/Project between it and a single-step Aggregate;
anything above the Aggregate (sort/limit/output operate on the small
aggregated result).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from presto_tpu import types as T
from presto_tpu.exec import hostsync as HS
from presto_tpu.plan import nodes as N


def _chain_to_scan(node: N.PlanNode) -> N.TableScan | None:
    """The TableScan under ``node`` if the path is all Filter/Project."""
    while isinstance(node, (N.Filter, N.Project)):
        node = node.source
    return node if isinstance(node, N.TableScan) else None


def _count_scans(plan: N.PlanNode) -> int:
    n = 1 if isinstance(plan, N.TableScan) else 0
    return n + sum(_count_scans(s) for s in plan.sources())


def _find_streamable(plan: N.PlanNode):
    """Find (aggregate, scan) when the plan is streamable."""
    if _count_scans(plan) != 1:
        return None
    node = plan
    while not isinstance(node, N.Aggregate):
        srcs = node.sources()
        if len(srcs) != 1:
            return None
        node = srcs[0]
    if node.step != N.AggStep.SINGLE:
        return None
    if any(call.distinct for call in node.aggs.values()):
        return None
    scan = _chain_to_scan(node.source)
    if scan is None:
        return None
    return node, scan


def _replace_node(plan: N.PlanNode, target: N.PlanNode,
                  repl: N.PlanNode) -> N.PlanNode:
    if plan is target:
        return repl
    updates = {}
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, N.PlanNode):
            updates[f.name] = _replace_node(v, target, repl)
        elif isinstance(v, list) and v and isinstance(v[0], N.PlanNode):
            updates[f.name] = [_replace_node(x, target, repl) for x in v]
    return dataclasses.replace(plan, **updates) if updates else plan


def try_execute_streamed(engine, plan: N.PlanNode):
    """Execute ``plan`` block-streamed, or return None if inapplicable."""
    from presto_tpu.exec.executor import (
        ScanInput, collect_scans, make_traced, run_plan)

    block = int(engine.session.get("scan_block_rows") or 0)
    if block <= 0:
        return None
    found = _find_streamable(plan)
    if found is None:
        return None
    agg, scan_node = found
    scans = collect_scans(plan, engine)
    scan = scans[0]
    if scan.nrows <= block:
        return None

    # -- phase 1: one compiled partial-aggregate program, run per block --
    partial = dataclasses.replace(agg, step=N.AggStep.PARTIAL)
    nblocks = -(-scan.nrows // block)
    capacities: dict[tuple, int] = {}
    partial_cols: list[list[np.ndarray]] = []
    partial_live: list[np.ndarray] = []
    out_schema = None

    def block_input(i: int) -> dict[str, np.ndarray]:
        lo, hi = i * block, min((i + 1) * block, scan.nrows)
        out = {}
        for sym, a in scan.arrays.items():
            b = a[lo:hi]
            if hi - lo < block:
                b = np.pad(b, [(0, block - (hi - lo))]
                           + [(0, 0)] * (a.ndim - 1))
            out[sym] = b
        out["__live__"] = np.arange(block) < (hi - lo)
        return out

    from presto_tpu.exec.cancel import checkpoint
    compiled = None
    meta = None
    for i in range(nblocks):
        checkpoint()
        arrays = block_input(i)
        for _attempt in range(10):
            if compiled is None:
                block_scan = ScanInput(scan.node, arrays,
                                       scan.dictionaries, scan.types,
                                       block)
                # collect_rows off: the block program replays per
                # block; run_plan over the concatenated partials (the
                # final program) still records its stats normally
                traced_fn, _flat, meta = make_traced(
                    [block_scan], partial, capacities, engine.session,
                    collect_rows=False)
                compiled = jax.jit(traced_fn)
            res, live, oks = compiled(
                *[arrays[sym] for sym in scan.arrays], arrays["__live__"])
            oks_np = HS.fetch(oks, site="streaming-ok-ladder")
            if oks_np.all():
                break
            from presto_tpu.ops.hash import grow_overflowed
            grow_overflowed(capacities, meta["ok_keys"], oks_np,
                            meta["used_capacity"])
            compiled = None  # recompile with grown capacity
        else:
            from presto_tpu.ops.hash import HashChainOverflow
            raise HashChainOverflow(
                "hash table capacity retry limit exceeded")
        out_schema = meta["out"]
        # one batched transfer per block, not one per output column
        res_np, live_np = HS.fetch((list(res), live),
                                   site="streaming-demux")
        partial_cols.append(res_np)
        partial_live.append(live_np)

    # -- phase 2: rest of the plan over the concatenated partials --------
    carrier_syms = [sym for sym, _t, _d, _v in out_schema]
    carrier_types = {sym: t for sym, t, _d, _v in out_schema}
    carrier = N.TableScan("__stream__", "__partials__",
                          {sym: sym for sym in carrier_syms},
                          carrier_types)
    final_agg = dataclasses.replace(agg, source=carrier,
                                    step=N.AggStep.FINAL)
    plan2 = _replace_node(plan, agg, final_agg)

    arrays2: dict[str, np.ndarray] = {}
    dicts2: dict[str, np.ndarray | None] = {}
    for j, (sym, _t, d, has_valid) in enumerate(out_schema):
        arrays2[sym] = np.concatenate([p[2 * j] for p in partial_cols])
        if has_valid:
            arrays2[f"{sym}$valid"] = np.concatenate(
                [p[2 * j + 1] for p in partial_cols])
        dicts2[sym] = d
    arrays2["__live__"] = np.concatenate(partial_live)
    total = int(arrays2["__live__"].shape[0])
    carrier_input = ScanInput(carrier, arrays2, dicts2, carrier_types,
                              total)
    engine.last_streamed_blocks = nblocks
    return run_plan(engine, plan2, [carrier_input])
