"""Host-finalized variable-length aggregates: array_agg / map_agg /
listagg.

Fixed-width HBM arrays cannot hold per-group variable-length values, so
these aggregates split execution: the device runs the aggregate's
source subplan (and the scalar part of the aggregation as usual), then
the host groups the materialized argument rows and assembles the
variable-length results — the same device/host split the reference
makes between its fixed-slice accumulators and the typed heap blocks
behind array_agg (operator/aggregation/ArrayAggregationFunction,
MapAggAggregationFunction, ListaggAggregationFunction).

Supported plan shape: the varlen Aggregate may sit under any chain of
Output / Project (varlen symbols passed through as bare references) /
Sort / Limit nodes. Anything else (varlen value feeding a scalar
expression, joins above the aggregation) raises a clear error.
"""

from __future__ import annotations

import numpy as np

from presto_tpu import types as T
from presto_tpu.block import Column, Table, _decode_column
from presto_tpu.expr import aggregates as A
from presto_tpu.expr import ir
from presto_tpu.plan import nodes as N


def find_varlen_aggregate(plan: N.PlanNode) -> N.Aggregate | None:
    """The (single) Aggregate node carrying varlen calls, or None."""
    found: list[N.Aggregate] = []

    def visit(node):
        if isinstance(node, N.Aggregate) and any(
                c.fn in A.VARLEN_FNS for c in node.aggs.values()):
            found.append(node)
        for s in node.sources():
            visit(s)

    visit(plan)
    if len(found) > 1:
        raise NotImplementedError(
            "multiple variable-length aggregations in one query")
    return found[0] if found else None


def _chain_to(plan: N.PlanNode, target: N.Aggregate) -> list[N.PlanNode]:
    """Root->target node chain; validates the supported shape."""
    chain: list[N.PlanNode] = []
    node = plan
    varlen_syms = {s for s, c in target.aggs.items()
                   if c.fn in A.VARLEN_FNS}
    while node is not target:
        if isinstance(node, N.Output):
            pass
        elif isinstance(node, N.Project):
            for sym, e in node.assignments.items():
                refs = ir.referenced_columns([e])
                if refs & varlen_syms and not isinstance(e, ir.ColumnRef):
                    raise NotImplementedError(
                        "variable-length aggregate results cannot feed "
                        "scalar expressions")
        elif isinstance(node, (N.Sort, N.TopN, N.Limit)):
            if isinstance(node, (N.Sort, N.TopN)) and any(
                    o.symbol in varlen_syms for o in node.orderings):
                raise NotImplementedError(
                    "ORDER BY on a variable-length aggregate result")
        else:
            raise NotImplementedError(
                f"plan node {type(node).__name__} above a "
                "variable-length aggregation is unsupported")
        chain.append(node)
        srcs = node.sources()
        if len(srcs) != 1:
            raise NotImplementedError(
                "variable-length aggregation under a multi-source node")
        node = srcs[0]
    return chain


def _strip_and_rebuild(chain: list[N.PlanNode], agg: N.Aggregate,
                       scalar_agg: N.Aggregate,
                       keep_syms: list[str]) -> N.PlanNode:
    """Rebuild the chain over ``scalar_agg`` with varlen symbols removed
    and group keys (``keep_syms``) passed through every level."""
    import dataclasses

    varlen_syms = {s for s, c in agg.aggs.items()
                   if c.fn in A.VARLEN_FNS}
    node: N.PlanNode = scalar_agg
    for level in reversed(chain):
        if isinstance(level, N.Output):
            keep_pairs = [(n, s) for n, s in
                          zip(level.names, level.symbols)
                          if s not in varlen_syms]
            names = [n for n, _ in keep_pairs]
            syms = [s for _, s in keep_pairs]
            # every group key also rides under a reserved name so host
            # matching never depends on what the user selected
            for k in keep_syms:
                names.append(f"__vl_{k}")
                syms.append(k)
            node = N.Output(node, names, syms)
        elif isinstance(level, N.Project):
            assigns = {s: e for s, e in level.assignments.items()
                       if not (ir.referenced_columns([e]) & varlen_syms)}
            for k in keep_syms:
                if k not in assigns:
                    assigns[k] = ir.ColumnRef(
                        _sym_type(scalar_agg, k), k)
            node = N.Project(node, assigns)
        else:
            node = dataclasses.replace(level, source=node)
    return node


def _sym_type(agg: N.Aggregate, sym: str) -> T.DataType:
    return agg.source.output_types()[sym]


def _decoded(col: Column):
    """(values as a plain Python list, validity list or None)."""
    data = _decode_column(col.dtype, np.asarray(col.data), col.dictionary)
    values = np.asarray(data).tolist()
    valid = None if col.valid is None else np.asarray(col.valid).tolist()
    return values, valid


def _key_tuples(table: Table, keys: list[str]) -> list[tuple]:
    cols = [_decoded(table.columns[k]) for k in keys]
    mask = None if table.mask is None else np.asarray(table.mask)
    out = []
    for i in range(table.nrows):
        if mask is not None and not mask[i]:
            out.append(None)
            continue
        out.append(tuple(
            None if v is not None and not v[i] else d[i]
            for d, v in cols))
    return out


def _execute_varlen_carrier(engine, plan: N.PlanNode,
                            agg: N.Aggregate) -> Table:
    """General shape: materialize the varlen aggregate alone (host
    object lists), then run the REST of the plan over a carrier scan —
    the 2D padded array layout (block.pad_object_lists) makes the
    aggregate's array outputs consumable by any downstream expression
    (cardinality/transform/UNNEST, VERDICT r3 item 4)."""
    from presto_tpu.exec.executor import run_plan
    from presto_tpu.exec.spill import _carrier_scan, _compact
    from presto_tpu.exec.streaming import _replace_node

    sub = N.Output(agg, list(agg.output_symbols),
                   list(agg.output_symbols))
    table = execute_with_varlen(engine, sub, agg)
    carrier_node, carrier_input = _carrier_scan(
        "__varlen__", _compact(table))
    rest = _replace_node(plan, agg, carrier_node)
    return run_plan(engine, rest, [carrier_input])


def execute_with_varlen(engine, plan: N.PlanNode,
                        agg: N.Aggregate) -> Table:
    from presto_tpu.exec.executor import execute_plan

    try:
        chain = _chain_to(plan, agg)
    except NotImplementedError:
        return _execute_varlen_carrier(engine, plan, agg)
    varlen = {s: c for s, c in agg.aggs.items() if c.fn in A.VARLEN_FNS}
    scalar = {s: c for s, c in agg.aggs.items()
              if c.fn not in A.VARLEN_FNS}

    # 1. materialize the aggregation input: group keys + varlen args
    #    (+ order columns), projected to symbols on the source
    need: dict[str, ir.Expr] = {}
    src_types = agg.source.output_types()
    for k in agg.group_keys:
        need[k] = ir.ColumnRef(src_types[k], k)
    arg_syms: dict[str, tuple] = {}
    for sym, call in varlen.items():
        a_sym = f"{sym}$arg"
        need[a_sym] = call.arg
        a2_sym = None
        if call.arg2 is not None:
            a2_sym = f"{sym}$arg2"
            need[a2_sym] = call.arg2
        o_sym = call.order_sym
        if o_sym is not None:
            need[o_sym] = ir.ColumnRef(src_types[o_sym], o_sym)
        if call.mask is not None:  # FILTER (WHERE ...) mask column
            need[call.mask] = ir.ColumnRef(src_types[call.mask],
                                           call.mask)
        arg_syms[sym] = (a_sym, a2_sym, o_sym)
    src_plan = N.Output(N.Project(agg.source, need),
                        list(need), list(need))
    src_table = execute_plan(engine, src_plan)

    # 2. scalar part on device (hidden count keeps the node non-empty)
    if not scalar:
        scalar = {"__vl_cnt": A.AggCall("count_star", None, T.BIGINT)}
    import dataclasses
    scalar_agg = dataclasses.replace(agg, aggs=scalar)
    scalar_plan = _strip_and_rebuild(chain, agg, scalar_agg,
                                     list(agg.group_keys))
    result = execute_plan(engine, scalar_plan)

    # 3. assemble varlen values per group on host
    src_keys = _key_tuples(src_table, list(agg.group_keys))
    values: dict[str, dict] = {sym: {} for sym in varlen}
    per_sym_cols = {}
    for sym, (a_sym, a2_sym, o_sym) in arg_syms.items():
        a = _decoded(src_table.columns[a_sym])
        a2 = _decoded(src_table.columns[a2_sym]) if a2_sym else None
        o = _decoded(src_table.columns[o_sym]) if o_sym else None
        call = varlen[sym]
        m = (_decoded(src_table.columns[call.mask])
             if call.mask is not None else None)
        per_sym_cols[sym] = (a, a2, o, m)
    for i, key in enumerate(src_keys):
        if key is None:
            continue
        for sym, call in varlen.items():
            (ad, av), a2c, oc, mc = per_sym_cols[sym]
            if mc is not None:
                md, mv = mc
                if (mv is not None and not mv[i]) or not md[i]:
                    continue  # row excluded by FILTER
            is_null = av is not None and not av[i]
            # NULL handling per function (reference semantics):
            # array_agg keeps NULL elements, map_agg drops NULL keys,
            # listagg drops NULL values
            if is_null and call.fn != "array_agg":
                continue
            v = None if is_null else ad[i]
            entry = values[sym].setdefault(key, [])
            okey = None
            if oc is not None:
                od, ov = oc
                okey = od[i] if (ov is None or ov[i]) else None
            if call.fn == "map_agg":
                a2d, a2v = a2c
                v2 = a2d[i] if (a2v is None or a2v[i]) else None
                entry.append((okey, v, v2))
            else:
                entry.append((okey, v))

    def finish(call: A.AggCall, entry: list):
        if call.order_sym is not None:
            entry = sorted(
                entry,
                key=lambda t: (t[0] is None, t[0]),
                reverse=call.order_desc)
        if call.fn == "map_agg":
            return {k: v for _, k, v in entry}
        vals = [v for _, v in entry]
        if call.distinct:
            seen, uniq = set(), []
            for v in vals:
                if v not in seen:
                    seen.add(v)
                    uniq.append(v)
            vals = uniq
        if call.fn == "listagg":
            return (call.sep or ",").join(str(v) for v in vals)
        return vals

    # 4. attach host columns to the device result, matched by the
    #    reserved __vl_<key> passthrough columns
    key_cols = [f"__vl_{k}" for k in agg.group_keys]
    if all(c in result.columns for c in key_cols):
        res_keys = _key_tuples(result, key_cols)
    else:  # chain was empty: columns keyed by symbol
        res_keys = _key_tuples(result, list(agg.group_keys))
    out_cols: dict[str, Column] = {}
    root = chain[0] if chain else plan
    # restore the original Output column order/names
    if isinstance(root, N.Output):
        name_syms = list(zip(root.names, root.symbols))
    else:
        name_syms = [(s, s) for s in agg.group_keys + list(agg.aggs)]
    for name, sym in name_syms:
        if sym in varlen:
            call = varlen[sym]
            data = np.empty(result.nrows, dtype=object)
            valid = np.zeros(result.nrows, dtype=bool)
            for i, key in enumerate(res_keys):
                if key is None:
                    continue
                entry = values[sym].get(key)
                if entry is None:
                    # every input was dropped (NULL keys / FILTER):
                    # the accumulator was never initialized -> NULL
                    # (reference MapAggAggregationFunction behavior);
                    # array_agg keeps NULLs so it cannot land here
                    # unless FILTER removed the whole group
                    data[i] = None
                    valid[i] = False
                else:
                    data[i] = finish(call, entry)
                    valid[i] = True
            out_cols[name] = Column(call.dtype, data, valid, None)
        elif name in result.columns:
            out_cols[name] = result.columns[name]
        else:  # chain was empty: keyed by symbol
            out_cols[name] = result.columns[sym]
    return Table(out_cols, result.nrows, result.mask)
