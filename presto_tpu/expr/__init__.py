"""Row-expression IR and its JAX compiler.

The analog of the reference's sql/relational RowExpression IR
(sql/relational/RowExpression.java: CallExpression, ConstantExpression,
SpecialForm) plus sql/gen's ExpressionCompiler — but instead of emitting JVM
bytecode per query, expressions trace to jitted XLA kernels, specialised
per (expression, input types) exactly like PageFunctionCompiler's cache
(sql/gen/PageFunctionCompiler.java:101).
"""

from presto_tpu.expr.ir import (
    Call,
    CaseWhen,
    Cast,
    ColumnRef,
    Expr,
    InList,
    IsNull,
    Literal,
)
from presto_tpu.expr.compile import ExprCompiler, Val

__all__ = [
    "Call", "CaseWhen", "Cast", "ColumnRef", "Expr", "InList", "IsNull",
    "Literal", "ExprCompiler", "Val",
]
