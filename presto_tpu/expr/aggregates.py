"""Aggregate function registry.

Analog of the reference's accumulator framework
(operator/aggregation/AccumulatorCompiler.java + ~90 @AggregationFunction
implementations). Each aggregate defines how to fold masked rows into
per-slot state via segment reductions, how to merge partial states
(the partial->final split used across exchanges, reference
PushPartialAggregationThroughExchange), and how to produce the final value.

State columns are plain device arrays, so partial aggregation states flow
through exchanges like any other column — exactly how the reference ships
serialized accumulator state in Pages.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.expr import ir


@dataclasses.dataclass(frozen=True)
class AggCall:
    """A planned aggregate: function name, argument expression (None for
    count(*)), distinct flag, output type."""

    fn: str
    arg: ir.Expr | None
    dtype: T.DataType
    distinct: bool = False
    # boolean column restricting which rows this call folds (reference
    # Aggregation.mask, fed by MarkDistinct for DISTINCT aggregates)
    mask: str | None = None

    def __str__(self) -> str:
        inner = "*" if self.arg is None else str(self.arg)
        d = "distinct " if self.distinct else ""
        m = f" mask {self.mask}" if self.mask else ""
        return f"{self.fn}({d}{inner}){m}"


# sample/population variance family — all DOUBLE-valued (reference
# operator/aggregation/VarianceAggregation + DoubleSumAggregation kin)
VAR_FNS = frozenset({"variance", "var_samp", "var_pop",
                     "stddev", "stddev_samp", "stddev_pop"})
BOOL_FNS = frozenset({"bool_and", "bool_or", "every"})


def output_type(fn: str, arg_type: T.DataType | None) -> T.DataType:
    if fn in ("count", "count_star", "count_if"):
        return T.BIGINT
    if fn in VAR_FNS or fn == "geometric_mean":
        return T.DOUBLE
    if fn in BOOL_FNS:
        return T.BOOLEAN
    if fn == "sum":
        if isinstance(arg_type, T.DecimalType):
            return T.DecimalType(18, arg_type.scale)
        if isinstance(arg_type, T.DoubleType):
            return T.DOUBLE
        return T.BIGINT
    if fn == "avg":
        if isinstance(arg_type, T.DecimalType):
            # decimal in -> decimal out at the same scale, HALF_UP
            # (reference AverageAggregations decimal path); this repo's
            # tpch catalog serves decimal columns, so parity demands the
            # decimal behavior, not the DOUBLE the reference shows on
            # its own all-DOUBLE tpch catalog
            return T.DecimalType(18, arg_type.scale)
        return T.DOUBLE
    if fn in ("min", "max", "arbitrary"):
        return arg_type
    raise NotImplementedError(f"aggregate {fn}")


def state_type(call: "AggCall", field: str) -> T.DataType:
    """Type of one partial-state column (the wire schema of partial
    aggregation states shipped through exchanges)."""
    if field == "count":
        return T.BIGINT
    if field == "sum":
        if call.fn == "avg":
            at = call.arg.dtype if call.arg is not None else T.BIGINT
            if isinstance(at, T.DecimalType):
                return T.DecimalType(18, at.scale)
            if isinstance(at, T.DoubleType):
                return T.DOUBLE
            return T.BIGINT
        return call.dtype
    if field == "val":
        if call.fn in BOOL_FNS:
            return T.INTEGER  # bool folded as 0/1 through min/max
        return call.arg.dtype if call.arg is not None else call.dtype
    if field in ("m2", "sumlog"):
        return T.DOUBLE
    raise NotImplementedError(field)


# state column suffixes per function (partial aggregation schema)
def state_fields(fn: str) -> list[str]:
    if fn in ("count", "count_star", "count_if"):
        return ["count"]
    if fn == "sum":
        return ["sum", "count"]  # count tracks non-null presence for SQL sum
    if fn == "avg":
        return ["sum", "count"]
    if fn in ("min", "max", "arbitrary") or fn in BOOL_FNS:
        return ["val", "count"]
    if fn in VAR_FNS:
        return ["count", "sum", "m2"]
    if fn == "geometric_mean":
        return ["count", "sumlog"]
    raise NotImplementedError(fn)


def prepare_arg(fn: str, data, arg_type: T.DataType | None):
    """Pre-convert the argument for aggregates that fold in the real
    domain (variance family, geometric_mean): decimals unscale to
    float64 so the states are plain doubles."""
    if fn not in VAR_FNS and fn != "geometric_mean":
        return data
    x = data.astype(jnp.float64)
    if isinstance(arg_type, T.DecimalType):
        x = x / arg_type.unscale_factor
    if fn == "geometric_mean":
        return jnp.log(x)
    return x


def fold(fn: str, data, weight, slots, capacity: int):
    """Fold rows into per-slot states. ``weight`` is bool live&valid.
    Returns dict state-field -> array[capacity]."""
    w = weight
    if fn in ("count", "count_star"):
        return {"count": jax.ops.segment_sum(
            w.astype(jnp.int64), slots, num_segments=capacity)}
    if fn in ("sum", "avg"):
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)  # int32 args must not wrap
        zero = jnp.zeros((), dtype=data.dtype)
        s = jax.ops.segment_sum(
            jnp.where(w, data, zero), slots, num_segments=capacity)
        c = jax.ops.segment_sum(
            w.astype(jnp.int64), slots, num_segments=capacity)
        return {"sum": s, "count": c}
    if fn in ("min", "max", "arbitrary"):
        if fn == "max" or fn == "arbitrary":
            sentinel = _min_sentinel(data.dtype)
            v = jax.ops.segment_max(jnp.where(w, data, sentinel), slots,
                                    num_segments=capacity)
        else:
            sentinel = _max_sentinel(data.dtype)
            v = jax.ops.segment_min(jnp.where(w, data, sentinel), slots,
                                    num_segments=capacity)
        c = jax.ops.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        return {"val": v, "count": c}
    if fn == "count_if":
        return {"count": jax.ops.segment_sum(
            (w & data.astype(bool)).astype(jnp.int64), slots,
            num_segments=capacity)}
    if fn in BOOL_FNS:
        b = data.astype(jnp.int32)
        c = jax.ops.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        if fn == "bool_or":
            v = jax.ops.segment_max(jnp.where(w, b, 0), slots,
                                    num_segments=capacity)
        else:
            v = jax.ops.segment_min(jnp.where(w, b, 1), slots,
                                    num_segments=capacity)
        return {"val": v, "count": c}
    if fn in VAR_FNS:
        # data pre-converted to float64 by prepare_arg. Two-pass M2
        # (centered second moment) per slot — the sumsq - mean^2 form
        # cancels catastrophically for mean >> spread; the reference's
        # accumulators carry M2 for the same reason (Welford merging)
        z = jnp.zeros((), jnp.float64)
        c = jax.ops.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        s = jax.ops.segment_sum(jnp.where(w, data, z), slots,
                                num_segments=capacity)
        mean = s / jnp.maximum(c, 1).astype(jnp.float64)
        d = data - mean[slots]
        m2 = jax.ops.segment_sum(jnp.where(w, d * d, z), slots,
                                 num_segments=capacity)
        return {"count": c, "sum": s, "m2": m2}
    if fn == "geometric_mean":
        z = jnp.zeros((), jnp.float64)
        return {
            "count": jax.ops.segment_sum(w.astype(jnp.int64), slots,
                                         num_segments=capacity),
            "sumlog": jax.ops.segment_sum(jnp.where(w, data, z), slots,
                                          num_segments=capacity),
        }
    raise NotImplementedError(fn)


def merge(fn: str, states: dict, slots, capacity: int, live):
    """Merge partial states (rows of state columns) into a final state
    table — used on the final side of an exchange."""
    w = live
    if fn in ("count", "count_star"):
        return {"count": jax.ops.segment_sum(
            jnp.where(w, states["count"], 0), slots, num_segments=capacity)}
    if fn in ("sum", "avg"):
        zero = jnp.zeros((), dtype=states["sum"].dtype)
        return {
            "sum": jax.ops.segment_sum(
                jnp.where(w, states["sum"], zero), slots,
                num_segments=capacity),
            "count": jax.ops.segment_sum(
                jnp.where(w, states["count"], 0), slots,
                num_segments=capacity),
        }
    if fn in ("min", "max", "arbitrary") or fn in BOOL_FNS:
        seg_max = fn in ("max", "arbitrary", "bool_or")
        if seg_max:
            sentinel = _min_sentinel(states["val"].dtype)
            v = jax.ops.segment_max(
                jnp.where(w, states["val"], sentinel), slots,
                num_segments=capacity)
        else:
            sentinel = _max_sentinel(states["val"].dtype)
            v = jax.ops.segment_min(
                jnp.where(w, states["val"], sentinel), slots,
                num_segments=capacity)
        return {"val": v, "count": jax.ops.segment_sum(
            jnp.where(w, states["count"], 0), slots, num_segments=capacity)}
    if fn == "count_if":
        return {"count": jax.ops.segment_sum(
            jnp.where(w, states["count"], 0), slots, num_segments=capacity)}
    if fn in VAR_FNS:
        # parallel M2 combination (Chan et al.): M2_tot = sum(M2_i) +
        # sum(n_i * (mean_i - mean_tot)^2), all segment reductions
        z = jnp.zeros((), jnp.float64)
        n_i = jnp.where(w, states["count"], 0)
        s_i = jnp.where(w, states["sum"], z)
        n = jax.ops.segment_sum(n_i, slots, num_segments=capacity)
        s = jax.ops.segment_sum(s_i, slots, num_segments=capacity)
        mean_tot = s / jnp.maximum(n, 1).astype(jnp.float64)
        mean_i = s_i / jnp.maximum(n_i, 1).astype(jnp.float64)
        dev = mean_i - mean_tot[slots]
        m2 = jax.ops.segment_sum(
            jnp.where(w, states["m2"], z)
            + n_i.astype(jnp.float64) * dev * dev,
            slots, num_segments=capacity)
        return {"count": n, "sum": s, "m2": m2}
    if fn == "geometric_mean":
        z = jnp.zeros((), jnp.float64)
        return {
            "count": jax.ops.segment_sum(
                jnp.where(w, states["count"], 0), slots,
                num_segments=capacity),
            "sumlog": jax.ops.segment_sum(
                jnp.where(w, states["sumlog"], z), slots,
                num_segments=capacity),
        }
    raise NotImplementedError(fn)


def finalize(fn: str, states: dict, out_type: T.DataType,
             arg_type: T.DataType | None):
    """States -> (data, valid) final columns."""
    if fn in ("count", "count_star"):
        return states["count"], None
    if fn == "sum":
        return states["sum"], states["count"] > 0
    if fn == "avg":
        s, c = states["sum"], states["count"]
        safe = jnp.maximum(c, 1)
        if isinstance(out_type, T.DecimalType):
            # HALF_UP integer division in the scaled domain:
            # sign(s) * ((2|s| + c) // 2c)
            q = jnp.sign(s) * ((2 * jnp.abs(s) + safe) // (2 * safe))
            return q, c > 0
        sf = s.astype(jnp.float64)
        if isinstance(arg_type, T.DecimalType):
            # decimal arg with a declared DOUBLE output (hand-built
            # plans; output_type-planned calls take the branch above)
            sf = sf / arg_type.unscale_factor
        return sf / safe.astype(jnp.float64), c > 0
    if fn in ("min", "max", "arbitrary"):
        return states["val"], states["count"] > 0
    if fn == "count_if":
        return states["count"], None
    if fn in BOOL_FNS:
        return states["val"] > 0, states["count"] > 0
    if fn in VAR_FNS:
        c = states["count"]
        safe = jnp.maximum(c, 1).astype(jnp.float64)
        m2 = states["m2"]
        if fn.endswith("_pop"):
            var = m2 / safe
            ok = c > 0
        else:
            # sample variance: M2/(n-1), undefined for n < 2
            var = m2 / jnp.maximum(safe - 1.0, 1.0)
            ok = c > 1
        if fn.startswith("stddev"):
            return jnp.sqrt(var), ok
        return var, ok
    if fn == "geometric_mean":
        c = states["count"]
        safe = jnp.maximum(c, 1).astype(jnp.float64)
        return jnp.exp(states["sumlog"] / safe), c > 0
    raise NotImplementedError(fn)


def _min_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)


def _max_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)
