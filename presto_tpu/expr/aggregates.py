"""Aggregate function registry.

Analog of the reference's accumulator framework
(operator/aggregation/AccumulatorCompiler.java + ~90 @AggregationFunction
implementations). Each aggregate defines how to fold masked rows into
per-slot state via segment reductions, how to merge partial states
(the partial->final split used across exchanges, reference
PushPartialAggregationThroughExchange), and how to produce the final value.

State columns are plain device arrays, so partial aggregation states flow
through exchanges like any other column — exactly how the reference ships
serialized accumulator state in Pages.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.expr import ir


@dataclasses.dataclass(frozen=True)
class AggCall:
    """A planned aggregate: function name, argument expression (None for
    count(*)), distinct flag, output type."""

    fn: str
    arg: ir.Expr | None
    dtype: T.DataType
    distinct: bool = False
    # boolean column restricting which rows this call folds (reference
    # Aggregation.mask, fed by MarkDistinct for DISTINCT aggregates)
    mask: str | None = None

    def __str__(self) -> str:
        inner = "*" if self.arg is None else str(self.arg)
        d = "distinct " if self.distinct else ""
        m = f" mask {self.mask}" if self.mask else ""
        return f"{self.fn}({d}{inner}){m}"


def output_type(fn: str, arg_type: T.DataType | None) -> T.DataType:
    if fn in ("count", "count_star"):
        return T.BIGINT
    if fn == "sum":
        if isinstance(arg_type, T.DecimalType):
            return T.DecimalType(18, arg_type.scale)
        if isinstance(arg_type, T.DoubleType):
            return T.DOUBLE
        return T.BIGINT
    if fn == "avg":
        if isinstance(arg_type, T.DecimalType):
            # decimal in -> decimal out at the same scale, HALF_UP
            # (reference AverageAggregations decimal path); this repo's
            # tpch catalog serves decimal columns, so parity demands the
            # decimal behavior, not the DOUBLE the reference shows on
            # its own all-DOUBLE tpch catalog
            return T.DecimalType(18, arg_type.scale)
        return T.DOUBLE
    if fn in ("min", "max", "arbitrary"):
        return arg_type
    raise NotImplementedError(f"aggregate {fn}")


def state_type(call: "AggCall", field: str) -> T.DataType:
    """Type of one partial-state column (the wire schema of partial
    aggregation states shipped through exchanges)."""
    if field == "count":
        return T.BIGINT
    if field == "sum":
        if call.fn == "avg":
            at = call.arg.dtype if call.arg is not None else T.BIGINT
            if isinstance(at, T.DecimalType):
                return T.DecimalType(18, at.scale)
            if isinstance(at, T.DoubleType):
                return T.DOUBLE
            return T.BIGINT
        return call.dtype
    if field == "val":
        return call.arg.dtype if call.arg is not None else call.dtype
    raise NotImplementedError(field)


# state column suffixes per function (partial aggregation schema)
def state_fields(fn: str) -> list[str]:
    if fn in ("count", "count_star"):
        return ["count"]
    if fn == "sum":
        return ["sum", "count"]  # count tracks non-null presence for SQL sum
    if fn == "avg":
        return ["sum", "count"]
    if fn in ("min", "max", "arbitrary"):
        return ["val", "count"]
    raise NotImplementedError(fn)


def fold(fn: str, data, weight, slots, capacity: int):
    """Fold rows into per-slot states. ``weight`` is bool live&valid.
    Returns dict state-field -> array[capacity]."""
    w = weight
    if fn in ("count", "count_star"):
        return {"count": jax.ops.segment_sum(
            w.astype(jnp.int64), slots, num_segments=capacity)}
    if fn in ("sum", "avg"):
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)  # int32 args must not wrap
        zero = jnp.zeros((), dtype=data.dtype)
        s = jax.ops.segment_sum(
            jnp.where(w, data, zero), slots, num_segments=capacity)
        c = jax.ops.segment_sum(
            w.astype(jnp.int64), slots, num_segments=capacity)
        return {"sum": s, "count": c}
    if fn in ("min", "max", "arbitrary"):
        if fn == "max" or fn == "arbitrary":
            sentinel = _min_sentinel(data.dtype)
            v = jax.ops.segment_max(jnp.where(w, data, sentinel), slots,
                                    num_segments=capacity)
        else:
            sentinel = _max_sentinel(data.dtype)
            v = jax.ops.segment_min(jnp.where(w, data, sentinel), slots,
                                    num_segments=capacity)
        c = jax.ops.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        return {"val": v, "count": c}
    raise NotImplementedError(fn)


def merge(fn: str, states: dict, slots, capacity: int, live):
    """Merge partial states (rows of state columns) into a final state
    table — used on the final side of an exchange."""
    w = live
    if fn in ("count", "count_star"):
        return {"count": jax.ops.segment_sum(
            jnp.where(w, states["count"], 0), slots, num_segments=capacity)}
    if fn in ("sum", "avg"):
        zero = jnp.zeros((), dtype=states["sum"].dtype)
        return {
            "sum": jax.ops.segment_sum(
                jnp.where(w, states["sum"], zero), slots,
                num_segments=capacity),
            "count": jax.ops.segment_sum(
                jnp.where(w, states["count"], 0), slots,
                num_segments=capacity),
        }
    if fn in ("min", "max", "arbitrary"):
        if fn == "max" or fn == "arbitrary":
            sentinel = _min_sentinel(states["val"].dtype)
            v = jax.ops.segment_max(
                jnp.where(w, states["val"], sentinel), slots,
                num_segments=capacity)
        else:
            sentinel = _max_sentinel(states["val"].dtype)
            v = jax.ops.segment_min(
                jnp.where(w, states["val"], sentinel), slots,
                num_segments=capacity)
        return {"val": v, "count": jax.ops.segment_sum(
            jnp.where(w, states["count"], 0), slots, num_segments=capacity)}
    raise NotImplementedError(fn)


def finalize(fn: str, states: dict, out_type: T.DataType,
             arg_type: T.DataType | None):
    """States -> (data, valid) final columns."""
    if fn in ("count", "count_star"):
        return states["count"], None
    if fn == "sum":
        return states["sum"], states["count"] > 0
    if fn == "avg":
        s, c = states["sum"], states["count"]
        safe = jnp.maximum(c, 1)
        if isinstance(out_type, T.DecimalType):
            # HALF_UP integer division in the scaled domain:
            # sign(s) * ((2|s| + c) // 2c)
            q = jnp.sign(s) * ((2 * jnp.abs(s) + safe) // (2 * safe))
            return q, c > 0
        sf = s.astype(jnp.float64)
        if isinstance(arg_type, T.DecimalType):
            # decimal arg with a declared DOUBLE output (hand-built
            # plans; output_type-planned calls take the branch above)
            sf = sf / arg_type.unscale_factor
        return sf / safe.astype(jnp.float64), c > 0
    if fn in ("min", "max", "arbitrary"):
        return states["val"], states["count"] > 0
    raise NotImplementedError(fn)


def _min_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)


def _max_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)
