"""Aggregate function registry.

Analog of the reference's accumulator framework
(operator/aggregation/AccumulatorCompiler.java + ~90 @AggregationFunction
implementations). Each aggregate defines how to fold masked rows into
per-slot state via segment reductions, how to merge partial states
(the partial->final split used across exchanges, reference
PushPartialAggregationThroughExchange), and how to produce the final value.

State columns are plain device arrays, so partial aggregation states flow
through exchanges like any other column — exactly how the reference ships
serialized accumulator state in Pages.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from presto_tpu import types as T
from presto_tpu.expr import ir
from presto_tpu.ops import segred


@dataclasses.dataclass(frozen=True)
class AggCall:
    """A planned aggregate: function name, argument expression (None for
    count(*)), distinct flag, output type."""

    fn: str
    arg: ir.Expr | None
    dtype: T.DataType
    distinct: bool = False
    # boolean column restricting which rows this call folds (reference
    # Aggregation.mask, fed by MarkDistinct for DISTINCT aggregates)
    mask: str | None = None
    # second argument for two-argument aggregates (min_by/max_by's
    # comparison key, corr/covar/regr's x)
    arg2: ir.Expr | None = None
    # literal parameter (approx_percentile's percentile)
    param: float | None = None
    # varlen aggregates (array_agg/map_agg/listagg): separator literal
    # and intra-group ordering column (host-finalized, exec/varlen.py)
    sep: str | None = None
    order_sym: str | None = None
    order_desc: bool = False

    def __str__(self) -> str:
        inner = "*" if self.arg is None else str(self.arg)
        if self.arg2 is not None:
            inner += f", {self.arg2}"
        if self.param is not None:
            inner += f", {self.param:g}"
        d = "distinct " if self.distinct else ""
        m = f" mask {self.mask}" if self.mask else ""
        return f"{self.fn}({d}{inner}){m}"


# sample/population variance family — all DOUBLE-valued (reference
# operator/aggregation/VarianceAggregation + DoubleSumAggregation kin)
VAR_FNS = frozenset({"variance", "var_samp", "var_pop",
                     "stddev", "stddev_samp", "stddev_pop"})
BOOL_FNS = frozenset({"bool_and", "bool_or", "every"})
# central-moments family: skewness/kurtosis carry (count, sum, m2, m3,
# m4) states (reference CentralMomentsAggregation /
# AggregationUtils.mergeCentralMomentsState)
MOMENT_FNS = frozenset({"skewness", "kurtosis"})
# bivariate co-moment family (reference CentralMomentsAggregation /
# CorrelationAggregation / CovarianceAggregation / RegressionAggregation):
# SQL shape fn(y, x), all DOUBLE-valued, rows with a NULL in either
# argument excluded
COVAR_FNS = frozenset({"corr", "covar_samp", "covar_pop",
                       "regr_slope", "regr_intercept"})
BY_FNS = frozenset({"min_by", "max_by"})
# variable-length-output aggregates: computed host-side at finalization
# (exec/varlen.py) because their results cannot live in fixed-width HBM
# arrays (reference operator/aggregation/ArrayAggregationFunction,
# MapAggAggregationFunction, ListaggAggregationFunction)
VARLEN_FNS = frozenset({"array_agg", "map_agg", "listagg"})

# HyperLogLog register count for approx_distinct: p=11 -> 2048 buckets,
# standard error 1.04/sqrt(2048) ~= 2.3% — the reference's default
# maxStandardError (ApproximateCountDistinctAggregation DEFAULT_STANDARD
# _ERROR 0.023). Registers live in a single [capacity, HLL_M] uint8
# state array: one flattened segment_max folds every row's rank.
HLL_M = 2048
# min-hash reservoir cells for approx_percentile: each group keeps, per
# cell, the row whose 64-bit hash is smallest among rows landing there —
# a mergeable uniform sample of ~K rows per group (TPU-first stand-in
# for the reference's qdigest state; error ~ 1/sqrt(K))
PCT_K = 1024


def output_type(fn: str, arg_type: T.DataType | None) -> T.DataType:
    if fn in ("count", "count_star", "count_if"):
        return T.BIGINT
    if fn in VAR_FNS or fn in MOMENT_FNS or fn == "geometric_mean":
        return T.DOUBLE
    if fn in BOOL_FNS:
        return T.BOOLEAN
    if fn == "sum":
        if isinstance(arg_type, T.DecimalType):
            # LONG input sums exactly in int128 limbs -> decimal(38, s)
            # (reference DecimalSumAggregation); short inputs keep the
            # int64 state (documented headroom: |sum| < 2^63)
            return T.DecimalType(38 if arg_type.is_long else 18,
                                 arg_type.scale)
        if isinstance(arg_type, T.DoubleType):
            return T.DOUBLE
        return T.BIGINT
    if fn == "avg":
        if isinstance(arg_type, T.DecimalType):
            # decimal in -> decimal out at the same scale, HALF_UP
            # (reference AverageAggregations decimal path); this repo's
            # tpch catalog serves decimal columns, so parity demands the
            # decimal behavior, not the DOUBLE the reference shows on
            # its own all-DOUBLE tpch catalog
            return T.DecimalType(38 if arg_type.is_long else 18,
                                 arg_type.scale)
        return T.DOUBLE
    if fn in ("min", "max", "arbitrary"):
        return arg_type
    if fn in ("approx_distinct", "checksum"):
        return T.BIGINT
    if fn in COVAR_FNS:
        return T.DOUBLE
    if fn in BY_FNS or fn == "approx_percentile":
        return arg_type
    if fn == "array_agg":
        return T.ArrayType(arg_type if arg_type is not None else T.UNKNOWN)
    if fn == "listagg":
        return T.VARCHAR
    raise NotImplementedError(f"aggregate {fn}")


def state_type(call: "AggCall", field: str) -> T.DataType:
    """Type of one partial-state column (the wire schema of partial
    aggregation states shipped through exchanges)."""
    if field == "count":
        return T.BIGINT
    if field in ("a", "b", "hi"):
        return T.BIGINT  # int128 limb sums (long-decimal sum/avg)
    if field in ("vlo", "vhi"):
        return T.BIGINT  # int128 extremum limbs (long-decimal min/max)
    if field == "sum":
        if call.fn == "checksum":
            return T.BIGINT  # wrapping uint64 hash sum, bitcast
        if call.fn == "avg":
            at = call.arg.dtype if call.arg is not None else T.BIGINT
            if isinstance(at, T.DecimalType):
                return T.DecimalType(18, at.scale)
            if isinstance(at, T.DoubleType):
                return T.DOUBLE
            return T.BIGINT
        return call.dtype
    if field == "val":
        if call.fn in BOOL_FNS:
            return T.INTEGER  # bool folded as 0/1 through min/max
        if call.fn in BY_FNS:  # extremum of the comparison key (arg2)
            return call.arg2.dtype
        return call.arg.dtype if call.arg is not None else call.dtype
    if field in ("m2", "m3", "m4", "sumlog", "sumx", "sumy", "cxy",
                 "m2x", "m2y", "rval"):
        return T.DOUBLE
    if field in ("regs", "rhash"):
        return T.BIGINT  # nominal: arrays carry their real dtype
    if field == "xval":
        return call.arg.dtype
    if field == "xok":
        return T.BOOLEAN
    raise NotImplementedError(field)


# state column suffixes per function (partial aggregation schema)
def state_fields(fn) -> list[str]:
    """``fn`` is a function name or an AggCall (needed to distinguish
    the long-decimal sum/avg limb states from the int64 state)."""
    if not isinstance(fn, str):
        call = fn
        if long_sum_call(call):
            return ["a", "b", "hi", "count"]
        if long_minmax_call(call):
            return ["vlo", "vhi", "count"]
        fn = call.fn
    if fn in ("count", "count_star", "count_if"):
        return ["count"]
    if fn == "sum":
        return ["sum", "count"]  # count tracks non-null presence for SQL sum
    if fn == "avg":
        return ["sum", "count"]
    if fn in ("min", "max", "arbitrary") or fn in BOOL_FNS:
        return ["val", "count"]
    if fn in VAR_FNS:
        return ["count", "sum", "m2"]
    if fn in MOMENT_FNS:
        return ["count", "sum", "m2", "m3", "m4"]
    if fn == "geometric_mean":
        return ["count", "sumlog"]
    if fn == "approx_distinct":
        return ["regs"]
    if fn == "checksum":
        return ["sum"]
    if fn in COVAR_FNS:
        return ["count", "sumx", "sumy", "cxy", "m2x", "m2y"]
    if fn in BY_FNS:
        return ["val", "xval", "xok", "count"]
    if fn == "approx_percentile":
        return ["rhash", "rval"]
    raise NotImplementedError(fn)


def _value_hash(data):
    """Per-row 64-bit hash of a value column (any numeric dtype).
    Distinct values map to distinct pre-mix words, so the only failure
    mode is a 64-bit hash collision.

    Floats use the double-float decomposition hi=f32(x), lo=f32(x-hi)
    (unique for doubles within f32 exponent range) because this TPU
    toolchain's X64 rewriter has no f64<->u64 bitcast; doubles beyond
    f32 range collapse to the inf fingerprint."""
    from presto_tpu.ops.hash import _splitmix64
    if jnp.issubdtype(data.dtype, jnp.floating):
        x = data.astype(jnp.float64)
        x = jnp.where(x == 0, 0.0, x)  # -0.0 and 0.0 are SQL-equal
        hi = x.astype(jnp.float32)
        lo = (x - hi.astype(jnp.float64)).astype(jnp.float32)
        hb = jax.lax.bitcast_convert_type(hi, jnp.uint32)
        lb = jax.lax.bitcast_convert_type(lo, jnp.uint32)
        bits = (hb.astype(jnp.uint64)
                | (lb.astype(jnp.uint64) << jnp.uint64(32)))
    elif data.dtype == jnp.bool_:
        bits = data.astype(jnp.uint64)
    else:
        bits = data.astype(jnp.int64).astype(jnp.uint64)
    return _splitmix64(bits)


def is_long_decimal(t) -> bool:
    return isinstance(t, T.DecimalType) and t.is_long


def long_minmax_call(call) -> bool:
    """min/max/arbitrary over a LONG decimal argument: the state is the
    extremum's two int64 limbs (vlo/vhi)."""
    return (call.fn in ("min", "max", "arbitrary")
            and call.arg is not None
            and is_long_decimal(call.arg.dtype))


def long_sum_call(call) -> bool:
    """True for sum/avg over a LONG decimal argument: the state is the
    exact int128 limb decomposition (fields a/b/hi/count) instead of an
    int64 running sum (reference DecimalSumAggregation's
    Int128State)."""
    return (call.fn in ("sum", "avg") and call.arg is not None
            and is_long_decimal(call.arg.dtype))


_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1


def _lo_sortable(lo64):
    """Low limb's bit pattern -> order-preserving SIGNED int64 (flip
    the top bit: unsigned u64 order == signed order of the flip)."""
    return (lo64.astype(jnp.uint64)
            ^ jnp.uint64(1 << 63)).astype(jnp.int64)


def _lo_unsortable(s64):
    return (s64.astype(jnp.uint64)
            ^ jnp.uint64(1 << 63)).astype(jnp.int64)


def _limb32(lo64):
    """Non-negative int64 halves of a low limb's bit pattern: each sums
    exactly in int64 for up to 2^31 rows (values < 2^32, sums < 2^63).
    The high 64-bit limb sums separately, wrapping mod 2^64 — the
    recombination in finalize is exact mod 2^128."""
    u = lo64.astype(jnp.uint64)
    a = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64)
    b = (u >> jnp.uint64(32)).astype(jnp.int64)
    return a, b


def _recombine128(a, b, hi64):
    """Per-slot limb sums -> int128 [n, 2] (see _limb32)."""
    from presto_tpu.ops import int128 as I
    ua = a.astype(jnp.uint64)
    ub = b.astype(jnp.uint64)
    lo = ua + (ub << jnp.uint64(32))
    carry = (lo < ua).astype(jnp.uint64)
    hi = hi64.astype(jnp.uint64) + (ub >> jnp.uint64(32)) + carry
    return I.pack(lo, hi)


def _normalize_limbs(states: dict) -> dict:
    """Carry-normalize LONG-decimal a/b partial sums back into the
    32-bit limb domain (hi absorbs the carries, wrapping mod 2^64 —
    the recombination is exact mod 2^128).

    A partial state's ``a``/``b`` accumulate one 32-bit half per row,
    so after N rows each holds up to N * (2^32 - 1): safe in int64 for
    N < 2^31 rows, but the PARTIAL->FINAL merge re-SUMS those already-
    large per-worker sums, so without normalization the merged total
    wraps int64 once the rows covered by the merged states pass 2^31
    (~2 x 10^9 — real at SF1000; ADVICE round 5). Normalized states
    re-enter the per-row domain (a', b' < 2^32), making the merge sum
    safe for up to 2^31 *states* instead of rows."""
    packed = _recombine128(states["a"], states["b"], states["hi"])
    u = packed[..., 0].astype(jnp.uint64)
    return {
        **states,
        "a": (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64),
        "b": (u >> jnp.uint64(32)).astype(jnp.int64),
        "hi": packed[..., 1],
    }


def prepare_arg(fn: str, data, arg_type: T.DataType | None):
    """Pre-convert the argument for aggregates that fold in a derived
    domain: variance family / geometric_mean / covariances unscale
    decimals to float64; sketches hash the value."""
    if fn in ("approx_distinct", "checksum"):
        return _value_hash(data)
    if fn == "approx_percentile":
        return data.astype(jnp.float64)  # scaled domain; recast at end
    if fn in COVAR_FNS:
        x = data.astype(jnp.float64)
        if isinstance(arg_type, T.DecimalType):
            x = x / arg_type.unscale_factor
        return x
    if (fn not in VAR_FNS and fn not in MOMENT_FNS
            and fn != "geometric_mean"):
        return data
    x = data.astype(jnp.float64)
    if isinstance(arg_type, T.DecimalType):
        x = x / arg_type.unscale_factor
    if fn == "geometric_mean":
        return jnp.log(x)
    return x


def prepare_arg2(fn: str, data, arg2_type: T.DataType | None):
    """Pre-convert the second argument (covariance family x; min_by /
    max_by comparison key stays in its natural dtype)."""
    if fn in COVAR_FNS:
        x = data.astype(jnp.float64)
        if isinstance(arg2_type, T.DecimalType):
            x = x / arg2_type.unscale_factor
        return x
    return data


_U64_MAX = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def _bitlen(x):
    """Bit length of a uint64 array (0 for 0) via unrolled binary CLZ —
    no data-dependent control flow, maps to 6 shift/compare rounds."""
    n = jnp.zeros(x.shape, jnp.int32)
    for s in (32, 16, 8, 4, 2, 1):
        big = x >= (jnp.uint64(1) << jnp.uint64(s))
        n = n + jnp.where(big, s, 0)
        x = jnp.where(big, x >> jnp.uint64(s), x)
    return n + (x > 0).astype(jnp.int32)


def _winner_scatter(values, valid, winner, slots, capacity: int):
    """Scatter ``values`` of winner rows to their slots (arbitrary
    winner on ties — SQL allows any row attaining the extremum)."""
    dest = jnp.where(winner, slots, capacity)
    data = jnp.zeros((capacity,), dtype=values.dtype)
    data = data.at[dest].set(values, mode="drop")
    ok = jnp.zeros((capacity,), dtype=bool)
    ok = ok.at[dest].set(valid if valid is not None
                         else jnp.ones(winner.shape, bool), mode="drop")
    return data, ok


def fold(fn: str, data, weight, slots, capacity: int, *,
         data2=None, data_valid=None, param=None):
    """Fold rows into per-slot states. ``weight`` is bool live&valid.
    Returns dict state-field -> array[capacity] (sketch states are
    [capacity, width])."""
    w = weight
    if fn == "approx_distinct":
        # data pre-hashed to uint64 (prepare_arg). Low 11 bits pick the
        # register, the remaining 53 bits' leading-zero rank feeds a
        # single flattened segment_max over [capacity * HLL_M]
        if capacity * HLL_M > (1 << 30):
            raise ValueError(
                "approx_distinct group capacity too large for HLL "
                f"registers ({capacity} slots x {HLL_M})")
        bucket = (data & jnp.uint64(HLL_M - 1)).astype(jnp.int64)
        rank = 54 - _bitlen(data >> jnp.uint64(11))
        seg = slots.astype(jnp.int64) * HLL_M + bucket
        regs = segred.segment_max(
            jnp.where(w, rank, 0), seg, num_segments=capacity * HLL_M)
        return {"regs": regs.reshape(capacity, HLL_M).astype(jnp.uint8)}
    if fn == "checksum":
        # order/partition-invariant wrapping int64 sum of row hashes
        # (reference ChecksumAggregationFunction's XOR equivalent); NULL
        # rows were remapped to a fixed constant by the caller.
        # u64 state reassembles to a wrapped int64 at finalize (no
        # 64-bit bitcast on this TPU toolchain)
        return {"sum": segred.segment_sum(
            jnp.where(w, data, jnp.uint64(0)), slots,
            num_segments=capacity)}
    if fn in COVAR_FNS:
        # two-pass centered co-moments (same cancellation argument as
        # the variance family): y=data, x=data2, both float64
        z = jnp.zeros((), jnp.float64)
        c = segred.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        sy = segred.segment_sum(jnp.where(w, data, z), slots,
                                 num_segments=capacity)
        sx = segred.segment_sum(jnp.where(w, data2, z), slots,
                                 num_segments=capacity)
        cf = jnp.maximum(c, 1).astype(jnp.float64)
        dy = data - (sy / cf)[slots]
        dx = data2 - (sx / cf)[slots]
        seg = lambda v: segred.segment_sum(  # noqa: E731
            jnp.where(w, v, z), slots, num_segments=capacity)
        return {"count": c, "sumx": sx, "sumy": sy, "cxy": seg(dx * dy),
                "m2x": seg(dx * dx), "m2y": seg(dy * dy)}
    if fn in BY_FNS:
        # x=data (kept raw), comparison key y=data2: extremum of y per
        # slot, then the winning row's x scatters into xval/xok
        # (reference MinMaxByNAggregation n=1 semantics: NULL y rows
        # ignored, x may be NULL)
        if fn == "max_by":
            sentinel = _min_sentinel(data2.dtype)
            best = segred.segment_max(jnp.where(w, data2, sentinel),
                                       slots, num_segments=capacity)
        else:
            sentinel = _max_sentinel(data2.dtype)
            best = segred.segment_min(jnp.where(w, data2, sentinel),
                                       slots, num_segments=capacity)
        winner = w & (data2 == best[slots])
        xval, xok = _winner_scatter(data, data_valid, winner, slots,
                                    capacity)
        c = segred.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        return {"val": best, "xval": xval, "xok": xok, "count": c}
    if fn == "approx_percentile":
        # min-hash reservoir: each (slot, cell) keeps the row with the
        # smallest decorrelated row hash — a mergeable uniform sample
        if capacity * PCT_K > (1 << 30):
            raise ValueError(
                "approx_percentile group capacity too large for the "
                f"reservoir ({capacity} slots x {PCT_K})")
        from presto_tpu.ops.hash import _splitmix64
        idx = jnp.arange(data.shape[0], dtype=jnp.uint64)
        h = _splitmix64(_value_hash(data)
                        ^ (idx * jnp.uint64(0xBF58476D1CE4E5B9)))
        cell = (h % jnp.uint64(PCT_K)).astype(jnp.int64)
        seg = slots.astype(jnp.int64) * PCT_K + cell
        minh = segred.segment_min(
            jnp.where(w, h, _U64_MAX), seg,
            num_segments=capacity * PCT_K)
        winner = w & (h == minh[seg])
        dest = jnp.where(winner, seg, capacity * PCT_K)
        rval = jnp.zeros((capacity * PCT_K,), jnp.float64)
        rval = rval.at[dest].set(data, mode="drop")
        return {"rhash": minh.reshape(capacity, PCT_K),
                "rval": rval.reshape(capacity, PCT_K)}
    if fn in ("count", "count_star"):
        return {"count": segred.segment_sum(
            w.astype(jnp.int64), slots, num_segments=capacity)}
    if fn in ("sum", "avg"):
        c = segred.segment_sum(
            w.astype(jnp.int64), slots, num_segments=capacity)
        if data2 is not None:
            # LONG decimal: data/data2 are the int128 value's low/high
            # int64 limbs (see _limb32); three exact int64 segment sums
            z = jnp.zeros((), jnp.int64)
            a, b = _limb32(jnp.where(w, data, z))
            return {"a": segred.segment_sum(a, slots,
                                            num_segments=capacity),
                    "b": segred.segment_sum(b, slots,
                                            num_segments=capacity),
                    "hi": segred.segment_sum(jnp.where(w, data2, z),
                                             slots,
                                             num_segments=capacity),
                    "count": c}
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)  # int32 args must not wrap
        zero = jnp.zeros((), dtype=data.dtype)
        s = segred.segment_sum(
            jnp.where(w, data, zero), slots, num_segments=capacity)
        return {"sum": s, "count": c}
    if fn in ("min", "max", "arbitrary"):
        c = segred.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        if data2 is not None:
            # LONG decimal extremum, two passes: signed high-limb
            # extremum, then the low limb (order-preserving signed
            # view) among high-limb winners
            maxi = fn in ("max", "arbitrary")
            ext = segred.segment_max if maxi else segred.segment_min
            hs = jnp.where(w, data2, _I64_MIN if maxi else _I64_MAX)
            bh = ext(hs, slots, num_segments=capacity)
            winner = w & (data2 == bh[slots])
            ls = jnp.where(winner, _lo_sortable(data),
                           _I64_MIN if maxi else _I64_MAX)
            bl = ext(ls, slots, num_segments=capacity)
            return {"vlo": _lo_unsortable(bl), "vhi": bh, "count": c}
        if fn == "max" or fn == "arbitrary":
            sentinel = _min_sentinel(data.dtype)
            v = segred.segment_max(jnp.where(w, data, sentinel), slots,
                                    num_segments=capacity)
        else:
            sentinel = _max_sentinel(data.dtype)
            v = segred.segment_min(jnp.where(w, data, sentinel), slots,
                                    num_segments=capacity)
        return {"val": v, "count": c}
    if fn == "count_if":
        return {"count": segred.segment_sum(
            (w & data.astype(bool)).astype(jnp.int64), slots,
            num_segments=capacity)}
    if fn in BOOL_FNS:
        b = data.astype(jnp.int32)
        c = segred.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        if fn == "bool_or":
            v = segred.segment_max(jnp.where(w, b, 0), slots,
                                    num_segments=capacity)
        else:
            v = segred.segment_min(jnp.where(w, b, 1), slots,
                                    num_segments=capacity)
        return {"val": v, "count": c}
    if fn in VAR_FNS:
        # data pre-converted to float64 by prepare_arg. Two-pass M2
        # (centered second moment) per slot — the sumsq - mean^2 form
        # cancels catastrophically for mean >> spread; the reference's
        # accumulators carry M2 for the same reason (Welford merging)
        z = jnp.zeros((), jnp.float64)
        c = segred.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        s = segred.segment_sum(jnp.where(w, data, z), slots,
                                num_segments=capacity)
        mean = s / jnp.maximum(c, 1).astype(jnp.float64)
        d = data - mean[slots]
        m2 = segred.segment_sum(jnp.where(w, d * d, z), slots,
                                 num_segments=capacity)
        return {"count": c, "sum": s, "m2": m2}
    if fn in MOMENT_FNS:
        # exact two-pass central moments about the group mean
        z = jnp.zeros((), jnp.float64)
        c = segred.segment_sum(w.astype(jnp.int64), slots,
                                num_segments=capacity)
        s = segred.segment_sum(jnp.where(w, data, z), slots,
                                num_segments=capacity)
        mean = s / jnp.maximum(c, 1).astype(jnp.float64)
        d = data - mean[slots]
        seg = lambda v: segred.segment_sum(  # noqa: E731
            jnp.where(w, v, z), slots, num_segments=capacity)
        return {"count": c, "sum": s, "m2": seg(d * d),
                "m3": seg(d * d * d), "m4": seg(d * d * d * d)}
    if fn == "geometric_mean":
        z = jnp.zeros((), jnp.float64)
        return {
            "count": segred.segment_sum(w.astype(jnp.int64), slots,
                                         num_segments=capacity),
            "sumlog": segred.segment_sum(jnp.where(w, data, z), slots,
                                          num_segments=capacity),
        }
    raise NotImplementedError(fn)


# aggregates foldable by segmented scans over hash-sorted rows (all but
# the 2D-register sketches, which keep the segment-op path)
SCAN_FNS = (frozenset({"count", "count_star", "count_if", "sum", "avg",
                       "min", "max", "arbitrary", "geometric_mean",
                       "checksum"})
            | VAR_FNS | MOMENT_FNS | BOOL_FNS | COVAR_FNS | BY_FNS)


def scan_fold(fn: str, data, weight, sg, *, data2=None, data_valid=None,
              param=None):
    """Sorted-order fold: like ``fold`` but inputs are in hash-sorted
    row order (``sg`` = ops.hash.SortedGroups) and the returned state
    arrays are per-row running values, meaningful at each run's last
    row. No scatters — see ops/segscan.py."""
    from presto_tpu.ops import segscan as S
    w = weight
    z64 = jnp.zeros((), jnp.float64)
    if fn in ("count", "count_star"):
        return {"count": S.seg_sum(w.astype(jnp.int64), sg)}
    if fn == "count_if":
        return {"count": S.seg_sum(
            (w & data.astype(bool)).astype(jnp.int64), sg)}
    if fn in ("sum", "avg"):
        c = S.seg_sum(w.astype(jnp.int64), sg)
        if data2 is not None:
            # LONG decimal limbs (see fold)
            z = jnp.zeros((), jnp.int64)
            a, b = _limb32(jnp.where(w, data, z))
            return {"a": S.seg_sum(a, sg), "b": S.seg_sum(b, sg),
                    "hi": S.seg_sum(jnp.where(w, data2, z), sg),
                    "count": c}
        if jnp.issubdtype(data.dtype, jnp.integer):
            data = data.astype(jnp.int64)
        s = S.seg_sum(jnp.where(w, data, jnp.zeros((), data.dtype)), sg)
        return {"sum": s, "count": c}
    if fn in ("min", "max", "arbitrary"):
        c = S.seg_sum(w.astype(jnp.int64), sg)
        if data2 is not None:
            maxi = fn != "min"
            ext = S.seg_max if maxi else S.seg_min
            hs = jnp.where(w, data2, _I64_MIN if maxi else _I64_MAX)
            bh = ext(hs, sg)
            tot_bh = S.broadcast_last(bh, sg)
            winner = w & (data2 == tot_bh)
            ls = jnp.where(winner, _lo_sortable(data),
                           _I64_MIN if maxi else _I64_MAX)
            bl = ext(ls, sg)
            return {"vlo": _lo_unsortable(bl), "vhi": bh, "count": c}
        if fn == "min":
            v = S.seg_min(jnp.where(w, data, _max_sentinel(data.dtype)),
                          sg)
        else:
            v = S.seg_max(jnp.where(w, data, _min_sentinel(data.dtype)),
                          sg)
        return {"val": v, "count": c}
    if fn in BOOL_FNS:
        b = data.astype(jnp.int32)
        c = S.seg_sum(w.astype(jnp.int64), sg)
        if fn == "bool_or":
            v = S.seg_max(jnp.where(w, b, 0), sg)
        else:
            v = S.seg_min(jnp.where(w, b, 1), sg)
        return {"val": v, "count": c}
    if fn in VAR_FNS:
        c = S.seg_sum(w.astype(jnp.int64), sg)
        s = S.seg_sum(jnp.where(w, data, z64), sg)
        tot_c = S.broadcast_last(c, sg)
        tot_s = S.broadcast_last(s, sg)
        mean = tot_s / jnp.maximum(tot_c, 1).astype(jnp.float64)
        d = data - mean
        m2 = S.seg_sum(jnp.where(w, d * d, z64), sg)
        return {"count": c, "sum": s, "m2": m2}
    if fn in MOMENT_FNS:
        c = S.seg_sum(w.astype(jnp.int64), sg)
        s = S.seg_sum(jnp.where(w, data, z64), sg)
        tot_c = S.broadcast_last(c, sg)
        tot_s = S.broadcast_last(s, sg)
        mean = tot_s / jnp.maximum(tot_c, 1).astype(jnp.float64)
        d = data - mean
        return {"count": c, "sum": s,
                "m2": S.seg_sum(jnp.where(w, d * d, z64), sg),
                "m3": S.seg_sum(jnp.where(w, d * d * d, z64), sg),
                "m4": S.seg_sum(jnp.where(w, d * d * d * d, z64), sg)}
    if fn == "geometric_mean":
        return {"count": S.seg_sum(w.astype(jnp.int64), sg),
                "sumlog": S.seg_sum(jnp.where(w, data, z64), sg)}
    if fn == "checksum":
        return {"sum": S.seg_sum(jnp.where(w, data, jnp.uint64(0)), sg)}
    if fn in COVAR_FNS:
        c = S.seg_sum(w.astype(jnp.int64), sg)
        sy = S.seg_sum(jnp.where(w, data, z64), sg)
        sx = S.seg_sum(jnp.where(w, data2, z64), sg)
        cf = jnp.maximum(S.broadcast_last(c, sg), 1).astype(jnp.float64)
        dy = data - S.broadcast_last(sy, sg) / cf
        dx = data2 - S.broadcast_last(sx, sg) / cf
        return {"count": c, "sumx": sx, "sumy": sy,
                "cxy": S.seg_sum(jnp.where(w, dx * dy, z64), sg),
                "m2x": S.seg_sum(jnp.where(w, dx * dx, z64), sg),
                "m2y": S.seg_sum(jnp.where(w, dy * dy, z64), sg)}
    if fn in BY_FNS:
        maximize = fn == "max_by"
        sentinel = (_min_sentinel(data2.dtype) if maximize
                    else _max_sentinel(data2.dtype))
        y = jnp.where(w, data2, sentinel)
        xok = (data_valid if data_valid is not None
               else jnp.ones(w.shape, bool)) & w
        best, (xval, xok) = S.seg_argbest(y, (data, xok), sg, maximize)
        return {"val": best, "xval": xval, "xok": xok,
                "count": S.seg_sum(w.astype(jnp.int64), sg)}
    raise NotImplementedError(fn)


def scan_merge(fn: str, states: dict, live, sg):
    """Sorted-order merge of partial states (states already gathered to
    sorted order); per-row running values, meaningful at run-last rows."""
    from presto_tpu.ops import segscan as S
    w = live
    z64 = jnp.zeros((), jnp.float64)
    if fn in ("count", "count_star", "count_if"):
        return {"count": S.seg_sum(jnp.where(w, states["count"], 0), sg)}
    if fn in ("sum", "avg"):
        if "a" in states:  # LONG decimal limb states
            states = _normalize_limbs(states)
            return {f: S.seg_sum(jnp.where(w, states[f], 0), sg)
                    for f in ("a", "b", "hi", "count")}
        zero = jnp.zeros((), states["sum"].dtype)
        return {"sum": S.seg_sum(jnp.where(w, states["sum"], zero), sg),
                "count": S.seg_sum(jnp.where(w, states["count"], 0), sg)}
    if fn in ("min", "max", "arbitrary") and "vlo" in states:
        from presto_tpu.ops import segscan as SS
        maxi = fn in ("max", "arbitrary")
        ext = SS.seg_max if maxi else SS.seg_min
        present = w & (states["count"] > 0)
        hs = jnp.where(present, states["vhi"],
                       _I64_MIN if maxi else _I64_MAX)
        bh = ext(hs, sg)
        winner = present & (states["vhi"] == SS.broadcast_last(bh, sg))
        ls = jnp.where(winner, _lo_sortable(states["vlo"]),
                       _I64_MIN if maxi else _I64_MAX)
        bl = ext(ls, sg)
        return {"vlo": _lo_unsortable(bl), "vhi": bh,
                "count": SS.seg_sum(jnp.where(w, states["count"], 0),
                                    sg)}
    if fn in ("min", "max", "arbitrary") or fn in BOOL_FNS:
        val = states["val"]
        if fn in ("max", "arbitrary", "bool_or"):
            v = S.seg_max(jnp.where(w, val, _min_sentinel(val.dtype)), sg)
        else:
            v = S.seg_min(jnp.where(w, val, _max_sentinel(val.dtype)), sg)
        return {"val": v, "count": S.seg_sum(
            jnp.where(w, states["count"], 0), sg)}
    if fn == "checksum":
        return {"sum": S.seg_sum(
            jnp.where(w, states["sum"], jnp.uint64(0)), sg)}
    if fn in VAR_FNS:
        n_i = jnp.where(w, states["count"], 0)
        s_i = jnp.where(w, states["sum"], z64)
        n = S.seg_sum(n_i, sg)
        s = S.seg_sum(s_i, sg)
        mean_tot = (S.broadcast_last(s, sg)
                    / jnp.maximum(S.broadcast_last(n, sg), 1
                                  ).astype(jnp.float64))
        mean_i = s_i / jnp.maximum(n_i, 1).astype(jnp.float64)
        dev = mean_i - mean_tot
        m2 = S.seg_sum(jnp.where(w, states["m2"]
                                 + n_i.astype(jnp.float64) * dev * dev,
                                 z64), sg)
        return {"count": n, "sum": s, "m2": m2}
    if fn in MOMENT_FNS:
        # shifted-moment identities (binomial expansion about the total
        # mean; the odd terms vanish because sum(x - mean_i) = 0):
        #   M3 += 3*d*M2_i + n_i*d^3;  M4 += 4*d*M3_i + 6*d^2*M2_i
        #   + n_i*d^4 — the k-way generalization of the reference's
        #   pairwise mergeCentralMomentsState
        n_i = jnp.where(w, states["count"], 0)
        s_i = jnp.where(w, states["sum"], z64)
        n = S.seg_sum(n_i, sg)
        s = S.seg_sum(s_i, sg)
        mean_tot = (S.broadcast_last(s, sg)
                    / jnp.maximum(S.broadcast_last(n, sg), 1
                                  ).astype(jnp.float64))
        mean_i = s_i / jnp.maximum(n_i, 1).astype(jnp.float64)
        d = mean_i - mean_tot
        nf = n_i.astype(jnp.float64)
        m2_i = jnp.where(w, states["m2"], z64)
        m3_i = jnp.where(w, states["m3"], z64)
        m4_i = jnp.where(w, states["m4"], z64)
        return {"count": n, "sum": s,
                "m2": S.seg_sum(m2_i + nf * d * d, sg),
                "m3": S.seg_sum(m3_i + 3 * d * m2_i + nf * d**3, sg),
                "m4": S.seg_sum(m4_i + 4 * d * m3_i + 6 * d * d * m2_i
                                + nf * d**4, sg)}
    if fn == "geometric_mean":
        return {"count": S.seg_sum(jnp.where(w, states["count"], 0), sg),
                "sumlog": S.seg_sum(
                    jnp.where(w, states["sumlog"], z64), sg)}
    if fn in COVAR_FNS:
        n_i = jnp.where(w, states["count"], 0)
        sx_i = jnp.where(w, states["sumx"], z64)
        sy_i = jnp.where(w, states["sumy"], z64)
        n = S.seg_sum(n_i, sg)
        sx = S.seg_sum(sx_i, sg)
        sy = S.seg_sum(sy_i, sg)
        nf = jnp.maximum(S.broadcast_last(n, sg), 1).astype(jnp.float64)
        nf_i = jnp.maximum(n_i, 1).astype(jnp.float64)
        dx = sx_i / nf_i - S.broadcast_last(sx, sg) / nf
        dy = sy_i / nf_i - S.broadcast_last(sy, sg) / nf
        nw = n_i.astype(jnp.float64)
        return {"count": n, "sumx": sx, "sumy": sy,
                "cxy": S.seg_sum(
                    jnp.where(w, states["cxy"] + nw * dx * dy, z64), sg),
                "m2x": S.seg_sum(
                    jnp.where(w, states["m2x"] + nw * dx * dx, z64), sg),
                "m2y": S.seg_sum(
                    jnp.where(w, states["m2y"] + nw * dy * dy, z64), sg)}
    if fn in BY_FNS:
        maximize = fn == "max_by"
        val = states["val"]
        present = w & (states["count"] > 0)
        sentinel = (_min_sentinel(val.dtype) if maximize
                    else _max_sentinel(val.dtype))
        y = jnp.where(present, val, sentinel)
        best, (xval, xok) = S.seg_argbest(
            y, (states["xval"], states["xok"] & present), sg, maximize)
        return {"val": best, "xval": xval, "xok": xok,
                "count": S.seg_sum(jnp.where(w, states["count"], 0), sg)}
    raise NotImplementedError(fn)


def merge(fn: str, states: dict, slots, capacity: int, live):
    """Merge partial states (rows of state columns) into a final state
    table — used on the final side of an exchange."""
    w = live
    if fn == "approx_distinct":
        # register-wise max across partials: segment_max broadcasts over
        # the trailing register axis
        regs = states["regs"]
        return {"regs": segred.segment_max(
            jnp.where(w[:, None], regs, jnp.uint8(0)), slots,
            num_segments=capacity)}
    if fn == "checksum":
        return {"sum": segred.segment_sum(
            jnp.where(w, states["sum"], jnp.uint64(0)), slots,
            num_segments=capacity)}
    if fn in COVAR_FNS:
        # bivariate Chan et al. combination: co-moments shift by the
        # product of the per-partial mean deviations
        z = jnp.zeros((), jnp.float64)
        n_i = jnp.where(w, states["count"], 0)
        sx_i = jnp.where(w, states["sumx"], z)
        sy_i = jnp.where(w, states["sumy"], z)
        n = segred.segment_sum(n_i, slots, num_segments=capacity)
        sx = segred.segment_sum(sx_i, slots, num_segments=capacity)
        sy = segred.segment_sum(sy_i, slots, num_segments=capacity)
        nf_i = jnp.maximum(n_i, 1).astype(jnp.float64)
        nf = jnp.maximum(n, 1).astype(jnp.float64)
        dx = sx_i / nf_i - (sx / nf)[slots]
        dy = sy_i / nf_i - (sy / nf)[slots]
        nw = n_i.astype(jnp.float64)
        seg = lambda v: segred.segment_sum(  # noqa: E731
            jnp.where(w, v, z), slots, num_segments=capacity)
        return {"count": n, "sumx": sx, "sumy": sy,
                "cxy": seg(states["cxy"] + nw * dx * dy),
                "m2x": seg(states["m2x"] + nw * dx * dx),
                "m2y": seg(states["m2y"] + nw * dy * dy)}
    if fn in BY_FNS:
        present = w & (states["count"] > 0)
        if fn == "max_by":
            sentinel = _min_sentinel(states["val"].dtype)
            best = segred.segment_max(
                jnp.where(present, states["val"], sentinel), slots,
                num_segments=capacity)
        else:
            sentinel = _max_sentinel(states["val"].dtype)
            best = segred.segment_min(
                jnp.where(present, states["val"], sentinel), slots,
                num_segments=capacity)
        winner = present & (states["val"] == best[slots])
        xval, xok = _winner_scatter(states["xval"], states["xok"],
                                    winner, slots, capacity)
        c = segred.segment_sum(jnp.where(w, states["count"], 0), slots,
                                num_segments=capacity)
        return {"val": best, "xval": xval, "xok": xok, "count": c}
    if fn == "approx_percentile":
        # same min-hash winner rule, per (slot, cell), across partials
        rhash, rval = states["rhash"], states["rval"]
        n, k = rhash.shape
        seg2 = (slots.astype(jnp.int64)[:, None] * k
                + jnp.arange(k, dtype=jnp.int64)[None, :])
        flat_seg = seg2.reshape(-1)
        minh = segred.segment_min(
            jnp.where(w[:, None], rhash, _U64_MAX).reshape(-1),
            flat_seg, num_segments=capacity * k)
        winner = w[:, None] & (rhash == minh[seg2])
        dest = jnp.where(winner, seg2, capacity * k).reshape(-1)
        out_val = jnp.zeros((capacity * k,), jnp.float64)
        out_val = out_val.at[dest].set(rval.reshape(-1), mode="drop")
        return {"rhash": minh.reshape(capacity, k),
                "rval": out_val.reshape(capacity, k)}
    if fn in ("count", "count_star"):
        return {"count": segred.segment_sum(
            jnp.where(w, states["count"], 0), slots, num_segments=capacity)}
    if fn in ("sum", "avg"):
        if "a" in states:  # LONG decimal limb states
            states = _normalize_limbs(states)
            return {f: segred.segment_sum(
                jnp.where(w, states[f], 0), slots,
                num_segments=capacity)
                for f in ("a", "b", "hi", "count")}
        zero = jnp.zeros((), dtype=states["sum"].dtype)
        return {
            "sum": segred.segment_sum(
                jnp.where(w, states["sum"], zero), slots,
                num_segments=capacity),
            "count": segred.segment_sum(
                jnp.where(w, states["count"], 0), slots,
                num_segments=capacity),
        }
    if fn in ("min", "max", "arbitrary") and "vlo" in states:
        maxi = fn in ("max", "arbitrary")
        ext = segred.segment_max if maxi else segred.segment_min
        present = w & (states["count"] > 0)
        hs = jnp.where(present, states["vhi"],
                       _I64_MIN if maxi else _I64_MAX)
        bh = ext(hs, slots, num_segments=capacity)
        winner = present & (states["vhi"] == bh[slots])
        ls = jnp.where(winner, _lo_sortable(states["vlo"]),
                       _I64_MIN if maxi else _I64_MAX)
        bl = ext(ls, slots, num_segments=capacity)
        return {"vlo": _lo_unsortable(bl), "vhi": bh,
                "count": segred.segment_sum(
                    jnp.where(w, states["count"], 0), slots,
                    num_segments=capacity)}
    if fn in ("min", "max", "arbitrary") or fn in BOOL_FNS:
        seg_max = fn in ("max", "arbitrary", "bool_or")
        if seg_max:
            sentinel = _min_sentinel(states["val"].dtype)
            v = segred.segment_max(
                jnp.where(w, states["val"], sentinel), slots,
                num_segments=capacity)
        else:
            sentinel = _max_sentinel(states["val"].dtype)
            v = segred.segment_min(
                jnp.where(w, states["val"], sentinel), slots,
                num_segments=capacity)
        return {"val": v, "count": segred.segment_sum(
            jnp.where(w, states["count"], 0), slots, num_segments=capacity)}
    if fn == "count_if":
        return {"count": segred.segment_sum(
            jnp.where(w, states["count"], 0), slots, num_segments=capacity)}
    if fn in VAR_FNS:
        # parallel M2 combination (Chan et al.): M2_tot = sum(M2_i) +
        # sum(n_i * (mean_i - mean_tot)^2), all segment reductions
        z = jnp.zeros((), jnp.float64)
        n_i = jnp.where(w, states["count"], 0)
        s_i = jnp.where(w, states["sum"], z)
        n = segred.segment_sum(n_i, slots, num_segments=capacity)
        s = segred.segment_sum(s_i, slots, num_segments=capacity)
        mean_tot = s / jnp.maximum(n, 1).astype(jnp.float64)
        mean_i = s_i / jnp.maximum(n_i, 1).astype(jnp.float64)
        dev = mean_i - mean_tot[slots]
        m2 = segred.segment_sum(
            jnp.where(w, states["m2"], z)
            + n_i.astype(jnp.float64) * dev * dev,
            slots, num_segments=capacity)
        return {"count": n, "sum": s, "m2": m2}
    if fn in MOMENT_FNS:
        z = jnp.zeros((), jnp.float64)
        n_i = jnp.where(w, states["count"], 0)
        s_i = jnp.where(w, states["sum"], z)
        n = segred.segment_sum(n_i, slots, num_segments=capacity)
        s = segred.segment_sum(s_i, slots, num_segments=capacity)
        mean_tot = s / jnp.maximum(n, 1).astype(jnp.float64)
        mean_i = s_i / jnp.maximum(n_i, 1).astype(jnp.float64)
        d = mean_i - mean_tot[slots]
        nf = n_i.astype(jnp.float64)
        m2_i = jnp.where(w, states["m2"], z)
        m3_i = jnp.where(w, states["m3"], z)
        m4_i = jnp.where(w, states["m4"], z)
        seg = lambda v: segred.segment_sum(  # noqa: E731
            v, slots, num_segments=capacity)
        return {"count": n, "sum": s,
                "m2": seg(m2_i + nf * d * d),
                "m3": seg(m3_i + 3 * d * m2_i + nf * d**3),
                "m4": seg(m4_i + 4 * d * m3_i + 6 * d * d * m2_i
                          + nf * d**4)}
    if fn == "geometric_mean":
        z = jnp.zeros((), jnp.float64)
        return {
            "count": segred.segment_sum(
                jnp.where(w, states["count"], 0), slots,
                num_segments=capacity),
            "sumlog": segred.segment_sum(
                jnp.where(w, states["sumlog"], z), slots,
                num_segments=capacity),
        }
    raise NotImplementedError(fn)


def finalize(fn: str, states: dict, out_type: T.DataType,
             arg_type: T.DataType | None, param: float | None = None):
    """States -> (data, valid) final columns."""
    if fn in ("count", "count_star"):
        return states["count"], None
    if fn == "approx_distinct":
        # standard HyperLogLog estimator with the linear-counting
        # small-range correction (Flajolet et al.; reference
        # ApproximateCountDistinctAggregation via airlift HLL)
        regs = states["regs"].astype(jnp.float64)
        m = float(HLL_M)
        z = jnp.sum(jnp.exp2(-regs), axis=1)
        v = jnp.sum(states["regs"] == 0, axis=1).astype(jnp.float64)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        e = alpha * m * m / z
        lin = m * jnp.log(m / jnp.maximum(v, 1.0))
        e = jnp.where((e <= 2.5 * m) & (v > 0), lin, e)
        return jnp.round(e).astype(jnp.int64), None
    if fn == "checksum":
        # u64 -> two's-complement int64 via 32-bit halves (wrapping
        # multiply-add; no 64-bit bitcast on this toolchain)
        s = states["sum"]
        lo = (s & jnp.uint64(0xFFFFFFFF)).astype(jnp.int64)
        hi = (s >> jnp.uint64(32)).astype(jnp.int64)
        return hi * jnp.int64(1 << 32) + lo, None
    if fn in COVAR_FNS:
        c = states["count"]
        cf = jnp.maximum(c, 1).astype(jnp.float64)
        cxy, m2x, m2y = states["cxy"], states["m2x"], states["m2y"]
        if fn == "covar_pop":
            return cxy / cf, c > 0
        if fn == "covar_samp":
            return cxy / jnp.maximum(cf - 1.0, 1.0), c > 1
        if fn == "corr":
            denom = jnp.sqrt(m2x * m2y)
            ok = (c > 1) & (m2x > 0) & (m2y > 0)
            return cxy / jnp.where(ok, denom, 1.0), ok
        slope = cxy / jnp.where(m2x > 0, m2x, 1.0)
        ok = (c > 0) & (m2x > 0)
        if fn == "regr_slope":
            return slope, ok
        meany = states["sumy"] / cf
        meanx = states["sumx"] / cf
        return meany - slope * meanx, ok  # regr_intercept
    if fn in BY_FNS:
        return states["xval"], (states["count"] > 0) & states["xok"]
    if fn == "approx_percentile":
        rhash, rval = states["rhash"], states["rval"]
        occupied = rhash != _U64_MAX
        cnt = jnp.sum(occupied, axis=1)
        vals = jnp.where(occupied, rval, jnp.inf)
        svals = jnp.sort(vals, axis=1)
        p = 0.5 if param is None else float(param)
        idx = jnp.clip(jnp.round(p * (cnt - 1)).astype(jnp.int32), 0,
                       rhash.shape[1] - 1)
        out = jnp.take_along_axis(svals, idx[:, None], axis=1)[:, 0]
        out = jnp.where(cnt > 0, out, 0.0)
        if isinstance(out_type, (T.DecimalType, T.BigintType,
                                 T.IntegerType, T.DateType)):
            out = jnp.round(out).astype(jnp.int64)
        return out, cnt > 0
    if fn in ("min", "max", "arbitrary") and "vlo" in states:
        from presto_tpu.ops import int128 as I
        return (I.pack(states["vlo"], states["vhi"]),
                states["count"] > 0)
    if fn == "sum" and "a" in states:
        return (_recombine128(states["a"], states["b"], states["hi"]),
                states["count"] > 0)
    if fn == "avg" and "a" in states:
        from presto_tpu.ops import int128 as I
        total = _recombine128(states["a"], states["b"], states["hi"])
        c = states["count"]
        q = I.div_round_half_up(total,
                                I.from_i64(jnp.maximum(c, 1)))
        return q, c > 0
    if fn == "sum":
        return states["sum"], states["count"] > 0
    if fn == "avg":
        s, c = states["sum"], states["count"]
        safe = jnp.maximum(c, 1)
        if isinstance(out_type, T.DecimalType):
            # HALF_UP integer division in the scaled domain:
            # sign(s) * ((2|s| + c) // 2c)
            q = jnp.sign(s) * ((2 * jnp.abs(s) + safe) // (2 * safe))
            return q, c > 0
        sf = s.astype(jnp.float64)
        if isinstance(arg_type, T.DecimalType):
            # decimal arg with a declared DOUBLE output (hand-built
            # plans; output_type-planned calls take the branch above)
            sf = sf / arg_type.unscale_factor
        return sf / safe.astype(jnp.float64), c > 0
    if fn in ("min", "max", "arbitrary"):
        return states["val"], states["count"] > 0
    if fn == "count_if":
        return states["count"], None
    if fn in BOOL_FNS:
        return states["val"] > 0, states["count"] > 0
    if fn in VAR_FNS:
        c = states["count"]
        safe = jnp.maximum(c, 1).astype(jnp.float64)
        m2 = states["m2"]
        if fn.endswith("_pop"):
            var = m2 / safe
            ok = c > 0
        else:
            # sample variance: M2/(n-1), undefined for n < 2
            var = m2 / jnp.maximum(safe - 1.0, 1.0)
            ok = c > 1
        if fn.startswith("stddev"):
            return jnp.sqrt(var), ok
        return var, ok
    if fn in MOMENT_FNS:
        # reference CentralMomentsAggregation.java:55-87 exactly
        c = states["count"]
        nf = c.astype(jnp.float64)
        m2 = states["m2"]
        if fn == "skewness":
            denom = jnp.maximum(m2, 1e-300) ** 1.5
            return jnp.sqrt(nf) * states["m3"] / denom, c > 2
        m4 = states["m4"]
        d23 = jnp.maximum((nf - 2) * (nf - 3), 1.0)
        val = ((nf - 1) * nf * (nf + 1)) / d23 * m4 \
            / jnp.maximum(m2 * m2, 1e-300) \
            - 3 * ((nf - 1) * (nf - 1)) / d23
        return val, c > 3
    if fn == "geometric_mean":
        c = states["count"]
        safe = jnp.maximum(c, 1).astype(jnp.float64)
        return jnp.exp(states["sumlog"] / safe), c > 0
    raise NotImplementedError(fn)


def _min_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)


def _max_sentinel(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)
