"""Expression IR -> JAX lowering.

Runs at jit-trace time: the compiler walks the IR and emits jnp ops over
the input columns, so the whole operator pipeline fuses into one XLA
computation. Analog of sql/gen/ExpressionCompiler.java +
PageFunctionCompiler.java:101 in the reference (which emits JVM bytecode
per (expression, types) and caches it — here jax's jit cache plays that
role).

Value model (`Val`): (dtype, data, valid, dictionary)
- data: jnp array [N] or scalar; physical per types.py
- valid: bool array or None (None = all valid); Kleene 3-valued logic for
  AND/OR, null-propagation elsewhere
- dictionary: host-side sorted numpy str array, present for VARCHAR values.
  String ops are *dictionary transforms*: LIKE evaluates the pattern over
  the (small) dictionary on host and gathers a boolean LUT by code;
  substring/lower/... rewrite the dictionary and remap codes. This is the
  TPU-native generalisation of the reference's DictionaryAwarePageProjection
  (operator/project/DictionaryAwarePageProjection.java).
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.expr import ir


@dataclasses.dataclass
class Val:
    """One columnar value during trace.

    Scalar columns: data [n]. ARRAY columns are FIXED-CAPACITY padded
    2D device values — data [n, cap] element values (codes for string
    elements), ``lengths`` [n] element counts, ``elem_valid`` [n, cap]
    per-element non-NULL mask (None = no NULL elements); positions past
    the length are dead padding. MAP columns additionally carry their
    key array in ``map_keys``. The 2D layout keeps every array
    operation (constructors, subscripts, lambdas, unnest) inside the
    traced XLA program — the TPU-native answer to the reference's
    variable-width ArrayBlock (spi/block/ArrayBlock.java)."""

    dtype: T.DataType
    data: object
    valid: object | None = None
    dictionary: np.ndarray | None = None
    lengths: object | None = None
    elem_valid: object | None = None
    map_keys: "Val | None" = None

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, T.VarcharType)

    @property
    def is_array(self) -> bool:
        return isinstance(self.dtype, T.ArrayType)

    def elem_mask(self):
        """[n, cap] mask of live (present, non-NULL) elements."""
        cap = self.data.shape[1]
        m = jnp.arange(cap)[None, :] < self.lengths[:, None]
        if self.elem_valid is not None:
            m = m & self.elem_valid
        return m


def is_long_dec(t) -> bool:
    """LONG decimal (precision 19..38): int128 as [n, 2] int64 limbs
    (reference spi/type/Decimals.java:45 long decimals; limb kernels in
    ops/int128.py)."""
    return isinstance(t, T.DecimalType) and t.is_long


def _lit128_np(value: int) -> np.ndarray:
    """Python int -> [2] int64 limb constant (low word bit pattern,
    signed high word)."""
    m = value & ((1 << 128) - 1)
    lov, hiv = m & ((1 << 64) - 1), (m >> 64) & ((1 << 64) - 1)
    tos = lambda x: x - (1 << 64) if x >= (1 << 63) else x  # noqa: E731
    return np.asarray([tos(lov), tos(hiv)], np.int64)


def as128(v: Val, scale: int):
    """A decimal/integer Val's data as int128 limbs at ``scale``
    (rescaling up only — callers align to the wider scale)."""
    from presto_tpu.ops import int128 as I
    if is_long_dec(v.dtype):
        d = v.data
        ds = v.dtype.scale
    elif isinstance(v.dtype, T.DecimalType):
        d = I.from_i64(v.data.astype(jnp.int64))
        ds = v.dtype.scale
    else:
        d = I.from_i64(v.data.astype(jnp.int64))
        ds = 0
    if scale > ds:
        d = I.rescale_up(d, scale - ds)
    return d


def where_data(cond, x, y, long: bool = False):
    """jnp.where that broadcasts a scalar/[n] condition over [n, 2]
    limb data. ``long`` marks LONG-decimal branches explicitly: two
    scalar limb values are [2]-shaped, indistinguishable from a 2-row
    column by shape alone."""
    if long or max(getattr(x, "ndim", 1), getattr(y, "ndim", 1)) == 2:
        if long:
            if getattr(x, "ndim", 1) == 1:
                x = x[None, :]
            if getattr(y, "ndim", 1) == 1:
                y = y[None, :]
        cond = jnp.asarray(cond)
        if cond.ndim == 0:
            cond = cond[None, None]
        elif cond.ndim == 1:
            cond = cond[:, None]
    return jnp.where(cond, x, y)


def and_valid(*vs):
    """AND of validity masks, None = all-valid."""
    masks = [v for v in vs if v is not None]
    if not masks:
        return None
    out = masks[0]
    for m in masks[1:]:
        out = out & m
    return out


def _bool(data, valid=None) -> Val:
    return Val(T.BOOLEAN, data, valid)


# column bindings of the innermost _c_call in flight (lambda
# captures). Per-THREAD: parallel segment compilation traces
# concurrent programs on pool threads, and a process-global stack
# would interleave their push/pop and bind another trace's columns
# into a lambda body (caught by the tracekey lint: a mutable module
# global read at trace time is also a cache-key soundness hazard)
_COMPILER_TLS = threading.local()


def _compiler_columns() -> list[dict]:
    stack = getattr(_COMPILER_TLS, "stack", None)
    if stack is None:
        stack = _COMPILER_TLS.stack = []
    return stack


# --- dictionary helpers (host side, trace time) ----------------------------


def _lit_code(dictionary: np.ndarray, s: str) -> int:
    """Code of string literal in a sorted dictionary, or -1 if absent."""
    i = int(np.searchsorted(dictionary, s))
    if i < len(dictionary) and dictionary[i] == s:
        return i
    return -1


def _dict_transform(v: Val, fn: Callable[[np.ndarray], np.ndarray]) -> Val:
    """Apply a host-side string->string function over the dictionary and
    remap codes to the new sorted dictionary."""
    new_strings = fn(v.dictionary.astype("U")).astype(object)
    new_dict, inverse = np.unique(new_strings.astype("U"), return_inverse=True)
    remap = jnp.asarray(inverse.astype(np.int32))
    return Val(T.VARCHAR, remap[v.data], v.valid, new_dict.astype(object))


def _dict_predicate(v: Val, pred: Callable[[np.ndarray], np.ndarray]) -> Val:
    """Host-evaluate a string predicate over the dictionary, gather by code."""
    lut = jnp.asarray(pred(v.dictionary.astype("U")).astype(np.bool_))
    return _bool(lut[v.data], v.valid)


def _like_regex(pattern: str, escape: str | None = None) -> re.Pattern:
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("".join(out), re.DOTALL)


def _align_strings(a: Val, b: Val) -> tuple[object, object]:
    """Return comparable code arrays for two string Vals.

    - same dictionary object: codes compare directly;
    - template parameter vs column: the parameter's traced value IS a
      code in the column's dictionary (resolved at bind time against
      the dictionary recorded here; -1 = absent = matches nothing);
    - literal vs column: resolve through the column's dictionary;
    - different dictionaries: translate a's codes into b's code space via a
      host-computed mapping (-1 where a's string is absent from b's dict).
    Only valid for equality comparisons unless dictionaries are identical.
    """
    from presto_tpu.templates.runtime import ParamDictionary
    if isinstance(a.dictionary, ParamDictionary):
        a.dictionary.bind(b.dictionary)
        return a.data, b.data
    if isinstance(b.dictionary, ParamDictionary):
        b.dictionary.bind(a.dictionary)
        return a.data, b.data
    if a.dictionary is b.dictionary:
        return a.data, b.data
    # map a's dict entries into b's code space
    idx = np.searchsorted(b.dictionary, a.dictionary.astype("U"))
    idx = np.clip(idx, 0, max(len(b.dictionary) - 1, 0))
    ok = (b.dictionary.astype("U")[idx] == a.dictionary.astype("U")) if len(
        b.dictionary) else np.zeros(len(a.dictionary), bool)
    mapping = np.where(ok, idx, -1).astype(np.int32)
    return jnp.asarray(mapping)[a.data], b.data


# --- the compiler ----------------------------------------------------------


class ExprCompiler:
    """Compiles IR against a set of named input columns (Vals)."""

    def __init__(self, columns: dict[str, Val]):
        self.columns = columns

    def compile(self, expr: ir.Expr) -> Val:
        method = getattr(self, "_c_" + type(expr).__name__.lower())
        return method(expr)

    # -- leaves

    def _c_columnref(self, e: ir.ColumnRef) -> Val:
        return self.columns[e.name]

    def _c_literal(self, e: ir.Literal) -> Val:
        if e.value is None:
            zero = np.zeros((2,) if is_long_dec(e.dtype) else (),
                            dtype=e.dtype.physical_dtype)
            dictionary = (np.array([""], dtype=object)
                          if isinstance(e.dtype, T.VarcharType) else None)
            return Val(e.dtype, jnp.asarray(zero), jnp.asarray(False),
                       dictionary)
        if isinstance(e.dtype, T.VarcharType):
            return Val(e.dtype, jnp.asarray(np.int32(0)), None,
                       np.array([e.value], dtype=object))
        if is_long_dec(e.dtype):
            return Val(e.dtype, jnp.asarray(_lit128_np(int(e.value))))
        return Val(e.dtype, jnp.asarray(
            np.asarray(e.value, dtype=e.dtype.physical_dtype)))

    # -- structured forms

    def _c_cast(self, e: ir.Cast) -> Val:
        v = self.compile(e.arg)
        return cast_val(v, e.dtype)

    def _c_isnull(self, e: ir.IsNull) -> Val:
        v = self.compile(e.arg)
        isnull = jnp.asarray(False) if v.valid is None else ~v.valid
        return _bool(~isnull if e.negated else isnull)

    def _c_inlist(self, e: ir.InList) -> Val:
        v = self.compile(e.arg)
        if v.is_string:
            values = {lit.value for lit in e.values}
            return _dict_predicate(v, lambda d: np.isin(d, list(values)))
        acc = None
        for lit in e.values:
            lv = self.compile(lit)
            hit = v.data == cast_val(lv, v.dtype).data
            acc = hit if acc is None else (acc | hit)
        return _bool(acc, v.valid)

    def _c_casewhen(self, e: ir.CaseWhen) -> Val:
        default = (self.compile(e.default) if e.default is not None
                   else self.compile(ir.Literal(e.dtype, None)))
        result = cast_val(default, e.dtype)
        # evaluate WHENs in reverse so earlier conditions win
        for cond, res in list(zip(e.conditions, e.results))[::-1]:
            c = self.compile(cond)
            r = cast_val(self.compile(res), e.dtype)
            take = c.data if c.valid is None else (c.data & c.valid)
            if r.is_string or result.is_string:
                r, result = _merge_dicts(r, result)
            data = where_data(take, r.data, result.data,
                              long=is_long_dec(e.dtype))
            rv = jnp.ones_like(take) if r.valid is None else r.valid
            dv = jnp.ones_like(take) if result.valid is None else result.valid
            valid = jnp.where(take, rv, dv)
            result = Val(e.dtype, data, valid, result.dictionary)
        return result

    def _c_call(self, e: ir.Call) -> Val:
        args = [self.compile(a) for a in e.args]
        fn = SCALARS.get(e.fn)
        if fn is None:
            raise NotImplementedError(f"scalar function {e.fn}")
        # higher-order kernels re-enter compilation for lambda bodies
        # and need this call's column bindings (outer captures)
        stack = _compiler_columns()
        stack.append(self.columns)
        try:
            return fn(e, args)
        finally:
            stack.pop()

    def _c_lambda(self, e: "ir.Lambda") -> Val:
        # lambdas are not values: higher-order kernels read them from
        # e.args and bind the params themselves
        return Val(e.dtype, None)

    def _c_parameter(self, e: "ir.Parameter") -> Val:
        # hoisted literal (templates/): the value is a traced device
        # scalar from the active params context, so literal variants
        # of one plan template share a compiled program. VARCHAR
        # parameters are dictionary codes; the marker dictionary makes
        # _align_strings record which dictionary to resolve against.
        from presto_tpu.templates.runtime import (ParamDictionary,
                                                  current_params)
        tp = current_params()
        data = tp.traced(e.index)
        if isinstance(e.dtype, T.VarcharType):
            return Val(e.dtype, data, None, ParamDictionary(e.index, tp))
        return Val(e.dtype, data)


def _merge_dicts(a: Val, b: Val) -> tuple[Val, Val]:
    """Bring two string Vals onto one shared sorted dictionary."""
    if a.dictionary is b.dictionary:
        return a, b
    union = np.unique(np.concatenate(
        [a.dictionary.astype("U"), b.dictionary.astype("U")]))
    ra = jnp.asarray(np.searchsorted(union, a.dictionary.astype("U"))
                     .astype(np.int32))
    rb = jnp.asarray(np.searchsorted(union, b.dictionary.astype("U"))
                     .astype(np.int32))
    u = union.astype(object)
    return (Val(a.dtype, ra[a.data], a.valid, u),
            Val(b.dtype, rb[b.data], b.valid, u))


# --- casts -----------------------------------------------------------------


def _parse_numeric_dictionary(v: Val, to: T.DataType) -> Val:
    """varchar -> numeric cast: parse each DICTIONARY entry host-side
    into a LUT, rows gather by code; malformed strings become NULL
    (try_cast) / the row's validity carries the failure."""
    k = len(v.dictionary)
    ok = np.zeros(k, bool)
    if isinstance(to, T.DoubleType):
        vals = np.zeros(k, np.float64)
        for i, s in enumerate(v.dictionary):
            try:
                vals[i] = float(str(s).strip())
                ok[i] = True
            except ValueError:
                pass
    elif isinstance(to, T.DecimalType):
        from decimal import Decimal, InvalidOperation
        vals = np.zeros((k, 2) if to.is_long else k, np.int64)
        for i, s in enumerate(v.dictionary):
            try:
                raw = int(Decimal(str(s).strip())
                          .scaleb(to.scale).to_integral_value())
                vals[i] = _lit128_np(raw) if to.is_long else raw
                ok[i] = True
            except (InvalidOperation, ValueError, OverflowError):
                pass
    else:
        vals = np.zeros(k, to.physical_dtype)
        for i, s in enumerate(v.dictionary):
            t = str(s).strip()
            try:
                vals[i] = int(t)
                ok[i] = True
            except ValueError:
                try:  # integral-valued decimals cast too ('5.0')
                    f = float(t)
                    if f == int(f):
                        vals[i] = int(f)
                        ok[i] = True
                except (ValueError, OverflowError):
                    pass
    codes = jnp.clip(v.data, 0, max(k - 1, 0))
    data = (jnp.asarray(vals)[codes] if k
            else jnp.zeros_like(v.data, dtype=vals.dtype))
    okrow = (jnp.asarray(ok)[codes] if k
             else jnp.zeros_like(v.data, dtype=bool))
    return Val(to, data, and_valid(v.valid, okrow))


def _rescale128(d, from_scale: int, to_scale: int):
    """int128 limbs rescaled between decimal scales (HALF_UP down)."""
    from presto_tpu.ops import int128 as I
    if to_scale >= from_scale:
        return I.rescale_up(d, to_scale - from_scale)
    k = from_scale - to_scale
    f = I.from_i64(jnp.int64(10 ** min(k, 18)))
    if k > 18:
        f = I.rescale_up(f, k - 18)
    return I.div_round_half_up(d, jnp.broadcast_to(f, d.shape))


def _cast_long_decimal(v: Val, to: T.DecimalType) -> Val:
    """Casts where the source or target is a LONG decimal."""
    from presto_tpu.ops import int128 as I
    if isinstance(v.dtype, T.UnknownType):  # typed NULL
        shape = ((v.data.shape[0], 2)
                 if getattr(v.data, "ndim", 0) >= 1 else (2,))
        return Val(to, jnp.zeros(shape, jnp.int64),
                   jnp.zeros(shape[:-1], bool) if len(shape) > 1
                   else jnp.asarray(False))
    if isinstance(v.dtype, T.DecimalType):
        src_scale = v.dtype.scale
        d = v.data if is_long_dec(v.dtype) \
            else I.from_i64(v.data.astype(jnp.int64))
    elif isinstance(v.dtype, (T.BigintType, T.IntegerType)):
        src_scale = 0
        d = I.from_i64(v.data.astype(jnp.int64))
    elif isinstance(v.dtype, T.DoubleType):
        x = v.data * (10.0 ** to.scale)
        hi = jnp.floor(x / jnp.float64(2.0 ** 64))
        lo = x - hi * jnp.float64(2.0 ** 64)
        d = I.pack(lo.astype(jnp.uint64), hi.astype(jnp.int64))
        src_scale = to.scale
    else:
        raise NotImplementedError(
            f"cast {v.dtype} -> {to}")
    d = _rescale128(d, src_scale, to.scale)
    if not to.is_long:
        return Val(to, I.to_i64(d), v.valid)
    return Val(to, d, v.valid)


def cast_val(v: Val, to: T.DataType) -> Val:
    if v.dtype == to:
        return v
    if v.is_string and isinstance(
            to, (T.BigintType, T.IntegerType, T.DoubleType,
                 T.DecimalType)) and v.dictionary is not None:
        return _parse_numeric_dictionary(v, to)
    d = v.data
    if isinstance(to, T.DoubleType):
        if is_long_dec(v.dtype):
            from presto_tpu.ops import int128 as I
            return Val(to, I.to_f64(d) / v.dtype.unscale_factor,
                       v.valid)
        if isinstance(v.dtype, T.DecimalType):
            return Val(to, d.astype(jnp.float64) / v.dtype.unscale_factor,
                       v.valid)
        return Val(to, d.astype(jnp.float64), v.valid)
    if isinstance(to, T.DecimalType):
        if to.is_long or is_long_dec(v.dtype):
            return _cast_long_decimal(v, to)
        if isinstance(v.dtype, T.DecimalType):
            ds, ts = v.dtype.scale, to.scale
            if ts >= ds:
                return Val(to, d * (10 ** (ts - ds)), v.valid)
            f = 10 ** (ds - ts)
            # round half up (reference DecimalType rescale semantics)
            return Val(to, _div_round(d, f), v.valid)
        if isinstance(v.dtype, (T.BigintType, T.IntegerType)):
            return Val(to, d.astype(jnp.int64) * to.unscale_factor, v.valid)
        if isinstance(v.dtype, T.DoubleType):
            return Val(to, jnp.round(d * to.unscale_factor).astype(jnp.int64),
                       v.valid)
    if isinstance(to, T.BigintType):
        if is_long_dec(v.dtype):
            from presto_tpu.ops import int128 as I
            scaled = _rescale128(d, v.dtype.scale, 0)
            return Val(to, I.to_i64(scaled), v.valid)
        if isinstance(v.dtype, T.DecimalType):
            return Val(to, _div_round(d, v.dtype.unscale_factor), v.valid)
        return Val(to, d.astype(jnp.int64), v.valid)
    if isinstance(to, T.IntegerType):
        return Val(to, d.astype(jnp.int32), v.valid)
    if isinstance(to, T.TimestampType):
        if isinstance(v.dtype, T.DateType):
            return Val(to, v.data.astype(jnp.int64) * T.US_PER_DAY,
                       v.valid)
        if v.is_string:
            return _parse_datetime_dictionary(v, to)
    if isinstance(to, T.DateType) and isinstance(v.dtype,
                                                 T.TimestampType):
        return Val(to, jnp.floor_divide(v.data, T.US_PER_DAY)
                   .astype(jnp.int32), v.valid)
    if isinstance(to, T.DateType) and v.is_string:
        # per-dictionary-entry ISO date parse (one parse per unique
        # string, rows gather by code); malformed strings become NULL
        # (reference operator/scalar/DateTimeFunctions castToDate)
        epoch = np.datetime64("1970-01-01")
        days = np.empty(len(v.dictionary), dtype=np.int32)
        ok = np.zeros(len(v.dictionary), dtype=bool)
        for i, s in enumerate(v.dictionary):
            try:
                d64 = np.datetime64(str(s).strip()[:10])
                # '' / 'NaT' parse to NaT without raising; NaT - epoch
                # is INT64_MIN which overflows the int32 store
                if not np.isnat(d64):
                    days[i] = int((d64 - epoch).astype(int))
                    ok[i] = True
                else:
                    days[i] = 0
            except (ValueError, OverflowError):
                days[i] = 0
        data = jnp.asarray(days)[jnp.clip(d, 0, max(len(days) - 1, 0))] \
            if len(days) else jnp.zeros_like(d, dtype=jnp.int32)
        okrow = (jnp.asarray(ok)[jnp.clip(d, 0, max(len(ok) - 1, 0))]
                 if len(ok) else jnp.zeros_like(d, dtype=bool))
        return Val(to, data, and_valid(v.valid, okrow))
    if isinstance(to, T.UnknownType) or isinstance(v.dtype, T.UnknownType):
        return Val(to, jnp.zeros_like(d, dtype=to.physical_dtype), v.valid)
    raise NotImplementedError(f"cast {v.dtype} -> {to}")


def _div_round(x, f: int):
    """Integer division rounding half away from zero."""
    half = f // 2
    return jnp.where(x >= 0, (x + half) // f, -((-x + half) // f))


def _parse_datetime_dictionary(v: Val, to: T.DataType) -> Val:
    """Per-dictionary-entry timestamp parse (cast varchar -> timestamp);
    malformed strings become NULL."""
    epoch = np.datetime64("1970-01-01", "us")
    us = np.zeros(len(v.dictionary), dtype=np.int64)
    ok = np.zeros(len(v.dictionary), dtype=bool)
    for i, s in enumerate(v.dictionary):
        try:
            d64 = np.datetime64(str(s).strip().replace(" ", "T"), "us")
            if not np.isnat(d64):
                us[i] = int((d64 - epoch).astype(np.int64))
                ok[i] = True
        except (ValueError, OverflowError):
            pass
    d = v.data
    data = (jnp.asarray(us)[jnp.clip(d, 0, max(len(us) - 1, 0))]
            if len(us) else jnp.zeros_like(d, dtype=jnp.int64))
    okrow = (jnp.asarray(ok)[jnp.clip(d, 0, max(len(ok) - 1, 0))]
             if len(ok) else jnp.zeros_like(d, dtype=bool))
    return Val(to, data, and_valid(v.valid, okrow))


# --- scalar function registry ---------------------------------------------

SCALARS: dict[str, Callable] = {}


def scalar(name: str):
    def deco(fn):
        SCALARS[name] = fn
        return fn
    return deco


def _decimal_align(a: Val, b: Val) -> tuple[Val, Val, int]:
    sa = a.dtype.scale if isinstance(a.dtype, T.DecimalType) else 0
    sb = b.dtype.scale if isinstance(b.dtype, T.DecimalType) else 0
    s = max(sa, sb)
    da = a.data * (10 ** (s - sa))
    db = b.data * (10 ** (s - sb))
    return (Val(a.dtype, da, a.valid), Val(b.dtype, db, b.valid), s)


def _arith(e: ir.Call, args: list[Val], op) -> Val:
    from presto_tpu.ops import int128 as I
    a, b = args
    valid = and_valid(a.valid, b.valid)
    if isinstance(e.dtype, T.DoubleType):
        a, b = cast_val(a, T.DOUBLE), cast_val(b, T.DOUBLE)
        return Val(e.dtype, op(a.data, b.data), valid)
    if isinstance(e.dtype, T.DecimalType):
        long_any = (e.dtype.is_long or is_long_dec(a.dtype)
                    or is_long_dec(b.dtype))
        if e.fn in ("add", "subtract"):
            if long_any:
                s = e.dtype.scale
                x, y = as128(a, s), as128(b, s)
                d = I.add(x, y) if e.fn == "add" else I.sub(x, y)
                if not e.dtype.is_long:
                    d = I.to_i64(d)
                return Val(e.dtype, d, valid)
            a2, b2, _ = _decimal_align(a, b)
            return Val(e.dtype, op(a2.data, b2.data), valid)
        if e.fn == "multiply":
            if long_any:
                if not (is_long_dec(a.dtype) or is_long_dec(b.dtype)):
                    # short x short -> exact int128 product
                    d = I.mul_i64(a.data.astype(jnp.int64),
                                  b.data.astype(jnp.int64))
                else:
                    sa = (a.dtype.scale if isinstance(
                        a.dtype, T.DecimalType) else 0)
                    sb = (b.dtype.scale if isinstance(
                        b.dtype, T.DecimalType) else 0)
                    d = I.mul(as128(a, sa), as128(b, sb))
                if not e.dtype.is_long:
                    d = I.to_i64(d)
                return Val(e.dtype, d, valid)
            return Val(e.dtype, a.data * b.data, valid)
    return Val(e.dtype, op(a.data, b.data), valid)


@scalar("add")
def _add(e, args):
    if isinstance(e.dtype, T.DateType):  # date + interval(days)
        a, b = args
        return Val(e.dtype, (a.data + b.data).astype(jnp.int32),
                   and_valid(a.valid, b.valid))
    return _arith(e, args, lambda x, y: x + y)


@scalar("subtract")
def _sub(e, args):
    if isinstance(e.dtype, T.DateType):
        a, b = args
        return Val(e.dtype, (a.data - b.data).astype(jnp.int32),
                   and_valid(a.valid, b.valid))
    return _arith(e, args, lambda x, y: x - y)


@scalar("multiply")
def _mul(e, args):
    return _arith(e, args, lambda x, y: x * y)


@scalar("divide")
def _div(e, args):
    a, b = args
    valid = and_valid(a.valid, b.valid)
    if isinstance(e.dtype, T.DoubleType):
        af, bf = cast_val(a, T.DOUBLE), cast_val(b, T.DOUBLE)
        # division by zero is an error in SQL; mask it as null to keep the
        # kernel total, matching masked-row semantics
        safe = jnp.where(bf.data == 0.0, 1.0, bf.data)
        return Val(e.dtype, af.data / safe,
                   and_valid(valid, bf.data != 0.0))
    if isinstance(e.dtype, T.DecimalType):
        # decimal / decimal at result scale s: (a * 10^(s + sb - sa)) / b,
        # rounded half up (reference DecimalOperators.divideShortShortShort)
        sa = a.dtype.scale if isinstance(a.dtype, T.DecimalType) else 0
        sb = b.dtype.scale if isinstance(b.dtype, T.DecimalType) else 0
        s = e.dtype.scale
        if (e.dtype.is_long or is_long_dec(a.dtype)
                or is_long_dec(b.dtype)
                or s + sb - sa + (a.dtype.precision if isinstance(
                    a.dtype, T.DecimalType) else 19) > 18):
            from presto_tpu.ops import int128 as I
            num = I.rescale_up(as128(a, sa), s + sb - sa)
            den = as128(b, sb)
            bz = I.eq(den, jnp.zeros_like(den))
            q = I.div_round_half_up(num, den)
            if not e.dtype.is_long:
                q = I.to_i64(q)
            return Val(e.dtype, q, and_valid(valid, ~bz))
        num = a.data * (10 ** (s + sb - sa))
        den = jnp.where(b.data == 0, 1, b.data)
        q = jnp.where(
            (num >= 0) == (den >= 0),
            (jnp.abs(num) + jnp.abs(den) // 2) // jnp.abs(den),
            -((jnp.abs(num) + jnp.abs(den) // 2) // jnp.abs(den)))
        return Val(e.dtype, q, and_valid(valid, b.data != 0))
    # SQL integer division truncates toward zero (floor differs on
    # negatives)
    safe = jnp.where(b.data == 0, 1, b.data)
    q = jnp.abs(a.data) // jnp.abs(safe)
    q = jnp.where((a.data >= 0) == (safe >= 0), q, -q)
    return Val(e.dtype, q, and_valid(valid, b.data != 0))


@scalar("modulus")
def _mod(e, args):
    a, b = args
    if isinstance(e.dtype, T.DoubleType):
        a, b = cast_val(a, T.DOUBLE), cast_val(b, T.DOUBLE)
    elif (is_long_dec(a.dtype) or is_long_dec(b.dtype)
          or is_long_dec(e.dtype)):
        # LONG decimal remainder via int128 (the int64 align/fmod
        # below would broadcast over the [n,2] limb arrays and decode
        # garbage — ADVICE r5 medium). Scales align up to the result
        # scale s = max(sa, sb); the remainder of the aligned values
        # is already at scale s (= e.dtype.scale by the planner's %
        # derivation).
        from presto_tpu.ops import int128 as I
        sa = a.dtype.scale if isinstance(a.dtype, T.DecimalType) else 0
        sb = b.dtype.scale if isinstance(b.dtype, T.DecimalType) else 0
        s = max(sa, sb)
        pa = (a.dtype.precision
              if isinstance(a.dtype, T.DecimalType) else 19)
        pb = (b.dtype.precision
              if isinstance(b.dtype, T.DecimalType) else 19)
        need = max(pa + s - sa, pb + s - sb)
        if need > 38:
            # the planner rejects `%` with this shape at plan time;
            # this guards the mod() function route to the same seam —
            # aligning past 38 digits wraps int128 into a silently
            # wrong remainder
            raise NotImplementedError(
                f"decimal remainder aligning {a.dtype} and {b.dtype} "
                f"needs {need} digits, exceeding the maximum decimal "
                f"precision 38")
        x, y = as128(a, s), as128(b, s)
        bz = I.eq(y, jnp.zeros_like(y))
        r = I.rem_trunc(x, y)
        out = r if is_long_dec(e.dtype) else I.to_i64(r)
        return Val(e.dtype, out, and_valid(a.valid, b.valid, ~bz))
    elif isinstance(a.dtype, T.DecimalType) or \
            isinstance(b.dtype, T.DecimalType):
        # align scales: (a*f) mod (b*f) = f*(a mod b), so the scaled-
        # int result is already at the common scale of e.dtype
        a, b, _ = _decimal_align(a, b)
    safe = jnp.where(b.data == 0, jnp.ones_like(b.data), b.data)
    # fmod truncates toward zero (result takes the dividend's sign) —
    # SQL/reference mod semantics; % would floor-mod
    out = jnp.fmod(a.data, safe)
    nz = b.data != 0
    if getattr(nz, "ndim", 1) == 0 and getattr(out, "ndim", 0) > 0:
        nz = jnp.broadcast_to(nz, out.shape)  # literal divisor
    return Val(e.dtype, out, and_valid(a.valid, b.valid, nz))


@scalar("negate")
def _neg(e, args):
    (a,) = args
    if is_long_dec(e.dtype):
        from presto_tpu.ops import int128 as I
        return Val(e.dtype, I.neg(a.data), a.valid)
    return Val(e.dtype, -a.data, a.valid)


def _compare(e: ir.Call, args: list[Val], op, eq_only_op) -> Val:
    a, b = args
    valid = and_valid(a.valid, b.valid)
    if a.is_string or b.is_string:
        if e.fn in ("eq", "neq"):
            da, db = _align_strings(a, b)
            return _bool(eq_only_op(da, db), valid)
        # ordering: same dictionary -> codes are collation-ordered; against a
        # literal -> host-evaluate the predicate over the dictionary
        if a.dictionary is b.dictionary:
            return _bool(op(a.data, b.data), valid)
        if len(b.dictionary) == 1:
            s = str(b.dictionary[0])
            out = _dict_predicate(a, lambda d: op(d, np.asarray(s)))
            return _bool(out.data, valid)
        if len(a.dictionary) == 1:
            s = str(a.dictionary[0])
            out = _dict_predicate(b, lambda d: op(np.asarray(s), d))
            return _bool(out.data, valid)
        raise NotImplementedError(
            "ordering comparison between differently-encoded strings")
    da, db = a.data, b.data
    if isinstance(a.dtype, T.DecimalType) or isinstance(b.dtype, T.DecimalType):
        if isinstance(a.dtype, T.DoubleType) or isinstance(b.dtype, T.DoubleType):
            da = cast_val(a, T.DOUBLE).data
            db = cast_val(b, T.DOUBLE).data
        elif is_long_dec(a.dtype) or is_long_dec(b.dtype):
            from presto_tpu.ops import int128 as I
            sc = max(a.dtype.scale if isinstance(a.dtype, T.DecimalType)
                     else 0,
                     b.dtype.scale if isinstance(b.dtype, T.DecimalType)
                     else 0)
            x, y = as128(a, sc), as128(b, sc)
            res = {"eq": I.eq(x, y), "neq": ~I.eq(x, y),
                   "lt": I.lt(x, y), "lte": I.le(x, y),
                   "gt": I.lt(y, x), "gte": I.le(y, x)}[e.fn]
            return _bool(res, valid)
        else:
            a2, b2, _ = _decimal_align(a, b)
            da, db = a2.data, b2.data
    elif isinstance(a.dtype, T.DoubleType) != isinstance(b.dtype, T.DoubleType):
        da = cast_val(a, T.DOUBLE).data
        db = cast_val(b, T.DOUBLE).data
    elif {type(a.dtype), type(b.dtype)} == {T.DateType, T.TimestampType}:
        # align epoch-days against epoch-micros (DATE widens)
        da = cast_val(a, T.TIMESTAMP).data
        db = cast_val(b, T.TIMESTAMP).data
    return _bool(op(da, db), valid)


@scalar("eq")
def _eq(e, args):
    return _compare(e, args, lambda x, y: x == y, lambda x, y: x == y)


@scalar("neq")
def _neq(e, args):
    return _compare(e, args, lambda x, y: x != y, lambda x, y: x != y)


@scalar("lt")
def _lt(e, args):
    return _compare(e, args, lambda x, y: x < y, None)


@scalar("lte")
def _lte(e, args):
    return _compare(e, args, lambda x, y: x <= y, None)


@scalar("gt")
def _gt(e, args):
    return _compare(e, args, lambda x, y: x > y, None)


@scalar("gte")
def _gte(e, args):
    return _compare(e, args, lambda x, y: x >= y, None)


@scalar("and")
def _and(e, args):
    # Kleene: FALSE dominates NULL
    data, valid = None, None
    for v in args:
        d = v.data
        vl = v.valid
        if data is None:
            data, valid = d, vl
            continue
        new_data = data & d
        if valid is None and vl is None:
            new_valid = None
        else:
            av = jnp.ones_like(data) if valid is None else valid
            bv = jnp.ones_like(d) if vl is None else vl
            known_false = (av & ~data) | (bv & ~d)
            new_valid = (av & bv) | known_false
        data, valid = new_data, new_valid
    return _bool(data, valid)


@scalar("or")
def _or(e, args):
    data, valid = None, None
    for v in args:
        d = v.data
        vl = v.valid
        if data is None:
            data, valid = d, vl
            continue
        new_data = data | d
        if valid is None and vl is None:
            new_valid = None
        else:
            av = jnp.ones_like(data) if valid is None else valid
            bv = jnp.ones_like(d) if vl is None else vl
            known_true = (av & data) | (bv & d)
            new_valid = (av & bv) | known_true
        data, valid = new_data, new_valid
    return _bool(data, valid)


@scalar("not")
def _not(e, args):
    (a,) = args
    return _bool(~a.data, a.valid)


@scalar("like")
def _like(e, args):
    col, pat = args[0], args[1]
    escape = str(args[2].dictionary[0]) if len(args) > 2 else None
    pattern = str(pat.dictionary[0])
    rx = _like_regex(pattern, escape)
    return _dict_predicate(
        col, lambda d: np.array([rx.fullmatch(s) is not None for s in d]))


@scalar("regexp_like")
def _regexp_like(e, args):
    col, pat = args[0], args[1]
    if not isinstance(e.args[1], ir.Literal):
        raise NotImplementedError("regexp_like with non-literal pattern")
    rx = re.compile(str(pat.dictionary[0]))
    return _dict_predicate(
        col, lambda d: np.array([rx.search(s) is not None for s in d]))


@scalar("regexp_replace")
def _regexp_replace(e, args):
    col = args[0]
    if not all(isinstance(a, ir.Literal) for a in e.args[1:]):
        raise NotImplementedError(
            "regexp_replace with non-literal pattern")
    rx = re.compile(str(args[1].dictionary[0]))
    repl = str(args[2].dictionary[0]) if len(args) > 2 else ""
    # SQL replacement groups use $1; python re uses \1
    repl_py = re.sub(r"\$(\d+)", r"\\\1", repl)
    return _dict_transform(
        col, lambda d: np.array([rx.sub(repl_py, s) for s in d], object))


@scalar("regexp_extract")
def _regexp_extract(e, args):
    col = args[0]
    if not all(isinstance(a, ir.Literal) for a in e.args[1:]):
        raise NotImplementedError(
            "regexp_extract with non-literal pattern")
    rx = re.compile(str(args[1].dictionary[0]))
    group = int(e.args[2].value) if len(e.args) > 2 else 0

    def f(d):
        out = []
        for s in d:
            m = rx.search(s)
            out.append("" if m is None else (m.group(group) or ""))
        return np.array(out, object)

    # NULL result for non-matching rows (reference regexp_extract
    # returns NULL when the pattern does not match)
    matched = _dict_predicate(
        col, lambda d: np.array([rx.search(s) is not None for s in d]))
    v = _dict_transform(col, f)
    valid = (matched.data if v.valid is None
             else (v.valid & matched.data))
    return Val(v.dtype, v.data, valid, v.dictionary)


def _string_contains(e, args):
    col = args[0]
    if not isinstance(e.args[1], ir.Literal):
        raise NotImplementedError("contains with non-literal needle")
    needle = str(args[1].dictionary[0])
    return _dict_predicate(
        col, lambda d: np.array([needle in s for s in d]))


@scalar("lpad")
def _lpad(e, args):
    col = args[0]
    if not all(isinstance(a, ir.Literal) for a in e.args[1:]):
        raise NotImplementedError("lpad with non-literal arguments")
    n = int(e.args[1].value)
    fill = str(args[2].dictionary[0]) if len(args) > 2 else " "
    return _dict_transform(col, lambda d: np.array(
        [s.rjust(n, fill)[:n] for s in d], object))


@scalar("rpad")
def _rpad(e, args):
    col = args[0]
    if not all(isinstance(a, ir.Literal) for a in e.args[1:]):
        raise NotImplementedError("rpad with non-literal arguments")
    n = int(e.args[1].value)
    fill = str(args[2].dictionary[0]) if len(args) > 2 else " "
    return _dict_transform(col, lambda d: np.array(
        [s.ljust(n, fill)[:n] for s in d], object))


@scalar("split_part")
def _split_part(e, args):
    col = args[0]
    if not all(isinstance(a, ir.Literal) for a in e.args[1:]):
        raise NotImplementedError("split_part with non-literal arguments")
    sep = str(args[1].dictionary[0])
    idx = int(e.args[2].value)  # 1-based

    def f(d):
        out = []
        for s in d:
            parts = s.split(sep)
            out.append(parts[idx - 1] if 0 < idx <= len(parts) else "")
        return np.array(out, object)

    return _dict_transform(col, f)


@scalar("between")
def _between(e, args):
    v, lo, hi = args
    ge = _compare(ir.Call(T.BOOLEAN, "gte", ()), [v, lo],
                  lambda x, y: x >= y, None)
    le = _compare(ir.Call(T.BOOLEAN, "lte", ()), [v, hi],
                  lambda x, y: x <= y, None)
    return _and(e, [ge, le])


# -- date/time ---------------------------------------------------------------


def _civil_from_days(days):
    """Hinnant's civil_from_days, vectorised: epoch days -> (y, m, d)."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _days_of(v: Val):
    """Epoch days of a DATE or TIMESTAMP Val (floor for pre-epoch)."""
    if isinstance(v.dtype, T.TimestampType):
        return jnp.floor_divide(v.data, T.US_PER_DAY)
    return v.data


def _us_of(v: Val):
    """Epoch micros of a DATE or TIMESTAMP Val."""
    if isinstance(v.dtype, T.DateType):
        return v.data.astype(jnp.int64) * T.US_PER_DAY
    return v.data


def _tod_us(v: Val):
    """Micros since midnight of a TIME/DATE/TIMESTAMP Val."""
    if isinstance(v.dtype, T.TimeType):
        return v.data
    return _us_of(v) - _days_of(v) * T.US_PER_DAY


def _days_from_civil(y, m, d):
    """Inverse of _civil_from_days (Hinnant's days_from_civil)."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


@scalar("add_months")
def _add_months(e, args):
    """date + N months [+ D days] with day-of-month clamping (reference
    DateTimeFunctions.addFieldValueDate semantics)."""
    a, months = args[0], args[1]
    days = args[2] if len(args) > 2 else None
    y, m, d = _civil_from_days(a.data)
    total = (y * 12 + (m - 1)) + months.data
    ny = jnp.floor_divide(total, 12)
    nm = total - ny * 12 + 1
    # clamp day to target month length
    month_days = jnp.asarray(
        [31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])[nm - 1]
    leap = ((ny % 4 == 0) & (ny % 100 != 0)) | (ny % 400 == 0)
    month_days = jnp.where((nm == 2) & leap, 29, month_days)
    nd = jnp.minimum(d, month_days)
    out = _days_from_civil(ny, nm, nd)
    if days is not None:
        out = out + days.data
    return Val(e.dtype, out.astype(jnp.int32), a.valid)


@scalar("year")
def _year(e, args):
    (a,) = args
    y, _, _ = _civil_from_days(_days_of(a))
    return Val(e.dtype, y, a.valid)


@scalar("month")
def _month(e, args):
    (a,) = args
    _, m, _ = _civil_from_days(_days_of(a))
    return Val(e.dtype, m, a.valid)


@scalar("day")
def _day(e, args):
    (a,) = args
    _, _, d = _civil_from_days(_days_of(a))
    return Val(e.dtype, d, a.valid)


@scalar("hour")
def _hour(e, args):
    (a,) = args
    us = _tod_us(a)
    return Val(e.dtype, us // T.US_PER_HOUR, a.valid)


@scalar("minute")
def _minute(e, args):
    (a,) = args
    us = _tod_us(a)
    return Val(e.dtype, (us // T.US_PER_MINUTE) % 60, a.valid)


@scalar("second")
def _second(e, args):
    (a,) = args
    us = _tod_us(a)
    return Val(e.dtype, (us // T.US_PER_SECOND) % 60, a.valid)


@scalar("millisecond")
def _millisecond(e, args):
    (a,) = args
    us = _tod_us(a)
    return Val(e.dtype, (us // 1000) % 1000, a.valid)


def _trunc_days(unit: str, days):
    """Truncate epoch days to the start of a civil unit (day stays)."""
    y, m, _d = _civil_from_days(days)
    one = jnp.ones_like(y)
    if unit == "year":
        return _days_from_civil(y, one, one)
    if unit == "quarter":
        return _days_from_civil(y, ((m - 1) // 3) * 3 + 1, one)
    if unit == "month":
        return _days_from_civil(y, m, one)
    if unit == "week":  # ISO week starts Monday; epoch day 0 = Thursday
        d = days.astype(jnp.int64)
        return d - ((d + 3) % 7)
    raise NotImplementedError(f"date_trunc unit {unit}")


@scalar("date_trunc")
def _date_trunc(e, args):
    unit = str(e.args[0].value).lower()
    v = args[1]
    if isinstance(v.dtype, T.DateType):
        if unit == "day":
            return v
        out = _trunc_days(unit, v.data)
        return Val(e.dtype, out.astype(jnp.int32), v.valid)
    us_per = {"second": T.US_PER_SECOND, "minute": T.US_PER_MINUTE,
              "hour": T.US_PER_HOUR, "day": T.US_PER_DAY}.get(unit)
    if us_per is not None:
        out = jnp.floor_divide(v.data, us_per) * us_per
        return Val(e.dtype, out, v.valid)
    out = _trunc_days(unit, _days_of(v)) * T.US_PER_DAY
    return Val(e.dtype, out, v.valid)


def _add_months_days(days, months):
    """days + months with day-of-month clamping (shared by add_months,
    ts_add_months, date_add)."""
    y, m, d = _civil_from_days(days)
    total = (y * 12 + (m - 1)) + months
    ny = jnp.floor_divide(total, 12)
    nm = total - ny * 12 + 1
    month_days = jnp.asarray(
        [31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31])[nm - 1]
    leap = ((ny % 4 == 0) & (ny % 100 != 0)) | (ny % 400 == 0)
    month_days = jnp.where((nm == 2) & leap, 29, month_days)
    return _days_from_civil(ny, nm, jnp.minimum(d, month_days))


@scalar("ts_add_months")
def _ts_add_months(e, args):
    a, months = args
    days = _days_of(a)
    tod = a.data - days * T.US_PER_DAY
    out = _add_months_days(days, months.data) * T.US_PER_DAY + tod
    return Val(e.dtype, out, and_valid(a.valid, months.valid))


@scalar("date_add")
def _date_add(e, args):
    unit = str(e.args[0].value).lower()
    if unit.endswith("s"):
        unit = unit[:-1]
    n, v = args[1], args[2]
    valid = and_valid(n.valid, v.valid)
    months = {"year": 12, "quarter": 3, "month": 1}.get(unit)
    if isinstance(v.dtype, T.DateType):
        if months is not None:
            out = _add_months_days(v.data, n.data * months)
            return Val(e.dtype, out.astype(jnp.int32), valid)
        per_day = {"day": 1, "week": 7}.get(unit)
        if per_day is None:
            raise NotImplementedError(
                f"date_add({unit}) on a date value")
        return Val(e.dtype, (v.data + n.data * per_day)
                   .astype(jnp.int32), valid)
    if months is not None:
        days = _days_of(v)
        tod = v.data - days * T.US_PER_DAY
        out = _add_months_days(days, n.data * months) \
            * T.US_PER_DAY + tod
        return Val(e.dtype, out, valid)
    us_per = {"second": T.US_PER_SECOND, "minute": T.US_PER_MINUTE,
              "hour": T.US_PER_HOUR, "day": T.US_PER_DAY,
              "week": 7 * T.US_PER_DAY,
              "millisecond": 1000}.get(unit)
    if us_per is None:
        raise NotImplementedError(f"date_add unit {unit}")
    return Val(e.dtype, v.data + n.data * us_per, valid)


@scalar("date_diff")
def _date_diff(e, args):
    unit = str(e.args[0].value).lower()
    if unit.endswith("s"):
        unit = unit[:-1]
    a, b = args[1], args[2]
    valid = and_valid(a.valid, b.valid)
    if unit in ("year", "quarter", "month"):
        # calendar-component difference (reference DateTimeFunctions
        # diffDate via epoch-month arithmetic)
        ya, ma, _ = _civil_from_days(_days_of(a))
        yb, mb, _ = _civil_from_days(_days_of(b))
        months = (yb * 12 + mb) - (ya * 12 + ma)
        div = {"year": 12, "quarter": 3, "month": 1}[unit]
        return Val(e.dtype, (months // div).astype(jnp.int64), valid)
    if unit in ("day", "week") and isinstance(a.dtype, T.DateType) \
            and isinstance(b.dtype, T.DateType):
        d = (b.data - a.data).astype(jnp.int64)
        if unit == "week":  # truncate toward zero, like the us branch
            d = jnp.where(d >= 0, d // 7, -((-d) // 7))
        return Val(e.dtype, d, valid)
    us_per = {"second": T.US_PER_SECOND, "minute": T.US_PER_MINUTE,
              "hour": T.US_PER_HOUR, "day": T.US_PER_DAY,
              "week": 7 * T.US_PER_DAY,
              "millisecond": 1000}.get(unit)
    if us_per is None:
        raise NotImplementedError(f"date_diff unit {unit}")
    diff = _us_of(b) - _us_of(a)
    # truncate toward zero (reference diffTimestamp semantics)
    out = jnp.where(diff >= 0, diff // us_per, -((-diff) // us_per))
    return Val(e.dtype, out, valid)


@scalar("from_unixtime")
def _from_unixtime(e, args):
    (a,) = args
    sec = a.data.astype(jnp.float64) / (
        a.dtype.unscale_factor if isinstance(a.dtype, T.DecimalType)
        else 1)
    return Val(e.dtype, jnp.round(sec * T.US_PER_SECOND)
               .astype(jnp.int64), a.valid)


@scalar("to_unixtime")
def _to_unixtime(e, args):
    (a,) = args
    return Val(e.dtype, _us_of(a).astype(jnp.float64) / T.US_PER_SECOND,
               a.valid)


# MySQL-style date_format specifiers with day granularity (time-of-day
# specifiers need per-row strings, which have no dictionary encoding)
_MYSQL_STRFTIME = {
    "%Y": "%Y", "%y": "%y", "%m": "%m", "%c": "%-m", "%d": "%d",
    "%e": "%-d", "%j": "%j", "%M": "%B", "%b": "%b", "%W": "%A",
    "%a": "%a",
}
_DATE_FORMAT_LO = -40179  # 1860-01-01
_DATE_FORMAT_HI = 80468   # 2190-04-25
_DATE_FORMAT_CACHE: dict[str, np.ndarray] = {}


@scalar("date_format")
def _date_format(e, args):
    import datetime
    import re

    if not isinstance(e.args[1], ir.Literal):
        raise NotImplementedError("date_format with non-literal format")
    fmt = str(e.args[1].value)
    v = args[0]
    if re.search(r"%[HhiSsfprT]", fmt):
        raise NotImplementedError(
            "date_format with time-of-day specifiers")
    lut = _DATE_FORMAT_CACHE.get(fmt)
    if lut is None:
        pyfmt = re.sub(
            "%.", lambda m: _MYSQL_STRFTIME.get(m.group(0), m.group(0)),
            fmt)
        base = datetime.date(1970, 1, 1).toordinal()
        lut = np.array(
            [datetime.date.fromordinal(base + d).strftime(pyfmt)
             for d in range(_DATE_FORMAT_LO, _DATE_FORMAT_HI)], object)
        if len(_DATE_FORMAT_CACHE) > 16:
            _DATE_FORMAT_CACHE.clear()
        _DATE_FORMAT_CACHE[fmt] = lut
    days = _days_of(v)
    code = (days - _DATE_FORMAT_LO).astype(jnp.int32)
    in_range = (code >= 0) & (code < len(lut))
    return Val(T.VARCHAR, jnp.clip(code, 0, len(lut) - 1),
               and_valid(v.valid, in_range), lut)


# -- strings -----------------------------------------------------------------


@scalar("substring")
def _substring(e, args):
    col = args[0]
    # start/length must be literals: read them from the IR, not traced
    # values (string ops run host-side over the dictionary)
    if not all(isinstance(a, ir.Literal) for a in e.args[1:]):
        raise NotImplementedError("substring with non-literal start/length")
    s0 = int(e.args[1].value)  # SQL 1-based
    ln = int(e.args[2].value) if len(e.args) > 2 else None

    def f(d):
        if ln is None:
            return np.array([s[s0 - 1:] for s in d], object)
        return np.array([s[s0 - 1:s0 - 1 + ln] for s in d], object)

    return _dict_transform(col, f)


@scalar("lower")
def _lower(e, args):
    return _dict_transform(args[0], lambda d: np.char.lower(d).astype(object))


@scalar("upper")
def _upper(e, args):
    return _dict_transform(args[0], lambda d: np.char.upper(d).astype(object))


@scalar("length")
def _length(e, args):
    (col,) = args
    lut = jnp.asarray(np.char.str_len(col.dictionary.astype("U"))
                      .astype(np.int64))
    return Val(e.dtype, lut[col.data], col.valid)


_CONCAT_PRODUCT_MAX = 1 << 16


@scalar("concat")
def _concat(e, args):
    a, b = args
    if a.is_array and b.is_array:
        return _array_concat_fn(e, args)
    if len(a.dictionary) == 1:  # literal + column
        s = str(a.dictionary[0])
        return _dict_transform(b, lambda d: np.array([s + x for x in d], object))
    if len(b.dictionary) == 1:
        s = str(b.dictionary[0])
        return _dict_transform(a, lambda d: np.array([x + s for x in d], object))
    # two real columns: product dictionary, code = ca * |db| + cb. The
    # dictionary must be static (host-side), so it enumerates all pairs;
    # bounded to keep degenerate high-cardinality concats from exploding
    # (the reference's per-row VarcharConcat has no such table at all —
    # dictionary encoding is this engine's string substrate).
    na, nb = len(a.dictionary), len(b.dictionary)
    if na * nb > _CONCAT_PRODUCT_MAX:
        raise NotImplementedError(
            f"concat of string columns with {na}x{nb} dictionary product "
            f"(> {_CONCAT_PRODUCT_MAX})")
    d = np.array([str(x) + str(y)
                  for x in a.dictionary for y in b.dictionary], object)
    codes = a.data.astype(jnp.int32) * nb + b.data.astype(jnp.int32)
    return Val(e.dtype, codes, and_valid(a.valid, b.valid), d)


@scalar("trim")
def _trim(e, args):
    return _dict_transform(
        args[0], lambda d: np.array([str(s).strip() for s in d], object))


@scalar("ltrim")
def _ltrim(e, args):
    return _dict_transform(
        args[0], lambda d: np.array([str(s).lstrip() for s in d], object))


@scalar("rtrim")
def _rtrim(e, args):
    return _dict_transform(
        args[0], lambda d: np.array([str(s).rstrip() for s in d], object))


@scalar("reverse")
def _reverse(e, args):
    return _dict_transform(
        args[0], lambda d: np.array([str(s)[::-1] for s in d], object))


@scalar("replace")
def _replace(e, args):
    col = args[0]
    if not all(isinstance(a, ir.Literal) for a in e.args[1:]):
        raise NotImplementedError("replace with non-literal patterns")
    pat = str(e.args[1].value)
    rep = str(e.args[2].value) if len(e.args) > 2 else ""
    return _dict_transform(
        col, lambda d: np.array([str(s).replace(pat, rep) for s in d],
                                object))


@scalar("starts_with")
def _starts_with(e, args):
    col = args[0]
    if not isinstance(e.args[1], ir.Literal):
        raise NotImplementedError("starts_with with non-literal prefix")
    prefix = str(e.args[1].value)
    return _dict_predicate(
        col, lambda d: np.array([str(s).startswith(prefix) for s in d]))


@scalar("strpos")
def _strpos(e, args):
    col = args[0]
    if not isinstance(e.args[1], ir.Literal):
        raise NotImplementedError("strpos with non-literal needle")
    needle = str(e.args[1].value)
    lut = jnp.asarray(np.array(
        [str(s).find(needle) + 1 for s in col.dictionary], np.int64))
    return Val(e.dtype, lut[col.data], col.valid)


@scalar("coalesce")
def _coalesce(e, args):
    if not any(a.is_string for a in args) \
            and not isinstance(e.dtype, T.VarcharType):
        # physical alignment to the result type (e.g. a DATE branch
        # under a TIMESTAMP result must not merge days with micros)
        args = [cast_val(a, e.dtype) for a in args]
    out = args[-1]
    for v in args[:-1][::-1]:
        if v.valid is not None:
            take = v.valid
        elif is_long_dec(e.dtype) and getattr(v.data, "ndim", 1) == 1:
            take = jnp.asarray(True)  # scalar limb pair [2]
        else:
            take = jnp.ones(v.data.shape[:1] or (), dtype=bool)
        if v.is_string or out.is_string:
            v, out = _merge_dicts(v, out)
        data = where_data(take, v.data, out.data,
                          long=is_long_dec(e.dtype))
        ov = (jnp.ones_like(take) if out.valid is None else out.valid)
        valid = jnp.where(take, True, ov)
        out = Val(e.dtype, data, valid, out.dictionary)
    return out


@scalar("row_index")
def _row_index(e, args):
    """Synthetic per-row identifier (planner-internal; backs the
    residual-EXISTS decorrelation when the outer relation has no
    unique key). Under a mesh axis the shard index lands in the high
    bits so ids are GLOBALLY unique across shards."""
    import jax as _jax
    (a,) = args
    n = a.data.shape[0]
    idx = jnp.arange(n, dtype=jnp.int64)
    try:
        shard = _jax.lax.axis_index("d").astype(jnp.int64)
        idx = idx + (shard << jnp.int64(40))
    except NameError:
        pass
    return Val(e.dtype, idx, None)


@scalar("abs")
def _abs(e, args):
    (a,) = args
    if is_long_dec(e.dtype):
        from presto_tpu.ops import int128 as I
        return Val(e.dtype, I.abs_(a.data), a.valid)
    return Val(e.dtype, jnp.abs(a.data), a.valid)


def _as_f64(v: Val):
    return cast_val(v, T.DOUBLE).data


def _mathfn(name, op, arity=1):
    """DOUBLE-valued math function (reference MathFunctions.java)."""
    @scalar(name)
    def _f(e, args, _op=op, _n=arity):
        if _n == 1:
            (a,) = args
            return Val(e.dtype, _op(_as_f64(a)), a.valid)
        a, b = args
        return Val(e.dtype, _op(_as_f64(a), _as_f64(b)),
                   and_valid(a.valid, b.valid))
    return _f


_mathfn("sqrt", jnp.sqrt)
_mathfn("cbrt", jnp.cbrt)
_mathfn("exp", jnp.exp)
_mathfn("ln", jnp.log)
_mathfn("log10", jnp.log10)
_mathfn("log2", jnp.log2)
_mathfn("floor", jnp.floor)
_mathfn("ceiling", jnp.ceil)
_mathfn("ceil", jnp.ceil)
_mathfn("truncate", jnp.trunc)
_mathfn("power", jnp.power, arity=2)
_mathfn("pow", jnp.power, arity=2)


@scalar("sign")
def _sign(e, args):
    (a,) = args
    return Val(e.dtype, jnp.sign(a.data).astype(a.data.dtype), a.valid)


@scalar("mod")
def _mod_alias(e, args):
    return _mod(e, args)


@scalar("greatest")
@scalar("least")
def _greatest_least(e, args):
    # NULL if any argument is NULL (reference semantics)
    op = jnp.maximum if e.fn == "greatest" else jnp.minimum
    if any(a.is_string for a in args):
        # merged dictionary is sorted, so codes are collation-ordered
        out = args[0]
        valid = out.valid
        for v in args[1:]:
            v, out = _merge_dicts(v, out)
            valid = and_valid(valid, v.valid)
            out = Val(e.dtype, op(out.data, v.data), None,
                      out.dictionary)
        return Val(e.dtype, out.data, valid, out.dictionary)
    out = cast_val(args[0], e.dtype)
    valid = out.valid
    for v in args[1:]:
        v = cast_val(v, e.dtype)
        if is_long_dec(e.dtype):
            from presto_tpu.ops import int128 as I
            sel = I.lt(out.data, v.data)
            pick_v = sel if e.fn == "greatest" else ~sel
            d = where_data(pick_v, v.data, out.data, long=True)
            out = Val(e.dtype, d, None)
        else:
            out = Val(e.dtype, op(out.data, v.data), None)
        valid = and_valid(valid, v.valid)
    return Val(e.dtype, out.data, valid)


@scalar("nullif")
def _nullif(e, args):
    a, b = args
    eqv = _compare(ir.Call(T.BOOLEAN, "eq", e.args), args,
                   lambda x, y: x == y, lambda x, y: x == y)
    both = eqv.data if eqv.valid is None else (eqv.data & eqv.valid)
    valid = (jnp.ones_like(both) if a.valid is None else a.valid) & ~both
    return Val(e.dtype, a.data, valid, a.dictionary)


@scalar("quarter")
def _quarter(e, args):
    (a,) = args
    _, m, _ = _civil_from_days(_days_of(a))
    return Val(e.dtype, (m - 1) // 3 + 1, a.valid)


@scalar("day_of_week")
def _day_of_week(e, args):
    # ISO: Monday=1..Sunday=7; epoch 1970-01-01 was a Thursday
    (a,) = args
    dow = (_days_of(a).astype(jnp.int64) + 3) % 7 + 1
    return Val(e.dtype, dow, a.valid)


@scalar("day_of_year")
def _day_of_year(e, args):
    (a,) = args
    days = _days_of(a)
    y, _, _ = _civil_from_days(days)
    jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return Val(e.dtype, days.astype(jnp.int64) - jan1 + 1, a.valid)


@scalar("week")
def _week(e, args):
    # ISO week number of the year (reference week_of_year)
    (a,) = args
    d = _days_of(a).astype(jnp.int64)
    # Thursday of this row's ISO week determines the ISO year
    thursday = d - ((d + 3) % 7) + 3
    y, _, _ = _civil_from_days(thursday)
    jan1 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return Val(e.dtype, (thursday - jan1) // 7 + 1, a.valid)


@scalar("round")
def _round(e, args):
    a = args[0]
    digits = 0
    if len(e.args) > 1:
        if not isinstance(e.args[1], ir.Literal):
            raise NotImplementedError("round with non-literal digits")
        digits = int(e.args[1].value)
    if isinstance(a.dtype, T.DecimalType):
        drop = a.dtype.scale - digits
        if drop <= 0:
            return Val(e.dtype, a.data, a.valid)
        keep_scale = (isinstance(e.dtype, T.DecimalType)
                      and e.dtype.scale == a.dtype.scale)
        if is_long_dec(a.dtype) or is_long_dec(e.dtype):
            # LONG decimals are [n,2] int128 limb arrays: the int64
            # _div_round below would divide the limbs elementwise and
            # return garbage (ADVICE r5 high) — round through int128
            from presto_tpu.ops import int128 as I
            d = (a.data if is_long_dec(a.dtype)
                 else I.from_i64(a.data.astype(jnp.int64)))
            if drop > 38:
                # 10^drop exceeds int128 (wraps into a garbage
                # divisor), but |x| < 10^38 <= 0.5 * 10^drop, so every
                # value half-up rounds to exactly zero
                q = jnp.zeros_like(d)
                if is_long_dec(e.dtype):
                    return Val(e.dtype, q, a.valid)
                return Val(e.dtype, I.to_i64(q), a.valid)
            f = I.from_i64(jnp.int64(10 ** min(drop, 18)))
            if drop > 18:
                f = I.rescale_up(f, drop - 18)
            q = I.div_round_half_up(d, jnp.broadcast_to(f, d.shape))
            if keep_scale:
                q = I.rescale_up(q, drop)
            elif digits < 0:
                # result scale is 0 but the rounding unit is 10^-digits:
                # round(123.45, -1) -> 12 tens -> 120
                q = I.rescale_up(q, -digits)
            if is_long_dec(e.dtype):
                return Val(e.dtype, q, a.valid)
            return Val(e.dtype, I.to_i64(q), a.valid)
        if drop > 18:
            # SHORT decimals hold |x| < 10^18 <= 0.5 * 10^drop: zero,
            # and 10^drop would not fit the int64 divisor anyway
            return Val(e.dtype, jnp.zeros_like(a.data), a.valid)
        # negative digits round to multiples of 10^-digits at scale 0:
        # the quotient counts units of 10^-digits, scale it back up
        mult = ((10 ** drop) if keep_scale
                else (10 ** -digits) if digits < 0 else 1)
        return Val(e.dtype, _div_round(a.data, 10 ** drop) * mult,
                   a.valid)
    f = 10.0 ** digits
    return Val(e.dtype, jnp.round(a.data * f) / f, a.valid)


# --- JSON functions (dictionary transforms over host-side parsing) ---------
# The reference implements these as per-row operators over a JSON slice
# type (operator/scalar/JsonFunctions.java, JsonExtract.java); here JSON
# values are dictionary-encoded strings, so each unique document parses
# exactly ONCE on host at trace time and rows gather the result by code
# — a strictly better fit for columnar repeated-document data.


def _json_path_steps(path: str) -> list:
    """Parse a JSONPath subset: $, .key, [index] (strict or lax head)."""
    if path.startswith("lax ") or path.startswith("strict "):
        path = path.split(" ", 1)[1]
    if not path.startswith("$"):
        raise NotImplementedError(f"unsupported JSON path {path!r}")
    steps: list = []
    i = 1
    while i < len(path):
        if path[i] == ".":
            j = i + 1
            while j < len(path) and path[j] not in ".[":
                j += 1
            steps.append(path[i + 1:j])
            i = j
        elif path[i] == "[":
            j = path.index("]", i)
            body = path[i + 1:j].strip()
            if body.startswith('"') or body.startswith("'"):
                steps.append(body[1:-1])
            else:
                steps.append(int(body))
            i = j + 1
        else:
            raise NotImplementedError(f"unsupported JSON path {path!r}")
    return steps


def _json_eval(doc: str, steps: list):
    """Returns (value, found)."""
    import json
    try:
        v = json.loads(doc)
    except (ValueError, TypeError):
        return None, False
    for s in steps:
        if isinstance(s, int):
            if not isinstance(v, list) or not -len(v) <= s < len(v):
                return None, False
            v = v[s]
        else:
            if not isinstance(v, dict) or s not in v:
                return None, False
            v = v[s]
    return v, True


def _json_lut(col: Val, e, per_doc) -> Val:
    """Gather a per-dictionary-entry (value, found) transform by code;
    rows whose document yields found=False become NULL."""
    import json
    strings = []
    found = np.zeros(len(col.dictionary), dtype=bool)
    for k, doc in enumerate(col.dictionary):
        v, ok = per_doc(str(doc))
        found[k] = ok
        strings.append(v if ok else None)
    lut_valid = jnp.asarray(found)
    row_valid = and_valid(col.valid, lut_valid[col.data])
    if isinstance(e.dtype, T.VarcharType):
        uniq = sorted({s for s in strings if s is not None})
        new_dict = np.asarray(uniq, dtype=object)
        remap = np.asarray(
            [0 if s is None else int(np.searchsorted(uniq, s))
             for s in strings], dtype=np.int32)
        codes = jnp.asarray(remap)[col.data]
        return Val(T.VARCHAR, codes, row_valid, new_dict)
    vals = np.asarray([0 if s is None else s for s in strings],
                      dtype=np.int64)
    return Val(e.dtype, jnp.asarray(vals)[col.data], row_valid)


def _literal_path(e, idx: int = 1) -> list:
    if not isinstance(e.args[idx], ir.Literal):
        raise NotImplementedError("JSON path must be a literal")
    return _json_path_steps(str(e.args[idx].value))


@scalar("json_extract_scalar")
def _json_extract_scalar(e, args):
    steps = _literal_path(e)

    def per_doc(doc):
        v, ok = _json_eval(doc, steps)
        if not ok or isinstance(v, (dict, list)) or v is None:
            return None, False
        if isinstance(v, bool):
            return ("true" if v else "false"), True
        if isinstance(v, float) and v.is_integer():
            return str(int(v)), True
        return str(v), True

    return _json_lut(args[0], e, per_doc)


@scalar("json_extract")
def _json_extract(e, args):
    import json
    steps = _literal_path(e)

    def per_doc(doc):
        v, ok = _json_eval(doc, steps)
        if not ok:
            return None, False
        return json.dumps(v, separators=(",", ":"), sort_keys=True), True

    return _json_lut(args[0], e, per_doc)


@scalar("json_array_length")
def _json_array_length(e, args):
    import json

    def per_doc(doc):
        try:
            v = json.loads(doc)
        except (ValueError, TypeError):
            return None, False
        if not isinstance(v, list):
            return None, False
        return len(v), True

    return _json_lut(args[0], e, per_doc)


@scalar("json_size")
def _json_size(e, args):
    steps = _literal_path(e)

    def per_doc(doc):
        v, ok = _json_eval(doc, steps)
        if not ok:
            return None, False
        return (len(v) if isinstance(v, (dict, list)) else 0), True

    return _json_lut(args[0], e, per_doc)


@scalar("json_parse")
@scalar("json_format")
def _json_identity(e, args):
    # JSON values are dictionary-encoded strings end to end; parse and
    # format are type adapters with no physical change
    a = args[0]
    return Val(T.VARCHAR, a.data, a.valid, a.dictionary)


# --- arrays / maps (fixed-capacity 2D device layout; see Val) ---------------


def _elem_string(t: T.DataType) -> bool:
    return isinstance(t, T.VarcharType)


def _broadcast_cols_2d(columns: dict[str, Val], cap: int) -> dict:
    """Outer scalar columns as [n, 1] views so lambda bodies broadcast
    against [n, cap] element values."""
    out = {}
    for sym, v in columns.items():
        if v.is_array or getattr(v.data, "ndim", 1) != 1:
            out[sym] = v
            continue
        out[sym] = Val(v.dtype, v.data[:, None],
                       None if v.valid is None else v.valid[:, None],
                       v.dictionary)
    return out


def _bind_lambda(lam: ir.Lambda, arrays: list[Val],
                 columns: dict[str, Val] | None = None) -> Val:
    """Compile a lambda body with each param bound to its array's
    [n, cap] element values (outer columns broadcast to [n, 1]);
    returns the body's [n, cap] Val."""
    if columns is None:
        stack = _compiler_columns()
        columns = stack[-1] if stack else {}
    cap = arrays[0].data.shape[1]
    cols = _broadcast_cols_2d(columns, cap)
    for p, arr in zip(lam.params, arrays):
        ev = arr.elem_mask()
        cols[p] = Val(arr.dtype.element, arr.data,
                      ev if arr.elem_valid is not None else None,
                      arr.dictionary)
    return ExprCompiler(cols).compile(lam.body)


@scalar("array_ctor")
def _array_ctor(e, args):
    """ARRAY[e1, ..., ek]: stack k scalar columns into [n, k]."""
    if not args:
        return Val(e.dtype, jnp.zeros((1, 1), jnp.int64), None, None,
                   jnp.zeros((1,), jnp.int32), None)
    et = e.dtype.element
    if _elem_string(et):
        base = args[0]
        unified = [base]
        for v in args[1:]:
            v, base = _merge_dicts(v, base)
            unified.append(v)
        # re-unify earlier args against the final dictionary
        args = [_merge_dicts(v, base)[0] for v in unified]
        dictionary = args[0].dictionary
    else:
        dictionary = None
    n = None
    for v in args:
        if getattr(v.data, "ndim", 0) == 1:
            n = v.data.shape[0]
            break
    if n is None:
        n = 1
    datas = []
    valids = []
    for v in args:
        d = v.data
        if getattr(d, "ndim", 0) == 0:
            d = jnp.broadcast_to(d, (n,))
        datas.append(d)
        va = v.valid
        if va is None:
            va = jnp.ones((n,), bool)
        elif getattr(va, "ndim", 0) == 0:
            va = jnp.broadcast_to(va, (n,))
        valids.append(va)
    data = jnp.stack(datas, axis=1)
    elem_valid = jnp.stack(valids, axis=1)
    lengths = jnp.full((n,), len(args), jnp.int32)
    return Val(e.dtype, data, None, dictionary, lengths, elem_valid)


@scalar("element_at")
@scalar("subscript")
def _element_at(e, args):
    v, idx = args
    if isinstance(v.dtype, T.MapType):
        # map lookup: position of the matching key
        keys = v.map_keys
        if _elem_string(keys.dtype.element) and idx.is_string:
            kd, _ = _align_strings(
                Val(T.VARCHAR, keys.data, None, keys.dictionary), idx)
            want = idx.data
            hit = (kd == (want[:, None] if getattr(
                want, "ndim", 0) == 1 else want)) & keys.elem_mask()
        else:
            want = idx.data
            hit = (keys.data == (want[:, None] if getattr(
                want, "ndim", 0) == 1 else want)) & keys.elem_mask()
        pos = jnp.argmax(hit, axis=1)
        found = jnp.any(hit, axis=1)
        data = jnp.take_along_axis(v.data, pos[:, None], axis=1)[:, 0]
        ev = (jnp.take_along_axis(v.elem_valid, pos[:, None],
                                  axis=1)[:, 0]
              if v.elem_valid is not None else True)
        valid = and_valid(v.valid, found & ev)
        return Val(e.dtype, data, valid, v.dictionary)
    # SQL arrays are 1-based; out-of-range -> NULL
    cap = v.data.shape[1]
    i0 = idx.data - 1
    if getattr(i0, "ndim", 0) == 0:
        i0 = jnp.broadcast_to(i0, (v.data.shape[0],))
    in_range = (i0 >= 0) & (i0 < v.lengths.astype(i0.dtype))
    pos = jnp.clip(i0, 0, cap - 1).astype(jnp.int32)
    data = jnp.take_along_axis(v.data, pos[:, None], axis=1)[:, 0]
    ev = (jnp.take_along_axis(v.elem_valid, pos[:, None], axis=1)[:, 0]
          if v.elem_valid is not None else True)
    valid = and_valid(v.valid, and_valid(idx.valid, in_range & ev))
    return Val(e.dtype, data, valid, v.dictionary)


@scalar("cardinality")
def _cardinality(e, args):
    (v,) = args
    return Val(e.dtype, v.lengths.astype(jnp.int64), v.valid)


@scalar("contains")
def _contains_dispatch(e, args):
    v, x = args
    if not v.is_array:  # string contains (substring test) kept as-is
        return _string_contains(e, args)
    if _elem_string(v.dtype.element) and x.is_string:
        vd, _ = _align_strings(
            Val(T.VARCHAR, v.data, None, v.dictionary), x)
        want = x.data
    else:
        vd, want = v.data, x.data
    if getattr(want, "ndim", 0) <= 1:
        want = want[..., None] if getattr(want, "ndim", 0) else want
    hit = (vd == want) & v.elem_mask()
    return Val(e.dtype, jnp.any(hit, axis=1),
               and_valid(v.valid, x.valid))


@scalar("transform")
def _transform(e, args):
    v = args[0]
    lam = e.args[1]
    body = _bind_lambda(lam, [v])
    data = body.data
    if getattr(data, "ndim", 0) != 2:
        data = jnp.broadcast_to(data, v.data.shape)
    # an outer-column capture widens a literal array's single row to
    # the table's row count: companion arrays follow the body shape
    n_out = data.shape[0]
    lengths = v.lengths
    if lengths.shape[0] != n_out:
        lengths = jnp.broadcast_to(lengths, (n_out,))
    valid = v.valid
    if valid is not None and valid.shape[0] != n_out:
        valid = jnp.broadcast_to(valid, (n_out,))
    ev = body.valid
    if ev is not None and ev.shape != data.shape:
        ev = jnp.broadcast_to(ev, data.shape)
    return Val(e.dtype, data, valid, body.dictionary, lengths, ev)


@scalar("filter")
def _filter_array(e, args):
    v = args[0]
    lam = e.args[1]
    body = _bind_lambda(lam, [v])
    keep = body.data
    if body.valid is not None:
        keep = keep & body.valid
    # PRESENT positions only (a NULL element the lambda accepts stays:
    # Trino filter(array[1,null], x -> x IS NULL) keeps the NULL)
    cap = v.data.shape[1]
    present = jnp.arange(cap)[None, :] < v.lengths[:, None]
    keep = keep & present
    key = (~keep).astype(jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(cap, dtype=jnp.int32),
                           v.data.shape)
    operands = [key, pos, v.data]
    has_ev = v.elem_valid is not None
    if has_ev:
        operands.append(v.elem_valid)
    out = jax.lax.sort(tuple(operands), num_keys=2, is_stable=True,
                       dimension=1)
    data = out[2]
    elem_valid = out[3] if has_ev else None
    lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
    return Val(e.dtype, data, v.valid, v.dictionary, lengths,
               elem_valid)


@scalar("reduce")
def _reduce_array(e, args):
    v, init = args[0], args[1]
    lam = e.args[2]  # (acc, x) -> expr
    out_lam = e.args[3] if len(e.args) > 3 else None
    n, cap = v.data.shape
    acc_t = init.dtype
    acc_data = init.data
    if getattr(acc_data, "ndim", 0) == 0:
        acc_data = jnp.broadcast_to(acc_data, (n,))
    acc = Val(acc_t, acc_data, init.valid)
    mask = v.elem_mask()
    for j in range(cap):
        elem = Val(v.dtype.element, v.data[:, j], None, v.dictionary)
        stack = _compiler_columns()
        cols = dict(stack[-1]) if stack else {}
        cols[lam.params[0]] = acc
        cols[lam.params[1]] = elem
        stepped = ExprCompiler(cols).compile(lam.body)
        take = mask[:, j]
        sd = stepped.data
        if getattr(sd, "ndim", 0) == 0:
            sd = jnp.broadcast_to(sd, (n,))
        new_data = jnp.where(take, sd, acc.data)
        if acc.valid is None and stepped.valid is None:
            new_valid = None
        else:
            av = acc.valid if acc.valid is not None \
                else jnp.ones((n,), bool)
            sv = stepped.valid if stepped.valid is not None \
                else jnp.ones((n,), bool)
            new_valid = jnp.where(take, sv, av)
        acc = Val(acc_t, new_data, new_valid)
    if out_lam is not None:
        stack = _compiler_columns()
        cols = dict(stack[-1]) if stack else {}
        cols[out_lam.params[0]] = acc
        acc = ExprCompiler(cols).compile(out_lam.body)
    return Val(e.dtype, acc.data, and_valid(v.valid, acc.valid))


def _match_reduce(e, args, op):
    v = args[0]
    lam = e.args[1]
    body = _bind_lambda(lam, [v])
    hit = body.data
    if body.valid is not None:
        hit = hit & body.valid
    m = v.elem_mask()
    if op == "any":
        out = jnp.any(hit & m, axis=1)
    else:
        out = jnp.all(jnp.where(m, hit, True), axis=1)
    return Val(e.dtype, out, v.valid)


@scalar("any_match")
def _any_match(e, args):
    return _match_reduce(e, args, "any")


@scalar("all_match")
def _all_match(e, args):
    return _match_reduce(e, args, "all")


@scalar("none_match")
def _none_match(e, args):
    r = _match_reduce(e, args, "any")
    return Val(e.dtype, ~r.data, r.valid)


@scalar("array_position")
def _array_position(e, args):
    v, x = args
    if _elem_string(v.dtype.element) and x.is_string:
        vd, _ = _align_strings(
            Val(T.VARCHAR, v.data, None, v.dictionary), x)
        want = x.data
    else:
        vd, want = v.data, x.data
    if getattr(want, "ndim", 0) == 1:
        want = want[:, None]
    hit = (vd == want) & v.elem_mask()
    pos = jnp.argmax(hit, axis=1) + 1
    found = jnp.any(hit, axis=1)
    return Val(e.dtype, jnp.where(found, pos, 0).astype(jnp.int64),
               and_valid(v.valid, x.valid))


@scalar("array_max")
@scalar("array_min")
def _array_minmax(e, args):
    (v,) = args
    is_max = e.fn == "array_max"
    m = v.elem_mask()
    if jnp.issubdtype(v.data.dtype, jnp.integer):
        ident = (jnp.iinfo(v.data.dtype).min if is_max
                 else jnp.iinfo(v.data.dtype).max)
    else:
        ident = -jnp.inf if is_max else jnp.inf
    masked = jnp.where(m, v.data, ident)
    out = masked.max(axis=1) if is_max else masked.min(axis=1)
    nonempty = jnp.any(m, axis=1)
    return Val(e.dtype, out, and_valid(v.valid, nonempty),
               v.dictionary)


@scalar("array_sum")
def _array_sum(e, args):
    (v,) = args
    m = v.elem_mask()
    out = jnp.sum(jnp.where(m, v.data, 0), axis=1)
    return Val(e.dtype, out, v.valid)


@scalar("array_concat_fn")
def _array_concat_fn(e, args):
    a, b = args
    if _elem_string(e.dtype.element):
        av = Val(T.VARCHAR, a.data, None, a.dictionary)
        bv = Val(T.VARCHAR, b.data, None, b.dictionary)
        av, bv = _merge_dicts(av, bv)
        a = dataclasses.replace(a, data=av.data,
                                dictionary=av.dictionary)
        b = dataclasses.replace(b, data=bv.data,
                                dictionary=bv.dictionary)
    n, ca = a.data.shape
    cb = b.data.shape[1]
    # concatenate then compact b's elements to follow a's lengths
    data = jnp.concatenate([a.data, b.data], axis=1)
    am, bm = a.elem_mask(), b.elem_mask()
    keep = jnp.concatenate([am, bm], axis=1)
    pos = jnp.broadcast_to(jnp.arange(ca + cb, dtype=jnp.int32),
                           data.shape)
    out = jax.lax.sort(((~keep).astype(jnp.int32), pos, data),
                       num_keys=2, is_stable=True, dimension=1)
    lengths = (jnp.sum(am, axis=1) + jnp.sum(bm, axis=1)) \
        .astype(jnp.int32)
    return Val(e.dtype, out[2], and_valid(a.valid, b.valid),
               a.dictionary, lengths, None)


@scalar("array_distinct")
def _array_distinct(e, args):
    (v,) = args
    m = v.elem_mask()
    n, cap = v.data.shape
    # sort elements (dead padding last), mark the first of each equal
    # run, compact the marks. Output order is value-sorted, NOT
    # first-occurrence order (Trino preserves occurrence order;
    # documented divergence).
    big = jnp.where(m, v.data, jnp.asarray(
        jnp.iinfo(v.data.dtype).max if jnp.issubdtype(
            v.data.dtype, jnp.integer) else jnp.inf, v.data.dtype))
    sdata = jnp.sort(big, axis=1)
    first = jnp.concatenate(
        [jnp.ones((n, 1), bool), sdata[:, 1:] != sdata[:, :-1]], axis=1)
    cnt = jnp.sum(m, axis=1)
    slive = (jnp.arange(cap)[None, :] < cnt[:, None])
    keep = first & slive
    pos = jnp.broadcast_to(jnp.arange(cap, dtype=jnp.int32),
                           sdata.shape)
    out = jax.lax.sort(((~keep).astype(jnp.int32), pos, sdata),
                       num_keys=2, is_stable=True, dimension=1)
    lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
    return Val(e.dtype, out[2], v.valid, v.dictionary, lengths, None)


@scalar("array_sort_fn")
def _array_sort_fn(e, args):
    (v,) = args
    m = v.elem_mask()
    big = jnp.where(m, v.data, jnp.asarray(
        jnp.iinfo(v.data.dtype).max if jnp.issubdtype(
            v.data.dtype, jnp.integer) else jnp.inf, v.data.dtype))
    sdata = jnp.sort(big, axis=1)
    return Val(e.dtype, sdata, v.valid, v.dictionary,
               jnp.sum(m, axis=1).astype(jnp.int32), None)


@scalar("sequence")
def _sequence(e, args):
    lo, hi = e.args[0], e.args[1]
    if not (isinstance(lo, ir.Literal) and isinstance(hi, ir.Literal)):
        raise NotImplementedError(
            "sequence() requires literal bounds (static array "
            "capacity)")
    step = int(e.args[2].value) if len(e.args) > 2 else 1
    vals = np.arange(int(lo.value), int(hi.value) + (1 if step > 0
                                                     else -1), step,
                     dtype=np.int64)
    n = 1
    for v in args:
        if getattr(v.data, "ndim", 0) == 1:
            n = v.data.shape[0]
            break
    data = jnp.broadcast_to(jnp.asarray(vals)[None, :],
                            (n, len(vals)))
    lengths = jnp.full((n,), len(vals), jnp.int32)
    return Val(e.dtype, data, None, None, lengths, None)


@scalar("split")
def _split(e, args):
    """split(string, delim): per-dictionary-entry split into a padded
    2D LUT, rows gather by code (dictionary transform generalized to
    array outputs)."""
    v, delim = args[0], args[1]
    if not isinstance(e.args[1], ir.Literal):
        raise NotImplementedError("split() delimiter must be a literal")
    d = str(e.args[1].value)
    parts = [str(s).split(d) for s in v.dictionary]
    cap = max((len(p) for p in parts), default=1)
    vocab = sorted({x for p in parts for x in p})
    code_of = {x: i for i, x in enumerate(vocab)}
    lut = np.zeros((len(parts), cap), np.int32)
    lens = np.zeros(len(parts), np.int32)
    for i, p in enumerate(parts):
        lens[i] = len(p)
        for j, x in enumerate(p):
            lut[i, j] = code_of[x]
    codes = v.data
    if getattr(codes, "ndim", 0) == 0:
        codes = codes[None]
    codes = jnp.clip(codes, 0, max(len(parts) - 1, 0))
    data = jnp.asarray(lut)[codes]
    lengths = jnp.asarray(lens)[codes]
    return Val(e.dtype, data, v.valid,
               np.array(vocab, dtype=object), lengths, None)


@scalar("map_ctor")
def _map_ctor(e, args):
    karr, varr = args
    return Val(e.dtype, varr.data, and_valid(karr.valid, varr.valid),
               varr.dictionary, varr.lengths, varr.elem_valid,
               map_keys=karr)


@scalar("map_keys")
def _map_keys(e, args):
    (v,) = args
    k = v.map_keys
    return Val(e.dtype, k.data, v.valid, k.dictionary, k.lengths,
               k.elem_valid)


@scalar("map_values")
def _map_values(e, args):
    (v,) = args
    return Val(e.dtype, v.data, v.valid, v.dictionary, v.lengths,
               v.elem_valid)


# --- math tail / bitwise / url / binary-string functions --------------------
# (reference operator/scalar/MathFunctions.java, BitwiseFunctions.java,
# UrlFunctions.java, StringFunctions.java, VarbinaryFunctions.java)

_mathfn("sin", jnp.sin)
_mathfn("cos", jnp.cos)
_mathfn("tan", jnp.tan)
_mathfn("asin", jnp.arcsin)
_mathfn("acos", jnp.arccos)
_mathfn("atan", jnp.arctan)
_mathfn("atan2", jnp.arctan2, arity=2)
_mathfn("sinh", jnp.sinh)
_mathfn("cosh", jnp.cosh)
_mathfn("tanh", jnp.tanh)
_mathfn("degrees", jnp.degrees)
_mathfn("radians", jnp.radians)
_mathfn("exp2", jnp.exp2)


@scalar("log")
def _log_base(e, args):
    # log(base, x) — the reference's two-argument log
    b, x = args
    return Val(e.dtype, jnp.log(_as_f64(x)) / jnp.log(_as_f64(b)),
               and_valid(b.valid, x.valid))


@scalar("is_nan")
def _is_nan(e, args):
    (a,) = args
    return Val(e.dtype, jnp.isnan(_as_f64(a)), a.valid)


@scalar("is_finite")
def _is_finite(e, args):
    (a,) = args
    return Val(e.dtype, jnp.isfinite(_as_f64(a)), a.valid)


@scalar("is_infinite")
def _is_infinite(e, args):
    (a,) = args
    return Val(e.dtype, jnp.isinf(_as_f64(a)), a.valid)


def _bitfn(name, op, arity=2):
    @scalar(name)
    def _f(e, args, _op=op, _n=arity):
        if _n == 1:
            (a,) = args
            return Val(e.dtype, _op(a.data.astype(jnp.int64)), a.valid)
        a, b = args
        return Val(e.dtype, _op(a.data.astype(jnp.int64),
                                b.data.astype(jnp.int64)),
                   and_valid(a.valid, b.valid))
    return _f


_bitfn("bitwise_and", jnp.bitwise_and)
_bitfn("bitwise_or", jnp.bitwise_or)
_bitfn("bitwise_xor", jnp.bitwise_xor)
_bitfn("bitwise_not", jnp.bitwise_not, arity=1)
_bitfn("bitwise_left_shift", jnp.left_shift)
_bitfn("bitwise_right_shift",
       lambda a, b: (a.astype(jnp.uint64) >> b.astype(jnp.uint64))
       .astype(jnp.int64))


@scalar("bit_count")
def _bit_count(e, args):
    a = args[0]
    bits = int(e.args[1].value) if len(e.args) > 1 else 64
    v = a.data.astype(jnp.int64)
    if bits < 64:  # interpret as a ``bits``-wide two's complement value
        v = v & jnp.int64((1 << bits) - 1)
    cnt = jax.lax.population_count(v.view(jnp.uint64))
    return Val(e.dtype, cnt.astype(jnp.int64), a.valid)


@scalar("width_bucket")
def _width_bucket(e, args):
    x, lo, hi, nb = (cast_val(a, T.DOUBLE) for a in args)
    n = nb.data.astype(jnp.int64)
    span = hi.data - lo.data
    frac = (x.data - lo.data) / jnp.where(span == 0, 1.0, span)
    b = jnp.floor(frac * n).astype(jnp.int64) + 1
    b = jnp.where(x.data < lo.data, 0, b)
    b = jnp.where(x.data >= hi.data, n + 1, b)
    return Val(e.dtype, b, and_valid(*[a.valid for a in args]))


@scalar("codepoint")
def _codepoint(e, args):
    (col,) = args
    lut = jnp.asarray(np.array(
        [ord(str(s)[0]) if len(str(s)) else 0
         for s in col.dictionary], np.int64))
    return Val(e.dtype, lut[col.data], col.valid)


@scalar("chr")
def _chr(e, args):
    (a,) = args
    if not isinstance(e.args[0], ir.Literal):
        raise NotImplementedError("chr() requires a literal")
    return Val(T.VARCHAR, jnp.asarray(np.int32(0)), a.valid,
               np.array([chr(int(e.args[0].value))], object))


@scalar("translate")
def _translate(e, args):
    col = args[0]
    if not all(isinstance(a, ir.Literal) for a in e.args[1:]):
        raise NotImplementedError("translate with non-literal maps")
    src, dst = str(e.args[1].value), str(e.args[2].value)
    table = {ord(c): (dst[i] if i < len(dst) else None)
             for i, c in enumerate(src)}
    return _dict_transform(
        col, lambda d: np.array([str(s).translate(table) for s in d],
                                object))


@scalar("levenshtein_distance")
def _levenshtein(e, args):
    a, b = args
    if len(e.args) < 2 or not isinstance(e.args[1], ir.Literal):
        raise NotImplementedError(
            "levenshtein_distance needs a literal second argument")
    want = str(e.args[1].value)

    def dist(s: str) -> int:
        prev = list(range(len(want) + 1))
        for i, ca in enumerate(s, 1):
            cur = [i]
            for j, cb in enumerate(want, 1):
                cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                               prev[j - 1] + (ca != cb)))
            prev = cur
        return prev[-1]

    lut = jnp.asarray(np.array([dist(str(s)) for s in a.dictionary],
                               np.int64))
    return Val(e.dtype, lut[a.data], and_valid(a.valid, b.valid))


@scalar("hamming_distance")
def _hamming(e, args):
    a, b = args
    if len(e.args) < 2 or not isinstance(e.args[1], ir.Literal):
        raise NotImplementedError(
            "hamming_distance needs a literal second argument")
    want = str(e.args[1].value)

    def dist(s: str) -> int:
        s = str(s)
        if len(s) != len(want):
            return -1
        return sum(x != y for x, y in zip(s, want))

    lut = jnp.asarray(np.array([dist(s) for s in a.dictionary],
                               np.int64))
    ok = lut >= 0
    return Val(e.dtype, lut[a.data],
               and_valid(a.valid, ok[a.data]))


def _urlfn(name, extract):
    @scalar(name)
    def _f(e, args, _x=extract):
        return _dict_transform(
            args[0], lambda d: np.array([_x(str(s)) for s in d],
                                        object))
    return _f


def _url_parts(s: str):
    from urllib.parse import urlparse
    try:
        return urlparse(s)
    except ValueError:
        return urlparse("")


_urlfn("url_extract_protocol", lambda s: _url_parts(s).scheme)
_urlfn("url_extract_host", lambda s: _url_parts(s).hostname or "")
_urlfn("url_extract_path", lambda s: _url_parts(s).path)
_urlfn("url_extract_query", lambda s: _url_parts(s).query)
_urlfn("url_extract_fragment", lambda s: _url_parts(s).fragment)


@scalar("url_extract_port")
def _url_port(e, args):
    (col,) = args
    ports = np.array(
        [(_url_parts(str(s)).port or -1) for s in col.dictionary],
        np.int64)
    lut = jnp.asarray(ports)
    has = lut >= 0
    return Val(e.dtype, jnp.clip(lut[col.data], 0, None),
               and_valid(col.valid, has[col.data]))


@scalar("url_extract_parameter")
def _url_param(e, args):
    if not isinstance(e.args[1], ir.Literal):
        raise NotImplementedError(
            "url_extract_parameter needs a literal name")
    name = str(e.args[1].value)

    def get(s: str):
        from urllib.parse import parse_qs
        vals = parse_qs(_url_parts(s).query,
                        keep_blank_values=True).get(name)
        return vals[0] if vals else ""

    col = args[0]
    out = _dict_transform(
        col, lambda d: np.array([get(str(s)) for s in d], object))
    has = np.array(
        [name in parse_qs_keys(str(s)) for s in col.dictionary])
    hasr = jnp.asarray(has)[jnp.clip(col.data, 0,
                                     max(len(has) - 1, 0))]
    return Val(T.VARCHAR, out.data, and_valid(col.valid, hasr),
               out.dictionary)


def parse_qs_keys(s: str):
    from urllib.parse import parse_qs
    return parse_qs(_url_parts(s).query, keep_blank_values=True).keys()


_urlfn("url_encode",
       lambda s: __import__("urllib.parse", fromlist=["quote_plus"])
       .quote_plus(s))
_urlfn("url_decode",
       lambda s: __import__("urllib.parse", fromlist=["unquote_plus"])
       .unquote_plus(s))
_urlfn("to_hex", lambda s: s.encode().hex().upper())
_urlfn("from_hex", lambda s: bytes.fromhex(s).decode("utf-8",
                                                     "replace"))
_urlfn("md5",
       lambda s: __import__("hashlib").md5(s.encode()).hexdigest())
_urlfn("sha256",
       lambda s: __import__("hashlib").sha256(s.encode()).hexdigest())
_urlfn("to_base64",
       lambda s: __import__("base64").b64encode(s.encode()).decode())
_urlfn("from_base64",
       lambda s: __import__("base64").b64decode(s.encode())
       .decode("utf-8", "replace"))
