"""Typed row-expression IR.

Every node carries its SQL result type. The analyzer builds these from AST
expressions; the planner rewrites them; the compiler lowers them to JAX.
Analog of sql/relational/RowExpression.java + SpecialForm.java in the
reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

from presto_tpu import types as T


@dataclasses.dataclass(frozen=True)
class Expr:
    dtype: T.DataType


@dataclasses.dataclass(frozen=True)
class ColumnRef(Expr):
    """Reference to an input column by symbol name."""

    name: str = ""

    def __str__(self) -> str:
        return self.name


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    """A constant. For VARCHAR the value is the raw Python string; it is
    resolved against column dictionaries at compile (trace) time. For
    DECIMAL the value is the *scaled* integer. For DATE, epoch days.
    value=None means typed NULL."""

    value: Any = None

    def __str__(self) -> str:
        return repr(self.value)


@dataclasses.dataclass(frozen=True)
class Parameter(Expr):
    """A hoisted literal (templates/analysis.py): position ``index`` of
    the plan template's runtime parameter vector. Enters the traced
    program as a device scalar argument, so literal variants of one
    query shape share a compiled executable. Only ever present in
    plans produced by templates.parameterize — the planner/optimizer
    never emit it."""

    index: int = 0

    def __str__(self) -> str:
        return f"?{self.index}"


@dataclasses.dataclass(frozen=True)
class Call(Expr):
    """Scalar function call, including operators (add, eq, and, or, like...).
    Function semantics live in expr/functions.py."""

    fn: str = ""
    args: tuple[Expr, ...] = ()

    def __str__(self) -> str:
        return f"{self.fn}({', '.join(map(str, self.args))})"


@dataclasses.dataclass(frozen=True)
class Lambda(Expr):
    """Lambda argument of a higher-order array function. ``params``
    name the element variables; the body references them as ColumnRefs
    with those names (compile binds them to per-element 2D values).
    ``dtype`` is the body's result type."""

    params: tuple[str, ...] = ()
    body: Expr = None  # type: ignore[assignment]

    def __str__(self) -> str:
        return f"({', '.join(self.params)}) -> {self.body}"


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    arg: Expr = None  # type: ignore[assignment]

    def __str__(self) -> str:
        return f"cast({self.arg} as {self.dtype})"


@dataclasses.dataclass(frozen=True)
class CaseWhen(Expr):
    """Searched CASE: WHEN cond THEN value ... ELSE default.
    conditions[i] pairs with results[i]; default may be a typed-NULL
    Literal."""

    conditions: tuple[Expr, ...] = ()
    results: tuple[Expr, ...] = ()
    default: Expr = None  # type: ignore[assignment]

    def __str__(self) -> str:
        parts = " ".join(
            f"when {c} then {r}" for c, r in zip(self.conditions, self.results))
        return f"case {parts} else {self.default} end"


@dataclasses.dataclass(frozen=True)
class InList(Expr):
    """value IN (literals...). Non-literal IN lists lower to OR chains in
    the planner; IN subqueries become semijoins before reaching here."""

    arg: Expr = None  # type: ignore[assignment]
    values: tuple[Literal, ...] = ()

    def __str__(self) -> str:
        return f"{self.arg} in ({', '.join(map(str, self.values))})"


@dataclasses.dataclass(frozen=True)
class IsNull(Expr):
    arg: Expr = None  # type: ignore[assignment]
    negated: bool = False

    def __str__(self) -> str:
        return f"{self.arg} is {'not ' if self.negated else ''}null"


def walk(expr: Expr):
    """Yield expr and all descendants."""
    yield expr
    if isinstance(expr, Call):
        for a in expr.args:
            yield from walk(a)
    elif isinstance(expr, Cast):
        yield from walk(expr.arg)
    elif isinstance(expr, CaseWhen):
        for c in expr.conditions:
            yield from walk(c)
        for r in expr.results:
            yield from walk(r)
        if expr.default is not None:
            yield from walk(expr.default)
    elif isinstance(expr, InList):
        yield from walk(expr.arg)
        for v in expr.values:
            yield from walk(v)
    elif isinstance(expr, IsNull):
        yield from walk(expr.arg)
    elif isinstance(expr, Lambda):
        yield from walk(expr.body)


def referenced_columns(exprs: Sequence[Expr]) -> set[str]:
    """FREE column references (lambda parameters are bound names)."""
    out: set[str] = set()

    def visit(e: Expr, bound: frozenset) -> None:
        if isinstance(e, ColumnRef):
            if e.name not in bound:
                out.add(e.name)
            return
        if isinstance(e, Lambda):
            visit(e.body, bound | frozenset(e.params))
            return
        if isinstance(e, Call):
            for a in e.args:
                visit(a, bound)
        elif isinstance(e, Cast):
            visit(e.arg, bound)
        elif isinstance(e, CaseWhen):
            for c in e.conditions:
                visit(c, bound)
            for r in e.results:
                visit(r, bound)
            if e.default is not None:
                visit(e.default, bound)
        elif isinstance(e, InList):
            visit(e.arg, bound)
            for v in e.values:
                visit(v, bound)
        elif isinstance(e, IsNull):
            visit(e.arg, bound)

    for e in exprs:
        visit(e, frozenset())
    return out


def rewrite_refs(expr: Expr, mapping: dict[str, Expr]) -> Expr:
    """Substitute ColumnRefs by name (used by pushdown/inlining rules)."""
    if isinstance(expr, ColumnRef):
        return mapping.get(expr.name, expr)
    if isinstance(expr, Call):
        return Call(expr.dtype, expr.fn,
                    tuple(rewrite_refs(a, mapping) for a in expr.args))
    if isinstance(expr, Cast):
        return Cast(expr.dtype, rewrite_refs(expr.arg, mapping))
    if isinstance(expr, CaseWhen):
        return CaseWhen(
            expr.dtype,
            tuple(rewrite_refs(c, mapping) for c in expr.conditions),
            tuple(rewrite_refs(r, mapping) for r in expr.results),
            None if expr.default is None else rewrite_refs(expr.default, mapping),
        )
    if isinstance(expr, InList):
        return InList(expr.dtype, rewrite_refs(expr.arg, mapping), expr.values)
    if isinstance(expr, IsNull):
        return IsNull(expr.dtype, rewrite_refs(expr.arg, mapping), expr.negated)
    if isinstance(expr, Lambda):
        inner = {k: v for k, v in mapping.items()
                 if k not in expr.params}
        return Lambda(expr.dtype, expr.params,
                      rewrite_refs(expr.body, inner))
    return expr
