"""Typed row-expression IR.

Every node carries its SQL result type. The analyzer builds these from AST
expressions; the planner rewrites them; the compiler lowers them to JAX.
Analog of sql/relational/RowExpression.java + SpecialForm.java in the
reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

from presto_tpu import types as T


@dataclasses.dataclass(frozen=True)
class Expr:
    dtype: T.DataType


@dataclasses.dataclass(frozen=True)
class ColumnRef(Expr):
    """Reference to an input column by symbol name."""

    name: str = ""

    def __str__(self) -> str:
        return self.name


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    """A constant. For VARCHAR the value is the raw Python string; it is
    resolved against column dictionaries at compile (trace) time. For
    DECIMAL the value is the *scaled* integer. For DATE, epoch days.
    value=None means typed NULL."""

    value: Any = None

    def __str__(self) -> str:
        return repr(self.value)


@dataclasses.dataclass(frozen=True)
class Call(Expr):
    """Scalar function call, including operators (add, eq, and, or, like...).
    Function semantics live in expr/functions.py."""

    fn: str = ""
    args: tuple[Expr, ...] = ()

    def __str__(self) -> str:
        return f"{self.fn}({', '.join(map(str, self.args))})"


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    arg: Expr = None  # type: ignore[assignment]

    def __str__(self) -> str:
        return f"cast({self.arg} as {self.dtype})"


@dataclasses.dataclass(frozen=True)
class CaseWhen(Expr):
    """Searched CASE: WHEN cond THEN value ... ELSE default.
    conditions[i] pairs with results[i]; default may be a typed-NULL
    Literal."""

    conditions: tuple[Expr, ...] = ()
    results: tuple[Expr, ...] = ()
    default: Expr = None  # type: ignore[assignment]

    def __str__(self) -> str:
        parts = " ".join(
            f"when {c} then {r}" for c, r in zip(self.conditions, self.results))
        return f"case {parts} else {self.default} end"


@dataclasses.dataclass(frozen=True)
class InList(Expr):
    """value IN (literals...). Non-literal IN lists lower to OR chains in
    the planner; IN subqueries become semijoins before reaching here."""

    arg: Expr = None  # type: ignore[assignment]
    values: tuple[Literal, ...] = ()

    def __str__(self) -> str:
        return f"{self.arg} in ({', '.join(map(str, self.values))})"


@dataclasses.dataclass(frozen=True)
class IsNull(Expr):
    arg: Expr = None  # type: ignore[assignment]
    negated: bool = False

    def __str__(self) -> str:
        return f"{self.arg} is {'not ' if self.negated else ''}null"


def walk(expr: Expr):
    """Yield expr and all descendants."""
    yield expr
    if isinstance(expr, Call):
        for a in expr.args:
            yield from walk(a)
    elif isinstance(expr, Cast):
        yield from walk(expr.arg)
    elif isinstance(expr, CaseWhen):
        for c in expr.conditions:
            yield from walk(c)
        for r in expr.results:
            yield from walk(r)
        if expr.default is not None:
            yield from walk(expr.default)
    elif isinstance(expr, InList):
        yield from walk(expr.arg)
        for v in expr.values:
            yield from walk(v)
    elif isinstance(expr, IsNull):
        yield from walk(expr.arg)


def referenced_columns(exprs: Sequence[Expr]) -> set[str]:
    out: set[str] = set()
    for e in exprs:
        for node in walk(e):
            if isinstance(node, ColumnRef):
                out.add(node.name)
    return out


def rewrite_refs(expr: Expr, mapping: dict[str, Expr]) -> Expr:
    """Substitute ColumnRefs by name (used by pushdown/inlining rules)."""
    if isinstance(expr, ColumnRef):
        return mapping.get(expr.name, expr)
    if isinstance(expr, Call):
        return Call(expr.dtype, expr.fn,
                    tuple(rewrite_refs(a, mapping) for a in expr.args))
    if isinstance(expr, Cast):
        return Cast(expr.dtype, rewrite_refs(expr.arg, mapping))
    if isinstance(expr, CaseWhen):
        return CaseWhen(
            expr.dtype,
            tuple(rewrite_refs(c, mapping) for c in expr.conditions),
            tuple(rewrite_refs(r, mapping) for r in expr.results),
            None if expr.default is None else rewrite_refs(expr.default, mapping),
        )
    if isinstance(expr, InList):
        return InList(expr.dtype, rewrite_refs(expr.arg, mapping), expr.values)
    if isinstance(expr, IsNull):
        return IsNull(expr.dtype, rewrite_refs(expr.arg, mapping), expr.negated)
    return expr
