"""File-format readers (the engine's analog of the reference's
lib/trino-parquet and lib/trino-orc readers)."""
