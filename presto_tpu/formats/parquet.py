"""From-scratch Parquet reader: the engine's first contact with
non-synthetic data (reference lib/trino-parquet — metadata reader
ParquetMetadataReader.java, page codec ParquetCompressionUtils.java,
RLE/bit-packed hybrid RunLengthBitPackingHybridDecoder.java).

Scope (the format's core, covering what pyarrow and most writers emit
for flat tables):
- Thrift COMPACT protocol metadata decoding (no thrift dependency —
  the protocol is a few varint rules, implemented in _CompactReader)
- flat schemas: required/optional primitive columns (nested lists/maps
  are rejected with a clear error)
- data page V1 and V2, PLAIN and RLE_DICTIONARY/PLAIN_DICTIONARY
  encodings, RLE/bit-packed hybrid definition levels
- physical types BOOLEAN/INT32/INT64/FLOAT/DOUBLE/BYTE_ARRAY/
  FIXED_LEN_BYTE_ARRAY with DATE/DECIMAL/UTF8 logical interpretation
- UNCOMPRESSED and SNAPPY column chunks (own snappy decoder — the
  raw-format LZ77 with 4 tag kinds)

Values decode into numpy columns ready for the engine's Block layer;
level/index unpacking is vectorized (np.unpackbits reshapes) rather
than per-value loops.
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

from presto_tpu import types as T

MAGIC = b"PAR1"

# parquet physical types
BOOLEAN, INT32, INT64, INT96, FLOAT, DOUBLE, BYTE_ARRAY, FIXED = range(8)
# page types
DATA_PAGE, INDEX_PAGE, DICTIONARY_PAGE, DATA_PAGE_V2 = range(4)
# encodings
ENC_PLAIN = 0
ENC_PLAIN_DICTIONARY = 2
ENC_RLE = 3
ENC_RLE_DICTIONARY = 8
# codecs
CODEC_UNCOMPRESSED = 0
CODEC_SNAPPY = 1


class ParquetError(RuntimeError):
    pass


# --------------------------------------------------------------------------
# Thrift compact protocol


class _CompactReader:
    """Minimal Thrift compact-protocol struct reader: produces
    {field_id: python value} dicts with nested structs/lists."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def _varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self._byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def _zigzag(self) -> int:
        v = self._varint()
        return (v >> 1) ^ -(v & 1)

    def _binary(self) -> bytes:
        n = self._varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def _value(self, ftype: int):
        if ftype == 1:
            return True
        if ftype == 2:
            return False
        if ftype == 3:
            return self._zigzag()
        if ftype in (4, 5, 6):
            return self._zigzag()
        if ftype == 7:
            v = struct.unpack_from("<d", self.buf, self.pos)[0]
            self.pos += 8
            return v
        if ftype == 8:
            return self._binary()
        if ftype in (9, 10):
            return self._list()
        if ftype == 12:
            return self.struct()
        raise ParquetError(f"thrift compact type {ftype}")

    def _list(self):
        head = self._byte()
        size = head >> 4
        etype = head & 0x0F
        if size == 15:
            size = self._varint()
        if etype in (1, 2):  # bool elements carry values in-band
            return [self._byte() == 1 for _ in range(size)]
        return [self._value(etype) for _ in range(size)]

    def struct(self) -> dict:
        out: dict[int, object] = {}
        fid = 0
        while True:
            head = self._byte()
            if head == 0:
                return out
            delta = head >> 4
            ftype = head & 0x0F
            fid = fid + delta if delta else self._zigzag()
            out[fid] = self._value(ftype)


# --------------------------------------------------------------------------
# Snappy (raw format)


def snappy_decompress(src: bytes) -> bytes:
    """Raw-snappy decoder: varint uncompressed length, then literal /
    copy tags (the format has exactly four element kinds)."""
    pos = 0
    out_len = 0
    shift = 0
    while True:
        b = src[pos]
        pos += 1
        out_len |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray(out_len)
    opos = 0
    n = len(src)
    while pos < n:
        tag = src[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            ln = (tag >> 2) + 1
            if ln > 60:
                extra = ln - 60
                ln = int.from_bytes(src[pos:pos + extra], "little") + 1
                pos += extra
            out[opos:opos + ln] = src[pos:pos + ln]
            pos += ln
            opos += ln
            continue
        if kind == 1:
            ln = ((tag >> 2) & 0x7) + 4
            off = ((tag >> 5) << 8) | src[pos]
            pos += 1
        elif kind == 2:
            ln = (tag >> 2) + 1
            off = int.from_bytes(src[pos:pos + 2], "little")
            pos += 2
        else:
            ln = (tag >> 2) + 1
            off = int.from_bytes(src[pos:pos + 4], "little")
            pos += 4
        start = opos - off
        if off >= ln:  # non-overlapping: one slice copy
            out[opos:opos + ln] = out[start:start + ln]
            opos += ln
        else:  # overlapping run: byte-by-byte semantics
            for _ in range(ln):
                out[opos] = out[opos - off]
                opos += 1
    return bytes(out)


def _decompress(codec: int, data: bytes, uncompressed_size: int) -> bytes:
    if codec == CODEC_UNCOMPRESSED:
        return data
    if codec == CODEC_SNAPPY:
        return snappy_decompress(data)
    raise ParquetError(f"unsupported compression codec {codec}")


# --------------------------------------------------------------------------
# RLE / bit-packed hybrid


def rle_bp_decode(buf: bytes, bit_width: int, count: int) -> np.ndarray:
    """Decode ``count`` values from an RLE/bit-packed hybrid run
    (reference RunLengthBitPackingHybridDecoder.java). Bit-packed
    groups unpack vectorized via np.unpackbits."""
    if bit_width == 0:
        return np.zeros(count, np.int64)
    out = np.empty(count, np.int64)
    filled = 0
    pos = 0
    byte_w = (bit_width + 7) // 8
    while filled < count:
        header = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if header & 1:  # bit-packed: (header>>1) groups of 8
            groups = header >> 1
            nvals = groups * 8
            nbytes = groups * bit_width
            raw = np.frombuffer(buf, np.uint8, nbytes, pos)
            pos += nbytes
            bits = np.unpackbits(raw, bitorder="little")
            vals = bits.reshape(nvals, bit_width)
            weights = (1 << np.arange(bit_width, dtype=np.int64))
            decoded = vals.astype(np.int64) @ weights
            take = min(nvals, count - filled)
            out[filled:filled + take] = decoded[:take]
            filled += take
        else:  # RLE run
            run = header >> 1
            v = int.from_bytes(buf[pos:pos + byte_w], "little")
            pos += byte_w
            take = min(run, count - filled)
            out[filled:filled + take] = v
            filled += take
    return out


# --------------------------------------------------------------------------
# value decoding


def _plain_values(ptype: int, buf: bytes, n: int, type_length: int):
    if ptype == INT32:
        return np.frombuffer(buf, "<i4", n)
    if ptype == INT64:
        return np.frombuffer(buf, "<i8", n)
    if ptype == FLOAT:
        return np.frombuffer(buf, "<f4", n)
    if ptype == DOUBLE:
        return np.frombuffer(buf, "<f8", n)
    if ptype == BOOLEAN:
        bits = np.unpackbits(np.frombuffer(buf, np.uint8),
                             bitorder="little")
        return bits[:n].astype(bool)
    if ptype == BYTE_ARRAY:
        out = np.empty(n, object)
        pos = 0
        for i in range(n):
            ln = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
            out[i] = buf[pos:pos + ln]
            pos += ln
        return out
    if ptype == FIXED:
        out = np.empty(n, object)
        for i in range(n):
            out[i] = buf[i * type_length:(i + 1) * type_length]
        return out
    raise ParquetError(f"unsupported physical type {ptype}")


@dataclasses.dataclass
class _SchemaCol:
    name: str
    ptype: int
    optional: bool
    type_length: int
    converted: int | None
    scale: int
    precision: int
    logical: dict | None


def _engine_type(col: _SchemaCol) -> T.DataType:
    # LogicalType union field ids (parquet.thrift): 1 STRING, 5
    # DECIMAL, 6 DATE, 8 TIMESTAMP; ConvertedType enum: 0 UTF8,
    # 5 DECIMAL, 6 DATE, 9/10 TIMESTAMP_(MILLIS|MICROS)
    lt = col.logical or {}
    if 6 in lt or col.converted == 6:
        return T.DATE
    if 1 in lt or col.converted == 0:
        return T.VARCHAR
    if 5 in lt or col.converted == 5:
        return T.DecimalType(col.precision or 38, col.scale or 0)
    if (8 in lt or col.converted in (9, 10)) and col.ptype == INT64:
        return T.TIMESTAMP
    return {
        BOOLEAN: T.BOOLEAN, INT32: T.INTEGER, INT64: T.BIGINT,
        FLOAT: T.DOUBLE, DOUBLE: T.DOUBLE, BYTE_ARRAY: T.VARCHAR,
        FIXED: T.VARCHAR,
    }.get(col.ptype, T.VARCHAR)


class ParquetFile:
    """One Parquet file's metadata + column readers."""

    def __init__(self, path: str):
        import mmap

        self.path = path
        with open(path, "rb") as f:
            # map instead of slurping: footer-only operations (schema,
            # stats, row counts — every plan-time call) touch just the
            # file tail, and the OS pages data in as chunks decode
            data = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        if data[:4] != MAGIC or data[-4:] != MAGIC:
            raise ParquetError(f"{path}: not a parquet file")
        footer_len = int.from_bytes(data[-8:-4], "little")
        meta = _CompactReader(data[len(data) - 8 - footer_len:]).struct()
        self._data = data
        self.num_rows = int(meta.get(3, 0))
        self.columns: list[_SchemaCol] = []
        schema = meta.get(2, [])
        root = schema[0] if schema else {}
        if int(root.get(5, 0)) != len(schema) - 1:
            raise ParquetError(
                f"{path}: nested schemas are not supported (flat "
                "primitive columns only)")
        for el in schema[1:]:
            if 5 in el and el[5]:
                raise ParquetError(
                    f"{path}: nested column "
                    f"{el.get(4, b'?').decode()} unsupported")
            self.columns.append(_SchemaCol(
                name=el[4].decode(),
                ptype=int(el[1]),
                optional=int(el.get(3, 0)) == 1,
                type_length=int(el.get(2, 0)),
                converted=(int(el[6]) if 6 in el else None),
                scale=int(el.get(7, 0)),
                precision=int(el.get(8, 0)),
                logical=el.get(10)))
        self.row_groups = meta.get(4, [])

    def schema(self) -> dict[str, T.DataType]:
        return {c.name: _engine_type(c) for c in self.columns}

    def column_stats(self, name: str):
        """Per-row-group (min, max) for integer-physical columns, or
        None entries where statistics are absent (footer
        ColumnMetaData.statistics, fields 5/6 min_value/max_value with
        the deprecated 1/2 fallback) — the input to row-group pruning
        (reference parquet predicate/TupleDomainParquetPredicate)."""
        idx = next((i for i, c in enumerate(self.columns)
                    if c.name == name), None)
        if idx is None:
            raise ParquetError(f"{self.path}: no column {name}")
        col = self.columns[idx]
        if col.ptype not in (INT32, INT64):
            return [None] * len(self.row_groups)
        # only UNIT-EXACT logical types: the engine compares stats
        # against physical literals (epoch days, scaled ints), but
        # TIMESTAMP stats stay in the file's millis/nanos unit while
        # engine literals are micros — pruning on them would drop
        # matching row groups
        et = _engine_type(col)
        if not isinstance(et, (T.BigintType, T.IntegerType,
                               T.DateType)):
            return [None] * len(self.row_groups)
        width = 4 if col.ptype == INT32 else 8
        out = []
        for rg in self.row_groups:
            st = rg[1][idx][3].get(12)
            if not st:
                out.append(None)
                continue
            mx = st.get(5, st.get(1))
            mn = st.get(6, st.get(2))
            if mn is None or mx is None or len(mn) != width \
                    or len(mx) != width:
                out.append(None)
                continue
            out.append((int.from_bytes(mn, "little", signed=True),
                        int.from_bytes(mx, "little", signed=True)))
        return out

    def read_column(self, name: str, row_groups=None):
        """(values np.ndarray, valid bool[n] | None) across the
        selected row groups (None = all)."""
        idx = next((i for i, c in enumerate(self.columns)
                    if c.name == name), None)
        if idx is None:
            raise ParquetError(f"{self.path}: no column {name}")
        col = self.columns[idx]
        vals_parts = []
        valid_parts = []
        any_null = False
        groups = (self.row_groups if row_groups is None
                  else [self.row_groups[i] for i in row_groups])
        for rg in groups:
            chunk = rg[1][idx]
            cmeta = chunk[3]
            vals, valid = self._read_chunk(col, cmeta)
            vals_parts.append(vals)
            if valid is None:
                valid_parts.append(np.ones(len(vals), bool))
            else:
                any_null = True
                valid_parts.append(valid)
        values = (np.concatenate(vals_parts) if vals_parts
                  else np.empty(0))
        valid = np.concatenate(valid_parts) if valid_parts else None
        return values, (valid if any_null else None)

    def _read_chunk(self, col: _SchemaCol, cmeta: dict):
        codec = int(cmeta.get(4, 0))
        num_values = int(cmeta.get(5, 0))
        start = int(cmeta.get(11, cmeta.get(9, 0)) or cmeta.get(9, 0))
        pos = start
        dictionary = None
        values = []
        valids = []
        got = 0
        while got < num_values:
            rd = _CompactReader(self._data, pos)
            header = rd.struct()
            body_start = rd.pos
            ptype = int(header.get(1, 0))
            comp_size = int(header.get(3, 0))
            uncomp_size = int(header.get(2, 0))
            body = self._data[body_start:body_start + comp_size]
            pos = body_start + comp_size
            if ptype == DICTIONARY_PAGE:
                dh = header.get(7, {})
                n = int(dh.get(1, 0))
                raw = _decompress(codec, body, uncomp_size)
                dictionary = _plain_values(col.ptype, raw, n,
                                           col.type_length)
                continue
            if ptype == DATA_PAGE:
                dh = header.get(5, {})
                n = int(dh.get(1, 0))
                enc = int(dh.get(2, 0))
                raw = _decompress(codec, body, uncomp_size)
                vpos = 0
                valid = None
                if col.optional:
                    ln = int.from_bytes(raw[:4], "little")
                    levels = rle_bp_decode(raw[4:4 + ln], 1, n)
                    valid = levels.astype(bool)
                    vpos = 4 + ln
                npresent = int(valid.sum()) if valid is not None else n
                vals = self._decode_values(
                    col, enc, raw[vpos:], npresent, dictionary)
                values.append((vals, valid, n))
                got += n
                continue
            if ptype == DATA_PAGE_V2:
                dh = header.get(8, {})
                n = int(dh.get(1, 0))
                nnull = int(dh.get(2, 0))
                enc = int(dh.get(4, 0))
                dl_len = int(dh.get(5, 0))
                rl_len = int(dh.get(6, 0))
                compressed = bool(dh.get(7, True))
                levels = self._data[body_start:body_start + rl_len
                                    + dl_len]
                vbody = body[rl_len + dl_len:]
                raw = (_decompress(codec, vbody,
                                   uncomp_size - rl_len - dl_len)
                       if compressed else vbody)
                valid = None
                if col.optional:
                    lv = rle_bp_decode(
                        levels[rl_len:rl_len + dl_len], 1, n)
                    valid = lv.astype(bool)
                npresent = n - nnull
                vals = self._decode_values(col, enc, raw, npresent,
                                           dictionary)
                values.append((vals, valid, n))
                got += n
                continue
            raise ParquetError(f"unsupported page type {ptype}")
        return self._assemble(col, values)

    def _decode_values(self, col: _SchemaCol, enc: int, raw: bytes,
                       n: int, dictionary):
        if enc == ENC_PLAIN:
            return _plain_values(col.ptype, raw, n, col.type_length)
        if enc in (ENC_PLAIN_DICTIONARY, ENC_RLE_DICTIONARY):
            if dictionary is None:
                raise ParquetError("dictionary page missing")
            width = raw[0]
            idxs = rle_bp_decode(raw[1:], width, n)
            return dictionary[idxs]
        if enc == ENC_RLE and col.ptype == BOOLEAN:
            # boolean values as an RLE/bit-packed run, 4-byte length
            # prefixed (format spec: RLE data encoding)
            ln = int.from_bytes(raw[:4], "little")
            return rle_bp_decode(raw[4:4 + ln], 1, n).astype(bool)
        raise ParquetError(f"unsupported encoding {enc}")

    def _assemble(self, col: _SchemaCol, pages):
        """Scatter present values to row positions + convert to the
        engine's physical representation."""
        total = sum(n for _, _, n in pages)
        valid_all = None
        if col.optional and any(v is not None for _, v, _ in pages):
            valid_all = np.concatenate([
                v if v is not None else np.ones(n, bool)
                for _, v, n in pages])
        present = (np.concatenate([np.asarray(v) for v, _, _ in pages])
                   if pages else np.empty(0))
        etype = _engine_type(col)
        vals = _convert(col, etype, present)
        if valid_all is None or valid_all.all():
            return vals, None
        # scatter present values into the full row vector
        if vals.dtype == object:
            full = np.empty(total, object)
            full[:] = b"" if isinstance(
                vals[0] if len(vals) else b"", bytes) else None
        else:
            full = np.zeros(
                total,
                vals.dtype if vals.ndim == 1 else vals.dtype)
            if vals.ndim == 2:
                full = np.zeros((total, vals.shape[1]), vals.dtype)
        full[valid_all] = vals
        return full, valid_all


def _convert(col: _SchemaCol, etype: T.DataType, present: np.ndarray):
    if isinstance(etype, T.DecimalType):
        if col.ptype in (INT32, INT64):
            scaled = present.astype(np.int64)
        else:  # FIXED / BYTE_ARRAY: big-endian two's complement
            scaled = np.array(
                [int.from_bytes(b, "big", signed=True)
                 for b in present], object)
        if etype.is_long:
            out = np.empty((len(scaled), 2), np.int64)
            for i, v in enumerate(scaled):
                m = int(v) & ((1 << 128) - 1)
                lo = m & ((1 << 64) - 1)
                hi = (m >> 64) & ((1 << 64) - 1)
                out[i, 0] = lo - (1 << 64) if lo >= 1 << 63 else lo
                out[i, 1] = hi - (1 << 64) if hi >= 1 << 63 else hi
            return out
        return np.asarray([int(v) for v in scaled], np.int64)
    if isinstance(etype, T.DateType):
        return present.astype(np.int32)
    if isinstance(etype, T.TimestampType):
        x = present.astype(np.int64)
        unit = ((col.logical or {}).get(8) or {}).get(2, {})
        if col.converted == 9 or 1 in unit:  # millis -> micros
            return x * 1000
        if 3 in unit:  # nanos -> micros
            return x // 1000
        return x  # micros
    if isinstance(etype, T.VarcharType):
        return np.array([b.decode("utf-8", "replace")
                         for b in present], object)
    if isinstance(etype, T.DoubleType):
        return present.astype(np.float64)
    if isinstance(etype, T.BooleanType):
        return present.astype(bool)
    return present.astype(np.int64)
