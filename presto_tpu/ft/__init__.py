"""Fault-tolerant distributed execution (the Trino FTE analog).

Three pieces, threaded through the coordinator, worker, exchange, and
server layers:

- **retry discipline** (``ft/retry.py``): session ``retry_policy`` in
  {NONE, QUERY, TASK}; bounded attempts with exponential backoff +
  full jitter and a per-query deadline budget; one
  :func:`retrying_call` helper classifying transient vs application
  failures for every internal HTTP call.
- **spooled exchange** (``ft/spool.py``): buffered task output pages
  persisted worker-locally (atomic tmp+rename) and served through the
  existing exchange endpoints, so a TASK retry re-fetches a dead
  producer's pages instead of aborting the query.
- **deterministic fault injection** (``ft/faults.py``): named fault
  points armed via ``PRESTO_TPU_FAULTS`` or :func:`FAULTS.arm`,
  hash-seeded so chaos tests reproduce exactly.
"""

from presto_tpu.ft.faults import (FAULT_POINTS, FAULTS, FaultRegistry,
                                  InjectedFault)
from presto_tpu.ft.retry import (RETRY_POLICIES, BackoffPolicy,
                                 Deadline, DeadlineExceeded,
                                 ExchangeFetchError,
                                 backoff_from_session, is_transient,
                                 parse_exchange_failure, retrying_call)
from presto_tpu.ft.spool import SpoolWriter, TaskSpool

__all__ = [
    "FAULT_POINTS", "FAULTS", "FaultRegistry", "InjectedFault",
    "RETRY_POLICIES", "BackoffPolicy", "Deadline", "DeadlineExceeded",
    "ExchangeFetchError", "backoff_from_session", "is_transient",
    "parse_exchange_failure", "retrying_call", "SpoolWriter",
    "TaskSpool",
]
