"""Deterministic fault injection for chaos-testing the cluster.

Named fault points sit at explicit call sites in the distributed
control plane (worker task intake, exchange page fetch, heartbeat
ping, task POST, XLA compile). Each point is ARMED with a probability,
a seed, an optional substring ``match`` against the call-site key, and
an optional total-fire ``limit``; an unarmed point costs one dict
lookup and fires never, so the hooks stay in production code.

Determinism: the fire decision for (point, key) is a pure hash of
``seed:point:key`` compared against the probability — NOT a shared
RNG stream — so concurrent dispatch threads cannot reorder draws and
the same seed reproduces the same failure set no matter how the
scheduler interleaves the cluster (the property chaos tests need).

Arming:

- env: ``PRESTO_TPU_FAULTS="point[:prob[:seed[:match[:limit]]]],..."``
  parsed once at first use (worker subprocesses inherit it);
- code: ``FAULTS.arm("worker-task-crash", prob=1.0, match="w1")`` for
  the in-process clusters the test suite boots.

Every fire increments ``presto_tpu_faults_injected_total{point=...}``
and emits a structured log line, so injected chaos is observable in
the same /metrics and jsonlog streams as the recovery it provokes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time

from presto_tpu.obs.jsonlog import LOG
from presto_tpu.obs.metrics import REGISTRY

# the named points and where they are injected
FAULT_POINTS = {
    "worker-task-crash": ("worker.py POST /v1/task: drop the "
                          "connection with no response (a worker "
                          "process dying mid-dispatch)"),
    "task-post-503": ("worker.py POST /v1/task: answer HTTP 503 (a "
                      "draining or overloaded node)"),
    "exchange-fetch-delay": ("worker.py _fetch_pages: sleep before "
                             "the page GET (a slow or congested peer)"),
    "exchange-fetch-drop": ("worker.py _fetch_pages: fail the page "
                            "GET with a connection error"),
    "heartbeat-blackout": ("coordinator.py RemoteWorker.ping: report "
                           "the node unreachable"),
    "compile-slow": ("exec/executor.py prepare_plan: sleep before "
                     "lower().compile() (compile-latency chaos)"),
}

ENV_VAR = "PRESTO_TPU_FAULTS"

_FIRED = REGISTRY.counter(
    "presto_tpu_faults_injected_total",
    "deterministic fault injections fired, by point (ft/faults.py)")


class InjectedFault(RuntimeError):
    """Raised by fault points that simulate a hard failure."""

    def __init__(self, point: str, key: str):
        super().__init__(f"injected fault {point!r} at {key!r}")
        self.point = point
        self.key = key


@dataclasses.dataclass
class _Armed:
    prob: float = 1.0
    seed: int = 0
    match: str = ""      # substring the key must contain ("" = any)
    limit: int | None = None  # max total fires (None = unbounded)
    delay_s: float = 0.05     # used by delay-type points
    fired: int = 0


def _decision(seed: int, point: str, key: str) -> float:
    """Uniform [0, 1) derived purely from (seed, point, key)."""
    digest = hashlib.blake2b(f"{seed}:{point}:{key}".encode(),
                             digest_size=8).digest()
    return int.from_bytes(digest, "big") / 2.0 ** 64


class FaultRegistry:
    """Thread-safe registry of armed fault points."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: dict[str, _Armed] = {}
        self._env_loaded = False

    # -- arming ----------------------------------------------------------

    def arm(self, point: str, prob: float = 1.0, seed: int = 0,
            match: str = "", limit: int | None = None,
            delay_s: float = 0.05) -> None:
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r} "
                f"(known: {sorted(FAULT_POINTS)})")
        with self._lock:
            self._armed[point] = _Armed(float(prob), int(seed),
                                        str(match), limit,
                                        float(delay_s))

    def disarm(self, point: str) -> None:
        with self._lock:
            self._armed.pop(point, None)

    def clear(self) -> None:
        with self._lock:
            self._armed.clear()

    def armed_points(self) -> list[str]:
        self._ensure_env()
        with self._lock:
            return sorted(self._armed)

    def load_env(self, value: str | None = None) -> None:
        """Parse ``PRESTO_TPU_FAULTS`` (or an explicit string):
        ``point[:prob[:seed[:match[:limit]]]]`` comma-separated."""
        spec = value if value is not None else os.environ.get(ENV_VAR)
        if not spec:
            return
        for item in spec.split(","):
            fields = item.strip().split(":")
            if not fields or not fields[0]:
                continue
            point = fields[0]
            prob = float(fields[1]) if len(fields) > 1 and fields[1] \
                else 1.0
            seed = int(fields[2]) if len(fields) > 2 and fields[2] \
                else 0
            match = fields[3] if len(fields) > 3 else ""
            limit = int(fields[4]) if len(fields) > 4 and fields[4] \
                else None
            self.arm(point, prob, seed, match, limit)

    def _ensure_env(self) -> None:
        with self._lock:
            if self._env_loaded:
                return
            self._env_loaded = True
        self.load_env()

    # -- firing ----------------------------------------------------------

    def should_fire(self, point: str, key: str = "") -> bool:
        """One deterministic draw for (point, key); counts and logs
        when it fires. The hot no-faults path is a single locked dict
        lookup."""
        self._ensure_env()
        with self._lock:
            armed = self._armed.get(point)
            if armed is None:
                return False
            if armed.match and armed.match not in key:
                return False
            if armed.limit is not None and armed.fired >= armed.limit:
                return False
            if _decision(armed.seed, point, key) >= armed.prob:
                return False
            armed.fired += 1
        _FIRED.inc(point=point)
        LOG.log("fault_injected", point=point, key=key)
        return True

    def fire(self, point: str, key: str = "") -> None:
        """Raise :class:`InjectedFault` when the point fires."""
        if self.should_fire(point, key):
            raise InjectedFault(point, key)

    def delay(self, point: str, key: str = "") -> None:
        """Sleep the armed delay when the point fires (slow-path
        chaos: compile stalls, congested exchange links)."""
        if not self.should_fire(point, key):
            return
        with self._lock:
            armed = self._armed.get(point)
            delay_s = armed.delay_s if armed is not None else 0.0
        time.sleep(delay_s)


# process-global registry: every injection site and the chaos tests
# share it (worker subprocesses re-create it from the env var)
FAULTS = FaultRegistry()
