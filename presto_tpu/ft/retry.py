"""Retry/backoff/deadline discipline for the distributed control plane.

The analog of Trino's fault-tolerant-execution retry machinery
(``retry-policy=QUERY|TASK``, io.trino.execution.QueryStateMachine +
io.airlift Backoff): every coordinator->worker RPC and worker->worker
exchange fetch routes through one :func:`retrying_call` helper that

- classifies failures as TRANSIENT (node died, connection refused,
  timeout, HTTP 502/503/504 — retrying elsewhere or later can succeed)
  vs APPLICATION errors (the task itself failed deterministically:
  ``TaskError`` / ``TaskFailed`` semantics — retrying would fail
  identically), and never retries the latter;
- backs off exponentially with FULL JITTER (sleep ~ U[0, min(cap,
  base*mult^attempt)] — the AWS-style decorrelated variant that avoids
  retry convoys when W workers retry the same dead peer at once);
- charges every retry against a per-query :class:`Deadline` budget so
  a flapping cluster fails loudly instead of retrying forever.

Retries are observable: each one increments
``presto_tpu_call_retries_total{op=...}`` and records a ``retry`` span
under the ambient trace (obs/trace.py), so a query's recovery shows up
in /metrics and the Chrome trace export.
"""

from __future__ import annotations

import dataclasses
import http.client
import random
import time
import urllib.error

from presto_tpu.obs import trace as OT
from presto_tpu.obs.metrics import REGISTRY

# retry policies (session property ``retry_policy``, the analog of
# Trino's retry-policy): NONE fails the query on the first task/node
# failure, QUERY re-runs the whole fragmented attempt on the surviving
# workers, TASK re-dispatches only the failed fragment tasks over the
# spooled exchange (ft/spool.py).
RETRY_POLICIES = ("NONE", "QUERY", "TASK")

_CALL_RETRIES = REGISTRY.counter(
    "presto_tpu_call_retries_total",
    "transient-failure retries of internal HTTP calls, by operation")

# HTTP statuses that mean "the node cannot take this request right now"
# (drain 503, proxy 502/504) — transient by contract; anything else the
# worker answered deliberately (application error).
TRANSIENT_HTTP_CODES = (502, 503, 504)


class DeadlineExceeded(RuntimeError):
    """The query's retry budget (``retry_deadline_s``) ran out."""


class ExchangeFetchError(RuntimeError):
    """A worker could not pull a producer task's pages. Carries the
    producer coordinates in a parseable form so the coordinator's
    TASK-retry path can repair the exchange (re-point the consumer at
    a surviving worker's spool, or re-run just that producer task)."""

    def __init__(self, task_id: str, part: int, uri: str, cause: str):
        super().__init__(
            f"exchange-fetch-failed task_id={task_id} part={part} "
            f"uri={uri}: {cause}")
        self.task_id = task_id
        self.part = part
        self.uri = uri


def parse_exchange_failure(message: str) -> tuple[str, str] | None:
    """(task_id, uri) out of an ExchangeFetchError message that crossed
    an HTTP error boundary as text; None when the message is not one."""
    import re
    m = re.search(r"exchange-fetch-failed task_id=(\S+) part=\d+ "
                  r"uri=(\S+):", message)
    if m is None:
        return None
    return m.group(1), m.group(2)


def is_transient(exc: BaseException) -> bool:
    """Transient (retry can help) vs application (deterministic)
    failure classification, shared by every retry site."""
    # local import: parallel/ imports this module at load time
    from presto_tpu.parallel.buffer import TaskFailed
    from presto_tpu.parallel.coordinator import TaskError
    if isinstance(exc, DeadlineExceeded):
        return False
    if isinstance(exc, ExchangeFetchError):
        # needs exchange REPAIR (coordinator-level), not a blind retry
        return False
    if isinstance(exc, (TaskError, TaskFailed)):
        return False
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in TRANSIENT_HTTP_CODES
    if isinstance(exc, (urllib.error.URLError, TimeoutError, OSError,
                        http.client.HTTPException)):
        return True
    return False


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Bounded exponential backoff with full jitter."""

    attempts: int = 3                # total tries, including the first
    initial_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0

    def delay_s(self, attempt: int,
                rng: random.Random | None = None) -> float:
        """Sleep before retry number ``attempt`` (0-based): full jitter
        over the exponentially growing cap."""
        cap = min(self.max_delay_s,
                  self.initial_delay_s * self.multiplier ** attempt)
        u = rng.random() if rng is not None else random.random()
        return u * cap


class Deadline:
    """Per-query wall-clock retry budget. ``budget_s`` <= 0 means
    unlimited (the reference's default: retries bounded by attempts
    only)."""

    def __init__(self, budget_s: float = 0.0):
        self.budget_s = float(budget_s)
        self._t0 = time.monotonic()

    @property
    def unlimited(self) -> bool:
        return self.budget_s <= 0

    def remaining_s(self) -> float:
        if self.unlimited:
            return float("inf")
        return self.budget_s - (time.monotonic() - self._t0)

    @property
    def expired(self) -> bool:
        return not self.unlimited and self.remaining_s() <= 0

    def check(self, op: str) -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"retry deadline of {self.budget_s:.1f}s exhausted "
                f"during {op}")

    def clamp(self, timeout_s: float) -> float:
        """Cap an individual call timeout to the remaining budget."""
        if self.unlimited:
            return timeout_s
        return max(0.001, min(timeout_s, self.remaining_s()))


def retrying_call(fn, *, op: str,
                  backoff: BackoffPolicy | None = None,
                  deadline: Deadline | None = None,
                  classify=is_transient,
                  rng: random.Random | None = None,
                  sleep=time.sleep):
    """Run ``fn()`` with transient-failure retries under ``backoff``
    and the optional ``deadline`` budget. Application errors and
    exhausted budgets propagate; every retry is counted and spanned."""
    policy = backoff if backoff is not None else BackoffPolicy()
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 - classified below
            if not classify(exc) or attempt + 1 >= policy.attempts:
                raise
            if deadline is not None:
                deadline.check(op)
            delay = policy.delay_s(attempt, rng)
            _CALL_RETRIES.inc(op=op)
            with OT.TRACER.span("retry", op=op, attempt=attempt,
                                delay_s=round(delay, 4),
                                error=f"{type(exc).__name__}: "
                                      f"{str(exc)[:200]}"):
                sleep(delay)
            attempt += 1


def backoff_from_session(session, attempts: int) -> BackoffPolicy:
    """Build the session-configured backoff (the same delay knobs serve
    task- and query-level retries; only the attempt bound differs)."""
    return BackoffPolicy(
        attempts=max(1, int(attempts)),
        initial_delay_s=float(session.get("retry_initial_delay_s")),
        max_delay_s=float(session.get("retry_max_delay_s")))
