"""Speculative straggler re-dispatch: attempt arbitration.

The Trino/Dryad-style mitigation for slow-node tail latency in the
TASK-mode stage walk (parallel/coordinator._execute_general_ft): when
most of a stage's sibling tasks have finished but one shard's task is
still running well past the siblings' typical completion time, the
coordinator dispatches a DUPLICATE attempt of that task on another
schedulable worker and takes whichever attempt finishes first. PR 5's
attempt-versioned task ids (``{qid}.{stage}.{shard}aN``) make the
duplicate collision-free, and the loser's output is dropped through
the existing task DELETE path (exact-id mode, so a losing primary
``...0`` cannot prefix-wipe its winning duplicate ``...0a1``).

This module holds the policy (session-configured thresholds) and the
:class:`StageArbiter` — the thread-safe first-finisher arbitration the
dispatch threads race through. The arbiter owns no sockets: dispatch,
retry, and cleanup stay in the coordinator; the arbiter only decides
who won, who should speculate, and when the stage is complete.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time

from presto_tpu.obs.metrics import REGISTRY

SPECULATIVE_ATTEMPTS = REGISTRY.counter(
    "presto_tpu_speculative_attempts_total",
    "duplicate task attempts dispatched against stragglers "
    "(ft/speculate.py)")
SPECULATIVE_WINS = REGISTRY.counter(
    "presto_tpu_speculative_wins_total",
    "stage tasks whose winning attempt was a speculative duplicate")


@dataclasses.dataclass(frozen=True)
class SpeculationPolicy:
    """Session-configured straggler thresholds: a task speculates once
    at least ``quantile`` of its siblings have finished and its own
    runtime exceeds ``multiplier`` x the quantile sibling completion
    time (floored at ``min_runtime_s`` so sub-second stages never
    duplicate work)."""

    enabled: bool = False
    quantile: float = 0.75
    multiplier: float = 2.0
    min_runtime_s: float = 0.5

    @classmethod
    def from_session(cls, session) -> "SpeculationPolicy":
        return cls(
            enabled=bool(session.get("speculative_execution")),
            quantile=min(max(
                float(session.get("speculation_quantile")), 0.05), 1.0),
            multiplier=max(
                float(session.get("speculation_threshold")), 1.0),
            min_runtime_s=max(
                float(session.get("speculation_min_runtime_s")), 0.0))


class AttemptLost(Exception):
    """Internal sentinel: this attempt finished second — its result
    was discarded and its task should be cleaned up by the caller."""


class StageArbiter:
    """First-finisher arbitration for one stage's W sharded tasks.

    Dispatch threads (primary and speculative attempts alike) call
    :meth:`claim_win` when their POST succeeds; exactly one attempt per
    shard wins. The stage driver waits on :meth:`wait_all_won`, polling
    :meth:`stragglers` to launch duplicates. Failures decrement the
    shard's outstanding-attempt count; a shard whose every attempt
    failed surfaces the last error to the driver."""

    def __init__(self, nshards: int, policy: SpeculationPolicy,
                 clock=time.monotonic):
        self.nshards = nshards
        self.policy = policy
        self._clock = clock
        self._cv = threading.Condition()
        self._t0 = clock()
        # shard -> (winning task id, result, was-speculative)
        self._won: dict[int, tuple[str, object, bool]] = {}
        self._durations: list[float] = []
        self._speculated: set[int] = set()
        self._spec_won = 0
        self._outstanding: dict[int, int] = {
            i: 1 for i in range(nshards)}
        self._errors: dict[int, BaseException] = {}

    # -- dispatch-thread side --------------------------------------------

    def has_winner(self, shard: int) -> bool:
        with self._cv:
            return shard in self._won

    def claim_win(self, shard: int, task_id: str, out,
                  speculative: bool, on_win=None) -> bool:
        """True when this attempt is the shard's first finisher; False
        when another attempt already won (the caller discards and
        cleans up). ``on_win`` runs INSIDE the claim's critical
        section, BEFORE the stage driver can observe the win — the
        winner's placement must be published before ``all_won()`` can
        release the walk to build the next stage's payloads, or a
        preempted winner thread would leave its producer entry missing
        from the consumer refs."""
        with self._cv:
            if shard in self._won:
                return False
            if on_win is not None:
                on_win()
            self._won[shard] = (task_id, out, speculative)
            self._durations.append(self._clock() - self._t0)
            if speculative:
                self._spec_won += 1
            self._cv.notify_all()
        if speculative:
            SPECULATIVE_WINS.inc()
        return True

    def winner_task_id(self, shard: int) -> str | None:
        with self._cv:
            hit = self._won.get(shard)
            return hit[0] if hit is not None else None

    def winner_was_speculative(self, shard: int) -> bool:
        with self._cv:
            hit = self._won.get(shard)
            return bool(hit is not None and hit[2])

    def record_failure(self, shard: int, exc: BaseException) -> None:
        """One attempt for ``shard`` exhausted its retries. The stage
        only fails when no attempt for the shard remains in flight and
        none won."""
        with self._cv:
            self._outstanding[shard] -= 1
            self._errors[shard] = exc
            self._cv.notify_all()

    # -- stage-driver side -----------------------------------------------

    def note_speculation(self, shard: int) -> None:
        with self._cv:
            self._speculated.add(shard)
            self._outstanding[shard] += 1
        SPECULATIVE_ATTEMPTS.inc()

    def stragglers(self) -> list[int]:
        """Shards that should speculate NOW: enough siblings finished,
        the shard has no winner, no duplicate yet, and its runtime
        exceeds the policy threshold."""
        p = self.policy
        if not p.enabled or self.nshards < 2:
            return []
        with self._cv:
            done = sorted(self._durations)
            # at least the quantile share of siblings must have
            # finished — capped at W-1 so a 2-shard stage can still
            # speculate against its single straggler
            need = min(self.nshards - 1,
                       max(1, math.ceil(p.quantile * self.nshards)))
            if len(done) < need or len(self._won) >= self.nshards:
                return []
            # the quantile completion time of the finished siblings
            qi = min(max(math.ceil(p.quantile * len(done)) - 1, 0),
                     len(done) - 1)
            threshold = max(p.min_runtime_s, p.multiplier * done[qi])
            now = self._clock() - self._t0
            if now <= threshold:
                return []
            return [i for i in range(self.nshards)
                    if i not in self._won
                    and i not in self._speculated
                    and self._outstanding.get(i, 0) > 0]

    def wait_turn(self, timeout_s: float) -> None:
        with self._cv:
            if len(self._won) < self.nshards:
                self._cv.wait(timeout=timeout_s)

    def failed_shard(self) -> tuple[int, BaseException] | None:
        """A shard with zero attempts left and no winner, or None."""
        with self._cv:
            for i in range(self.nshards):
                if i not in self._won \
                        and self._outstanding.get(i, 0) <= 0:
                    return i, self._errors.get(
                        i, RuntimeError(f"shard {i} failed"))
            return None

    def all_won(self) -> bool:
        with self._cv:
            return len(self._won) >= self.nshards

    def results(self) -> list:
        with self._cv:
            return [self._won[i][1] for i in range(self.nshards)]

    def speculation_summary(self) -> dict:
        with self._cv:
            return {"speculated": sorted(self._speculated),
                    "speculative_wins": self._spec_won}
