"""Spooled exchange: task output pages persisted for fault tolerance.

The analog of Trino's fault-tolerant spooling exchange (the
``exchange.base-directories`` filesystem exchange behind
``retry-policy=TASK``): a worker running a buffered fragment task
writes every page it emits into a worker-local spool directory
(atomic tmp+rename, the progcache discipline) alongside the in-memory
OutputBuffer.

Arrow pages persist as Arrow IPC **files** (``p*.arrow``): the
producer's already-encoded batch is re-framed with the IPC file footer
— the buffers are referenced, never value-decoded — and consumers are
served straight off ``mmap`` (pyarrow ``memory_map``): exchange
REPAIR, retried consumers, and stats replay stream spooled bytes from
the page cache with ZERO deserialization and zero heap copies on the
serving worker (PAPERS.md 2204.03032: columnar IPC saturates the link
once serde leaves the path). npz pages (``p*.page``, the
mixed-version fallback) are mmap-served verbatim the same way.

The spool serves through the EXISTING exchange HTTP surface: the
worker results endpoint falls back to the spool when the in-memory
buffer is gone (evicted, task deleted, or the page already freed by a
prior reader's acks), so a TASK retry can re-fetch a dead producer's
pages from any worker sharing the spool directory instead of aborting
the query ("buffers on the dead node are lost") or recomputing the
task.

Layout: ``{dir}/{task_id}/p{partition}.{index:06d}.arrow`` (or
``.page``) plus a ``COMPLETE.json`` marker carrying per-partition page
counts and row counts; a task without the marker is not served (a
half-spooled failed attempt must never feed a consumer — stale
attempts are additionally unreachable because retries get fresh
attempt-versioned task ids).
"""

from __future__ import annotations

import json
import mmap as _mmap
import os
import re
import shutil
import threading

from presto_tpu.obs.metrics import REGISTRY

_SPOOLED_PAGES = REGISTRY.counter(
    "presto_tpu_spooled_pages_total",
    "task output pages persisted to the exchange spool (ft/spool.py)")
_SPOOL_SERVED = REGISTRY.counter(
    "presto_tpu_spool_served_pages_total",
    "exchange pages served from the spool instead of a live buffer")
_SPOOL_MMAP = REGISTRY.counter(
    "presto_tpu_spool_mmap_served_pages_total",
    "spooled pages served zero-copy off an mmap of the page cache "
    "(no deserialize, no heap copy on the serving worker)")

_TASK_ID_RE = re.compile(r"^[A-Za-z0-9._\-]+$")

COMPLETE_MARKER = "COMPLETE.json"


def _safe(task_id: str) -> str:
    if not _TASK_ID_RE.match(task_id):
        raise ValueError(f"unspoolable task id {task_id!r}")
    return task_id


def _mmap_bytes(path: str):
    """A read-only memoryview over the file's mapping: the HTTP
    handler writes it to the socket straight off the page cache (no
    heap copy, no deserialize); the view keeps the map alive."""
    with open(path, "rb") as f:
        if os.fstat(f.fileno()).st_size == 0:
            return memoryview(b"")
        mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
    return memoryview(mm)


class TaskSpool:
    """One worker's spool directory (may be shared between workers —
    any worker with the directory can serve any spooled task)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    # -- producer side ---------------------------------------------------

    def writer(self, task_id: str) -> "SpoolWriter":
        return SpoolWriter(self, _safe(task_id))

    # -- consumer side ---------------------------------------------------

    def _task_dir(self, task_id: str) -> str:
        return os.path.join(self.directory, _safe(task_id))

    def complete_meta(self, task_id: str) -> dict | None:
        """The completion marker, or None when the task is absent or
        was never completed (do not serve half-spooled output)."""
        try:
            with open(os.path.join(self._task_dir(task_id),
                                   COMPLETE_MARKER),
                      encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _page_path(self, task_id: str, partition: int,
                   token: int) -> str:
        base = os.path.join(self._task_dir(task_id),
                            f"p{partition}.{token:06d}")
        arrow = f"{base}.arrow"
        if os.path.exists(arrow):
            return arrow
        return f"{base}.page"

    def page(self, task_id: str, partition: int,
             token: int) -> tuple[memoryview | None, int, bool]:
        """Same (blob, next_token, complete) contract as
        OutputBuffer.page, served off a read-only mmap of the page file
        (zero deserialization — arrow pages go to the socket in their
        IPC file form, which any current reader parses zero-copy).
        Raises FileNotFoundError when the task is not spooled (caller
        404s)."""
        meta = self.complete_meta(task_id)
        if meta is None:
            raise FileNotFoundError(task_id)
        npages = int(meta["pages"].get(str(partition), 0))
        if token >= npages:
            return None, token, True
        blob = _mmap_bytes(self._page_path(task_id, partition, token))
        _SPOOL_SERVED.inc()
        _SPOOL_MMAP.inc()
        return blob, token + 1, False

    def replay_columns(self, task_id: str, partition: int):
        """Decode one spooled partition into ({name: Column}, rows):
        the REPAIR/stats-replay convenience over the mmap'd pages —
        arrow page files parse into zero-copy views of the page cache,
        so a replay costs no deserialization beyond the final
        assembly."""
        from presto_tpu.parallel.wire import pages_to_columns
        meta = self.complete_meta(task_id)
        if meta is None:
            raise FileNotFoundError(task_id)
        blobs = []
        for token in range(int(meta["pages"].get(str(partition), 0))):
            blobs.append(_mmap_bytes(
                self._page_path(task_id, partition, token)))
            _SPOOL_SERVED.inc()
            _SPOOL_MMAP.inc()
        return pages_to_columns(blobs)

    def rows(self, task_id: str) -> list[int] | None:
        meta = self.complete_meta(task_id)
        return None if meta is None else list(meta["rows"])

    # -- lifecycle -------------------------------------------------------

    def delete_prefix(self, prefix: str) -> None:
        """Drop every spooled task whose id starts with ``prefix``
        (query cleanup: one query's tasks share the query-id prefix)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if name.startswith(prefix):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def delete_exact(self, task_id: str) -> None:
        """Drop exactly one spooled task (the speculation loser-cancel
        path: a losing primary's id is a PREFIX of its winning
        attempt-versioned duplicate, so prefix deletion would wipe the
        winner's pages too)."""
        shutil.rmtree(self._task_dir(task_id), ignore_errors=True)


class SpoolWriter:
    """Per-task page writer. Page indices are assigned here (the
    buffer's emit loop is single-threaded per task, but partitions
    interleave); writes are atomic tmp+rename so a concurrently
    crashing worker never leaves a torn page for a peer to serve."""

    def __init__(self, spool: TaskSpool, task_id: str):
        self.spool = spool
        self.task_id = task_id
        self.dir = os.path.join(spool.directory, task_id)
        self._lock = threading.Lock()
        self._counts: dict[int, int] = {}
        os.makedirs(self.dir, exist_ok=True)

    def write(self, partition: int, blob: bytes) -> None:
        """Persist one already-encoded page. Arrow stream pages are
        RE-FRAMED (not re-encoded: the batch buffers are referenced
        verbatim) into the IPC file form mmap serving wants; npz pages
        write as-is."""
        from presto_tpu.parallel import wire
        body = wire.arrow_file_bytes(blob)
        suffix = ".arrow"
        if body is None:
            body, suffix = blob, ".page"
        with self._lock:
            index = self._counts.get(partition, 0)
            self._counts[partition] = index + 1
        path = os.path.join(self.dir,
                            f"p{partition}.{index:06d}{suffix}")
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(body)
        os.replace(tmp, path)
        _SPOOLED_PAGES.inc()

    def complete(self, rows: list[int]) -> None:
        with self._lock:
            pages = {str(p): n for p, n in self._counts.items()}
        marker = os.path.join(self.dir, COMPLETE_MARKER)
        tmp = f"{marker}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"pages": pages, "rows": list(rows)}, f)
        os.replace(tmp, marker)

    def abort(self) -> None:
        """Drop a failed attempt's pages — they must never be served."""
        shutil.rmtree(self.dir, ignore_errors=True)
