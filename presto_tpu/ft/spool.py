"""Spooled exchange: task output pages persisted for fault tolerance.

The analog of Trino's fault-tolerant spooling exchange (the
``exchange.base-directories`` filesystem exchange behind
``retry-policy=TASK``): a worker running a buffered fragment task
writes every page it emits into a worker-local spool directory
(atomic tmp+rename, the progcache discipline) alongside the in-memory
OutputBuffer. The wire format stays the compact columnar one
(parallel/wire.py framed npz) — per PAPERS.md's Arrow Flight result,
columnar batch framing, not the transport, dominates exchange cost, so
the durable copy is byte-identical to the streamed one.

The spool serves through the EXISTING exchange HTTP surface: the
worker results endpoint falls back to the spool when the in-memory
buffer is gone (evicted, task deleted, or the page already freed by a
prior reader's acks), so a TASK retry can re-fetch a dead producer's
pages from any worker sharing the spool directory instead of aborting
the query ("buffers on the dead node are lost") or recomputing the
task.

Layout: ``{dir}/{task_id}/p{partition}.{index:06d}.page`` plus a
``COMPLETE.json`` marker carrying per-partition page counts and row
counts; a task without the marker is not served (a half-spooled failed
attempt must never feed a consumer — stale attempts are additionally
unreachable because retries get fresh attempt-versioned task ids).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading

from presto_tpu.obs.metrics import REGISTRY

_SPOOLED_PAGES = REGISTRY.counter(
    "presto_tpu_spooled_pages_total",
    "task output pages persisted to the exchange spool (ft/spool.py)")
_SPOOL_SERVED = REGISTRY.counter(
    "presto_tpu_spool_served_pages_total",
    "exchange pages served from the spool instead of a live buffer")

_TASK_ID_RE = re.compile(r"^[A-Za-z0-9._\-]+$")

COMPLETE_MARKER = "COMPLETE.json"


def _safe(task_id: str) -> str:
    if not _TASK_ID_RE.match(task_id):
        raise ValueError(f"unspoolable task id {task_id!r}")
    return task_id


class TaskSpool:
    """One worker's spool directory (may be shared between workers —
    any worker with the directory can serve any spooled task)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    # -- producer side ---------------------------------------------------

    def writer(self, task_id: str) -> "SpoolWriter":
        return SpoolWriter(self, _safe(task_id))

    # -- consumer side ---------------------------------------------------

    def _task_dir(self, task_id: str) -> str:
        return os.path.join(self.directory, _safe(task_id))

    def complete_meta(self, task_id: str) -> dict | None:
        """The completion marker, or None when the task is absent or
        was never completed (do not serve half-spooled output)."""
        try:
            with open(os.path.join(self._task_dir(task_id),
                                   COMPLETE_MARKER),
                      encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def page(self, task_id: str, partition: int,
             token: int) -> tuple[bytes | None, int, bool]:
        """Same (blob, next_token, complete) contract as
        OutputBuffer.page, read from disk. Raises FileNotFoundError
        when the task is not spooled (caller 404s)."""
        meta = self.complete_meta(task_id)
        if meta is None:
            raise FileNotFoundError(task_id)
        npages = int(meta["pages"].get(str(partition), 0))
        if token >= npages:
            return None, token, True
        path = os.path.join(self._task_dir(task_id),
                            f"p{partition}.{token:06d}.page")
        with open(path, "rb") as f:
            blob = f.read()
        _SPOOL_SERVED.inc()
        return blob, token + 1, False

    def rows(self, task_id: str) -> list[int] | None:
        meta = self.complete_meta(task_id)
        return None if meta is None else list(meta["rows"])

    # -- lifecycle -------------------------------------------------------

    def delete_prefix(self, prefix: str) -> None:
        """Drop every spooled task whose id starts with ``prefix``
        (query cleanup: one query's tasks share the query-id prefix)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if name.startswith(prefix):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)


class SpoolWriter:
    """Per-task page writer. Page indices are assigned here (the
    buffer's emit loop is single-threaded per task, but partitions
    interleave); writes are atomic tmp+rename so a concurrently
    crashing worker never leaves a torn page for a peer to serve."""

    def __init__(self, spool: TaskSpool, task_id: str):
        self.spool = spool
        self.task_id = task_id
        self.dir = os.path.join(spool.directory, task_id)
        self._lock = threading.Lock()
        self._counts: dict[int, int] = {}
        os.makedirs(self.dir, exist_ok=True)

    def write(self, partition: int, blob: bytes) -> None:
        with self._lock:
            index = self._counts.get(partition, 0)
            self._counts[partition] = index + 1
        path = os.path.join(self.dir,
                            f"p{partition}.{index:06d}.page")
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        _SPOOLED_PAGES.inc()

    def complete(self, rows: list[int]) -> None:
        with self._lock:
            pages = {str(p): n for p, n in self._counts.items()}
        marker = os.path.join(self.dir, COMPLETE_MARKER)
        tmp = f"{marker}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"pages": pages, "rows": list(rows)}, f)
        os.replace(tmp, marker)

    def abort(self) -> None:
        """Drop a failed attempt's pages — they must never be served."""
        shutil.rmtree(self.dir, ignore_errors=True)
