"""Hand-written Pallas/Mosaic kernels for the operator inner loops.

The operator layer (exec/operators.py) lowers everything to
whole-array XLA ops over pow2-padded buffers — hash joins pay
sort/gather cascades, aggregations pay full-width segment ops, and
compaction pays nonzero+gather passes. This package hand-writes the
3-4 inner loops that dominate ``system.operator_stats`` as Pallas
kernels with tiled HBM->VMEM pipelines:

==============  ===================================  ====================
kernel          Pallas implementation                XLA fallback
==============  ===================================  ====================
join_lookup     open-addressing build+probe          sorted-merge lookup
                (kernels/hashjoin.py)                (ops/hash.probe_runs)
multijoin       fused star-chain probe walk          sequential sorted
                (kernels/multijoin.py)               walk (apply_multi_join)
agg_sum/min/max per-tile VMEM accumulate             ops/segred.py
                (kernels/segagg.py)                  (MXU limb matmuls)
compact         one-pass dense survivor write        nonzero+gather
                (kernels/compact.py)                 (compact_dtable)
==============  ===================================  ====================

Every kernel has a NUMERICALLY IDENTICAL fallback — the pre-kernel
XLA path — registered beside it in :data:`KERNELS` (the
``kernel-parity`` lint rule keeps the table total). Selection is the
``kernel_backend`` session property:

- ``auto`` (default): Pallas on TPU, XLA elsewhere;
- ``pallas``: force the kernels; off-TPU they run under
  ``pl.pallas_call(interpret=True)`` so the CPU test tier executes
  the real kernel bodies;
- ``xla``: force the fallbacks.

The resolved backend is installed as an ambient context for the
duration of one plan trace (both interpreters wrap ``interp.run``),
rides the program-cache key (``kernel_backend`` is in
TRACE_RELEVANT_PROPERTIES and the resolved default rides the
platform fingerprint), and every dispatch is noted against the plan
node being traced so ``system.operator_stats`` can name the kernel
and split execute wall per operator.
"""

from __future__ import annotations

import contextlib
import contextvars

from presto_tpu.kernels import compact as _compact
from presto_tpu.kernels import hashjoin as _hashjoin
from presto_tpu.kernels import multijoin as _multijoin
from presto_tpu.kernels import segagg as _segagg

BACKENDS = ("auto", "pallas", "xla")

_ACTIVE: contextvars.ContextVar[str] = contextvars.ContextVar(
    "presto_tpu_kernel_backend", default="xla")
_USED: contextvars.ContextVar[list | None] = contextvars.ContextVar(
    "presto_tpu_kernel_used", default=None)


# kernel name -> backend -> implementation. Both entries of every row
# must exist and be reachable from dispatch() — asserted statically by
# the kernel-parity lint rule (lint/kernels.py).
KERNELS: dict[str, dict[str, object]] = {
    "join_lookup": {"pallas": _hashjoin.lookup_join_pallas,
                    "xla": _hashjoin.lookup_join_xla},
    "agg_sum": {"pallas": _segagg.segment_sum_pallas,
                "xla": _segagg.segment_sum_xla},
    "agg_max": {"pallas": _segagg.segment_max_pallas,
                "xla": _segagg.segment_max_xla},
    "agg_min": {"pallas": _segagg.segment_min_pallas,
                "xla": _segagg.segment_min_xla},
    "compact": {"pallas": _compact.filter_compact_pallas,
                "xla": _compact.filter_compact_xla},
    "multijoin": {"pallas": _multijoin.try_fused,
                  "xla": _multijoin.try_fused_xla},
}


def default_backend() -> str:
    """What ``auto`` resolves to on this process' platform."""
    import jax
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def interpret_mode() -> bool:
    """Pallas kernels run interpreted off-TPU (forced ``pallas`` on a
    CPU container is exactly how tier-1 exercises the kernel bodies)."""
    import jax
    return jax.default_backend() != "tpu"


def resolve(session) -> str:
    """Resolve the session's ``kernel_backend`` property to a concrete
    backend for this trace."""
    try:
        value = str(session.get("kernel_backend") or "auto").lower()
    except Exception:  # noqa: BLE001 - sessionless callers get auto
        value = "auto"
    if value == "auto":
        return default_backend()
    return value if value in ("pallas", "xla") else default_backend()


def active_backend() -> str:
    return _ACTIVE.get()


@contextlib.contextmanager
def use_backend(backend: str):
    """Install the resolved backend for one plan trace (ambient, like
    the trace context — operators and ops/segred read it instead of
    threading a session through every call)."""
    tok = _ACTIVE.set(backend)
    try:
        yield
    finally:
        _ACTIVE.reset(tok)


@contextlib.contextmanager
def collect():
    """Collect the kernel dispatches of one plan node's trace (the
    interpreter wraps each node handler; nested nodes re-enter, so
    notes land on the NEAREST enclosing node)."""
    used: list[str] = []
    tok = _USED.set(used)
    try:
        yield used
    finally:
        _USED.reset(tok)


def dispatch(name: str):
    """The active backend's implementation of kernel ``name``.
    Attribution is SELF-noted by the implementations (each function
    calls :func:`note` for the path that actually executes) — a
    pallas entry may still decline at its eligibility gate and run
    the XLA fallback, and a dispatch-time note would name a kernel
    that never ran."""
    backend = _ACTIVE.get()
    fns = KERNELS[name]
    return fns.get(backend) or fns["xla"]


def note(tag: str) -> None:
    """Record one kernel execution (``backend:kernel``) against the
    collecting plan node. No-op outside a collection scope."""
    used = _USED.get()
    if used is not None and tag not in used:
        used.append(tag)
