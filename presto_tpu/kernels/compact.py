"""Fused filter+compact: write surviving rows densely in one pass.

Between-operator compaction on the XLA path is a sort or an
``nonzero``+gather — full-width random-access passes over every
column just to drop dead rows (exec/operators.compact_dtable; a
60M-row ``jnp.nonzero`` alone measured 5.4 s on v5e). This kernel
streams the input tiles once: a running survivor count lives in a
VMEM accumulator, each live row appends at the next dense output
position, and every column of the row is copied while the tile is
resident — the predicate's mask goes in, compacted columns come out,
and downstream operators stop paying for padded width + ``__live__``
masks.

Semantics match the XLA fallback (:func:`filter_compact_xla`, the
pre-kernel ``compact_dtable`` gather) exactly where results can
observe them: live rows land at the same dense positions in the same
stable order; positions past the live count are DEAD either way (the
returned mask kills them) and only differ in which garbage they hold
(the gather replicates the last row, the kernel leaves zeros).

The sequential TPU grid is what makes the running count race-free —
same property the hash-build kernel leans on. Appends past
``capacity`` drop; the caller computes the overflow flag from the
live count (identical on both backends) and feeds the capacity retry
ladder.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from presto_tpu.kernels import u64

TILE = 256
# eligibility gate: every OUTPUT column block is [capacity, ...] with
# a constant index map, i.e. all compacted columns stay VMEM-resident
# together; past this byte bound the kernel declines and the XLA
# gather runs (a 60M-row compaction is exactly the case that must
# degrade, not fail Mosaic allocation)
PALLAS_MAX_OUT_BYTES = 8 << 20


def _interpret_mode() -> bool:
    from presto_tpu import kernels as K
    return K.interpret_mode()


def _out_bytes(arrays: dict, capacity: int) -> int:
    total = 0
    for a in arrays.values():
        row = int(a.dtype.itemsize)
        for dim in a.shape[1:]:
            row *= int(dim)
        total += capacity * row
    return total


def _split64(a):
    """Bitcast a 64-bit column into uint32 planes for the kernel body
    (Mosaic has no 64-bit ALU — see kernels/u64.py; row copies are
    dtype-blind, so a [n] int64/float64 column rides as [n, 2] uint32
    and a [n, m] one as [n, 2m]). Returns (kernel array, restore spec
    or None for pass-through dtypes)."""
    if a.dtype.itemsize != 8:
        return a, None
    v = a.view(jnp.uint32)
    if a.ndim == 1:
        v = v.reshape(a.shape[0], 2)
    return v, (a.dtype, a.ndim)


def _join64(a, spec, capacity: int):
    """Inverse of :func:`_split64` at the compacted width."""
    if spec is None:
        return a
    dtype, ndim = spec
    out = a.view(dtype)
    if ndim == 1:
        return out.reshape(capacity)
    return out


def filter_compact_pallas(live, arrays: dict, capacity: int) -> dict:
    """Compact ``arrays`` (1-D or 2-D, [n, ...]) to ``capacity`` rows,
    keeping rows where ``live`` in stable order. Returns the
    compacted arrays keyed as given (pad rows zeroed, dead).
    Output sets past the VMEM bound fall back to the XLA gather."""
    from jax.experimental import pallas as pl

    from presto_tpu import kernels as K
    cap = int(capacity)
    if _out_bytes(arrays, cap) > PALLAS_MAX_OUT_BYTES:
        return filter_compact_xla(live, arrays, capacity)
    K.note("pallas:compact")
    names = list(arrays)
    specs64 = {}
    arrays = dict(arrays)
    for k in names:
        arrays[k], specs64[k] = _split64(arrays[k])
    ins = [u64.pad_rows(live, TILE, False)] + [
        u64.pad_rows(arrays[k], TILE, 0) for k in names]

    def kernel(*refs):
        live_ref = refs[0]
        in_refs = refs[1:1 + len(names)]
        out_refs = refs[1 + len(names):-1]
        cnt_ref = refs[-1]
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            cnt_ref[...] = jnp.zeros((1,), jnp.int32)
            for o in out_refs:
                o[...] = jnp.zeros(o.shape, o.dtype)

        def row(i, _):
            pos = cnt_ref[0]

            @pl.when(live_ref[i] & (pos < cap))
            def _emit():
                for src, dst in zip(in_refs, out_refs):
                    if len(dst.shape) == 1:
                        dst[pos] = src[i]
                    else:
                        dst[pos, :] = src[i, :]
                cnt_ref[0] = pos + 1

            return 0

        jax.lax.fori_loop(0, TILE, row, 0)

    ntiles = ins[0].shape[0] // TILE
    in_specs = [pl.BlockSpec((TILE,), lambda t: (t,))]
    out_specs = []
    out_shape = []
    for k in names:
        a = arrays[k]
        if a.ndim == 1:
            in_specs.append(pl.BlockSpec((TILE,), lambda t: (t,)))
            out_specs.append(pl.BlockSpec((cap,), lambda t: (0,)))
            out_shape.append(jax.ShapeDtypeStruct((cap,), a.dtype))
        else:
            m = a.shape[1]
            in_specs.append(
                pl.BlockSpec((TILE, m), lambda t: (t, 0)))
            out_specs.append(
                pl.BlockSpec((cap, m), lambda t: (0, 0)))
            out_shape.append(
                jax.ShapeDtypeStruct((cap, m), a.dtype))
    out_specs.append(pl.BlockSpec((1,), lambda t: (0,)))
    out_shape.append(jax.ShapeDtypeStruct((1,), jnp.int32))
    outs = pl.pallas_call(
        kernel,
        grid=(ntiles,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret_mode(),
    )(*ins)
    return {k: _join64(o, specs64[k], cap)
            for k, o in zip(names, outs[:-1])}


def filter_compact_xla(live, arrays: dict, capacity: int) -> dict:
    """XLA fallback: the nonzero+gather compaction the kernel
    replaces (pad rows replicate the last row — dead either way)."""
    from presto_tpu import kernels as K
    K.note("xla:compact")
    n = live.shape[0]
    idx = jnp.nonzero(live, size=int(capacity), fill_value=n - 1)[0]
    return {k: v[idx] for k, v in arrays.items()}
