"""Hash-join build + probe as Pallas open-addressing kernels.

The XLA lookup path (ops/hash.py) pays a sort/gather cascade per join:
one combined (build+probe) sort, two scans, two un-sort permutations —
each a full random-access HBM pass. These kernels replace it with the
classic in-kernel hash table the reference engine uses
(operator/join/PagesHash.java): a BUILD pass inserts every live build
row into an open-addressing table (linear probing, table resident in
VMEM across the sequential TPU grid), and a PROBE pass looks each
probe row up with a data-dependent probe chain — O(rows) work instead
of O(rows log rows) sort passes, no permutation traffic.

Layout is specialized per query: the planner-chosen ``capacity``
(build NDV estimate, grown by the executor's overflow-retry ladder)
sizes the table, and hashes live as two uint32 planes (kernels/u64.py
— Mosaic has no 64-bit ALU) with key width folded in by the XLA-side
``combine_hashes`` before the kernel ever sees a row.

Semantics are byte-identical to the XLA fallback (:func:`lookup_join_xla`
— the exact code this replaces): ``found`` = live probe row whose
64-bit combined hash matches a live build row, representative on
duplicate build keys = the LARGEST build row index (the sorted path's
last-run-row choice; the build kernel accumulates ``max`` per slot),
value verification against residual 64-bit collisions stays with the
caller (exec/operators._verify_keys) on both backends.

Probe chains are bounded at ``max_probes``: a chain that long means
the capacity estimate was badly wrong, and the kernel reports it
LOUDLY through the ``ok`` flag so the executor's capacity retry
ladder rebuilds at a larger size (counted as
``presto_tpu_hash_probe_overflow_total``; the ladder's exhaustion
raises ops/hash.HashChainOverflow) — never a silent wrong answer.

On non-TPU backends the kernels run under ``interpret=True`` so the
CPU test tier executes the real kernel bodies (the ``kernel_backend``
session property's ``pallas`` setting forces exactly that).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from presto_tpu.kernels import u64
from presto_tpu.ops import hash as H

TILE = 256
MAX_PROBES = 256
# auto-eligibility bound: three table planes (hi, lo, row) must stay
# VMEM-resident across the grid; 1<<20 slots * 12 B = 12 MB ~ one core
PALLAS_MAX_TABLE = 1 << 20


def _interpret_mode() -> bool:
    from presto_tpu import kernels as K
    return K.interpret_mode()


def build_table(row_hash, live, capacity: int,
                max_probes: int = MAX_PROBES):
    """Insert live rows into an open-addressing table. Returns
    (table_hi, table_lo uint32 [capacity], table_row int32 [capacity]
    (-1 = empty; duplicates keep the max row index), ok bool [1]).

    The grid over row tiles is SEQUENTIAL on TPU, so read-modify-write
    claims need no atomics; the table planes are outputs with a
    constant index map, i.e. VMEM-resident accumulators written back
    once at the end.
    """
    from jax.experimental import pallas as pl
    cap = max(int(capacity), 8)
    if cap & (cap - 1):
        cap = H.next_pow2(cap)
    mask = cap - 1
    hi, lo = u64.split(row_hash)
    hi = u64.pad_rows(hi, TILE, 0)
    lo = u64.pad_rows(lo, TILE, 0)
    livep = u64.pad_rows(live, TILE, False)

    def kernel(hi_ref, lo_ref, live_ref, thi_ref, tlo_ref, trow_ref,
               ok_ref):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            thi_ref[...] = jnp.full((cap,), u64.EMPTY32, jnp.uint32)
            tlo_ref[...] = jnp.full((cap,), u64.EMPTY32, jnp.uint32)
            trow_ref[...] = jnp.full((cap,), -1, jnp.int32)
            ok_ref[...] = jnp.ones((1,), jnp.bool_)

        base = t * TILE

        def row(i, _):
            h_hi = hi_ref[i]
            h_lo = lo_ref[i]
            slot0 = (u64.slot32(h_hi, h_lo)
                     & jnp.uint32(mask)).astype(jnp.int32)

            def cond(c):
                _slot, j, done = c
                return jnp.logical_not(done) & (j < max_probes)

            def step(c):
                slot, j, _done = c
                t_hi = thi_ref[slot]
                t_lo = tlo_ref[slot]
                empty = (t_hi == u64.EMPTY32) & (t_lo == u64.EMPTY32)
                claim = empty | ((t_hi == h_hi) & (t_lo == h_lo))

                @pl.when(claim)
                def _claim():
                    thi_ref[slot] = h_hi
                    tlo_ref[slot] = h_lo
                    trow_ref[slot] = jnp.maximum(trow_ref[slot],
                                                 base + i)

                nxt = jnp.where(claim, slot,
                                (slot + 1) & jnp.int32(mask))
                return nxt, j + jnp.int32(1), claim

            _slot, _j, done = jax.lax.while_loop(
                cond, step,
                (slot0, jnp.int32(0), jnp.logical_not(live_ref[i])))

            @pl.when(jnp.logical_not(done))
            def _overflow():
                ok_ref[0] = False

            return 0

        jax.lax.fori_loop(0, TILE, row, 0)

    ntiles = hi.shape[0] // TILE
    thi, tlo, trow, ok = pl.pallas_call(
        kernel,
        grid=(ntiles,),
        in_specs=[pl.BlockSpec((TILE,), lambda t: (t,))] * 3,
        out_specs=[pl.BlockSpec((cap,), lambda t: (0,)),
                   pl.BlockSpec((cap,), lambda t: (0,)),
                   pl.BlockSpec((cap,), lambda t: (0,)),
                   pl.BlockSpec((1,), lambda t: (0,))],
        out_shape=[jax.ShapeDtypeStruct((cap,), jnp.uint32),
                   jax.ShapeDtypeStruct((cap,), jnp.uint32),
                   jax.ShapeDtypeStruct((cap,), jnp.int32),
                   jax.ShapeDtypeStruct((1,), jnp.bool_)],
        interpret=_interpret_mode(),
    )(hi, lo, livep)
    return thi, tlo, trow, ok


def probe_table(thi, tlo, trow, probe_hash, probe_live,
                max_probes: int = MAX_PROBES):
    """Look each live probe row up in a built table. Returns
    (build_row int32 [n] (-1 = no match), found bool [n], ok bool [1]
    — False when a chain hit ``max_probes`` undecided)."""
    from jax.experimental import pallas as pl
    cap = thi.shape[0]
    mask = cap - 1
    n = probe_hash.shape[0]
    hi, lo = u64.split(probe_hash)
    hi = u64.pad_rows(hi, TILE, 0)
    lo = u64.pad_rows(lo, TILE, 0)
    livep = u64.pad_rows(probe_live, TILE, False)

    # per-row probe outcome states (python ints: captured jnp scalars
    # are rejected by pallas as closure constants)
    walk, hit, miss = 0, 1, 2

    def kernel(hi_ref, lo_ref, live_ref, thi_ref, tlo_ref, trow_ref,
               brow_ref, found_ref, ok_ref):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            ok_ref[...] = jnp.ones((1,), jnp.bool_)

        def row(i, _):
            h_hi = hi_ref[i]
            h_lo = lo_ref[i]
            slot0 = (u64.slot32(h_hi, h_lo)
                     & jnp.uint32(mask)).astype(jnp.int32)

            def cond(c):
                _slot, j, state = c
                return (state == walk) & (j < max_probes)

            def step(c):
                slot, j, _state = c
                t_hi = thi_ref[slot]
                t_lo = tlo_ref[slot]
                empty = (t_hi == u64.EMPTY32) & (t_lo == u64.EMPTY32)
                match = (t_hi == h_hi) & (t_lo == h_lo)
                state = jnp.where(match, jnp.int32(hit),
                                  jnp.where(empty, jnp.int32(miss),
                                            jnp.int32(walk)))
                nxt = jnp.where(state == walk,
                                (slot + 1) & jnp.int32(mask), slot)
                return nxt, j + jnp.int32(1), state

            slot, _j, state = jax.lax.while_loop(
                cond, step,
                (slot0, jnp.int32(0),
                 jnp.where(live_ref[i], jnp.int32(walk),
                           jnp.int32(miss))))
            got = state == hit
            brow_ref[i] = jnp.where(got, trow_ref[slot], -1)
            found_ref[i] = got

            @pl.when(state == walk)
            def _undecided():
                ok_ref[0] = False

            return 0

        jax.lax.fori_loop(0, TILE, row, 0)

    ntiles = hi.shape[0] // TILE
    brow, found, ok = pl.pallas_call(
        kernel,
        grid=(ntiles,),
        in_specs=[pl.BlockSpec((TILE,), lambda t: (t,)),
                  pl.BlockSpec((TILE,), lambda t: (t,)),
                  pl.BlockSpec((TILE,), lambda t: (t,)),
                  pl.BlockSpec((cap,), lambda t: (0,)),
                  pl.BlockSpec((cap,), lambda t: (0,)),
                  pl.BlockSpec((cap,), lambda t: (0,))],
        out_specs=[pl.BlockSpec((TILE,), lambda t: (t,)),
                   pl.BlockSpec((TILE,), lambda t: (t,)),
                   pl.BlockSpec((1,), lambda t: (0,))],
        out_shape=[jax.ShapeDtypeStruct((hi.shape[0],), jnp.int32),
                   jax.ShapeDtypeStruct((hi.shape[0],), jnp.bool_),
                   jax.ShapeDtypeStruct((1,), jnp.bool_)],
        interpret=_interpret_mode(),
    )(hi, lo, livep, thi, tlo, trow)
    return brow[:n], found[:n], ok


def table_fits_vmem(capacity: int) -> bool:
    """Eligibility gate: the table planes must stay VMEM-resident
    across the sequential grid. Past the bound the kernel DECLINES
    and the numerically identical XLA lookup runs instead — a
    too-large build must degrade to the sort path, not fail Mosaic
    allocation (the capacity retry ladder would only grow it)."""
    return H.next_pow2(max(int(capacity), 8)) <= PALLAS_MAX_TABLE


def lookup_join_pallas(build_hash, build_live, probe_hash, probe_live,
                       capacity: int, max_probes: int = MAX_PROBES):
    """Pallas FK->PK join lookup: (build_row int32 [n_probe]
    (-1 = none), found bool [n_probe], ok bool scalar). Tables past
    the VMEM bound fall back to the XLA lookup (see
    table_fits_vmem)."""
    from presto_tpu import kernels as K
    if not table_fits_vmem(capacity):
        return lookup_join_xla(build_hash, build_live, probe_hash,
                               probe_live, capacity, max_probes)
    K.note("pallas:join_lookup")
    thi, tlo, trow, b_ok = build_table(build_hash, build_live,
                                       capacity, max_probes)
    brow, found, p_ok = probe_table(thi, tlo, trow, probe_hash,
                                    probe_live, max_probes)
    return brow, found, b_ok[0] & p_ok[0]


def lookup_join_xla(build_hash, build_live, probe_hash, probe_live,
                    capacity: int, max_probes: int = MAX_PROBES):
    """XLA fallback: the sorted-merge lookup this package's kernel
    replaces (sort_build_side + probe_runs + last-run representative —
    verbatim the pre-kernel apply_join/apply_semijoin body, so the
    two backends are byte-identical by construction)."""
    from presto_tpu import kernels as K
    K.note("xla:join_lookup")
    nb = build_hash.shape[0]
    _bsh, bsidx = H.sort_build_side(build_hash, build_live)
    lo, count, found = H.probe_runs(build_hash, build_live,
                                    probe_hash, probe_live)
    build_row = jnp.where(
        found, bsidx[jnp.clip(lo + count - 1, 0, nb - 1)], -1)
    return build_row, found, jnp.asarray(True)
