"""The MultiJoin sorted-probe walk as ONE Pallas kernel.

The fused star-schema chain (plan/nodes.MultiJoin, PR 10) already
collapsed Q5/Q9's join cascade into a sequential probe walk — but on
the XLA path each of its k steps still pays the full sort/merge
lookup over the spine's static width, so a 5-dimension chain makes
~10 full-width HBM sort passes. This kernel walks the WHOLE chain
while a spine tile is resident in VMEM: per row it combines the
step's key hashes (gathering hashes of earlier builds' matched rows
straight out of the walk state), probes that step's open-addressing
table (built once per build by kernels/hashjoin.build_table), and
carries the accumulated live mask — k probes, one pass over the
spine, zero sorts.

Semantics against the XLA walk (exec/operators.apply_multi_join):
identical per live row. Step hashes are the same per-column
hash + ``combine_hashes`` chain (re-derived in 32-bit limbs,
kernels/u64.py), dead rows gather build row 0 exactly like the XLA
path's ``clip(where(found, row, -1))``, and 64-bit-collision value
verification is applied to the kernel's gather outputs with the same
skip-strings rule as ``_verify_keys``. Rows differ only in the
garbage their DEAD lanes carry — invisible to results.

``try_fused`` returns None when the chain isn't kernel-shaped (a
2-D LONG-decimal key, a key symbol that isn't a plain spine/build
column): the caller then runs the XLA walk — dispatch-level parity
is total either way.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from presto_tpu.kernels import hashjoin as HJ
from presto_tpu.kernels import u64
from presto_tpu.ops import hash as H

TILE = 256
_SPINE = -1


def _interpret_mode() -> bool:
    from presto_tpu import kernels as K
    return K.interpret_mode()


def _col_hash(v):
    """Per-column 64-bit key of a 1-D Val (ops/hash contract)."""
    if v.is_string:
        return H.hash_string_column(v.data, v.dictionary, v.valid)
    return H.hash_int_column(v.data, v.valid)


def _combined_hash(cols, keys):
    return H.combine_hashes([_col_hash(cols[k]) for k in keys])


def try_fused(spine_cols: dict, spine_live, width: int,
              builds: list, criteria: list, growth: int = 1,
              max_probes: int = HJ.MAX_PROBES):
    """Run the fused probe walk. ``builds`` is a list of
    (cols dict, live mask, nrows) per build, ``criteria`` the
    per-step [(probe_sym, build_sym)] lists. Returns
    (gathers list of int32 [width], live bool [width], ok bool
    scalar) or None when the chain is not kernel-shaped."""
    from jax.experimental import pallas as pl

    # -- resolve every probe key to its source relation --------------
    sources: dict[str, int] = {s: _SPINE for s in spine_cols}
    steps: list[dict] = []
    for si, ((bcols, blive, bn), crit) in enumerate(
            zip(builds, criteria)):
        keys = []
        for lk, rk in crit:
            src = sources.get(lk)
            v = spine_cols[lk] if src == _SPINE else \
                builds[src][0].get(lk) if src is not None else None
            bv = bcols.get(rk)
            if (src is None or v is None or bv is None
                    or getattr(v.data, "ndim", 1) != 1
                    or getattr(bv.data, "ndim", 1) != 1):
                return None
            keys.append((lk, rk, src, v))
        steps.append({"keys": keys, "build": (bcols, blive, bn)})
        for sym in bcols:
            sources[sym] = si

    # -- build one open-addressing table per step --------------------
    # every step's table (and its build-side hash planes) must be
    # VMEM-resident during the walk: a chain with one oversized build
    # declines whole, and the caller runs the XLA walk instead
    k = len(steps)
    for st in steps:
        bn = st["build"][2]
        if not HJ.table_fits_vmem(
                H.next_pow2(2 * max(bn, 1)) * max(int(growth), 1)):
            return None
    for st in steps:
        bcols, blive, bn = st["build"]
        rkeys = [rk for _lk, rk, _s, _v in st["keys"]]
        bl = blive
        for rk in rkeys:
            bv = bcols[rk]
            if bv.valid is not None:
                bl = bl & bv.valid
        cap = H.next_pow2(2 * max(bn, 1)) * max(int(growth), 1)
        rh = _combined_hash(bcols, rkeys)
        thi, tlo, trow, b_ok = HJ.build_table(rh, bl, cap, max_probes)
        st["table"] = (thi, tlo, trow)
        st["cap"] = thi.shape[0]
        st["build_ok"] = b_ok

    # -- flatten kernel inputs ---------------------------------------
    flat = [u64.pad_rows(spine_live, TILE, False)]
    specs = [pl.BlockSpec((TILE,), lambda t: (t,))]

    def add(arr, spine_side: bool) -> int:
        if spine_side:
            arr = u64.pad_rows(arr, TILE, 0)
            specs.append(pl.BlockSpec((TILE,), lambda t: (t,)))
        else:
            size = arr.shape[0]
            specs.append(pl.BlockSpec((size,), lambda t: (0,)))
        flat.append(arr)
        return len(flat) - 1

    kspec = []  # per step: table positions + key/valid positions
    for st in steps:
        thi, tlo, trow = st["table"]
        tpos = (add(thi, False), add(tlo, False), add(trow, False))
        kpos = []
        vpos = []
        for _lk, _rk, src, v in st["keys"]:
            hhi, hlo = u64.split(_col_hash(v))
            kpos.append((src, add(hhi, src == _SPINE),
                         add(hlo, src == _SPINE)))
            if v.valid is not None:
                vpos.append((src, add(v.valid, src == _SPINE)))
        kspec.append({"tpos": tpos, "mask": st["cap"] - 1,
                      "kpos": kpos, "vpos": vpos})

    # probe outcome states (python ints: captured jnp scalars are
    # rejected by pallas as closure constants)
    walk, hit, miss = 0, 1, 2

    def kernel(*refs):
        live_ref = refs[0]
        g_refs = refs[len(flat):len(flat) + k]
        alive_ref = refs[len(flat) + k]
        ok_ref = refs[len(flat) + k + 1]
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            ok_ref[...] = jnp.ones((1,), jnp.bool_)

        def row(i, _):
            alive = live_ref[i]
            g = [jnp.int32(0)] * k
            for si, st in enumerate(kspec):
                kv = alive
                for src, vp in st["vpos"]:
                    vref = refs[vp]
                    kv = kv & (vref[i] if src == _SPINE
                               else vref[g[src]])
                hh = hl = None
                for src, hp, lp in st["kpos"]:
                    idx = i if src == _SPINE else g[src]
                    kh = refs[hp][idx]
                    kl = refs[lp][idx]
                    if hh is None:
                        hh, hl = kh, kl
                    else:
                        hh, hl = u64.combine_step(hh, hl, kh, kl)
                hh, hl = u64.remap_empty(hh, hl)
                thi_ref = refs[st["tpos"][0]]
                tlo_ref = refs[st["tpos"][1]]
                trow_ref = refs[st["tpos"][2]]
                mask = st["mask"]
                slot0 = (u64.slot32(hh, hl)
                         & jnp.uint32(mask)).astype(jnp.int32)

                def cond(c):
                    _slot, j, state = c
                    return (state == walk) & (j < max_probes)

                def step(c, thi_ref=thi_ref, tlo_ref=tlo_ref,
                         hh=hh, hl=hl, mask=mask):
                    slot, j, _state = c
                    t_hi = thi_ref[slot]
                    t_lo = tlo_ref[slot]
                    empty = ((t_hi == u64.EMPTY32)
                             & (t_lo == u64.EMPTY32))
                    match = (t_hi == hh) & (t_lo == hl)
                    state = jnp.where(match, jnp.int32(hit),
                                      jnp.where(empty, jnp.int32(miss),
                                                jnp.int32(walk)))
                    nxt = jnp.where(state == walk,
                                    (slot + 1) & jnp.int32(mask),
                                    slot)
                    return nxt, j + jnp.int32(1), state

                # dead rows (and zero-hash pad rows) skip the chain
                # entirely: their found is False regardless, and a
                # long cluster walked by a row whose result cannot
                # matter must not flip the overflow flag
                slot, _j, state = jax.lax.while_loop(
                    cond, step,
                    (slot0, jnp.int32(0),
                     jnp.where(kv, jnp.int32(walk), jnp.int32(miss))))
                found = kv & (state == hit)
                rowi = jnp.where(found, trow_ref[slot], 0)
                g_refs[si][i] = rowi
                g[si] = rowi
                alive = found

                @pl.when(state == walk)
                def _undecided():
                    ok_ref[0] = False

            alive_ref[i] = alive
            return 0

        jax.lax.fori_loop(0, TILE, row, 0)

    padded = flat[0].shape[0]
    ntiles = padded // TILE
    out_specs = ([pl.BlockSpec((TILE,), lambda t: (t,))] * (k + 1)
                 + [pl.BlockSpec((1,), lambda t: (0,))])
    out_shape = ([jax.ShapeDtypeStruct((padded,), jnp.int32)] * k
                 + [jax.ShapeDtypeStruct((padded,), jnp.bool_),
                    jax.ShapeDtypeStruct((1,), jnp.bool_)])
    outs = pl.pallas_call(
        kernel,
        grid=(ntiles,),
        in_specs=specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_interpret_mode(),
    )(*flat)
    gathers = [o[:width] for o in outs[:k]]
    alive = outs[k][:width]
    ok = outs[k + 1][0]
    for st in steps:
        ok = ok & st["build_ok"][0]

    # -- 64-bit-collision value verification (XLA, gathers only) -----
    live = alive
    for si, st in enumerate(steps):
        bcols = st["build"][0]
        gather = gathers[si]
        for lk, rk, src, v in st["keys"]:
            bv = bcols[rk]
            if v.is_string or bv.is_string:
                continue  # content-hashed dictionaries, as _verify_keys
            ld = v.data if src == _SPINE else v.data[gathers[src]]
            live = live & (ld == bv.data[gather])
    from presto_tpu import kernels as K
    K.note("pallas:multijoin")
    return gathers, live, ok


def try_fused_xla(*_args, **_kw):
    """The dispatch-table fallback of the fused walk: returns None —
    "not fused" — so the caller runs its inline XLA walk
    (exec/operators.apply_multi_join's sequential sorted-probe body,
    which is the numerical reference the kernel is held to). The walk
    is an operator body, not a separable array->array function, so
    the fallback lives as this sentinel rather than a copy."""
    return None
