"""Segmented aggregation as Pallas tile-accumulate kernels.

The XLA aggregation path pays for 64-bit scatters twice over: plain
``jax.ops.segment_sum`` costs ~500 ms per 6M-row call on v5e (emulated
64-bit scatter-add), and the MXU workaround (ops/segred.py) pays 8
one-hot matmuls per 256-row block. These kernels do what the hardware
actually wants: accumulate per-segment partials in VMEM scratch while
each HBM tile is resident, one pass, no scatter unit and no one-hot
FLOPs. Totals live as two uint32 planes with explicit carry
(kernels/u64.add64) — exact mod 2^64, i.e. bit-identical to the
int64 scatter-add contract including wraparound.

Eligibility is integer-only on purpose: integer sums are
order-independent mod 2^64 and min/max are order-independent always,
so a sequential tile walk cannot diverge from the scatter's
unspecified accumulation order. Float SUMs would reassociate — those
stay on the XLA path on every backend (the same line ops/segred.py
already draws for its MXU path).

The XLA fallbacks (:func:`segment_sum_xla` & co) ARE ops/segred.py —
registered here so the ``kernel_backend`` dispatch table (and the
``kernel-parity`` lint rule) see one catalog of kernel/fallback
pairs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from presto_tpu.kernels import u64

TILE = 256
# accumulator planes ([k] uint32 x 2) must stay VMEM-resident
PALLAS_MAX_SEGMENTS = 1 << 16

# lint/kernels.py kernel-parity rule: *_pallas functions outside the
# dispatch table must justify themselves
KERNEL_DISPATCH_EXEMPT = {
    "_cmp_pallas": "shared body of segment_max_pallas/"
                   "segment_min_pallas, both registered",
}


def _eligible(data, num_segments: int) -> bool:
    if getattr(data, "ndim", 1) != 1 or data.shape[0] == 0:
        return False
    if num_segments > PALLAS_MAX_SEGMENTS:
        return False
    return (jnp.issubdtype(data.dtype, jnp.integer)
            or data.dtype == jnp.bool_)


def sum_eligible(data, num_segments: int) -> bool:
    return _eligible(data, num_segments)


def cmp_eligible(data, num_segments: int) -> bool:
    # bool has no min/max fold in the engine; integers only
    return _eligible(data, num_segments) and data.dtype != jnp.bool_


def _interpret_mode() -> bool:
    from presto_tpu import kernels as K
    return K.interpret_mode()


def segment_sum_pallas(data, segment_ids, num_segments: int, **_kw):
    """Per-segment wrapping 64-bit sum of an integer column (bool
    counts as int64, matching jax.ops/segred). Out-of-range segment
    ids drop, matching the scatter contract."""
    from jax.experimental import pallas as pl

    from presto_tpu import kernels as K
    K.note("pallas:agg_sum")
    out_dtype = jnp.int64 if data.dtype == jnp.bool_ else data.dtype
    k = int(num_segments)
    u = data.astype(jnp.uint64)  # sign-extends: two's complement sum
    v_hi, v_lo = u64.split(u)
    v_hi = u64.pad_rows(v_hi, TILE, 0)
    v_lo = u64.pad_rows(v_lo, TILE, 0)
    sid = u64.pad_rows(segment_ids.astype(jnp.int32), TILE, -1)

    def kernel(vh_ref, vl_ref, sid_ref, ah_ref, al_ref):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            ah_ref[...] = jnp.zeros((k,), jnp.uint32)
            al_ref[...] = jnp.zeros((k,), jnp.uint32)

        def row(i, _):
            s = sid_ref[i]

            @pl.when((s >= 0) & (s < k))
            def _acc():
                hi, lo = u64.add64(ah_ref[s], al_ref[s],
                                   vh_ref[i], vl_ref[i])
                ah_ref[s] = hi
                al_ref[s] = lo

            return 0

        jax.lax.fori_loop(0, TILE, row, 0)

    ntiles = v_hi.shape[0] // TILE
    ah, al = pl.pallas_call(
        kernel,
        grid=(ntiles,),
        in_specs=[pl.BlockSpec((TILE,), lambda t: (t,))] * 3,
        out_specs=[pl.BlockSpec((k,), lambda t: (0,))] * 2,
        out_shape=[jax.ShapeDtypeStruct((k,), jnp.uint32)] * 2,
        interpret=_interpret_mode(),
    )(v_hi, v_lo, sid)
    return u64.join(ah, al).astype(out_dtype)


def _cmp_pallas(data, segment_ids, num_segments: int, is_max: bool):
    """Per-segment integer min/max via lexicographic limb compare
    (high limb sign-flipped so unsigned order == signed order). Empty
    segments hold the dtype identity, matching jax.ops.segment_max's
    dtype-min fill (and segment_min's dtype-max)."""
    from jax.experimental import pallas as pl
    k = int(num_segments)
    info = jnp.iinfo(data.dtype)
    ident = int(info.min if is_max else info.max)
    signed = jnp.issubdtype(data.dtype, jnp.signedinteger)
    if signed:
        u = data.astype(jnp.int64).astype(jnp.uint64)
        id_bits = ident & 0xFFFFFFFFFFFFFFFF  # two's complement
    else:
        u = data.astype(jnp.uint64)
        id_bits = ident
    v_hi, v_lo = u64.split(u)
    # bias flips the sign bit so unsigned limb order == value order
    # (python ints: captured jnp scalars are rejected by pallas)
    sign = 0x80000000 if signed else 0
    v_hi = u64.pad_rows(v_hi, TILE, 0)
    v_lo = u64.pad_rows(v_lo, TILE, 0)
    sid = u64.pad_rows(segment_ids.astype(jnp.int32), TILE, -1)
    id_hi = id_bits >> 32
    id_lo = id_bits & 0xFFFFFFFF

    def kernel(vh_ref, vl_ref, sid_ref, ah_ref, al_ref):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            ah_ref[...] = jnp.full((k,), id_hi, jnp.uint32)
            al_ref[...] = jnp.full((k,), id_lo, jnp.uint32)

        def row(i, _):
            s = sid_ref[i]

            @pl.when((s >= 0) & (s < k))
            def _acc():
                vh = vh_ref[i]
                vl = vl_ref[i]
                ch = ah_ref[s]
                cl = al_ref[s]
                vb, cb = vh ^ sign, ch ^ sign  # biased signed compare
                if is_max:
                    better = (vb > cb) | ((vb == cb) & (vl > cl))
                else:
                    better = (vb < cb) | ((vb == cb) & (vl < cl))

                @pl.when(better)
                def _take():
                    ah_ref[s] = vh
                    al_ref[s] = vl

            return 0

        jax.lax.fori_loop(0, TILE, row, 0)

    ntiles = v_hi.shape[0] // TILE
    ah, al = pl.pallas_call(
        kernel,
        grid=(ntiles,),
        in_specs=[pl.BlockSpec((TILE,), lambda t: (t,))] * 3,
        out_specs=[pl.BlockSpec((k,), lambda t: (0,))] * 2,
        out_shape=[jax.ShapeDtypeStruct((k,), jnp.uint32)] * 2,
        interpret=_interpret_mode(),
    )(v_hi, v_lo, sid)
    packed = u64.join(ah, al)
    if signed:
        packed = packed.astype(jnp.int64)
    return packed.astype(data.dtype)


def segment_max_pallas(data, segment_ids, num_segments: int, **_kw):
    from presto_tpu import kernels as K
    K.note("pallas:agg_max")
    return _cmp_pallas(data, segment_ids, num_segments, True)


def segment_min_pallas(data, segment_ids, num_segments: int, **_kw):
    from presto_tpu import kernels as K
    K.note("pallas:agg_min")
    return _cmp_pallas(data, segment_ids, num_segments, False)


# -- XLA fallbacks: the existing segred paths, re-exported so the
#    kernel registry maps every Pallas kernel to its fallback ---------


def segment_sum_xla(data, segment_ids, num_segments: int, **kwargs):
    from presto_tpu.ops import segred
    return segred.xla_segment_sum(data, segment_ids, num_segments,
                                  **kwargs)


def segment_max_xla(data, segment_ids, num_segments: int, **kwargs):
    from presto_tpu.ops import segred
    return segred.xla_segment_max(data, segment_ids, num_segments,
                                  **kwargs)


def segment_min_xla(data, segment_ids, num_segments: int, **kwargs):
    from presto_tpu.ops import segred
    return segred.xla_segment_min(data, segment_ids, num_segments,
                                  **kwargs)
