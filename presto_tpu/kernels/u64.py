"""64-bit hash arithmetic as 32-bit limb pairs for Pallas kernels.

Mosaic has no native 64-bit ALU (TPU v5e emulates int64, and Pallas
rejects it outright inside kernels), so every kernel in this package
carries row hashes as two uint32 planes ``(hi, lo)``. This module is
the limb calculus: splitting/packing against the uint64 arrays the
XLA-side hash machinery (ops/hash.py) produces, the golden-ratio
multiply of ``combine_hashes`` re-derived over 16-bit limb products,
and a 32-bit avalanche mix for slot addressing.

The multiply must be BIT-IDENTICAL to ``ops/hash.combine_hashes``:
in-kernel probe hashes are compared against table entries built from
the XLA-computed combined hash, so one differing bit is a missed join
row. tests/test_kernels.py cross-checks every helper against the
uint64 reference on random inputs.

Slot addressing gets a murmur3 finalizer (``mix32``) the XLA path
never needed: ``hash_int_column`` is deliberately an identity key
(see ops/hash.py — sort-based kernels only need equality), but open
addressing with identity keys degenerates — dense key ranges form
one giant cluster and every miss walks it end to end. Mixing only
decides WHERE a hash sits, never WHETHER two hashes are equal, so
layout stays an internal detail and results stay byte-identical.
"""

from __future__ import annotations

import jax.numpy as jnp

# golden-ratio constant of ops/hash.combine_hashes, split into limbs.
# Plain Python ints throughout: module-level jnp scalars would be
# CLOSURE-CAPTURED device arrays inside pallas kernel functions
# (pallas rejects captured constants); weak-typed ints inline as
# literals instead.
PHI64 = 0x9E3779B97F4A7C15
_MASK16 = 0xFFFF

# the EMPTY slot sentinel (ops/hash._EMPTY = max uint64) per plane
EMPTY32 = 0xFFFFFFFF


def split(h):
    """uint64 [n] -> (hi uint32 [n], lo uint32 [n])."""
    return ((h >> jnp.uint64(32)).astype(jnp.uint32),
            h.astype(jnp.uint32))


def join(hi, lo):
    """Inverse of :func:`split` (host/XLA side only)."""
    return ((hi.astype(jnp.uint64) << jnp.uint64(32))
            | lo.astype(jnp.uint64))


def _mul32_wide(a, b):
    """Full 64-bit product of two uint32 values as (hi, lo) uint32,
    via 16-bit limb products (each partial fits uint32 exactly)."""
    a0, a1 = a & _MASK16, a >> jnp.uint32(16)
    b0, b1 = b & _MASK16, b >> jnp.uint32(16)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> jnp.uint32(16)) + (p01 & _MASK16) + (p10 & _MASK16)
    lo = (p00 & _MASK16) | ((mid & _MASK16) << jnp.uint32(16))
    hi = (p11 + (p01 >> jnp.uint32(16)) + (p10 >> jnp.uint32(16))
          + (mid >> jnp.uint32(16)))
    return hi, lo


def mul_const(hi, lo, c: int):
    """(hi, lo) * c mod 2^64 for a Python-int constant ``c``."""
    c_lo = jnp.uint32(c & 0xFFFFFFFF)
    c_hi = jnp.uint32((c >> 32) & 0xFFFFFFFF)
    phi, plo = _mul32_wide(lo, c_lo)
    # high word only needs the products' low 32 bits (wrapping * is it)
    out_hi = phi + lo * c_hi + hi * c_lo
    return out_hi, plo


def combine_step(hi, lo, kh, kl):
    """One accumulation step of ops/hash.combine_hashes:
    ``acc = acc * PHI64 ^ key``."""
    hi, lo = mul_const(hi, lo, PHI64)
    return hi ^ kh, lo ^ kl


def remap_empty(hi, lo):
    """combine_hashes' tail: keep the EMPTY sentinel unreachable
    (``where(out == EMPTY, out - 1, out)`` — EMPTY has lo = all-ones,
    so the decrement never borrows into the high word)."""
    is_empty = (hi == EMPTY32) & (lo == EMPTY32)
    return hi, jnp.where(is_empty, lo - jnp.uint32(1), lo)


def mix32(x):
    """murmur3 fmix32: avalanche a uint32 for open-address slot
    choice (identity row keys would otherwise cluster; see module
    docstring). Layout-only — never part of hash equality."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> jnp.uint32(16))


def slot32(hi, lo):
    """Open-address home slot of a 64-bit hash. Both words avalanche
    INDEPENDENTLY before folding: a plain ``mix32(hi ^ lo)`` would
    alias every key whose words are equal — e.g. the identity int
    keys (m << 32) | m — into ONE cluster at every table size, so no
    capacity-retry rung could ever break the chain. Layout-only."""
    return mix32(lo ^ mix32(hi))


def pad_rows(arr, tile: int, fill):
    """Pad an [n, ...] array's row axis up to a multiple of ``tile``
    (the shared tile-padding of every kernel's blocked inputs)."""
    n = arr.shape[0]
    pad = (-n) % tile
    if pad == 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return jnp.pad(arr, widths, constant_values=fill)


def add64(acc_hi, acc_lo, v_hi, v_lo):
    """(acc + v) mod 2^64 in limb planes (carry via unsigned wrap
    detection) — the exact two's-complement accumulate of an int64
    scatter-add, including wraparound."""
    lo = acc_lo + v_lo
    carry = (lo < acc_lo).astype(jnp.uint32)
    return acc_hi + v_hi + carry, lo
