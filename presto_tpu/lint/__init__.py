"""Engine-specific static analysis (stdlib ``ast`` only).

Thirteen rule families guard the places where this engine's bugs ship
silently (the reference defends the analogous seams with its
PlanSanityChecker pipeline, sql/planner/sanity/PlanSanityChecker.java):

- **tracer hygiene** (``lint/tracer.py``): inside ``@jax.jit``-reachable
  functions, Python-level inspection of traced values either crashes at
  trace time on a rarely-hit path or silently forces a retrace per call.
- **lock discipline** (``lint/locks.py``): an attribute written under
  ``with self._lock`` in one method and read bare in another is a latent
  race that only fires under load. The same lockset analysis powers
  **blocking-under-lock**: no network round-trip, plan compile, or
  device sync while holding a lock in ``server/``/``parallel/``/
  ``ft/`` — a multi-second XLA compile inside a coordinator lock
  serializes the whole serve path.
- **dispatch exhaustiveness** (``lint/dispatch.py``): a new ``PlanNode``
  subclass that one of the visitors (serde, printer, sanity,
  fingerprint, executor) forgets fails only on the query shape that
  reaches it.
- **metric naming** (``lint/metrics.py``): registrations against the
  obs/metrics registry checked statically with the registry's own
  validator — a bad name on a rarely-hit path would otherwise only
  raise in production.
- **timeout discipline** (``lint/timeouts.py``): every
  ``urlopen``/``_urlopen`` call site must pass an explicit
  ``timeout=`` — an internal HTTP call without a deadline turns one
  dead peer into a hung thread the failure detector cannot see.
- **span discipline** (``lint/spans.py``): every ``obs.trace`` span
  must be opened via ``with`` (or ``ExitStack.enter_context``) — a
  hand-entered span leaks both an unfinished span and the ambient
  trace context on any exception before close.
- **pool discipline** (``lint/pools.py``): every ``MemoryPool.reserve``
  call site must pair with a ``free`` on all exit paths (a ``finally``
  in the same function) — a leaked reservation permanently shrinks the
  pool under exactly the load it governs.
- **field-level locksets** (``lint/races.py``): the Eraser-style
  refinement of lock discipline — every field's read/write sites must
  agree on WHICH lock guards it; written-under-A-read-under-B races
  are invisible to the boolean rule.
- **ambient-context handoff** (``lint/handoff.py``): thread-spawn
  sites in modules using ambient contextvars/thread-locals (trace
  context, cancel token, stats recorder, session override) must hand
  the state over explicitly or document why the thread is
  context-free.
- **kernel parity** (``lint/kernels.py``): every Pallas kernel is
  registered in the ``kernel_backend`` dispatch table beside a real
  XLA fallback — an unregistered kernel is unreachable from the
  session property and invisible to parity testing.
- **trace-key provenance** (``lint/tracekey.py``): every ambient
  input trace-reachable code reads (session property, env var,
  mutable module global — tracked across aliases, parameters, and
  helper calls) must participate in the program-cache key or carry a
  justified ``TRACE_KEY_EXEMPT`` entry, and every
  ``TRACE_RELEVANT_PROPERTIES`` entry must be genuinely read — the
  compile-cache soundness contract, machine-checked both ways.
- **device-sync boundary** (``lint/devicesync.py``): every
  host-blocking device read reachable from the execute-path roots
  (``.item()``, ``np.asarray`` of a jit output, ``jax.device_get``,
  ``block_until_ready``, ``int()`` of a device scalar) must go through
  the counted ``exec/hostsync`` boundary or carry a justified
  ``DEVICE_SYNC_EXEMPT`` entry — one stray sync in a stage walk
  serializes every dispatch behind a ~90ms round-trip.
- **retrace hazards** (``lint/retrace.py``): data-dependent integers
  (``bincount().max()``, ``fetch_int`` readbacks) must pass through
  ``next_pow2``/``bucket_*`` before reaching a shape constructor, a
  Python branch, or a cache-key component — an unbucketed value
  compiles one program per dataset and the cache never hits.

Run ``python -m presto_tpu.lint presto_tpu/`` (exits nonzero on
findings; ``--changed`` scopes reporting to files changed since HEAD
for pre-commit runs; ``--sarif`` emits a SARIF 2.1.0 log for CI
diff annotation, in-source waivers exported as suppressed results);
suppress a single line with ``# lint: disable=rule-name`` plus a
comment saying why. Stale suppressions — disables that no longer
suppress anything — are reported as ``stale-suppression`` findings by
the runner itself.
"""

from presto_tpu.lint.core import (Finding, Project, available_rules,
                                  run_lint)

# rule modules self-register on import
from presto_tpu.lint import tracer as _tracer  # noqa: E402,F401
from presto_tpu.lint import locks as _locks  # noqa: E402,F401
from presto_tpu.lint import dispatch as _dispatch  # noqa: E402,F401
from presto_tpu.lint import metrics as _metrics  # noqa: E402,F401
from presto_tpu.lint import timeouts as _timeouts  # noqa: E402,F401
from presto_tpu.lint import pools as _pools  # noqa: E402,F401
from presto_tpu.lint import spans as _spans  # noqa: E402,F401
from presto_tpu.lint import races as _races  # noqa: E402,F401
from presto_tpu.lint import handoff as _handoff  # noqa: E402,F401
from presto_tpu.lint import kernels as _kernels  # noqa: E402,F401
from presto_tpu.lint import tracekey as _tracekey  # noqa: E402,F401
from presto_tpu.lint import devicesync as _devicesync  # noqa: E402,F401
from presto_tpu.lint import retrace as _retrace  # noqa: E402,F401

__all__ = ["Finding", "Project", "available_rules", "run_lint"]
