"""CLI: ``python -m presto_tpu.lint [paths...] [--json] [--rules ...]``.

Exits 0 when clean, 1 when there are unsuppressed findings, 2 on usage
errors — so the lint can gate CI the way the tier-1 tests do.
"""

from __future__ import annotations

import argparse
import json
import sys

from presto_tpu.lint import available_rules, run_lint


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m presto_tpu.lint",
        description="Engine-specific static analysis: tracer hygiene, "
                    "lock discipline, plan-dispatch exhaustiveness.")
    parser.add_argument("paths", nargs="*", default=["presto_tpu"],
                        help="files or directories to analyze "
                             "(default: presto_tpu)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset "
                             f"(available: {', '.join(available_rules())})")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable JSON findings on stdout")
    args = parser.parse_args(argv)

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        findings = run_lint(args.paths, rules)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        if findings:
            print(f"\n{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
