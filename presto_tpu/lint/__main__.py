"""CLI: ``python -m presto_tpu.lint [paths...] [--json | --sarif]
[--rules ...] [--changed]``.

Exits 0 when clean, 1 when there are unsuppressed findings, 2 on usage
errors — so the lint can gate CI the way the tier-1 tests do.
``--changed --sarif`` is the pre-commit/CI recipe: whole-tree
analysis, reporting scoped to files touched since HEAD, output a
SARIF 2.1.0 log standard diff-annotation tooling ingests verbatim.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

from presto_tpu.lint import available_rules, run_lint


def _changed_files(paths: list[str]) -> set[Path]:
    """Resolved paths of ``.py`` files touched since HEAD (worktree
    diff, staged diff, and untracked files) in the git repo containing
    the first analyzed path. Raises ValueError outside a repo."""
    anchor = Path(paths[0]).resolve()
    anchor_dir = anchor if anchor.is_dir() else anchor.parent
    try:
        root = subprocess.run(
            ["git", "-C", str(anchor_dir), "rev-parse",
             "--show-toplevel"],
            capture_output=True, text=True, check=True,
            timeout=30).stdout.strip()
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", "HEAD", "--"],
            capture_output=True, text=True, check=True, timeout=30)
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, check=True, timeout=30)
    except (subprocess.CalledProcessError, OSError,
            subprocess.TimeoutExpired) as e:
        detail = getattr(e, "stderr", "") or str(e)
        raise ValueError(
            f"--changed needs a git checkout: {detail.strip()}") from e
    out: set[Path] = set()
    for line in (diff.stdout + untracked.stdout).splitlines():
        if line.strip().endswith(".py"):
            out.add((Path(root) / line.strip()).resolve())
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m presto_tpu.lint",
        description="Engine-specific static analysis: tracer hygiene, "
                    "lock discipline, lockset/handoff concurrency "
                    "rules, plan-dispatch exhaustiveness.")
    parser.add_argument("paths", nargs="*", default=["presto_tpu"],
                        help="files or directories to analyze "
                             "(default: presto_tpu)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset "
                             f"(available: {', '.join(available_rules())})")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable JSON findings on stdout")
    parser.add_argument("--sarif", action="store_true",
                        dest="as_sarif",
                        help="SARIF 2.1.0 log on stdout (rule ids, "
                             "file/line regions, messages, in-source "
                             "suppressions as suppressed results) — "
                             "the CI/code-scanning format; combine "
                             "with --changed for the pre-commit "
                             "recipe")
    parser.add_argument("--changed", action="store_true",
                        help="report only findings in files changed "
                             "since HEAD (worktree + staged + "
                             "untracked) — the fast pre-commit mode; "
                             "analysis still covers the whole tree so "
                             "cross-file rules stay sound")
    args = parser.parse_args(argv)

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    if args.as_json and args.as_sarif:
        print("--json and --sarif are mutually exclusive",
              file=sys.stderr)
        return 2
    only_files = None
    suppressed: list | None = [] if args.as_sarif else None
    try:
        if args.changed:
            only_files = _changed_files(args.paths)
            if not only_files:
                # validate paths and rule names even on the fast
                # exit: a pre-commit hook with a typo'd --rules or
                # path must fail loudly on EVERY run, not only once
                # the worktree is dirty
                missing = [p for p in args.paths
                           if not Path(p).exists()]
                if missing:
                    raise ValueError(f"paths do not exist: {missing}")
                if rules:
                    unknown = [r for r in rules
                               if r not in available_rules()]
                    if unknown:
                        raise ValueError(
                            f"unknown lint rules: {unknown} "
                            f"(available: {available_rules()})")
                if args.as_json:
                    print("[]")
                elif args.as_sarif:
                    from presto_tpu.lint.sarif import to_sarif
                    print(json.dumps(to_sarif(
                        [], [], rules or available_rules()), indent=2))
                else:
                    print("no changed .py files; nothing to lint",
                          file=sys.stderr)
                return 0
        findings = run_lint(args.paths, rules, only_files=only_files,
                            collect_suppressed=suppressed)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    elif args.as_sarif:
        from presto_tpu.lint.sarif import to_sarif
        print(json.dumps(to_sarif(findings, suppressed,
                                  rules or available_rules()),
                         indent=2))
    else:
        for f in findings:
            print(f.format())
        if findings:
            print(f"\n{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
