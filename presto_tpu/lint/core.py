"""Analyzer core: source loading, rule registry, suppressions.

The unit of analysis is a :class:`Project` — every ``.py`` file under
the paths given to the CLI, parsed once. Rules are functions from a
Project to findings, registered by name; per-line suppressions
(``# lint: disable=rule-name`` on the offending line) are honored
centrally so every rule gets them for free.

Paths are normalized to package-relative form (``presto_tpu/...``), so
rule scopes (which directories a family applies to) match no matter
where the analyzed tree lives — the test suite exercises rules on
synthetic packages in temp directories this way.
"""

from __future__ import annotations

import ast
import dataclasses
import re
import tokenize
from pathlib import Path
from typing import Callable, Iterable

PACKAGE = "presto_tpu"

# comment syntax: a '#' then ``lint: disable=rule-a,rule-b``, or the
# bare ``disable`` form covering every rule (phrased here without the
# leading hash so the stale-suppression check does not read THIS
# comment as a suppression)
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable(?:=([A-Za-z0-9_,\- ]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # package-relative, e.g. "presto_tpu/exec/executor.py"
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: " \
               f"[{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class SourceModule:
    """One parsed source file plus its suppression table and the
    shared walk/alias caches every rule reads instead of re-walking
    the tree (one full ``ast.walk`` per rule per module dominated
    lint runtime before these)."""

    def __init__(self, path: Path, relpath: str, text: str):
        self.path = path
        self.relpath = relpath
        self.text = text
        self.tree = ast.parse(text, filename=str(path))
        # line -> set of suppressed rule names, or None meaning all
        self.suppressions: dict[int, set[str] | None] = {}
        self._scan_suppressions(text)
        self._walk_cache: list[ast.AST] | None = None
        self._call_cache: list[ast.Call] | None = None
        self._alias_cache: dict[str, str] | None = None

    @property
    def modname(self) -> str:
        return self.relpath[:-3].replace("/", ".")

    def walk(self) -> list[ast.AST]:
        """Every node of the module tree, walked ONCE and cached for
        the project's lifetime — rules iterate this flat list instead
        of paying their own ``ast.walk`` pass."""
        if self._walk_cache is None:
            self._walk_cache = list(ast.walk(self.tree))
        return self._walk_cache

    def calls(self) -> list[ast.Call]:
        """Just the Call nodes of the shared walk — most per-call
        rules (timeouts, spans, metric names, spawn sites) scan only
        these, a ~10x smaller list than the full walk."""
        if self._call_cache is None:
            self._call_cache = [n for n in self.walk()
                                if isinstance(n, ast.Call)]
        return self._call_cache

    @property
    def aliases(self) -> dict[str, str]:
        """Cached :func:`import_aliases` for this module (computed
        off the shared walk, not a private re-walk)."""
        if self._alias_cache is None:
            self._alias_cache = import_aliases(self.tree,
                                               nodes=self.walk())
        return self._alias_cache

    def _scan_suppressions(self, text: str) -> None:
        # tokenize (not line regex) so a lint-disable marker inside a
        # string literal is not treated as a suppression
        import io
        if "lint:" not in text:  # tokenizing every file is ~1/3 of
            return                # total runtime; most have nothing
        try:
            tokens = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                names = m.group(1)
                if names is None:
                    self.suppressions[tok.start[0]] = None
                else:
                    cur = self.suppressions.setdefault(tok.start[0],
                                                       set())
                    if cur is not None:
                        cur.update(n.strip() for n in names.split(",")
                                   if n.strip())
        except tokenize.TokenError:
            pass

    def suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line, ...)
        if rules is ...:
            return False
        return rules is None or rule in rules


def _relpath(path: Path) -> str:
    """Path from the last ``presto_tpu`` component down (how rule
    scopes are expressed); falls back to the bare filename."""
    parts = path.parts
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == PACKAGE:
            return "/".join(parts[i:])
    return path.name


class Project:
    """Parsed modules for one lint run."""

    def __init__(self, modules: list[SourceModule]):
        self.modules = modules
        self.by_relpath = {m.relpath: m for m in modules}

    @classmethod
    def load(cls, paths: Iterable[str | Path]) -> "Project":
        files: list[Path] = []
        for p in paths:
            p = Path(p)
            if p.is_dir():
                files.extend(sorted(p.rglob("*.py")))
            elif p.suffix == ".py":
                files.append(p)
        modules = []
        seen = set()
        for f in files:
            key = f.resolve()
            if key in seen:
                continue
            seen.add(key)
            try:
                text = f.read_text(encoding="utf-8")
                modules.append(SourceModule(f, _relpath(f), text))
            except (SyntaxError, UnicodeDecodeError) as e:
                # surface as a usage error (CLI exit 2), not a
                # traceback a CI gate would misread as findings
                raise ValueError(f"cannot parse {f}: {e}") from e
        return cls(modules)

    def in_scope(self, scopes: tuple[str, ...]) -> list[SourceModule]:
        """Modules whose relpath starts with any of ``scopes`` (a
        trailing '/' scopes a directory, otherwise an exact file)."""
        out = []
        for m in self.modules:
            for s in scopes:
                if (m.relpath.startswith(s) if s.endswith("/")
                        else m.relpath == s):
                    out.append(m)
                    break
        return out


RuleFn = Callable[[Project], list[Finding]]
_RULES: dict[str, RuleFn] = {}


def rule(name: str) -> Callable[[RuleFn], RuleFn]:
    def deco(fn: RuleFn) -> RuleFn:
        _RULES[name] = fn
        return fn
    return deco


def available_rules() -> list[str]:
    return sorted(_RULES)


# pseudo-rule emitted by run_lint itself for suppression comments that
# no longer suppress anything (it needs every real rule's output, so
# it cannot live in the registry)
STALE_RULE = "stale-suppression"


def _stale_suppressions(project: Project, selected: list[str],
                        used: dict[tuple[str, int], set[str]],
                        blanket_used: set[tuple[str, int]]
                        ) -> list[Finding]:
    """Suppression comments that excused nothing this run — the code
    they covered was fixed or deleted, and a stale disable would
    silently swallow the NEXT real finding on that line. Only rules
    that actually ran are judged (a ``--rules`` subset run cannot
    call another rule's suppression stale); blanket ``disable``
    comments are judged only on full runs for the same reason.
    Unknown rule names are always stale — a typo'd suppression
    suppresses nothing while looking like it does."""
    known = set(available_rules()) | {STALE_RULE}
    ran = set(selected)
    full_run = ran == set(available_rules())
    out: list[Finding] = []
    for mod in project.modules:
        for line, names in sorted(mod.suppressions.items()):
            key = (mod.relpath, line)
            stale: list[str] = []
            if names is None:
                if full_run and key not in blanket_used:
                    out.append(Finding(
                        STALE_RULE, mod.relpath, line, 0,
                        "blanket '# lint: disable' suppresses no "
                        "finding on this line; delete it (a stale "
                        "disable hides the next real finding here)"))
                continue
            for name in sorted(names):
                if name == STALE_RULE:
                    continue  # judged by its own mechanism below
                if name not in known:
                    out.append(Finding(
                        STALE_RULE, mod.relpath, line, 0,
                        f"suppression names unknown rule {name!r} "
                        f"(available: {', '.join(available_rules())})"
                        " — it suppresses nothing"))
                elif name in ran and name not in used.get(key, ()):
                    stale.append(name)
            if stale:
                out.append(Finding(
                    STALE_RULE, mod.relpath, line, 0,
                    f"'# lint: disable={','.join(stale)}' no longer "
                    "suppresses any finding; the code it excused was "
                    "fixed or moved — delete the stale suppression"))
    return out


def run_lint(paths: Iterable[str | Path],
             rules: Iterable[str] | None = None,
             only_files: set[Path] | None = None,
             collect_suppressed: list[Finding] | None = None
             ) -> list[Finding]:
    """Run the selected rules (default: all) over ``paths``; returns
    unsuppressed findings — plus ``stale-suppression`` findings for
    disable comments that excused nothing — sorted by location.
    ``only_files`` (resolved paths) restricts REPORTING to those
    files while the analysis still sees the whole tree (the CLI's
    ``--changed`` mode: cross-file rules stay sound).
    ``collect_suppressed`` (a list, appended in place) receives the
    findings an in-source ``# lint: disable`` excused — the SARIF
    export reports them as suppressed results instead of dropping
    them, so CI dashboards can audit the waivers."""
    import presto_tpu.lint  # noqa: F401 - ensure rules registered
    paths = list(paths)
    missing = [str(p) for p in paths if not Path(p).exists()]
    if missing:
        raise ValueError(f"paths do not exist: {missing}")
    project = Project.load(paths)
    if not project.modules:
        # a typo'd path must not read as "lint clean"
        raise ValueError(
            f"no Python files found under {[str(p) for p in paths]}")
    selected = list(rules) if rules is not None else available_rules()
    unknown = [r for r in selected if r not in _RULES]
    if unknown:
        raise ValueError(f"unknown lint rules: {unknown} "
                         f"(available: {available_rules()})")
    findings: list[Finding] = []
    used: dict[tuple[str, int], set[str]] = {}
    blanket_used: set[tuple[str, int]] = set()
    for name in selected:
        for f in _RULES[name](project):
            mod = project.by_relpath.get(f.path)
            if mod is not None and mod.suppressed(f.line, f.rule):
                names = mod.suppressions.get(f.line, set())
                if names is None:
                    blanket_used.add((f.path, f.line))
                else:
                    used.setdefault((f.path, f.line),
                                    set()).add(f.rule)
                if collect_suppressed is not None:
                    collect_suppressed.append(f)
                continue
            findings.append(f)
    for f in _stale_suppressions(project, selected, used,
                                 blanket_used):
        mod = project.by_relpath.get(f.path)
        if mod is not None:
            names = mod.suppressions.get(f.line)
            # only an EXPLICIT disable=stale-suppression silences a
            # staleness report — the blanket being reported as stale
            # must not vouch for itself
            if names is not None and STALE_RULE in names:
                if collect_suppressed is not None:
                    collect_suppressed.append(f)
                continue
        findings.append(f)
    if only_files is not None:
        findings = [f for f in findings
                    if (m := project.by_relpath.get(f.path)) is not None
                    and m.path.resolve() in only_files]
        if collect_suppressed is not None:
            collect_suppressed[:] = [
                f for f in collect_suppressed
                if (m := project.by_relpath.get(f.path)) is not None
                and m.path.resolve() in only_files]
    if collect_suppressed is not None:
        collect_suppressed.sort(key=lambda f: (f.path, f.line, f.col,
                                               f.rule))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col,
                                           f.rule))


# -- shared AST helpers used by the rule modules ---------------------------

def qual_name(node: ast.AST) -> str | None:
    """Dotted name of a Name/Attribute chain ('jax.lax.scan'), else
    None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def walk_functions(tree: ast.AST):
    """Yield (qualpath, FunctionDef) for every function in a module,
    including methods and nested functions. ``qualpath`` is a tuple of
    enclosing class/function names."""
    def visit(node, path):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                yield path + (child.name,), child
                yield from visit(child, path + (child.name,))
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, path + (child.name,))
            else:
                yield from visit(child, path)
    yield from visit(tree, ())


def literal_str_dict(mod: SourceModule, name: str
                     ) -> dict[str, tuple[str, int]]:
    """Module-level ``name = {"key": "reason", ...}`` assignments
    (plain or annotated) -> {key: (reason, line)}. The shared parser
    behind the exemption registries (KERNEL_DISPATCH_EXEMPT,
    TRACE_KEY_EXEMPT): non-string reasons parse as "" so the owning
    rule can demand a justification."""
    out: dict[str, tuple[str, int]] = {}
    for node in mod.tree.body:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target]
                   if isinstance(node, ast.AnnAssign) else [])
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in targets):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                reason = (v.value if isinstance(v, ast.Constant)
                          and isinstance(v.value, str) else "")
                out[k.value] = (reason, k.lineno)
    return out


def import_aliases(tree: ast.AST,
                   nodes: Iterable[ast.AST] | None = None
                   ) -> dict[str, str]:
    """Local name -> imported dotted module/object path. Pass a
    pre-walked node list via ``nodes`` to skip the tree walk."""
    out: dict[str, str] = {}
    for node in (nodes if nodes is not None else ast.walk(tree)):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out
