"""Device-boundary discipline: no hidden host<->device syncs.

A host-blocking device read in the middle of the execute path —
``.item()`` on a device scalar, ``np.asarray`` over a jit output,
``jax.device_get``, ``.block_until_ready()`` — serializes the
dispatch pipeline: every occurrence costs a full round-trip (~90ms
over a tunneled TPU) and stalls the host until the device drains.
One stray ``.item()`` in a stage walk turns an async pipeline into a
lock-step crawl, and it benches fine on CPU where the transfer is a
memcpy (Tailwind's transfer/compute discipline is THE practical
accelerator-query bottleneck). The engine therefore has ONE designated
boundary — ``exec/hostsync.py`` (``fetch``/``fetch_int``/``wait``,
each batched and counted) — and this rule proves, whole-tree, that
every sync on the execute path goes through it.

The rule rides the shared ``lint/tracer.py`` ``CallGraph`` from the
execute-path roots (``exec/executor.prepare_plan``/``run_plan``,
``parallel/executor.execute_plan_distributed``, the serve/result
paths in ``server/``, ``parallel/coordinator``, ``parallel/worker``)
and asks, for every reachable call site: is this a host-blocking sync,
and is the value a DEVICE value? Value provenance reuses the tracekey
least-fixpoint argument-taint over the call graph: device-ness seeds
at ``jax.numpy``/``jax.lax`` producers, ``jax.jit``/``shard_map``
wrappers and AOT ``.compile()`` results (calls on a tainted callable
yield device values), ``jax.device_put`` and ``Engine.device_array``;
it propagates through tuple unpacking, subscripts, arithmetic,
comprehensions, helper parameters, and return values. Attribute reads
(``x.shape``, ``r.nbytes``) deliberately kill taint — shape/metadata
math is host-side and free.

Findings:

- ``jax.device_get``/``jax.block_until_ready``/``.block_until_ready()``
  outside the boundary: ALWAYS flagged (these exist only to sync);
- ``np.asarray``/``np.array``/``np.ascontiguousarray`` of a device
  value (the implicit ``__array__`` round-trip);
- ``.item()``/``.tolist()`` on a device value;
- ``int()``/``float()``/``bool()`` of a device value (implicit
  concretization — the tuple-of-ok-flags ladder bug class: one
  round-trip per flag instead of one per program).

Deliberate boundary reads are declared in
``exec/hostsync.DEVICE_SYNC_EXEMPT`` (id -> justification, id form
``<relpath>:<dotted.unit>:<kind>``) with kernel-parity-style
staleness enforcement: an entry matching no finding is itself a
finding.
"""

from __future__ import annotations

import ast

from presto_tpu.lint.core import (Finding, Project, literal_str_dict,
                                  qual_name, rule)
from presto_tpu.lint.tracekey import _params, _taint_targets
from presto_tpu.lint.tracer import (CallGraph, _FnUnit,
                                    _is_traced_producer, _resolve,
                                    call_graph)

RULE = "device-sync"

# everything the execute path can reach: the trace scopes plus the
# serve/dispatch layers that demux results, and the engine facade
SCOPES = (
    "presto_tpu/ops/",
    "presto_tpu/exec/",
    "presto_tpu/expr/",
    "presto_tpu/parallel/",
    "presto_tpu/server/",
    "presto_tpu/obs/",
    "presto_tpu/templates/",
    "presto_tpu/engine.py",
)

# the designated boundary: syncs INSIDE it are the point
BOUNDARY_PATH = "presto_tpu/exec/hostsync.py"

# execute-path roots: whole serve/dispatch modules (every handler
# demuxes results) plus the named executor entry points
_ROOT_MODULES = (
    "presto_tpu/server/server.py",
    "presto_tpu/server/results.py",
    "presto_tpu/parallel/coordinator.py",
    "presto_tpu/parallel/worker.py",
)
_ROOT_UNITS = (
    ("presto_tpu/exec/executor.py", "prepare_plan"),
    ("presto_tpu/exec/executor.py", "execute_plan"),
    ("presto_tpu/exec/executor.py", "run_plan"),
    ("presto_tpu/exec/executor.py", "run_plan_device"),
    ("presto_tpu/parallel/executor.py", "execute_plan_distributed"),
    ("presto_tpu/exec/streaming.py", "try_execute_streamed"),
    ("presto_tpu/exec/spill.py", "try_execute_spilled"),
    ("presto_tpu/exec/spill.py", "try_execute_grouped"),
    ("presto_tpu/exec/profile.py", "explain_analyze"),
    ("presto_tpu/exec/profile.py", "explain_analyze_distributed"),
)

# numpy coercions that call __array__ on a device value (one implicit
# device->host transfer each)
_NP_COERCE = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}

# builtins that concretize a device scalar
_CONCRETIZE = {"int", "float", "bool"}


def _roots(graph: CallGraph) -> set[tuple]:
    roots: set[tuple] = set()
    for key, u in graph.units.items():
        if u.mod.relpath in _ROOT_MODULES:
            roots.add(key)
    for relpath, name in _ROOT_UNITS:
        for u in graph.named(relpath, name):
            roots.add(u.key)
    return roots


class _DeviceTaint:
    """Least-fixpoint device-value provenance over the call graph (the
    tracekey session-taint machinery applied to array values)."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.param_taint: dict[tuple, set[str]] = {}
        self.returns_device: set[tuple] = set()
        self._stmts: dict[tuple, list[ast.AST]] = {}
        self._propagate()

    def stmts(self, u: _FnUnit) -> list[ast.AST]:
        out = self._stmts.get(u.key)
        if out is None:
            out = self._stmts[u.key] = list(u.own_statements())
        return out

    # -- expression provenance ---------------------------------------

    def is_device(self, node: ast.AST, env: set[str],
                  u: _FnUnit) -> bool:
        if isinstance(node, ast.Name):
            return node.id in env
        if isinstance(node, (ast.Subscript, ast.Starred,
                             ast.NamedExpr, ast.Await)):
            return self.is_device(node.value, env, u)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_device(e, env, u) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return (self.is_device(node.body, env, u)
                    or self.is_device(node.orelse, env, u))
        if isinstance(node, ast.BinOp):
            return (self.is_device(node.left, env, u)
                    or self.is_device(node.right, env, u))
        if isinstance(node, ast.UnaryOp):
            return self.is_device(node.operand, env, u)
        if isinstance(node, ast.BoolOp):
            return any(self.is_device(v, env, u) for v in node.values)
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp)):
            return self.is_device(node.elt, env, u)
        if isinstance(node, ast.Call):
            return self._call_is_device(node, env, u)
        # Attribute (x.shape, r.nbytes), Constant, Compare, JoinedStr:
        # host-side metadata — taint deliberately stops here
        return False

    def _call_is_device(self, call: ast.Call, env: set[str],
                        u: _FnUnit) -> bool:
        aliases = self.graph.alias_cache[u.mod.relpath]
        fn = call.func
        q = _resolve(qual_name(fn), aliases)
        if q is not None:
            if q in _NP_COERCE or q == "jax.device_get":
                return False  # the sync itself yields a HOST value
            if q.startswith("re."):
                return False  # compiled regexes are not executables
            if _is_traced_producer(q) or q in (
                    "jax.device_put", "jax.jit") or \
                    q.endswith("shard_map"):
                return True
        if isinstance(fn, ast.Name):
            # a tainted callable (an AOT-compiled executable) returns
            # device outputs
            if fn.id in env:
                return True
            if fn.id in _CONCRETIZE or fn.id == "len":
                return False
        if isinstance(fn, ast.Attribute):
            if fn.attr in ("compile", "device_array"):
                # jax.jit(...).lower(...).compile() executables and
                # Engine.device_array pins — the two cross-module
                # device producers name resolution cannot follow
                return True
            if self.is_device(fn.value, env, u):
                # a method of a device value (x.astype, live.sum,
                # jit(fn).lower) stays on device — except the syncs
                return fn.attr not in ("item", "tolist")
        for callee in self.graph.resolve_call(u, call):
            if callee.key in self.returns_device:
                return True
        return False

    # -- per-unit name environment ------------------------------------

    def _flood(self, t: ast.AST, env: set[str]) -> bool:
        if isinstance(t, (ast.Tuple, ast.List)):
            grew = False
            for e in t.elts:
                grew |= self._flood(e, env)
            return grew
        if isinstance(t, ast.Starred):
            return self._flood(t.value, env)
        while isinstance(t, (ast.Subscript, ast.Attribute)):
            t = t.value  # storing device data taints the container
        if isinstance(t, ast.Name) and t.id not in env:
            env.add(t.id)
            return True
        return False

    def _assign(self, t: ast.AST, v: ast.AST, env: set[str],
                u: _FnUnit) -> bool:
        if isinstance(t, (ast.Tuple, ast.List)) and \
                isinstance(v, (ast.Tuple, ast.List)) and \
                len(t.elts) == len(v.elts) and not any(
                    isinstance(e, ast.Starred) for e in t.elts):
            grew = False
            for te, ve in zip(t.elts, v.elts):
                grew |= self._assign(te, ve, env, u)
            return grew
        if not self.is_device(v, env, u):
            return False
        return self._flood(t, env)

    def env(self, u: _FnUnit) -> set[str]:
        env = set(self.param_taint.get(u.key, ()))
        changed = True
        while changed:
            changed = False
            for stmt in self.stmts(u):
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        changed |= self._assign(t, stmt.value, env, u)
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    if stmt.value is not None:
                        changed |= self._assign(stmt.target,
                                                stmt.value, env, u)
                elif isinstance(stmt, ast.NamedExpr):
                    changed |= self._assign(stmt.target, stmt.value,
                                            env, u)
                elif isinstance(stmt, ast.For):
                    # iterating a device array yields device elements
                    if self.is_device(stmt.iter, env, u):
                        changed |= self._flood(stmt.target, env)
        return env

    # -- interprocedural fixpoint -------------------------------------

    def _propagate(self) -> None:
        units = list(self.graph.units.values())
        changed = True
        while changed:
            changed = False
            for u in units:
                if u.mod.relpath == BOUNDARY_PATH:
                    continue  # fetch/wait return HOST values
                env = self.env(u)
                for stmt in self.stmts(u):
                    if isinstance(stmt, ast.Return) and \
                            stmt.value is not None and \
                            u.key not in self.returns_device and \
                            self.is_device(stmt.value, env, u):
                        self.returns_device.add(u.key)
                        changed = True
                    if not isinstance(stmt, ast.Call):
                        continue
                    args = [(i, a) for i, a in enumerate(stmt.args)
                            if self.is_device(a, env, u)]
                    kwargs = [kw for kw in stmt.keywords
                              if kw.arg is not None
                              and self.is_device(kw.value, env, u)]
                    if not args and not kwargs:
                        continue
                    for callee, shift in _taint_targets(
                            self.graph, u, stmt):
                        cp = _params(callee)
                        tset = self.param_taint.setdefault(
                            callee.key, set())
                        for i, _a in args:
                            j = i + shift
                            if j < len(cp) and cp[j] not in tset:
                                tset.add(cp[j])
                                changed = True
                        for kw in kwargs:
                            if kw.arg in cp and kw.arg not in tset:
                                tset.add(kw.arg)
                                changed = True


class _Sync:
    """One host-blocking sync call site."""

    __slots__ = ("kind", "unit", "line", "col", "what")

    def __init__(self, kind: str, unit: _FnUnit, line: int, col: int,
                 what: str):
        self.kind = kind
        self.unit = unit
        self.line = line
        self.col = col
        self.what = what

    @property
    def exempt_id(self) -> str:
        return (f"{self.unit.mod.relpath}:"
                f"{'.'.join(self.unit.path)}:{self.kind}")


def _collect_syncs(graph: CallGraph, taint: _DeviceTaint,
                   reachable: set[tuple]) -> list[_Sync]:
    syncs: list[_Sync] = []
    for key in sorted(reachable):
        u = graph.units.get(key)
        if u is None or u.mod.relpath == BOUNDARY_PATH:
            continue
        aliases = graph.alias_cache[u.mod.relpath]
        env = taint.env(u)
        for stmt in taint.stmts(u):
            if not isinstance(stmt, ast.Call):
                continue
            fn = stmt.func
            q = _resolve(qual_name(fn), aliases)
            if q == "jax.device_get":
                syncs.append(_Sync("device_get", u, stmt.lineno,
                                   stmt.col_offset, "`jax.device_get`"))
                continue
            if q == "jax.block_until_ready" or (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "block_until_ready"):
                syncs.append(_Sync(
                    "block_until_ready", u, stmt.lineno,
                    stmt.col_offset, "`block_until_ready`"))
                continue
            if q in _NP_COERCE and stmt.args and \
                    taint.is_device(stmt.args[0], env, u):
                syncs.append(_Sync(
                    "asarray", u, stmt.lineno, stmt.col_offset,
                    f"`{q.replace('numpy.', 'np.')}` over a device "
                    "value (implicit `__array__` transfer)"))
                continue
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in ("item", "tolist") and \
                    taint.is_device(fn.value, env, u):
                syncs.append(_Sync(
                    fn.attr, u, stmt.lineno, stmt.col_offset,
                    f"`.{fn.attr}()` on a device value"))
                continue
            if isinstance(fn, ast.Name) and fn.id in _CONCRETIZE and \
                    len(stmt.args) == 1 and \
                    taint.is_device(stmt.args[0], env, u):
                syncs.append(_Sync(
                    fn.id, u, stmt.lineno, stmt.col_offset,
                    f"`{fn.id}()` of a device value (implicit "
                    "concretization)"))
    return syncs


@rule(RULE)
def device_sync(project: Project) -> list[Finding]:
    graph = call_graph(project, SCOPES)
    if not graph.mods:
        return []
    findings: list[Finding] = []

    exempt: dict[str, tuple[str, int]] = {}
    boundary_mod = project.by_relpath.get(BOUNDARY_PATH)
    if boundary_mod is not None:
        exempt = literal_str_dict(boundary_mod, "DEVICE_SYNC_EXEMPT")

    roots = _roots(graph)
    if not roots:
        return []
    taint = _DeviceTaint(graph)
    reachable = graph.reachable(roots)
    syncs = _collect_syncs(graph, taint, reachable)

    used_exemptions: set[str] = set()

    def exempted(eid: str) -> bool:
        if eid in exempt:
            used_exemptions.add(eid)
            return True
        return False

    for s in syncs:
        if exempted(s.exempt_id):
            continue
        where = f"execute-path `{'.'.join(s.unit.path)}`"
        findings.append(Finding(
            RULE, s.unit.mod.relpath, s.line, s.col,
            f"hidden host sync: {where} calls {s.what} outside the "
            "exec/hostsync boundary — every occurrence blocks the "
            "host for a full device round-trip (~90ms tunneled) and "
            "serializes the dispatch pipeline; batch it through "
            "hostsync.fetch / fetch_int / wait (counted in "
            "presto_tpu_device_syncs_total) or exempt "
            f"'{s.exempt_id}' in DEVICE_SYNC_EXEMPT with a "
            "justification"))

    # exemption hygiene: the registry must not rot (kernel-parity's
    # staleness discipline)
    for eid, (reason, line) in sorted(exempt.items()):
        if eid not in used_exemptions:
            findings.append(Finding(
                RULE, BOUNDARY_PATH, line, 0,
                f"stale-exemption: DEVICE_SYNC_EXEMPT entry {eid!r} "
                "matched no finding this run — the sync it excused "
                "was fixed, moved, or routed through the boundary; "
                "delete the stale exemption (it would silently waive "
                "the next real sync under that id)"))
        elif not reason:
            findings.append(Finding(
                RULE, BOUNDARY_PATH, line, 0,
                f"DEVICE_SYNC_EXEMPT entry {eid!r} needs a non-empty "
                "justification string"))
    return findings
