"""Dispatch-exhaustiveness rule: every visitor handles every PlanNode.

The PlanNode subclass registry is read from ``plan/nodes.py`` (classes
transitively inheriting ``PlanNode``); each dispatch site is then
checked with a site-appropriate notion of "handles":

- ``isinstance`` sites (plan/sanity.py, plan/printer.py): the node
  class appears in an ``isinstance`` test somewhere in the module.
- ``register`` sites (plan/serde.py): the class is passed to
  ``_register(...)``.
- ``method-prefix`` sites (exec/executor.py): the interpreter class
  defines ``_r_<nodename>`` (matching the ``getattr`` dispatch in
  ``PlanInterpreter.run``).
- ``generic`` sites (plan/fingerprint.py): the module walks
  ``dataclasses.fields`` and declares ``GENERIC_PLAN_DISPATCH = True``
  — total over node types by construction.

A site may deliberately skip node types via a module-level

    DISPATCH_EXEMPT = {"NodeName": "why this site need not handle it"}

The rule also flags *stale* entries: an exemption for a node the site
actually handles, or for a node that no longer exists — so the opt-out
list cannot rot into silence (the same hygiene Trino's
PlanSanityChecker gets from its visitor base classes failing loudly).
"""

from __future__ import annotations

import ast
from pathlib import Path

from presto_tpu.lint.core import (Finding, Project, SourceModule,
                                  qual_name, rule)

REGISTRY_PATH = "presto_tpu/plan/nodes.py"
REGISTRY_BASE = "PlanNode"

# relpath -> (kind, detail)
SITES: dict[str, tuple[str, str]] = {
    "presto_tpu/plan/sanity.py": ("isinstance", ""),
    "presto_tpu/plan/printer.py": ("isinstance", ""),
    "presto_tpu/plan/serde.py": ("register", "_register"),
    "presto_tpu/plan/fingerprint.py": ("generic", ""),
    "presto_tpu/exec/executor.py": ("method-prefix", "_r_"),
    # StatsCalculator's per-node estimation rules: a PlanNode without a
    # stats rule would silently fall to the unknown-estimate default
    # and poison join ordering
    "presto_tpu/cost/stats.py": ("method-prefix", "_s_"),
}


def plan_node_registry(tree: ast.AST) -> dict[str, int]:
    """Subclasses of PlanNode (transitive, by name) -> def line."""
    bases_of: dict[str, tuple[list[str], int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            names = []
            for b in node.bases:
                q = qual_name(b)
                if q:
                    names.append(q.rsplit(".", 1)[-1])
            bases_of[node.name] = (names, node.lineno)
    out: dict[str, int] = {}
    changed = True
    while changed:
        changed = False
        for name, (bases, line) in bases_of.items():
            if name == REGISTRY_BASE or name in out:
                continue
            if any(b == REGISTRY_BASE or b in out for b in bases):
                out[name] = line
                changed = True
    return out


def _load_registry(project: Project) -> dict[str, int] | None:
    mod = project.by_relpath.get(REGISTRY_PATH)
    if mod is not None:
        return plan_node_registry(mod.tree)
    # subtree run: locate nodes.py on disk relative to any loaded
    # module of the package
    for m in project.modules:
        if not m.relpath.startswith("presto_tpu/"):
            continue
        depth = m.relpath.count("/")
        root = m.path
        for _ in range(depth):
            root = root.parent
        candidate = Path(root) / "plan" / "nodes.py"
        if candidate.is_file():
            return plan_node_registry(
                ast.parse(candidate.read_text(encoding="utf-8")))
    return None


def _exemptions(mod: SourceModule) -> dict[str, tuple[str, int]]:
    """Parse ``DISPATCH_EXEMPT = {"Name": "reason"}``."""
    out: dict[str, tuple[str, int]] = {}
    for node in mod.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets
                       if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            targets = [node.target]
            value = node.value
        else:
            continue
        if not any(t.id == "DISPATCH_EXEMPT" for t in targets):
            continue
        if isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    reason = (v.value if isinstance(v, ast.Constant)
                              and isinstance(v.value, str) else "")
                    out[k.value] = (reason, k.lineno)
    return out


def _handled_isinstance(mod: SourceModule,
                        registry: dict[str, int]) -> set[str]:
    handled: set[str] = set()
    for node in mod.walk():
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "isinstance"
                and len(node.args) == 2):
            continue
        types = node.args[1]
        elts = types.elts if isinstance(types, ast.Tuple) else [types]
        for e in elts:
            q = qual_name(e)
            if q:
                name = q.rsplit(".", 1)[-1]
                if name in registry:
                    handled.add(name)
    return handled


def _handled_register(mod: SourceModule, registry: dict[str, int],
                      fn_name: str) -> set[str]:
    handled: set[str] = set()
    for node in mod.walk():
        if isinstance(node, ast.Call) and \
                qual_name(node.func) is not None and \
                qual_name(node.func).rsplit(".", 1)[-1] == fn_name:
            for a in node.args:
                q = qual_name(a)
                if q:
                    name = q.rsplit(".", 1)[-1]
                    if name in registry:
                        handled.add(name)
    return handled


def _handled_method_prefix(mod: SourceModule,
                           registry: dict[str, int],
                           prefix: str) -> tuple[set[str], int]:
    """(handled names, anchor line of the dispatching class)."""
    by_lower = {name.lower(): name for name in registry}
    best: tuple[set[str], int] = (set(), 1)
    for node in mod.walk():
        if not isinstance(node, ast.ClassDef):
            continue
        handled: set[str] = set()
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) and \
                    stmt.name.startswith(prefix):
                suffix = stmt.name[len(prefix):]
                if suffix in by_lower:
                    handled.add(by_lower[suffix])
        if len(handled) > len(best[0]):
            best = (handled, node.lineno)
    return best


def _check_generic(mod: SourceModule) -> list[str]:
    """Problems with a generic (field-driven) site, as messages."""
    has_marker = False
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and \
                        t.id == "GENERIC_PLAN_DISPATCH" and \
                        isinstance(node.value, ast.Constant) and \
                        node.value.value is True:
                    has_marker = True
    walks_fields = any(
        isinstance(n, ast.Call) and qual_name(n.func) in
        ("dataclasses.fields", "fields")
        for n in mod.walk())
    problems = []
    if not walks_fields:
        problems.append(
            "declared generic over plan nodes but no "
            "dataclasses.fields() traversal found")
    if not has_marker:
        problems.append(
            "generic dispatch site must declare "
            "GENERIC_PLAN_DISPATCH = True to confirm it is total "
            "over node types by construction")
    return problems


@rule("plan-dispatch")
def plan_dispatch(project: Project) -> list[Finding]:
    registry = _load_registry(project)
    findings: list[Finding] = []
    if registry is None:
        return findings  # registry unreachable: nothing checkable
    for relpath, (kind, detail) in SITES.items():
        mod = project.by_relpath.get(relpath)
        if mod is None:
            continue
        exempt = _exemptions(mod)
        anchor = 1
        if kind == "isinstance":
            handled = _handled_isinstance(mod, registry)
        elif kind == "register":
            handled = _handled_register(mod, registry, detail)
        elif kind == "method-prefix":
            handled, anchor = _handled_method_prefix(mod, registry,
                                                     detail)
        elif kind == "generic":
            for msg in _check_generic(mod):
                findings.append(Finding("plan-dispatch", relpath, 1, 0,
                                        msg))
            handled = set(registry)
        else:  # pragma: no cover - config error
            continue
        for name in sorted(set(registry) - handled - set(exempt)):
            findings.append(Finding(
                "plan-dispatch", relpath, anchor, 0,
                f"plan node {name} (plan/nodes.py:{registry[name]}) "
                f"is not handled by this {kind} dispatch site; add a "
                "case or list it in DISPATCH_EXEMPT with a reason"))
        for name, (reason, line) in sorted(exempt.items()):
            if name not in registry:
                findings.append(Finding(
                    "plan-dispatch", relpath, line, 0,
                    f"DISPATCH_EXEMPT lists unknown plan node "
                    f"{name!r} (stale entry?)"))
            elif name in handled:
                findings.append(Finding(
                    "plan-dispatch", relpath, line, 0,
                    f"DISPATCH_EXEMPT lists {name} but this site "
                    "handles it; drop the stale exemption"))
            elif not reason:
                findings.append(Finding(
                    "plan-dispatch", relpath, line, 0,
                    f"DISPATCH_EXEMPT entry for {name} needs a "
                    "non-empty reason string"))
    return findings
