"""Ambient-context handoff rule: thread spawns must carry context over.

Four kinds of ambient state ride the spawning thread in this engine
and do NOT follow work onto a new thread (``ThreadPoolExecutor`` and
``threading.Thread`` copy neither contextvars nor ``threading.local``):

- the trace context (``obs/trace.py`` TRACER contextvar) — dropped, a
  worker thread's spans orphan into phantom root traces;
- the cancel token (``exec/cancel.py`` thread-local) — dropped, pool
  threads become unkillable by the reaper/low-memory killer;
- the stats recorder (``obs/qstats.py`` TaskRecorder contextvar) —
  dropped, the thread's operator stats vanish from the query tree;
- the per-query session override (``session.py`` thread-local) —
  dropped, HTTP queries compile under the wrong session properties.

Each of PRs 2, 4, 6 and 8 hand-fixed one instance of this bug class.
This rule makes the handoff a checked contract: every thread-spawn
site (``threading.Thread(target=...)``, ``threading.Timer``,
``<ThreadPoolExecutor>.submit/map``) in a module that USES ambient
context must show explicit handoff or establishment evidence in the
spawning function — a capture (``current_context()``,
``CANCEL.current()``, ``current_override()``, ``current_task()``/
``current_query()``, ``trace_headers()``), an install
(``TRACER.attach``, ``cancel.install``, ``install_override``,
``install_task``), or the thread opening its own fresh scope
(``TRACER.trace``/``root_or_span``, ``QS.task``/``QS.query``,
``CancelToken()``). The evidence scope is the innermost enclosing
function INCLUDING its nested defs, so the usual shape — capture
before the spawn, install inside the local target function — passes
as written.

A thread that is genuinely context-free (a daemon health sweeper, a
best-effort cleanup fan-out, a metrics scraper) carries
``# lint: disable=handoff`` on the spawn line plus a comment naming
why no ambient state applies. Modules that never touch ambient
context are out of scope — their threads cannot drop what the module
does not use.
"""

from __future__ import annotations

import ast

from presto_tpu.lint.core import (Finding, Project, SourceModule,
                                  qual_name, rule)

# ambient-state source modules: referencing anything under these marks
# the module as ambient-using (kind name -> module path prefix)
_AMBIENT_MODULES = {
    "trace context": "presto_tpu.obs.trace",
    "stats recorder": "presto_tpu.obs.qstats",
    "cancel token": "presto_tpu.exec.cancel",
}
# session.py is imported nearly everywhere for plain properties; only
# the per-thread override APIs are ambient state
_AMBIENT_NAMES = {
    "current_override": "session override",
    "install_override": "session override",
    "current_context": "trace context",
    "trace_headers": "trace context",
    "current_task": "stats recorder",
    "current_query": "stats recorder",
    "install_task": "stats recorder",
    "TRACER": "trace context",
}

# call-name suffixes that count as handoff/establishment evidence
_EVIDENCE_CALLS = {
    # captures (snapshot on the spawning thread, installed on the new)
    "current_context", "trace_headers", "current_override",
    "current_task", "current_query",
    # installs on the receiving thread
    "attach", "install", "install_override", "install_task",
    # the thread establishing its OWN fresh context is equally sound
    "trace", "root_or_span", "task", "query", "CancelToken",
}
# "current" alone is too generic; require a cancel-ish receiver
_CANCEL_RECEIVER = ("cancel", "CANCEL")

_EXECUTOR_NAMES = ("ThreadPoolExecutor",
                   "concurrent.futures.ThreadPoolExecutor")


def _resolve(qname: str | None, aliases: dict[str, str]) -> str | None:
    if qname is None:
        return None
    head, _, rest = qname.partition(".")
    head = aliases.get(head, head)
    return f"{head}.{rest}" if rest else head


def _ambient_kinds(mod: SourceModule,
                   aliases: dict[str, str]) -> set[str]:
    """Which kinds of ambient state this module touches at all."""
    kinds: set[str] = set()
    for node in mod.walk():
        q = None
        if isinstance(node, (ast.Name, ast.Attribute)):
            q = _resolve(qual_name(node), aliases)
        if q is None:
            continue
        for kind_name, prefix in _AMBIENT_MODULES.items():
            if q == prefix or q.startswith(prefix + "."):
                kinds.add(kind_name)
        tail = q.rsplit(".", 1)[-1]
        if tail in _AMBIENT_NAMES:
            kinds.add(_AMBIENT_NAMES[tail])
    return kinds


def _is_executor_ctor(call: ast.Call, aliases: dict[str, str]) -> bool:
    return _resolve(qual_name(call.func), aliases) in _EXECUTOR_NAMES


def _executor_names(fn: ast.AST,
                    aliases: dict[str, str]) -> set[str]:
    """Local names bound to a ThreadPoolExecutor inside ``fn`` (via
    assignment or ``with ... as name``)."""
    names: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _is_executor_ctor(node.value, aliases):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call) and \
                        _is_executor_ctor(item.context_expr, aliases) \
                        and isinstance(item.optional_vars, ast.Name):
                    names.add(item.optional_vars.id)
    return names


def _module_executor_attrs(mod: SourceModule,
                           aliases: dict[str, str]) -> set[str]:
    """Attribute names assigned a ThreadPoolExecutor anywhere in the
    module (``self.pool = ThreadPoolExecutor(...)`` — submit sites may
    be in another method)."""
    attrs: set[str] = set()
    for node in mod.walk():
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _is_executor_ctor(node.value, aliases):
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    attrs.add(t.attr)
    return attrs


def _has_evidence(scope: ast.AST, aliases: dict[str, str]) -> bool:
    for node in ast.walk(scope):
        if not isinstance(node, ast.Call):
            continue
        q = qual_name(node.func)
        if q is None:
            continue
        tail = q.rsplit(".", 1)[-1]
        if tail == "current":
            recv = q.rsplit(".", 2)[-2] if "." in q else ""
            if any(c in recv for c in _CANCEL_RECEIVER):
                return True
            continue
        if tail not in _EVIDENCE_CALLS:
            continue
        if tail in ("attach", "install", "trace", "root_or_span",
                    "task", "query"):
            # these are methods: require an ambient-ish receiver so
            # re.Match.span()-style lookalikes don't count
            recv = q.rsplit(".", 2)[-2] if "." in q else ""
            rq = _resolve(q, aliases) or q
            if not (recv in ("TRACER", "_TRACER", "tracer", "QS",
                             "qstats", "CANCEL", "cancel", "_cancel")
                    or ".obs.trace." in rq or ".obs.qstats." in rq
                    or ".exec.cancel." in rq):
                continue
        return True
    return False


def _enclosing_function_map(tree: ast.AST) -> dict[int, ast.AST]:
    """id(node) -> innermost enclosing FunctionDef (or the module)."""
    out: dict[int, ast.AST] = {}

    def visit(node: ast.AST, fn: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            out[id(child)] = fn
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                visit(child, child)
            else:
                visit(child, fn)

    visit(tree, tree)
    return out


_SPAWNISH = ("Thread", "Timer", "submit", "map")


def _has_spawn_candidate(mod: SourceModule) -> bool:
    """Cheap pre-filter: any call spelled like a spawn at all? Most
    modules have none, and the full ambient-usage scan is the
    expensive part of this rule."""
    for node in mod.calls():
        fn = node.func
        name = (fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None)
        if name in _SPAWNISH:
            return True
    return False


@rule("handoff")
def handoff(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules:
        if not mod.relpath.startswith("presto_tpu/") or \
                mod.relpath.startswith("presto_tpu/lint/"):
            continue
        if not _has_spawn_candidate(mod):
            continue
        aliases = mod.aliases
        kinds = _ambient_kinds(mod, aliases)
        if not kinds:
            continue
        enclosing = _enclosing_function_map(mod.tree)
        module_pool_attrs = _module_executor_attrs(mod, aliases)
        # executor-bound local names per function scope
        exec_names: dict[int, set[str]] = {}

        def spawn_desc(call: ast.Call) -> str | None:
            q = _resolve(qual_name(call.func), aliases)
            if q in ("threading.Thread", "threading.Timer"):
                return q
            fn = call.func
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in ("submit", "map"):
                recv = fn.value
                scope = enclosing.get(id(call), mod.tree)
                if id(scope) not in exec_names:
                    exec_names[id(scope)] = _executor_names(
                        scope, aliases)
                if isinstance(recv, ast.Name) and \
                        recv.id in exec_names[id(scope)]:
                    return f"{recv.id}.{fn.attr}"
                if isinstance(recv, ast.Attribute) and \
                        recv.attr in module_pool_attrs:
                    return f"{recv.attr}.{fn.attr}"
            return None

        for node in mod.calls():
            desc = spawn_desc(node)
            if desc is None:
                continue
            scope = enclosing.get(id(node), mod.tree)
            if _has_evidence(scope, aliases):
                continue
            findings.append(Finding(
                "handoff", mod.relpath, node.lineno, node.col_offset,
                f"{desc}(...) spawns a thread in a module using "
                f"ambient {', '.join(sorted(kinds))}, but the "
                "spawning function neither hands any of it over "
                "(current_context/CANCEL.current/current_override/"
                "current_task capture + attach/install on the "
                "thread) nor opens a fresh scope there — the new "
                "thread silently drops that state; hand it over, or "
                "suppress with a comment naming why this thread is "
                "context-free"))
    return findings
