"""Kernel-parity rule: every Pallas kernel has a reachable fallback.

The kernel subsystem's contract (presto_tpu/kernels/__init__.py) is
that the ``kernel_backend`` session property can always force ``xla``
and get numerically identical results — which only holds if EVERY
Pallas kernel is registered in the :data:`KERNELS` dispatch table
beside an XLA fallback, and both names resolve to real functions. A
Pallas kernel wired directly into an operator (bypassing the table)
would be unreachable from the session property, untested by the
parity tier, and invisible to per-operator kernel attribution.

Checked statically, in the spirit of lint/dispatch.py's plan-node
exhaustiveness sites:

- ``KERNELS`` is a literal dict of ``name -> {"pallas": ref,
  "xla": ref}`` with BOTH backend keys per row;
- every referenced function exists in the kernels module it names;
- every module-level ``*_pallas`` function in ``presto_tpu/kernels/``
  appears in some row's ``pallas`` slot (reachability from the
  dispatch table);
- ``dispatch`` itself exists and reads ``KERNELS``.

Kernels exempt from registration (helpers, building blocks) use a
module-level ``KERNEL_DISPATCH_EXEMPT = {"fn_name": "reason"}`` in
their defining module — same hygiene as DISPATCH_EXEMPT, including
staleness detection.
"""

from __future__ import annotations

import ast

from presto_tpu.lint.core import (Finding, Project, SourceModule,
                                  literal_str_dict, qual_name, rule)

REGISTRY_PATH = "presto_tpu/kernels/__init__.py"
PACKAGE_PREFIX = "presto_tpu/kernels/"


def _registry_rows(mod: SourceModule):
    """Parse ``KERNELS = {...}``: name -> {backend: (module_alias,
    fn_name, line)}; None when the assignment is missing/not literal."""
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "KERNELS"
                   for t in targets):
            continue
        if not isinstance(node.value, ast.Dict):
            return None
        rows: dict[str, dict[str, tuple]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Dict)):
                return None
            entry: dict[str, tuple] = {}
            for bk, bv in zip(v.keys, v.values):
                if not (isinstance(bk, ast.Constant)
                        and isinstance(bk.value, str)):
                    return None
                q = qual_name(bv)
                if q is None or "." not in q:
                    return None
                alias, fn = q.rsplit(".", 1)
                entry[bk.value] = (alias, fn, bv.lineno)
            rows[k.value] = entry
        return rows
    return None


def _module_functions(mod: SourceModule) -> set[str]:
    return {n.name for n in mod.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _exempt(mod: SourceModule) -> dict[str, tuple[str, int]]:
    return literal_str_dict(mod, "KERNEL_DISPATCH_EXEMPT")


@rule("kernel-parity")
def kernel_parity(project: Project) -> list[Finding]:
    reg_mod = project.by_relpath.get(REGISTRY_PATH)
    if reg_mod is None:
        return []  # subtree run without the kernels package
    findings: list[Finding] = []
    rows = _registry_rows(reg_mod)
    if rows is None:
        return [Finding(
            "kernel-parity", REGISTRY_PATH, 1, 0,
            "KERNELS must be a literal dict of "
            "name -> {'pallas': fn, 'xla': fn} (the parity contract "
            "is checked statically against it)")]

    # module alias -> kernels submodule relpath (from the imports)
    submods = {m.relpath.rsplit("/", 1)[-1][:-3]: m
               for m in project.modules
               if m.relpath.startswith(PACKAGE_PREFIX)
               and m.relpath != REGISTRY_PATH}
    alias_to_mod: dict[str, SourceModule] = {}
    for alias, target in reg_mod.aliases.items():
        leaf = target.rsplit(".", 1)[-1]
        if leaf in submods:
            alias_to_mod[alias] = submods[leaf]

    registered_pallas: set[tuple[str, str]] = set()  # (module, fn)
    for name, entry in sorted(rows.items()):
        for backend in ("pallas", "xla"):
            if backend not in entry:
                findings.append(Finding(
                    "kernel-parity", REGISTRY_PATH, 1, 0,
                    f"kernel {name!r} has no {backend!r} entry — "
                    "every Pallas kernel needs a registered XLA "
                    "fallback (and vice versa) so kernel_backend "
                    "can always force either"))
                continue
            alias, fn, line = entry[backend]
            mod = alias_to_mod.get(alias)
            if mod is None:
                findings.append(Finding(
                    "kernel-parity", REGISTRY_PATH, line, 0,
                    f"kernel {name!r} {backend} entry references "
                    f"unknown module alias {alias!r}"))
                continue
            if fn not in _module_functions(mod):
                findings.append(Finding(
                    "kernel-parity", REGISTRY_PATH, line, 0,
                    f"kernel {name!r} {backend} entry references "
                    f"{mod.relpath}:{fn} which does not exist"))
            elif backend == "pallas":
                registered_pallas.add((mod.relpath, fn))

    # a dispatch() that ignores the table would make the rows above
    # decorative: require the function and a KERNELS read inside it
    dispatch_fns = [n for n in reg_mod.tree.body
                    if isinstance(n, ast.FunctionDef)
                    and n.name == "dispatch"]
    if not dispatch_fns or not any(
            isinstance(sub, ast.Name) and sub.id == "KERNELS"
            for fn in dispatch_fns for sub in ast.walk(fn)):
        findings.append(Finding(
            "kernel-parity", REGISTRY_PATH, 1, 0,
            "kernels/__init__.py must define dispatch() reading the "
            "KERNELS table (the kernel_backend selection point)"))

    # reachability: every *_pallas kernel function is registered
    for mod in project.modules:
        if not mod.relpath.startswith(PACKAGE_PREFIX) \
                or mod.relpath == REGISTRY_PATH:
            continue
        exempt = _exempt(mod)
        fns = _module_functions(mod)
        for node in mod.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            if not node.name.endswith("_pallas"):
                continue
            if (mod.relpath, node.name) in registered_pallas:
                continue
            if node.name in exempt:
                continue
            findings.append(Finding(
                "kernel-parity", mod.relpath, node.lineno, 0,
                f"Pallas kernel {node.name} is not registered in the "
                "kernel_backend dispatch table "
                "(kernels/__init__.KERNELS) — unreachable from the "
                "session property and invisible to parity testing; "
                "register it or list it in KERNEL_DISPATCH_EXEMPT "
                "with a reason"))
        for name, (reason, line) in sorted(exempt.items()):
            if name not in fns:
                findings.append(Finding(
                    "kernel-parity", mod.relpath, line, 0,
                    f"KERNEL_DISPATCH_EXEMPT lists unknown function "
                    f"{name!r} (stale entry?)"))
            elif (mod.relpath, name) in registered_pallas:
                findings.append(Finding(
                    "kernel-parity", mod.relpath, line, 0,
                    f"KERNEL_DISPATCH_EXEMPT lists {name} but it IS "
                    "registered; drop the stale exemption"))
            elif not reason:
                findings.append(Finding(
                    "kernel-parity", mod.relpath, line, 0,
                    f"KERNEL_DISPATCH_EXEMPT entry for {name} needs "
                    "a non-empty reason string"))
    return findings
